"""Headline bench: rows/sec/chip on the fused q01-class pipeline.

Runs the flagship kernel (filter → hash-group → segment aggregate, see
__graft_entry__._q01_kernel) on the available accelerator and compares
against a single-threaded host (pyarrow) implementation of the same query —
the "single-partition CPU reference" of BASELINE.md.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N,
   "platform": "...", ...}

Resilience contract (round-2 BENCH_r02.json was rc=1 with no parseable
output because the TPU client was wedged at init): the parent process
first health-probes the ambient accelerator in a watchdogged subprocess
with retries; if the accelerator can't initialize, the bench still runs —
on the CPU backend in a sanitized child env — and the JSON records
``platform`` plus ``accel_error`` so an environmental failure is
distinguishable from a perf regression. If even that fails, the output is
``{"metric": ..., "error": ...}`` — always one parseable line.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time

_METRIC = "q01_pipeline_rows_per_sec_per_chip"

# sizes overridable so tests can drive the full parent/probe/child pipeline
# in seconds; the defaults are the measured configuration
CAPACITY = int(os.environ.get("AURON_BENCH_CAPACITY", 1 << 20))
ITERS = int(os.environ.get("AURON_BENCH_ITERS", 20))
WARMUP = 3

#: seconds for one accelerator-init probe / the bench child before its
#: faulthandler watchdog dumps stacks and exits
_PROBE_TIMEOUT_S = 90
_BENCH_TIMEOUT_S = 900
_PROBE_ATTEMPTS = 2
_PROBE_BACKOFF_S = 10


# ---------------------------------------------------------------------------
# child: the actual measurement (runs under an already-validated platform)
# ---------------------------------------------------------------------------

def make_batch(seed: int):
    import numpy as np
    import jax.numpy as jnp
    from auron_tpu.columnar.batch import DeviceBatch, PrimitiveColumn

    rng = np.random.default_rng(seed)
    n = CAPACITY
    k = rng.integers(0, 65536, size=n).astype(np.int64)
    v = rng.normal(size=n)
    f = rng.integers(0, 40, size=n).astype(np.int32)
    v_valid = rng.random(n) > 0.05
    host = {"k": k, "v": v, "f": f, "v_valid": v_valid}
    batch = DeviceBatch(
        columns=(
            PrimitiveColumn(jnp.asarray(k), jnp.ones(n, jnp.bool_)),
            PrimitiveColumn(jnp.asarray(v), jnp.asarray(v_valid)),
            PrimitiveColumn(jnp.asarray(f), jnp.ones(n, jnp.bool_)),
        ),
        num_rows=jnp.asarray(n, jnp.int32),
    )
    return batch, host


def _bench_kernel(kernel, iters: int, batch) -> float:
    """Time ``iters`` launches of a jitted kernel over ``batch``.
    Device->host readback is the reliable sync point (on the tunneled
    axon platform block_until_ready returns before execution finishes);
    stream ordering makes the last result's readback cover all iters."""
    import numpy as np
    import jax

    fn = jax.jit(kernel)
    for _ in range(WARMUP):
        np.asarray(fn(batch)[2])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(batch)
    np.asarray(out[2])
    dt = time.perf_counter() - t0
    return CAPACITY * iters / dt


def _bench_flagship_backend(batch, backend: str, iters: int) -> float:
    """Time the flagship kernel with the grouped-agg backend pinned
    (auron.kernels.backend), restoring the dispatch default after.
    flagship_kernel() resolves the backend EAGERLY into a per-backend
    function object — jitting `_q01_kernel` here would let jax's trace
    cache serve the first backend's trace for every later one."""
    import __graft_entry__ as graft
    from auron_tpu import config as cfg
    conf = cfg.get_config()
    conf.set(cfg.KERNELS_BACKEND, backend)
    try:
        return _bench_kernel(graft.flagship_kernel(), iters, batch)
    finally:
        conf.unset(cfg.KERNELS_BACKEND)


def bench_device(batch) -> float:
    # headline dense number: pin the committed one-hot matmul formulation
    # so it never depends on a Mosaic compile — the Pallas kernel is
    # measured separately (bench_device_pallas) AFTER the dense result is
    # snapshotted, so a Mosaic-induced wedge can never cost this datum
    return _bench_flagship_backend(batch, "dense", ITERS)


def bench_device_pallas(batch) -> float:
    """The Pallas VMEM-accumulate grouped-agg kernel through the same
    flagship pipeline (auron.kernels.backend=pallas). This is the only
    real-chip Mosaic compile in the bench/tier-1 surface, and it runs
    in the bench child AFTER the healthy-window probe passed and after
    the dense snapshot was committed (TPU-tunnel pitfall: a Mosaic
    compile against a wedged client can re-wedge it)."""
    return _bench_flagship_backend(batch, "pallas", ITERS)


def bench_device_general(batch) -> float:
    """The GENERAL (unbounded-key) agg path: xxhash64 → sort → segment
    reduce (__graft_entry__._q01_kernel_sort — the AggOp representation),
    measured alongside the fused dense kernel so an on-chip capture
    carries both (round-4 verdict directive 2)."""
    import __graft_entry__ as graft
    return _bench_kernel(graft._q01_kernel_sort, max(1, ITERS // 4),
                         batch)


def bench_device_hash(batch) -> float:
    """The general path re-based on the device hash table
    (auron_tpu/hashtable): __graft_entry__._q01_kernel_hash — claim-owner
    probe rounds + slot-indexed accumulator scatters, no sort. Measured
    additively next to the sort-based general number; the ISSUE 3 gate is
    hash >= 1.5x sort on the CPU snapshot."""
    import __graft_entry__ as graft
    return _bench_kernel(graft._q01_kernel_hash, max(1, ITERS // 4),
                         batch)


#: trace-overhead A/B sizing defaults (the measured configuration);
#: the env overrides are read at CALL time so tests can monkeypatch
#: without reloading the module
_TRACE_BENCH_SCALE = 0.01
_TRACE_BENCH_REPS = 8
_TRACE_BENCH_QUERIES = "q3,q42,q52"


def bench_trace_overhead() -> dict:
    """Additive A/B: a TPC-DS subset with tracing OFF vs ON
    (auron.trace.enabled), same process, compiles warmed first so the
    delta is the tracing plane's recording cost, not compile noise.
    The observability contract is measured, not assumed: the gate is
    trace_overhead_pct < 2 (PERF.md 'Tracing & metric tree')."""
    import tempfile

    from auron_tpu import config as cfg
    from auron_tpu.frontend.session import Session
    from auron_tpu.it.tpcds import generate
    from auron_tpu.it.tpcds_queries import QUERIES
    from auron_tpu.obs import trace

    scale = float(os.environ.get("AURON_BENCH_TRACE_SCALE",
                                 str(_TRACE_BENCH_SCALE)))
    reps = int(os.environ.get("AURON_BENCH_TRACE_REPS",
                              str(_TRACE_BENCH_REPS)))
    names = [n.strip()
             for n in os.environ.get("AURON_BENCH_TRACE_QUERIES",
                                     _TRACE_BENCH_QUERIES).split(",")
             if n.strip()]
    subset = [q for q in QUERIES if q.name in names]
    if not subset:
        raise ValueError(f"no TPC-DS queries match {names}")
    data = tempfile.mkdtemp(prefix="auron_trace_ab_")
    tables = generate(data, scale=scale)
    conf = cfg.get_config()

    def run_suite():
        for q in subset:
            q.run(Session(), tables)

    # warm every compile site AND the host caches: the suite keeps
    # speeding up for a couple of repetitions, so the arms must
    # INTERLEAVE (off, on, off, on, ...) — back-to-back blocks would
    # attribute the warm-up drift to whichever arm ran first. The
    # estimator is the sum of PER-QUERY minima per arm: container
    # timing noise is additive and positive (scheduler stalls inflate a
    # rep, nothing deflates one), so each query's min converges on its
    # uncontended floor — and per-QUERY granularity matters because a
    # stall hits one query, not the whole suite, so a suite-level min
    # almost never runs every query clean at once (measured A/A bias:
    # suite-min 4.3%, per-query-min 0.1% on this container, whose
    # single-rep deltas of ±10-50% dwarf the <2% gate).
    off_min = {q.name: float("inf") for q in subset}
    on_min = {q.name: float("inf") for q in subset}

    def accrue(mins: dict) -> None:
        for q in subset:
            t0 = time.perf_counter()
            q.run(Session(), tables)
            mins[q.name] = min(mins[q.name],
                               time.perf_counter() - t0)

    try:
        # explicit pins, not unset: unset falls back to ambient
        # AURON_CONF_TRACE_* env vars, which would trace BOTH arms
        # (vacuous gate), make the ON arm pay per-query export I/O, or
        # narrow the recorded categories (understated overhead)
        conf.set(cfg.TRACE_DIR, "")
        conf.set(cfg.TRACE_EVENTS, "")
        run_suite()
        run_suite()
        for _ in range(reps):
            conf.set(cfg.TRACE_ENABLED, False)
            accrue(off_min)
            conf.set(cfg.TRACE_ENABLED, True)
            accrue(on_min)
        traced_spans = len(trace.tracer().spans())
    finally:
        conf.unset(cfg.TRACE_ENABLED)
        conf.unset(cfg.TRACE_DIR)
        conf.unset(cfg.TRACE_EVENTS)
        trace.reset()
        shutil.rmtree(data, ignore_errors=True)
    off_s, on_s = sum(off_min.values()), sum(on_min.values())
    pct = (on_s - off_s) / off_s * 100.0
    return {
        "trace_overhead_pct": round(pct, 2),
        "trace_overhead_gate_pct": 2.0,
        "trace_ab_queries": names,
        "trace_ab_scale": scale,
        "trace_ab_off_s": round(off_s, 3),
        "trace_ab_on_s": round(on_s, 3),
        "trace_ab_spans": traced_spans,
    }


def bench_cpu_reference(threads: int = 1) -> float:
    """Same query via pyarrow's vectorized C++ kernels.

    threads=1 is the single-partition CPU reference of BASELINE.md (the
    historical ``vs_baseline`` denominator). threads=N runs the SAME query
    on Arrow's full multicore thread pool — the honest stand-in for the
    reference's multi-core SIMD engine (the BASELINE.md ≥3× north star
    denominator, recorded as ``vs_baseline_mc``)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    pa.set_cpu_count(max(1, threads))
    use_threads = threads > 1
    _, host = make_batch(0)
    tbl = pa.table({
        "k": host["k"],
        "v": pa.array(host["v"], mask=~host["v_valid"]),
        "f": host["f"],
    })
    iters = max(1, ITERS // 4)

    def run_once():
        filt = tbl.filter(pc.and_(pc.greater(tbl["f"], 10),
                                  pc.is_valid(tbl["v"])))
        return filt.group_by("k", use_threads=use_threads).aggregate(
            [("v", "sum"), ("v", "count"), ("v", "mean")])

    run_once()
    t0 = time.perf_counter()
    for _ in range(iters):
        run_once()
    dt = time.perf_counter() - t0
    return CAPACITY * iters / dt


def _snapshot_partial(result: dict) -> None:
    """Persist a successful REAL-CHIP measurement the moment it exists
    (BENCH_partial.json + best-effort git commit). Round 3 lost its only
    on-chip datum because the TPU client wedged hours later and the
    round-end bench fell back to CPU; the snapshot makes the strongest
    measurement of the round durable regardless of what the client does
    afterwards."""
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "BENCH_partial.json")
    snap = dict(result)
    snap["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        prev = None
        if os.path.exists(path):
            with open(path) as f:
                prev = json.load(f)
        # keep the best on-chip number of the round; equal-value writes
        # go through so additive metrics (general/pallas) upgrade in place
        if prev and prev.get("value", 0) > snap["value"]:
            return
        with open(path, "w") as f:
            f.write(json.dumps(snap) + "\n")
        subprocess.run(["git", "add", "BENCH_partial.json"], cwd=here,
                       capture_output=True, timeout=30)
        subprocess.run(
            ["git", "commit", "-o", "BENCH_partial.json", "-m",
             f"Snapshot on-chip bench: {snap['value']:.0f} rows/s"],
            cwd=here, capture_output=True, timeout=30)
    except Exception:
        pass   # snapshotting must never fail the bench


def _child_main() -> None:
    import faulthandler
    faulthandler.dump_traceback_later(_BENCH_TIMEOUT_S - 30, exit=True)

    import jax
    platform = jax.devices()[0].platform

    batch, _host = make_batch(0)
    dev_rps = bench_device(batch)
    cpu_rps = bench_cpu_reference(threads=1)
    mc_rps = bench_cpu_reference(threads=os.cpu_count() or 1)
    result = {
        "metric": _METRIC,
        "value": round(dev_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(dev_rps / cpu_rps, 3),
        "vs_baseline_mc": round(dev_rps / mc_rps, 3),
        # hosts with <8 cores cannot measure the >=8-core denominator;
        # BASELINE.md pins linear scaling to 8 threads as the documented
        # stand-in, so scale the measured mc figure by 8/threads
        # (replace with a measured figure on the first >=8-core host)
        "vs_baseline_mc_pinned8": round(
            dev_rps / (mc_rps * max(1.0, 8.0 / (os.cpu_count() or 1))),
            4),
        "baseline_mc_rows_per_sec": round(mc_rps, 1),
        "baseline_mc_threads": os.cpu_count() or 1,
        "platform": platform,
    }
    if platform != "cpu":
        # snapshot the dense on-chip datum BEFORE anything else can fail
        # (round-3 lost its only number to a later wedge)
        _snapshot_partial(result)
    try:
        result["general_agg_rows_per_sec"] = round(
            bench_device_general(batch), 1)
        if platform != "cpu":
            _snapshot_partial(result)   # upgrade the snapshot in place
    except Exception as e:   # additive metric: never lose the dense one
        result["general_agg_error"] = str(e)[:300]
    try:
        # hash-table general path (auron_tpu/hashtable), additive next
        # to the sort-based general number — same snapshot protocol
        result["hash_agg_rows_per_sec"] = round(
            bench_device_hash(batch), 1)
        if platform != "cpu":
            _snapshot_partial(result)
    except Exception as e:   # additive: never lose the earlier data
        result["hash_agg_error"] = str(e)[:300]
    if platform == "tpu":
        # the kernel dispatch would pick on-chip (kernels/dispatch.py):
        # measured additively so the next healthy window reports its
        # vs_baseline_mc_pinned8 alongside the dense number. Gated on
        # tpu EXACTLY: on every other platform the pallas backend runs
        # interpreted — a debug mode, not a datum
        try:
            pallas_rps = bench_device_pallas(batch)
            result["pallas_agg_rows_per_sec"] = round(pallas_rps, 1)
            result["pallas_vs_baseline_mc_pinned8"] = round(
                pallas_rps / (mc_rps * max(1.0, 8.0 / (os.cpu_count()
                                                       or 1))), 4)
            _snapshot_partial(result)
        except Exception as e:   # additive: never lose the dense datum
            result["pallas_agg_error"] = str(e)[:300]
    try:
        # tracing-plane overhead A/B on the TPC-DS subset (additive —
        # never lose the earlier data; the <2% gate lives in PERF.md)
        result.update(bench_trace_overhead())
        if platform != "cpu":
            _snapshot_partial(result)
    except Exception as e:   # additive: never lose the earlier data
        result["trace_overhead_error"] = str(e)[:300]
    # set when this child is the CPU fallback after an accelerator
    # failure (probe or bench): keeps environmental failures
    # distinguishable from perf regressions in the recorded line
    accel_error = os.environ.get("_AURON_BENCH_ACCEL_ERROR")
    if accel_error:
        result["accel_error"] = accel_error[:500]
    faulthandler.cancel_dump_traceback_later()
    print(json.dumps(result))


# ---------------------------------------------------------------------------
# parent: backend health probe + dispatch
# ---------------------------------------------------------------------------

def _condense_error(text: str) -> str:
    """Reduce a (possibly truncated, multi-line) child stderr — a python
    traceback or a faulthandler watchdog stack dump — to ONE grep-able
    line: the terminal exception plus the innermost frame location. The
    recorded ``accel_error`` JSON field stays a single canonical line
    instead of an embedded multi-line traceback."""
    import re
    lines = [ln.strip() for ln in (text or "").strip().splitlines()
             if ln.strip()]
    if not lines:
        return ""
    exc = next((ln for ln in reversed(lines)
                if re.match(r"[A-Za-z_][\w.]*(Error|Exception|Interrupt"
                            r"|Exit)\b", ln)
                or ln.startswith("Fatal Python error")), None)
    frames = [ln for ln in lines if ln.startswith('File "')]
    loc = ""
    if frames:
        # faulthandler dumps are most-recent-call-FIRST, tracebacks
        # most-recent-call-LAST; the truncated tail keeps the frame
        # nearest the fault in both cases at opposite ends — prefer the
        # last frame (traceback order), which r05-style dumps also end on
        m = re.match(r'File "([^"]+)", line (\d+)(?:,? in (.+))?',
                     frames[-1])
        if m:
            loc = f"{os.path.basename(m.group(1))}:{m.group(2)}"
            if m.group(3):
                loc += f" in {m.group(3).strip()}"
    if exc is None:
        exc = lines[-1] if not loc else "backend init failed (stack dump)"
    return (f"{exc} [at {loc}]" if loc else exc)[:300]


def _probe_accelerator() -> tuple[bool, str]:
    """Initialize jax in a throwaway subprocess under the AMBIENT env.
    Returns (ok, platform-or-error). A wedged accelerator client hangs at
    init, so the probe carries its own watchdog + hard timeout."""
    from auron_tpu.utils.envsafe import watchdogged_child_code

    code, _ = watchdogged_child_code(
        "import jax\n"
        "d = jax.devices()\n"
        "print('PLATFORM=' + d[0].platform)",
        _PROBE_TIMEOUT_S, margin_s=10)
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=_PROBE_TIMEOUT_S,
                              cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return False, f"backend init exceeded {_PROBE_TIMEOUT_S}s (hung client)"
    for line in proc.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return True, line.split("=", 1)[1]
    return False, _condense_error(proc.stderr) or "backend init failed"


def _run_bench_child(env: dict) -> subprocess.CompletedProcess:
    env = dict(env)
    env["_AURON_BENCH_CHILD"] = "1"
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=_BENCH_TIMEOUT_S,
        cwd=os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    if os.environ.get("_AURON_BENCH_CHILD") == "1":
        _child_main()
        return

    from auron_tpu.utils.envsafe import cpu_child_env

    accel_error = ""
    accel_ok = False
    for attempt in range(_PROBE_ATTEMPTS):
        accel_ok, info = _probe_accelerator()
        if accel_ok:
            break
        accel_error = info
        if attempt + 1 < _PROBE_ATTEMPTS:
            time.sleep(_PROBE_BACKOFF_S)

    def try_child(env):
        try:
            proc = _run_bench_child(env)
        except subprocess.TimeoutExpired:
            return None, f"bench child exceeded {_BENCH_TIMEOUT_S}s"
        if proc.returncode == 0 and proc.stdout.strip():
            return proc, ""
        return None, (_condense_error(proc.stderr)
                      or f"bench child rc={proc.returncode}")

    proc = None
    if accel_error:
        # environmental: the accelerator never initialized
        accel_error = f"probe: {accel_error}"
    if accel_ok:
        proc, failure = try_child(dict(os.environ))
        if proc is None:
            # the accelerator FAILED MID-BENCH after a HEALTHY probe —
            # likely a product bug on the accelerator path, not an
            # environmental failure; the stage prefix keeps the two
            # distinguishable in the recorded line
            accel_error = f"bench: {failure}"
    if proc is None:
        # CPU fallback env: sanitized so a hostile sitecustomize can't
        # drag the child back onto the broken accelerator
        fallback = cpu_child_env(os.path.dirname(os.path.abspath(__file__)))
        if accel_error:
            fallback["_AURON_BENCH_ACCEL_ERROR"] = accel_error
        proc, failure = try_child(fallback)

    if proc is not None:
        sys.stderr.write(proc.stderr)
        print(proc.stdout.strip().splitlines()[-1])
        return

    print(json.dumps({"metric": _METRIC, "error": failure,
                      "accel_error": accel_error[:500] or None}))
    sys.exit(1)


if __name__ == "__main__":
    main()
