"""Headline bench: rows/sec/chip on the fused q01-class pipeline.

Runs the flagship kernel (filter → hash-group → segment aggregate, see
__graft_entry__._q01_kernel) on the available accelerator and compares
against a single-threaded host (pyarrow) implementation of the same query —
the "single-partition CPU reference" of BASELINE.md.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

import jax

import __graft_entry__ as graft
from auron_tpu.columnar.batch import DeviceBatch, PrimitiveColumn
import jax.numpy as jnp

CAPACITY = 1 << 20          # 1M rows per batch
ITERS = 20
WARMUP = 3


def make_batch(seed: int) -> tuple[DeviceBatch, dict]:
    rng = np.random.default_rng(seed)
    n = CAPACITY
    k = rng.integers(0, 65536, size=n).astype(np.int64)
    v = rng.normal(size=n)
    f = rng.integers(0, 40, size=n).astype(np.int32)
    v_valid = rng.random(n) > 0.05
    host = {"k": k, "v": v, "f": f, "v_valid": v_valid}
    batch = DeviceBatch(
        columns=(
            PrimitiveColumn(jnp.asarray(k), jnp.ones(n, jnp.bool_)),
            PrimitiveColumn(jnp.asarray(v), jnp.asarray(v_valid)),
            PrimitiveColumn(jnp.asarray(f), jnp.ones(n, jnp.bool_)),
        ),
        num_rows=jnp.asarray(n, jnp.int32),
    )
    return batch, host


def bench_device() -> float:
    fn = jax.jit(graft._q01_kernel)
    batch, _ = make_batch(0)
    for _ in range(WARMUP):
        np.asarray(fn(batch)[2])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(batch)
    # device->host readback is the reliable sync point (on the tunneled
    # axon platform block_until_ready returns before execution finishes);
    # stream ordering makes the last result's readback cover all iters
    np.asarray(out[2])
    dt = time.perf_counter() - t0
    return CAPACITY * ITERS / dt


def bench_cpu_reference() -> float:
    """Same query via pyarrow (vectorized C++ single-thread class baseline).
    Arrow's kernels are multi-threaded by default; pin the pool to one
    thread so the baseline really is the single-partition CPU reference."""
    pa.set_cpu_count(1)
    _, host = make_batch(0)
    tbl = pa.table({
        "k": host["k"],
        "v": pa.array(host["v"], mask=~host["v_valid"]),
        "f": host["f"],
    })
    iters = max(1, ITERS // 4)

    def run_once():
        filt = tbl.filter(pc.and_(pc.greater(tbl["f"], 10),
                                  pc.is_valid(tbl["v"])))
        return filt.group_by("k", use_threads=False).aggregate(
            [("v", "sum"), ("v", "count"), ("v", "mean")])

    run_once()
    t0 = time.perf_counter()
    for _ in range(iters):
        run_once()
    dt = time.perf_counter() - t0
    return CAPACITY * iters / dt


def main() -> None:
    dev_rps = bench_device()
    cpu_rps = bench_cpu_reference()
    result = {
        "metric": "q01_pipeline_rows_per_sec_per_chip",
        "value": round(dev_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(dev_rps / cpu_rps, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
