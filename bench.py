"""Headline bench: rows/sec/chip on the fused q01-class pipeline.

Runs the flagship kernel (filter → hash-group → segment aggregate, see
__graft_entry__._q01_kernel) on the available accelerator and compares
against a single-threaded host (pyarrow) implementation of the same query —
the "single-partition CPU reference" of BASELINE.md.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N,
   "platform": "...", ...}

Resilience contract (round-2 BENCH_r02.json was rc=1 with no parseable
output because the TPU client was wedged at init): the parent process
first health-probes the ambient accelerator in a watchdogged subprocess
with retries; if the accelerator can't initialize, the bench still runs —
on the CPU backend in a sanitized child env — and the JSON records
``platform`` plus ``accel_error`` so an environmental failure is
distinguishable from a perf regression. If even that fails, the output is
``{"metric": ..., "error": ...}`` — always one parseable line.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time

_METRIC = "q01_pipeline_rows_per_sec_per_chip"

# sizes overridable so tests can drive the full parent/probe/child pipeline
# in seconds; the defaults are the measured configuration
CAPACITY = int(os.environ.get("AURON_BENCH_CAPACITY", 1 << 20))
ITERS = int(os.environ.get("AURON_BENCH_ITERS", 20))
WARMUP = 3

#: seconds for one accelerator-init probe / the bench child before its
#: faulthandler watchdog dumps stacks and exits
_PROBE_TIMEOUT_S = 90
_BENCH_TIMEOUT_S = 900
_PROBE_ATTEMPTS = 2
_PROBE_BACKOFF_S = 10


# ---------------------------------------------------------------------------
# child: the actual measurement (runs under an already-validated platform)
# ---------------------------------------------------------------------------

def make_batch(seed: int):
    import numpy as np
    import jax.numpy as jnp
    from auron_tpu.columnar.batch import DeviceBatch, PrimitiveColumn

    rng = np.random.default_rng(seed)
    n = CAPACITY
    k = rng.integers(0, 65536, size=n).astype(np.int64)
    v = rng.normal(size=n)
    f = rng.integers(0, 40, size=n).astype(np.int32)
    v_valid = rng.random(n) > 0.05
    host = {"k": k, "v": v, "f": f, "v_valid": v_valid}
    batch = DeviceBatch(
        columns=(
            PrimitiveColumn(jnp.asarray(k), jnp.ones(n, jnp.bool_)),
            PrimitiveColumn(jnp.asarray(v), jnp.asarray(v_valid)),
            PrimitiveColumn(jnp.asarray(f), jnp.ones(n, jnp.bool_)),
        ),
        num_rows=jnp.asarray(n, jnp.int32),
    )
    return batch, host


def _bench_kernel(kernel, iters: int, batch) -> float:
    """Time ``iters`` launches of a jitted kernel over ``batch``.
    Device->host readback is the reliable sync point (on the tunneled
    axon platform block_until_ready returns before execution finishes);
    stream ordering makes the last result's readback cover all iters."""
    import numpy as np
    import jax

    fn = jax.jit(kernel)
    for _ in range(WARMUP):
        np.asarray(fn(batch)[2])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(batch)
    np.asarray(out[2])
    dt = time.perf_counter() - t0
    return CAPACITY * iters / dt


def _bench_flagship_backend(batch, backend: str, iters: int) -> float:
    """Time the flagship kernel with the grouped-agg backend pinned
    (auron.kernels.backend), restoring the dispatch default after.
    flagship_kernel() resolves the backend EAGERLY into a per-backend
    function object — jitting `_q01_kernel` here would let jax's trace
    cache serve the first backend's trace for every later one."""
    import __graft_entry__ as graft
    from auron_tpu import config as cfg
    conf = cfg.get_config()
    conf.set(cfg.KERNELS_BACKEND, backend)
    try:
        return _bench_kernel(graft.flagship_kernel(), iters, batch)
    finally:
        conf.unset(cfg.KERNELS_BACKEND)


def bench_device(batch) -> float:
    # headline dense number: pin the committed one-hot matmul formulation
    # so it never depends on a Mosaic compile — the Pallas kernel is
    # measured separately (bench_device_pallas) AFTER the dense result is
    # snapshotted, so a Mosaic-induced wedge can never cost this datum
    return _bench_flagship_backend(batch, "dense", ITERS)


def bench_device_pallas(batch) -> float:
    """The Pallas VMEM-accumulate grouped-agg kernel through the same
    flagship pipeline (auron.kernels.backend=pallas). This is the only
    real-chip Mosaic compile in the bench/tier-1 surface, and it runs
    in the bench child AFTER the healthy-window probe passed and after
    the dense snapshot was committed (TPU-tunnel pitfall: a Mosaic
    compile against a wedged client can re-wedge it)."""
    return _bench_flagship_backend(batch, "pallas", ITERS)


def bench_device_general(batch) -> float:
    """The GENERAL (unbounded-key) agg path: xxhash64 → sort → segment
    reduce (__graft_entry__._q01_kernel_sort — the AggOp representation),
    measured alongside the fused dense kernel so an on-chip capture
    carries both (round-4 verdict directive 2)."""
    import __graft_entry__ as graft
    return _bench_kernel(graft._q01_kernel_sort, max(1, ITERS // 4),
                         batch)


def bench_device_hash(batch) -> float:
    """The general path re-based on the device hash table
    (auron_tpu/hashtable): __graft_entry__._q01_kernel_hash — claim-owner
    probe rounds + slot-indexed accumulator scatters, no sort. Measured
    additively next to the sort-based general number; the ISSUE 3 gate is
    hash >= 1.5x sort on the CPU snapshot."""
    import __graft_entry__ as graft
    return _bench_kernel(graft._q01_kernel_hash, max(1, ITERS // 4),
                         batch)


#: observability-overhead A/B sizing defaults (the measured
#: configuration); the env overrides are read at CALL time so tests can
#: monkeypatch without reloading the module. Reps dropped 8 → 6 when the
#: third arm landed (PR 6): 3 arms × 6 reps costs what 2 × 8 + warmup
#: did, and the per-query-min estimator converges by ~5 reps (the A/A
#: methodology note in PERF.md).
_TRACE_BENCH_SCALE = 0.01
_TRACE_BENCH_REPS = 6
_TRACE_BENCH_QUERIES = "q3,q42,q52"


def bench_trace_overhead() -> dict:
    """Additive three-arm A/B on a TPC-DS subset, same process, compiles
    warmed first so the deltas are recording cost, not compile noise:

    - base — tracing OFF, profiler ON (the shipping defaults);
    - trace — tracing ON, profiler ON: ``trace_overhead_pct`` is
      (trace − base)/base (the PR 5 <2% gate, PERF.md);
    - noprof — tracing OFF, profiler OFF:  ``profile_overhead_pct`` is
      (base − noprof)/noprof — what the host/device attribution plane
      (obs/profile.py) costs with everything else unchanged (the PR 6
      <2% gate; the disabled path must be near-zero BY this same
      measurement read the other way);
    - norec — tracing OFF, profiler ON, flight recorder OFF:
      ``flight_overhead_pct`` is (base − norec)/norec — what the
      always-on flight recorder (obs/flight_recorder.py) costs with
      the recorder armed and trace export off, exactly the shipping
      posture (the ops-plane <2% gate, PERF.md 'Ops plane').

    All three observability contracts are measured, not assumed."""
    import tempfile

    from auron_tpu import config as cfg
    from auron_tpu.frontend.session import Session
    from auron_tpu.it.tpcds import generate
    from auron_tpu.it.tpcds_queries import QUERIES
    from auron_tpu.obs import trace

    scale = float(os.environ.get("AURON_BENCH_TRACE_SCALE",
                                 str(_TRACE_BENCH_SCALE)))
    reps = int(os.environ.get("AURON_BENCH_TRACE_REPS",
                              str(_TRACE_BENCH_REPS)))
    names = [n.strip()
             for n in os.environ.get("AURON_BENCH_TRACE_QUERIES",
                                     _TRACE_BENCH_QUERIES).split(",")
             if n.strip()]
    subset = [q for q in QUERIES if q.name in names]
    if not subset:
        raise ValueError(f"no TPC-DS queries match {names}")
    data = tempfile.mkdtemp(prefix="auron_trace_ab_")
    tables = generate(data, scale=scale)
    conf = cfg.get_config()

    def run_suite():
        for q in subset:
            q.run(Session(), tables)

    # warm every compile site AND the host caches: the suite keeps
    # speeding up for a couple of repetitions, so the arms must
    # INTERLEAVE (base, trace, noprof, base, ...) — back-to-back blocks
    # would attribute the warm-up drift to whichever arm ran first. The
    # estimator is the sum of PER-QUERY minima per arm: container
    # timing noise is additive and positive (scheduler stalls inflate a
    # rep, nothing deflates one), so each query's min converges on its
    # uncontended floor — and per-QUERY granularity matters because a
    # stall hits one query, not the whole suite, so a suite-level min
    # almost never runs every query clean at once (measured A/A bias:
    # suite-min 4.3%, per-query-min 0.1% on this container, whose
    # single-rep deltas of ±10-50% dwarf the <2% gates).
    arms = {
        "base": {cfg.TRACE_ENABLED: False, cfg.PROFILE_ENABLED: True,
                 cfg.FLIGHT_ENABLED: True},
        "trace": {cfg.TRACE_ENABLED: True, cfg.PROFILE_ENABLED: True,
                  cfg.FLIGHT_ENABLED: True},
        "noprof": {cfg.TRACE_ENABLED: False,
                   cfg.PROFILE_ENABLED: False,
                   cfg.FLIGHT_ENABLED: True},
        "norec": {cfg.TRACE_ENABLED: False, cfg.PROFILE_ENABLED: True,
                  cfg.FLIGHT_ENABLED: False},
    }
    mins = {arm: {q.name: float("inf") for q in subset} for arm in arms}

    def accrue(arm: str) -> None:
        for q in subset:
            t0 = time.perf_counter()
            q.run(Session(), tables)
            mins[arm][q.name] = min(mins[arm][q.name],
                                    time.perf_counter() - t0)

    try:
        # explicit pins, not unset: unset falls back to ambient
        # AURON_CONF_TRACE_* env vars, which would trace BOTH arms
        # (vacuous gate), make the ON arm pay per-query export I/O, or
        # narrow the recorded categories (understated overhead)
        conf.set(cfg.TRACE_DIR, "")
        conf.set(cfg.TRACE_EVENTS, "")
        run_suite()
        run_suite()
        for _ in range(reps):
            for arm, knobs in arms.items():
                for key, val in knobs.items():
                    conf.set(key, val)
                accrue(arm)
        traced_spans = len(trace.tracer().spans())
    finally:
        conf.unset(cfg.TRACE_ENABLED)
        conf.unset(cfg.PROFILE_ENABLED)
        conf.unset(cfg.FLIGHT_ENABLED)
        conf.unset(cfg.TRACE_DIR)
        conf.unset(cfg.TRACE_EVENTS)
        trace.reset()
        from auron_tpu.obs import flight_recorder as _flight
        _flight.reset()
        shutil.rmtree(data, ignore_errors=True)
    base_s = sum(mins["base"].values())
    on_s = sum(mins["trace"].values())
    noprof_s = sum(mins["noprof"].values())
    norec_s = sum(mins["norec"].values())
    return {
        "trace_overhead_pct": round((on_s - base_s) / base_s * 100.0, 2),
        "trace_overhead_gate_pct": 2.0,
        "profile_overhead_pct": round(
            (base_s - noprof_s) / noprof_s * 100.0, 2),
        "profile_overhead_gate_pct": 2.0,
        "flight_overhead_pct": round(
            (base_s - norec_s) / norec_s * 100.0, 2),
        "flight_overhead_gate_pct": 2.0,
        "trace_ab_queries": names,
        "trace_ab_scale": scale,
        "trace_ab_off_s": round(base_s, 3),
        "trace_ab_on_s": round(on_s, 3),
        "trace_ab_noprofile_s": round(noprof_s, 3),
        "trace_ab_norecorder_s": round(norec_s, 3),
        "trace_ab_spans": traced_spans,
    }


def _table_rows(files) -> int:
    """Row count of a parquet table (metadata only)."""
    import pyarrow.parquet as pq
    files = [files] if isinstance(files, str) else list(files)
    return sum(pq.read_metadata(f).num_rows for f in files)


def bench_profile_q01() -> dict:
    """Machine-readable host/device profile of the q01 OPERATOR pipeline
    (it/queries.py q01_filter_agg — the plan-shaped twin of the flagship
    kernel the headline metric times): one profiled explain-analyze run,
    rolled up by obs/profile.summarize_tree, plus the end-to-end
    OPERATOR-pipeline throughput ``pipeline_rows_per_sec`` (input rows /
    wall — the number the pipelined-execution work moves and the CPU
    floor tools/perf_gate.py gates). This is the bench record's
    attribution section — the gate carries it through so a rows/s
    regression arrives WITH the category split that explains it."""
    import tempfile

    from auron_tpu import config as cfg
    from auron_tpu.frontend.session import Session
    from auron_tpu.it.tpcds_data import generate as gen_data
    from auron_tpu.obs import metric_tree as mt
    from auron_tpu.obs import profile as obs_profile

    # scale 4 ≈ 480k fact rows: large enough that per-query fixed
    # overhead (plan/trace/host-fn glue, ~100 ms) stops dominating the
    # throughput figure the gate's CPU pipeline floor watches
    scale = float(os.environ.get("AURON_BENCH_PROFILE_SCALE", "4"))
    reps = max(1, int(os.environ.get("AURON_BENCH_PROFILE_REPS", "2")))
    data = tempfile.mkdtemp(prefix="auron_profile_q01_")
    conf = cfg.get_config()
    try:
        tables = gen_data(data, scale=scale)
        conf.set(cfg.PROFILE_ENABLED, True)
        from auron_tpu.it.queries import q01_dataframe
        q01_dataframe(Session(), tables).collect()   # warm compiles
        # best-of-N (container timing noise is additive and positive —
        # the per-query-min estimator argument, PERF.md)
        wall_s, tree = float("inf"), None
        for _ in range(reps):
            s = Session()
            df = q01_dataframe(s, tables)
            t0 = time.perf_counter()
            op = s.plan_physical(df)
            rep_tree, _tbl = mt.explain_analyze(
                op, num_partitions=df.num_partitions,
                mem_manager=s.mem_manager, config=s.config)
            rep_wall = time.perf_counter() - t0
            if rep_wall < wall_s:
                wall_s, tree = rep_wall, rep_tree
        summary = obs_profile.summarize_tree(tree)
        summary["wall_s"] = round(wall_s, 3)
        summary["scale"] = scale
        try:
            rows = _table_rows(tables["store_sales"])
            summary["input_rows"] = rows
            summary["pipeline_rows_per_sec"] = round(rows / wall_s, 1)
        except Exception:
            pass
        return summary
    finally:
        conf.unset(cfg.PROFILE_ENABLED)
        shutil.rmtree(data, ignore_errors=True)


def bench_fusion2() -> dict:
    """Map-side combine A/B (Fusion 2.0): the dup-heavy grouped-agg
    shape — a q01-style multi-partition sum/count group-by whose key
    domain is tiny relative to the row count — executed with
    ``auron.fusion.combine`` on and off. Records the live shuffle bytes
    both ways (``shuffle_bytes_live`` counts exactly what crosses the
    exchange: batch bytes scaled by live rows), the reduction, and the
    combined run's end-to-end rows/s. Additive like every satellite
    metric: tools/perf_gate.py --smoke gates the reduction floor."""
    import numpy as np
    import pyarrow as pa

    from auron_tpu import config as cfg
    from auron_tpu.frontend import Session, col
    from auron_tpu.frontend import functions as F
    from auron_tpu.ops.base import ExecContext

    rng = np.random.default_rng(0)
    n = int(os.environ.get("AURON_BENCH_FUSION2_ROWS", "200000"))
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 200, n), pa.int64()),
        "v": pa.array(rng.integers(0, 1000, n), pa.int64()),
    })
    conf = cfg.get_config()

    def run(combine: bool):
        if not combine:
            conf.set("auron.fusion.combine", "false")
        try:
            s = Session()
            s.register("fusion2_bench", tbl)
            df = (s.table("fusion2_bench").repartition(4).group_by("k")
                  .agg(F.sum(col("v")).alias("sv"),
                       F.count(col("v")).alias("c")))
            op = s.plan_physical(df)
            ctx = ExecContext()
            t0 = time.perf_counter()
            for p in range(df.num_partitions):
                for _ in op.execute(p, ctx):
                    pass
            wall = time.perf_counter() - t0
            m = ctx.metrics["shuffle_exchange"]
            return m.counter("shuffle_bytes_live").value, wall
        finally:
            if not combine:
                conf.unset("auron.fusion.combine")

    run(True)   # warm programs so the timed runs measure execution
    run(False)
    b_on, w_on = run(True)
    b_off, _w_off = run(False)
    return {
        "combine_shuffle_bytes_on": int(b_on),
        "combine_shuffle_bytes_off": int(b_off),
        "combine_byte_reduction": round(1.0 - b_on / max(1, b_off), 4),
        "fusion2_rows_per_sec": round(n / w_on, 1),
    }


def bench_cpu_reference(threads: int = 1) -> float:
    """Same query via pyarrow's vectorized C++ kernels.

    threads=1 is the single-partition CPU reference of BASELINE.md (the
    historical ``vs_baseline`` denominator). threads=N runs the SAME query
    on Arrow's full multicore thread pool — the honest stand-in for the
    reference's multi-core SIMD engine (the BASELINE.md ≥3× north star
    denominator, recorded as ``vs_baseline_mc``)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    pa.set_cpu_count(max(1, threads))
    use_threads = threads > 1
    _, host = make_batch(0)
    tbl = pa.table({
        "k": host["k"],
        "v": pa.array(host["v"], mask=~host["v_valid"]),
        "f": host["f"],
    })
    iters = max(1, ITERS // 4)

    def run_once():
        filt = tbl.filter(pc.and_(pc.greater(tbl["f"], 10),
                                  pc.is_valid(tbl["v"])))
        return filt.group_by("k", use_threads=use_threads).aggregate(
            [("v", "sum"), ("v", "count"), ("v", "mean")])

    run_once()
    t0 = time.perf_counter()
    for _ in range(iters):
        run_once()
    dt = time.perf_counter() - t0
    return CAPACITY * iters / dt


def _snapshot_partial(result: dict) -> None:
    """Persist a successful REAL-CHIP measurement the moment it exists
    (BENCH_partial.json + best-effort git commit). Round 3 lost its only
    on-chip datum because the TPU client wedged hours later and the
    round-end bench fell back to CPU; the snapshot makes the strongest
    measurement of the round durable regardless of what the client does
    afterwards."""
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "BENCH_partial.json")
    snap = dict(result)
    snap["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        prev = None
        if os.path.exists(path):
            with open(path) as f:
                prev = json.load(f)
        # keep the best on-chip number of the round; equal-value writes
        # go through so additive metrics (general/pallas) upgrade in place
        if prev and prev.get("value", 0) > snap["value"]:
            return
        with open(path, "w") as f:
            f.write(json.dumps(snap) + "\n")
        subprocess.run(["git", "add", "BENCH_partial.json"], cwd=here,
                       capture_output=True, timeout=30)
        subprocess.run(
            ["git", "commit", "-o", "BENCH_partial.json", "-m",
             f"Snapshot on-chip bench: {snap['value']:.0f} rows/s"],
            cwd=here, capture_output=True, timeout=30)
    except Exception:
        pass   # snapshotting must never fail the bench


def _bind_xla_cache() -> dict:
    """Bind jax's persistent compilation cache for the bench child
    (``auron.xla_cache_dir``; default a stable per-container dir so
    successive rounds share compiles): q01's multi-second first-call
    tracing cost stops polluting per-round throughput comparisons.
    Returns the cache record for the bench JSON — ``entries_before`` >
    0 means this run started warm (cache hits), ``new_entries`` counts
    the misses this run compiled and persisted."""
    import tempfile

    from auron_tpu import config as cfg
    conf = cfg.get_config()
    cache_dir = conf.get(cfg.XLA_CACHE_DIR) or os.path.join(
        tempfile.gettempdir(), "auron_xla_cache")
    record = {"dir": cache_dir, "entries_before": 0}
    try:
        os.makedirs(cache_dir, exist_ok=True)
        conf.set(cfg.XLA_CACHE_DIR, cache_dir)   # Sessions re-bind too
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default min-compile-time gate (1s) would skip most CPU-mesh
        # programs; persist everything so the warm-round diet is real
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception:
            pass
        record["entries_before"] = len(os.listdir(cache_dir))
    except Exception as e:   # cache must never fail the bench
        record["error"] = str(e)[:200]
    return record


def _finish_xla_cache(record: dict) -> dict:
    try:
        entries = len(os.listdir(record["dir"]))
        record["entries_after"] = entries
        record["new_entries"] = entries - record.get("entries_before", 0)
        record["warm"] = record.get("entries_before", 0) > 0
    except Exception:
        pass
    return record


#: wall budget for the mesh scaling child (virtual 8-device CPU mesh)
_MESH_TIMEOUT_S = 480


def _mesh_child_main() -> None:
    """SPMD scaling measurement on the virtual CPU mesh (runs in its own
    subprocess with ``--xla_force_host_platform_device_count=8`` forced):
    the q01 operator pipeline at 1, 2, 4 and 8 partitions with
    ``auron.mesh.enabled`` on and ``auron.mesh.devices`` clamped to the
    partition count, so every hash exchange that CAN ride the on-device
    all-to-all does — and the route is verified from the recorded
    ``exchange.route`` trace events, never inferred. Emits one JSON
    line: per-device-count rows/s, the 8-device ``mesh_rows_per_sec``
    headline (the tools/perf_gate.py 'mesh' platform floor), the
    per-chip scaling factor vs single-device, and the on-device
    exchange bytes. This graduates the MULTICHIP_* dryruns into a real
    scaling figure tier-1 can gate; real-slice numbers land in
    MULTICHIP records when the accelerator is reachable."""
    import faulthandler
    import tempfile

    faulthandler.dump_traceback_later(_MESH_TIMEOUT_S - 20, exit=True)
    import jax

    from auron_tpu import config as cfg
    from auron_tpu.frontend.session import Session
    from auron_tpu.it.queries import q01_dataframe
    from auron_tpu.it.tpcds_data import generate as gen_data
    from auron_tpu.obs import trace

    scale = float(os.environ.get("AURON_BENCH_MESH_SCALE", "2"))
    reps = max(1, int(os.environ.get("AURON_BENCH_MESH_REPS", "2")))
    counts = [int(c) for c in os.environ.get(
        "AURON_BENCH_MESH_COUNTS", "1,2,4,8").split(",") if c.strip()]
    n_dev = len(jax.devices())
    counts = [c for c in counts if c <= n_dev]
    conf = cfg.get_config()
    data = tempfile.mkdtemp(prefix="auron_mesh_bench_")
    record = {"platform": "mesh", "devices_visible": n_dev,
              "scale": scale}
    try:
        tables = gen_data(data, scale=scale)
        rows = _table_rows(tables["store_sales"])
        record["input_rows"] = rows
        conf.set(cfg.MESH_ENABLED, True)
        conf.set(cfg.TRACE_ENABLED, True)
        conf.set(cfg.TRACE_DIR, "")
        per_count = {}
        routes = {}
        route_mix = {}
        demoted = {}
        bytes_moved = {}
        combine_mix = {}
        for n in counts:
            # devices == partitions: the exchange's square contract; at
            # n=1 the plan has no exchange at all — the single-device
            # strong-scaling baseline
            conf.set(cfg.MESH_DEVICES, n)
            q01_dataframe(Session(), tables, partitions=n).collect()
            best = float("inf")
            for _ in range(reps):
                trace.reset()
                t0 = time.perf_counter()
                q01_dataframe(Session(), tables, partitions=n).collect()
                best = min(best, time.perf_counter() - t0)
            spans = trace.tracer().spans()
            evs = [s for s in spans if s.name == "exchange.route"
                   and s.attrs.get("route") == "all_to_all"]
            # the FULL route mix per exchange, demotions included: a
            # run whose rounds fell back to host mid-exchange
            # (exchange.demote) measures the recovery path, not the
            # mesh — perf_gate must see that and skip the floor
            mix: dict = {}
            comb = {"folds": 0, "rows_in": 0, "rows_out": 0}
            for s in spans:
                if s.name == "exchange.route":
                    r = s.attrs.get("route", "?")
                    mix[r] = mix.get(r, 0) + 1
                    # combine-fold attrs ride the route event on every
                    # route (all_to_all, device_buffer, demoted): their
                    # presence on a demoted run is how perf_gate tells
                    # "mesh recovered mid-combine" from "combine off"
                    if s.attrs.get("combine_mode"):
                        comb["folds"] += 1
                        comb["rows_in"] += int(
                            s.attrs.get("combine_rows_in", 0))
                        comb["rows_out"] += int(
                            s.attrs.get("combine_rows_out", 0))
            combine_mix[str(n)] = comb
            per_count[str(n)] = round(rows / best, 1)
            routes[str(n)] = len(evs)
            route_mix[str(n)] = mix
            demoted[str(n)] = sum(1 for s in spans
                                  if s.name == "exchange.demote")
            bytes_moved[str(n)] = sum(int(s.attrs.get("bytes", 0))
                                      for s in evs)
            trace.reset()
        record["rows_per_sec_by_devices"] = per_count
        record["route_all_to_all_by_devices"] = routes
        record["route_mix_by_devices"] = route_mix
        record["route_demoted_by_devices"] = demoted
        record["mesh_bytes_moved_by_devices"] = bytes_moved
        record["combine_by_devices"] = combine_mix
        top = str(max(counts))
        # any multi-device top count MUST have ridden the all-to-all —
        # keyed on the top count itself, not the sweep width, so a
        # single-count AURON_BENCH_MESH_COUNTS=8 run is still verified
        if int(top) > 1 and routes.get(top, 0) < 1 \
                and demoted.get(top, 0) < 1:
            # the mesh path never engaged — the figure would be a lie.
            # (A demotion at the top count is NOT this case: the mesh
            # engaged and recovered — fall through so the run carries
            # the mesh_demoted skip flag instead of failing the gate.)
            record["error"] = (f"no all_to_all route recorded at "
                               f"{top} devices")
        else:
            record["mesh_rows_per_sec"] = per_count[top]
            record["devices"] = int(top)
            # demoted rounds at the gated count: the figure is a
            # recovery-path measurement — recorded for the report,
            # flagged so perf_gate neither fails nor passes the mesh
            # floor on it
            record["mesh_demoted"] = demoted.get(top, 0) > 0
            base = per_count.get(str(counts[0]), 0.0)
            if base:
                record["scaling_factor"] = round(
                    per_count[top] / base, 3)
                record["per_chip_efficiency"] = round(
                    per_count[top] / base / int(top), 4)
    except Exception as e:   # one parseable line, whatever happens
        record["error"] = f"{type(e).__name__}: {e}"[:300]
    finally:
        for key in (cfg.MESH_ENABLED, cfg.MESH_DEVICES, cfg.TRACE_ENABLED,
                    cfg.TRACE_DIR):
            conf.unset(key)
        shutil.rmtree(data, ignore_errors=True)
    faulthandler.cancel_dump_traceback_later()
    print(json.dumps(record))


def _bench_mesh_record() -> dict:
    """Run the mesh scaling child on a forced 8-device virtual CPU mesh
    and return its record (raises on an unusable one — the caller files
    it under ``mesh_error`` so the main record survives additively)."""
    from auron_tpu.utils.envsafe import cpu_child_env
    here = os.path.dirname(os.path.abspath(__file__))
    env = cpu_child_env(here, n_devices=8)
    env.pop("_AURON_BENCH_CHILD", None)
    env["_AURON_BENCH_MESH_CHILD"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)], env=env,
        capture_output=True, text=True, timeout=_MESH_TIMEOUT_S + 60,
        cwd=here)
    lines = [ln for ln in (proc.stdout or "").strip().splitlines()
             if ln.strip()]
    if not lines:
        raise RuntimeError(
            f"mesh child produced no output (rc={proc.returncode}): "
            f"{_condense_error(proc.stderr)}")
    record = json.loads(lines[-1])
    if record.get("error"):
        raise RuntimeError(record["error"])
    return record


def _child_main() -> None:
    import faulthandler
    faulthandler.dump_traceback_later(_BENCH_TIMEOUT_S - 30, exit=True)

    xla_cache = _bind_xla_cache()

    import jax
    platform = jax.devices()[0].platform

    batch, _host = make_batch(0)
    dev_rps = bench_device(batch)
    cpu_rps = bench_cpu_reference(threads=1)
    mc_rps = bench_cpu_reference(threads=os.cpu_count() or 1)
    result = {
        "metric": _METRIC,
        "value": round(dev_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(dev_rps / cpu_rps, 3),
        "vs_baseline_mc": round(dev_rps / mc_rps, 3),
        # hosts with <8 cores cannot measure the >=8-core denominator;
        # BASELINE.md pins linear scaling to 8 threads as the documented
        # stand-in, so scale the measured mc figure by 8/threads
        # (replace with a measured figure on the first >=8-core host)
        "vs_baseline_mc_pinned8": round(
            dev_rps / (mc_rps * max(1.0, 8.0 / (os.cpu_count() or 1))),
            4),
        "baseline_mc_rows_per_sec": round(mc_rps, 1),
        "baseline_mc_threads": os.cpu_count() or 1,
        "platform": platform,
    }
    if platform != "cpu":
        # snapshot the dense on-chip datum BEFORE anything else can fail
        # (round-3 lost its only number to a later wedge)
        _snapshot_partial(result)
    try:
        result["general_agg_rows_per_sec"] = round(
            bench_device_general(batch), 1)
        if platform != "cpu":
            _snapshot_partial(result)   # upgrade the snapshot in place
    except Exception as e:   # additive metric: never lose the dense one
        result["general_agg_error"] = str(e)[:300]
    try:
        # hash-table general path (auron_tpu/hashtable), additive next
        # to the sort-based general number — same snapshot protocol
        result["hash_agg_rows_per_sec"] = round(
            bench_device_hash(batch), 1)
        if platform != "cpu":
            _snapshot_partial(result)
    except Exception as e:   # additive: never lose the earlier data
        result["hash_agg_error"] = str(e)[:300]
    if platform == "tpu":
        # the kernel dispatch would pick on-chip (kernels/dispatch.py):
        # measured additively so the next healthy window reports its
        # vs_baseline_mc_pinned8 alongside the dense number. Gated on
        # tpu EXACTLY: on every other platform the pallas backend runs
        # interpreted — a debug mode, not a datum
        try:
            pallas_rps = bench_device_pallas(batch)
            result["pallas_agg_rows_per_sec"] = round(pallas_rps, 1)
            result["pallas_vs_baseline_mc_pinned8"] = round(
                pallas_rps / (mc_rps * max(1.0, 8.0 / (os.cpu_count()
                                                       or 1))), 4)
            _snapshot_partial(result)
        except Exception as e:   # additive: never lose the dense datum
            result["pallas_agg_error"] = str(e)[:300]
    try:
        # tracing + profiler overhead A/B on the TPC-DS subset (additive
        # — never lose the earlier data; the <2% gates live in PERF.md)
        result.update(bench_trace_overhead())
        if platform != "cpu":
            _snapshot_partial(result)
    except Exception as e:   # additive: never lose the earlier data
        result["trace_overhead_error"] = str(e)[:300]
    try:
        # machine-readable host/device attribution of the q01 operator
        # pipeline (tools/perf_gate.py records it next to the rows/s
        # verdict so a regression arrives with its category split)
        result["profile"] = bench_profile_q01()
    except Exception as e:   # additive: never lose the earlier data
        result["profile_error"] = str(e)[:300]
    try:
        # Fusion 2.0 map-side combine A/B (shuffle-byte reduction +
        # combined-run throughput — the perf_gate --smoke fusion floor)
        result.update(bench_fusion2())
    except Exception as e:   # additive: never lose the earlier data
        result["fusion2_error"] = str(e)[:300]
    # persistent-compile-cache economics of this run (satellite of the
    # pipelined-execution PR: warm rounds stop re-paying q01's tracing)
    result["xla_cache"] = _finish_xla_cache(xla_cache)
    # set when this child is the CPU fallback after an accelerator
    # failure (probe or bench): keeps environmental failures
    # distinguishable from perf regressions in the recorded line
    accel_error = os.environ.get("_AURON_BENCH_ACCEL_ERROR")
    if accel_error:
        result["accel_error"] = accel_error[:500]
    faulthandler.cancel_dump_traceback_later()
    print(json.dumps(result))


# ---------------------------------------------------------------------------
# parent: backend health probe + dispatch
# ---------------------------------------------------------------------------

#: frame locations kept by _condense_error (innermost last)
_CONDENSE_FRAMES = 2


def _condense_error(text: str, frames: int = _CONDENSE_FRAMES) -> str:
    """Reduce a (possibly truncated, multi-line) child stderr — a python
    traceback or a faulthandler watchdog stack dump — to ONE grep-able
    line that LEADS with the exception ``Type: message`` (continuation
    lines of a multi-line message joined in) and then carries the last
    ``frames`` frame locations. The r02–r05 regression this fixes: the
    old condenser kept only a frame location, so every recorded
    ``accel_error`` was a message-less ``[at rt.py:123]`` stub nobody
    could act on."""
    import re
    lines = [ln.strip() for ln in (text or "").strip().splitlines()
             if ln.strip()]
    if not lines:
        return ""
    exc_re = re.compile(
        r"([A-Za-z_][\w.]*(?:Error|Exception|Interrupt|Exit))\b:?\s*(.*)")
    exc = None
    for i in range(len(lines) - 1, -1, -1):
        if lines[i].startswith("Fatal Python error"):
            exc = lines[i]
            break
        m = exc_re.match(lines[i])
        if m:
            # join message continuation lines (a wrapped/multi-line
            # message follows the `Type: head` line until the next
            # structural traceback line)
            parts = [m.group(2).strip()]
            for cont in lines[i + 1:i + 4]:
                if cont.startswith(('File "', "Traceback",
                                    "Current thread", "Thread ",
                                    "The above exception")):
                    break
                parts.append(cont)
            msg = " ".join(p for p in parts if p)
            exc = f"{m.group(1)}: {msg}" if msg else m.group(1)
            break
    frame_lines = [ln for ln in lines if ln.startswith('File "')]
    locs = []
    # tracebacks are most-recent-call-LAST (r05-style faulthandler dumps
    # also end on the faulting frame), so the tail frames are the ones
    # nearest the fault; rendered innermost-first after "at"
    for fl in frame_lines[-max(frames, 1):]:
        m = re.match(r'File "([^"]+)", line (\d+)(?:,? in (.+))?', fl)
        if m:
            loc = f"{os.path.basename(m.group(1))}:{m.group(2)}"
            if m.group(3):
                loc += f" in {m.group(3).strip()}"
            locs.append(loc)
    if exc is None:
        exc = ("backend init failed (stack dump)" if locs else lines[-1])
    if locs:
        exc += " [at " + " < ".join(reversed(locs)) + "]"
    return exc[:300]


def _probe_accelerator():
    """Diagnose the ambient accelerator with the watchdog's structured
    probe ladder (env vars → plugin registration → jax.devices() →
    first-compile smoke), each rung in a sacrificial child with a hard
    deadline — a wedged client hangs, and is killed, with the child.
    Returns the ProbeReport; ``report.ok`` gates the accelerator bench
    arm and ``report.summary()`` is the one-line ``accel_error``."""
    from auron_tpu.runtime import watchdog
    return watchdog.run_probe_ladder(_PROBE_TIMEOUT_S)


def _run_bench_child(env: dict) -> subprocess.CompletedProcess:
    env = dict(env)
    env["_AURON_BENCH_CHILD"] = "1"
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=_BENCH_TIMEOUT_S,
        cwd=os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    if os.environ.get("_AURON_BENCH_MESH_CHILD") == "1":
        _mesh_child_main()
        return
    if os.environ.get("_AURON_BENCH_CHILD") == "1":
        _child_main()
        return

    from auron_tpu.utils.envsafe import cpu_child_env

    accel_error = ""
    accel_ok = False
    probe_report = None
    for attempt in range(_PROBE_ATTEMPTS):
        probe_report = _probe_accelerator()
        accel_ok = probe_report.ok
        if accel_ok:
            break
        accel_error = probe_report.summary()
        if attempt + 1 < _PROBE_ATTEMPTS:
            time.sleep(_PROBE_BACKOFF_S)
    if probe_report is not None:
        # persist the structured diagnosis next to the traces (when
        # auron.trace.dir is configured) — best-effort, never fatal
        try:
            from auron_tpu.runtime import watchdog
            watchdog.write_report(probe_report)
        except Exception:
            pass

    def try_child(env):
        try:
            proc = _run_bench_child(env)
        except subprocess.TimeoutExpired:
            return None, f"bench child exceeded {_BENCH_TIMEOUT_S}s"
        if proc.returncode == 0 and proc.stdout.strip():
            return proc, ""
        return None, (_condense_error(proc.stderr)
                      or f"bench child rc={proc.returncode}")

    proc = None
    if accel_error:
        # environmental: the accelerator never initialized
        accel_error = f"probe: {accel_error}"
    if accel_ok:
        proc, failure = try_child(dict(os.environ))
        if proc is None:
            # the accelerator FAILED MID-BENCH after a HEALTHY probe —
            # likely a product bug on the accelerator path, not an
            # environmental failure; the stage prefix keeps the two
            # distinguishable in the recorded line
            accel_error = f"bench: {failure}"
    if proc is None:
        # CPU fallback env: sanitized so a hostile sitecustomize can't
        # drag the child back onto the broken accelerator
        fallback = cpu_child_env(os.path.dirname(os.path.abspath(__file__)))
        if accel_error:
            fallback["_AURON_BENCH_ACCEL_ERROR"] = accel_error
        proc, failure = try_child(fallback)

    if proc is not None:
        sys.stderr.write(proc.stderr)
        line = proc.stdout.strip().splitlines()[-1]
        # attach the structured backend diagnosis to the child's record:
        # the probe_report (exception TYPE + MESSAGE per ladder rung)
        # replaces log archaeology over the truncated accel_error blobs
        # of BENCH_r02–r05. Best-effort: a non-JSON line passes through.
        try:
            rec = json.loads(line)
        except Exception:
            rec = None
        if rec is not None:
            if probe_report is not None:
                rec["probe_report"] = probe_report.to_dict()
            # SPMD scaling figure (virtual 8-device CPU mesh, own
            # subprocess so it measures regardless of the ambient
            # platform) — additive like every non-headline metric, and
            # a failure records WHY (tools/perf_gate.py fails a record
            # whose mesh section is missing for a reason)
            try:
                rec["mesh"] = _bench_mesh_record()
            except Exception as e:
                rec["mesh_error"] = str(e)[:300]
            line = json.dumps(rec)
        print(line)
        return

    print(json.dumps({"metric": _METRIC, "error": failure,
                      "accel_error": accel_error[:500] or None,
                      "probe_report": (probe_report.to_dict()
                                       if probe_report is not None
                                       else None)}))
    sys.exit(1)


if __name__ == "__main__":
    main()
