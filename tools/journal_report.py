"""Crash-recovery journal report: per-query stage map of what a resume
reused vs recomputed.

Reads a journal directory (``auron.journal.dir``):

- ``report_*.json`` — resume reports persisted by completed journaled
  queries (runtime/journal.QueryJournal.complete): per-exchange
  satisfied / maps skipped / maps recomputed / bytes reused, plus the
  hot-path cost ledger the perf gate reads.
- ``*.journal`` — the PENDING resume inventory: journals of queries
  that have not completed (in-flight, crashed, or awaiting adoption),
  printed with their owner's liveness verdict (utils/liveness) so an
  operator can tell "running right now" from "resumable after a crash"
  at a glance.

    python tools/journal_report.py /path/to/journal/dir
    python tools/journal_report.py dirA --compare dirB

``--compare`` diffs the two directories' aggregate reuse (maps skipped,
bytes reused, hot-path ns) and WARNS when the newer side reuses less —
the regression surface for resume coverage. The last stdout line is one
JSON record (the bench.py / chaos_report.py driver contract).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_reports(dir_: str) -> list:
    out = []
    for path in sorted(glob.glob(os.path.join(dir_, "report_*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
            rec["_path"] = path
            out.append(rec)
        except (OSError, ValueError) as e:
            print(f"  ! unreadable report {path}: {e}")
    return out


def load_inventory(dir_: str) -> list:
    """Pending (not-yet-completed) journals with owner liveness."""
    from auron_tpu import errors
    from auron_tpu.runtime import journal as jrn
    from auron_tpu.utils import liveness
    out = []
    for path in sorted(glob.glob(os.path.join(dir_, "*.journal"))):
        stem = os.path.splitext(os.path.basename(path))[0]
        try:
            header, records, _vl = jrn._read_records(path)
        except errors.JournalError as e:
            out.append({"stem": stem, "state": "corrupt",
                        "error": str(e)})
            continue
        owner = header.get("owner", "")
        commits = sum(1 for r in records if r.get("k") == "c")
        maps = sum(1 for r in records if r.get("k") == "m")
        exchanges = sum(1 for r in records if r.get("k") == "x")
        out.append({
            "stem": stem,
            "query_id": header.get("query_id", ""),
            "plan_fp": header.get("plan_fp", ""),
            "owner": owner,
            "owner_live": liveness.is_live(owner) if owner else None,
            "state": ("in-flight" if owner and liveness.is_live(owner)
                      else "resumable"),
            "exchanges": exchanges,
            "maps_committed": maps,
            "shuffles_committed": commits,
        })
    return out


def summarize(reports: list) -> dict:
    agg = {"queries": len(reports), "maps_skipped": 0,
           "maps_recomputed": 0, "bytes_reused": 0, "hot_ns": 0,
           "satisfied_exchanges": 0, "recomputed_exchanges": 0}
    for rec in reports:
        st = rec.get("stats", {})
        agg["maps_skipped"] += st.get("maps_skipped", 0)
        agg["maps_recomputed"] += st.get("maps_recomputed", 0)
        agg["bytes_reused"] += st.get("bytes_reused", 0)
        agg["hot_ns"] += st.get("hot_ns", 0)
        for entry in st.get("resume_log", {}).values():
            if entry.get("satisfied"):
                agg["satisfied_exchanges"] += 1
            elif entry.get("maps_recomputed"):
                agg["recomputed_exchanges"] += 1
    return agg


def print_report(rec: dict) -> None:
    st = rec.get("stats", {})
    print(f"\nquery {rec.get('query_id', '?')}  "
          f"(journal {rec.get('stem', '?')}, "
          f"plan {rec.get('plan_fp', '?')[:12]})")
    print(f"  hot-path cost: {st.get('hot_ns', 0) / 1e6:.2f} ms over "
          f"{st.get('records', 0)} records / "
          f"{st.get('commits', 0)} commits")
    exchanges = rec.get("exchanges", {})
    resume_log = st.get("resume_log", {})
    if not exchanges:
        print("  (no exchanges journaled)")
        return
    print(f"  {'shuffle':>8} {'kind':>12} {'maps':>5} {'parts':>6} "
          f"{'verdict':>10} {'skipped':>8} {'recomp':>7} "
          f"{'bytes reused':>13}")
    for sid in sorted(exchanges, key=lambda x: int(x)):
        ex = exchanges[sid]
        log = resume_log.get(str(sid), {})
        if log.get("satisfied"):
            verdict = "satisfied"
        elif log.get("maps_skipped") or log.get("maps_recomputed"):
            verdict = "partial"
        else:
            verdict = "fresh"
        print(f"  {sid:>8} {ex.get('kind', '?'):>12} "
              f"{ex.get('maps', 0):>5} {ex.get('partitions', 0):>6} "
              f"{verdict:>10} {log.get('maps_skipped', 0):>8} "
              f"{log.get('maps_recomputed', 0):>7} "
              f"{log.get('bytes_reused', 0):>13,}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("journal_dir", help="auron.journal.dir to report on")
    ap.add_argument("--compare", default=None, metavar="OTHER_DIR",
                    help="second journal dir: diff aggregate reuse "
                         "(positional dir is the NEW side)")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.journal_dir):
        print(f"journal dir not found: {args.journal_dir}")
        print(json.dumps({"error": "no_journal_dir",
                          "dir": args.journal_dir}))
        return 2

    reports = load_reports(args.journal_dir)
    inventory = load_inventory(args.journal_dir)
    agg = summarize(reports)

    print(f"journal dir: {args.journal_dir}")
    print(f"completed resume reports: {len(reports)}   "
          f"pending journals: {len(inventory)}")
    for rec in reports:
        print_report(rec)
    if inventory:
        print("\npending resume inventory:")
        for inv in inventory:
            if inv.get("state") == "corrupt":
                print(f"  {inv['stem']:>24}  CORRUPT  {inv['error']}")
            else:
                print(f"  {inv['stem']:>24}  {inv['state']:>9}  "
                      f"exchanges={inv['exchanges']} "
                      f"maps={inv['maps_committed']} "
                      f"commits={inv['shuffles_committed']} "
                      f"owner={'live' if inv['owner_live'] else 'dead'}")
    print(f"\naggregate: {agg['maps_skipped']} maps skipped / "
          f"{agg['maps_recomputed']} recomputed, "
          f"{agg['bytes_reused']:,} bytes reused, "
          f"{agg['satisfied_exchanges']} exchanges satisfied, "
          f"hot-path {agg['hot_ns'] / 1e6:.2f} ms")

    record = {"dir": args.journal_dir, "aggregate": agg,
              "pending": len(inventory),
              "corrupt": sum(1 for i in inventory
                             if i.get("state") == "corrupt")}
    rc = 0
    if args.compare:
        other = summarize(load_reports(args.compare))
        record["compare"] = {"dir": args.compare, "aggregate": other}
        print(f"\ncompare vs {args.compare}:")
        for key in ("maps_skipped", "bytes_reused",
                    "satisfied_exchanges", "hot_ns"):
            print(f"  {key:>20}: {other[key]:,} -> {agg[key]:,}")
        if other["queries"] and agg["queries"] \
                and agg["maps_skipped"] < other["maps_skipped"]:
            print("  WARNING: resume reuse REGRESSED — the new side "
                  "skipped fewer committed maps than the old")
            record["regressed"] = True
            rc = 1
    print(json.dumps(record))
    return rc


if __name__ == "__main__":
    sys.exit(main())
