"""graftlint report: per-rule / per-package violation table, baseline
health, and cross-run comparison — the house-report face of
``python -m auron_tpu.analysis`` (ANALYSIS.md documents the rules).

    python tools/lint_report.py                      # analyze HEAD
    python tools/lint_report.py --json out.json      # + machine record
    python tools/lint_report.py --compare old.json new.json

A single run prints the rule×package table (baselined / suppressed /
NEW columns), the suppression inventory (every '# graft: disable'
carries its reason — this is where they are audited), and the stale-
baseline list (fixed code whose frozen entries should be pruned).
``--compare`` diffs two ``--json`` records: new rules firing, packages
whose counts grew, and baseline shrinkage — the numbers a PR review
quotes. The last stdout line of a single run is one JSON record.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _package(rel: str) -> str:
    parts = rel.split("/")
    if parts[0] == "auron_tpu" and len(parts) > 2:
        return "/".join(parts[:2])
    return parts[0]


def build_record(baseline_path=None) -> dict:
    from auron_tpu.analysis import core
    result = core.analyze()
    record = result.to_json()
    baseline_path = baseline_path or core.default_baseline_path()
    new = result.violations
    grandfathered: list = []
    stale: list = []
    if os.path.exists(baseline_path):
        baseline = core.load_baseline(baseline_path)
        new, grandfathered, stale = core.apply_baseline(
            result.violations, baseline)
    record["new"] = [v.to_json() for v in new]
    record["grandfathered"] = [v.to_json() for v in grandfathered]
    record["stale_baseline_entries"] = stale
    record["baseline"] = baseline_path if os.path.exists(baseline_path) \
        else None
    # rule × package rollup
    table: dict = {}
    for kind, vs in (("baselined", record["grandfathered"]),
                     ("new", record["new"])):
        for v in vs:
            ent = table.setdefault(
                (v["rule"], _package(v["file"])),
                {"baselined": 0, "new": 0})
            ent[kind] += 1
    record["table"] = [
        {"rule": r, "package": p, **ent}
        for (r, p), ent in sorted(table.items())]
    return record


def print_report(record: dict) -> None:
    print("graftlint report")
    print(f"  files scanned : {record['files_scanned']}")
    print(f"  violations    : "
          f"{len(record['grandfathered']) + len(record['new'])} "
          f"({len(record['new'])} NEW, "
          f"{len(record['grandfathered'])} baselined, "
          f"{record['suppressed']} suppressed)")
    if record["table"]:
        print(f"\n  {'rule':<7} {'package':<22} {'baselined':>9} "
              f"{'new':>5}")
        for row in record["table"]:
            print(f"  {row['rule']:<7} {row['package']:<22} "
                  f"{row['baselined']:>9} {row['new']:>5}")
    for v in record["new"]:
        print(f"\n  NEW {v['file']}:{v['line']}: {v['rule']}: "
              f"{v['message']}")
    inventory = record.get("suppression_inventory", [])
    if inventory:
        print(f"\n  suppression inventory ({len(inventory)} directives "
              f"— every disable carries its reason; used=0 suppresses "
              f"nothing and deserves a look):")
        for d in inventory:
            mark = "" if d["used"] else "  <-- UNUSED"
            print(f"    {d['file']}:{d['line']} "
                  f"[{','.join(d['rules'])}] used={d['used']} — "
                  f"{d['reason'][:60]}{mark}")
    stale = record["stale_baseline_entries"]
    if stale:
        print(f"\n  stale baseline entries ({len(stale)} — fixed code; "
              f"prune with --update-baseline):")
        for e in stale[:20]:
            print(f"    {e['file']} [{e['rule']}] "
                  f"unmatched={e.get('unmatched', '?')} "
                  f"{e['context'][:60]}")
        if len(stale) > 20:
            print(f"    ... and {len(stale) - 20} more")
    if record.get("parse_errors"):
        for rel, msg in record["parse_errors"]:
            print(f"  PARSE ERROR {rel}: {msg}")


def compare(old: dict, new: dict) -> int:
    """Diff two --json records; nonzero when the candidate regressed
    (new violations appeared, or a package's baselined count grew)."""
    def totals(rec):
        out: dict = {}
        for row in rec.get("table", ()):
            key = (row["rule"], row["package"])
            out[key] = row["baselined"] + row["new"]
        return out

    o, n = totals(old), totals(new)
    regressed = False
    print(f"{'rule':<7} {'package':<22} {'old':>6} {'new':>6} {'Δ':>6}")
    for key in sorted(set(o) | set(n)):
        ov, nv = o.get(key, 0), n.get(key, 0)
        if ov == nv == 0:
            continue
        mark = ""
        if nv > ov:
            mark = "  <-- GREW"
            regressed = True
        print(f"{key[0]:<7} {key[1]:<22} {ov:>6} {nv:>6} "
              f"{nv - ov:>+6}{mark}")
    new_count = len(new.get("new", ()))
    if new_count:
        print(f"\ncandidate has {new_count} NEW (unbaselined) violations")
        regressed = True
    shrunk = len(old.get("grandfathered", ())) \
        - len(new.get("grandfathered", ()))
    if shrunk > 0:
        print(f"\nbaseline debt shrank by {shrunk} (good)")
    return 1 if regressed else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default tools/lint_baseline.json)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="also write the machine record to this path")
    ap.add_argument("--compare", nargs=2, metavar=("OLD", "NEW"),
                    help="diff two --json records instead of analyzing")
    args = ap.parse_args(argv)

    if args.compare:
        with open(args.compare[0]) as f:
            old = json.load(f)
        with open(args.compare[1]) as f:
            new = json.load(f)
        return compare(old, new)

    record = build_record(args.baseline)
    print_report(record)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=1)
    print(json.dumps({
        "files_scanned": record["files_scanned"],
        "new": len(record["new"]),
        "baselined": len(record["grandfathered"]),
        "suppressed": record["suppressed"],
        "stale": len(record["stale_baseline_entries"]),
    }))
    return 0 if not record["new"] else 1


if __name__ == "__main__":
    sys.exit(main())
