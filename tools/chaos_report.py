"""Chaos-battery sweep report: injected faults vs recovery outcome.

Runs the chaos harness (auron_tpu/it/chaos.py) across N seeds for every
(scenario, fault plan) pair of the battery and prints a site-by-site
table: how many faults each plan injected, how many runs recovered to
bit-identical output, how many surfaced a classified ``AuronError`` —
and, the failure buckets, how many diverged silently (``mismatch``) or
crashed unclassified. A non-zero exit means the robustness contract
broke somewhere in the sweep; the failing (plan, seed) pairs replay
exactly via ``auron.faults.plan`` / ``auron.faults.seed``.

    python tools/chaos_report.py                   # default 8 seeds
    python tools/chaos_report.py --seeds 32
    python tools/chaos_report.py --scenario spill_sort

The last stdout line is one JSON record (same driver contract as
bench.py / compile_report.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

# CPU mesh before jax init: chaos verifies recovery logic, not device
# perf — it must run on a wedged-accelerator host
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xf = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xf:
    os.environ["XLA_FLAGS"] = (
        _xf + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the battery's (scenario, plan) pairs — one per site/kind with traffic
PLANS = [
    ("rss_pipeline", "rss.write:io_error@0.2"),
    ("rss_pipeline", "rss.write:corrupt@0.3"),
    ("rss_pipeline", "rss.flush:io_error@0.4"),
    ("rss_pipeline", "rss.commit:fatal@0.5"),
    ("rss_pipeline", "rss.fetch:corrupt@0.1"),
    ("rss_pipeline", "rss.fetch:io_error@0.3"),
    ("spill_sort", "spill.write:io_error@0.3"),
    ("spill_sort", "spill.write:corrupt@0.4"),
    ("spill_sort", "spill.read:io_error@0.4"),
    ("spill_sort", "spill.read:corrupt@0.15"),
    ("agg_pipeline", "device.compute:io_error@0.3"),
    ("agg_pipeline", "device.compute:fatal@0.5"),
    ("agg_pipeline", "program.build:io_error@0.2"),
    ("agg_pipeline", "device.compute:io_error@0.2;rss.fetch:corrupt@0.1"),
    # Chaos 2.0 lifecycle battery (PR 8): cancel races, stall-watchdog
    # hangs, forced memory-pressure sheds
    ("lifecycle_pipeline", "cancel.race:cancel@0.3"),
    ("lifecycle_pipeline", "task.hang:hang@0.15"),
    ("lifecycle_pipeline", "memmgr.deny:deny@0.5"),
    ("lifecycle_pipeline", "cancel.race:cancel@0.2;task.hang:hang@0.1"),
    # concurrency battery (the [serving] scheduler plane): admission
    # denies + forced memory pressure against racing queries
    ("overload", "sched.admit:deny@0.5"),
    ("overload", "memmgr.deny:deny@0.4"),
    ("overload", "sched.admit:deny@0.3;memmgr.deny:deny@0.3"),
    # mesh fault domain (ISSUE 12): per-round device losses recover by
    # route demotion (identical, not merely classified), hangs drive
    # the straggler defense, gang-door cancels dequeue cleanly
    ("mesh_pipeline", "mesh.all_to_all:io_error@0.3"),
    ("mesh_pipeline", "mesh.all_to_all:fatal@0.5"),
    ("mesh_pipeline", "mesh.all_to_all:hang@0.15"),
    ("mesh_pipeline", "mesh.gang:cancel@0.5"),
    ("mesh_pipeline",
     "mesh.all_to_all:io_error@0.2;device.compute:io_error@0.1"),
    # crash-safe query journal (ISSUE 13): write/commit faults must
    # DEGRADE journaling (journal.disable), never the query — every run
    # identical, no journal file left behind
    ("journal_pipeline", "journal.write:io_error@0.3"),
    ("journal_pipeline", "journal.write:fatal@0.5"),
    ("journal_pipeline", "journal.commit:io_error@0.5"),
    ("journal_pipeline",
     "journal.write:io_error@0.2;rss.write:io_error@0.2"),
    # serving fleet (ISSUE 19): a replica SIGKILLed mid-query every run
    # (the scenario's own drill) PLUS seeded faults on the router's own
    # sites — routing errors and forward-leg breaks must end in a
    # spill-over, a failover, or a classified verdict, with the shared
    # journal dir clean after teardown
    ("fleet_failover", "fleet.route:io_error@0.25"),
    ("fleet_failover", "fleet.forward:io_error@0.25"),
    ("fleet_failover",
     "fleet.route:io_error@0.15;fleet.forward:io_error@0.15"),
]


def lifecycle_summary() -> dict:
    """Process-level lifecycle telemetry accumulated over the sweep:
    cancel-to-unwind latency percentiles per kind (the registry
    histogram the acceptance gate reads), stall detections, and
    degradation-ladder rung counts."""
    out = {"cancel_latency_s": {}, "stall_detections": 0,
           "pressure_rungs": {}, "admission_sheds": {}}
    try:
        from auron_tpu.obs import registry as obs_registry
        snap = obs_registry.get_registry().snapshot()
        for key, val in snap.items():
            if key.startswith("auron_cancel_latency_seconds"):
                kind = key.split('kind="')[1].rstrip('"}') \
                    if 'kind="' in key else "all"
                out["cancel_latency_s"][kind] = {
                    "count": val["count"],
                    "p50": round(val["p50"], 4),
                    "p99": round(val["p99"], 4)}
            elif key.startswith("auron_memmgr_pressure_total"):
                rung = key.split('rung="')[1].rstrip('"}') \
                    if 'rung="' in key else "?"
                out["pressure_rungs"][rung] = int(val)
            elif key.startswith("auron_sched_rejected_total"):
                reason = key.split('reason="')[1].rstrip('"}') \
                    if 'reason="' in key else "?"
                out["admission_sheds"][reason] = int(val)
    except Exception:
        pass
    try:
        from auron_tpu.runtime import watchdog
        out["stall_detections"] = watchdog.stall_totals()
    except Exception:
        pass
    return out


def mesh_summary() -> dict:
    """Mesh-recovery telemetry accumulated over the sweep: route
    demotions by reason (device_loss vs straggler), device-loss
    quarantines, straggler detections and stall verdicts the round
    guard downgraded to slow rounds — the fault domain's ledger
    alongside the per-(plan, seed) contract table."""
    out = {"demotions": {}, "quarantines": 0, "stragglers": 0,
           "rounds_forgiven": 0, "device_losses": 0}
    try:
        from auron_tpu.obs import registry as obs_registry
        snap = obs_registry.get_registry().snapshot()
        for key, val in snap.items():
            if key.startswith("auron_mesh_demotions_total"):
                reason = key.split('reason="')[1].rstrip('"}') \
                    if 'reason="' in key else "?"
                out["demotions"][reason] = int(val)
            elif key.startswith("auron_mesh_quarantines_total"):
                out["quarantines"] = int(val)
            elif key.startswith("auron_mesh_stragglers_total"):
                out["stragglers"] = int(val)
    except Exception:
        pass
    try:
        from auron_tpu.runtime import watchdog
        out["rounds_forgiven"] = watchdog.mesh_rounds_forgiven()
    except Exception:
        pass
    try:
        from auron_tpu.parallel import mesh as mesh_mod
        plane = mesh_mod._PLANE[1]
        if plane is not None:
            out["device_losses"] = plane.device_losses
    except Exception:
        pass
    return out


def run_sweep(seeds: int, scenario_filter: str | None) -> dict:
    from auron_tpu.it import chaos

    rows = []
    failures = []
    sites: dict = {}
    with tempfile.TemporaryDirectory(prefix="chaos_report_") as d:
        scenarios = {name: factory(os.path.join(d, name))
                     for name, factory in chaos.SCENARIOS.items()}
        for scen_name, plan in PLANS:
            if scenario_filter and scen_name != scenario_filter:
                continue
            agg = {"identical": 0, "classified": 0, "mismatch": 0,
                   "unclassified": 0}
            injected = 0
            leaked = 0
            for seed in range(1, seeds + 1):
                o = chaos.run_chaos(scenarios[scen_name], plan, seed)
                agg[o.status] += 1
                injected += sum(sum(v.values())
                                for v in o.injected.values())
                leaked += len(o.leaks)
                # site→span correlation, aggregated across the sweep:
                # each injected site accumulates the recovery spans its
                # faults triggered (trace ids make single runs
                # replayable/inspectable)
                for site, c in o.correlation.items():
                    s = sites.setdefault(
                        site, {"injected": 0, "recovery": {}, "runs": 0})
                    s["injected"] += c["injected"]
                    s["runs"] += 1
                    for name, n in c["recovery"].items():
                        s["recovery"][name] = \
                            s["recovery"].get(name, 0) + n
                if not o.ok:
                    failures.append({
                        "scenario": scen_name, "plan": plan, "seed": seed,
                        "status": o.status, "error_type": o.error_type,
                        "error": o.error, "leaks": o.leaks,
                        "trace_id": o.trace_id})
            rows.append({"scenario": scen_name, "plan": plan,
                         "injected": injected, "leaked": leaked, **agg})
    return {"seeds": seeds, "rows": rows, "failures": failures,
            "sites": sites, "lifecycle": lifecycle_summary(),
            "mesh": mesh_summary()}


def print_table(report: dict) -> None:
    w_plan = max(len(r["plan"]) for r in report["rows"])
    hdr = (f"{'scenario':13s} {'fault plan':{w_plan}s} {'inj':>5s} "
           f"{'ident':>5s} {'class':>5s} {'mism':>4s} {'uncls':>5s} "
           f"{'leak':>4s}")
    print(hdr)
    print("-" * len(hdr))
    for r in report["rows"]:
        print(f"{r['scenario']:13s} {r['plan']:{w_plan}s} "
              f"{r['injected']:>5d} {r['identical']:>5d} "
              f"{r['classified']:>5d} {r['mismatch']:>4d} "
              f"{r['unclassified']:>5d} {r['leaked']:>4d}")
    total = {k: sum(r[k] for r in report["rows"])
             for k in ("injected", "identical", "classified", "mismatch",
                       "unclassified", "leaked")}
    print("-" * len(hdr))
    print(f"{'TOTAL':13s} {'':{w_plan}s} {total['injected']:>5d} "
          f"{total['identical']:>5d} {total['classified']:>5d} "
          f"{total['mismatch']:>4d} {total['unclassified']:>5d} "
          f"{total['leaked']:>4d}")
    sites = report.get("sites") or {}
    if sites:
        print()
        print("site -> recovery-span correlation "
              "(fault events linked to the recovery they triggered)")
        w_site = max(len(s) for s in sites)
        for site in sorted(sites):
            s = sites[site]
            rec = ", ".join(f"{k}x{v}"
                            for k, v in sorted(s["recovery"].items())) \
                or "-"
            print(f"  {site:{w_site}s}  injected={s['injected']:<5d} "
                  f"runs={s['runs']:<4d} recovery: {rec}")
    life = report.get("lifecycle") or {}
    if life.get("cancel_latency_s") or life.get("stall_detections") \
            or life.get("pressure_rungs") or life.get("admission_sheds"):
        print()
        print("lifecycle (cancel latency / stalls / pressure rungs / "
              "admission sheds)")
        for kind, p in sorted(life.get("cancel_latency_s", {}).items()):
            print(f"  cancel->unwind [{kind:9s}]  n={p['count']:<4d} "
                  f"p50={p['p50']*1000:.1f}ms p99={p['p99']*1000:.1f}ms")
        print(f"  stall detections: {life.get('stall_detections', 0)}")
        rungs = ", ".join(f"{k}x{v}" for k, v in
                          sorted(life.get("pressure_rungs", {}).items())) \
            or "-"
        print(f"  degradation rungs taken: {rungs}")
        sheds = ", ".join(f"{k}x{v}" for k, v in
                          sorted(life.get("admission_sheds", {}).items())) \
            or "-"
        print(f"  admission sheds: {sheds}")
    m = report.get("mesh") or {}
    if m.get("demotions") or m.get("quarantines") or m.get("stragglers") \
            or m.get("rounds_forgiven"):
        print()
        print("mesh recovery (route demotions / quarantines / "
              "straggler defense)")
        dem = ", ".join(f"{k}x{v}" for k, v in
                        sorted(m.get("demotions", {}).items())) or "-"
        print(f"  route demotions by reason: {dem}")
        print(f"  device-loss quarantines: {m.get('quarantines', 0)} "
              f"(losses recorded: {m.get('device_losses', 0)})")
        print(f"  straggler rounds: {m.get('stragglers', 0)} "
              f"(stall verdicts forgiven as slow rounds: "
              f"{m.get('rounds_forgiven', 0)})")
    for f in report["failures"]:
        print(f"CONTRACT BROKEN: {f['scenario']} plan={f['plan']!r} "
              f"seed={f['seed']} trace={f.get('trace_id', 0)} -> "
              f"{f['status']} ({f['error_type']}: {f['error']}) "
              f"leaks={f['leaks']}")


def run_crash(kill_points=None) -> dict:
    """The subprocess crash sweep (auron_tpu/it/chaos.run_crash_sweep):
    a child Session SIGKILLed at every journal stage boundary of the
    two-exchange crash query, the parent resuming each time. Reported
    like the seeded battery: identical-or-classified, zero leaks."""
    from auron_tpu.it import chaos
    outs = chaos.run_crash_sweep(kill_points=kill_points)
    rows = [{"kill_point": o.kill_point, "child_rc": o.child_rc,
             "status": o.status, "error_type": o.error_type,
             "maps_skipped": o.maps_skipped,
             "maps_recomputed": o.maps_recomputed,
             "bytes_reused": o.bytes_reused,
             "resume_wall_s": round(o.resume_wall_s, 3),
             "leaks": o.leaks} for o in outs]
    return {"rows": rows, "ok": all(o.ok for o in outs)}


def print_crash(report: dict) -> None:
    hdr = (f"{'kill@':>5s} {'rc':>4s} {'status':>10s} {'skip':>5s} "
           f"{'recomp':>6s} {'bytes reused':>13s} {'resume s':>8s} "
           f"{'leaks':>5s}")
    print("crash sweep (child SIGKILLed at every journal boundary, "
          "parent resumes)")
    print(hdr)
    print("-" * len(hdr))
    for r in report["rows"]:
        print(f"{r['kill_point']:>5d} {r['child_rc']:>4d} "
              f"{r['status']:>10s} {r['maps_skipped']:>5d} "
              f"{r['maps_recomputed']:>6d} {r['bytes_reused']:>13,d} "
              f"{r['resume_wall_s']:>8.3f} {len(r['leaks']):>5d}")
    for r in report["rows"]:
        if r["status"] not in ("identical", "classified", "completed") \
                or r["leaks"]:
            print(f"CONTRACT BROKEN: kill@{r['kill_point']} -> "
                  f"{r['status']} ({r.get('error_type')}) "
                  f"leaks={r['leaks']}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=8,
                    help="seeds per (scenario, plan) pair")
    ap.add_argument("--scenario", choices=["rss_pipeline", "spill_sort",
                                           "agg_pipeline",
                                           "mesh_pipeline",
                                           "lifecycle_pipeline",
                                           "overload",
                                           "journal_pipeline",
                                           "fleet_failover"],
                    default=None)
    ap.add_argument("--crash", action="store_true",
                    help="run the subprocess crash sweep (SIGKILL at "
                         "every journal stage boundary + resume) "
                         "instead of the seeded fault battery")
    args = ap.parse_args(argv)

    if args.crash:
        report = run_crash()
        print_crash(report)
        print(json.dumps({"crash_points": len(report["rows"]),
                          "crash_rows": report["rows"],
                          "crash_contract_ok": report["ok"]}))
        return 0 if report["ok"] else 1

    report = run_sweep(args.seeds, args.scenario)
    print_table(report)
    ok = not report["failures"]
    print(json.dumps({"chaos_seeds": report["seeds"],
                      "chaos_runs": sum(
                          sum(r[k] for k in ("identical", "classified",
                                             "mismatch", "unclassified"))
                          for r in report["rows"]),
                      "chaos_injected": sum(r["injected"]
                                            for r in report["rows"]),
                      "chaos_sites": report.get("sites") or {},
                      "chaos_lifecycle": report.get("lifecycle") or {},
                      "chaos_mesh": report.get("mesh") or {},
                      "chaos_contract_ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
