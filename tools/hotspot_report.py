"""Host-overhead hotspot report: where did the wall time actually go.

Reads the per-task attribution records the profiler exports next to the
traces (``profile_*.jsonl`` in ``auron.trace.dir`` — one line per
operator instance per finished task, obs/profile.export_task) and ranks
the host-overhead sinks: a category × operator table over the
attribution buckets (``device``, ``dispatch``, ``convert``, ``serde``,
``iter``, ``other``), per-category totals, and the top-N individual
(category, operator) sinks. This is the tool that answers the ROADMAP
[speed] question — "where did q01's 400× gap vs the pandas baseline
go" — with numbers instead of a guess:

    python tools/hotspot_report.py /tmp/trace_dir
    python tools/hotspot_report.py /tmp/trace_dir --top 8
    python tools/hotspot_report.py --compare /tmp/base /tmp/candidate

``--compare`` diffs two trace dirs by per-category totals (A/B runs:
profiler-guided fix vs baseline). The last stdout line is one JSON
record (the bench.py / trace_report.py driver contract).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: attribution categories, display order (device first, then the host
#: buckets by typical magnitude)
CATEGORIES = ("device", "dispatch", "convert", "serde", "iter", "other")

_METRIC_FOR = {"device": "elapsed_device"}
_METRIC_FOR.update({b: "elapsed_host_" + b for b in CATEGORIES[1:]})


def load_dir(trace_dir: str) -> list[dict]:
    files = sorted(glob.glob(os.path.join(trace_dir, "profile_*.jsonl")))
    if not files:
        raise SystemExit(
            f"no profile_*.jsonl files under {trace_dir!r} (run with "
            "auron.profile.enabled + auron.trace.dir set)")
    records = []
    for path in files:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
    return records


def aggregate(records: list[dict]) -> dict:
    """{(category, op): total_ns} plus per-category and per-op rollups
    (and per-op output rows/batches — the achieved batch size, so
    batch-size experiments read straight off the table)."""
    cells: dict = {}
    compute_ns: dict = {}
    rows: dict = {}
    batches: dict = {}
    for r in records:
        op = r.get("op", "?")
        metrics = r.get("metrics", {})
        compute_ns[op] = compute_ns.get(op, 0) + \
            metrics.get("elapsed_compute", 0)
        rows[op] = rows.get(op, 0) + metrics.get("output_rows", 0)
        batches[op] = batches.get(op, 0) + metrics.get("output_batches", 0)
        for cat in CATEGORIES:
            v = metrics.get(_METRIC_FOR[cat], 0)
            if v:
                cells[(cat, op)] = cells.get((cat, op), 0) + v
    by_cat = {c: 0 for c in CATEGORIES}
    by_op: dict = {}
    for (cat, op), ns in cells.items():
        by_cat[cat] += ns
        by_op[op] = by_op.get(op, 0) + ns
    return {"cells": cells, "by_cat": by_cat, "by_op": by_op,
            "compute_ns": compute_ns, "rows": rows, "batches": batches}


def _ms(ns: int) -> float:
    return round(ns / 1e6, 2)


def report(agg: dict, top: int = 10) -> dict:
    host_cats = {c: _ms(v) for c, v in agg["by_cat"].items()
                 if c != "device" and v}
    # the headline: host-overhead categories ranked by total time
    top_categories = sorted(host_cats.items(), key=lambda kv: -kv[1])
    top_sinks = sorted(
        ((cat, op, _ms(ns)) for (cat, op), ns in agg["cells"].items()
         if cat != "device"),
        key=lambda t: -t[2])[:top]
    compute_ms = _ms(sum(agg["compute_ns"].values()))
    attributed_ms = _ms(agg["by_cat"]["device"]) + \
        round(sum(host_cats.values()), 2)
    return {
        "device_ms": _ms(agg["by_cat"]["device"]),
        "host_ms": round(sum(host_cats.values()), 2),
        "host_categories_ms": dict(top_categories),
        "top_host_categories": [c for c, _v in top_categories[:3]],
        "top_sinks": [{"category": c, "op": o, "ms": m}
                      for c, o, m in top_sinks],
        # achieved batch sizes (output rows / output batches per op) —
        # the auron.scan.batch_rows experiment readout
        "rows_per_batch": {
            op: round(agg["rows"][op] / agg["batches"][op], 1)
            for op in agg.get("batches", {})
            if agg["batches"].get(op)},
        # attribution coverage: how much of the timers' measured wall
        # the buckets explain (convert/serde/iter live OUTSIDE
        # elapsed_compute, so >100% is normal on scan-heavy plans)
        "compute_ms": compute_ms,
        "attributed_pct": (round(attributed_ms / compute_ms * 100.0, 1)
                           if compute_ms else None),
    }


def _rows_per_batch(agg: dict, op: str):
    b = agg.get("batches", {}).get(op, 0)
    return (agg.get("rows", {}).get(op, 0) / b) if b else None


def print_table(agg: dict, rep: dict, top: int) -> None:
    ops = sorted(agg["by_op"], key=lambda o: -agg["by_op"][o])
    print("category × operator attribution (ms):")
    header = f"{'operator':24s}" + "".join(f"{c:>10s}" for c in CATEGORIES)
    header += f"{'rows/batch':>12s}"
    print(header)
    for op in ops:
        row = f"{op[:24]:24s}"
        for cat in CATEGORIES:
            row += f"{_ms(agg['cells'].get((cat, op), 0)):>10.1f}"
        rpb = _rows_per_batch(agg, op)
        row += f"{rpb:>12.0f}" if rpb is not None else f"{'-':>12s}"
        print(row)
    total_row = f"{'TOTAL':24s}"
    for cat in CATEGORIES:
        total_row += f"{_ms(agg['by_cat'][cat]):>10.1f}"
    print(total_row)
    print(f"\ndevice total: {rep['device_ms']}ms   "
          f"host total: {rep['host_ms']}ms   "
          f"(timers' elapsed_compute: {rep['compute_ms']}ms)")
    print("top host-overhead categories: "
          + ", ".join(f"{c}={rep['host_categories_ms'][c]}ms"
                      for c in rep["top_host_categories"]))
    print(f"\ntop-{top} host-overhead sinks:")
    for s in rep["top_sinks"]:
        print(f"  {s['ms']:>10.1f}ms  {s['category']:9s} {s['op']}")


def _compare(base_dir: str, cand_dir: str) -> int:
    base = aggregate(load_dir(base_dir))
    cand = aggregate(load_dir(cand_dir))
    print(f"{'category':10s} {'base_ms':>10s} {'cand_ms':>10s} "
          f"{'delta':>8s}")
    deltas = {}
    for cat in CATEGORIES:
        b, c = _ms(base["by_cat"][cat]), _ms(cand["by_cat"][cat])
        # None (not inf) for a category absent from base: json.dumps
        # would emit the non-RFC 'Infinity' token otherwise
        pct = round((c - b) / b * 100.0, 2) if b else (None if c else 0.0)
        deltas[cat] = {"base_ms": b, "cand_ms": c, "delta_pct": pct}
        shown = "new" if pct is None else f"{pct:.1f}%"
        print(f"{cat:10s} {b:>10.1f} {c:>10.1f} {shown:>8s}")
    print(json.dumps({"categories": deltas}))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace_dir", nargs="?", default=None,
                    help="directory holding profile_*.jsonl files")
    ap.add_argument("--top", type=int, default=10,
                    help="individual (category, operator) sinks listed")
    ap.add_argument("--compare", nargs=2, metavar=("BASE", "CANDIDATE"),
                    default=None,
                    help="diff two trace dirs by per-category totals")
    args = ap.parse_args(argv)
    if args.compare:
        return _compare(args.compare[0], args.compare[1])
    if not args.trace_dir:
        ap.error("trace_dir (or --compare) is required")
    records = load_dir(args.trace_dir)
    agg = aggregate(records)
    rep = report(agg, args.top)
    print_table(agg, rep, args.top)
    print(json.dumps(dict(rep, profile_records=len(records))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
