"""Microbench: dense group-aggregate kernel variants on the live chip.

The flagship kernel's einsum currently runs at Precision.HIGHEST — on TPU
that is ~6 bf16 passes per [n,512]x[n,256] contraction. Variants here
restructure the work so exact parts (one-hot counts) pay 1 pass and the
value operand pays 2-3 additive bf16-split passes, and measure accuracy
against the f64 host reference.

Run: python tools/microbench_q01.py  (uses the ambient accelerator)
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

_GRID = 256
_DOMAIN = _GRID * _GRID


def make_inputs(n, seed=0):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, _DOMAIN, size=n).astype(np.int32)
    v = rng.normal(size=n).astype(np.float32)
    c = (rng.random(n) > 0.05).astype(np.float32)
    return jnp.asarray(k), jnp.asarray(v), jnp.asarray(c)


def ref_sums_counts(k, v, c):
    k = np.asarray(k)
    v = np.asarray(v, np.float64)
    c = np.asarray(c, np.float64)
    sums = np.zeros(_DOMAIN)
    cnts = np.zeros(_DOMAIN)
    np.add.at(sums, k, v * c)
    np.add.at(cnts, k, c)
    return sums, cnts


def v_current(kb, vb, cb):
    """Today's kernel: stacked lhs, HIGHEST f32 einsum."""
    def block(inp):
        kk, vals, cnts = inp
        hi = jax.nn.one_hot(kk >> 8, _GRID, dtype=jnp.float32)
        lo = jax.nn.one_hot(kk & 255, _GRID, dtype=jnp.float32)
        lhs = jnp.concatenate([hi * (vals * cnts)[:, None],
                               hi * cnts[:, None]], axis=1)
        out = jnp.einsum("nh,nl->hl", lhs, lo,
                         precision=lax.Precision.HIGHEST,
                         preferred_element_type=jnp.float32)
        return out[:_GRID], out[_GRID:]
    s, c = lax.map(block, (kb, vb, cb))
    return jnp.sum(s, axis=0), jnp.sum(c, axis=0)


def _mask_hi(x):
    """Top-16-bit truncation of f32 via opaque bit ops: exactly
    bf16-representable, and XLA's bf16-propagation pass cannot fold the
    residual x - _mask_hi(x) to zero (it does fold f32->bf16->f32 convert
    pairs, silently collapsing a convert-based split to 1 term)."""
    bits = lax.bitcast_convert_type(x, jnp.uint32)
    return lax.bitcast_convert_type(bits & jnp.uint32(0xFFFF0000),
                                    jnp.float32)


def make_masked_variant(terms):
    """Split the value operand into `terms` additive bf16-exact f32 arrays;
    one stacked DEFAULT-precision matmul (1 bf16 pass per term + 1 for
    counts) replaces HIGHEST's 6 passes over the double-height lhs."""
    def v_split(kb, vb, cb):
        def block(inp):
            kk, vals, cnts = inp
            hi_ids = kk >> 8
            lo = jax.nn.one_hot(kk & 255, _GRID, dtype=jnp.float32)
            hv = jax.nn.one_hot(hi_ids, _GRID, dtype=jnp.float32) \
                * (vals * cnts)[:, None]
            parts, rem = [], hv
            for _ in range(terms - 1):
                p = _mask_hi(rem)
                parts.append(p)
                rem = rem - p
            parts.append(rem)
            hi_c = jax.nn.one_hot(hi_ids, _GRID, dtype=jnp.float32) \
                * cnts[:, None]
            lhs = jnp.concatenate(parts + [hi_c], axis=1)
            out = jnp.einsum("nh,nl->hl", lhs, lo,
                             precision=lax.Precision.DEFAULT,
                             preferred_element_type=jnp.float32)
            sums = out[:_GRID]
            for t in range(1, terms):
                sums = sums + out[t * _GRID:(t + 1) * _GRID]
            return sums, out[terms * _GRID:]
        s, c = lax.map(block, (kb, vb, cb))
        return jnp.sum(s, axis=0), jnp.sum(c, axis=0)
    return v_split


def make_f32_lhs_bf16_rhs(prec):
    """f32 lhs, bf16-exact rhs, per-operand precision tuple."""
    def v(kb, vb, cb):
        def block(inp):
            kk, vals, cnts = inp
            hi = jax.nn.one_hot(kk >> 8, _GRID, dtype=jnp.float32)
            lo = jax.nn.one_hot(kk & 255, _GRID, dtype=jnp.float32)
            lhs = jnp.concatenate([hi * (vals * cnts)[:, None],
                                   hi * cnts[:, None]], axis=1)
            out = jnp.einsum("nh,nl->hl", lhs, lo, precision=prec,
                             preferred_element_type=jnp.float32)
            return out[:_GRID], out[_GRID:]
        s, c = lax.map(block, (kb, vb, cb))
        return jnp.sum(s, axis=0), jnp.sum(c, axis=0)
    return v


def bench(name, fn, k, v, c, n, block, iters=10):
    nb = n // block
    kb = k.reshape(nb, block)
    cb = c.reshape(nb, block)
    # distinct value inputs per iteration: identical (executable, inputs)
    # pairs can be served from an execution cache over the tunnel, which
    # times pure RPC instead of compute
    vbs = [(v + jnp.float32(i)).reshape(nb, block) for i in range(iters)]
    jax.block_until_ready(vbs)
    jf = jax.jit(fn)
    out = jf(kb, v.reshape(nb, block), cb)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    outs = [jf(kb, vb_i, cb) for vb_i in vbs]
    jax.block_until_ready(outs)
    dt = (time.perf_counter() - t0) / iters
    out = jf(kb, v.reshape(nb, block), cb)
    sums, cnts = out
    sums = np.asarray(sums, np.float64).reshape(-1)
    cnts = np.asarray(cnts, np.float64).reshape(-1)
    rs, rc = ref_sums_counts(k, v, c)
    s_err = float(np.max(np.abs(np.asarray(sums, np.float64) - rs))
                  / max(1.0, np.max(np.abs(rs))))
    c_err = float(np.max(np.abs(np.asarray(cnts, np.float64) - rc)))
    print(f"{name:28s} block={block:6d} {n / dt / 1e6:9.1f} M rows/s "
          f"rel_sum_err={s_err:.2e} abs_cnt_err={c_err:.1f}")
    return n / dt


if __name__ == "__main__":
    print("devices:", jax.devices())
    n = 1 << 20
    k, v, c = make_inputs(n)
    for block in (1 << 14, 1 << 16):
        bench("current_highest", v_current, k, v, c, n, block)
    for block in (1 << 14, 1 << 15, 1 << 16, 1 << 17):
        bench("mask2", make_masked_variant(2), k, v, c, n, block)
        bench("mask3", make_masked_variant(3), k, v, c, n, block)
