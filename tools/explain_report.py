"""EXPLAIN ANALYZE report: run a suite query, print the annotated plan.

Runs one (or every) TPC-DS / TPC-H query through the engine with a
mirrored metric tree (obs/metric_tree.py — the positional
update_metric_node walk of the reference, rt.rs:302-308) and prints
each plan node annotated with what actually happened: elapsed_compute,
output_rows/batches, spill and shuffle counters, dispatch decisions.

    python tools/explain_report.py --suite tpcds --query q3
    python tools/explain_report.py --suite tpcds --scale 0.02 --query all

Each suite Query collects internally, so the tool captures the query's
top-level DataFrame by hooking Session.execute, then re-runs it under
``explain(analyze=True)``.

The last stdout line is one JSON record (driver contract shared with
bench.py / compile_report.py): per-query node counts plus the
zero-metric audit (plan nodes whose elapsed_compute or output_rows
stayed zero — the acceptance gate wants none on a served query).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# CPU mesh before jax init (accounting tool, not a perf gate)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xf = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xf:
    os.environ["XLA_FLAGS"] = (
        _xf + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def analyze_query(session, q, tables) -> dict:
    """Capture the query's top-level DataFrame (the LAST Session.execute
    — the final .collect()) and re-run it with a mirrored metric tree."""
    from auron_tpu.obs import metric_tree as mt

    captured = {}
    original = session.execute

    def capturing_execute(df):
        captured["df"] = df
        return original(df)

    session.execute = capturing_execute
    try:
        q.run(session, tables)
    finally:
        session.execute = original
    df = captured.get("df")
    if df is None:
        raise RuntimeError(f"{q.name}: no DataFrame execution captured")
    op = session.plan_physical(df)
    tree, table = mt.explain_analyze(
        op, num_partitions=df.num_partitions,
        mem_manager=session.mem_manager, config=session.config)
    zero = [n.op_repr for n in tree.walk()
            if not n.metrics.get("elapsed_compute")
            or not n.metrics.get("output_rows")]
    return {"render": mt.render(tree), "totals": mt.totals(tree),
            "rows": table.num_rows, "zero_metric_nodes": zero}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--suite", default="tpcds", choices=["tpcds", "tpch"])
    ap.add_argument("--scale", type=float, default=0.02)
    ap.add_argument("--query", default="q3", help="query name, or 'all'")
    ap.add_argument("--data", default=None,
                    help="reuse/create the dataset in this directory")
    args = ap.parse_args(argv)

    import tempfile

    if args.suite == "tpcds":
        from auron_tpu.it.tpcds import generate
        from auron_tpu.it.tpcds_queries import QUERIES
    else:
        from auron_tpu.it.tpch import generate
        from auron_tpu.it.tpch_queries import QUERIES
    from auron_tpu.frontend.session import Session

    data_dir = args.data or tempfile.mkdtemp(prefix="explain_report_")
    tables = generate(data_dir, scale=args.scale)
    names = None if args.query == "all" else {args.query}

    out = []
    for q in QUERIES:
        if names and q.name not in names:
            continue
        try:
            res = analyze_query(Session(), q, tables)
        except Exception as e:   # noqa: BLE001 — report, don't abort
            out.append({"query": q.name,
                        "error": f"{type(e).__name__}: {e}"})
            print(f"== {q.name}: ERROR {str(e)[:200]}")
            continue
        print(f"== {q.name} ({res['rows']} rows) ==")
        print(res["render"], end="")
        t = res["totals"]
        print(f"-- nodes={t['nodes']} elapsed={t['elapsed_compute_ms']}ms "
              f"rows={t['output_rows']} "
              f"zero_metric_nodes={len(res['zero_metric_nodes'])}")
        out.append({"query": q.name, "nodes": t["nodes"],
                    "elapsed_compute_ms": t["elapsed_compute_ms"],
                    "rows": res["rows"],
                    "zero_metric_nodes": res["zero_metric_nodes"]})
    print(json.dumps({"suite": args.suite, "scale": args.scale,
                      "queries": out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
