"""Perf regression gate: fresh q01 bench vs the checked-in baseline.

The ROADMAP [speed] item's third front: q01 CPU throughput decayed
276k → 108k rows/s across BENCH_r03→r05 and nobody noticed until the
round-5 verdict read the history side by side. This gate makes that
trajectory a failing exit code: it takes a fresh ``bench.py`` record
(or one from a file/stdin), looks up the platform's floor in
``tools/perf_baseline.json`` (distilled from BENCH_r01–r05 — the
weakest HONEST measurement per platform), applies the tolerance
(CLI > ``auron.perf_gate.tolerance_pct`` > baseline default, sized to
this container's measured wall-clock variance), and exits nonzero on a
regression past it.

    python tools/perf_gate.py --run                # runs bench.py
    python tools/perf_gate.py --bench-json rec.json
    python bench.py | python tools/perf_gate.py --bench-json -

Exit codes: 0 pass, 1 regression, 2 unusable record (bench errored or
the platform has no baseline). The last stdout line is one JSON record
(the bench.py / chaos_report.py driver contract) carrying the verdict
AND the bench record's host/device ``profile`` section, so a failing
gate arrives WITH the attribution that explains where the time went.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_HERE = os.path.dirname(os.path.abspath(__file__))
_DEFAULT_BASELINE = os.path.join(_HERE, "perf_baseline.json")


def load_baseline(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def fresh_bench_record(timeout_s: int = 1800) -> dict:
    # sized for the bench child (900s budget) PLUS the mesh scaling
    # child the parent runs afterwards (~540s budget)
    """Run bench.py and parse its one-JSON-line contract."""
    repo = os.path.dirname(_HERE)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=timeout_s, cwd=repo)
    lines = [ln for ln in (proc.stdout or "").strip().splitlines()
             if ln.strip()]
    if not lines:
        raise SystemExit(
            f"bench.py produced no output (rc={proc.returncode}); "
            f"stderr tail: {(proc.stderr or '')[-500:]}")
    return json.loads(lines[-1])


def resolve_tolerance(cli_pct, baseline: dict) -> float:
    if cli_pct is not None:
        return float(cli_pct)
    try:
        from auron_tpu import config as cfg
        conf = cfg.get_config()
        # honor an explicit override — a programmatic AuronConfig.set
        # (the documented top of the resolution order) or the env
        # binding; otherwise prefer the baseline file so the floor and
        # its tolerance travel together in one reviewed artifact
        opt = cfg._REGISTRY[cfg.PERF_GATE_TOLERANCE_PCT]
        with conf._lock:
            session_set = cfg.PERF_GATE_TOLERANCE_PCT in conf._overrides
        if session_set or os.environ.get(opt.env_var) is not None:
            return float(conf.get(cfg.PERF_GATE_TOLERANCE_PCT))
    except Exception:
        pass
    return float(baseline.get("default_tolerance_pct", 50.0))


def evaluate(record: dict, baseline: dict, tolerance_pct: float,
             tolerance_pinned: bool = False) -> dict:
    """Pure gate verdict from a bench record + baseline (the unit the
    mechanics tests drive with synthetic records).

    Two floors per platform: the flagship KERNEL metric
    (``record["value"]``, the historical rows/s headline) under the
    platform's ``tolerance_pct`` (falling back to the resolved default),
    and — when both the baseline entry and the record carry one — the
    q01 OPERATOR-PIPELINE floor (``profile.pipeline_rows_per_sec``, the
    end-to-end number the pipelined-execution work moves) under its own
    tighter tolerance. Either floor failing fails the gate. The
    pipeline floor only applies when the record's profile scale matches
    the baseline's (batch-size/scale experiments must not trip it)."""
    if "error" in record and record.get("value") is None:
        return {"perf_gate": "unusable",
                "reason": f"bench errored: {record['error']}"}
    platform = record.get("platform", "")
    aliases = baseline.get("platform_aliases", {})
    entry = baseline.get("platforms", {}).get(
        aliases.get(platform, platform))
    if entry is None:
        return {"perf_gate": "unusable",
                "reason": f"no baseline for platform {platform!r}"}
    value = float(record.get("value", 0.0))
    base = float(entry["rows_per_sec"])
    # per-platform tolerance override (the tightened CPU floor) unless
    # the caller pinned one explicitly (CLI --tolerance-pct)
    entry_tol = entry.get("tolerance_pct")
    eff_tol = (float(entry_tol)
               if entry_tol is not None and not tolerance_pinned
               else tolerance_pct)
    floor = base * (1.0 - eff_tol / 100.0)
    verdict = {
        "perf_gate": "pass" if value >= floor else "fail",
        "metric": baseline.get("metric"),
        "platform": platform,
        "value_rows_per_sec": round(value, 1),
        "baseline_rows_per_sec": round(base, 1),
        "floor_rows_per_sec": round(floor, 1),
        "tolerance_pct": eff_tol,
        "delta_vs_baseline_pct": round((value - base) / base * 100.0, 2),
    }
    pentry = entry.get("pipeline")
    if pentry:
        prof = record.get("profile")
        pscale = pentry.get("scale")
        has_value = isinstance(prof, dict) \
            and bool(prof.get("pipeline_rows_per_sec"))
        if has_value and pscale is not None \
                and float(prof.get("scale", -1)) != float(pscale):
            # batch-size / scale experiments must not trip the floor,
            # but the skip is RECORDED, never silent
            verdict["pipeline"] = {
                "verdict": "skipped",
                "reason": f"profile scale {prof.get('scale')} != "
                          f"baseline scale {pscale}",
            }
        elif not has_value:
            # the baseline expects a pipeline number and the record
            # can't produce one (bench profile errored, or throughput
            # collapsed to 0) — exactly the silent-decay mode the
            # floor exists to catch: fail loudly
            verdict["pipeline"] = {
                "verdict": "missing",
                "reason": "record carries no usable "
                          "profile.pipeline_rows_per_sec "
                          + (f"(profile_error: {record['profile_error']})"
                             if record.get("profile_error") else ""),
            }
            verdict["perf_gate"] = "fail"
        else:
            pval = float(prof["pipeline_rows_per_sec"])
            pbase = float(pentry["rows_per_sec"])
            ptol = float(pentry.get("tolerance_pct", eff_tol))
            pfloor = pbase * (1.0 - ptol / 100.0)
            verdict["pipeline"] = {
                "verdict": "pass" if pval >= pfloor else "fail",
                "value_rows_per_sec": round(pval, 1),
                "baseline_rows_per_sec": round(pbase, 1),
                "floor_rows_per_sec": round(pfloor, 1),
                "tolerance_pct": ptol,
                "delta_vs_baseline_pct": round(
                    (pval - pbase) / pbase * 100.0, 2),
            }
            if pval < pfloor:
                verdict["perf_gate"] = "fail"
    # SPMD mesh floor: the virtual 8-device CPU mesh q01 scaling figure
    # (bench's mesh child). Gated whenever the record carries a mesh
    # section; a bench that TRIED and failed records mesh_error and
    # FAILS (the silent-decay hole stays closed for every fresh bench);
    # records predating the mesh bench skip with the skip recorded.
    mentry = baseline.get("platforms", {}).get("mesh")
    if mentry:
        mrec = record.get("mesh")
        if isinstance(mrec, dict) and mrec.get("mesh_rows_per_sec"):
            mscale = mentry.get("scale")
            mdev = int(mentry.get("devices", 8))
            if mscale is not None \
                    and float(mrec.get("scale", -1)) != float(mscale):
                verdict["mesh"] = {
                    "verdict": "skipped",
                    "reason": f"mesh scale {mrec.get('scale')} != "
                              f"baseline scale {mscale}",
                }
            elif int(mrec.get("devices", 0)) != mdev:
                verdict["mesh"] = {
                    "verdict": "skipped",
                    "reason": f"mesh devices {mrec.get('devices')} != "
                              f"baseline devices {mdev}",
                }
            elif mrec.get("mesh_demoted") or (mrec.get(
                    "route_demoted_by_devices") or {}).get(
                    str(mrec.get("devices", 0)), 0):
                # a run whose rounds demoted to host mid-exchange
                # measured the RECOVERY path, not the mesh: it must
                # neither fail the floor (the demotion worked as
                # designed) nor pass it (host throughput is not a mesh
                # figure) — recorded and reported, never miscounted
                verdict["mesh"] = {
                    "verdict": "skipped",
                    "reason": "mesh rounds demoted to host mid-run "
                              "(recovery path measured, not the mesh)",
                    "value_rows_per_sec": round(
                        float(mrec["mesh_rows_per_sec"]), 1),
                    "route_demoted": mrec.get(
                        "route_demoted_by_devices"),
                    "route_mix": mrec.get("route_mix_by_devices"),
                }
            else:
                mval = float(mrec["mesh_rows_per_sec"])
                mbase = float(mentry["rows_per_sec"])
                mtol = float(mentry.get("tolerance_pct", eff_tol))
                mfloor = mbase * (1.0 - mtol / 100.0)
                verdict["mesh"] = {
                    "verdict": "pass" if mval >= mfloor else "fail",
                    "value_rows_per_sec": round(mval, 1),
                    "baseline_rows_per_sec": round(mbase, 1),
                    "floor_rows_per_sec": round(mfloor, 1),
                    "tolerance_pct": mtol,
                    "delta_vs_baseline_pct": round(
                        (mval - mbase) / mbase * 100.0, 2),
                    "scaling_factor": mrec.get("scaling_factor"),
                    "route_all_to_all": mrec.get(
                        "route_all_to_all_by_devices"),
                    "route_mix": mrec.get("route_mix_by_devices"),
                }
                if mval < mfloor:
                    verdict["perf_gate"] = "fail"
        elif record.get("mesh_error"):
            verdict["mesh"] = {
                "verdict": "missing",
                "reason": f"mesh bench errored: {record['mesh_error']}",
            }
            verdict["perf_gate"] = "fail"
        elif "mesh" in record:
            # a mesh section WITHOUT a usable value (interrupted child,
            # renamed key) is the silent-decay mode, not a pre-mesh
            # record — fail loudly like the pipeline floor's zero case
            verdict["mesh"] = {
                "verdict": "missing",
                "reason": "mesh section carries no usable "
                          "mesh_rows_per_sec",
            }
            verdict["perf_gate"] = "fail"
        else:
            verdict["mesh"] = {
                "verdict": "skipped",
                "reason": "record carries no mesh section "
                          "(predates the mesh bench)",
            }
    # carry the forensics along: a failing gate should arrive WITH the
    # host/device attribution and the structured backend diagnosis
    if isinstance(record.get("profile"), dict):
        verdict["profile"] = record["profile"]
    pr = record.get("probe_report")
    if isinstance(pr, dict):
        verdict["probe_ok"] = pr.get("ok")
        if not pr.get("ok"):
            failed = next((s for s in pr.get("steps", [])
                           if not s.get("ok")), {})
            verdict["probe_failed_step"] = failed.get("name")
            verdict["probe_error"] = (
                f"{failed.get('error_type', '')}: "
                f"{failed.get('error_message', '')}").strip(": ")
    return verdict


def scrape_ops_metrics(port: int, host: str = "127.0.0.1") -> dict:
    """One STRICT ops-endpoint scrape (the ops-plane gate's unit):
    fetch ``/metrics``, run it through the conformance parser
    (obs/registry.parse_prometheus — ValueError on any text-format
    violation), and verify the SLO family
    ``auron_query_duration_seconds`` is being exposed. Returns the
    parsed families."""
    import urllib.request

    from auron_tpu.obs import registry as obs_registry
    with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10) as r:
        text = r.read().decode()
    fams = obs_registry.parse_prometheus(text)
    if "auron_query_duration_seconds" not in fams:
        raise ValueError(
            "auron_query_duration_seconds absent from /metrics — the "
            "per-query SLO surface is gone")
    return fams


def run_ops_gate(tables) -> dict:
    """Ops-plane smoke gate: boot a Session with the telemetry endpoint
    on (ephemeral port), scrape ``/metrics`` in a loop WHILE q01 runs,
    and fail loudly when any scrape is unparseable, the SLO histogram
    is missing, or the endpoint never answered. Returns
    ``{"ops_gate": "pass"|"fail", "ops_scrapes": n, "ops_error": ...}``."""
    import threading

    from auron_tpu import config as cfg
    from auron_tpu.frontend.session import Session
    from auron_tpu.it.queries import q01_dataframe
    conf = cfg.get_config()
    conf.set(cfg.OPS_ENABLED, True)
    conf.set(cfg.OPS_PORT, 0)
    errors: list = []
    scrapes = [0]
    try:
        s = Session()
        try:
            if s.ops_address is None:
                return {"ops_gate": "fail", "ops_scrapes": 0,
                        "ops_error": "ops endpoint did not start "
                                     "(auron.ops.enabled was on)"}
            port = s.ops_address[1]
            stop = threading.Event()

            def scraper():
                while not stop.is_set():
                    try:
                        scrape_ops_metrics(port)
                        scrapes[0] += 1
                    except Exception as e:   # noqa: BLE001 — verdict
                        errors.append(f"{type(e).__name__}: {e}")
                        return
                    stop.wait(0.002)

            th = threading.Thread(target=scraper, daemon=True)
            th.start()
            q01_dataframe(s, tables).collect()   # scraped mid-flight
            stop.set()
            th.join(10)
            try:
                # final post-run scrape: the family must be present
                # and parseable AFTER the query observed its outcome
                scrape_ops_metrics(port)
                scrapes[0] += 1
            except Exception as e:   # noqa: BLE001 — verdict
                errors.append(f"{type(e).__name__}: {e}")
        finally:
            s.close()
    finally:
        conf.unset(cfg.OPS_ENABLED)
        conf.unset(cfg.OPS_PORT)
    out = {"ops_gate": "pass" if not errors and scrapes[0] else "fail",
           "ops_scrapes": scrapes[0]}
    if errors:
        out["ops_error"] = errors[0]
    elif not scrapes[0]:
        out["ops_error"] = "ops endpoint answered no scrape"
    return out


def run_lint_gate() -> dict:
    """graftlint arm of the smoke gate: the contract checker
    (auron_tpu/analysis, ANALYSIS.md) must hold on HEAD. Fails LOUDLY
    when the baseline file is missing or unparseable (a deleted/garbage
    baseline would otherwise let every frozen violation pass as 'new
    code clean'), when baseline entries have gone stale en masse (the
    file no longer describes this tree), or when unbaselined
    violations/parse errors exist. Returns
    ``{"lint_gate": "pass"|"fail", "lint_new": n, ...}``."""
    from auron_tpu.analysis import core
    path = core.default_baseline_path()
    if not os.path.exists(path):
        return {"lint_gate": "fail", "lint_new": -1,
                "lint_error": f"lint baseline missing: {path} — run "
                              f"python -m auron_tpu.analysis "
                              f"--update-baseline"}
    try:
        baseline = core.load_baseline(path)
    except (ValueError, json.JSONDecodeError, OSError) as e:
        return {"lint_gate": "fail", "lint_new": -1,
                "lint_error": f"lint baseline unreadable: {e}"}
    result = core.analyze()
    new, old, stale = core.apply_baseline(result.violations, baseline)
    out = {"lint_gate": "pass", "lint_new": len(new),
           "lint_baselined": len(old), "lint_stale": len(stale),
           "lint_suppressed": result.suppressed,
           "lint_files": result.files_scanned}
    entries = len(baseline.get("entries", ()))
    if result.parse_errors:
        out["lint_gate"] = "fail"
        out["lint_error"] = (f"{len(result.parse_errors)} files failed "
                             f"to parse: {result.parse_errors[0]}")
    elif new:
        out["lint_gate"] = "fail"
        v = new[0]
        out["lint_error"] = (f"{len(new)} unbaselined violations, "
                             f"first: {v.file}:{v.line} {v.rule} "
                             f"{v.message}")
    elif entries and len(stale) * 2 > entries:
        # over half the frozen entries match nothing in this tree: the
        # baseline is from another world (mass rename/refactor) and
        # 'pass' would be vacuous — regenerate it deliberately
        out["lint_gate"] = "fail"
        out["lint_error"] = (f"lint baseline is stale: {len(stale)} of "
                             f"{entries} entries match nothing — "
                             f"regenerate with --update-baseline")
    return out


def run_cache_gate(tables, smoke: dict) -> dict:
    """Warm-path cache arm (the serving-plane cache, cache/result_cache):
    with ``auron.cache.*`` armed, the SAME q01 re-submitted through one
    Session must come back from the result cache — bit-identical and at
    least ``smoke.cache_speedup_floor_x`` times faster than the fresh
    run — and a fresh Session's AOT warmer (``auron.cache.aot_top_n``)
    must replay the recorded plan with zero silent errors. A repeat
    submission that never hits, a non-identical cached result, a
    speedup under the floor, an erroring warmer, or a warmer that
    warmed NOTHING all fail loudly. Returns
    ``{"cache_gate": "pass"|"fail", "cache_speedup_x": ..., ...}``."""
    import shutil
    import tempfile
    import time

    from auron_tpu import config as cfg
    from auron_tpu.cache import aot as _aot
    from auron_tpu.cache.result_cache import get_cache
    from auron_tpu.frontend.session import Session
    from auron_tpu.it.queries import q01_dataframe

    floor_x = float(smoke.get("cache_speedup_floor_x", 5.0))
    conf = cfg.get_config()
    cache = get_cache()
    # the AOT inventory rides next to the persistent XLA cache; Session
    # binds jax_compilation_cache_dir to it, so remember the binding and
    # restore it after — the gate's temp dir must not outlive the gate
    aot_root = tempfile.mkdtemp(prefix="auron_cache_gate_")
    try:
        import jax
        prev_xla_dir = jax.config.jax_compilation_cache_dir
    except Exception:   # noqa: BLE001 — jax-version dependent attr
        jax, prev_xla_dir = None, None
    conf.set(cfg.CACHE_ENABLED, True)
    conf.set(cfg.XLA_CACHE_DIR, aot_root)
    try:
        cache.clear(reset_counters=True)
        s = Session()
        try:
            t0 = time.perf_counter()
            fresh = q01_dataframe(s, tables).collect()
            fresh_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            cached = q01_dataframe(s, tables).collect()
            cached_s = time.perf_counter() - t0
        finally:
            s.close()
        st = cache.stats()
        speedup = fresh_s / cached_s if cached_s > 0 else float("inf")
        out = {
            "cache_gate": "pass",
            "cache_speedup_x": round(speedup, 1),
            "cache_speedup_floor_x": floor_x,
            "cache_fresh_s": round(fresh_s, 4),
            "cache_hit_s": round(cached_s, 4),
            "cache_hits": st["hits"],
        }
        if not st["hits"]:
            out["cache_gate"] = "fail"
            out["cache_error"] = (
                "repeat submission never hit the result cache (0 hits "
                "recorded) — the warm path did not engage")
        elif not cached.equals(fresh):
            out["cache_gate"] = "fail"
            out["cache_error"] = ("cached q01 result is not bit-identical "
                                  "to the fresh run")
        elif speedup < floor_x:
            out["cache_gate"] = "fail"
            out["cache_error"] = (
                f"repeat-query speedup {speedup:.1f}x < floor "
                f"{floor_x:.0f}x (warm-path serving gate)")
        # AOT arm: the fresh run above recorded its plan in the
        # inventory; a NEW Session with the warmer armed must replay it
        # cleanly (errors are collected, never raised — exactly the
        # silent-failure mode this arm exists to catch)
        conf.set(cfg.CACHE_AOT_TOP_N, 2)
        try:
            cache.clear(reset_counters=True)
            Session().close()
        finally:
            conf.unset(cfg.CACHE_AOT_TOP_N)
        # the warmer runs on a background thread since Fusion 2.0's
        # overlap work; close() joins it, but join explicitly anyway —
        # this arm must gate the FINAL summary, not an in-flight one
        _aot.wait(timeout=120.0)
        aot = _aot.last_stats()
        out["aot_warmed"] = aot["warmed"]
        out["aot_errors"] = len(aot["errors"])
        out["aot_overlapped_ms"] = aot.get("overlapped_ms", 0.0)
        if aot["errors"]:
            out["cache_gate"] = "fail"
            out["cache_error"] = (
                f"AOT warmer errored silently: {aot['errors'][0]}")
        elif not aot["warmed"]:
            out["cache_gate"] = "fail"
            out["cache_error"] = (
                "AOT warmer warmed nothing — the recorded q01 plan "
                "never reached the inventory")
        return out
    finally:
        conf.unset(cfg.CACHE_ENABLED)
        conf.unset(cfg.XLA_CACHE_DIR)
        cache.clear(reset_counters=True)
        if jax is not None:
            try:
                jax.config.update(
                    "jax_compilation_cache_dir", prev_xla_dir)
            except Exception:   # noqa: BLE001 — best-effort restore
                pass
        shutil.rmtree(aot_root, ignore_errors=True)


def run_fusion_gate(smoke: dict) -> dict:
    """Fusion 2.0 map-side-combine arm: the dup-heavy grouped-agg A/B
    (bench.bench_fusion2 — ``auron.fusion.combine`` on vs off over a
    tiny-key-domain multi-partition group-by) must cut the LIVE shuffle
    bytes by at least ``smoke.combine_byte_reduction_floor``. A run
    whose byte counters read zero (the exchange's live-bytes ledger
    went dark), or whose combined run shipped no fewer bytes than
    combine-off (the fold silently disengaged — the seeded-regression
    mode this arm exists to catch), fails loudly rather than gating a
    vacuous measurement. Returns
    ``{"fusion_gate": "pass"|"fail", "combine_byte_reduction": ...}``."""
    from bench import bench_fusion2
    floor = float(smoke.get("combine_byte_reduction_floor", 0.40))
    try:
        r = bench_fusion2()
    except Exception as e:   # noqa: BLE001 — verdict, not a crash
        return {"fusion_gate": "fail",
                "fusion_error": f"{type(e).__name__}: {e}"}
    on = int(r.get("combine_shuffle_bytes_on", 0))
    off = int(r.get("combine_shuffle_bytes_off", 0))
    out = {
        "fusion_gate": "pass",
        "combine_byte_reduction": r.get("combine_byte_reduction", 0.0),
        "combine_byte_reduction_floor": floor,
        "combine_shuffle_bytes_on": on,
        "combine_shuffle_bytes_off": off,
        "fusion2_rows_per_sec": r.get("fusion2_rows_per_sec", 0.0),
    }
    if not on or not off:
        out["fusion_gate"] = "fail"
        out["fusion_error"] = (
            "shuffle byte counters read zero — the exchange's "
            "live-bytes ledger went dark, nothing to gate")
    elif on >= off:
        out["fusion_gate"] = "fail"
        out["fusion_error"] = (
            f"combined run shipped no fewer shuffle bytes than "
            f"combine-off ({on:,} vs {off:,}) — map-side combine "
            f"silently disengaged")
    elif out["combine_byte_reduction"] < floor:
        out["fusion_gate"] = "fail"
        out["fusion_error"] = (
            f"shuffle-byte reduction "
            f"{out['combine_byte_reduction']:.1%} < floor {floor:.0%} "
            f"(map-side-combine gate)")
    return out


def run_fleet_gate(smoke: dict) -> dict:
    """Serving-fleet arm (the replicated-AuronServer plane): TWO real
    replica subprocesses behind an in-process ``FleetRouter``; a query
    is driven through the router and the replica that picked it up is
    SIGKILLed mid-flight. The gate holds when the client still receives
    the bit-identical table (journal RESUME on the survivor, or guarded
    re-execution — either is a legitimate failover), exactly one
    replica death is recorded, and the detect-to-done failover latency
    stays under ``smoke.fleet_failover_ceiling_s`` — an idle survivor
    has free capacity, so a slow failover here is router overhead, not
    admission queueing. Returns ``{"fleet_gate": "pass"|"fail",
    "fleet_failover_s": ..., ...}``."""
    import tempfile
    import threading
    import time

    ceiling = float(smoke.get("fleet_failover_ceiling_s", 10.0))
    out: dict = {"fleet_gate": "pass",
                 "fleet_failover_ceiling_s": ceiling}
    root = None
    try:
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        from auron_tpu.fleet.replica import FleetHarness
        from auron_tpu.ir import pb

        root = tempfile.mkdtemp(prefix="auron_fleet_gate_")
        rng = np.random.default_rng(19)
        n = 600_000
        path = os.path.join(root, "fleet.parquet")
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 64, n), pa.int64()),
            "v": pa.array(rng.normal(size=n), pa.float64())}), path)
        col = lambda i: pb.ExprNode(column=pb.ColumnRefE(index=i))
        plan = pb.PlanNode(agg=pb.AggNode(
            child=pb.PlanNode(parquet_scan=pb.ParquetScanNode(
                files=[path])),
            mode="complete", group_exprs=[col(0)],
            aggs=[pb.AggFunctionP(fn="sum", arg=col(1)),
                  pb.AggFunctionP(fn="count", arg=col(1))]))
        task = pb.TaskDefinition(plan=plan,
                                 task_id=1).SerializeToString()

        with FleetHarness(2) as h:
            warm, _ = h.client(timeout_s=120).execute(task)
            box: dict = {}

            def drive() -> None:
                try:
                    tbl, _ = h.client(timeout_s=120).execute(task)
                    box["table"] = tbl
                except BaseException as e:   # noqa: BLE001 — verdict below
                    box["err"] = e

            t = threading.Thread(target=drive, daemon=True)
            t.start()
            victim = None
            deadline = time.monotonic() + 10.0
            while victim is None and t.is_alive() \
                    and time.monotonic() < deadline:
                h.router._poll_once()
                for i in range(len(h.replicas)):
                    snap = h.router._replicas[i].snapshot
                    if snap is not None and snap.occupancy > 0:
                        victim = i
                        break
                if victim is None:
                    time.sleep(0.05)
            if victim is not None and h.replicas[victim].alive():
                h.kill_replica(victim)
            t.join(timeout=120)
            stats = h.router.stats_dict()
            r = stats["router"]
            out["fleet_deaths"] = r["replica_deaths"]
            out["fleet_failover_kind"] = (
                "resume" if r["failovers_resume"]
                else "reexecute" if r["failovers_reexecute"] else "none")
            lats = stats.get("failover_latency_s") or []
            out["fleet_failover_s"] = round(lats[0], 3) if lats else None
            if t.is_alive():
                out["fleet_gate"] = "fail"
                out["fleet_error"] = ("the killed query never "
                                      "completed or classified (wedged)")
            elif victim is None:
                out["fleet_gate"] = "fail"
                out["fleet_error"] = ("no replica ever showed the query "
                                      "running — nothing was killed, "
                                      "nothing gated")
            elif "err" in box:
                out["fleet_gate"] = "fail"
                out["fleet_error"] = (f"failover surfaced an error to "
                                      f"the client: "
                                      f"{type(box['err']).__name__}: "
                                      f"{str(box['err'])[:200]}")
            elif not box["table"].equals(warm):
                out["fleet_gate"] = "fail"
                out["fleet_error"] = ("failed-over query's table is "
                                      "not bit-identical to the warm "
                                      "pass")
            elif r["replica_deaths"] != 1:
                out["fleet_gate"] = "fail"
                out["fleet_error"] = (f"expected exactly one recorded "
                                      f"replica death, saw "
                                      f"{r['replica_deaths']}")
            elif out["fleet_failover_kind"] == "none":
                out["fleet_gate"] = "fail"
                out["fleet_error"] = ("no failover recorded — the "
                                      "query survived without one "
                                      "(kill landed too late?)")
            elif lats and lats[0] >= ceiling:
                out["fleet_gate"] = "fail"
                out["fleet_error"] = (
                    f"failover took {lats[0]:.2f}s >= ceiling "
                    f"{ceiling:.0f}s against an IDLE survivor — "
                    f"router overhead, not admission queueing")
    except Exception as e:   # noqa: BLE001 — verdict, not a crash
        return {"fleet_gate": "fail",
                "fleet_failover_ceiling_s": ceiling,
                "fleet_error": f"{type(e).__name__}: {e}"}
    finally:
        if root is not None:
            import shutil
            shutil.rmtree(root, ignore_errors=True)
    return out


def obs_fleet_verdict(base_s: float, obs_s: float, smoke: dict, *,
                      ledgers_on: int, ledgers_off: int,
                      queries: int) -> dict:
    """Pure verdict for the fleet-observability overhead arm (the unit
    the seeded-regression test drives with synthetic walls): ``base_s``
    is the best observed wall with trace propagation + the cost ledger
    OFF, ``obs_s`` with both ON, over the same ``queries``-query batch.
    The A/B must be HONEST to gate anything: the on-arm must have
    produced a cost ledger on every query (an idle ledger would measure
    nothing) and the off-arm must have produced none (a knob that no
    longer disengages would measure the feature against itself)."""
    limit = float(smoke.get("obs_fleet_overhead_pct_max", 2.0))
    out: dict = {"obs_fleet_gate": "pass",
                 "obs_fleet_overhead_pct_max": limit,
                 "obs_fleet_queries": queries,
                 "obs_fleet_base_s": round(base_s, 4),
                 "obs_fleet_obs_s": round(obs_s, 4),
                 "obs_fleet_ledgers": ledgers_on}
    if not (base_s > 0.0) or not (obs_s > 0.0):
        out["obs_fleet_gate"] = "fail"
        out["obs_fleet_error"] = (
            "overhead measurement went dark (non-positive wall) — "
            "nothing to gate")
        return out
    overhead = (obs_s - base_s) / base_s * 100.0
    out["obs_fleet_overhead_pct"] = round(overhead, 3)
    if ledgers_on < queries:
        out["obs_fleet_gate"] = "fail"
        out["obs_fleet_error"] = (
            f"cost ledger engaged on only {ledgers_on}/{queries} "
            f"on-arm queries — the overhead measured an idle ledger")
    elif ledgers_off:
        out["obs_fleet_gate"] = "fail"
        out["obs_fleet_error"] = (
            f"off-arm still produced {ledgers_off} cost ledger(s) — "
            f"auron.ledger.enabled no longer disengages, the A/B "
            f"measured the feature against itself")
    elif overhead >= limit:
        out["obs_fleet_gate"] = "fail"
        out["obs_fleet_error"] = (
            f"trace-propagation + cost-ledger overhead "
            f"{overhead:.2f}% >= {limit:.0f}% of the serving wall "
            f"(fleet-observability gate)")
    return out


def run_obs_fleet_gate(smoke: dict) -> dict:
    """Fleet-observability overhead arm (ISSUE 20): the cross-process
    trace plumbing (KIND_TRACE prefix frame + wire_scope adoption) and
    the per-query cost ledger both sit on the serving hot path, so this
    arm runs the SAME grouped-agg through one in-process AuronServer
    with tracing on in BOTH arms and only ``auron.trace.propagate`` +
    ``auron.ledger.enabled`` toggled between them. Best-of-3
    interleaved passes per arm (min wall over a 4-query batch) against
    ``smoke.obs_fleet_overhead_pct_max``; verdict mechanics live in
    ``obs_fleet_verdict``."""
    import tempfile
    import time

    try:
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        from auron_tpu import config as cfg
        from auron_tpu.ir import pb
        from auron_tpu.runtime.serving import AuronClient, AuronServer

        root = tempfile.mkdtemp(prefix="auron_obs_gate_")
        try:
            rng = np.random.default_rng(20)
            n = 120_000
            path = os.path.join(root, "obs.parquet")
            pq.write_table(pa.table({
                "k": pa.array(rng.integers(0, 32, n), pa.int64()),
                "v": pa.array(rng.normal(size=n), pa.float64())}), path)
            col = lambda i: pb.ExprNode(column=pb.ColumnRefE(index=i))
            plan = pb.PlanNode(agg=pb.AggNode(
                child=pb.PlanNode(parquet_scan=pb.ParquetScanNode(
                    files=[path])),
                mode="complete", group_exprs=[col(0)],
                aggs=[pb.AggFunctionP(fn="sum", arg=col(1)),
                      pb.AggFunctionP(fn="count", arg=col(1))]))
            task = pb.TaskDefinition(plan=plan,
                                     task_id=1).SerializeToString()

            conf = cfg.get_config()
            conf.set(cfg.TRACE_ENABLED, True)
            srv = AuronServer()
            srv.serve_background()
            try:
                client = AuronClient(*srv.address, timeout_s=120)
                passes, batch = 3, 4

                def arm(obs_on: bool) -> "tuple[float, int]":
                    conf.set(cfg.TRACE_PROPAGATE, obs_on)
                    conf.set(cfg.LEDGER_ENABLED, obs_on)
                    led = 0
                    t0 = time.perf_counter()
                    for _ in range(batch):
                        _tbl, metrics = client.execute(task)
                        if isinstance(metrics.get("cost_ledger"), dict):
                            led += 1
                    return time.perf_counter() - t0, led

                arm(True)   # warm compiles + first-span setup costs
                base_s = obs_s = float("inf")
                ledgers_on = ledgers_off = 0
                # interleaved passes so container drift hits both arms
                for _ in range(passes):
                    w, led = arm(False)
                    base_s = min(base_s, w)
                    ledgers_off += led
                    w, led = arm(True)
                    obs_s = min(obs_s, w)
                    ledgers_on += led
            finally:
                srv.shutdown()
                conf.unset(cfg.TRACE_ENABLED)
                conf.unset(cfg.TRACE_PROPAGATE)
                conf.unset(cfg.LEDGER_ENABLED)
            # the on-arm must have engaged on EVERY query of every pass
            # and the off-arm on none — obs_fleet_verdict normalizes to
            # one pass's batch for the engagement contract
            return obs_fleet_verdict(
                base_s, obs_s, smoke,
                ledgers_on=ledgers_on // passes,
                ledgers_off=ledgers_off, queries=batch)
        finally:
            import shutil
            shutil.rmtree(root, ignore_errors=True)
    except Exception as e:   # noqa: BLE001 — verdict, not a crash
        return {"obs_fleet_gate": "fail",
                "obs_fleet_overhead_pct_max": float(
                    smoke.get("obs_fleet_overhead_pct_max", 2.0)),
                "obs_fleet_error": f"{type(e).__name__}: {e}"}


def run_smoke(baseline: dict) -> dict:
    """Tier-1-fast smoke arm: run the q01 operator pipeline in-process
    at a tiny scale and compare against the generous smoke floor — an
    order-of-magnitude tripwire (compile-cache regressions, accidental
    per-row host loops) cheap enough for a test to invoke every run,
    so throughput can't silently decay between bench rounds again.

    Doubles as the CONCURRENCY-TAX gate: every query now enters the
    scheduler (admission + fairness bookkeeping), and this mode asserts
    the solo-query path pays < 2% of wall for it. Measured from the
    slot's own overhead ledger (time INSIDE acquire/turn/release, not
    policy waits) against the best run's wall — a deterministic ratio,
    immune to the container's wall-clock noise that plagues A/B runs.

    And as the JOURNAL-OVERHEAD gate (the crash-safe query journal,
    runtime/journal.py): one extra q01 run with ``auron.journal.dir``
    armed, asserting the journal's HOT-PATH cost (its own ``hot_ns``
    ledger: record enqueues + the commit-boundary drain/fsync waits —
    everything the driving thread ever blocks on) stays under
    ``smoke.journal_overhead_limit_pct`` of that run's wall. Same
    deterministic-ledger discipline as the scheduler tax: a regression
    in the hot-path cost fails the gate instead of hiding in container
    noise.

    And as the WARM-PATH CACHE gate (``run_cache_gate``): with
    ``auron.cache.*`` armed, a repeated identical q01 must be served
    from the result cache bit-identically and at least
    ``smoke.cache_speedup_floor_x`` times faster than fresh, and the
    AOT warmer must replay the recorded plan with zero errors.

    And as the FUSION 2.0 gate (``run_fusion_gate``): map-side combine
    must cut the dup-heavy grouped-agg A/B's live shuffle bytes by at
    least ``smoke.combine_byte_reduction_floor`` — a fold that silently
    disengaged ships exactly the combine-off bytes and fails here.

    And as the SERVING-FLEET gate (``run_fleet_gate``): a two-replica
    fleet with one replica SIGKILLed mid-query must hand the client the
    bit-identical table via failover within
    ``smoke.fleet_failover_ceiling_s`` of detection."""
    import tempfile
    import time

    scale = float(os.environ.get("AURON_PERF_SMOKE_SCALE", "0.5"))
    from auron_tpu.frontend.session import Session
    from auron_tpu.it.queries import q01_dataframe
    from auron_tpu.it.tpcds_data import generate as gen_data
    smoke = baseline.get("smoke", {})
    floor = float(smoke.get("cpu_floor_rows_per_sec", 20000.0))
    tax_limit = float(smoke.get("sched_tax_limit_pct", 2.0))
    journal_limit = float(smoke.get("journal_overhead_limit_pct", 2.0))
    data = tempfile.mkdtemp(prefix="auron_perf_smoke_")
    try:
        tables = gen_data(data, scale=scale)
        from bench import _table_rows
        rows = _table_rows(tables["store_sales"])
        q01_dataframe(Session(), tables).collect()   # warm compiles
        wall, tax_ns = float("inf"), 0
        for _ in range(2):
            s = Session()
            t0 = time.perf_counter()
            q01_dataframe(s, tables).collect()
            w = time.perf_counter() - t0
            if w < wall:
                wall, tax_ns = w, s._scheduler.last_overhead_ns
        value = rows / wall
        tax_pct = tax_ns / (wall * 1e9) * 100.0
        # journal arm: same query, journaling armed, hot-path ledger
        from auron_tpu import config as cfg
        from auron_tpu.runtime import journal as jrn
        conf = cfg.get_config()
        jdir = os.path.join(data, "journal")
        conf.set(cfg.JOURNAL_DIR, jdir)
        try:
            # best-of-2 like the main loop: one cold fsync outlier on
            # this container must not fail a healthy hot path
            journal_pct, jstats = float("inf"), {}
            for _ in range(2):
                s = Session()
                t0 = time.perf_counter()
                q01_dataframe(s, tables).collect()
                jwall = time.perf_counter() - t0
                s.close()
                st = jrn.last_stats()
                pct = st.get("hot_ns", 0) / (jwall * 1e9) * 100.0
                if pct < journal_pct:
                    journal_pct, jstats = pct, st
        finally:
            conf.unset(cfg.JOURNAL_DIR)
        verdict = {
            "perf_gate": "pass" if value >= floor else "fail",
            "mode": "smoke",
            "scale": scale,
            "input_rows": rows,
            "value_rows_per_sec": round(value, 1),
            "floor_rows_per_sec": round(floor, 1),
            "sched_tax_pct": round(tax_pct, 4),
            "sched_tax_limit_pct": tax_limit,
            "journal_overhead_pct": round(journal_pct, 4),
            "journal_overhead_limit_pct": journal_limit,
            "journal_records": jstats.get("records", 0),
            "journal_commits": jstats.get("commits", 0),
        }
        if tax_pct >= tax_limit:
            verdict["perf_gate"] = "fail"
            verdict["reason"] = (
                f"scheduler tax {tax_pct:.3f}% >= {tax_limit}% of the "
                f"solo-query wall (concurrency-tax gate)")
        if not jstats.get("records"):
            # the journaled run recorded NOTHING: the plane silently
            # disarmed itself (or degraded) — the gate must not pass
            # on a measurement of an idle journal
            verdict["perf_gate"] = "fail"
            verdict["reason"] = (
                "journal-overhead gate measured an idle journal "
                "(0 records) — journaling did not engage")
        elif journal_pct >= journal_limit:
            verdict["perf_gate"] = "fail"
            verdict["reason"] = (
                f"journal hot-path overhead {journal_pct:.3f}% >= "
                f"{journal_limit}% of the journaled q01 wall "
                f"(crash-safe journal gate)")
        # warm-path cache arm: repeated identical q01 must be served
        # from the result cache (bit-identical, >= the floor's speedup)
        # and the AOT warmer must replay the recorded plan cleanly
        verdict.update(run_cache_gate(tables, smoke))
        if verdict["cache_gate"] != "pass" \
                and verdict["perf_gate"] == "pass":
            verdict["perf_gate"] = "fail"
            verdict["reason"] = (
                f"cache gate: {verdict.get('cache_error', 'failed')}")
        # Fusion 2.0 arm: map-side combine must still cut the live
        # shuffle bytes of the dup-heavy grouped-agg A/B by the floor
        # (a silently disengaged fold fails loudly, not as a bytes tie)
        verdict.update(run_fusion_gate(smoke))
        if verdict["fusion_gate"] != "pass" \
                and verdict["perf_gate"] == "pass":
            verdict["perf_gate"] = "fail"
            verdict["reason"] = (
                f"fusion gate: {verdict.get('fusion_error', 'failed')}")
        # ops-plane arm: the live telemetry endpoint must expose a
        # parseable /metrics carrying the SLO histogram, scraped WHILE
        # q01 runs (unparseable exposition or a vanished
        # auron_query_duration_seconds fails the gate loudly)
        verdict.update(run_ops_gate(tables))
        if verdict["ops_gate"] != "pass" \
                and verdict["perf_gate"] == "pass":
            verdict["perf_gate"] = "fail"
            verdict["reason"] = (
                f"ops-plane gate: {verdict.get('ops_error', 'failed')}")
        # serving-fleet arm: a 2-replica fleet must survive a SIGKILL
        # mid-query — bit-identical answer to the client via failover
        # (resume or guarded re-execution), within the latency ceiling
        verdict.update(run_fleet_gate(smoke))
        if verdict["fleet_gate"] != "pass" \
                and verdict["perf_gate"] == "pass":
            verdict["perf_gate"] = "fail"
            verdict["reason"] = (
                f"fleet gate: {verdict.get('fleet_error', 'failed')}")
        # fleet-observability arm: the trace-propagation + cost-ledger
        # plumbing on the serving hot path must stay under the
        # obs_fleet_overhead_pct_max share of the A/B wall, with the
        # ledger engaging on-arm and disengaging off-arm
        verdict.update(run_obs_fleet_gate(smoke))
        if verdict["obs_fleet_gate"] != "pass" \
                and verdict["perf_gate"] == "pass":
            verdict["perf_gate"] = "fail"
            verdict["reason"] = (
                f"obs-fleet gate: "
                f"{verdict.get('obs_fleet_error', 'failed')}")
        # lint arm: the AST contract checker must hold on HEAD (a
        # missing/stale tools/lint_baseline.json fails loudly — decay
        # of the invariant surface can't hide between rounds either)
        verdict.update(run_lint_gate())
        if verdict["lint_gate"] != "pass" \
                and verdict["perf_gate"] == "pass":
            verdict["perf_gate"] = "fail"
            verdict["reason"] = (
                f"lint gate: {verdict.get('lint_error', 'failed')}")
        return verdict
    finally:
        import shutil
        shutil.rmtree(data, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline", default=_DEFAULT_BASELINE,
                    help="baseline JSON (default tools/perf_baseline.json)")
    ap.add_argument("--bench-json", default=None,
                    help="bench record file ('-' reads stdin) instead of "
                         "running bench.py")
    ap.add_argument("--run", action="store_true",
                    help="run bench.py for a fresh record (the default "
                         "when --bench-json is absent)")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1-fast mode: run the q01 operator "
                         "pipeline in-process at a tiny scale against "
                         "the generous smoke floor (no bench.py child)")
    ap.add_argument("--tolerance-pct", type=float, default=None,
                    help="allowed shortfall vs the baseline floor "
                         "(default: auron.perf_gate.tolerance_pct env "
                         "override, else the baseline file's / the "
                         "platform entry's)")
    args = ap.parse_args(argv)

    baseline = load_baseline(args.baseline)
    if args.smoke:
        verdict = run_smoke(baseline)
        print(f"perf gate [smoke @ scale {verdict['scale']}]: "
              f"{verdict['value_rows_per_sec']:,.0f} rows/s vs floor "
              f"{verdict['floor_rows_per_sec']:,.0f}, sched tax "
              f"{verdict['sched_tax_pct']:.3f}% (limit "
              f"{verdict['sched_tax_limit_pct']:.0f}%), journal "
              f"overhead {verdict['journal_overhead_pct']:.3f}% (limit "
              f"{verdict['journal_overhead_limit_pct']:.0f}%), cache "
              f"{verdict.get('cache_speedup_x', '?')}x (floor "
              f"{verdict.get('cache_speedup_floor_x', '?')}x, aot "
              f"{verdict.get('aot_warmed', '?')} warmed), combine "
              f"-{verdict.get('combine_byte_reduction', 0) * 100:.0f}% "
              f"shuffle bytes (floor "
              f"-{verdict.get('combine_byte_reduction_floor', 0) * 100:.0f}%), "
              f"fleet failover "
              f"{verdict.get('fleet_failover_kind', '?')} in "
              f"{verdict.get('fleet_failover_s', '?')}s (ceiling "
              f"{verdict.get('fleet_failover_ceiling_s', '?'):.0f}s), "
              f"obs overhead "
              f"{verdict.get('obs_fleet_overhead_pct', '?')}% (limit "
              f"{verdict.get('obs_fleet_overhead_pct_max', '?'):.0f}%), "
              f"lint {verdict.get('lint_new', '?')} new → "
              f"{verdict['perf_gate'].upper()}")
        print(json.dumps(verdict))
        return 0 if verdict["perf_gate"] == "pass" else 1
    if args.bench_json == "-":
        record = json.loads(sys.stdin.read().strip().splitlines()[-1])
    elif args.bench_json:
        with open(args.bench_json) as f:
            record = json.loads(f.read().strip().splitlines()[-1])
    else:
        record = fresh_bench_record()

    tolerance = resolve_tolerance(args.tolerance_pct, baseline)
    verdict = evaluate(record, baseline, tolerance,
                       tolerance_pinned=args.tolerance_pct is not None)

    if verdict["perf_gate"] == "unusable":
        print(f"perf gate: UNUSABLE — {verdict['reason']}")
        print(json.dumps(verdict))
        return 2
    print(f"perf gate [{verdict['platform']}]: "
          f"{verdict['value_rows_per_sec']:,.0f} rows/s vs baseline "
          f"{verdict['baseline_rows_per_sec']:,.0f} "
          f"(floor {verdict['floor_rows_per_sec']:,.0f}, "
          f"tolerance {verdict['tolerance_pct']:.0f}%) → "
          f"{verdict['perf_gate'].upper()}")
    if "pipeline" in verdict:
        p = verdict["pipeline"]
        if p["verdict"] in ("skipped", "missing"):
            print(f"  q01 pipeline: {p['verdict'].upper()} — "
                  f"{p['reason']}")
        else:
            print(f"  q01 pipeline: {p['value_rows_per_sec']:,.0f} "
                  f"rows/s vs baseline "
                  f"{p['baseline_rows_per_sec']:,.0f} "
                  f"(floor {p['floor_rows_per_sec']:,.0f}, tolerance "
                  f"{p['tolerance_pct']:.0f}%) → {p['verdict'].upper()}")
    if "mesh" in verdict:
        m = verdict["mesh"]
        if m["verdict"] in ("skipped", "missing"):
            print(f"  mesh (8-dev virtual): {m['verdict'].upper()} — "
                  f"{m['reason']}")
        else:
            print(f"  mesh (8-dev virtual): "
                  f"{m['value_rows_per_sec']:,.0f} rows/s vs baseline "
                  f"{m['baseline_rows_per_sec']:,.0f} "
                  f"(floor {m['floor_rows_per_sec']:,.0f}, tolerance "
                  f"{m['tolerance_pct']:.0f}%, scaling "
                  f"{m.get('scaling_factor')}) → {m['verdict'].upper()}")
    if "profile" in verdict:
        p = verdict["profile"]
        print(f"  host/device split: device={p.get('device_ms')}ms "
              f"host={p.get('host_ms')}ms "
              f"buckets={p.get('host_buckets_ms')}")
    print(json.dumps(verdict))
    return 0 if verdict["perf_gate"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
