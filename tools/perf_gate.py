"""Perf regression gate: fresh q01 bench vs the checked-in baseline.

The ROADMAP [speed] item's third front: q01 CPU throughput decayed
276k → 108k rows/s across BENCH_r03→r05 and nobody noticed until the
round-5 verdict read the history side by side. This gate makes that
trajectory a failing exit code: it takes a fresh ``bench.py`` record
(or one from a file/stdin), looks up the platform's floor in
``tools/perf_baseline.json`` (distilled from BENCH_r01–r05 — the
weakest HONEST measurement per platform), applies the tolerance
(CLI > ``auron.perf_gate.tolerance_pct`` > baseline default, sized to
this container's measured wall-clock variance), and exits nonzero on a
regression past it.

    python tools/perf_gate.py --run                # runs bench.py
    python tools/perf_gate.py --bench-json rec.json
    python bench.py | python tools/perf_gate.py --bench-json -

Exit codes: 0 pass, 1 regression, 2 unusable record (bench errored or
the platform has no baseline). The last stdout line is one JSON record
(the bench.py / chaos_report.py driver contract) carrying the verdict
AND the bench record's host/device ``profile`` section, so a failing
gate arrives WITH the attribution that explains where the time went.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_HERE = os.path.dirname(os.path.abspath(__file__))
_DEFAULT_BASELINE = os.path.join(_HERE, "perf_baseline.json")


def load_baseline(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def fresh_bench_record(timeout_s: int = 1200) -> dict:
    """Run bench.py and parse its one-JSON-line contract."""
    repo = os.path.dirname(_HERE)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=timeout_s, cwd=repo)
    lines = [ln for ln in (proc.stdout or "").strip().splitlines()
             if ln.strip()]
    if not lines:
        raise SystemExit(
            f"bench.py produced no output (rc={proc.returncode}); "
            f"stderr tail: {(proc.stderr or '')[-500:]}")
    return json.loads(lines[-1])


def resolve_tolerance(cli_pct, baseline: dict) -> float:
    if cli_pct is not None:
        return float(cli_pct)
    try:
        from auron_tpu import config as cfg
        conf = cfg.get_config()
        # honor an explicit override — a programmatic AuronConfig.set
        # (the documented top of the resolution order) or the env
        # binding; otherwise prefer the baseline file so the floor and
        # its tolerance travel together in one reviewed artifact
        opt = cfg._REGISTRY[cfg.PERF_GATE_TOLERANCE_PCT]
        with conf._lock:
            session_set = cfg.PERF_GATE_TOLERANCE_PCT in conf._overrides
        if session_set or os.environ.get(opt.env_var) is not None:
            return float(conf.get(cfg.PERF_GATE_TOLERANCE_PCT))
    except Exception:
        pass
    return float(baseline.get("default_tolerance_pct", 50.0))


def evaluate(record: dict, baseline: dict, tolerance_pct: float) -> dict:
    """Pure gate verdict from a bench record + baseline (the unit the
    mechanics tests drive with synthetic records)."""
    if "error" in record and record.get("value") is None:
        return {"perf_gate": "unusable",
                "reason": f"bench errored: {record['error']}"}
    platform = record.get("platform", "")
    aliases = baseline.get("platform_aliases", {})
    entry = baseline.get("platforms", {}).get(
        aliases.get(platform, platform))
    if entry is None:
        return {"perf_gate": "unusable",
                "reason": f"no baseline for platform {platform!r}"}
    value = float(record.get("value", 0.0))
    base = float(entry["rows_per_sec"])
    floor = base * (1.0 - tolerance_pct / 100.0)
    verdict = {
        "perf_gate": "pass" if value >= floor else "fail",
        "metric": baseline.get("metric"),
        "platform": platform,
        "value_rows_per_sec": round(value, 1),
        "baseline_rows_per_sec": round(base, 1),
        "floor_rows_per_sec": round(floor, 1),
        "tolerance_pct": tolerance_pct,
        "delta_vs_baseline_pct": round((value - base) / base * 100.0, 2),
    }
    # carry the forensics along: a failing gate should arrive WITH the
    # host/device attribution and the structured backend diagnosis
    if isinstance(record.get("profile"), dict):
        verdict["profile"] = record["profile"]
    pr = record.get("probe_report")
    if isinstance(pr, dict):
        verdict["probe_ok"] = pr.get("ok")
        if not pr.get("ok"):
            failed = next((s for s in pr.get("steps", [])
                           if not s.get("ok")), {})
            verdict["probe_failed_step"] = failed.get("name")
            verdict["probe_error"] = (
                f"{failed.get('error_type', '')}: "
                f"{failed.get('error_message', '')}").strip(": ")
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline", default=_DEFAULT_BASELINE,
                    help="baseline JSON (default tools/perf_baseline.json)")
    ap.add_argument("--bench-json", default=None,
                    help="bench record file ('-' reads stdin) instead of "
                         "running bench.py")
    ap.add_argument("--run", action="store_true",
                    help="run bench.py for a fresh record (the default "
                         "when --bench-json is absent)")
    ap.add_argument("--tolerance-pct", type=float, default=None,
                    help="allowed shortfall vs the baseline floor "
                         "(default: auron.perf_gate.tolerance_pct env "
                         "override, else the baseline file's)")
    args = ap.parse_args(argv)

    baseline = load_baseline(args.baseline)
    if args.bench_json == "-":
        record = json.loads(sys.stdin.read().strip().splitlines()[-1])
    elif args.bench_json:
        with open(args.bench_json) as f:
            record = json.loads(f.read().strip().splitlines()[-1])
    else:
        record = fresh_bench_record()

    tolerance = resolve_tolerance(args.tolerance_pct, baseline)
    verdict = evaluate(record, baseline, tolerance)

    if verdict["perf_gate"] == "unusable":
        print(f"perf gate: UNUSABLE — {verdict['reason']}")
        print(json.dumps(verdict))
        return 2
    print(f"perf gate [{verdict['platform']}]: "
          f"{verdict['value_rows_per_sec']:,.0f} rows/s vs baseline "
          f"{verdict['baseline_rows_per_sec']:,.0f} "
          f"(floor {verdict['floor_rows_per_sec']:,.0f}, "
          f"tolerance {tolerance:.0f}%) → "
          f"{verdict['perf_gate'].upper()}")
    if "profile" in verdict:
        p = verdict["profile"]
        print(f"  host/device split: device={p.get('device_ms')}ms "
              f"host={p.get('host_ms')}ms "
              f"buckets={p.get('host_buckets_ms')}")
    print(json.dumps(verdict))
    return 0 if verdict["perf_gate"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
