"""Render a post-mortem failure bundle — or a live ops-endpoint poll —
into a human post-mortem.

    python tools/ops_report.py <bundle_dir>          # post-mortem
    python tools/ops_report.py --url http://h:port   # live poll
    python tools/ops_report.py --dir <bundles_root>  # inventory table

The bundle mode prints the failure's identity (query, outcome, error,
site), the flight-recorder event timeline leading up to it (the
failing query's events flagged, neighbors interleaved), the scheduler /
memmgr / mesh state at failure time, and the explain-with-metrics tree
when the bundle carries one. The live mode polls /healthz, /queries and
/metrics and prints the same shape for a process that is still up.

``--url`` understands BOTH endpoint flavors: a replica's ops endpoint
(PR 14) and the fleet router's (``auron.fleet.ops_port``) — the
/healthz body's ``role`` key picks the renderer. Against a router it
prints the merged fleet query table (each row tagged with its replica)
and the per-replica health/occupancy table, dead replicas labeled
``down``. Fleet death bundles (``bundle_fleet_death_*``) render their
routing timeline, the dead replica's last scraped state, and the
survivor's failover record.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_ts(us: float) -> str:
    return f"{us / 1e6:10.3f}s"


def _fmt_attrs(attrs: dict, limit: int = 6) -> str:
    items = list(attrs.items())[:limit]
    return " ".join(f"{k}={v}" for k, v in items)


def render_timeline(events: list[dict], query_id: str = "",
                    tail: int = 60) -> list[str]:
    """The failure's event timeline: last ``tail`` events, the failing
    query's rows marked with '>' so the cause reads at a glance."""
    lines = [f"  {'':1} {'ts':>11} {'cat':<9} {'event':<28} "
             f"{'query':<12} attrs"]
    for ev in events[-tail:]:
        mark = ">" if query_id and ev.get("query") == query_id else " "
        lines.append(
            f"  {mark} {_fmt_ts(ev.get('ts_us', 0.0))} "
            f"{ev.get('cat', '?'):<9} {ev.get('name', '?'):<28} "
            f"{(ev.get('query') or '-'):<12} "
            f"{_fmt_attrs(ev.get('attrs') or {})}")
    return lines


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except OSError:
        return None


def render_fleet_death(path: str, mf: dict) -> str:
    """A fleet death bundle: the router's routing/failover timeline,
    the dead replica's last scraped state, and (when recovery landed)
    the survivor's failover record."""
    from auron_tpu.obs import flight_recorder as flight
    out = [
        f"fleet death bundle: {path}",
        f"  replica   : {mf.get('replica')}",
        f"  outcome   : {mf.get('outcome')}",
        f"  router pid: {mf.get('pid')}   created: "
        f"{mf.get('created_wall')}",
    ]
    fo = _load_json(os.path.join(path, "failover.json"))
    if fo:
        out.append(f"  recovery  : {fo.get('action')} on "
                   f"{fo.get('survivor')} after "
                   f"{fo.get('latency_s')}s")
    else:
        out.append("  recovery  : (no failover record — nothing was "
                   "in flight, or recovery failed)")
    health = _load_json(os.path.join(path, "replica_health.json"))
    if health:
        out.append("")
        out.append(f"dead replica's last scraped health: "
                   f"status={health.get('status')}"
                   + (f" reasons={health.get('reasons')}"
                      if health.get("reasons") else ""))
    queries = _load_json(os.path.join(path, "replica_queries.json"))
    if queries:
        rows = queries.get("queries") or []
        out.append(f"dead replica's last query table "
                   f"({len(rows)} rows):")
        for row in rows:
            out.append(f"  {row.get('query'):<12} "
                       f"{row.get('state'):<8} "
                       f"wall={row.get('wall_s')}s")
    tl = os.path.join(path, "routing_timeline.jsonl")
    if os.path.exists(tl):
        events = flight.read_jsonl(tl)
        out.append("")
        out.append(f"routing timeline ({len(events)} router events):")
        out.extend(render_timeline(events))
    stats = _load_json(os.path.join(path, "router_stats.json"))
    if stats:
        out.append("")
        out.append(f"router counters: {stats.get('router')}")
    return "\n".join(out) + "\n"


def render_bundle(path: str) -> str:
    from auron_tpu.obs import bundle as bundle_mod
    from auron_tpu.obs import flight_recorder as flight
    mf = bundle_mod.read_manifest(path)
    if mf.get("kind") == "fleet_death":
        return render_fleet_death(path, mf)
    qid = mf.get("query_id", "?")
    out = [
        f"post-mortem bundle: {path}",
        f"  query     : {qid}",
        f"  outcome   : {mf.get('outcome')}",
        f"  error     : {mf.get('error_type')}: {mf.get('error')}",
        f"  site      : {mf.get('site') or '-'}",
        f"  progress  : {mf.get('tasks_done')}/{mf.get('tasks_total')} "
        f"tasks",
        f"  pid       : {mf.get('pid')}   created: "
        f"{mf.get('created_wall')}",
    ]
    led = _load_json(os.path.join(path, "ledger.json"))
    if led:
        out.append(f"  cost      : device={led.get('device_s')}s "
                   f"host={led.get('host_total_s')}s "
                   f"wall={led.get('wall_s')}s "
                   f"rows={led.get('rows')} "
                   f"spill={_g(led, 'spill', 'bytes')}B "
                   f"shuffle={_g(led, 'shuffle', 'bytes')}B "
                   f"retries={_g(led, 'retries', 'transient_retries')}")
    flight_path = os.path.join(path, "flight.jsonl")
    if os.path.exists(flight_path):
        events = flight.read_jsonl(flight_path)
        out.append("")
        out.append(f"event timeline ({len(events)} recorded; "
                   f"'>' = the failing query):")
        out.extend(render_timeline(events, query_id=qid))
    sched = _load_json(os.path.join(path, "scheduler.json"))
    if sched:
        out.append("")
        out.append("scheduler at failure:")
        for row in sched.get("table", []):
            out.append(
                f"  {row.get('query'):<12} {row.get('state'):<8} "
                f"wall={row.get('wall_s')}s "
                f"tasks={row.get('tasks_done')}/{row.get('tasks_total')}"
                f" mem={row.get('mem_used_bytes', '-')}"
                f"/{row.get('mem_quota_bytes', '-')}")
        if "stats" in sched:
            st = sched["stats"]
            out.append(f"  admitted={st.get('admitted')} "
                       f"rejected={st.get('rejected')} "
                       f"dequeued={st.get('dequeued')}")
    mem = _load_json(os.path.join(path, "memmgr.json"))
    if mem:
        out.append("")
        out.append("memmgr at failure:")
        for st in mem:
            out.append(f"  used={st.get('used')}/{st.get('total')} "
                       f"consumers={st.get('num_consumers')} "
                       f"spills={st.get('num_spills')} "
                       f"queries={st.get('queries')}")
    mesh = _load_json(os.path.join(path, "mesh.json"))
    if mesh:
        out.append("")
        out.append(f"mesh plane: {json.dumps(mesh, default=str)[:500]}")
    probe = _load_json(os.path.join(path, "probe_report.json"))
    if probe:
        out.append("")
        out.append(f"backend probe: ok={probe.get('ok')} "
                   f"platform={probe.get('platform')}")
    stalls = sorted(p for p in os.listdir(path)
                    if p.startswith("stall_report_"))
    for p in stalls:
        rep = _load_json(os.path.join(path, p)) or {}
        out.append(f"stall report {p}: last_site="
                   f"{rep.get('last_site', '?')}")
    explain = os.path.join(path, "explain.txt")
    if os.path.exists(explain):
        out.append("")
        out.append("explain (metrics from completed tasks):")
        with open(explain) as f:
            out.extend("  " + ln.rstrip() for ln in f)
    return "\n".join(out) + "\n"


def _g(d: dict, *keys, default="-"):
    """Nested dict get for report rows (missing keys render '-')."""
    for k in keys:
        if not isinstance(d, dict) or k not in d:
            return default
        d = d[k]
    return d


def render_fleet_live(url: str, get, health: dict) -> str:
    """The router flavor of the live poll: per-replica health /
    occupancy (dead replicas labeled ``down``), the merged fleet query
    table, router counters, and the federated metrics' outcome view."""
    fleet = json.loads(get("/fleet/queries"))
    out = [f"live fleet poll: {url}",
           f"  status : {health.get('status')}  replicas "
           f"{health.get('replicas_live')}/"
           f"{health.get('replicas_total')} live"]
    rt = health.get("router") or {}
    out.append(f"  router : routed={rt.get('routed')} "
               f"spillovers={rt.get('spillovers')} "
               f"deaths={rt.get('replica_deaths')} "
               f"failovers={rt.get('failovers_resume')}+"
               f"{rt.get('failovers_reexecute')}")
    out.append("")
    out.append("replicas:")
    for label, rep in sorted((fleet.get("replicas") or {}).items()):
        out.append(f"  {label:<4} {rep.get('name'):<22} "
                   f"{rep.get('status'):<12} "
                   f"running={rep.get('running')} "
                   f"queued={rep.get('queued')} "
                   f"pid={rep.get('pid')}")
    out.append("")
    out.append("fleet queries (merged):")
    rows = fleet.get("queries") or []
    if not rows:
        out.append("  (idle)")
    for row in rows:
        out.append(f"  {row.get('replica'):<4} "
                   f"{row.get('query'):<12} {row.get('state'):<8} "
                   f"wall={row.get('wall_s')}s "
                   f"tasks={row.get('tasks_done')}/"
                   f"{row.get('tasks_total')}")
    from auron_tpu.obs import registry as obs_registry
    fams = obs_registry.parse_prometheus(get("/metrics").decode())
    up = fams.get("auron_fleet_replica_up")
    if up:
        out.append("")
        out.append("federated reachability (auron_fleet_replica_up):")
        for name, labels, value in up["samples"]:
            out.append(f"  {labels.get('replica'):<22} "
                       f"{'up' if value else 'DOWN'}")
    dur = fams.get("auron_query_duration_seconds")
    if dur:
        out.append("")
        out.append("fleet query outcomes (per replica):")
        for name, labels, value in dur["samples"]:
            if name.endswith("_count"):
                out.append(f"  replica={labels.get('replica', '-'):<4} "
                           f"outcome={labels.get('outcome'):<10} "
                           f"count={value:g}")
    return "\n".join(out) + "\n"


def render_live(url: str) -> str:
    import urllib.request

    def get(path: str) -> bytes:
        with urllib.request.urlopen(url.rstrip("/") + path,
                                    timeout=10) as r:
            return r.read()

    health = json.loads(get("/healthz"))
    if health.get("role") == "router":
        return render_fleet_live(url, get, health)
    queries = json.loads(get("/queries"))
    out = [f"live ops poll: {url}",
           f"  status : {health.get('status')}"
           + (f"  reasons: {health.get('reasons')}"
              if health.get("reasons") else "")]
    sched = health.get("scheduler") or {}
    for name, st in sched.items():
        out.append(f"  scheduler[{name}]: running={st.get('running')} "
                   f"queued={st.get('queued')}")
    out.append("")
    out.append("live queries:")
    rows = queries.get("queries", [])
    if not rows:
        out.append("  (idle)")
    for row in rows:
        out.append(f"  {row.get('query'):<12} {row.get('state'):<8} "
                   f"wall={row.get('wall_s')}s "
                   f"tasks={row.get('tasks_done')}/"
                   f"{row.get('tasks_total')}")
    out.append("")
    out.append("recent flight events:")
    events = [json.loads(ln) for ln in
              get("/flight?last=30").decode().splitlines() if ln]
    out.extend(render_timeline(events, tail=30))
    from auron_tpu.obs import registry as obs_registry
    fams = obs_registry.parse_prometheus(get("/metrics").decode())
    dur = fams.get("auron_query_duration_seconds")
    if dur:
        out.append("")
        out.append("query outcomes (auron_query_duration_seconds):")
        for name, labels, value in dur["samples"]:
            if name.endswith("_count"):
                out.append(f"  outcome={labels.get('outcome'):<10} "
                           f"count={value:g}")
    return "\n".join(out) + "\n"


def render_inventory(root: str) -> str:
    from auron_tpu.obs import bundle as bundle_mod
    out = [f"bundle inventory: {root}"]
    entries = bundle_mod.list_bundles(root)
    if not entries:
        out.append("  (no bundles)")
    for p in entries:
        try:
            mf = bundle_mod.read_manifest(p)
            out.append(f"  {os.path.basename(p):<28} "
                       f"{mf.get('outcome'):<18} "
                       f"{mf.get('error_type')}: "
                       f"{(mf.get('error') or '')[:60]}")
        except Exception as e:   # noqa: BLE001 — inventory best-effort
            out.append(f"  {os.path.basename(p):<28} <unreadable: {e}>")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("bundle", nargs="?",
                    help="path to one bundle_<query_id>/ directory")
    ap.add_argument("--url", help="live ops endpoint "
                                  "(http://host:port) to poll instead")
    ap.add_argument("--dir", help="bundles root: print the inventory "
                                  "table")
    args = ap.parse_args(argv)
    if args.url:
        print(render_live(args.url), end="")
    elif args.dir:
        print(render_inventory(args.dir), end="")
    elif args.bundle:
        print(render_bundle(args.bundle), end="")
    else:
        ap.error("give a bundle directory, --url, or --dir")
    return 0


if __name__ == "__main__":
    sys.exit(main())
