"""Shuffle durable-tier microbench: frame checksum overhead A/B.

Measures the engine's shuffle path with ``auron.durability.checksum``
on vs off and prints the relative overhead (median of PAIRED
interleaved reps, alternating order, so system drift cancels). The
ISSUE 4 acceptance gate is < 3% regression on the default ``e2e`` mode
— a full RssShuffleExchangeOp materialize+read cycle, exactly the path
queries pay (partition-id kernel, device→host, serde, durable-tier
framing+CRC, host→device). Spill frames share the same CRC code path,
so this is the integrity tax for both durable tiers.

``--mode serde`` strips the device/kernel half and measures
serialize→write→commit→fetch→deserialize; ``--mode raw`` strips serde
too and measures framing+CRC alone over opaque frames — the most
adversarial slice (nothing amortizes the checksum), for sizing the CRC
itself, not the gate.

    python tools/microbench_shuffle.py                  # e2e, the gate
    python tools/microbench_shuffle.py --mode serde --rows 32768
    python tools/microbench_shuffle.py --mode raw --gate 100

Prints one human table and ends with ONE JSON line (same driver
contract as bench.py / compile_report.py).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_host_batches(n_batches: int, rows: int):
    """Serde-level host batches (3 columns: int64 key, f64 value, int32
    code — the chaos/TPC-DS row shape) — built directly so the bench
    needs no device round trip."""
    import numpy as np

    from auron_tpu.columnar.serde import HostBatch, HostPrimitive

    rng = np.random.default_rng(11)
    out = []
    for _ in range(n_batches):
        valid = np.ones(rows, bool)
        out.append(HostBatch([
            HostPrimitive(rng.integers(0, 1 << 40, rows,
                                       dtype=np.int64), valid),
            HostPrimitive(rng.normal(size=rows), valid),
            HostPrimitive(rng.integers(0, 1000, rows,
                                       dtype=np.int32).astype(np.int32),
                          valid),
        ], rows))
    return out


def _run_serde(root: str, hosts, num_partitions: int) -> tuple[float, int]:
    """One serialize→write→commit→fetch→deserialize cycle; returns
    (wall seconds, payload bytes on the durable tier)."""
    from auron_tpu.columnar.serde import (deserialize_host_batch,
                                          serialize_host_batch)
    from auron_tpu.parallel.shuffle_service import FileShuffleService

    service = FileShuffleService(root)
    t0 = time.perf_counter()
    nbytes = 0
    with service.partition_writer(1, 0, num_partitions) as w:
        for i, host in enumerate(hosts):
            frame = serialize_host_batch(host, codec_level=1)
            nbytes += len(frame)
            w.write(i % num_partitions, frame)
        w.commit()
    service.commit_shuffle(1, 1)
    rows = 0
    for p in range(num_partitions):
        for fr in service.map_partition_frames(1, 0, p):
            host, _ = deserialize_host_batch(fr)
            rows += host.num_rows
    dt = time.perf_counter() - t0
    assert rows == sum(h.num_rows for h in hosts)
    service.delete_shuffle(1)
    return dt, nbytes


def _make_record_batches(n_batches: int, rows: int):
    import numpy as np
    import pyarrow as pa

    rng = np.random.default_rng(11)
    return [pa.record_batch({
        "k": pa.array(rng.integers(0, 1 << 20, rows), pa.int64()),
        "v": pa.array(rng.normal(size=rows)),
        "c": pa.array(rng.integers(0, 1000, rows), pa.int32()),
    }) for _ in range(n_batches)]


def _run_e2e(root: str, rbs, num_partitions: int) -> tuple[float, int]:
    """One full RssShuffleExchangeOp materialize+read cycle — the
    engine's shuffle path exactly as queries drive it (partition-id
    kernel, device→host, serde, durable tier, host→device)."""
    from auron_tpu.columnar.arrow_bridge import schema_from_arrow
    from auron_tpu.exprs import ir
    from auron_tpu.io.parquet import MemoryScanOp
    from auron_tpu.parallel.exchange import RssShuffleExchangeOp
    from auron_tpu.parallel.partitioning import HashPartitioning
    from auron_tpu.parallel.shuffle_service import FileShuffleService
    from auron_tpu.runtime.executor import collect

    service = FileShuffleService(root)
    scan = MemoryScanOp([rbs], schema_from_arrow(rbs[0].schema),
                        capacity=rbs[0].num_rows)
    op = RssShuffleExchangeOp(
        scan, HashPartitioning([ir.ColumnRef(0)], num_partitions),
        service, shuffle_id=1, input_partitions=1)
    t0 = time.perf_counter()
    out = collect(op, num_partitions=num_partitions)
    dt = time.perf_counter() - t0
    assert out.num_rows == sum(rb.num_rows for rb in rbs)
    nbytes = sum(os.path.getsize(p) for p in service.map_outputs(1))
    service.delete_shuffle(1)
    return dt, nbytes


def _run_raw(root: str, frames, num_partitions: int) -> tuple[float, int]:
    """Framing-only cycle over opaque frames (no serde)."""
    from auron_tpu.parallel.shuffle_service import FileShuffleService

    service = FileShuffleService(root)
    t0 = time.perf_counter()
    with service.partition_writer(1, 0, num_partitions) as w:
        for i, fr in enumerate(frames):
            w.write(i % num_partitions, fr)
        w.commit()
    service.commit_shuffle(1, 1)
    fetched = 0
    for p in range(num_partitions):
        for fr in service.map_partition_frames(1, 0, p):
            fetched += len(fr)
    dt = time.perf_counter() - t0
    assert fetched == sum(len(f) for f in frames)
    service.delete_shuffle(1)
    return dt, fetched


def bench(args) -> dict:
    import numpy as np

    from auron_tpu import config as cfg
    from auron_tpu.utils import checksum as cks

    if args.mode == "raw":
        rng = np.random.default_rng(11)
        payload = [rng.integers(0, 64, args.frame_kb << 10,
                                dtype=np.uint8).tobytes()
                   for _ in range(args.batches)]
        runner = _run_raw
    elif args.mode == "serde":
        payload = _make_host_batches(args.batches, args.rows)
        runner = _run_serde
    else:
        payload = _make_record_batches(args.batches, args.rows)
        runner = _run_e2e

    conf = cfg.get_config()
    root = tempfile.mkdtemp(prefix="shuffle_bench_")
    on_times, off_times, nbytes = [], [], 0
    try:
        # warm-up rep (page cache, import paths) then PAIRED interleaved
        # reps: each rep runs on then off back to back, and the reported
        # overhead is the MEDIAN of per-rep ratios — system drift between
        # reps cancels within a pair instead of polluting the A/B
        conf.set(cfg.DURABILITY_CHECKSUM, False)
        runner(os.path.join(root, "warmup"), payload, args.partitions)
        for r in range(args.reps):
            # alternate which half goes first so ordering effects
            # (page-cache state, allocator warmth) cancel across reps
            for on in ((True, False) if r % 2 == 0 else (False, True)):
                import gc
                gc.collect()   # keep collector pauses out of the pair
                conf.set(cfg.DURABILITY_CHECKSUM, on)
                dt, nbytes = runner(
                    os.path.join(root, f"{'on' if on else 'off'}_{r}"),
                    payload, args.partitions)
                (on_times if on else off_times).append(dt)
    finally:
        conf.unset(cfg.DURABILITY_CHECKSUM)
        shutil.rmtree(root, ignore_errors=True)
    mb = nbytes / 2**20
    ratios = sorted(a / b for a, b in zip(on_times, off_times))
    overhead = ratios[len(ratios) // 2] - 1.0
    return {
        "mode": args.mode,
        "algo": {cks.ALGO_CRC32C: "crc32c", cks.ALGO_CRC32: "zlib-crc32"}[
            cks.preferred_algo()],
        "frames": args.batches, "mb": round(mb, 1), "reps": args.reps,
        "shuffle_mb_per_sec_checksum_on": mb / min(on_times),
        "shuffle_mb_per_sec_checksum_off": mb / min(off_times),
        "checksum_overhead_pct": overhead * 100.0,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=65536,
                    help="rows per batch (serde mode; the engine's "
                         "default spill/shuffle frame)")
    ap.add_argument("--batches", type=int, default=32,
                    help="batches (frames in --raw mode)")
    ap.add_argument("--frame-kb", type=int, default=256,
                    help="bytes per frame (KiB, --raw mode)")
    ap.add_argument("--partitions", type=int, default=8)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--mode", choices=["e2e", "serde", "raw"],
                    default="e2e",
                    help="e2e: the engine's full exchange path (the "
                         "gate); serde: serialize+frame+fetch only; "
                         "raw: framing+CRC over opaque frames (the "
                         "most adversarial slice)")
    ap.add_argument("--gate", type=float, default=None,
                    help="fail (exit 1) when overhead exceeds this pct")
    args = ap.parse_args(argv)

    r = bench(args)
    print(f"mode                 {r['mode']}")
    print(f"algorithm            {r['algo']}")
    print(f"payload              {r['frames']} frames, {r['mb']:.0f} MiB "
          f"on the durable tier, {args.partitions} partitions")
    print(f"checksum on          {r['shuffle_mb_per_sec_checksum_on']:.0f} "
          f"MiB/s (write+commit+fetch)")
    print(f"checksum off         {r['shuffle_mb_per_sec_checksum_off']:.0f} "
          f"MiB/s")
    print(f"overhead             {r['checksum_overhead_pct']:+.2f}%")
    print(json.dumps(r))
    if args.gate is not None and r["checksum_overhead_pct"] > args.gate:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
