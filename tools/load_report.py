"""Concurrent-serving load report: drive M clients against AuronServer.

The measurement half of the [serving] scheduler plane: spin an
in-process ``AuronServer`` (admission control armed via the
``auron.sched.*`` knobs), hammer it with M concurrent clients x R
requests each, and print the admission/shed/latency table the PERF.md
"Concurrent serving" section quotes:

- serial baseline wall vs concurrent wall → the aggregate-vs-serial
  throughput ratio (the ROADMAP gate asks >= ~0.8x of serial);
- admission outcomes: ok / rejected-by-reason / cancelled, straight
  from the server scheduler's registry-independent counters;
- latency p50/p99 of successful requests and the scheduler's observed
  queue-wait p50/p99;
- an overload arm: clients sized at 2x the concurrency + queue budget
  MUST produce rejections (shed-not-crash) — the report fails loudly
  when overload produced zero sheds, because that means the admission
  door was not actually exercised.

    python tools/load_report.py                      # defaults
    python tools/load_report.py --clients 8 --requests 4 \
        --max-concurrent 2 --queue-depth 2

``--repeat N`` switches to the WARM-PATH measurement (the PR 16 cache
acceptance figure): the same task driven N times cold (cache disabled,
every run executes fully) and N times warm (cache enabled, first run
populates, the rest hit), reporting cold/warm latency p50s, their
ratio, a bit-identical check of cached-vs-fresh results, and the
server's cache counters from ``AuronClient.stats()``.
``--expect-speedup X`` makes a warm-p50 speedup under X exit nonzero:

    python tools/load_report.py --repeat 10 --expect-speedup 10

``--fleet N`` switches to the FLEET measurement (the serving-fleet
acceptance figure): N subprocess replicas behind an in-process
``FleetRouter``, each replica throttled to one concurrent query +
one queue slot so admission capacity — the thing replication buys —
is the measured resource.  The same concurrent burst is driven twice
(once at fleet size 1, once at N, with one replica SIGKILLed
mid-burst) and the report gates on:

- zero UNCLASSIFIED client errors (every request ends in a result or
  a structured AdmissionRejected — replica death included);
- every successful result bit-identical to the baseline table
  (journal-backed failover must not change bytes);
- aggregate admitted throughput >= ``--expect-scale`` x the
  single-replica run (default 2.5);
- a clean shared journal dir after the dead-owner sweep (a resumable
  journal nobody failed over = a dropped query).

    python tools/load_report.py --fleet 3

The last stdout line is one JSON record (the bench.py/chaos_report.py
driver contract)."""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _dataset(root: str, rows: int):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(7)
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 64, rows), pa.int64()),
        "v": pa.array(rng.normal(size=rows), pa.float64())})
    path = os.path.join(root, "load.parquet")
    pq.write_table(tbl, path)
    return path


def _task_bytes(path: str):
    from auron_tpu.ir import pb
    col = lambda i: pb.ExprNode(column=pb.ColumnRefE(index=i))
    plan = pb.PlanNode(agg=pb.AggNode(
        child=pb.PlanNode(parquet_scan=pb.ParquetScanNode(files=[path])),
        mode="complete", group_exprs=[col(0)],
        aggs=[pb.AggFunctionP(fn="sum", arg=col(1)),
              pb.AggFunctionP(fn="count", arg=col(1))]))
    return pb.TaskDefinition(plan=plan, task_id=1).SerializeToString()


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(p * len(sorted_vals)),
                           len(sorted_vals) - 1)]


def _drive(addr, task, n_requests, outcomes, lock, ledgers=None):
    from auron_tpu.runtime.serving import AuronClient
    client = AuronClient(*addr, timeout_s=120)
    for _ in range(n_requests):
        t0 = time.perf_counter()
        try:
            _tbl, metrics = client.execute(task)
            kind = "ok"
            if ledgers is not None and isinstance(metrics, dict) \
                    and isinstance(metrics.get("cost_ledger"), dict):
                with lock:
                    ledgers.append(metrics["cost_ledger"])
        except RuntimeError as e:
            kind = ("rejected" if "AdmissionRejected" in str(e)
                    else "error")
        except Exception:   # noqa: BLE001 — tally, don't crash the driver
            kind = "error"
        with lock:
            outcomes.append((kind, time.perf_counter() - t0))


def run_load(clients: int, requests: int, max_concurrent: int,
             queue_depth: int, rows: int) -> dict:
    from auron_tpu import config as cfg
    from auron_tpu.runtime.serving import AuronServer
    conf = cfg.get_config()
    conf.set(cfg.SCHED_MAX_CONCURRENT, max_concurrent)
    conf.set(cfg.SCHED_QUEUE_DEPTH, queue_depth)
    root = tempfile.mkdtemp(prefix="auron_load_")
    try:
        path = _dataset(root, rows)
        task = _task_bytes(path)
        srv = AuronServer()
        srv.serve_background()
        try:
            lock = threading.Lock()
            # warm compiles so the serial/concurrent comparison is fair
            warm: list = []
            _drive(srv.address, task, 1, warm, lock)
            if warm[0][0] != "ok":
                raise SystemExit("load_report: warmup request failed")

            # serial baseline: the same total request count, one at a
            # time through one client
            serial: list = []
            t0 = time.perf_counter()
            _drive(srv.address, task, clients * requests, serial, lock)
            serial_wall = time.perf_counter() - t0
            serial_ok = sum(1 for k, _ in serial if k == "ok")

            # concurrent storm
            before = srv.scheduler.stats()
            outcomes: list = []
            ledgers: list = []
            threads = [threading.Thread(
                target=_drive,
                args=(srv.address, task, requests, outcomes, lock,
                      ledgers),
                daemon=True) for _ in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            wedged = 0
            for t in threads:
                t.join(300)
                if t.is_alive():
                    wedged += 1
            conc_wall = time.perf_counter() - t0
            st = srv.scheduler.stats()

            oks = sorted(lat for k, lat in outcomes if k == "ok")
            n_ok = len(oks)
            n_rej = sum(1 for k, _ in outcomes if k == "rejected")
            # tally against the EXPECTED request count: a wedged
            # client's missing outcomes register as errors — a dropped
            # thread must fail the report, not flatter its table
            n_err = clients * requests - n_ok - n_rej
            # aggregate throughput ratio: completed requests per second,
            # concurrent vs serial (rejected requests completed NOTHING
            # — shedding must not flatter the ratio)
            serial_rps = serial_ok / serial_wall if serial_wall else 0.0
            conc_rps = n_ok / conc_wall if conc_wall else 0.0
            return {
                "clients": clients,
                "requests_per_client": requests,
                "max_concurrent": max_concurrent,
                "queue_depth": queue_depth,
                "input_rows": rows,
                "serial": {"ok": serial_ok,
                           "wall_s": round(serial_wall, 3),
                           "req_per_sec": round(serial_rps, 2)},
                "concurrent": {
                    "ok": n_ok, "rejected": n_rej, "error": n_err,
                    "wall_s": round(conc_wall, 3),
                    "req_per_sec": round(conc_rps, 2),
                    "latency_p50_s": round(_pct(oks, 0.50), 4),
                    "latency_p99_s": round(_pct(oks, 0.99), 4),
                },
                "throughput_ratio_vs_serial": round(
                    conc_rps / serial_rps, 3) if serial_rps else 0.0,
                "sched": {
                    "rejected_by_reason": {
                        k: v - before["rejected_by_reason"].get(k, 0)
                        for k, v in st["rejected_by_reason"].items()},
                    "dequeued_by_reason": st["dequeued_by_reason"],
                    "queue_wait_p50_s": st["queue_wait_p50_s"],
                    "queue_wait_p99_s": st["queue_wait_p99_s"],
                },
                "wedged_clients": wedged,
                "server_stats": dict(srv.stats),
                # per-query cost ledgers off the DONE frames, folded
                # into fleet-scale totals (obs/ledger.fold)
                "cost": _fold_ledgers(ledgers),
            }
        finally:
            srv.shutdown()
    finally:
        conf.unset(cfg.SCHED_MAX_CONCURRENT)
        conf.unset(cfg.SCHED_QUEUE_DEPTH)
        import shutil
        shutil.rmtree(root, ignore_errors=True)


def run_repeat(repeats: int, rows: int) -> dict:
    """Warm-path A/B: the same task N times with the result cache OFF
    (cold — every run executes the full pipeline) then N times with it
    ON (warm — the first run populates, the rest are served from
    cache). Cold/warm p50s and their ratio are the PERF.md "Warm-path
    serving" figures; the bit-identical check and the server's cache
    counters prove the warm runs actually came from the cache rather
    than a faster execution."""
    from auron_tpu import config as cfg
    from auron_tpu.cache.result_cache import get_cache
    from auron_tpu.runtime.serving import AuronClient, AuronServer
    conf = cfg.get_config()
    cache = get_cache()
    root = tempfile.mkdtemp(prefix="auron_repeat_")
    try:
        path = _dataset(root, rows)
        task = _task_bytes(path)
        srv = AuronServer()
        srv.serve_background()
        try:
            client = AuronClient(*srv.address, timeout_s=120)
            # cold phase: cache off; one unmeasured warmup first so the
            # cold p50 measures execution, not first-compile
            conf.set(cfg.CACHE_ENABLED, False)
            client.execute(task)
            cold_lat: list = []
            cold_tbl = None
            for _ in range(repeats):
                t0 = time.perf_counter()
                cold_tbl, _ = client.execute(task)
                cold_lat.append(time.perf_counter() - t0)
            # warm phase: cache on, starting empty; the first request
            # misses and populates, the measured N all hit
            conf.set(cfg.CACHE_ENABLED, True)
            cache.clear(reset_counters=True)
            fresh_tbl, _ = client.execute(task)
            warm_lat: list = []
            warm_tbl, hit_flags = None, []
            for _ in range(repeats):
                t0 = time.perf_counter()
                warm_tbl, metrics = client.execute(task)
                warm_lat.append(time.perf_counter() - t0)
                hit_flags.append(bool(metrics.get("cache_hit")))
            identical = (warm_tbl.equals(fresh_tbl)
                         and warm_tbl.equals(cold_tbl))
            stats = client.stats()
            cold_sorted, warm_sorted = sorted(cold_lat), sorted(warm_lat)
            cold_p50 = _pct(cold_sorted, 0.50)
            warm_p50 = _pct(warm_sorted, 0.50)
            return {
                "mode": "repeat",
                "repeats": repeats,
                "input_rows": rows,
                "cold": {"p50_s": round(cold_p50, 4),
                         "p99_s": round(_pct(cold_sorted, 0.99), 4)},
                "warm": {"p50_s": round(warm_p50, 4),
                         "p99_s": round(_pct(warm_sorted, 0.99), 4),
                         "cache_hits": sum(hit_flags)},
                "speedup_x": round(cold_p50 / warm_p50, 1)
                if warm_p50 > 0 else 0.0,
                "bit_identical": identical,
                "cache": stats.get("cache", {}),
            }
        finally:
            srv.shutdown()
    finally:
        conf.unset(cfg.CACHE_ENABLED)
        cache.clear(reset_counters=True)
        import shutil
        shutil.rmtree(root, ignore_errors=True)


def _fold_ledgers(ledgers: list) -> dict:
    from auron_tpu.obs import ledger as ledger_mod
    return ledger_mod.fold(ledgers)


def _fleet_burst(harness, task, clients: int, requests: int,
                 kill_index=None, kill_after_s: float = 0.0):
    """Drive ``clients`` x ``requests`` through the harness's router,
    optionally SIGKILLing one replica mid-burst.  Returns (outcomes,
    wall_s, tables) where outcomes are ("ok"|"rejected"|"error", lat)
    tuples — "rejected" strictly means a structured AdmissionRejected
    verdict, anything else non-ok is an UNCLASSIFIED error."""
    lock = threading.Lock()
    outcomes: list = []
    tables: list = []
    error_samples: list = []
    # all clients pass the gate together: admission capacity is the
    # measured resource, so the burst must actually be simultaneous
    # (thread start stagger on a small host would smuggle refill
    # capacity into the "one replica" baseline)
    barrier = threading.Barrier(clients)

    ledgers: list = []

    def drive():
        client = harness.client(timeout_s=120)
        barrier.wait(timeout=60)
        for _ in range(requests):
            t0 = time.perf_counter()
            try:
                tbl, metrics = client.execute(task)
                kind = "ok"
                with lock:
                    tables.append(tbl)
                    if isinstance(metrics, dict) and isinstance(
                            metrics.get("cost_ledger"), dict):
                        ledgers.append(metrics["cost_ledger"])
            except Exception as e:   # noqa: BLE001 — tally, don't crash
                kind = ("rejected" if "AdmissionRejected" in str(e)
                        else "error")
                if kind == "error":
                    with lock:
                        if len(error_samples) < 3:
                            error_samples.append(
                                str(e).replace("\n", " | ")[:300])
            with lock:
                outcomes.append((kind, time.perf_counter() - t0))

    threads = [threading.Thread(target=drive, daemon=True)
               for _ in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    if kill_index is not None:
        time.sleep(kill_after_s)
        # prefer a replica that is actually busy so the kill lands on
        # an in-flight conversation (the failover surface under test)
        harness.router._poll_once()
        busy = kill_index
        for i, rep in enumerate(harness.router._replicas):
            if rep.snapshot.running or rep.snapshot.queued:
                busy = i
                break
        harness.kill_replica(busy)
    wedged = 0
    for t in threads:
        t.join(300)
        if t.is_alive():
            wedged += 1
    wall = time.perf_counter() - t0
    return outcomes, wall, tables, wedged, error_samples, ledgers


def _journal_orphans(journal_dir: str) -> list:
    """Artifacts left in the shared journal dir after the dead-owner
    sweep: every one is a query failover dropped on the floor."""
    import glob as globmod

    from auron_tpu.runtime import journal as jrn
    jrn.sweep_orphans(journal_dir, force=True)
    leftovers = []
    for pat in ("*.journal", "*.part", "*.claim"):
        leftovers.extend(os.path.basename(p) for p in globmod.glob(
            os.path.join(journal_dir, pat)))
    rss = os.path.join(journal_dir, "rss")
    if os.path.isdir(rss):
        leftovers.extend("rss/" + n for n in os.listdir(rss))
    return sorted(leftovers)


def run_fleet(n: int, clients: int, requests: int, rows: int) -> dict:
    from auron_tpu.fleet import FleetHarness
    root = tempfile.mkdtemp(prefix="auron_fleet_load_")
    # throttle each replica to 1 running + 1 queued query: on a small
    # host the fleet's win is ADMISSION capacity (more replicas admit
    # more of the same burst), and this makes that the measured axis
    env_extra = {"AURON_CONF_SCHED_MAX_CONCURRENT": "1",
                 "AURON_CONF_SCHED_QUEUE_DEPTH": "1"}
    try:
        path = _dataset(root, rows)
        task = _task_bytes(path)
        jdir_one = os.path.join(root, "journal_one")
        jdir_n = os.path.join(root, "journal_n")
        os.makedirs(jdir_one)
        os.makedirs(jdir_n)

        with FleetHarness(1, journal_dir=jdir_one,
                          env_extra=env_extra) as h1:
            warm: list = []
            lock = threading.Lock()
            _drive(h1.address, task, 1, warm, lock)
            if warm[0][0] != "ok":
                raise SystemExit("fleet report: warmup failed")
            base_tbl, _ = h1.client(timeout_s=120).execute(task)
            out1, wall1, _tbls1, wedged1, errs1, _led1 = _fleet_burst(
                h1, task, clients, requests)
            stats1 = h1.router.stats_dict()

        with FleetHarness(n, journal_dir=jdir_n,
                          env_extra=env_extra) as hn:
            _drive(hn.address, task, 1, [], lock)   # warm compiles
            outn, walln, tblsn, wedgedn, errsn, ledn = _fleet_burst(
                hn, task, clients, requests, kill_index=0,
                kill_after_s=1.0)
            statsn = hn.router.stats_dict()

        orphans = (_journal_orphans(jdir_one)
                   + _journal_orphans(jdir_n))

        def tally(outcomes, total):
            ok = sum(1 for k, _ in outcomes if k == "ok")
            rej = sum(1 for k, _ in outcomes if k == "rejected")
            return ok, rej, total - ok - rej

        total = clients * requests
        ok1, rej1, err1 = tally(out1, total)
        okn, rejn, errn = tally(outn, total)
        rps1 = ok1 / wall1 if wall1 else 0.0
        rpsn = okn / walln if walln else 0.0
        identical = all(t.equals(base_tbl) for t in tblsn)
        lat = statsn.get("failover_latency_s") or []
        return {
            "mode": "fleet",
            "replicas": n,
            "clients": clients,
            "requests_per_client": requests,
            "input_rows": rows,
            "one": {"ok": ok1, "rejected": rej1, "error": err1,
                    "wall_s": round(wall1, 3),
                    "req_per_sec": round(rps1, 2),
                    "wedged": wedged1},
            "fleet": {"ok": okn, "rejected": rejn, "error": errn,
                      "wall_s": round(walln, 3),
                      "req_per_sec": round(rpsn, 2),
                      "wedged": wedgedn},
            "admitted_scale_x": round(okn / ok1, 2) if ok1 else 0.0,
            "throughput_scale_x": round(rpsn / rps1, 2) if rps1
            else 0.0,
            "bit_identical": identical,
            "failover": {
                "deaths": statsn["router"]["replica_deaths"],
                "resumes": statsn["router"]["failovers_resume"],
                "reexecutes": statsn["router"]["failovers_reexecute"],
                "latency_p50_s": round(_pct(lat, 0.50), 4),
                "latency_p99_s": round(_pct(lat, 0.99), 4),
            },
            "router": statsn["router"],
            "journal_orphans": orphans,
            "error_samples": errs1 + errsn,
            # folded per-query cost ledgers from the fleet burst's DONE
            # frames — fleet.hops/failover facts stamped by the router
            "cost": _fold_ledgers(ledn),
        }
    finally:
        import shutil
        shutil.rmtree(root, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=None,
                    help="concurrent client threads (default 8; "
                         "fleet mode: 4 x N)")
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per client (default 3; fleet "
                         "mode: 1 — a single simultaneous round "
                         "measures admission capacity, not refill "
                         "dynamics)")
    ap.add_argument("--max-concurrent", type=int, default=2,
                    help="auron.sched.max_concurrent for the run")
    ap.add_argument("--queue-depth", type=int, default=2,
                    help="auron.sched.queue_depth for the run")
    ap.add_argument("--rows", type=int, default=None,
                    help="rows in the driven aggregation (default "
                         "200k; fleet mode: 3M — query time must "
                         "dwarf burst stagger so admission capacity, "
                         "not thread scheduling, decides outcomes)")
    ap.add_argument("--expect-shed", action="store_true",
                    help="fail (exit 1) when the overload produced ZERO "
                         "rejections — the admission door went untested")
    ap.add_argument("--repeat", type=int, default=0, metavar="N",
                    help="warm-path mode: drive the same task N times "
                         "cold (cache off) and N times warm (cache on) "
                         "and report the p50 speedup instead of the "
                         "concurrency table")
    ap.add_argument("--expect-speedup", type=float, default=None,
                    metavar="X",
                    help="with --repeat: fail (exit 1) when the warm "
                         "p50 speedup is under X or the cached results "
                         "are not bit-identical")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet mode: N subprocess replicas behind a "
                         "router, one SIGKILLed mid-burst; reports "
                         "admitted-throughput scale vs one replica, "
                         "failover latency, and journal cleanliness")
    ap.add_argument("--expect-scale", type=float, default=2.5,
                    metavar="X",
                    help="with --fleet: fail (exit 1) when aggregate "
                         "admitted throughput is under X x the one-"
                         "replica run (default 2.5)")
    args = ap.parse_args(argv)

    if args.fleet > 0:
        rep = run_fleet(args.fleet,
                        args.clients or 4 * args.fleet,
                        args.requests or 1,
                        args.rows or 3_000_000)
        o, f, fo = rep["one"], rep["fleet"], rep["failover"]
        print(f"fleet report: {args.fleet} replicas, "
              f"{rep['clients']} clients x "
              f"{rep['requests_per_client']} req, one replica "
              "SIGKILLed mid-burst")
        print(f"  one replica : {o['ok']} ok / {o['rejected']} "
              f"rejected / {o['error']} error in {o['wall_s']}s "
              f"({o['req_per_sec']} req/s)")
        print(f"  fleet       : {f['ok']} ok / {f['rejected']} "
              f"rejected / {f['error']} error in {f['wall_s']}s "
              f"({f['req_per_sec']} req/s)")
        print(f"  admitted scale: {rep['admitted_scale_x']}x ; "
              f"throughput scale: {rep['throughput_scale_x']}x")
        print(f"  failover: {fo['deaths']} death(s), {fo['resumes']} "
              f"resumed / {fo['reexecutes']} re-executed, "
              f"p50/p99 {fo['latency_p50_s']}s / {fo['latency_p99_s']}s")
        print(f"  bit-identical results: {rep['bit_identical']} ; "
              f"journal orphans: {len(rep['journal_orphans'])}")
        cost = rep.get("cost") or {}
        if cost.get("queries"):
            print(f"  cost ledgers: {cost['queries']} queries, "
                  f"device {cost['device_s']}s / host "
                  f"{cost['host_total_s']}s, "
                  f"{cost['rows']} rows, "
                  f"{cost['replica_hops']} replica hop(s), "
                  f"{cost['failovers']} failed-over, "
                  f"{cost['cache_hits']} cache hit(s)")
        rc = 0
        if f["error"] or f["wedged"] or o["error"] or o["wedged"]:
            print(f"  FAIL: {f['error'] + o['error']} request(s) died "
                  f"UNCLASSIFIED / {f['wedged'] + o['wedged']} "
                  "wedged — replica death leaked to a client")
            rc = 1
        if not rep["bit_identical"]:
            print("  FAIL: a failed-over result differs from the "
                  "baseline table")
            rc = 1
        if rep["journal_orphans"]:
            print(f"  FAIL: journal orphans left behind: "
                  f"{rep['journal_orphans']}")
            rc = 1
        if rep["throughput_scale_x"] < args.expect_scale \
                and rep["admitted_scale_x"] < args.expect_scale:
            print(f"  FAIL: admitted throughput scaled "
                  f"{rep['throughput_scale_x']}x (admitted "
                  f"{rep['admitted_scale_x']}x) < expected "
                  f"{args.expect_scale}x")
            rc = 1
        print(json.dumps(rep))
        return rc

    if args.repeat > 0:
        rep = run_repeat(args.repeat, args.rows or 200_000)
        c, w = rep["cold"], rep["warm"]
        print(f"repeat report: {args.repeat} runs cold vs warm "
              f"({rep['input_rows']} rows)")
        print(f"  cold p50/p99: {c['p50_s']}s / {c['p99_s']}s "
              f"(cache disabled)")
        print(f"  warm p50/p99: {w['p50_s']}s / {w['p99_s']}s "
              f"({w['cache_hits']}/{args.repeat} served from cache)")
        print(f"  speedup: {rep['speedup_x']}x ; bit-identical: "
              f"{rep['bit_identical']}")
        print(f"  server cache stats: {rep['cache']}")
        rc = 0
        if not rep["bit_identical"]:
            print("  FAIL: cached result differs from the fresh run")
            rc = 1
        if w["cache_hits"] < args.repeat:
            print(f"  FAIL: only {w['cache_hits']}/{args.repeat} warm "
                  "runs hit the cache — the warm path did not engage")
            rc = 1
        if args.expect_speedup is not None \
                and rep["speedup_x"] < args.expect_speedup:
            print(f"  FAIL: speedup {rep['speedup_x']}x < expected "
                  f"{args.expect_speedup}x")
            rc = 1
        print(json.dumps(rep))
        return rc

    rep = run_load(args.clients or 8, args.requests or 3,
                   args.max_concurrent, args.queue_depth,
                   args.rows or 200_000)
    c, s = rep["concurrent"], rep["serial"]
    print(f"load report: {args.clients} clients x {args.requests} req, "
          f"max_concurrent={args.max_concurrent} "
          f"queue_depth={args.queue_depth}")
    print(f"  serial    : {s['ok']} ok in {s['wall_s']}s "
          f"({s['req_per_sec']} req/s)")
    print(f"  concurrent: {c['ok']} ok / {c['rejected']} rejected / "
          f"{c['error']} error in {c['wall_s']}s "
          f"({c['req_per_sec']} req/s)")
    print(f"  throughput ratio vs serial: "
          f"{rep['throughput_ratio_vs_serial']}x")
    print(f"  latency p50/p99: {c['latency_p50_s']}s / "
          f"{c['latency_p99_s']}s ; queue wait p50/p99: "
          f"{rep['sched']['queue_wait_p50_s']}s / "
          f"{rep['sched']['queue_wait_p99_s']}s")
    print(f"  sheds by reason: {rep['sched']['rejected_by_reason']}")
    cost = rep.get("cost") or {}
    if cost.get("queries"):
        print(f"  cost ledgers: {cost['queries']} queries, "
              f"device {cost['device_s']}s / host "
              f"{cost['host_total_s']}s, shuffle "
              f"{cost['shuffle_bytes']}B, spill {cost['spill_bytes']}B")
    rc = 0
    if args.expect_shed and c["rejected"] == 0:
        print("  FAIL: overload produced no rejections — admission "
              "control untested at this load")
        rc = 1
    if c["error"]:
        print(f"  FAIL: {c['error']} requests died UNCLASSIFIED "
              "(neither DONE nor AdmissionRejected)")
        rc = 1
    print(json.dumps(rep))
    return rc


if __name__ == "__main__":
    sys.exit(main())
