"""Per-query XLA program-build report from the central program registry.

For each query of a TPC-DS / TPC-H suite run, prints the programs BUILT
(central registry, auron_tpu/runtime/programs.py), the registry cache
hits, and the raw backend compiles + seconds (utils/compile_stats) —
the numbers behind PERF.md's compile-economics section and the
whole-stage-fusion acceptance gate.

    python tools/compile_report.py --suite tpcds --scale 0.05
    python tools/compile_report.py --fusion off          # unfused baseline
    python tools/compile_report.py --compare             # both, fresh
                                                         # process each,
                                                         # prints the delta

``--compare`` runs the suite twice in CHILD processes (one per fusion
setting) so neither run warms the other's kernel caches, then reports
total builds and the fused-vs-unfused reduction — the ISSUE 2 acceptance
check (builds drop >= 30% on the CI-scale gate).

The last stdout line of a single run is one JSON record, so drivers and
--compare can parse totals without scraping the table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# CPU mesh before jax init, like the IT runner: this is an accounting
# tool, not a perf gate — it must run on a wedged-accelerator host
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xf = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xf:
    os.environ["XLA_FLAGS"] = (
        _xf + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_report(suite: str, scale: float, names, data_dir=None) -> dict:
    import tempfile
    import time

    from auron_tpu.runtime import programs
    from auron_tpu.utils import compile_stats

    if suite == "tpcds":
        from auron_tpu.it.tpcds import generate
        from auron_tpu.it.tpcds_queries import QUERIES
    else:
        from auron_tpu.it.tpch import generate
        from auron_tpu.it.tpch_queries import QUERIES
    from auron_tpu.frontend.session import Session

    data_dir = data_dir or tempfile.mkdtemp(prefix="compile_report_")
    tables = generate(data_dir, scale=scale)

    rows = []
    t_start = compile_stats.snapshot()
    p_start = programs.totals()
    print(f"{'query':>6}  {'builds':>6}  {'hits':>6}  {'compiles':>8}  "
          f"{'compile_s':>9}  {'wall_s':>7}")
    for q in QUERIES:
        if names and q.name not in names:
            continue
        compile_stats.maybe_clear()
        c0 = compile_stats.snapshot()
        p0 = programs.totals()
        t0 = time.perf_counter()
        err = None
        try:
            q.run(Session(), tables)
        except Exception as e:   # noqa: BLE001 — report, don't abort
            err = f"{type(e).__name__}: {e}"
        wall = time.perf_counter() - t0
        cd = compile_stats.delta(c0)
        pd = programs.delta(p0)
        rows.append({"query": q.name, "builds": pd.builds,
                     "hits": pd.hits, "compiles": cd.count,
                     "compile_s": round(cd.seconds, 2),
                     "wall_s": round(wall, 2), "error": err})
        line = (f"{q.name:>6}  {pd.builds:>6}  {pd.hits:>6}  "
                f"{cd.count:>8}  {cd.seconds:>9.2f}  {wall:>7.2f}")
        if err:
            line += f"  ERROR {err[:80]}"
        print(line, flush=True)
    td = compile_stats.delta(t_start)
    pdt = programs.delta(p_start)
    from auron_tpu import config as cfg
    sites = {k: v for k, v in programs.snapshot().items() if v["builds"]}
    # hash-table subsystem attribution: every hashtable.* compile site
    # (agg_step/agg_grow/agg_export/build/probe/grow/join_index) rides
    # the central registry like any other builder — break its share out
    # so hash-path compile costs are visible at a glance
    ht_sites = {k: v for k, v in sites.items()
                if k.startswith("hashtable.")}
    summary = {
        "suite": suite, "scale": scale,
        "queries": len(rows),
        "fusion": cfg.get_config().get(cfg.FUSION_ENABLED),
        "hashtable": cfg.get_config().get(cfg.HASHTABLE_ENABLED),
        "program_builds": pdt.builds,
        "program_hits": pdt.hits,
        "hashtable_builds": sum(v["builds"] for v in ht_sites.values()),
        "backend_compiles": td.count,
        "compile_seconds": round(td.seconds, 2),
        "sites": sites,
        "hashtable_sites": ht_sites,
        "per_query": rows,
    }
    print(f"total: {pdt.builds} program builds, {pdt.hits} hits, "
          f"{td.count} backend compiles, {td.seconds:.1f}s compiling")
    if ht_sites:
        per = ", ".join(f"{k.split('.', 1)[1]}={v['builds']}"
                        for k, v in sorted(ht_sites.items()))
        print(f"hashtable sites: {summary['hashtable_builds']} builds "
              f"({per})")
    return summary


def _compare(args) -> int:
    """Fused vs unfused in fresh child processes; prints the reduction."""
    import subprocess
    results = {}
    for fused in ("false", "true"):
        env = dict(os.environ)
        env["AURON_CONF_FUSION_ENABLED"] = fused
        cmd = [sys.executable, os.path.abspath(__file__),
               "--suite", args.suite, "--scale", str(args.scale)]
        if args.queries:
            cmd += ["--queries", args.queries]
        if args.data:
            cmd += ["--data", args.data]
        proc = subprocess.run(cmd, capture_output=True, text=True, env=env)
        if proc.returncode != 0 or not proc.stdout.strip():
            sys.stderr.write(proc.stderr)
            print(f"fusion={fused} child failed rc={proc.returncode}")
            return 1
        results[fused] = json.loads(proc.stdout.strip().splitlines()[-1])
    off, on = results["false"], results["true"]
    drop = 1.0 - (on["program_builds"] / max(1, off["program_builds"]))
    print(f"unfused: {off['program_builds']} builds, "
          f"{off['compile_seconds']}s compiling")
    print(f"fused:   {on['program_builds']} builds, "
          f"{on['compile_seconds']}s compiling")
    print(f"program-build reduction: {drop:.1%} "
          f"({'meets' if drop >= 0.30 else 'BELOW'} the >=30% gate)")
    print(json.dumps({"unfused_builds": off["program_builds"],
                      "fused_builds": on["program_builds"],
                      "reduction": round(drop, 4)}))
    return 0 if drop >= 0.30 else 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--suite", default="tpcds", choices=["tpcds", "tpch"])
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--queries", default="",
                    help="comma-separated query names (default: all)")
    ap.add_argument("--data", default=None,
                    help="reuse/create the dataset in this directory")
    ap.add_argument("--fusion", default=None, choices=["on", "off"],
                    help="override auron.fusion.enabled for this run")
    ap.add_argument("--compare", action="store_true",
                    help="run fused AND unfused (fresh process each) and "
                         "print the program-build reduction")
    args = ap.parse_args(argv)
    if args.compare:
        return _compare(args)
    if args.fusion is not None:
        from auron_tpu import config as cfg
        cfg.get_config().set("auron.fusion.enabled", args.fusion == "on")
    names = [n.strip() for n in args.queries.split(",") if n.strip()] or None
    summary = run_report(args.suite, args.scale, names, data_dir=args.data)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
