"""Per-query XLA program-build report from the central program registry.

For each query of a TPC-DS / TPC-H suite run, prints the programs BUILT
(central registry, auron_tpu/runtime/programs.py), the registry cache
hits, and the raw backend compiles + seconds (utils/compile_stats) —
the numbers behind PERF.md's compile-economics section and the
whole-stage-fusion acceptance gate.

    python tools/compile_report.py --suite tpcds --scale 0.05
    python tools/compile_report.py --fusion off          # unfused baseline
    python tools/compile_report.py --compare             # both, fresh
                                                         # process each,
                                                         # prints the delta

``--compare`` runs the suite twice in CHILD processes (one per fusion
setting) so neither run warms the other's kernel caches, then reports
total builds and the fused-vs-unfused reduction — the ISSUE 2 acceptance
check (builds drop >= 30% on the CI-scale gate).

The last stdout line of a single run is one JSON record, so drivers and
--compare can parse totals without scraping the table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# CPU mesh before jax init, like the IT runner: this is an accounting
# tool, not a perf gate — it must run on a wedged-accelerator host
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xf = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xf:
    os.environ["XLA_FLAGS"] = (
        _xf + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _decision_mix(decisions: dict) -> dict:
    """Histogram of plan-time fusion decisions (ir/cost.record_decision):
    {'combine': n, 'passthrough': n, 'fold': n, 'unfused': n}."""
    mix: dict = {}
    for _kind, mode in decisions.values():
        mix[mode] = mix.get(mode, 0) + 1
    return mix


def run_report(suite: str, scale: float, names, data_dir=None,
               repeat: int = 1) -> dict:
    import tempfile
    import time

    from auron_tpu.ir import cost as cost_mod
    from auron_tpu.runtime import programs
    from auron_tpu.utils import compile_stats

    if suite == "tpcds":
        from auron_tpu.it.tpcds import generate
        from auron_tpu.it.tpcds_queries import QUERIES
    else:
        from auron_tpu.it.tpch import generate
        from auron_tpu.it.tpch_queries import QUERIES
    from auron_tpu.frontend.session import Session

    data_dir = data_dir or tempfile.mkdtemp(prefix="compile_report_")
    tables = generate(data_dir, scale=scale)

    rows = []
    t_start = compile_stats.snapshot()
    p_start = programs.totals()
    print(f"{'query':>6}  {'builds':>6}  {'hits':>6}  {'compiles':>8}  "
          f"{'compile_s':>9}  {'wall_s':>7}  {'modes':>18}")
    for q in QUERIES:
        if names and q.name not in names:
            continue
        compile_stats.maybe_clear()
        c0 = compile_stats.snapshot()
        p0 = programs.totals()
        d0 = set(cost_mod.decisions_snapshot())
        err = None
        # --repeat N re-runs the query in-process: run 1 seeds the
        # ir/cost history, run N reports the steady state the cost
        # model selects with real statistics (greedy runs are
        # history-independent, so repeats only warm program caches)
        for _ in range(max(1, repeat)):
            t0 = time.perf_counter()
            c0r, p0r = compile_stats.snapshot(), programs.totals()
            try:
                q.run(Session(), tables)
            except Exception as e:   # noqa: BLE001 — report, don't abort
                err = f"{type(e).__name__}: {e}"
                break
        wall = time.perf_counter() - t0
        cd = compile_stats.delta(c0)
        pd = programs.delta(p0)
        # builds/hits of the LAST repeat (steady state) ride separate
        # keys so --compare can diff both cold and warm economics
        cdl = compile_stats.delta(c0r)
        pdl = programs.delta(p0r)
        dec = {k: v for k, v in cost_mod.decisions_snapshot().items()
               if k not in d0}
        mix = _decision_mix(dec)
        mix_s = " ".join(f"{k}={v}" for k, v in sorted(mix.items()))
        rows.append({"query": q.name, "builds": pd.builds,
                     "hits": pd.hits, "compiles": cd.count,
                     "compile_s": round(cd.seconds, 2),
                     "wall_s": round(wall, 2),
                     "last_builds": pdl.builds, "last_hits": pdl.hits,
                     "last_compiles": cdl.count,
                     "modes": mix, "error": err})
        line = (f"{q.name:>6}  {pd.builds:>6}  {pd.hits:>6}  "
                f"{cd.count:>8}  {cd.seconds:>9.2f}  {wall:>7.2f}  "
                f"{mix_s:>18}")
        if err:
            line += f"  ERROR {err[:80]}"
        print(line, flush=True)
    td = compile_stats.delta(t_start)
    pdt = programs.delta(p_start)
    from auron_tpu import config as cfg
    sites = {k: v for k, v in programs.snapshot().items() if v["builds"]}
    # hash-table subsystem attribution: every hashtable.* compile site
    # (agg_step/agg_grow/agg_export/build/probe/grow/join_index) rides
    # the central registry like any other builder — break its share out
    # so hash-path compile costs are visible at a glance
    ht_sites = {k: v for k, v in sites.items()
                if k.startswith("hashtable.")}
    gcfg = cfg.get_config()
    summary = {
        "suite": suite, "scale": scale,
        "queries": len(rows),
        "repeat": repeat,
        "fusion": gcfg.get(cfg.FUSION_ENABLED),
        "hashtable": gcfg.get(cfg.HASHTABLE_ENABLED),
        "combine": gcfg.get(cfg.FUSION_COMBINE),
        "cost_model": gcfg.get(cfg.FUSION_COST_MODEL),
        "program_builds": pdt.builds,
        "program_hits": pdt.hits,
        "hashtable_builds": sum(v["builds"] for v in ht_sites.values()),
        "backend_compiles": td.count,
        "compile_seconds": round(td.seconds, 2),
        "last_wall_s": round(sum(r["wall_s"] for r in rows), 2),
        "last_builds": sum(r["last_builds"] for r in rows),
        "decision_mix": _decision_mix(cost_mod.decisions_snapshot()),
        "sites": sites,
        "hashtable_sites": ht_sites,
        "per_query": rows,
    }
    print(f"total: {pdt.builds} program builds, {pdt.hits} hits, "
          f"{td.count} backend compiles, {td.seconds:.1f}s compiling")
    if ht_sites:
        per = ", ".join(f"{k.split('.', 1)[1]}={v['builds']}"
                        for k, v in sorted(ht_sites.items()))
        print(f"hashtable sites: {summary['hashtable_builds']} builds "
              f"({per})")
    return summary


def _compare(args) -> int:
    """A/B in fresh child processes (one per knob setting) along the
    selected --dimension:

      fusion      — auron.fusion.enabled off vs on; gate: program builds
                    drop >= 30% (the ISSUE 2 acceptance check).
      cost_model  — auron.fusion.cost_model off (greedy-maximal) vs on;
                    children run with --repeat 3 so run 1 seeds the cost
                    history, run 2 re-plans with it, and run 3 reports
                    the selected steady state.
                    Gate: at least one plan decision differs from greedy
                    AND the selected run's steady-state wall is no slower
                    (<= 10% tolerance) with no more program builds.
    """
    import subprocess
    env_key = ("AURON_CONF_FUSION_ENABLED" if args.dimension == "fusion"
               else "AURON_CONF_FUSION_COST_MODEL")
    # cost_model children need >= 3 repeats: run 1 seeds the history,
    # run 2 re-plans with it (building any newly selected programs),
    # run 3 is the steady state both the wall and build gates read
    repeat = args.repeat if args.dimension == "fusion" else \
        max(3, args.repeat)
    results = {}
    for setting in ("false", "true"):
        env = dict(os.environ)
        env[env_key] = setting
        cmd = [sys.executable, os.path.abspath(__file__),
               "--suite", args.suite, "--scale", str(args.scale),
               "--repeat", str(repeat)]
        if args.queries:
            cmd += ["--queries", args.queries]
        if args.data:
            cmd += ["--data", args.data]
        proc = subprocess.run(cmd, capture_output=True, text=True, env=env)
        if proc.returncode != 0 or not proc.stdout.strip():
            sys.stderr.write(proc.stderr)
            print(f"{env_key}={setting} child failed rc={proc.returncode}")
            return 1
        results[setting] = json.loads(proc.stdout.strip().splitlines()[-1])
    off, on = results["false"], results["true"]
    if args.dimension == "fusion":
        drop = 1.0 - (on["program_builds"] / max(1, off["program_builds"]))
        print(f"unfused: {off['program_builds']} builds, "
              f"{off['compile_seconds']}s compiling")
        print(f"fused:   {on['program_builds']} builds, "
              f"{on['compile_seconds']}s compiling")
        print(f"program-build reduction: {drop:.1%} "
              f"({'meets' if drop >= 0.30 else 'BELOW'} the >=30% gate)")
        print(json.dumps({"unfused_builds": off["program_builds"],
                          "fused_builds": on["program_builds"],
                          "reduction": round(drop, 4)}))
        return 0 if drop >= 0.30 else 2
    # cost_model: plan-diff + steady-state wall/builds comparison
    differs = sum(1 for a, b in zip(off["per_query"], on["per_query"])
                  if a["modes"] != b["modes"])
    wall_off, wall_on = off["last_wall_s"], on["last_wall_s"]
    b_off, b_on = off["last_builds"], on["last_builds"]
    print(f"greedy (cost_model off): mix={off['decision_mix']} "
          f"steady wall={wall_off}s builds={b_off}")
    print(f"selected (cost_model on): mix={on['decision_mix']} "
          f"steady wall={wall_on}s builds={b_on}")
    print(f"queries whose chosen plan differs from greedy: {differs}")
    ok = differs >= 1 and wall_on <= wall_off * 1.10 and b_on <= b_off
    print(f"cost-model gate: {'meets' if ok else 'BELOW'} "
          f"(>=1 plan differs, steady wall no slower, no extra builds)")
    print(json.dumps({"plans_differ": differs,
                      "greedy_wall_s": wall_off,
                      "selected_wall_s": wall_on,
                      "greedy_builds": b_off,
                      "selected_builds": b_on}))
    return 0 if ok else 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--suite", default="tpcds", choices=["tpcds", "tpch"])
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--queries", default="",
                    help="comma-separated query names (default: all)")
    ap.add_argument("--data", default=None,
                    help="reuse/create the dataset in this directory")
    ap.add_argument("--fusion", default=None, choices=["on", "off"],
                    help="override auron.fusion.enabled for this run")
    ap.add_argument("--repeat", type=int, default=1,
                    help="run each query N times in-process (run 1 seeds "
                         "the ir/cost history; the reported wall and "
                         "last_builds are the final run's steady state)")
    ap.add_argument("--compare", action="store_true",
                    help="A/B along --dimension (fresh process per "
                         "setting) and print the delta")
    ap.add_argument("--dimension", default="fusion",
                    choices=["fusion", "cost_model"],
                    help="what --compare toggles: auron.fusion.enabled "
                         "or auron.fusion.cost_model (greedy vs selected)")
    args = ap.parse_args(argv)
    if args.compare:
        return _compare(args)
    if args.fusion is not None:
        from auron_tpu import config as cfg
        cfg.get_config().set("auron.fusion.enabled", args.fusion == "on")
    names = [n.strip() for n in args.queries.split(",") if n.strip()] or None
    summary = run_report(args.suite, args.scale, names, data_dir=args.data,
                         repeat=args.repeat)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
