"""Pallas grouped-aggregate kernel microbench.

The XLA formulations of the dense 2^16-domain group-aggregate are bound by
materializing [n, 512..1024] one-hot operands in HBM (~4 GB per 1M rows).
This kernel builds the one-hot tiles in VMEM per 2048-row block and
accumulates the [hi, lo] grids in VMEM across the whole grid — HBM traffic
collapses to the 12 B/row inputs.

Accuracy: the value operand is split into 3 additive bf16-exact terms via
bit-masking (f32 = 3 bf16 mantissa windows), so the single DEFAULT-precision
bf16 MXU pass reproduces f32-HIGHEST quality (~1e-7 rel).
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_GRID = 256
_DOMAIN = _GRID * _GRID


def _mask16(x):
    bits = lax.bitcast_convert_type(x, jnp.uint32)
    return lax.bitcast_convert_type(bits & jnp.uint32(0xFFFF0000),
                                    jnp.float32)


def _agg_kernel(k_ref, v_ref, c_ref, sums_ref, cnts_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        cnts_ref[:] = jnp.zeros_like(cnts_ref)

    k = k_ref[:]          # [1, BLK] int32 in [0, 2^16)
    v = v_ref[:]          # [1, BLK] f32, nulls already zeroed
    c = c_ref[:]          # [1, BLK] f32 0/1 count mask
    blk = k.shape[1]

    v1 = _mask16(v)
    r = v - v1
    v2 = _mask16(r)
    v3 = r - v2

    iota = lax.broadcasted_iota(jnp.int32, (blk, _GRID), 1)
    hi = (k.reshape(blk, 1) >> 8) == iota
    lo = ((k.reshape(blk, 1) & 255) == iota).astype(jnp.bfloat16)

    def masked(vals):
        return jnp.where(hi, vals.reshape(blk, 1), 0.0).astype(jnp.bfloat16)

    lhs = jnp.concatenate(
        [masked(v1), masked(v2), masked(v3), masked(c)], axis=1)
    out = lax.dot_general(lhs, lo, (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    sums_ref[:] += out[:_GRID] + out[_GRID:2 * _GRID] + out[2 * _GRID:3 * _GRID]
    cnts_ref[:] += out[3 * _GRID:]


@functools.partial(jax.jit, static_argnames=("blk",))
def pallas_agg(k, v, c, blk=2048):
    n = k.shape[0]
    grid = n // blk
    return pl.pallas_call(
        _agg_kernel,
        out_shape=(jax.ShapeDtypeStruct((_GRID, _GRID), jnp.float32),
                   jax.ShapeDtypeStruct((_GRID, _GRID), jnp.float32)),
        grid=(grid,),
        in_specs=[pl.BlockSpec((1, blk), lambda i: (0, i)),
                  pl.BlockSpec((1, blk), lambda i: (0, i)),
                  pl.BlockSpec((1, blk), lambda i: (0, i))],
        out_specs=(pl.BlockSpec((_GRID, _GRID), lambda i: (0, 0)),
                   pl.BlockSpec((_GRID, _GRID), lambda i: (0, 0))),
    )(k.reshape(1, n), v.reshape(1, n), c.reshape(1, n))


def main():
    print("devices:", jax.devices())
    rng = np.random.default_rng(0)
    n = 1 << 20
    iters = 20
    k0 = jnp.asarray(rng.integers(0, _DOMAIN, size=n).astype(np.int32))
    c0 = jnp.asarray((rng.random(n) > 0.05).astype(np.float32))
    # v arrives pre-masked (nulls zeroed), as in the engine kernel
    v0 = jnp.asarray(rng.normal(size=n).astype(np.float32)) * c0

    for blk in (1024, 2048, 4096, 8192):
        f = lambda k, v, c: pallas_agg(k, v, c, blk=blk)
        s, cn = f(k0, v0, c0)
        s.block_until_ready()
        # chained timing: output scalar feeds next input, defeating any
        # async/dedup effects; final host readback is the sync point
        def step(v):
            s, cn = f(k0, v, c0)
            return v + s[0, 0] * 1e-30
        st = jax.jit(step)
        v = st(v0)
        _ = float(jnp.sum(v))
        t0 = time.perf_counter()
        for _ in range(iters):
            v = st(v)
        _ = float(jnp.sum(v))
        dt = (time.perf_counter() - t0) / iters
        # accuracy
        s, cn = f(k0, v0, c0)
        kk = np.asarray(k0)
        vv = np.asarray(v0, np.float64) * np.asarray(c0, np.float64)
        rs = np.zeros(_DOMAIN)
        np.add.at(rs, kk, vv)
        rc = np.zeros(_DOMAIN)
        np.add.at(rc, kk, np.asarray(c0, np.float64))
        serr = (np.max(np.abs(np.asarray(s, np.float64).reshape(-1) - rs))
                / np.max(np.abs(rs)))
        cerr = np.max(np.abs(np.asarray(cn, np.float64).reshape(-1) - rc))
        print(f"pallas blk={blk:5d} {dt*1e3:8.3f} ms "
              f"{n/dt/1e6:9.1f} M rows/s rel={serr:.2e} cnt={cerr:.1f}")


if __name__ == "__main__":
    main()
