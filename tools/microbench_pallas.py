"""Pallas grouped-aggregate kernel microbench.

The kernel itself now lives in the engine — auron_tpu/kernels/
grouped_agg.py ``pallas_sum_count`` (promoted from this script's round-5
prototype), selected per-plan by kernels/dispatch.py. This script keeps
the standalone measurement harness: block-size sweep, chained-dependency
timing (honest on the tunneled platform, where block_until_ready returns
early), and an f64 numpy accuracy cross-check.

The XLA formulations of the dense 2^16-domain group-aggregate are bound
by materializing [n, 512..1024] one-hot operands in HBM (~4 GB per 1M
rows). The VMEM kernel builds the one-hot tiles in VMEM per row block
and accumulates the [hi, lo] grids in VMEM across the whole grid — HBM
traffic collapses to the 12 B/row inputs.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from auron_tpu.kernels.grouped_agg import (MAX_KEY_DOMAIN,  # noqa: E402
                                           pallas_sum_count)

_DOMAIN = MAX_KEY_DOMAIN


def main():
    print("devices:", jax.devices())
    interpret = jax.default_backend() != "tpu"
    if interpret:
        print("non-TPU backend: running the kernel INTERPRETED "
              "(correctness sweep only, timings are meaningless)")
    rng = np.random.default_rng(0)
    n = 1 << (14 if interpret else 20)
    iters = 2 if interpret else 20
    k0 = jnp.asarray(rng.integers(0, _DOMAIN, size=n).astype(np.int32))
    c0 = jnp.asarray((rng.random(n) > 0.05).astype(np.float32))
    # v arrives pre-masked (nulls zeroed), as in the engine kernel
    v0 = jnp.asarray(rng.normal(size=n).astype(np.float32)) * c0

    for blk in (1024, 2048, 4096, 8192):
        def f(k, v, c, _blk=blk):
            return pallas_sum_count(k, v, c, _DOMAIN, blk=_blk,
                                    interpret=interpret)
        s, cn = f(k0, v0, c0)
        s.block_until_ready()
        # chained timing: output scalar feeds next input, defeating any
        # async/dedup effects; final host readback is the sync point
        def step(v):
            s, cn = f(k0, v, c0)
            return v + s[0] * 1e-30
        st = jax.jit(step)
        v = st(v0)
        _ = float(jnp.sum(v))
        t0 = time.perf_counter()
        for _ in range(iters):
            v = st(v)
        _ = float(jnp.sum(v))
        dt = (time.perf_counter() - t0) / iters
        # accuracy
        s, cn = f(k0, v0, c0)
        kk = np.asarray(k0)
        vv = np.asarray(v0, np.float64) * np.asarray(c0, np.float64)
        rs = np.zeros(_DOMAIN)
        np.add.at(rs, kk, vv)
        rc = np.zeros(_DOMAIN)
        np.add.at(rc, kk, np.asarray(c0, np.float64))
        serr = (np.max(np.abs(np.asarray(s, np.float64) - rs))
                / np.max(np.abs(rs)))
        cerr = np.max(np.abs(np.asarray(cn, np.float64) - rc))
        print(f"pallas blk={blk:5d} {dt*1e3:8.3f} ms "
              f"{n/dt/1e6:9.1f} M rows/s rel={serr:.2e} cnt={cerr:.1f}")


if __name__ == "__main__":
    main()
