"""Microbenchmark for the device hash table (auron_tpu/hashtable):
build / probe / agg_update in isolation, plus the fused single-shot
grouped aggregation against the sort-based formulation.

    python tools/microbench_hashtable.py                 # defaults
    python tools/microbench_hashtable.py --rows 20 --keys 16
    # rows/keys are log2; --dups runs the duplicate-heavy shape

Prints one human table and ends with ONE JSON line (same driver contract
as bench.py / compile_report.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time(fn, *args, iters: int = 5):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=20, help="log2 input rows")
    ap.add_argument("--keys", type=int, default=16,
                    help="log2 distinct keys")
    ap.add_argument("--load", type=float, default=0.125,
                    help="table load factor (capacity sizing)")
    args = ap.parse_args(argv)

    import numpy as np

    import jax
    import jax.numpy as jnp

    from auron_tpu.columnar.batch import PrimitiveColumn
    from auron_tpu.hashtable import grouped_agg_once
    from auron_tpu.hashtable import core
    from auron_tpu.hashtable.agg import _hashes
    from auron_tpu.utils.shapes import next_pow2

    n = 1 << args.rows
    n_keys = 1 << args.keys
    cap = next_pow2(int(n_keys / args.load))
    rng = np.random.default_rng(0)
    k = jnp.asarray(rng.integers(0, n_keys, n).astype(np.int64))
    v = jnp.asarray(rng.normal(size=n))
    valid = jnp.asarray(rng.random(n) > 0.05)
    live = jnp.ones(n, bool)
    keys = (PrimitiveColumn(k, jnp.ones(n, bool)),)
    meta = core.key_meta(keys)
    results = {}

    # -- build: hash + claim rounds + install --------------------------------
    @jax.jit
    def build(k):
        cols = (PrimitiveColumn(k, jnp.ones(n, bool)),)
        h = _hashes(cols, n)
        w = core.key_words(cols, meta)
        th = jnp.full(cap, core.EMPTY, jnp.uint64)
        tw = jnp.zeros((cap, core.total_words(meta)), jnp.uint64)
        claims, slot, resolved = core.insert_loop(th, tw, h, w, live,
                                                  128, 1, tail_frac=8)
        th, tw = core.table_install(th, tw, h, w, claims)
        return th, tw, slot, resolved

    th, tw, slot, resolved = build(k)
    dt = _time(build, k)
    results["build_rows_per_sec"] = n / dt
    print(f"build       {dt * 1e3:8.1f} ms   {n / dt:14,.0f} rows/s "
          f"(cap 2^{cap.bit_length() - 1})")

    # -- probe: lookup-only --------------------------------------------------
    @jax.jit
    def probe(k, th, tw):
        cols = (PrimitiveColumn(k, jnp.ones(n, bool)),)
        h = _hashes(cols, n)
        w = core.key_words(cols, meta)
        return core.probe_loop(th, tw, h, w, live, 128)

    _slot2, found = probe(k, th, tw)
    assert bool(jnp.all(found)), "probe missed inserted keys"
    dt = _time(probe, k, th, tw)
    results["probe_rows_per_sec"] = n / dt
    print(f"probe       {dt * 1e3:8.1f} ms   {n / dt:14,.0f} rows/s")

    # -- agg_update: slot-indexed accumulator scatters -----------------------
    acc_meta = (("sum", "float64"), ("sum", "int32"))

    @jax.jit
    def update(slot, resolved, v, valid):
        accs, auxs = core.init_accs(acc_meta, cap)
        accs, _ = core.agg_update(
            accs, auxs, acc_meta, slot, resolved,
            (jnp.where(valid, v, 0.0), valid.astype(jnp.int32)),
            jnp.int64(0))
        return accs

    dt = _time(update, slot, resolved, v, valid)
    results["agg_update_rows_per_sec"] = n / dt
    print(f"agg_update  {dt * 1e3:8.1f} ms   {n / dt:14,.0f} rows/s")

    # -- fused single-shot vs the sort formulation ---------------------------
    @jax.jit
    def fused(k, v, valid):
        cols, accs, ng, gvalid = grouped_agg_once(
            (PrimitiveColumn(k, jnp.ones(n, bool)),),
            (jnp.where(valid, v, 0.0), valid.astype(jnp.int32)),
            ("sum", "sum"), live, cap)
        return accs[0], accs[1], ng

    @jax.jit
    def sort_formulation(k, v, valid):
        h = _hashes((PrimitiveColumn(k, jnp.ones(n, bool)),), n)
        perm = jnp.argsort(h, stable=True)
        h_s, k_s = h[perm], k[perm]
        v_s = jnp.where(valid, v, 0.0)[perm]
        c_s = valid.astype(jnp.int32)[perm]
        first = jnp.concatenate([jnp.ones(1, bool), h_s[1:] != h_s[:-1]])
        seg = jnp.cumsum(first.astype(jnp.int32)) - 1
        sums = jax.ops.segment_sum(v_s, seg, num_segments=n)
        cnts = jax.ops.segment_sum(c_s, seg, num_segments=n)
        return sums, cnts, jnp.sum(first.astype(jnp.int32))

    dt_h = _time(fused, k, v, valid)
    dt_s = _time(sort_formulation, k, v, valid)
    results["hash_agg_rows_per_sec"] = n / dt_h
    results["sort_agg_rows_per_sec"] = n / dt_s
    results["hash_vs_sort"] = dt_s / dt_h
    print(f"hash agg    {dt_h * 1e3:8.1f} ms   {n / dt_h:14,.0f} rows/s")
    print(f"sort agg    {dt_s * 1e3:8.1f} ms   {n / dt_s:14,.0f} rows/s")
    print(f"hash vs sort: {dt_s / dt_h:.2f}x")

    print(json.dumps({"metric": "microbench_hashtable",
                      "rows": n, "distinct_keys": n_keys,
                      "capacity": cap,
                      **{m: round(val, 1) for m, val in results.items()}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
