"""Trace-directory report: what the engine spent its time on.

Summarizes the JSONL event logs a traced run exported into
``auron.trace.dir`` (obs/trace.py, one ``trace_*.jsonl`` per top-level
query): per-category span counts and total/max duration, the top-N
slowest spans per category, the retry/recompute timeline (task.retry,
shuffle.corruption_recompute, fault.injected, watchdog.fallback events
in order), and compile-time attribution (program.build spans grouped by
compile site). ``--compare`` diffs two trace dirs (A/B runs: fused vs
unfused, checksums on vs off, ...) by per-category totals.

    python tools/trace_report.py /tmp/trace_dir
    python tools/trace_report.py /tmp/trace_dir --top 5
    python tools/trace_report.py --compare /tmp/base /tmp/candidate
    python tools/trace_report.py /tmp/trace_dir --stitch
    python tools/trace_report.py /tmp/trace_dir --stitch --trace 7

``--stitch`` renders one CROSS-PROCESS trace as a single timeline:
the wire protocol's TRACE frame propagates a trace id client → router
→ replica, each process streams its spans to its own
``trace_<id>_<role><pid>.jsonl`` (role/pid/epoch-wall stamped on every
record), and the ``fleet.adopt`` span's remote_parent/remote_role/
remote_pid attributes carry the cross-process parent link (span ids
are per-process counters, so the link cannot be an id match). The
stitcher groups records by (role, pid), builds each process's local
span tree, grafts adopted groups under their remote parent, and orders
everything on the epoch wall clock — a mid-query failover shows as the
dead replica's truncated group followed by the adoption hop to the
survivor.

The last stdout line is one JSON record (same driver contract as
bench.py / compile_report.py / chaos_report.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: event names that form the retry/recompute timeline
_TIMELINE_NAMES = ("fault.injected", "task.retry",
                   "shuffle.corruption_recompute", "watchdog.fallback")


def load_dir(trace_dir: str) -> list:
    from auron_tpu.obs.trace import read_jsonl
    spans = []
    files = sorted(glob.glob(os.path.join(trace_dir, "trace_*.jsonl")))
    if not files:
        raise SystemExit(f"no trace_*.jsonl files under {trace_dir!r} "
                         "(run with auron.trace.enabled + auron.trace.dir)")
    for f in files:
        spans.extend(read_jsonl(f))
    spans.sort(key=lambda s: (s.ts_ns, s.span_id))
    return spans


def load_dir_raw(trace_dir: str) -> list:
    """Every exported span record in ``trace_dir`` as raw dicts (the
    stitch path needs the role/pid/wall keys the Span class does not
    carry). Tolerant of partial files — a SIGKILLed replica leaves a
    torn last line, which ``read_jsonl_raw`` skips."""
    from auron_tpu.obs.trace import read_jsonl_raw
    recs: list = []
    files = sorted(glob.glob(os.path.join(trace_dir, "trace_*.jsonl")))
    if not files:
        raise SystemExit(f"no trace_*.jsonl files under {trace_dir!r} "
                         "(run with auron.trace.enabled + auron.trace.dir)")
    for f in files:
        recs.extend(read_jsonl_raw(f))
    return recs


def stitch(records: list, trace_id=None) -> dict:
    """Assemble one cross-process trace from raw exported records.

    Returns ``{"trace", "groups": [group...], "links": [...],
    "spans", "processes", "wall_span_s"}`` where each group is one
    (role, pid) process view — records wall-ordered, local parent tree
    resolved — and each link is a (parent group, parent span id, child
    group) graft derived from a ``fleet.adopt`` span's remote_* attrs.
    """
    recs = [r for r in records if isinstance(r.get("span"), int)]
    if trace_id is None:
        # the most interesting trace: most distinct processes, then
        # most records (a fleet query beats a local warm-up trace)
        counts: dict = {}
        for r in recs:
            t = r.get("trace")
            ent = counts.setdefault(t, [set(), 0])
            ent[0].add((r.get("role"), r.get("pid")))
            ent[1] += 1
        if not counts:
            raise SystemExit("no span records to stitch")
        trace_id = max(counts,
                       key=lambda t: (len(counts[t][0]), counts[t][1]))
    recs = [r for r in recs if r.get("trace") == trace_id]
    groups: dict = {}
    for r in recs:
        key = (str(r.get("role") or "?"), int(r.get("pid") or 0))
        groups.setdefault(key, []).append(r)
    out_groups = []
    links = []
    for key in sorted(groups, key=lambda k: min(
            r.get("wall") or 0.0 for r in groups[k])):
        rows = sorted(groups[key], key=lambda r: (r.get("wall") or 0.0,
                                                  r["span"]))
        by_id = {r["span"]: r for r in rows}
        roots = [r for r in rows
                 if not r.get("parent") or r["parent"] not in by_id]
        out_groups.append({"role": key[0], "pid": key[1],
                           "records": rows, "by_id": by_id,
                           "roots": roots})
        for r in rows:
            if r.get("name") != "fleet.adopt":
                continue
            attrs = r.get("attrs") or {}
            links.append({
                "parent_group": (str(attrs.get("remote_role") or "?"),
                                 int(attrs.get("remote_pid") or 0)),
                "parent_span": int(attrs.get("remote_parent") or 0),
                "child_group": key, "adopt_span": r["span"]})
    walls = [r.get("wall") or 0.0 for r in recs]
    return {"trace": trace_id, "groups": out_groups, "links": links,
            "spans": len(recs), "processes": len(out_groups),
            "wall_span_s": round(max(walls) - min(walls), 6)
            if walls else 0.0}


def print_stitched(st: dict) -> None:
    """One timeline, all processes: each span at its wall offset from
    the trace start, adopted process groups nested under the span that
    forwarded the context to them."""
    t0 = min((r.get("wall") or 0.0 for g in st["groups"]
              for r in g["records"]), default=0.0)
    by_key = {(g["role"], g["pid"]): g for g in st["groups"]}
    grafts: dict = {}     # (parent group key, parent span) -> [links]
    orphan_links = []
    for ln in st["links"]:
        pg = by_key.get(ln["parent_group"])
        if pg is not None and ln["parent_span"] in pg["by_id"]:
            grafts.setdefault((ln["parent_group"], ln["parent_span"]),
                              []).append(ln)
        else:
            orphan_links.append(ln)
    print(f"stitched trace {st['trace']}: {st['processes']} "
          f"process(es), {st['spans']} spans, "
          f"{st['wall_span_s'] * 1e3:.1f}ms wall")
    rendered: set = set()

    def line(rec, depth):
        rel = ((rec.get("wall") or 0.0) - t0) * 1e3
        dur = (rec.get("dur_us") or 0.0) / 1e3
        attrs = rec.get("attrs") or {}
        shown = {k: v for k, v in attrs.items()
                 if k not in ("remote_parent", "remote_role",
                              "remote_pid") and v not in ("", 0, None)}
        pad = "  " * depth
        print(f"  +{rel:9.2f}ms {dur:9.2f}ms  {pad}"
              f"{rec.get('name')}  {shown}" if shown else
              f"  +{rel:9.2f}ms {dur:9.2f}ms  {pad}{rec.get('name')}")

    def render_span(gkey, rec, depth):
        line(rec, depth)
        g = by_key[gkey]
        kids = sorted((r for r in g["records"]
                       if r.get("parent") == rec["span"]
                       and r is not rec),
                      key=lambda r: (r.get("wall") or 0.0, r["span"]))
        for kid in kids:
            render_span(gkey, kid, depth + 1)
        for ln in grafts.get((gkey, rec["span"]), ()):
            render_group(ln["child_group"], depth + 1)

    def render_group(gkey, depth):
        if gkey in rendered:
            return
        rendered.add(gkey)
        g = by_key[gkey]
        pad = "  " * depth
        print(f"  {'':22s}  {pad}-> {g['role']} pid {g['pid']} "
              f"({len(g['records'])} spans)")
        for root in g["roots"]:
            render_span(gkey, root, depth + 1)

    # roots: groups nobody adopted (normally just the client)
    child_keys = {ln["child_group"] for ln in st["links"]}
    for g in st["groups"]:
        key = (g["role"], g["pid"])
        if key not in child_keys:
            render_group(key, 0)
    # orphan links (the remote parent span never hit disk — a killed
    # process) and any group still unrendered: surface, never drop
    for ln in orphan_links:
        if ln["child_group"] not in rendered:
            pr, pp = ln["parent_group"]
            print(f"  (adopted from {pr} pid {pp}, parent span "
                  f"{ln['parent_span']} not on disk)")
            render_group(ln["child_group"], 0)
    for g in st["groups"]:
        render_group((g["role"], g["pid"]), 0)


def summarize(spans: list, top: int = 10) -> dict:
    by_cat: dict = {}
    for s in spans:
        c = by_cat.setdefault(s.cat, {"count": 0, "total_ms": 0.0,
                                      "max_ms": 0.0, "device_ms": 0.0})
        c["count"] += 1
        ms = s.dur_ns / 1e6
        c["total_ms"] += ms
        c["max_ms"] = max(c["max_ms"], ms)
        # host/device split: spans the profiler annotates (program.call
        # carries its block_until_ready wait as device_ms) contribute
        # device time; the category's host_ms is the remainder
        dev = s.attrs.get("device_ms")
        if isinstance(dev, (int, float)):
            c["device_ms"] += dev
    slowest = {}
    for cat in by_cat:
        worst = sorted((s for s in spans if s.cat == cat and s.dur_ns),
                       key=lambda s: -s.dur_ns)[:top]
        slowest[cat] = [
            {"name": s.name, "ms": round(s.dur_ns / 1e6, 3),
             "device_ms": s.attrs.get("device_ms", 0.0),
             "trace": s.trace_id, "span": s.span_id, "attrs": s.attrs}
            for s in worst]
    timeline = [
        {"ts_ms": round(s.ts_ns / 1e6, 3), "name": s.name,
         "attrs": s.attrs}
        for s in spans if s.name in _TIMELINE_NAMES]
    compile_sites: dict = {}
    for s in spans:
        if s.name == "program.build":
            site = s.attrs.get("site", "?")
            c = compile_sites.setdefault(site, {"builds": 0,
                                                "total_ms": 0.0})
            c["builds"] += 1
            c["total_ms"] += s.dur_ns / 1e6
    hits: dict = {}
    for s in spans:
        if s.name == "program.hit":
            site = s.attrs.get("site", "?")
            hits[site] = hits.get(site, 0) + 1
    for site, n in hits.items():
        compile_sites.setdefault(site, {"builds": 0, "total_ms": 0.0})
        compile_sites[site]["hits"] = n
    for c in compile_sites.values():
        c["total_ms"] = round(c["total_ms"], 3)
        c.setdefault("hits", 0)
    for c in by_cat.values():
        c["total_ms"] = round(c["total_ms"], 3)
        c["max_ms"] = round(c["max_ms"], 3)
        c["device_ms"] = round(c["device_ms"], 3)
        c["host_ms"] = round(max(c["total_ms"] - c["device_ms"], 0.0), 3)
    return {"spans": len(spans),
            "traces": len({s.trace_id for s in spans}),
            "by_category": by_cat, "slowest": slowest,
            "timeline": timeline, "compile_sites": compile_sites}


def print_summary(rep: dict, top: int) -> None:
    print(f"{rep['spans']} spans across {rep['traces']} trace(s)")
    print(f"{'category':10s} {'count':>7s} {'total_ms':>10s} "
          f"{'device_ms':>10s} {'host_ms':>9s} {'max_ms':>9s}")
    for cat, c in sorted(rep["by_category"].items()):
        print(f"{cat:10s} {c['count']:>7d} {c['total_ms']:>10.1f} "
              f"{c.get('device_ms', 0.0):>10.1f} "
              f"{c.get('host_ms', c['total_ms']):>9.1f} "
              f"{c['max_ms']:>9.1f}")
    print(f"\ntop-{top} slowest spans per category:")
    for cat, worst in sorted(rep["slowest"].items()):
        if not worst:
            continue
        print(f"  [{cat}]")
        for w in worst:
            attrs = {k: v for k, v in w["attrs"].items()
                     if k not in ("error", "device_ms", "dispatch_ms")}
            dev = w.get("device_ms") or 0.0
            split = f" dev={dev:.2f}ms" if dev else ""
            print(f"    {w['ms']:>10.2f}ms{split}  {w['name']}  {attrs}")
    if rep["compile_sites"]:
        print("\ncompile-time attribution (program.build per site):")
        for site, c in sorted(rep["compile_sites"].items(),
                              key=lambda kv: -kv[1]["total_ms"]):
            print(f"  {site:40s} builds={c['builds']:<4d} "
                  f"hits={c['hits']:<6d} {c['total_ms']:>9.1f}ms")
    if rep["timeline"]:
        print("\nretry/recompute timeline:")
        for t in rep["timeline"]:
            print(f"  {t['ts_ms']:>12.2f}ms  {t['name']}  {t['attrs']}")


def _compare(base_dir: str, cand_dir: str, top: int) -> int:
    base = summarize(load_dir(base_dir), top)
    cand = summarize(load_dir(cand_dir), top)
    print(f"{'category':10s} {'base_ms':>10s} {'cand_ms':>10s} "
          f"{'delta':>8s}")
    deltas = {}
    for cat in sorted(set(base["by_category"]) | set(cand["by_category"])):
        b = base["by_category"].get(cat, {}).get("total_ms", 0.0)
        c = cand["by_category"].get(cat, {}).get("total_ms", 0.0)
        # None, not inf, for a category absent from base: json.dumps
        # would emit the non-RFC 'Infinity' token and break the
        # last-line JSON driver contract
        pct = round((c - b) / b * 100.0, 2) if b else (None if c else 0.0)
        deltas[cat] = {"base_ms": b, "cand_ms": c, "delta_pct": pct}
        shown = "new" if pct is None else f"{pct:.1f}%"
        print(f"{cat:10s} {b:>10.1f} {c:>10.1f} {shown:>8s}")
    print(json.dumps({"base_spans": base["spans"],
                      "cand_spans": cand["spans"],
                      "categories": deltas}))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace_dir", nargs="?", default=None,
                    help="directory of trace_*.jsonl files")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest spans listed per category")
    ap.add_argument("--compare", nargs=2, metavar=("BASE", "CANDIDATE"),
                    default=None,
                    help="diff two trace dirs by per-category totals")
    ap.add_argument("--stitch", action="store_true",
                    help="render one cross-process trace as a single "
                         "client→router→replica timeline")
    ap.add_argument("--trace", type=int, default=None,
                    help="trace id to stitch (default: the one "
                         "spanning the most processes)")
    args = ap.parse_args(argv)
    if args.compare:
        return _compare(args.compare[0], args.compare[1], args.top)
    if not args.trace_dir:
        ap.error("trace_dir (or --compare) is required")
    if args.stitch:
        st = stitch(load_dir_raw(args.trace_dir), args.trace)
        print_stitched(st)
        print(json.dumps({
            "trace": st["trace"], "spans": st["spans"],
            "processes": st["processes"],
            "roles": sorted({g["role"] for g in st["groups"]}),
            "hops": len(st["links"]),
            "wall_span_s": st["wall_span_s"]}))
        return 0
    rep = summarize(load_dir(args.trace_dir), args.top)
    print_summary(rep, args.top)
    print(json.dumps({"trace_spans": rep["spans"],
                      "trace_traces": rep["traces"],
                      "by_category": rep["by_category"],
                      "compile_sites": rep["compile_sites"],
                      "timeline_events": len(rep["timeline"])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
