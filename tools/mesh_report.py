"""Per-exchange SPMD routing report from a trace directory.

Every exchange records its routing decision as an ``exchange.route``
event in the ``mesh`` trace category (parallel/exchange._record_route):
``all_to_all`` (the mesh-routed on-device shuffle, with rounds / quota
escalations / bytes moved / per-device skew attributes), ``device_buffer``
(the host-orchestrated classic path, with the fallback reason) or
``rss`` (the durable/multihost tier). This tool prints the table those
events make — which shuffles actually rode the mesh, how much data moved
on-device vs through host tiers, and where the quota contract escalated
— plus the ``mesh.gang`` occupancy events (gang waits are the
cross-query serialization cost of "one slot = the mesh").

    AURON_CONF_TRACE_ENABLED=1 AURON_CONF_TRACE_DIR=/tmp/tr <run>
    python tools/mesh_report.py /tmp/tr
    python tools/mesh_report.py --compare /tmp/base /tmp/candidate

``--compare`` diffs two trace dirs (e.g. mesh off vs on): per-route
exchange counts and bytes side by side, PLUS the fault domain's
recovery ledger (``exchange.demote`` / ``mesh.straggler`` /
``mesh.quarantine`` events) — a candidate round that silently started
demoting rounds to host or breeding stragglers is a recovery-path
regression this diff makes visible, not a throughput mystery.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_events(trace_dir: str) -> list[dict]:
    """All spans of every trace_*.jsonl in ``trace_dir`` (dict form)."""
    out = []
    paths = sorted(glob.glob(os.path.join(trace_dir, "trace_*.jsonl")))
    if not paths:
        raise SystemExit(
            f"no trace_*.jsonl in {trace_dir!r} — run with "
            "AURON_CONF_TRACE_ENABLED=1 and AURON_CONF_TRACE_DIR set")
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out


def route_events(events: list[dict]) -> list[dict]:
    return [e for e in events if e.get("name") == "exchange.route"]


def gang_events(events: list[dict]) -> list[dict]:
    return [e for e in events if e.get("name") == "mesh.gang"]


def demote_events(events: list[dict]) -> list[dict]:
    return [e for e in events if e.get("name") == "exchange.demote"]


def straggler_events(events: list[dict]) -> list[dict]:
    return [e for e in events if e.get("name") == "mesh.straggler"]


def quarantine_events(events: list[dict]) -> list[dict]:
    return [e for e in events if e.get("name") == "mesh.quarantine"]


def summarize(events: list[dict]) -> dict:
    """Aggregate per-route totals for one trace dir (the --compare
    unit): exchange counts, bytes, rounds, escalations."""
    routes = route_events(events)
    agg: dict = {}
    for e in routes:
        a = e.get("attrs", {})
        r = a.get("route", "?")
        ent = agg.setdefault(r, {"exchanges": 0, "bytes": 0, "rows": 0,
                                 "rounds": 0, "escalations": 0,
                                 "combined": 0, "combine_rows_in": 0,
                                 "combine_rows_out": 0})
        ent["exchanges"] += 1
        ent["bytes"] += int(a.get("bytes", 0))
        ent["rows"] += int(a.get("rows", 0))
        ent["rounds"] += int(a.get("rounds", 0))
        ent["escalations"] += int(a.get("escalations", 0))
        # Fusion 2.0 map-side combine telemetry (attrs present only on
        # folded exchanges): how many rows the fold saw vs shipped — the
        # route mix distinguishes a demoted combined run (combine attrs
        # on a 'demoted' route) from a combine-off run (no attrs at all)
        if a.get("combine_mode"):
            ent["combined"] += 1
            ent["combine_rows_in"] += int(a.get("combine_rows_in", 0))
            ent["combine_rows_out"] += int(a.get("combine_rows_out", 0))
    gangs = gang_events(events)
    demotes = demote_events(events)
    dem_by_reason: dict = {}
    for e in demotes:
        r = e.get("attrs", {}).get("reason", "?")
        dem_by_reason[r] = dem_by_reason.get(r, 0) + 1
    return {
        "by_route": agg,
        "gang": {
            "acquisitions": len(gangs),
            "contended": sum(1 for g in gangs
                             if g.get("attrs", {}).get("contended")),
            "wait_ms": round(sum(float(g.get("attrs", {})
                                       .get("wait_ms", 0.0))
                                 for g in gangs), 3),
        },
        # the fault domain's recovery ledger: a recovery-path
        # regression (new demotions, new stragglers) must be visible
        # between rounds via --compare
        "demotions": dem_by_reason,
        "stragglers": len(straggler_events(events)),
        "quarantines": len(quarantine_events(events)),
    }


def print_table(events: list[dict]) -> None:
    routes = route_events(events)
    if not routes:
        print("no exchange.route events recorded "
              "(is auron.mesh category traced?)")
    else:
        hdr = (f"{'route':<14} {'reason':<28} {'parts':>5} {'maps':>5} "
               f"{'rounds':>6} {'esc':>4} {'rows':>10} {'bytes':>12} "
               f"{'skew':>6} {'combine':>12}")
        print(hdr)
        print("-" * len(hdr))
        for e in routes:
            a = e.get("attrs", {})
            comb = ""
            if a.get("combine_mode"):
                comb = f"{a['combine_mode'][:7]}:" \
                       f"{a.get('combine_ratio', '')}"
            print(f"{a.get('route', '?'):<14} "
                  f"{str(a.get('reason', ''))[:28]:<28} "
                  f"{a.get('partitions', ''):>5} {a.get('maps', ''):>5} "
                  f"{a.get('rounds', ''):>6} {a.get('escalations', ''):>4} "
                  f"{a.get('rows', ''):>10} {a.get('bytes', ''):>12} "
                  f"{a.get('skew', ''):>6} {comb:>12}")
    s = summarize(events)
    print()
    for r, ent in sorted(s["by_route"].items()):
        line = (f"{r}: {ent['exchanges']} exchange(s), "
                f"{ent['bytes']:,} bytes, {ent['rows']:,} rows, "
                f"{ent['rounds']} round(s), "
                f"{ent['escalations']} quota escalation(s)")
        if ent["combined"]:
            ratio = (ent["combine_rows_out"]
                     / max(1, ent["combine_rows_in"]))
            line += (f", {ent['combined']} combined fold(s) "
                     f"({ent['combine_rows_in']:,} -> "
                     f"{ent['combine_rows_out']:,} rows, "
                     f"ratio {ratio:.3f})")
        print(line)
    g = s["gang"]
    if g["acquisitions"]:
        print(f"mesh gang: {g['acquisitions']} acquisition(s), "
              f"{g['contended']} contended, "
              f"total wait {g['wait_ms']}ms")
    if s["demotions"] or s["stragglers"] or s["quarantines"]:
        dem = ", ".join(f"{k}x{v}"
                        for k, v in sorted(s["demotions"].items())) or "-"
        print(f"mesh recovery: demotions {dem}; "
              f"{s['stragglers']} straggler round(s); "
              f"{s['quarantines']} quarantine(s)")
        for e in demote_events(events):
            a = e.get("attrs", {})
            print(f"  demote [{a.get('reason', '?')}] "
                  f"after {a.get('rounds_completed', '?')} mesh "
                  f"round(s), usable={a.get('usable', '?')} "
                  f"quarantined={a.get('quarantined', [])}")


def print_compare(base_dir: str, cand_dir: str) -> None:
    base = summarize(load_events(base_dir))
    cand = summarize(load_events(cand_dir))
    routes = sorted(set(base["by_route"]) | set(cand["by_route"]))
    print(f"{'route':<14} {'base ex':>8} {'cand ex':>8} "
          f"{'base bytes':>14} {'cand bytes':>14} "
          f"{'base comb':>10} {'cand comb':>10}")
    for r in routes:
        b = base["by_route"].get(r, {})
        c = cand["by_route"].get(r, {})
        print(f"{r:<14} {b.get('exchanges', 0):>8} "
              f"{c.get('exchanges', 0):>8} "
              f"{b.get('bytes', 0):>14,} {c.get('bytes', 0):>14,} "
              f"{b.get('combined', 0):>10} {c.get('combined', 0):>10}")
    # combine-fold delta: shipped-row reduction side by side — a
    # candidate whose folds vanished (combine silently off) shows up as
    # combined exchanges dropping to zero, not as a bytes mystery
    bci, bco = (sum(e.get("combine_rows_in", 0)
                    for e in base["by_route"].values()),
                sum(e.get("combine_rows_out", 0)
                    for e in base["by_route"].values()))
    cci, cco = (sum(e.get("combine_rows_in", 0)
                    for e in cand["by_route"].values()),
                sum(e.get("combine_rows_out", 0)
                    for e in cand["by_route"].values()))
    if bci or cci:
        print(f"{'combine rows':<14} base {bci:,} -> {bco:,} "
              f"(ratio {bco / max(1, bci):.3f}); "
              f"cand {cci:,} -> {cco:,} "
              f"(ratio {cco / max(1, cci):.3f})")
    print(f"gang waits: base {base['gang']['wait_ms']}ms "
          f"({base['gang']['acquisitions']} acq) -> cand "
          f"{cand['gang']['wait_ms']}ms "
          f"({cand['gang']['acquisitions']} acq)")
    # recovery-path delta: demotions/stragglers appearing only on the
    # candidate side are the regression --compare exists to catch
    bd = sum(base["demotions"].values())
    cd = sum(cand["demotions"].values())
    print(f"{'demotions':<14} {bd:>8} {cd:>8}   "
          f"base {base['demotions'] or '-'} -> cand "
          f"{cand['demotions'] or '-'}")
    print(f"{'stragglers':<14} {base['stragglers']:>8} "
          f"{cand['stragglers']:>8}")
    print(f"{'quarantines':<14} {base['quarantines']:>8} "
          f"{cand['quarantines']:>8}")
    if cd > bd or cand["stragglers"] > base["stragglers"]:
        print("WARNING: candidate run demoted/straggled more than base "
              "— a mesh recovery-path regression, not a perf win")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace_dir", nargs="?",
                    help="directory of trace_*.jsonl files")
    ap.add_argument("--compare", nargs=2, metavar=("BASE", "CANDIDATE"),
                    help="diff two trace dirs instead")
    ap.add_argument("--json", action="store_true",
                    help="print the aggregate as one JSON line too")
    args = ap.parse_args(argv)
    if args.compare:
        print_compare(*args.compare)
        return 0
    if not args.trace_dir:
        ap.error("trace_dir (or --compare) is required")
    events = load_events(args.trace_dir)
    print_table(events)
    if args.json:
        print(json.dumps(summarize(events)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
