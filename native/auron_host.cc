// auron-tpu host-side native kernels.
//
// The reference's native layer is a Rust engine (loser-tree merge:
// datafusion-ext-commons/src/algorithm/loser_tree.rs, radix sort:
// algorithm/rdx_sort.rs). In this framework the *device* compute path is
// XLA; the native layer accelerates the host runtime around it — the spill
// merge and host-side orderings that would otherwise run as numpy passes.
// C API, bound from Python with ctypes (no pybind11 in the image).
//
// Build: make -C native   (produces libauron_host.so)

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// Lexicographic comparison of two rows of w big-endian-significant u64
// words (word 0 most significant).
inline int cmp_rows(const uint64_t* a, const uint64_t* b, int64_t w) {
  for (int64_t i = 0; i < w; ++i) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}

}  // namespace

extern "C" {

// Stable LSD radix sort of n rows of w u64 words each (row-major `words`),
// most-significant word first. Writes the sorting permutation into
// perm_out[n]. 16-bit digits → 4 passes per word.
void at_lex_sort_words(const uint64_t* words, int64_t n, int64_t w,
                       int32_t* perm_out) {
  std::vector<int32_t> perm(n), tmp(n);
  for (int64_t i = 0; i < n; ++i) perm[i] = static_cast<int32_t>(i);

  constexpr int kRadixBits = 16;
  constexpr int kBuckets = 1 << kRadixBits;
  std::vector<int64_t> counts(kBuckets);

  // least-significant word to most-significant; within a word, low digit
  // to high digit — classic stable LSD
  for (int64_t word = w - 1; word >= 0; --word) {
    for (int shift = 0; shift < 64; shift += kRadixBits) {
      std::fill(counts.begin(), counts.end(), 0);
      for (int64_t i = 0; i < n; ++i) {
        uint64_t v = words[static_cast<int64_t>(perm[i]) * w + word];
        ++counts[(v >> shift) & (kBuckets - 1)];
      }
      int64_t total = 0;
      for (int b = 0; b < kBuckets; ++b) {
        int64_t c = counts[b];
        counts[b] = total;
        total += c;
      }
      for (int64_t i = 0; i < n; ++i) {
        uint64_t v = words[static_cast<int64_t>(perm[i]) * w + word];
        tmp[counts[(v >> shift) & (kBuckets - 1)]++] = perm[i];
      }
      perm.swap(tmp);
    }
  }
  std::memcpy(perm_out, perm.data(), n * sizeof(int32_t));
}

// Loser-tree k-way merge (reference: loser_tree.rs). Inputs: k sorted runs
// concatenated row-major in `words` [total, w]; run r spans rows
// [run_offsets[r], run_offsets[r+1]). Emits the global merge order as row
// indices into `words` (out_order[total]). Ties resolve by run index, so
// the merge is stable across runs.
void at_merge_runs(const uint64_t* words, const int64_t* run_offsets,
                   int64_t k, int64_t w, int32_t* out_order) {
  std::vector<int64_t> cursor(k);
  for (int64_t r = 0; r < k; ++r) cursor[r] = run_offsets[r];

  // tournament tree of run indices; size = next power of two
  int64_t size = 1;
  while (size < k) size <<= 1;
  const int64_t kExhausted = -1;

  auto run_key = [&](int64_t r) -> const uint64_t* {
    return words + cursor[r] * w;
  };
  auto less = [&](int64_t a, int64_t b) -> bool {
    // a, b are run ids or kExhausted; exhausted loses to everything
    if (a == kExhausted) return false;
    if (b == kExhausted) return true;
    int c = cmp_rows(run_key(a), run_key(b), w);
    return c < 0 || (c == 0 && a < b);
  };

  // internal nodes hold losers; tree[0] holds the winner
  std::vector<int64_t> tree(2 * size, kExhausted);
  // leaves
  for (int64_t r = 0; r < k; ++r)
    tree[size + r] = (cursor[r] < run_offsets[r + 1]) ? r : kExhausted;
  for (int64_t r = k; r < size; ++r) tree[size + r] = kExhausted;
  // initial playoff
  for (int64_t node = size - 1; node >= 1; --node) {
    int64_t a = tree[2 * node], b = tree[2 * node + 1];
    if (less(a, b)) {
      tree[node] = a;
    } else {
      tree[node] = b;
    }
  }
  // rebuild: store losers on path, winner at root. Simplest correct form:
  // recompute path from the winner's leaf after each pop.
  int64_t total = run_offsets[k];
  for (int64_t out = 0; out < total; ++out) {
    int64_t winner = tree[1];
    out_order[out] = static_cast<int32_t>(cursor[winner]);
    ++cursor[winner];
    int64_t leaf = size + winner;
    tree[leaf] =
        (cursor[winner] < run_offsets[winner + 1]) ? winner : kExhausted;
    for (int64_t node = leaf / 2; node >= 1; node /= 2) {
      int64_t a = tree[2 * node], b = tree[2 * node + 1];
      tree[node] = less(a, b) ? a : b;
    }
  }
}

// Gather rows: out[i] = src[order[i]] for row-major [n, row_bytes] byte
// matrices — the payload reorder companion to the merges above.
void at_take_rows(const uint8_t* src, const int32_t* order, int64_t n,
                  int64_t row_bytes, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(out + i * row_bytes,
                src + static_cast<int64_t>(order[i]) * row_bytes, row_bytes);
  }
}

int64_t at_version() { return 1; }

}  // extern "C"
