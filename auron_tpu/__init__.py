"""auron-tpu: a TPU-native query-execution engine.

A ground-up re-design of the capability set of Apache Auron (the Spark/Flink
native-execution accelerator, see /root/reference) for TPU hardware:

- host engine physical plans arrive as a protobuf IR (``auron_tpu.ir``),
- a physical planner lowers the IR to a tree of columnar operators
  (``auron_tpu.ops``) whose hot loops are jax.jit / pallas kernels running on
  Arrow-derived device batches (``auron_tpu.columnar``),
- a memory manager tiers batches between TPU HBM and host DRAM with spilling
  (``auron_tpu.memmgr``),
- stage exchange (hash / round-robin / range / single partitioning and
  broadcast) runs as ICI all-to-all over a ``jax.sharding.Mesh``
  (``auron_tpu.parallel``).

Unlike the reference (Rust + DataFusion on CPU, reference:
native-engine/auron/src/rt.rs), the compute path here is XLA: batches are
fixed-capacity, validity-masked device arrays so every kernel traces to a
static-shape HLO module that XLA can tile onto the MXU/VPU.
"""

import jax

# SQL semantics need real 64-bit integers (BIGINT sums, xxhash64, decimal64).
# TPU emulates i64 with i32 pairs; kernels that are perf-critical choose
# narrower dtypes explicitly.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from auron_tpu.columnar.batch import DeviceBatch, PrimitiveColumn, StringColumn  # noqa: E402,F401
from auron_tpu.columnar.schema import DataType, Field, Schema  # noqa: E402,F401
