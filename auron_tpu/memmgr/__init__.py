"""Memory manager: HBM budget arbitration + host-DRAM/disk spill tiering.

The TPU re-design of the reference's memmgr (reference:
native-engine/auron-memmgr/src/lib.rs:38-423, spill.rs:40-275): operators
register as MemConsumers against one MemManager arbitrating a device (HBM)
budget; when an update pushes usage past the consumer's fair share, the
manager tells it to spill. Spills tier through host DRAM first (the
HBM↔DRAM tiering of the north star — on TPU, host memory plays the role the
JVM on-heap spill plays in the reference) and overflow to compressed disk
files.
"""

from auron_tpu.memmgr.manager import MemConsumer, MemManager  # noqa: F401
from auron_tpu.memmgr.spill import Spill, SpillManager  # noqa: F401
