"""Host-side k-way merge of sorted spill runs.

The reference merges runs with a loser tree over row cursors (reference:
datafusion-ext-commons/src/algorithm/loser_tree.rs, sort_exec.rs k-way
merge). A per-row tournament is a host-bound scalar loop — poison on this
architecture — so the merge here is *blockwise and vectorized*: each run's
frames carry the device-computed order words (uint64 [rows, W], produced by
the same kernel that sorted the run, so host comparisons agree with device
sort order bit-for-bit). Per round:

  1. bound = min over runs of (last key words of the run's current block);
  2. every row ≤ bound anywhere is safe to emit — later rows of run r are
     ≥ r's block-last ≥ bound;
  3. those rows are merged with one np.lexsort and emitted as one batch.

The run whose block defines the bound always drains fully, so each round
retires ≥ one block. Ties at the bound may interleave across runs: the
merge is not stable across runs for equal keys (neither is the output
contract — SQL sort is non-stable).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from auron_tpu.columnar.serde import (HostBatch, HostDecimal128, HostList,
                                      HostPrimitive, HostString,
                                      deserialize_host_batch)

ORDER_WORDS_EXTRA = "order_words"
#: per-key (word count, pad word) matrix — lets runs whose string keys
#: landed in different width buckets merge correctly
WORD_LAYOUT_EXTRA = "word_layout"


def _expand_words(words: np.ndarray, layout: np.ndarray,
                  target_counts: list[int]) -> np.ndarray:
    """Align one run's word matrix to the merge-wide per-key word counts by
    inserting each key's pad word for its missing trailing words (exactly
    what the device kernel would have emitted at the wider bucket)."""
    n = words.shape[0]
    parts = []
    pos = 0
    for (cnt, pad), tgt in zip(layout.tolist(), target_counts):
        cnt = int(cnt)
        parts.append(words[:, pos:pos + cnt])
        if tgt > cnt:
            parts.append(np.full((n, tgt - cnt), np.uint64(pad), np.uint64))
        pos += cnt
    return np.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


class _RunCursor:
    """One sorted run: frame iterator + current decoded block."""

    def __init__(self, frames: Iterator[bytes]):
        self._frames = iter(frames)
        self.batch: Optional[HostBatch] = None
        self.words: Optional[np.ndarray] = None
        self.layout: Optional[np.ndarray] = None
        self.target_counts: Optional[list[int]] = None
        self.pos = 0
        self._advance()

    def _advance(self) -> None:
        for frame in self._frames:
            batch, extras = deserialize_host_batch(frame)
            if batch.num_rows == 0:
                continue
            self.batch = batch
            self.layout = extras[WORD_LAYOUT_EXTRA]
            words = extras[ORDER_WORDS_EXTRA]
            if self.target_counts is not None:
                words = _expand_words(words, self.layout, self.target_counts)
            self.words = words
            self.pos = 0
            return
        self.batch = None
        self.words = None

    def align(self, target_counts: list[int]) -> None:
        self.target_counts = target_counts
        if self.words is not None:
            self.words = _expand_words(self.words, self.layout, target_counts)

    @property
    def exhausted(self) -> bool:
        return self.batch is None

    def remaining_words(self) -> np.ndarray:
        return self.words[self.pos:]

    def last_words(self) -> np.ndarray:
        return self.words[-1]

    def take(self, n: int) -> tuple[HostBatch, np.ndarray]:
        """Consume n rows from the front of the current block."""
        from auron_tpu.columnar.serde import slice_host_batch
        lo, hi = self.pos, self.pos + n
        out = slice_host_batch(self.batch, lo, hi)
        words = self.words[lo:hi]
        self.pos = hi
        if self.pos >= self.batch.num_rows:
            self._advance()
        return out, words


def _lex_leq(words: np.ndarray, bound: np.ndarray) -> np.ndarray:
    """rows ≤ bound, lexicographic over word columns (vectorized)."""
    n, w = words.shape
    le = np.zeros(n, bool)
    eq = np.ones(n, bool)
    for i in range(w):
        le |= eq & (words[:, i] < bound[i])
        eq &= words[:, i] == bound[i]
    return le | eq


def _lex_min(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    for x, y in zip(a, b):
        if x < y:
            return a
        if x > y:
            return b
    return a


def _concat_host(parts: list[HostBatch]) -> HostBatch:
    ncols = len(parts[0].columns)
    cols = []
    for i in range(ncols):
        cs = [p.columns[i] for p in parts]
        if isinstance(cs[0], HostString):
            width = max(c.chars.shape[1] for c in cs)
            chars = np.concatenate([
                np.pad(c.chars, ((0, 0), (0, width - c.chars.shape[1])))
                for c in cs])
            cols.append(HostString(chars,
                                   np.concatenate([c.lens for c in cs]),
                                   np.concatenate([c.validity for c in cs])))
        elif isinstance(cs[0], HostList):
            m = max(c.values.shape[1] for c in cs)
            values = np.concatenate([
                np.pad(c.values, ((0, 0), (0, m - c.values.shape[1])))
                for c in cs])
            ev = np.concatenate([
                np.pad(c.elem_valid, ((0, 0), (0, m - c.elem_valid.shape[1])))
                for c in cs])
            cols.append(HostList(values, ev,
                                 np.concatenate([c.lens for c in cs]),
                                 np.concatenate([c.validity for c in cs])))
        elif isinstance(cs[0], HostDecimal128):
            cols.append(HostDecimal128(
                np.concatenate([c.hi for c in cs]),
                np.concatenate([c.lo for c in cs]),
                np.concatenate([c.validity for c in cs])))
        else:
            cols.append(HostPrimitive(
                np.concatenate([c.data for c in cs]),
                np.concatenate([c.validity for c in cs])))
    return HostBatch(cols, sum(p.num_rows for p in parts))


def _reorder_host(batch: HostBatch, perm: np.ndarray) -> HostBatch:
    from auron_tpu.native import take_rows
    cols = []
    for c in batch.columns:
        if isinstance(c, HostString):
            # chars matrices are the wide payload — native memcpy gather
            cols.append(HostString(take_rows(c.chars, perm), c.lens[perm],
                                   c.validity[perm]))
        elif isinstance(c, HostList):
            cols.append(HostList(take_rows(c.values, perm),
                                 take_rows(c.elem_valid, perm),
                                 c.lens[perm], c.validity[perm]))
        elif isinstance(c, HostDecimal128):
            cols.append(HostDecimal128(c.hi[perm], c.lo[perm],
                                       c.validity[perm]))
        else:
            cols.append(HostPrimitive(c.data[perm], c.validity[perm]))
    return HostBatch(cols, len(perm))


def merge_sorted_runs(run_frames: list[Iterator[bytes]]) -> Iterator[HostBatch]:
    """Merge k sorted runs (frames with ORDER_WORDS_EXTRA) into a stream of
    sorted HostBatches (one per merge round)."""
    cursors = [_RunCursor(f) for f in run_frames]
    cursors = [c for c in cursors if not c.exhausted]
    if cursors:
        n_keys = cursors[0].layout.shape[0]
        target_counts = [max(int(c.layout[k, 0]) for c in cursors)
                         for k in range(n_keys)]
        for c in cursors:
            c.align(target_counts)

    while cursors:
        if len(cursors) == 1:
            c = cursors[0]
            n = c.batch.num_rows - c.pos
            batch, _ = c.take(n)
            yield batch
            cursors = [c for c in cursors if not c.exhausted]
            continue

        bound = cursors[0].last_words()
        for c in cursors[1:]:
            bound = _lex_min(bound, c.last_words())

        parts: list[tuple[HostBatch, np.ndarray]] = []
        for c in cursors:
            rw = c.remaining_words()
            le = _lex_leq(rw, bound)
            # rows are sorted, so ≤-bound rows form a prefix
            n = int(np.searchsorted(~le, True)) if le.size else 0
            if n:
                parts.append(c.take(n))

        merged = _concat_host([p[0] for p in parts])
        words = np.concatenate([p[1] for p in parts])
        # each part is itself sorted → loser-tree merge of the sub-runs
        # (native C++ when available; numpy lexsort fallback inside)
        from auron_tpu import native
        offsets = np.zeros(len(parts) + 1, np.int64)
        np.cumsum([p[0].num_rows for p in parts], out=offsets[1:])
        perm = native.merge_runs(words, offsets)
        yield _reorder_host(merged, perm)
        cursors = [c for c in cursors if not c.exhausted]
