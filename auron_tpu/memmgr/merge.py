"""Host-side k-way merge of sorted spill runs.

The reference merges runs with a loser tree over row cursors (reference:
datafusion-ext-commons/src/algorithm/loser_tree.rs, sort_exec.rs k-way
merge). A per-row tournament is a host-bound scalar loop — poison on this
architecture — so the merge here is *blockwise and vectorized*: each run's
frames carry the device-computed order words (uint64 [rows, W], produced by
the same kernel that sorted the run, so host comparisons agree with device
sort order bit-for-bit). Per round:

  1. bound = min over runs of (last key words of the run's current block);
  2. every row ≤ bound anywhere is safe to emit — later rows of run r are
     ≥ r's block-last ≥ bound;
  3. those rows are merged with one np.lexsort and emitted as one batch.

The run whose block defines the bound always drains fully, so each round
retires ≥ one block. Ties at the bound may interleave across runs: the
merge is not stable across runs for equal keys (neither is the output
contract — SQL sort is non-stable).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from auron_tpu.columnar.serde import (HostBatch, HostPrimitive, HostString,
                                      deserialize_host_batch)

ORDER_WORDS_EXTRA = "order_words"


class _RunCursor:
    """One sorted run: frame iterator + current decoded block."""

    def __init__(self, frames: Iterator[bytes]):
        self._frames = iter(frames)
        self.batch: Optional[HostBatch] = None
        self.words: Optional[np.ndarray] = None
        self.pos = 0
        self._advance()

    def _advance(self) -> None:
        for frame in self._frames:
            batch, extras = deserialize_host_batch(frame)
            if batch.num_rows == 0:
                continue
            self.batch = batch
            self.words = extras[ORDER_WORDS_EXTRA]
            self.pos = 0
            return
        self.batch = None
        self.words = None

    @property
    def exhausted(self) -> bool:
        return self.batch is None

    def remaining_words(self) -> np.ndarray:
        return self.words[self.pos:]

    def last_words(self) -> np.ndarray:
        return self.words[-1]

    def take(self, n: int) -> tuple[HostBatch, np.ndarray]:
        """Consume n rows from the front of the current block."""
        from auron_tpu.columnar.serde import slice_host_batch
        lo, hi = self.pos, self.pos + n
        out = slice_host_batch(self.batch, lo, hi)
        words = self.words[lo:hi]
        self.pos = hi
        if self.pos >= self.batch.num_rows:
            self._advance()
        return out, words


def _lex_leq(words: np.ndarray, bound: np.ndarray) -> np.ndarray:
    """rows ≤ bound, lexicographic over word columns (vectorized)."""
    n, w = words.shape
    le = np.zeros(n, bool)
    eq = np.ones(n, bool)
    for i in range(w):
        le |= eq & (words[:, i] < bound[i])
        eq &= words[:, i] == bound[i]
    return le | eq


def _lex_min(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    for x, y in zip(a, b):
        if x < y:
            return a
        if x > y:
            return b
    return a


def _concat_host(parts: list[HostBatch]) -> HostBatch:
    ncols = len(parts[0].columns)
    cols = []
    for i in range(ncols):
        cs = [p.columns[i] for p in parts]
        if isinstance(cs[0], HostString):
            width = max(c.chars.shape[1] for c in cs)
            chars = np.concatenate([
                np.pad(c.chars, ((0, 0), (0, width - c.chars.shape[1])))
                for c in cs])
            cols.append(HostString(chars,
                                   np.concatenate([c.lens for c in cs]),
                                   np.concatenate([c.validity for c in cs])))
        else:
            cols.append(HostPrimitive(
                np.concatenate([c.data for c in cs]),
                np.concatenate([c.validity for c in cs])))
    return HostBatch(cols, sum(p.num_rows for p in parts))


def _reorder_host(batch: HostBatch, perm: np.ndarray) -> HostBatch:
    cols = []
    for c in batch.columns:
        if isinstance(c, HostString):
            cols.append(HostString(c.chars[perm], c.lens[perm],
                                   c.validity[perm]))
        else:
            cols.append(HostPrimitive(c.data[perm], c.validity[perm]))
    return HostBatch(cols, len(perm))


def merge_sorted_runs(run_frames: list[Iterator[bytes]]) -> Iterator[HostBatch]:
    """Merge k sorted runs (frames with ORDER_WORDS_EXTRA) into a stream of
    sorted HostBatches (one per merge round)."""
    cursors = [_RunCursor(f) for f in run_frames]
    cursors = [c for c in cursors if not c.exhausted]

    while cursors:
        if len(cursors) == 1:
            c = cursors[0]
            n = c.batch.num_rows - c.pos
            batch, _ = c.take(n)
            yield batch
            cursors = [c for c in cursors if not c.exhausted]
            continue

        bound = cursors[0].last_words()
        for c in cursors[1:]:
            bound = _lex_min(bound, c.last_words())

        parts: list[tuple[HostBatch, np.ndarray]] = []
        for c in cursors:
            rw = c.remaining_words()
            le = _lex_leq(rw, bound)
            # rows are sorted, so ≤-bound rows form a prefix
            n = int(np.searchsorted(~le, True)) if le.size else 0
            if n:
                parts.append(c.take(n))

        merged = _concat_host([p[0] for p in parts])
        words = np.concatenate([p[1] for p in parts])
        # np.lexsort: last key is primary → feed most-significant last
        perm = np.lexsort(tuple(words[:, i]
                                for i in range(words.shape[1] - 1, -1, -1)))
        yield _reorder_host(merged, perm)
        cursors = [c for c in cursors if not c.exhausted]
