"""HBM budget arbitration.

Mirrors the reference's design (reference: auron-memmgr/src/lib.rs:303-423):
one manager per process, consumers update their usage after each growth
step, the manager answers Nothing or Spill based on fair share and a
global watermark. The reference's Wait arm (condvar, 10 s) exists because
many tasks share one pool concurrently; over-budget here resolves by
spilling the requester first when it holds at least its share (the
biggest consumer otherwise).

Concurrent-query fairness (the [serving] scheduler plane): every
consumer is tagged at registration with the query that created it (the
lifecycle plane's thread-local token), giving the manager a per-query
ledger. ``fair_share()`` divides the budget over LIVE QUERIES, not
consumers; the per-query quota (``auron.memmgr.query_quota_bytes``,
auto = budget / auron.sched.max_concurrent under concurrency) is
enforced against the requester's OWN ledger — and a quota breach spills
or sheds that query, never an innocent neighbor.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

logger = logging.getLogger("auron_tpu.memmgr")

#: don't bother spilling consumers below this (reference: MIN_TRIGGER_SIZE
#: 16MB, auron-memmgr/src/lib.rs:36)
MIN_TRIGGER_SIZE = 16 << 20

#: every live manager, weakly held — the process-wide consumer-leak
#: probe the tier-1 leak-audit fixture and the chaos battery read
import weakref as _weakref

_MANAGERS: "_weakref.WeakSet" = _weakref.WeakSet()


def live_consumer_count() -> int:
    """Registered consumers across every live MemManager (after a gc, a
    finished query must leave this at its pre-query value — consumers
    are weakly held, so anything still counted is either genuinely live
    or pinned by a leak)."""
    total = 0
    for m in list(_MANAGERS):
        with m._lock:
            total += len(m._used)
    return total


def aggregate_status() -> list[dict]:
    """Status snapshots of every live manager in the process — the ops
    plane's ``/healthz`` memmgr section and the bundle's memmgr.json
    (a scrape has no Session handle, so the weak registry is the
    discovery surface). Empty-ledger managers (no consumers, no spill
    history) are skipped: long-lived processes accumulate idle managers
    from finished tests/sessions and the operator surface should show
    pressure, not archaeology."""
    out = []
    for m in list(_MANAGERS):
        try:
            st = m.status()
        except Exception:   # pragma: no cover - status best-effort
            continue
        if st["num_consumers"] or st["num_spills"] or st["used"]:
            out.append(st)
    return out


class MemConsumer:
    """Spillable participant. Operators subclass / duck-type this."""

    #: display name for the status dump
    consumer_name: str = "consumer"

    #: may ``spill()`` be invoked from a thread OTHER than the one that
    #: registered (drives) this consumer? Under the concurrent runtime
    #: pressure can originate on any thread — a neighbor query's
    #: driver, this query's own prefetch worker — and pick any consumer
    #: as victim, but only consumers with internal locking
    #: (BufferedSpillConsumer's claim-under-lock protocol) survive a
    #: foreign-thread spill; the rest are spilled only from their OWN
    #: driving thread (victim pools filter on thread identity — the
    #: cross-query safety audit's finding)
    spill_thread_safe: bool = False

    def mem_used(self) -> int:
        raise NotImplementedError

    def spill(self) -> int:
        """Release device memory; returns bytes freed."""
        raise NotImplementedError

    def shrink(self) -> int:
        """OPTIONAL degradation hook (pressure ladder rung 1): release
        PART of the held memory — cheaper than a full spill — returning
        bytes freed. The default declines (0); consumers that buffer
        batch lists override (memmgr/consumer.BufferedSpillConsumer
        sheds its oldest half)."""
        return 0


class MemManager:
    def __init__(self, total_bytes: Optional[int] = None,
                 min_trigger: int = MIN_TRIGGER_SIZE,
                 spill_manager: Optional["object"] = None,
                 config=None):
        if total_bytes is None:
            total_bytes = self.default_budget()
        self.total = total_bytes
        self.min_trigger = min_trigger
        self.spill_manager = spill_manager
        #: knob source for the auto per-query quota divisor
        #: (auron.sched.max_concurrent): the owning Session binds its
        #: own config here so the quota divisor and the scheduler's
        #: admission clamp cannot desynchronize under per-Session
        #: overrides; None = process config
        self.config = config
        #: (config epoch, quota knob, max_concurrent) memo — per
        #: manager because the knob source is
        self._quota_cache: tuple = (-1, 0, 1)
        self._lock = threading.Lock()
        # weak keys: a consumer whose operator was dropped without an
        # explicit unregister (e.g. a memoized exchange buffer released
        # with its query) must not pin itself — or its accounted bytes —
        # in the manager for the process lifetime
        import weakref
        self._used: "weakref.WeakKeyDictionary[MemConsumer, int]" = \
            weakref.WeakKeyDictionary()
        #: per-query ledger: consumer → owning query id (tagged at
        #: registration from the lifecycle plane's thread-local token;
        #: "" is the anonymous bucket of direct collect() calls). The
        #: concurrent scheduler's fairness — per-query fair_share, the
        #: quota breach check, the over-quota-first force-spill — reads
        #: usage grouped by this tag.
        self._query_of: "weakref.WeakKeyDictionary[MemConsumer, str]" = \
            weakref.WeakKeyDictionary()
        #: consumer → registering (driving) thread id: the safety key
        #: for victim selection — spill() on a non-thread-safe consumer
        #: is only sound from the thread that drives it
        self._thread_of: "weakref.WeakKeyDictionary[MemConsumer, int]" = \
            weakref.WeakKeyDictionary()
        self.num_spills = 0
        self.spilled_bytes = 0
        #: degradation-ladder state: shrink rungs taken (drives the
        #: advised batch-rows hint scans consult) + per-rung counters
        self._shrink_level = 0
        #: consecutive comfortable grants (under half budget) since the
        #: last pressure event — the shrink-level decay hysteresis
        self._comfort_grants = 0
        self.pressure_counts = {"shrink": 0, "cache_evict": 0,
                                "force_spill": 0, "deny": 0, "shed": 0}
        _MANAGERS.add(self)

    @staticmethod
    def default_budget() -> int:
        """auron.memory.fraction of the device's HBM (the reference's
        spark.auron.memoryFraction × executor memory); falls back to a
        conservative 8 GB figure when the backend doesn't report a limit
        (e.g. the CPU test mesh)."""
        from auron_tpu import config as cfg
        fraction = cfg.get_config().get(cfg.MEMORY_FRACTION)
        limit = 8 << 30
        try:
            import jax
            stats = jax.devices()[0].memory_stats() or {}
            limit = int(stats.get("bytes_limit", limit)) or limit
        except Exception:
            pass
        return int(limit * fraction)

    # -- registration -------------------------------------------------------

    def register_consumer(self, c: MemConsumer) -> None:
        from auron_tpu.runtime import lifecycle
        qid = lifecycle.current_query_id()
        with self._lock:
            self._used.setdefault(c, 0)
            self._query_of[c] = qid
            self._thread_of[c] = threading.get_ident()

    def unregister_consumer(self, c: MemConsumer) -> None:
        with self._lock:
            self._used.pop(c, None)
            self._query_of.pop(c, None)
            self._thread_of.pop(c, None)

    def _spill_eligible_locked(self, v: MemConsumer) -> bool:
        """May the CURRENT thread invoke ``v.spill()``? Yes when it is
        v's own driving (registering) thread, or when v advertises an
        internally-locked spill (``spill_thread_safe``). Query tags do
        NOT make a victim safe — this query's prefetch worker racing
        this query's agg consumer is just as unsynchronized as a
        neighbor's driver. Caller holds ``self._lock``."""
        return (self._thread_of.get(v) == threading.get_ident()
                or getattr(v, "spill_thread_safe", False))

    # -- accounting ---------------------------------------------------------

    @property
    def used_total(self) -> int:
        with self._lock:
            return sum(self._used.values())

    def _usage_by_query_locked(self) -> dict:
        """{query tag: accounted bytes} over registered consumers — the
        ONE definition every per-query view (live count, quota check,
        force-spill pool, status) derives from; "" is the anonymous tag
        of direct collect() calls. Caller holds ``self._lock``."""
        out: dict[str, int] = {}
        for c, u in self._used.items():
            tag = self._query_of.get(c, "")
            out[tag] = out.get(tag, 0) + u
        return out

    def _live_queries_locked(self) -> set:
        """Distinct query tags across registered consumers; the
        anonymous "" tag counts as one query (direct collect() calls).
        Caller holds ``self._lock``."""
        return set(self._usage_by_query_locked())

    def fair_share(self) -> int:
        """Budget divided over LIVE QUERIES (not consumers): the
        concurrent runtime's fairness unit — a query spawning many
        consumers must not multiply its claim on the budget. With one
        query live (the solo path) this is the whole budget."""
        with self._lock:
            n = max(len(self._live_queries_locked()), 1)
        return self.total // n

    def query_used(self, qid: str) -> int:
        """Bytes accounted to ``qid``'s registered consumers."""
        with self._lock:
            return self._query_used_locked(qid)

    def query_quota(self) -> int:
        """Public face of the effective per-query quota (0 = none) —
        the ops plane's /queries table prints usage against it."""
        return self._query_quota()

    def _query_used_locked(self, qid: str) -> int:
        return self._usage_by_query_locked().get(qid, 0)

    def update_mem_used(self, c: MemConsumer, used: int) -> str:
        """Record ``c``'s usage; returns 'nothing' or 'spilled'. May invoke
        c.spill() (or the largest consumer's) synchronously.

        Every accounting decision is observable on the same planes as
        compute (the PR 6 forensics contract): the post-decision status
        mirrors onto registry gauges (obs/registry.observe_memmgr), an
        under-budget grant drops a ``memory`` trace event, each spill
        opens a ``memmgr.spill`` span around the victim's spill, and an
        over-budget exit with no spillable candidate left records a
        ``memmgr.deny`` — so memory pressure lines up with the span
        timeline instead of hiding in log archaeology."""
        from auron_tpu.obs import trace
        from auron_tpu.runtime import faults
        observe = self._registry_enabled()
        with self._lock:
            self._used[c] = used
            qid = self._query_of.get(c, "")
            # ONE per-query walk serves every grant-path read (total,
            # the requester's query usage, live-query count) — the hot
            # path stays a single O(consumers) pass under the lock the
            # accounting already holds
            by_query = self._usage_by_query_locked()
            total_used = sum(by_query.values())
            q_used = by_query.get(qid, 0)
            n_live = len(by_query)
            # grant-path telemetry snapshot under the SAME lock — no
            # second acquisition, and the consumer copy only happens
            # when the registry will see it
            status = self._status_locked() if observe else None

        # the memmgr.deny chaos site: pretend the budget is exhausted so
        # the degradation ladder gets deterministic traffic
        forced = faults.fires("memmgr.deny", "deny")
        quota = self._query_quota(live=n_live)
        over_quota = bool(quota) and q_used > quota
        if total_used <= self.total and not over_quota and not forced:
            if self._shrink_level:
                # decay the shrink advice once pressure has demonstrably
                # subsided (16 consecutive grants under HALF budget) —
                # one pressure episode must not pin 8x-smaller scan
                # batches for the manager's lifetime
                if total_used <= self.total // 2:
                    self._comfort_grants += 1
                    if self._comfort_grants >= 16:
                        self._shrink_level -= 1
                        self._comfort_grants = 0
                else:
                    self._comfort_grants = 0
            trace.event("memory", "memmgr.grant",
                        consumer=getattr(c, "consumer_name", "?"),
                        used=used, total_used=total_used,
                        budget=self.total)
            if status is not None:
                self._observe(status)
            return "nothing"

        # Spill until under budget or out of candidates (the reference loops
        # to its watermark the same way; one victim's spill may free less
        # than the overshoot — e.g. a consumer refusing mid-merge).
        spilled_any = False
        exhausted = forced    # an injected deny skips straight to the ladder
        tried: set = set()
        while not exhausted:
            with self._lock:
                by_query = self._usage_by_query_locked()
                total_used = sum(by_query.values())
                q_used = by_query.get(qid, 0)
                q_consumers = [v for v in self._used
                               if self._query_of.get(v, "") == qid]
                n_queries = max(len(by_query), 1)
                c_used = self._used.get(c, 0)
            over_budget = total_used > self.total
            over_quota = bool(quota) and q_used > quota
            if not over_budget and not over_quota:
                break
            # requester-first when it holds at least its slice of its
            # query's fair share (per-query share split over the query's
            # consumers — reduces to total // num_consumers when one
            # query is live, the legacy heuristic)
            share = self.total // n_queries // max(len(q_consumers), 1)
            if (c not in tried and c_used >= max(share, 1)
                    and c_used >= self.min_trigger):
                victim = c
            else:
                with self._lock:
                    # a quota-only breach spills the OVER-QUOTA query's
                    # own consumers — a neighbor must not pay for this
                    # query's appetite; a global over-budget considers
                    # every consumer. Either way the victim must be
                    # spill-safe FROM THIS THREAD (its own driving
                    # thread, or an internally locked spill)
                    pool = (q_consumers if over_quota and not over_budget
                            else list(self._used))
                    candidates = [(self._used.get(v, 0), v) for v in pool
                                  if self._spill_eligible_locked(v)
                                  and self._used.get(v, 0)
                                  >= self.min_trigger and v not in tried]
                if not candidates:
                    exhausted = True
                    break
                _, victim = max(candidates, key=lambda t: t[0])
            tried.add(victim)

            with trace.span("memory", "memmgr.spill",
                            victim=getattr(victim, "consumer_name", "?"),
                            total_used=total_used,
                            budget=self.total) as sp:
                freed = victim.spill()
                sp.set(freed=freed)
            with self._lock:
                self._used[victim] = max(self._used.get(victim, 0) - freed, 0)
                if freed:
                    self.num_spills += 1
                    self.spilled_bytes += freed
            if freed:
                spilled_any = True
                logger.info("memmgr: spilled %s (%d bytes freed, %d/%d used)",
                            victim.consumer_name, freed,
                            max(total_used - freed, 0), self.total)
        if exhausted:
            # the spill loop ran dry still over budget — the old hard
            # "deny": now a policy (auron.memmgr.pressure_policy)
            if self._pressure_ladder(c, qid, quota, forced=forced):
                spilled_any = True
        if self._registry_enabled():
            self._observe(self.status())
        return "spilled" if spilled_any else "nothing"

    # -- memory-pressure degradation ladder (PR 8) --------------------------

    def _query_quota(self, live: Optional[int] = None) -> int:
        """Effective per-query quota (0 = none). The knob values are
        cached against the config epoch — update_mem_used runs per
        batch-add, so the common path costs one int compare plus
        arithmetic; the live-query count rides in from the accounting
        lock the caller already held (``live``), so no second lock
        acquisition happens on the hot path. An explicit positive
        ``auron.memmgr.query_quota_bytes`` wins; the default 0 is AUTO
        — budget / auron.sched.max_concurrent once MORE than one query
        is live on this manager (the per-query ledger makes the cap
        genuinely per-query), no quota while a single query runs;
        negative disables entirely. Knobs resolve from ``self.config``
        (the owning Session's — bound at Session init so the quota
        divisor and the scheduler's admission clamp read the SAME
        max_concurrent) falling back to the process config."""
        from auron_tpu import config as cfg
        epoch, knob, maxc = self._quota_cache
        if epoch != cfg.config_epoch():
            try:
                conf = (self.config if self.config is not None
                        else cfg.get_config())
                knob = int(conf.get(cfg.MEMMGR_QUERY_QUOTA_BYTES))
                maxc = max(int(conf.get(cfg.SCHED_MAX_CONCURRENT)), 1)
            except Exception:   # pragma: no cover - config resolvable
                knob, maxc = 0, 1
            self._quota_cache = (cfg.config_epoch(), knob, maxc)
        if knob > 0:
            return knob
        if knob < 0:
            return 0
        if live is None:
            with self._lock:
                live = len(self._live_queries_locked())
        return self.total // maxc if live > 1 else 0

    def advised_batch_rows(self, base: int) -> int:
        """Pressure-adapted scan granularity: every shrink rung taken
        halves the advised batch rows (floor ``base/8``, never below
        256), so the scans feeding a struggling query deliver smaller
        device batches instead of ramming full-capacity ones into a
        budget that just denied. Scans consult this per batch
        (io/parquet.ParquetScanOp)."""
        lvl = self._shrink_level
        if lvl <= 0:
            return base
        return max(base >> min(lvl, 3), min(base, 256))

    def _count_rung(self, rung: str) -> None:
        self.pressure_counts[rung] = self.pressure_counts.get(rung, 0) + 1
        if self._registry_enabled():
            try:
                from auron_tpu.obs import registry as obs_registry
                obs_registry.get_registry().counter(
                    "auron_memmgr_pressure_total", rung=rung).inc()
            except Exception:   # pragma: no cover - telemetry best-effort
                pass

    def _pressure_ladder(self, c: MemConsumer, qid: str, quota: int,
                         forced: bool = False) -> bool:
        """Walk the degradation rungs after the spill loop ran dry still
        over budget: (1) **shrink** — bump the advised-batch-rows hint
        and ask the REQUESTER to shrink (partial release, cheaper than a
        full spill); (2) **force-spill** — spill the largest consumer of
        the OVER-QUOTA query first (the query over its ledger pays for
        its own pressure before any neighbor), min_trigger waived; (3)
        **shed** — fail THIS query with the classified
        ``errors.MemoryExhausted`` (policy 'shed', or the requester's
        per-query quota breached), never the process — or, under the
        default 'degrade' policy, record a survivable deny. Returns True
        when any rung freed bytes. ``forced`` (the memmgr.deny chaos
        site) treats every rung as over budget so the whole ladder gets
        traffic."""
        from auron_tpu import config as cfg
        from auron_tpu.obs import trace
        policy = cfg.get_config().get(cfg.MEMMGR_PRESSURE_POLICY)
        cname = getattr(c, "consumer_name", "?")

        def over() -> tuple[bool, int]:
            with self._lock:
                total_used = sum(self._used.values())
                q_used = self._query_used_locked(qid)
            breach = total_used > self.total \
                or (bool(quota) and q_used > quota)
            return (forced or breach), total_used

        if policy == "legacy":
            _o, total_used = over()
            self._count_rung("deny")
            trace.event("memory", "memmgr.deny", consumer=cname,
                        total_used=total_used, budget=self.total)
            return False

        freed_any = False
        # rung 1: shrink — advise smaller scan batches from here on and
        # ask the requester for a partial release
        is_over, total_used = over()
        if is_over:
            self._shrink_level = min(self._shrink_level + 1, 3)
            self._comfort_grants = 0
            shrink_fn = getattr(c, "shrink", None)   # duck-typed consumers
            try:
                freed = int(shrink_fn() or 0) if shrink_fn else 0
            except Exception:   # pragma: no cover - consumer bug guard
                logger.exception("memmgr: %s.shrink() failed", cname)
                freed = 0
            if freed:
                freed_any = True
                with self._lock:
                    self._used[c] = max(self._used.get(c, 0) - freed, 0)
                    self.num_spills += 1
                    self.spilled_bytes += freed
            self._count_rung("shrink")
            trace.event("memory", "memmgr.pressure", rung="shrink",
                        consumer=cname, freed=freed,
                        advised_shift=self._shrink_level)

        # rung 1.5: cache_evict — drop warm-path cache entries (any
        # consumer marked pressure_evictable, i.e. pure DERIVED state
        # re-creatable at the cost of one query) before force-spilling
        # WORKING state. min_trigger is irrelevant here: small caches
        # that the main spill loop skipped still free real bytes
        is_over, total_used = over()
        if is_over:
            with self._lock:
                victims = [v for v, u in self._used.items()
                           if getattr(v, "pressure_evictable", False)
                           and u > 0 and self._spill_eligible_locked(v)]
            if victims:
                freed = 0
                for victim in victims:
                    with trace.span("memory", "memmgr.spill",
                                    victim=getattr(victim,
                                                   "consumer_name", "?"),
                                    total_used=total_used,
                                    budget=self.total,
                                    rung="cache_evict") as sp:
                        v_freed = victim.spill()
                        sp.set(freed=v_freed)
                    with self._lock:
                        self._used[victim] = max(
                            self._used.get(victim, 0) - v_freed, 0)
                        if v_freed:
                            self.num_spills += 1
                            self.spilled_bytes += v_freed
                    freed += v_freed
                if freed:
                    freed_any = True
                self._count_rung("cache_evict")
                trace.event("memory", "memmgr.pressure",
                            rung="cache_evict", consumer=cname,
                            freed=freed, victims=len(victims))

        # rung 2: force-spill the largest holder, min_trigger waived —
        # under real pressure many small consumers add up to the budget.
        # Victim pool: consumers of OVER-QUOTA queries first (the query
        # over its per-query ledger pays before any neighbor), every
        # consumer when no query is over quota
        is_over, total_used = over()
        if is_over:
            with self._lock:
                per_query = self._usage_by_query_locked()
                over_q = {q for q, u in per_query.items()
                          if quota and u > quota}
                # over-quota queries' consumers first; fall back to ALL
                # eligible consumers only when the GLOBAL budget is
                # breached (or the chaos deny forces the rung) — on a
                # quota-only breach spilling an innocent neighbor could
                # not lower the offender's ledger anyway ('never an
                # innocent neighbor'), so an empty offender pool lets
                # rung 3 decide instead
                pool = [(u, v) for v, u in self._used.items()
                        if self._query_of.get(v, "") in over_q
                        and self._spill_eligible_locked(v) and u > 0]
                if not pool and (forced
                                 or sum(per_query.values()) > self.total):
                    pool = [(u, v) for v, u in self._used.items()
                            if u > 0 and self._spill_eligible_locked(v)]
                candidates = pool
            freed = 0
            if candidates:
                _, victim = max(candidates, key=lambda t: t[0])
                with trace.span("memory", "memmgr.spill",
                                victim=getattr(victim, "consumer_name",
                                               "?"),
                                total_used=total_used, budget=self.total,
                                rung="force_spill") as sp:
                    freed = victim.spill()
                    sp.set(freed=freed)
                with self._lock:
                    self._used[victim] = max(
                        self._used.get(victim, 0) - freed, 0)
                    if freed:
                        self.num_spills += 1
                        self.spilled_bytes += freed
                if freed:
                    freed_any = True
            self._count_rung("force_spill")
            trace.event("memory", "memmgr.pressure", rung="force_spill",
                        consumer=cname, freed=freed)

        # rung 3: shed or survivable deny
        is_over, total_used = over()
        if is_over:
            with self._lock:
                q_used = self._query_used_locked(qid)
            if policy == "shed" or (quota and q_used > quota):
                self._count_rung("shed")
                trace.event("memory", "memmgr.shed", consumer=cname,
                            query=qid, query_used=q_used,
                            total_used=total_used, budget=self.total,
                            quota=quota)
                from auron_tpu import errors
                raise errors.MemoryExhausted(
                    f"memory pressure unresolved after the degradation "
                    f"ladder: {total_used} bytes used against budget "
                    f"{self.total}"
                    + (f" (query {qid or '<anon>'} used {q_used} against "
                       f"quota {quota})" if quota else "")
                    + f"; shedding the query (requester {cname})",
                    site="memmgr.deny")
            self._count_rung("deny")
            trace.event("memory", "memmgr.deny", consumer=cname,
                        total_used=total_used, budget=self.total)
        return freed_any

    @staticmethod
    def _registry_enabled() -> bool:
        try:
            from auron_tpu.obs import registry as obs_registry
            return obs_registry.enabled()
        except Exception:   # pragma: no cover
            return False

    def _observe(self, status: dict) -> None:
        """Mirror a status snapshot onto the process registry gauges
        (best-effort: telemetry must never fail an accounting update)."""
        try:
            from auron_tpu.obs import registry as obs_registry
            obs_registry.observe_memmgr(status)
        except Exception:   # pragma: no cover - observability best-effort
            logger.exception("memmgr gauge update failed")

    # -- status (reference dumps the consumer table on exit,
    #    auron-memmgr/src/lib.rs:143-163) ----------------------------------

    def status(self) -> dict:
        with self._lock:
            return self._status_locked()

    def _status_locked(self) -> dict:
        """Status snapshot; caller holds ``self._lock``."""
        queries = {tag or "<anon>": u
                   for tag, u in self._usage_by_query_locked().items()}
        n = max(len(queries), 1)
        return {
            "total": self.total,
            "used": sum(self._used.values()),
            "num_consumers": len(self._used),
            "num_queries": len(queries),
            # per LIVE QUERY, the concurrent runtime's fairness unit
            "fair_share": self.total // n,
            "num_spills": self.num_spills,
            "spilled_bytes": self.spilled_bytes,
            "consumers": {getattr(c, "consumer_name", "?"): u
                          for c, u in self._used.items()},
            "queries": queries,
        }
