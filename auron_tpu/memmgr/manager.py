"""HBM budget arbitration.

Mirrors the reference's design (reference: auron-memmgr/src/lib.rs:303-423):
one manager per process, consumers update their usage after each growth
step, the manager answers Nothing or Spill based on the consumer's fair
share ``total / num_spillable_consumers`` and a global watermark. The
reference's Wait arm (condvar, 10 s) exists because many tasks share one
pool concurrently; the host driver here executes partitions cooperatively,
so over-budget resolves by spilling the requester (the biggest consumer is
asked first when the requester is under fair share).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

logger = logging.getLogger("auron_tpu.memmgr")

#: don't bother spilling consumers below this (reference: MIN_TRIGGER_SIZE
#: 16MB, auron-memmgr/src/lib.rs:36)
MIN_TRIGGER_SIZE = 16 << 20


class MemConsumer:
    """Spillable participant. Operators subclass / duck-type this."""

    #: display name for the status dump
    consumer_name: str = "consumer"

    def mem_used(self) -> int:
        raise NotImplementedError

    def spill(self) -> int:
        """Release device memory; returns bytes freed."""
        raise NotImplementedError


class MemManager:
    def __init__(self, total_bytes: Optional[int] = None,
                 min_trigger: int = MIN_TRIGGER_SIZE,
                 spill_manager: Optional["object"] = None):
        if total_bytes is None:
            total_bytes = self.default_budget()
        self.total = total_bytes
        self.min_trigger = min_trigger
        self.spill_manager = spill_manager
        self._lock = threading.Lock()
        # weak keys: a consumer whose operator was dropped without an
        # explicit unregister (e.g. a memoized exchange buffer released
        # with its query) must not pin itself — or its accounted bytes —
        # in the manager for the process lifetime
        import weakref
        self._used: "weakref.WeakKeyDictionary[MemConsumer, int]" = \
            weakref.WeakKeyDictionary()
        self.num_spills = 0
        self.spilled_bytes = 0

    @staticmethod
    def default_budget() -> int:
        """auron.memory.fraction of the device's HBM (the reference's
        spark.auron.memoryFraction × executor memory); falls back to a
        conservative 8 GB figure when the backend doesn't report a limit
        (e.g. the CPU test mesh)."""
        from auron_tpu import config as cfg
        fraction = cfg.get_config().get(cfg.MEMORY_FRACTION)
        limit = 8 << 30
        try:
            import jax
            stats = jax.devices()[0].memory_stats() or {}
            limit = int(stats.get("bytes_limit", limit)) or limit
        except Exception:
            pass
        return int(limit * fraction)

    # -- registration -------------------------------------------------------

    def register_consumer(self, c: MemConsumer) -> None:
        with self._lock:
            self._used.setdefault(c, 0)

    def unregister_consumer(self, c: MemConsumer) -> None:
        with self._lock:
            self._used.pop(c, None)

    # -- accounting ---------------------------------------------------------

    @property
    def used_total(self) -> int:
        with self._lock:
            return sum(self._used.values())

    def fair_share(self) -> int:
        with self._lock:
            n = max(len(self._used), 1)
        return self.total // n

    def update_mem_used(self, c: MemConsumer, used: int) -> str:
        """Record ``c``'s usage; returns 'nothing' or 'spilled'. May invoke
        c.spill() (or the largest consumer's) synchronously.

        Every accounting decision is observable on the same planes as
        compute (the PR 6 forensics contract): the post-decision status
        mirrors onto registry gauges (obs/registry.observe_memmgr), an
        under-budget grant drops a ``memory`` trace event, each spill
        opens a ``memmgr.spill`` span around the victim's spill, and an
        over-budget exit with no spillable candidate left records a
        ``memmgr.deny`` — so memory pressure lines up with the span
        timeline instead of hiding in log archaeology."""
        from auron_tpu.obs import trace
        observe = self._registry_enabled()
        with self._lock:
            self._used[c] = used
            total_used = sum(self._used.values())
            # grant-path telemetry snapshot under the SAME lock the
            # accounting already holds — no second acquisition, and the
            # consumer copy only happens when the registry will see it
            status = self._status_locked() if observe else None

        if total_used <= self.total:
            trace.event("memory", "memmgr.grant",
                        consumer=getattr(c, "consumer_name", "?"),
                        used=used, total_used=total_used,
                        budget=self.total)
            if status is not None:
                self._observe(status)
            return "nothing"

        # Spill until under budget or out of candidates (the reference loops
        # to its watermark the same way; one victim's spill may free less
        # than the overshoot — e.g. a consumer refusing mid-merge).
        spilled_any = False
        tried: set = set()
        while True:
            with self._lock:
                total_used = sum(self._used.values())
                share = self.total // max(len(self._used), 1)
                c_used = self._used.get(c, 0)
            if total_used <= self.total:
                break
            if (c not in tried and c_used >= max(share, 1)
                    and c_used >= self.min_trigger):
                victim = c
            else:
                with self._lock:
                    candidates = [(u, v) for v, u in self._used.items()
                                  if u >= self.min_trigger and v not in tried]
                if not candidates:
                    trace.event("memory", "memmgr.deny",
                                consumer=getattr(c, "consumer_name", "?"),
                                total_used=total_used, budget=self.total,
                                tried=len(tried))
                    break
                _, victim = max(candidates, key=lambda t: t[0])
            tried.add(victim)

            with trace.span("memory", "memmgr.spill",
                            victim=getattr(victim, "consumer_name", "?"),
                            total_used=total_used,
                            budget=self.total) as sp:
                freed = victim.spill()
                sp.set(freed=freed)
            with self._lock:
                self._used[victim] = max(self._used.get(victim, 0) - freed, 0)
                if freed:
                    self.num_spills += 1
                    self.spilled_bytes += freed
            if freed:
                spilled_any = True
                logger.info("memmgr: spilled %s (%d bytes freed, %d/%d used)",
                            victim.consumer_name, freed,
                            max(total_used - freed, 0), self.total)
        if self._registry_enabled():
            self._observe(self.status())
        return "spilled" if spilled_any else "nothing"

    @staticmethod
    def _registry_enabled() -> bool:
        try:
            from auron_tpu.obs import registry as obs_registry
            return obs_registry.enabled()
        except Exception:   # pragma: no cover
            return False

    def _observe(self, status: dict) -> None:
        """Mirror a status snapshot onto the process registry gauges
        (best-effort: telemetry must never fail an accounting update)."""
        try:
            from auron_tpu.obs import registry as obs_registry
            obs_registry.observe_memmgr(status)
        except Exception:   # pragma: no cover - observability best-effort
            logger.exception("memmgr gauge update failed")

    # -- status (reference dumps the consumer table on exit,
    #    auron-memmgr/src/lib.rs:143-163) ----------------------------------

    def status(self) -> dict:
        with self._lock:
            return self._status_locked()

    def _status_locked(self) -> dict:
        """Status snapshot; caller holds ``self._lock``."""
        n = max(len(self._used), 1)
        return {
            "total": self.total,
            "used": sum(self._used.values()),
            "num_consumers": len(self._used),
            "fair_share": self.total // n,
            "num_spills": self.num_spills,
            "spilled_bytes": self.spilled_bytes,
            "consumers": {getattr(c, "consumer_name", "?"): u
                          for c, u in self._used.items()},
        }
