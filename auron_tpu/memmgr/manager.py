"""HBM budget arbitration.

Mirrors the reference's design (reference: auron-memmgr/src/lib.rs:303-423):
one manager per process, consumers update their usage after each growth
step, the manager answers Nothing or Spill based on the consumer's fair
share ``total / num_spillable_consumers`` and a global watermark. The
reference's Wait arm (condvar, 10 s) exists because many tasks share one
pool concurrently; the host driver here executes partitions cooperatively,
so over-budget resolves by spilling the requester (the biggest consumer is
asked first when the requester is under fair share).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

logger = logging.getLogger("auron_tpu.memmgr")

#: don't bother spilling consumers below this (reference: MIN_TRIGGER_SIZE
#: 16MB, auron-memmgr/src/lib.rs:36)
MIN_TRIGGER_SIZE = 16 << 20

#: every live manager, weakly held — the process-wide consumer-leak
#: probe the tier-1 leak-audit fixture and the chaos battery read
import weakref as _weakref

_MANAGERS: "_weakref.WeakSet" = _weakref.WeakSet()

#: (config epoch, quota bytes) — see MemManager._query_quota
_QUOTA_CACHE: tuple = (-1, 0)


def live_consumer_count() -> int:
    """Registered consumers across every live MemManager (after a gc, a
    finished query must leave this at its pre-query value — consumers
    are weakly held, so anything still counted is either genuinely live
    or pinned by a leak)."""
    total = 0
    for m in list(_MANAGERS):
        with m._lock:
            total += len(m._used)
    return total


class MemConsumer:
    """Spillable participant. Operators subclass / duck-type this."""

    #: display name for the status dump
    consumer_name: str = "consumer"

    def mem_used(self) -> int:
        raise NotImplementedError

    def spill(self) -> int:
        """Release device memory; returns bytes freed."""
        raise NotImplementedError

    def shrink(self) -> int:
        """OPTIONAL degradation hook (pressure ladder rung 1): release
        PART of the held memory — cheaper than a full spill — returning
        bytes freed. The default declines (0); consumers that buffer
        batch lists override (memmgr/consumer.BufferedSpillConsumer
        sheds its oldest half)."""
        return 0


class MemManager:
    def __init__(self, total_bytes: Optional[int] = None,
                 min_trigger: int = MIN_TRIGGER_SIZE,
                 spill_manager: Optional["object"] = None):
        if total_bytes is None:
            total_bytes = self.default_budget()
        self.total = total_bytes
        self.min_trigger = min_trigger
        self.spill_manager = spill_manager
        self._lock = threading.Lock()
        # weak keys: a consumer whose operator was dropped without an
        # explicit unregister (e.g. a memoized exchange buffer released
        # with its query) must not pin itself — or its accounted bytes —
        # in the manager for the process lifetime
        import weakref
        self._used: "weakref.WeakKeyDictionary[MemConsumer, int]" = \
            weakref.WeakKeyDictionary()
        self.num_spills = 0
        self.spilled_bytes = 0
        #: degradation-ladder state: shrink rungs taken (drives the
        #: advised batch-rows hint scans consult) + per-rung counters
        self._shrink_level = 0
        #: consecutive comfortable grants (under half budget) since the
        #: last pressure event — the shrink-level decay hysteresis
        self._comfort_grants = 0
        self.pressure_counts = {"shrink": 0, "force_spill": 0,
                                "deny": 0, "shed": 0}
        _MANAGERS.add(self)

    @staticmethod
    def default_budget() -> int:
        """auron.memory.fraction of the device's HBM (the reference's
        spark.auron.memoryFraction × executor memory); falls back to a
        conservative 8 GB figure when the backend doesn't report a limit
        (e.g. the CPU test mesh)."""
        from auron_tpu import config as cfg
        fraction = cfg.get_config().get(cfg.MEMORY_FRACTION)
        limit = 8 << 30
        try:
            import jax
            stats = jax.devices()[0].memory_stats() or {}
            limit = int(stats.get("bytes_limit", limit)) or limit
        except Exception:
            pass
        return int(limit * fraction)

    # -- registration -------------------------------------------------------

    def register_consumer(self, c: MemConsumer) -> None:
        with self._lock:
            self._used.setdefault(c, 0)

    def unregister_consumer(self, c: MemConsumer) -> None:
        with self._lock:
            self._used.pop(c, None)

    # -- accounting ---------------------------------------------------------

    @property
    def used_total(self) -> int:
        with self._lock:
            return sum(self._used.values())

    def fair_share(self) -> int:
        with self._lock:
            n = max(len(self._used), 1)
        return self.total // n

    def update_mem_used(self, c: MemConsumer, used: int) -> str:
        """Record ``c``'s usage; returns 'nothing' or 'spilled'. May invoke
        c.spill() (or the largest consumer's) synchronously.

        Every accounting decision is observable on the same planes as
        compute (the PR 6 forensics contract): the post-decision status
        mirrors onto registry gauges (obs/registry.observe_memmgr), an
        under-budget grant drops a ``memory`` trace event, each spill
        opens a ``memmgr.spill`` span around the victim's spill, and an
        over-budget exit with no spillable candidate left records a
        ``memmgr.deny`` — so memory pressure lines up with the span
        timeline instead of hiding in log archaeology."""
        from auron_tpu.obs import trace
        from auron_tpu.runtime import faults
        observe = self._registry_enabled()
        with self._lock:
            self._used[c] = used
            total_used = sum(self._used.values())
            # grant-path telemetry snapshot under the SAME lock the
            # accounting already holds — no second acquisition, and the
            # consumer copy only happens when the registry will see it
            status = self._status_locked() if observe else None

        # the memmgr.deny chaos site: pretend the budget is exhausted so
        # the degradation ladder gets deterministic traffic
        forced = faults.fires("memmgr.deny", "deny")
        quota = self._query_quota()
        budget = min(self.total, quota) if quota else self.total
        if total_used <= budget and not forced:
            if self._shrink_level:
                # decay the shrink advice once pressure has demonstrably
                # subsided (16 consecutive grants under HALF budget) —
                # one pressure episode must not pin 8x-smaller scan
                # batches for the manager's lifetime
                if total_used <= budget // 2:
                    self._comfort_grants += 1
                    if self._comfort_grants >= 16:
                        self._shrink_level -= 1
                        self._comfort_grants = 0
                else:
                    self._comfort_grants = 0
            trace.event("memory", "memmgr.grant",
                        consumer=getattr(c, "consumer_name", "?"),
                        used=used, total_used=total_used,
                        budget=self.total)
            if status is not None:
                self._observe(status)
            return "nothing"

        # Spill until under budget or out of candidates (the reference loops
        # to its watermark the same way; one victim's spill may free less
        # than the overshoot — e.g. a consumer refusing mid-merge).
        spilled_any = False
        exhausted = forced    # an injected deny skips straight to the ladder
        tried: set = set()
        while not exhausted:
            with self._lock:
                total_used = sum(self._used.values())
                share = self.total // max(len(self._used), 1)
                c_used = self._used.get(c, 0)
            if total_used <= budget:
                break
            if (c not in tried and c_used >= max(share, 1)
                    and c_used >= self.min_trigger):
                victim = c
            else:
                with self._lock:
                    candidates = [(u, v) for v, u in self._used.items()
                                  if u >= self.min_trigger and v not in tried]
                if not candidates:
                    exhausted = True
                    break
                _, victim = max(candidates, key=lambda t: t[0])
            tried.add(victim)

            with trace.span("memory", "memmgr.spill",
                            victim=getattr(victim, "consumer_name", "?"),
                            total_used=total_used,
                            budget=self.total) as sp:
                freed = victim.spill()
                sp.set(freed=freed)
            with self._lock:
                self._used[victim] = max(self._used.get(victim, 0) - freed, 0)
                if freed:
                    self.num_spills += 1
                    self.spilled_bytes += freed
            if freed:
                spilled_any = True
                logger.info("memmgr: spilled %s (%d bytes freed, %d/%d used)",
                            victim.consumer_name, freed,
                            max(total_used - freed, 0), self.total)
        if exhausted:
            # the spill loop ran dry still over budget — the old hard
            # "deny": now a policy (auron.memmgr.pressure_policy)
            if self._pressure_ladder(c, budget, forced=forced):
                spilled_any = True
        if self._registry_enabled():
            self._observe(self.status())
        return "spilled" if spilled_any else "nothing"

    # -- memory-pressure degradation ladder (PR 8) --------------------------

    def _query_quota(self) -> int:
        """auron.memmgr.query_quota_bytes resolved from the process
        config (0 = no quota), cached against the config epoch —
        update_mem_used runs per batch-add, so the common no-quota path
        must cost one int compare. Scope honesty: the quota caps THIS
        MANAGER's total — today a Session runs one query at a time, so
        that is the query's footprint; the concurrent scheduler
        (ROADMAP [serving]) must give each query its own manager (or a
        per-query ledger) for the cap to stay per-query."""
        global _QUOTA_CACHE
        from auron_tpu import config as cfg
        epoch, val = _QUOTA_CACHE
        if epoch == cfg.config_epoch():
            return val
        try:
            val = int(cfg.get_config().get(cfg.MEMMGR_QUERY_QUOTA_BYTES))
        except Exception:   # pragma: no cover - config always resolvable
            val = 0
        _QUOTA_CACHE = (cfg.config_epoch(), val)
        return val

    def advised_batch_rows(self, base: int) -> int:
        """Pressure-adapted scan granularity: every shrink rung taken
        halves the advised batch rows (floor ``base/8``, never below
        256), so the scans feeding a struggling query deliver smaller
        device batches instead of ramming full-capacity ones into a
        budget that just denied. Scans consult this per batch
        (io/parquet.ParquetScanOp)."""
        lvl = self._shrink_level
        if lvl <= 0:
            return base
        return max(base >> min(lvl, 3), min(base, 256))

    def _count_rung(self, rung: str) -> None:
        self.pressure_counts[rung] = self.pressure_counts.get(rung, 0) + 1
        if self._registry_enabled():
            try:
                from auron_tpu.obs import registry as obs_registry
                obs_registry.get_registry().counter(
                    "auron_memmgr_pressure_total", rung=rung).inc()
            except Exception:   # pragma: no cover - telemetry best-effort
                pass

    def _pressure_ladder(self, c: MemConsumer, budget: int,
                         forced: bool = False) -> bool:
        """Walk the degradation rungs after the spill loop ran dry still
        over budget: (1) **shrink** — bump the advised-batch-rows hint
        and ask the REQUESTER to shrink (partial release, cheaper than a
        full spill); (2) **force-spill** — spill the largest consumer
        ignoring ``min_trigger`` (small consumers add up); (3) **shed**
        — fail THIS query with the classified ``errors.MemoryExhausted``
        (policy 'shed', or any per-query quota breach), never the
        process — or, under the default 'degrade' policy, record a
        survivable deny. Returns True when any rung freed bytes.
        ``forced`` (the memmgr.deny chaos site) treats every rung as
        over budget so the whole ladder gets traffic."""
        from auron_tpu import config as cfg
        from auron_tpu.obs import trace
        policy = cfg.get_config().get(cfg.MEMMGR_PRESSURE_POLICY)
        cname = getattr(c, "consumer_name", "?")

        def over() -> tuple[bool, int]:
            with self._lock:
                total_used = sum(self._used.values())
            return (forced or total_used > budget), total_used

        if policy == "legacy":
            _o, total_used = over()
            self._count_rung("deny")
            trace.event("memory", "memmgr.deny", consumer=cname,
                        total_used=total_used, budget=self.total)
            return False

        freed_any = False
        # rung 1: shrink — advise smaller scan batches from here on and
        # ask the requester for a partial release
        is_over, total_used = over()
        if is_over:
            self._shrink_level = min(self._shrink_level + 1, 3)
            self._comfort_grants = 0
            shrink_fn = getattr(c, "shrink", None)   # duck-typed consumers
            try:
                freed = int(shrink_fn() or 0) if shrink_fn else 0
            except Exception:   # pragma: no cover - consumer bug guard
                logger.exception("memmgr: %s.shrink() failed", cname)
                freed = 0
            if freed:
                freed_any = True
                with self._lock:
                    self._used[c] = max(self._used.get(c, 0) - freed, 0)
                    self.num_spills += 1
                    self.spilled_bytes += freed
            self._count_rung("shrink")
            trace.event("memory", "memmgr.pressure", rung="shrink",
                        consumer=cname, freed=freed,
                        advised_shift=self._shrink_level)

        # rung 2: force-spill the largest holder, min_trigger waived —
        # under real pressure many small consumers add up to the budget
        is_over, total_used = over()
        if is_over:
            with self._lock:
                candidates = [(u, v) for v, u in self._used.items()
                              if u > 0]
            freed = 0
            if candidates:
                _, victim = max(candidates, key=lambda t: t[0])
                with trace.span("memory", "memmgr.spill",
                                victim=getattr(victim, "consumer_name",
                                               "?"),
                                total_used=total_used, budget=self.total,
                                rung="force_spill") as sp:
                    freed = victim.spill()
                    sp.set(freed=freed)
                with self._lock:
                    self._used[victim] = max(
                        self._used.get(victim, 0) - freed, 0)
                    if freed:
                        self.num_spills += 1
                        self.spilled_bytes += freed
                if freed:
                    freed_any = True
            self._count_rung("force_spill")
            trace.event("memory", "memmgr.pressure", rung="force_spill",
                        consumer=cname, freed=freed)

        # rung 3: shed or survivable deny
        is_over, total_used = over()
        if is_over:
            quota = self._query_quota()
            if policy == "shed" or (quota and total_used > quota):
                self._count_rung("shed")
                trace.event("memory", "memmgr.shed", consumer=cname,
                            total_used=total_used, budget=self.total,
                            quota=quota)
                from auron_tpu import errors
                raise errors.MemoryExhausted(
                    f"memory pressure unresolved after the degradation "
                    f"ladder: {total_used} bytes used against budget "
                    f"{self.total}" + (f" (query quota {quota})"
                                       if quota else "")
                    + f"; shedding the query (requester {cname})",
                    site="memmgr.deny")
            self._count_rung("deny")
            trace.event("memory", "memmgr.deny", consumer=cname,
                        total_used=total_used, budget=self.total)
        return freed_any

    @staticmethod
    def _registry_enabled() -> bool:
        try:
            from auron_tpu.obs import registry as obs_registry
            return obs_registry.enabled()
        except Exception:   # pragma: no cover
            return False

    def _observe(self, status: dict) -> None:
        """Mirror a status snapshot onto the process registry gauges
        (best-effort: telemetry must never fail an accounting update)."""
        try:
            from auron_tpu.obs import registry as obs_registry
            obs_registry.observe_memmgr(status)
        except Exception:   # pragma: no cover - observability best-effort
            logger.exception("memmgr gauge update failed")

    # -- status (reference dumps the consumer table on exit,
    #    auron-memmgr/src/lib.rs:143-163) ----------------------------------

    def status(self) -> dict:
        with self._lock:
            return self._status_locked()

    def _status_locked(self) -> dict:
        """Status snapshot; caller holds ``self._lock``."""
        n = max(len(self._used), 1)
        return {
            "total": self.total,
            "used": sum(self._used.values()),
            "num_consumers": len(self._used),
            "fair_share": self.total // n,
            "num_spills": self.num_spills,
            "spilled_bytes": self.spilled_bytes,
            "consumers": {getattr(c, "consumer_name", "?"): u
                          for c, u in self._used.items()},
        }
