"""Tiered spill storage: host DRAM → compressed disk files.

The reference spills to JVM on-heap buffers or local files with a
block-compressed codec (reference: auron-memmgr/src/spill.rs:89-275,
OnHeapSpill via JNI / FileSpill via tempfile). Here tier 1 is host DRAM
(already-serialized compressed frames held as bytes — on TPU the device→host
hop is the expensive part, compression is cheap), tier 2 is an append-only
temp file of length-prefixed frames. A Spill written while DRAM budget
lasts can later overflow: frames are flushed to disk in order and the spill
keeps a single frame sequence either way.

Disk format v2 (header magic ``ASP2`` + a checksum-algorithm byte,
utils/checksum.py): each frame record is ``<u32 len><u32 crc>`` + bytes,
and every disk read verifies the CRC before the frame reaches the serde
— a flipped byte surfaces as ``errors.SpillCorruption``, which is
TRANSIENT at task granularity (spill files are per-attempt artifacts;
the retry driver's recompute rewrites them from source), never silently
wrong merge output. Headerless v1 files are rejected, not misread.
DRAM-tier frames carry no CRC (host memory is trusted; the durable tier
is the disk file).

Fault-injection sites (runtime/faults.py): ``spill.write`` (write
failure + on-disk corruption after the CRC), ``spill.read`` (read
failure + in-flight corruption).
"""

from __future__ import annotations

import os
import struct
import tempfile
import threading
from typing import Iterator, Optional

from auron_tpu import errors
from auron_tpu.utils import checksum as cks

#: v2 file header: magic + <B algo>
_SPILL_MAGIC = b"ASP2"
_HEADER_SIZE = len(_SPILL_MAGIC) + 1
#: per-frame record header (shared with the RSS tier, utils/checksum.py)
_FRAME_HDR = cks.FRAME_HDR


def _host_hex() -> str:
    """8-hex-char hostname digest for the spill owner token (raw
    hostnames can contain the '-'/'.' the filename grammar uses)."""
    import hashlib
    import socket
    return hashlib.sha1(
        socket.gethostname().encode()).hexdigest()[:8]


_HOST_HEX = _host_hex()


_OWN_TOKEN = (None, "")


def _owner_token() -> str:
    """``p<pid>.<epoch>.<hosthex>`` filename token of this process
    (dots inside so the '-'-separated name parses unambiguously; the
    host digest keeps the sweep HOST-SCOPED like the RSS/journal owner
    tags — on a shared spill mount another host's pid numbers mean
    nothing here).  Memoized per pid: it is stamped on every spill
    file and the epoch is immutable for the process's lifetime."""
    global _OWN_TOKEN
    pid = os.getpid()
    if _OWN_TOKEN[0] != pid:
        from auron_tpu.utils import liveness
        _OWN_TOKEN = (
            pid, f"p{pid}.{liveness.process_epoch(pid)}.{_HOST_HEX}")
    return _OWN_TOKEN[1]


def _parse_owner_token(name: str):
    """(pid, epoch, host_hex) from a spill filename, or None for the
    pre-sweep name format (never swept — provenance unknowable)."""
    if not name.startswith("auron-spill-p"):
        return None
    token = name[len("auron-spill-p"):].split("-", 1)[0]
    try:
        pid_s, epoch_s, host = token.split(".", 2)
        return int(pid_s), int(epoch_s), host
    except ValueError:
        return None


#: spill dirs already startup-swept by this process (the system temp
#: dir is shared and large — sweep it once; explicitly configured dirs
#: are swept on every manager construction, they are small and the
#: crash harness re-enters them)
_SWEPT_DIRS: set = set()
_SWEPT_LOCK = threading.Lock()


class Spill:
    """One spill: an ordered sequence of opaque frames (serialized batches).

    Write phase: ``write_frame`` × N then ``finish``. Read phase:
    ``frames()`` re-yields in order (repeatable). ``release`` drops memory
    and deletes the file (reference deletes on drop, spill.rs:163-175).
    """

    def __init__(self, manager: "SpillManager", spill_id: int):
        self._mgr = manager
        self.spill_id = spill_id
        self._mem_frames: list[bytes] = []
        self._file: Optional[object] = None
        self._path: Optional[str] = None
        self._finished = False
        self._algo = cks.write_algo()
        self.mem_bytes = 0
        self.disk_bytes = 0
        self._frame_sizes: list[int] = []
        self._offsets: Optional[list[int]] = None  # built at finish()

    # -- write --------------------------------------------------------------

    def write_frame(self, frame: bytes) -> None:
        assert not self._finished
        if self._file is None and not self._mgr.try_reserve_host(len(frame)):
            self._spill_to_disk()
        if self._file is not None:
            self._write_disk_frame(frame)
        else:
            self._mem_frames.append(frame)
            self.mem_bytes += len(frame)
        self._frame_sizes.append(len(frame))

    def _write_disk_frame(self, frame: bytes) -> None:
        from auron_tpu.runtime import faults
        faults.maybe_fail("spill.write", errors.SpillIOError)
        crc = cks.compute(frame, self._algo)
        # corruption injects AFTER the CRC over the clean bytes: durable
        # bit rot is the integrity layer's problem, not the writer's
        payload = faults.maybe_corrupt("spill.write", frame)
        self._file.write(_FRAME_HDR.pack(len(frame), crc))
        self._file.write(payload)
        self.disk_bytes += len(frame) + _FRAME_HDR.size

    def _spill_to_disk(self) -> None:
        # tier decision: the DRAM budget ran out, this spill moves to
        # the disk tier — a timeline-visible event
        from auron_tpu.obs import trace
        with trace.span("spill", "spill.overflow_to_disk",
                        spill=self.spill_id,
                        frames=len(self._mem_frames),
                        bytes=self.mem_bytes):
            # the filename carries the owner's pid.epoch (utils/
            # liveness) so a successor process's startup sweep can
            # prove a crashed writer dead and reclaim the file — the
            # per-manager ledger (sweep_orphans at Session close) only
            # covers crashes the process SURVIVES
            fd, self._path = tempfile.mkstemp(
                prefix=f"auron-spill-{_owner_token()}-"
                       f"{self.spill_id}-",
                suffix=".atb", dir=self._mgr.spill_dir)
            # registered with the manager so a crashed attempt's orphan
            # is swept at Session close (sweep_orphans) — the spill-tier
            # equivalent of the RSS commit-time .part sweep
            self._mgr._track_path(self._path)
            self._file = os.fdopen(fd, "wb")
            self._file.write(_SPILL_MAGIC + struct.pack("<B", self._algo))
            self.disk_bytes += _HEADER_SIZE
            for frame in self._mem_frames:
                self._write_disk_frame(frame)
            self._mem_frames.clear()
            self._mgr.release_host(self.mem_bytes)
            self.mem_bytes = 0

    def finish(self) -> "Spill":
        self._finished = True
        if self._file is not None:
            self._file.flush()
            self._file.close()
            self._file = None
        # byte-offset index for frame_at (the reference's partition-offset
        # array alongside the data file, sort_repartitioner.rs:151+);
        # disk offsets account the file header + per-frame record headers
        offs, o = [], _HEADER_SIZE
        for s in self._frame_sizes:
            offs.append(o)
            o += _FRAME_HDR.size + s
        self._offsets = offs
        return self

    # -- read ---------------------------------------------------------------

    def _corrupt(self, msg: str) -> errors.SpillCorruption:
        return errors.SpillCorruption(
            f"{msg} (spill {self.spill_id}: {self._path})",
            site="spill.read")

    def _open_verified(self):
        """Open the disk file and verify the v2 header; returns
        (file, algo)."""
        from auron_tpu.runtime import faults
        faults.maybe_fail("spill.read", errors.SpillIOError)
        f = open(self._path, "rb")
        hdr = f.read(_HEADER_SIZE)
        if hdr[:4] != _SPILL_MAGIC or len(hdr) != _HEADER_SIZE:
            f.close()
            raise self._corrupt("bad spill-file header (v1 or foreign "
                                "file rejected)")
        return f, hdr[4]

    def _read_frame(self, f, algo: int) -> Optional[bytes]:
        """One verified frame record at the current offset; None at EOF."""
        from auron_tpu.runtime import faults
        hdr = f.read(_FRAME_HDR.size)
        if not hdr:
            return None
        if len(hdr) != _FRAME_HDR.size:
            raise self._corrupt("spill frame header truncated")
        ln, crc = _FRAME_HDR.unpack(hdr)
        frame = f.read(ln)
        if len(frame) != ln:
            raise self._corrupt("spill frame body truncated")
        frame = faults.maybe_corrupt("spill.read", frame)
        cks.verify_or_raise(frame, crc, algo, self._corrupt,
                            what="spill frame")
        return frame

    def frames(self) -> Iterator[bytes]:
        assert self._finished
        if self._path is not None:
            # production-segment timing only, zero per-frame overhead
            # when the 'spill' category is off (obs/trace.stream_spanned
            # explains the span-across-yield hazard)
            from auron_tpu.obs import trace

            def read_frames():
                f, algo = self._open_verified()
                with f:
                    while True:
                        frame = self._read_frame(f, algo)
                        if frame is None:
                            return
                        yield frame

            yield from trace.stream_spanned(
                "spill", "spill.read", read_frames(),
                spill=self.spill_id, tier="disk")
        else:
            yield from self._mem_frames

    def frame_at(self, index: int) -> bytes:
        """Random access to one frame: one seek via the offset index built
        at finish() (the offset-indexed fetch of the reference's shuffle
        files, sort_repartitioner.rs:151+)."""
        assert self._finished
        if self._path is None:
            return self._mem_frames[index]
        if index >= len(self._offsets):
            raise IndexError(index)
        f, algo = self._open_verified()
        with f:
            f.seek(self._offsets[index])
            frame = self._read_frame(f, algo)
            if frame is None:
                raise self._corrupt("spill frame offset past EOF")
            return frame

    # -- lifecycle ----------------------------------------------------------

    def release(self) -> None:
        # mid-write abort support: a failed run write releases before
        # finish(), so the file may still be open
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        self._mgr.release_host(self.mem_bytes)
        self._mem_frames.clear()
        self.mem_bytes = 0
        if self._path is not None:
            if os.path.exists(self._path):
                os.unlink(self._path)
            self._mgr._untrack_path(self._path)
        self._path = None


class SpillManager:
    """Owns the host-DRAM spill budget and the spill directory."""

    def __init__(self, host_budget_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        from auron_tpu import config as cfg
        conf = cfg.get_config()
        if host_budget_bytes is None:
            host_budget_bytes = conf.get(cfg.HOST_SPILL_BUDGET)
        if spill_dir is None:
            spill_dir = conf.get(cfg.SPILL_DIR) or None
        self.host_budget = host_budget_bytes
        self.spill_dir = spill_dir
        # RLock: Spill.release can run from a GC finalizer that fires while
        # the same thread is inside a budget-accounting critical section
        self._lock = threading.RLock()
        self._host_used = 0
        self._next_id = 0
        #: every disk-tier file this manager created and has not yet
        #: seen released — the sweep ledger (scoped to THIS manager so a
        #: sweep can never delete another process's spills in a shared
        #: temp dir)
        self._live_paths: set[str] = set()
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        # startup half of the orphan sweep: a SIGKILLed process never
        # ran Session.close(), so its ledger died with it — reclaim by
        # pid+epoch liveness from the filename instead. Explicit dirs
        # sweep every construction; the shared system temp dir once
        # per process.
        sweep_dir = spill_dir or tempfile.gettempdir()
        if spill_dir is None:
            with _SWEPT_LOCK:
                if sweep_dir in _SWEPT_DIRS:
                    sweep_dir = None
                else:
                    _SWEPT_DIRS.add(sweep_dir)
        if sweep_dir:
            self.sweep_dead_owners(sweep_dir)

    @staticmethod
    def sweep_dead_owners(directory: str) -> int:
        """Remove spill files whose owning process (pid.epoch in the
        filename) is provably dead — the startup complement of the
        Session-close ledger sweep; counted on
        ``auron_spill_orphans_swept_total``. Files in the pre-sweep
        name format (no owner token) are never touched."""
        from auron_tpu.utils import liveness
        removed = 0
        try:
            names = os.listdir(directory)
        except OSError:
            return 0
        for name in names:
            if not name.startswith("auron-spill-"):
                continue
            parsed = _parse_owner_token(name)
            if parsed is None:
                continue
            pid, epoch, host = parsed
            if host != _HOST_HEX:
                continue   # another host's writer: their sweep, not ours
            if not liveness.owner_dead(pid, epoch):
                continue
            try:
                os.unlink(os.path.join(directory, name))
                removed += 1
            except OSError:   # pragma: no cover - fs race
                pass
        liveness.note_swept("auron_spill_orphans_swept_total", removed,
                            directory, "spill")
        return removed

    def _track_path(self, path: str) -> None:
        with self._lock:
            self._live_paths.add(path)

    def _untrack_path(self, path: str) -> None:
        with self._lock:
            self._live_paths.discard(path)

    def sweep_orphans(self) -> int:
        """Delete every disk spill file this manager created that was
        never released — orphans of crashed/cancelled attempts (PR 4
        added the commit-time ``.part`` sweep for the RSS tier; this is
        the spill-tier equivalent, run at Session close). Returns how
        many files were removed. Ledger-scoped: files of other managers
        or processes in the same directory are never touched."""
        with self._lock:
            paths, self._live_paths = self._live_paths, set()
        removed = 0
        for p in paths:
            try:
                if os.path.exists(p):
                    os.unlink(p)
                    removed += 1
            except OSError:   # pragma: no cover - fs race
                pass
        if removed:
            import logging
            logging.getLogger("auron_tpu.memmgr").warning(
                "spill sweep removed %d orphaned spill file(s) at close",
                removed)
        return removed

    def live_disk_files(self) -> int:
        """Disk-tier files currently tracked (the leak-audit probe)."""
        with self._lock:
            return len(self._live_paths)

    @property
    def host_used(self) -> int:
        with self._lock:
            return self._host_used

    def try_reserve_host(self, nbytes: int) -> bool:
        with self._lock:
            if self._host_used + nbytes > self.host_budget:
                return False
            self._host_used += nbytes
            return True

    def release_host(self, nbytes: int) -> None:
        with self._lock:
            self._host_used = max(self._host_used - nbytes, 0)

    def new_spill(self) -> Spill:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        return Spill(self, sid)
