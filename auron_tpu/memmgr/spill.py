"""Tiered spill storage: host DRAM → compressed disk files.

The reference spills to JVM on-heap buffers or local files with a
block-compressed codec (reference: auron-memmgr/src/spill.rs:89-275,
OnHeapSpill via JNI / FileSpill via tempfile). Here tier 1 is host DRAM
(already-serialized compressed frames held as bytes — on TPU the device→host
hop is the expensive part, compression is cheap), tier 2 is an append-only
temp file of length-prefixed frames. A Spill written while DRAM budget
lasts can later overflow: frames are flushed to disk in order and the spill
keeps a single frame sequence either way.
"""

from __future__ import annotations

import os
import struct
import tempfile
import threading
from typing import Iterator, Optional


class Spill:
    """One spill: an ordered sequence of opaque frames (serialized batches).

    Write phase: ``write_frame`` × N then ``finish``. Read phase:
    ``frames()`` re-yields in order (repeatable). ``release`` drops memory
    and deletes the file (reference deletes on drop, spill.rs:163-175).
    """

    def __init__(self, manager: "SpillManager", spill_id: int):
        self._mgr = manager
        self.spill_id = spill_id
        self._mem_frames: list[bytes] = []
        self._file: Optional[object] = None
        self._path: Optional[str] = None
        self._finished = False
        self.mem_bytes = 0
        self.disk_bytes = 0
        self._frame_sizes: list[int] = []
        self._offsets: Optional[list[int]] = None  # built at finish()

    # -- write --------------------------------------------------------------

    def write_frame(self, frame: bytes) -> None:
        assert not self._finished
        if self._file is None and not self._mgr.try_reserve_host(len(frame)):
            self._spill_to_disk()
        if self._file is not None:
            self._file.write(struct.pack("<I", len(frame)))
            self._file.write(frame)
            self.disk_bytes += len(frame) + 4
        else:
            self._mem_frames.append(frame)
            self.mem_bytes += len(frame)
        self._frame_sizes.append(len(frame))

    def _spill_to_disk(self) -> None:
        fd, self._path = tempfile.mkstemp(
            prefix=f"auron-spill-{self.spill_id}-", suffix=".atb",
            dir=self._mgr.spill_dir)
        self._file = os.fdopen(fd, "wb")
        for frame in self._mem_frames:
            self._file.write(struct.pack("<I", len(frame)))
            self._file.write(frame)
            self.disk_bytes += len(frame) + 4
        self._mem_frames.clear()
        self._mgr.release_host(self.mem_bytes)
        self.mem_bytes = 0

    def finish(self) -> "Spill":
        self._finished = True
        if self._file is not None:
            self._file.flush()
            self._file.close()
            self._file = None
        # byte-offset index for frame_at (the reference's partition-offset
        # array alongside the data file, sort_repartitioner.rs:151+)
        offs, o = [], 0
        for s in self._frame_sizes:
            offs.append(o)
            o += 4 + s
        self._offsets = offs
        return self

    # -- read ---------------------------------------------------------------

    def frames(self) -> Iterator[bytes]:
        assert self._finished
        if self._path is not None:
            with open(self._path, "rb") as f:
                while True:
                    hdr = f.read(4)
                    if not hdr:
                        break
                    (ln,) = struct.unpack("<I", hdr)
                    yield f.read(ln)
        else:
            yield from self._mem_frames

    def frame_at(self, index: int) -> bytes:
        """Random access to one frame: one seek via the offset index built
        at finish() (the offset-indexed fetch of the reference's shuffle
        files, sort_repartitioner.rs:151+)."""
        assert self._finished
        if self._path is None:
            return self._mem_frames[index]
        if index >= len(self._offsets):
            raise IndexError(index)
        with open(self._path, "rb") as f:
            f.seek(self._offsets[index])
            hdr = f.read(4)
            (ln,) = struct.unpack("<I", hdr)
            return f.read(ln)

    # -- lifecycle ----------------------------------------------------------

    def release(self) -> None:
        self._mgr.release_host(self.mem_bytes)
        self._mem_frames.clear()
        self.mem_bytes = 0
        if self._path is not None and os.path.exists(self._path):
            os.unlink(self._path)
        self._path = None


class SpillManager:
    """Owns the host-DRAM spill budget and the spill directory."""

    def __init__(self, host_budget_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        from auron_tpu import config as cfg
        conf = cfg.get_config()
        if host_budget_bytes is None:
            host_budget_bytes = conf.get(cfg.HOST_SPILL_BUDGET)
        if spill_dir is None:
            spill_dir = conf.get(cfg.SPILL_DIR) or None
        self.host_budget = host_budget_bytes
        self.spill_dir = spill_dir
        # RLock: Spill.release can run from a GC finalizer that fires while
        # the same thread is inside a budget-accounting critical section
        self._lock = threading.RLock()
        self._host_used = 0
        self._next_id = 0
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)

    @property
    def host_used(self) -> int:
        with self._lock:
            return self._host_used

    def try_reserve_host(self, nbytes: int) -> bool:
        with self._lock:
            if self._host_used + nbytes > self.host_budget:
                return False
            self._host_used += nbytes
            return True

    def release_host(self, nbytes: int) -> None:
        with self._lock:
            self._host_used = max(self._host_used - nbytes, 0)

    def new_spill(self) -> Spill:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        return Spill(self, sid)
