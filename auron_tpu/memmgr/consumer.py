"""Shared buffering MemConsumer skeleton.

Several operators buffer device batches and spill them to tiered storage
under memory pressure (sort runs, join build sides — the reference's
MemConsumer impls in sort_exec.rs:375 and the join build registration).
The lock/accounting/metrics protocol is identical everywhere; only how a
spill run is serialized differs, so that is the one override point
(``_write_run``)."""

from __future__ import annotations

import threading
from typing import Optional

from auron_tpu.columnar.batch import DeviceBatch, batch_nbytes


class BufferedSpillConsumer:
    """Buffers child batches; under pressure writes them as one spill run.

    Subclasses override ``_write_run`` to control the run format (e.g. the
    sort consumer sorts the buffer and attaches order words)."""

    #: buffer claims happen under self._lock and runs serialize outside
    #: it, so a FOREIGN thread (a neighbor query's pressure walk under
    #: the concurrent scheduler) may safely invoke spill()/shrink()
    spill_thread_safe = True

    def __init__(self, name: str, mem, metrics, conf,
                 frame_rows: Optional[int] = None):
        from auron_tpu import config as cfg
        self.mem = mem
        self.metrics = metrics
        self.consumer_name = name
        self.frame_rows = frame_rows or conf.get(cfg.SPILL_FRAME_ROWS)
        self.codec_level = conf.get(cfg.SPILL_CODEC_LEVEL)
        self.buffered: list[DeviceBatch] = []
        self.bytes = 0
        self.spills = []
        self._lock = threading.RLock()
        #: victim spills claim the buffer under the lock but serialize it
        #: outside; this counts claimed-but-unpublished runs so readers
        #: can wait for a consistent (buffered, spills) view
        self._inflight_spills = 0
        self._quiesced = threading.Condition(self._lock)
        mem.register_consumer(self)

    # -- write side ---------------------------------------------------------

    def add(self, batch: DeviceBatch) -> None:
        with self._lock:
            self.buffered.append(batch)
            self.bytes += batch_nbytes(batch)
            used = self.bytes
        self.mem.update_mem_used(self, used)

    def take_buffered(self) -> list[DeviceBatch]:
        with self._lock:
            out, self.buffered = self.buffered, []
            self.bytes = 0
        return out

    def wait_spills_published(self) -> None:
        """Block until no victim spill holds claimed-but-unpublished
        batches, so a subsequent (take_buffered, spills) read is a
        consistent snapshot — without this, a reader could see an empty
        buffer AND an empty spill list while a whole run is mid-write
        and silently lose it."""
        with self._quiesced:
            while self._inflight_spills:
                self._quiesced.wait()

    def mem_used(self) -> int:
        with self._lock:
            return self.bytes

    # -- MemConsumer --------------------------------------------------------

    def spill(self) -> int:
        from auron_tpu.obs import trace
        with self._lock:
            if not self.buffered:
                return 0
            buffered, self.buffered = self.buffered, []
            freed, self.bytes = self.bytes, 0
            self._inflight_spills += 1
        try:
            with trace.span("spill", "spill.run_write",
                            consumer=self.consumer_name,
                            batches=len(buffered), bytes=freed) as sp:
                spill = self.mem.spill_manager.new_spill()
                try:
                    self._write_run(spill, buffered)
                except BaseException:
                    # a failed run write (IO error mid-frame) must not
                    # leak the half-written spill file: the run was
                    # claimed but never published, so nobody else will
                    # ever release it
                    spill.release()
                    raise
                # tier decision: DRAM while the host budget lasted,
                # disk once it overflowed (spill.overflow_to_disk)
                sp.set(tier="disk" if spill.disk_bytes else "dram")
                with self._lock:
                    self.spills.append(spill.finish())
        finally:
            with self._quiesced:
                self._inflight_spills -= 1
                self._quiesced.notify_all()
        self.metrics.counter("mem_spill_count").add(1)
        self.metrics.counter("mem_spill_size").add(freed)
        return freed

    def shrink(self) -> int:
        """Degradation-ladder rung 1 (memmgr/manager._pressure_ladder):
        shed the OLDEST half of the buffered batches as one spill run —
        partial relief that keeps the newest (still hot) batches on
        device. Returns bytes freed; declines (0) when fewer than two
        batches are buffered (a full spill is then the right tool and
        rung 2 will take it)."""
        from auron_tpu.obs import trace
        if getattr(self.mem, "spill_manager", None) is None:
            return 0
        with self._lock:
            if len(self.buffered) < 2:
                return 0
            half = len(self.buffered) // 2
            victims, self.buffered = (self.buffered[:half],
                                      self.buffered[half:])
            freed = sum(batch_nbytes(b) for b in victims)
            self.bytes -= freed
            self._inflight_spills += 1
        try:
            with trace.span("spill", "spill.run_write",
                            consumer=self.consumer_name,
                            batches=len(victims), bytes=freed,
                            rung="shrink") as sp:
                spill = self.mem.spill_manager.new_spill()
                try:
                    self._write_run(spill, victims)
                except BaseException:
                    spill.release()
                    raise
                sp.set(tier="disk" if spill.disk_bytes else "dram")
                with self._lock:
                    self.spills.append(spill.finish())
        finally:
            with self._quiesced:
                self._inflight_spills -= 1
                self._quiesced.notify_all()
        self.metrics.counter("mem_spill_count").add(1)
        self.metrics.counter("mem_spill_size").add(freed)
        return freed

    def _write_run(self, spill, batches: list[DeviceBatch]) -> None:
        """Default run format: each batch's live rows as unsorted frames."""
        from auron_tpu.columnar.serde import (batch_to_host,
                                              serialize_host_batch,
                                              slice_host_batch)
        for b in batches:
            n = int(b.num_rows)
            host = batch_to_host(b, n)
            for lo in range(0, max(n, 1), self.frame_rows):
                hi = min(lo + self.frame_rows, n)
                spill.write_frame(serialize_host_batch(
                    slice_host_batch(host, lo, hi),
                    codec_level=self.codec_level))

    def close(self) -> None:
        self.mem.unregister_consumer(self)
        for s in self.spills:
            s.release()
        self.spills = []
