"""Parquet / ORC sinks.

The reference writes parquet/ORC back through its JVM FileSystem wrapper,
with Hive dynamic partitions handled JVM-side (reference: datafusion-ext-
plans/src/parquet_sink_exec.rs, orc_sink_exec.rs, NativeParquetSinkUtils).
Here the sink is the device→host off-ramp: child batches are materialized to
Arrow and written with pyarrow; dynamic partitions use pyarrow's hive-style
dataset writer. Each execute() partition writes its own file(s) — the same
task-parallel layout as the reference's one-file-per-task sinks — and emits
a single bookkeeping row (num_rows written), mirroring the reference sinks'
metric-only output.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from auron_tpu.columnar.arrow_bridge import to_arrow, to_device
from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer

_RESULT_SCHEMA = Schema((Field("num_rows", DataType.INT64, False),))


class _FileSinkOp(PhysicalOp):
    """Streaming sink: child batches flush to the writer whenever the
    buffer reaches auron.sink.buffer_rows — sink host memory is bounded
    regardless of partition size, the same streaming row-group contract as
    the reference sinks (parquet_sink_exec.rs)."""

    def __init__(self, child: PhysicalOp, path: str, compression: str):
        from auron_tpu.io.fs import resolve
        self.child = child
        #: remote-FS seam (io/fs.py): URI → (filesystem, fs-local path)
        self.fs, self.path = resolve(path)
        self.compression = compression

    def _makedirs(self) -> None:
        self.fs.create_dir(self.path, recursive=True)

    def _unlink(self, p: str) -> None:
        try:
            self.fs.delete_file(p)
        except (OSError, FileNotFoundError):
            pass

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return _RESULT_SCHEMA

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from auron_tpu import config as cfg
        metrics = ctx.metrics_for(self)
        io_time = metrics.counter("io_time")
        child_schema = self.child.schema()
        buffer_rows = ctx.conf.get(cfg.SINK_BUFFER_ROWS)

        def stream():
            pending: list[pa.Table] = []
            pending_rows = 0
            n = 0
            writer = None
            # per-execute write state: flush sequence restarts at 0 so a
            # task retry overwrites the previous attempt's fragments, and
            # every path written this attempt is tracked so a mid-stream
            # failure leaves NO output (the all-or-nothing contract a
            # one-shot write had)
            wstate = {"seq": 0, "paths": []}
            ok = False
            try:
                for batch in self.child.execute(partition, ctx):
                    # durable-tier drive loop: poll like the shuffle/
                    # spill writers so cancels land between chunks
                    ctx.checkpoint("sink.write")
                    rb = to_arrow(batch, child_schema)
                    if not rb.num_rows:
                        continue
                    pending.append(pa.Table.from_batches([rb]))
                    pending_rows += rb.num_rows
                    n += rb.num_rows
                    if pending_rows >= buffer_rows:
                        chunk = pa.concat_tables(pending).combine_chunks()
                        pending, pending_rows = [], 0
                        with timer(io_time, bucket="serde"):
                            writer = self._write_chunk(writer, chunk,
                                                       partition, wstate)
                if pending:
                    chunk = pa.concat_tables(pending).combine_chunks()
                    with timer(io_time, bucket="serde"):
                        writer = self._write_chunk(writer, chunk, partition,
                                                   wstate)
                ok = True
            finally:
                if writer is not None:
                    try:
                        with timer(io_time, bucket="serde"):
                            writer.close()
                            for st in wstate.get("streams", ()):
                                if not st.closed:
                                    st.close()
                    except Exception:
                        # on the failure path a close() error (e.g. the
                        # same full disk) must not mask the original
                        # exception or skip cleanup
                        if ok:
                            raise
                if not ok:
                    self._cleanup_failed(partition, wstate)
            result = pa.record_batch({"num_rows": pa.array([n], pa.int64())})
            yield to_device(result, capacity=16)[0]

        return count_output(stream(), metrics, timed=True)

    def _write_chunk(self, writer, chunk: pa.Table, partition: int,
                     wstate: dict):
        """Write one flushed chunk; returns the (possibly newly opened)
        long-lived writer, or None for writers that are per-chunk. Must
        append every file it creates to ``wstate['paths']``."""
        raise NotImplementedError

    def _cleanup_failed(self, partition: int, wstate: dict) -> None:
        """All-or-nothing per attempt: remove everything this attempt
        wrote. Tracked paths first; subclasses extend for files a failed
        write call may have created before raising."""
        for p in wstate["paths"]:
            self._unlink(p)

    def __repr__(self):
        return f"{type(self).__name__}[{self.path}]"


class ParquetSinkOp(_FileSinkOp):
    name = "parquet_sink"

    def __init__(self, child: PhysicalOp, path: str,
                 partition_by: Optional[list[str]] = None,
                 compression: str = "snappy"):
        super().__init__(child, path, compression)
        self.partition_by = list(partition_by or [])

    def _write_chunk(self, writer, chunk: pa.Table, partition: int,
                     wstate: dict):
        comp = self.compression if self.compression != "none" else None
        if self.partition_by:
            # hive-style dynamic partitions: every flush appends dataset
            # fragments under path/key=value/. The sequence is per-execute
            # so a retry overwrites the previous attempt's fragment names.
            seq = wstate["seq"]
            wstate["seq"] += 1
            collector: list = []
            pq.write_to_dataset(
                chunk, root_path=self.path, partition_cols=self.partition_by,
                compression=comp, filesystem=self.fs,
                basename_template=f"part-{partition:05d}-{seq:04d}-{{i}}"
                                  ".parquet",
                metadata_collector=collector)
            for md in collector:
                wstate["paths"].append(os.path.join(self.path,
                                                    md.row_group(0)
                                                    .column(0).file_path))
            return None
        if writer is None:
            self._makedirs()
            target = f"{self.path}/part-{partition:05d}.parquet"
            writer = pq.ParquetWriter(target, chunk.schema,
                                      compression=comp or "none",
                                      filesystem=self.fs)
            wstate["paths"].append(target)
        writer.write_table(chunk)
        return writer

    def _cleanup_failed(self, partition: int, wstate: dict) -> None:
        super()._cleanup_failed(partition, wstate)
        if not self.partition_by:
            return
        import pyarrow.fs as pafs
        try:
            infos = self.fs.get_file_info(
                pafs.FileSelector(self.path, recursive=True,
                                  allow_not_found=True))
        except (OSError, FileNotFoundError):
            return
        # a write_to_dataset call that raised mid-write may have created
        # fragments never reported to the collector; this attempt's (and
        # any previous attempt's) fragments all carry this partition's
        # basename prefix, so a prefix sweep restores all-or-nothing
        prefix = f"part-{partition:05d}-"
        for info in infos:
            if info.type == pafs.FileType.File and \
                    info.base_name.startswith(prefix):
                self._unlink(info.path)
        # sweep now-empty hive key=value directories (deepest first)
        try:
            infos = self.fs.get_file_info(
                pafs.FileSelector(self.path, recursive=True,
                                  allow_not_found=True))
        except (OSError, FileNotFoundError):
            return
        dirs = sorted((i.path for i in infos
                       if i.type == pafs.FileType.Directory),
                      key=len, reverse=True)
        for d in dirs:
            try:
                if not self.fs.get_file_info(pafs.FileSelector(d)):
                    self.fs.delete_dir(d)
            except (OSError, FileNotFoundError):
                pass


class OrcSinkOp(_FileSinkOp):
    name = "orc_sink"

    _ORC_COMPRESSION = {"none": "uncompressed", "snappy": "snappy",
                        "zstd": "zstd", "zlib": "zlib", "lz4": "lz4"}

    def __init__(self, child: PhysicalOp, path: str, compression: str = "zstd"):
        super().__init__(child, path, compression)

    def _write_chunk(self, writer, chunk: pa.Table, partition: int,
                     wstate: dict):
        from pyarrow import orc
        if writer is None:
            self._makedirs()
            target = f"{self.path}/part-{partition:05d}.orc"
            sink_stream = self.fs.open_output_stream(target)
            writer = orc.ORCWriter(
                sink_stream,
                compression=self._ORC_COMPRESSION.get(self.compression,
                                                      self.compression))
            # ORCWriter.close() does NOT close the underlying stream; an
            # unclosed object-store stream never finalizes its upload
            wstate["streams"] = wstate.get("streams", []) + [sink_stream]
            wstate["paths"].append(target)
        writer.write(chunk)
        return writer
