"""Parquet / ORC sinks.

The reference writes parquet/ORC back through its JVM FileSystem wrapper,
with Hive dynamic partitions handled JVM-side (reference: datafusion-ext-
plans/src/parquet_sink_exec.rs, orc_sink_exec.rs, NativeParquetSinkUtils).
Here the sink is the device→host off-ramp: child batches are materialized to
Arrow and written with pyarrow; dynamic partitions use pyarrow's hive-style
dataset writer. Each execute() partition writes its own file(s) — the same
task-parallel layout as the reference's one-file-per-task sinks — and emits
a single bookkeeping row (num_rows written), mirroring the reference sinks'
metric-only output.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from auron_tpu.columnar.arrow_bridge import to_arrow, to_device
from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer

_RESULT_SCHEMA = Schema((Field("num_rows", DataType.INT64, False),))


class _FileSinkOp(PhysicalOp):
    def __init__(self, child: PhysicalOp, path: str, compression: str):
        self.child = child
        self.path = path
        self.compression = compression

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return _RESULT_SCHEMA

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self.name)
        io_time = metrics.counter("io_time")
        child_schema = self.child.schema()

        def stream():
            tables = []
            for batch in self.child.execute(partition, ctx):
                rb = to_arrow(batch, child_schema)
                if rb.num_rows:
                    tables.append(pa.Table.from_batches([rb]))
            n = 0
            if tables:
                table = pa.concat_tables(tables).combine_chunks()
                n = table.num_rows
                with timer(io_time):
                    self._write(table, partition)
            result = pa.record_batch({"num_rows": pa.array([n], pa.int64())})
            yield to_device(result, capacity=16)[0]

        return count_output(stream(), metrics)

    def _write(self, table: pa.Table, partition: int) -> None:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}[{self.path}]"


class ParquetSinkOp(_FileSinkOp):
    name = "parquet_sink"

    def __init__(self, child: PhysicalOp, path: str,
                 partition_by: Optional[list[str]] = None,
                 compression: str = "snappy"):
        super().__init__(child, path, compression)
        self.partition_by = list(partition_by or [])

    def _write(self, table: pa.Table, partition: int) -> None:
        comp = None if self.compression == "none" else self.compression
        if self.partition_by:
            # hive-style dynamic partitions: path/key=value/part-....parquet
            pq.write_to_dataset(
                table, root_path=self.path, partition_cols=self.partition_by,
                compression=comp,
                basename_template=f"part-{partition:05d}-{{i}}.parquet")
        else:
            os.makedirs(self.path, exist_ok=True)
            pq.write_table(
                table, os.path.join(self.path, f"part-{partition:05d}.parquet"),
                compression=comp)


class OrcSinkOp(_FileSinkOp):
    name = "orc_sink"

    _ORC_COMPRESSION = {"none": "uncompressed", "snappy": "snappy",
                        "zstd": "zstd", "zlib": "zlib", "lz4": "lz4"}

    def __init__(self, child: PhysicalOp, path: str, compression: str = "zstd"):
        super().__init__(child, path, compression)

    def _write(self, table: pa.Table, partition: int) -> None:
        from pyarrow import orc
        os.makedirs(self.path, exist_ok=True)
        orc.write_table(
            table, os.path.join(self.path, f"part-{partition:05d}.orc"),
            compression=self._ORC_COMPRESSION.get(self.compression,
                                                  self.compression))
