"""ORC scan.

The reference reads ORC with the orc-rust crate through the same JVM
FileSystem wrapper as parquet (reference: datafusion-ext-plans/src/
orc_exec.rs). Here the host side is pyarrow's ORC dataset reader feeding the
same double-buffered host→device on-ramp as the parquet scan — the two scans
share everything but the file format, so OrcScanOp is the generic FileScan
with the format pinned.
"""

from __future__ import annotations

from typing import Optional

from auron_tpu.columnar.schema import Schema
from auron_tpu.io.parquet import ParquetScanOp
from auron_tpu.utils.shapes import DEFAULT_BATCH_CAPACITY


class OrcScanOp(ParquetScanOp):
    name = "orc_scan"
    _format = "orc"

    def __init__(self, files: list[str], schema: Optional[Schema] = None,
                 columns: Optional[list[str]] = None,
                 batch_rows: int = DEFAULT_BATCH_CAPACITY,
                 string_widths: Optional[dict[str, int]] = None):
        # ORC proto node carries no pushed-down predicates (the device
        # filter applies them); dataset-level pruning is parquet-only.
        super().__init__(files, schema=schema, columns=columns,
                         predicates=None, batch_rows=batch_rows,
                         string_widths=string_widths)
