"""Filesystem abstraction for scans, sinks and the shuffle service.

The reference reaches every byte of storage through a JVM Hadoop
FileSystem wrapper (reference: datafusion-ext-commons/src/hadoop_fs.rs,
scan/internal_file_reader.rs, the hadoop-shim module), so one seam serves
local disk, HDFS and object stores. The TPU engine's seam is pyarrow's
FileSystem layer: ``resolve`` maps a URI to (filesystem, fs-local path),
with built-in schemes (file, s3, gs, hdfs) and a registry for custom
providers — the extension point a deployment uses to mount its own store
(the FsProvider role)."""

from __future__ import annotations

from typing import Callable, Optional
from urllib.parse import urlparse

import pyarrow.fs as pafs

#: scheme → factory(netloc) -> (FileSystem, path_prefix)
_PROVIDERS: dict[str, Callable] = {}


def register_filesystem(scheme: str, factory: Callable) -> None:
    """factory(netloc: str) -> (pyarrow.fs.FileSystem, path_prefix: str);
    the fs-local path is path_prefix + uri.path."""
    _PROVIDERS[scheme] = factory


def resolve(path: str) -> tuple[pafs.FileSystem, str]:
    """URI or plain path → (filesystem, fs-local path)."""
    parsed = urlparse(path)
    scheme = parsed.scheme
    if not scheme or len(scheme) == 1:       # plain / windows-drive path
        return pafs.LocalFileSystem(), path
    if scheme in _PROVIDERS:
        fs, prefix = _PROVIDERS[scheme](parsed.netloc)
        return fs, prefix + parsed.path
    if scheme == "file":
        return pafs.LocalFileSystem(), parsed.path
    if scheme == "s3":
        return pafs.S3FileSystem(), parsed.netloc + parsed.path
    if scheme in ("gs", "gcs"):
        return pafs.GcsFileSystem(), parsed.netloc + parsed.path
    if scheme in ("hdfs", "viewfs"):
        host, _, port = parsed.netloc.partition(":")
        return (pafs.HadoopFileSystem(host or "default",
                                      int(port) if port else 8020),
                parsed.path)
    raise NotImplementedError(
        f"no filesystem provider for scheme {scheme!r} "
        f"(register one with auron_tpu.io.fs.register_filesystem)")


def resolve_many(paths: list[str]) -> tuple[Optional[pafs.FileSystem],
                                            list[str]]:
    """One filesystem for a file list (scans require a uniform scheme).
    Returns (None, paths) for plain local paths — pyarrow's default."""
    if not paths:
        return None, paths
    origins = {(urlparse(p).scheme, urlparse(p).netloc) for p in paths}
    if len(origins) > 1:
        raise ValueError(
            f"mixed filesystem origins in one scan: {sorted(origins)} — "
            "one (scheme, host) per scan")
    scheme, _host = origins.pop()
    if not scheme or len(scheme) == 1:
        return None, list(paths)
    resolved = [resolve(p) for p in paths]
    return resolved[0][0], [r[1] for r in resolved]
