"""Parquet scan.

The reference reads parquet through a JVM FileSystem wrapper into DataFusion's
parquet opener with row-group/page pruning (reference: datafusion-ext-plans/
src/parquet_exec.rs:151-237, scan/internal_file_reader.rs). Here the host side
is pyarrow (column pruning + row-group statistics pruning + dictionary-aware
reads) feeding padded DeviceBatches to the TPU; the scan is the host→device
on-ramp, deliberately kept off the device's critical path by the prefetching
worker (``ScanPrefetcher``): while the device crunches batch N, a bounded
background thread decodes and transfers batch N+1 (and beyond, up to
``auron.scan.prefetch_batches``), with the decoded bytes registered with the
memory manager so lookahead degrades to 1 under pressure. With
``auron.pipeline.enabled`` off the scan decodes inline on the query thread —
the fully serial differential baseline.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.dataset as pa_ds
import pyarrow.parquet as pq

from auron_tpu.columnar.arrow_bridge import schema_from_arrow, to_device
from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.columnar.schema import Schema
from auron_tpu.exprs import ir
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.utils.shapes import DEFAULT_BATCH_CAPACITY


def _expr_to_arrow_filter(e: ir.Expr, names: list[str]):
    """Best-effort translation of predicates to pyarrow dataset filters for
    row-group pruning; anything untranslatable is skipped (the device filter
    re-applies everything, so this is pruning-only — same contract as the
    reference's rowgroup pruning, conf.rs:43-46)."""
    import pyarrow.compute as pc
    try:
        if isinstance(e, ir.BinaryExpr) and e.op in ("==", "!=", "<", "<=", ">", ">="):
            l, r = e.left, e.right
            if isinstance(l, ir.ColumnRef) and isinstance(r, ir.Literal):
                f = pc.field(names[l.index])
                v = r.value
                return {"==": f == v, "!=": f != v, "<": f < v,
                        "<=": f <= v, ">": f > v, ">=": f >= v}[e.op]
        if isinstance(e, ir.BinaryExpr) and e.op == "and":
            a = _expr_to_arrow_filter(e.left, names)
            b = _expr_to_arrow_filter(e.right, names)
            if a is not None and b is not None:
                return a & b
            return a if a is not None else b
        if isinstance(e, ir.InList) and isinstance(e.child, ir.ColumnRef) and not e.negated:
            return pc.field(names[e.child.index]).isin(list(e.values))
        if isinstance(e, ir.IsNotNull) and isinstance(e.child, ir.ColumnRef):
            return ~pc.field(names[e.child.index]).is_null()
    except Exception:
        return None
    return None


class ScanPrefetcher:
    """Bounded background decode worker for the file scans.

    One daemon thread drives the decode→transfer iterator and parks the
    resulting DeviceBatches in a bounded buffer; the query thread drains
    it in order, so row-group N+1 decodes while the device computes
    batch N. Three contracts beyond the overlap:

    - **memory**: the buffered decoded bytes are registered with the
      memory manager (a duck-typed MemConsumer named ``scan_prefetch``),
      and the effective lookahead degrades to 1 whenever the pressure
      ladder's shrink rung is active (``advised_batch_rows`` < base) or
      the ladder asked this consumer to ``shrink()`` — prefetch depth is
      the first thing a struggling query gives back;
    - **cancellation**: the consumer polls ``ExecContext.checkpoint``
      while waiting, so a cancel/deadline unwinds within one poll
      interval; ``close()`` stops the worker, drains the buffer, zeroes
      the memmgr accounting and unregisters — a cancel mid-prefetch
      leaks neither consumers nor buffered batches;
    - **errors**: a worker-side exception (decode failure, classified
      memmgr shed) is re-raised on the query thread with its type
      intact.

    Batches arrive in exactly source order — prefetching changes WHEN
    decode happens, never what streams out.
    """

    consumer_name = "scan_prefetch"

    #: consumer-side wait quantum (seconds): bounds cancel latency while
    #: parked on an empty buffer
    _POLL_S = 0.02

    def __init__(self, source, ctx: ExecContext, depth: int):
        self._source = source
        self._ctx = ctx
        self._depth = max(1, int(depth))
        self._cond = threading.Condition()
        self._buf: deque = deque()
        self._bytes = 0
        self._done = False
        self._stop = False
        self._err: Optional[BaseException] = None
        self._degraded = False
        self._mem = ctx.mem_manager
        #: serializes the worker's accounting update against close()'s
        #: unregister, so a slow in-flight update_mem_used (it may walk
        #: the spill loop) can never re-insert an unregistered consumer
        self._mem_lock = threading.Lock()
        if self._mem is not None:
            self._mem.register_consumer(self)
        self._thread = threading.Thread(
            target=self._run, name="auron-scan-prefetch", daemon=True)
        self._thread.start()

    # -- memmgr duck-type ---------------------------------------------------

    def mem_used(self) -> int:
        with self._cond:
            return self._bytes

    def spill(self) -> int:
        """Prefetched batches cannot be released without losing data —
        the prefetcher degrades by shrinking lookahead, not by
        spilling."""
        return 0

    def shrink(self) -> int:
        """Pressure-ladder rung 1: give back the lookahead for the rest
        of this scan (the worker stops refilling past depth 1)."""
        self._degraded = True
        return 0

    def target_depth(self) -> int:
        """Effective lookahead right now: 1 while the memory manager's
        shrink rung is active (or the ladder shrank this consumer),
        else the configured depth."""
        if self._degraded:
            return 1
        mem = self._mem
        if mem is not None:
            fn = getattr(mem, "advised_batch_rows", None)
            if fn is not None and fn(1 << 20) < (1 << 20):
                return 1
        return self._depth

    # -- worker -------------------------------------------------------------

    def _run(self) -> None:
        try:
            for item in self._source:
                with self._cond:
                    while (len(self._buf) >= self.target_depth()
                           and not self._stop):
                        self._cond.wait(self._POLL_S)
                    if self._stop:
                        return
                    self._buf.append(item)
                    self._bytes += item[1]
                    self._cond.notify_all()
                with self._mem_lock:
                    if self._mem is not None and not self._stop:
                        # outside the condition: accounting may spill /
                        # walk the pressure ladder synchronously
                        # (shrink() re-enters on this thread, a flag
                        # set only)
                        self._mem.update_mem_used(self, self.mem_used())
                if self._stop or self._ctx.should_stop:
                    return
        except BaseException as e:   # noqa: BLE001 — forwarded verbatim
            with self._cond:
                self._err = e
                self._cond.notify_all()
        finally:
            with self._cond:
                self._done = True
                self._cond.notify_all()

    # -- consumer -----------------------------------------------------------

    def batches(self, io_time) -> Iterator[DeviceBatch]:
        """Drain in order. The dequeue wait is decode time the worker
        could not hide — attributed to the ``convert`` host bucket like
        the serial path's inline decode."""
        while True:
            with timer(io_time, bucket="convert"):
                with self._cond:
                    while (not self._buf and not self._done
                           and self._err is None):
                        self._cond.wait(self._POLL_S)
                        # surface cancel/deadline/stall while parked
                        self._ctx.checkpoint("scan.prefetch")
                    if self._err is not None:
                        raise self._err
                    if self._buf:
                        batch, nbytes = self._buf.popleft()
                        self._bytes -= nbytes
                        self._cond.notify_all()
                    else:   # done and drained
                        return
            with self._mem_lock:
                if self._mem is not None and not self._stop:
                    self._mem.update_mem_used(self, self.mem_used())
            self._ctx.checkpoint("scan.decode")
            yield batch

    def close(self) -> None:
        """Stop the worker, drop buffered batches, zero the accounting
        and unregister from the memory manager (idempotent)."""
        with self._cond:
            self._stop = True
            self._buf.clear()
            self._bytes = 0
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        with self._mem_lock:
            if self._mem is not None:
                self._mem.unregister_consumer(self)
                self._mem = None


class ParquetScanOp(PhysicalOp):
    name = "parquet_scan"
    #: pyarrow.dataset format — OrcScanOp subclasses with "orc"
    _format = "parquet"
    #: SPMD layout: scan output shards on the batch dim (one map
    #: partition per mesh device — parallel/mesh.buffer_spec)
    mesh_buffer_kind = "scan_batch"

    def __init__(self, files: list[str], schema: Optional[Schema] = None,
                 columns: Optional[list[str]] = None,
                 predicates: Optional[list[ir.Expr]] = None,
                 batch_rows: int = DEFAULT_BATCH_CAPACITY,
                 string_widths: Optional[dict[str, int]] = None):
        self.files = list(files)
        self.columns = columns
        self.predicates = predicates or []
        self.batch_rows = batch_rows
        # remote-FS seam (the reference reads through its JVM Hadoop
        # FileSystem wrapper; here io/fs.py resolves URIs to pyarrow
        # filesystems — hdfs://, s3://, gs://, registered providers)
        from auron_tpu.io.fs import resolve_many
        self._fs, self.files = resolve_many(self.files)
        ds = pa_ds.dataset(self.files, format=self._format,
                           filesystem=self._fs)
        arrow_schema = ds.schema
        if columns:
            arrow_schema = pa.schema([arrow_schema.field(c) for c in columns])
        self._arrow_schema = arrow_schema
        self._schema = schema or schema_from_arrow(arrow_schema)
        self._dataset = ds
        # Pre-size string widths from the data unless caller pinned them, so
        # every batch of a file lands in the same compiled kernel bucket.
        self.string_widths = dict(string_widths or {})

    @property
    def children(self):
        return []

    def schema(self) -> Schema:
        return self._schema

    def _partition_files(self, partition: int, num_partitions: int) -> list[str]:
        return [f for i, f in enumerate(self.files)
                if i % num_partitions == partition]

    def _capacity_for(self, partition: int, files: list[str]) -> int:
        """Conversion capacity for one partition's file set: pinned to
        batch_rows (ONE program shape per scan) but clamped to the
        partition's actual row-count bucket, so a small file never pads
        its batches to the full configured batch size. Metadata-only
        (parquet footers / ORC stripe stats), cached per partition so
        retries don't re-parse footers; falls back to batch_rows when
        the count is unavailable."""
        cache = getattr(self, "_cap_cache", None)
        if cache is None:
            cache = self._cap_cache = {}
        cap = cache.get(partition)
        if cap is not None:
            return cap
        from auron_tpu.utils.shapes import bucket_rows
        cap = self.batch_rows
        try:
            ds = pa_ds.dataset(files, format=self._format,
                               filesystem=self._fs)
            total = ds.count_rows()
            if total:
                cap = min(self.batch_rows, bucket_rows(int(total)))
        except Exception:
            pass
        cache[partition] = cap
        return cap

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        io_time = metrics.counter("io_time")
        files = self._partition_files(partition, max(ctx.num_partitions, 1))

        arrow_filter = None
        for p in self.predicates:
            f = _expr_to_arrow_filter(p, self._schema.names)
            if f is not None:
                arrow_filter = f if arrow_filter is None else (arrow_filter & f)

        def advised_rows(base: int) -> int:
            fn = getattr(ctx.mem_manager, "advised_batch_rows", None) \
                if ctx.mem_manager is not None else None
            return fn(base) if fn is not None else base

        capacity = (self._capacity_for(partition, files)
                    if files else self.batch_rows)

        def host_batches():
            if not files:
                return
            ds = pa_ds.dataset(files, format=self._format,
                               filesystem=self._fs)
            scanner = ds.scanner(columns=self.columns, filter=arrow_filter,
                                 batch_size=self.batch_rows)
            for rb in scanner.to_batches():
                if rb.num_rows == 0:
                    continue
                # split oversized batches (scanner batch_size is a
                # hint); under memory pressure the manager's shrink rung
                # advises smaller slices (memmgr degradation ladder) so
                # the scan stops ramming full-capacity batches into a
                # budget that just denied
                rows = advised_rows(self.batch_rows)
                for off in range(0, rb.num_rows, rows):
                    yield rb.slice(off, min(rows, rb.num_rows - off))

        def convert(rb):
            # capacity stays pinned per scan unless the pressure ladder
            # shrank the slices — smaller capacity is the point then
            from auron_tpu.utils.shapes import bucket_rows
            cap = capacity
            if rb.num_rows < cap and advised_rows(cap) < cap:
                cap = bucket_rows(rb.num_rows)
            return to_device(rb, capacity=cap,
                             string_widths=self._widths_for(rb))[0]

        from auron_tpu.runtime import pipeline
        if not pipeline.enabled():
            # serial baseline: decode → transfer inline on the query
            # thread (the differential twin the pipelined==serial
            # battery compares against)
            def stream():
                for rb in host_batches():
                    ctx.checkpoint("scan.decode")
                    with timer(io_time, bucket="convert"):
                        yield convert(rb)

            return count_output(stream(), metrics, timed=True)

        from auron_tpu import config as cfg
        depth = max(1, int(ctx.conf.get(cfg.SCAN_PREFETCH_BATCHES)))

        def decoded():
            from auron_tpu.columnar.batch import batch_nbytes
            for rb in host_batches():
                batch = convert(rb)
                # account the DEVICE footprint of what sits in the
                # buffer (padded to capacity), not the smaller Arrow
                # slice it came from — under-reporting would hide the
                # prefetch buffer from the pressure ladder
                yield batch, batch_nbytes(batch)

        def stream():
            pf = ScanPrefetcher(decoded(), ctx, depth)
            try:
                for batch in pf.batches(io_time):
                    yield batch
            finally:
                pf.close()

        return count_output(stream(), metrics, timed=True)

    def _widths_for(self, rb: pa.RecordBatch) -> dict[str, int]:
        """Stable width buckets per string column, learned once per scan from
        parquet statistics / first batch and then pinned."""
        import pyarrow.compute as pc
        from auron_tpu.utils.shapes import bucket_string_width
        widths = self.string_widths
        for i, f in enumerate(rb.schema):
            if pa.types.is_string(f.type) or pa.types.is_large_string(f.type):
                if f.name not in widths:
                    col = rb.column(i)
                    max_len = pc.max(pc.binary_length(col)).as_py() or 1
                    widths[f.name] = bucket_string_width(max(max_len, 1))
                else:
                    col = rb.column(i)
                    max_len = pc.max(pc.binary_length(col)).as_py() or 0
                    if max_len > widths[f.name]:
                        widths[f.name] = bucket_string_width(max_len)
        return widths

    def __repr__(self):
        return f"{type(self).__name__}[{len(self.files)} files]"


class MemoryScanOp(PhysicalOp):
    """In-memory source (tests and broadcast-side plumbing)."""

    name = "memory_scan"
    mesh_buffer_kind = "scan_batch"   # SPMD layout: shard on batch dim

    def __init__(self, partitions: list[list[pa.RecordBatch]], schema: Schema,
                 capacity: int = DEFAULT_BATCH_CAPACITY,
                 string_widths: Optional[dict[str, int]] = None):
        self.partitions = partitions
        self._schema = schema
        self.capacity = capacity
        self.string_widths = string_widths

    @property
    def children(self):
        return []

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)

        def stream():
            for rb in self.partitions[partition]:
                if rb.num_rows:
                    yield to_device(rb, capacity=self.capacity,
                                    string_widths=self.string_widths)[0]

        return count_output(stream(), metrics, timed=True)


class DeviceBatchScanOp(PhysicalOp):
    """Source over already-device-resident batches (shuffle-read side)."""

    name = "device_scan"
    #: replays stored batches (broadcast builds, resource maps) that
    #: later readers share — consumers must never donate them
    owns_output = False
    #: SPMD layout: replayed shared batches behave like broadcast
    #: relations — every shard reads them whole
    mesh_buffer_kind = "broadcast"

    def __init__(self, partitions, schema: Schema):
        self.partitions = partitions  # list[list[DeviceBatch]] or callable
        self._schema = schema

    @property
    def children(self):
        return []

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        parts = self.partitions(partition) if callable(self.partitions) \
            else self.partitions[partition]
        metrics = ctx.metrics_for(self)
        return count_output(iter(parts), metrics, timed=True)
