"""Parquet scan.

The reference reads parquet through a JVM FileSystem wrapper into DataFusion's
parquet opener with row-group/page pruning (reference: datafusion-ext-plans/
src/parquet_exec.rs:151-237, scan/internal_file_reader.rs). Here the host side
is pyarrow (column pruning + row-group statistics pruning + dictionary-aware
reads) feeding padded DeviceBatches to the TPU; the scan is the host→device
on-ramp, deliberately kept off the device's critical path via double
buffering: while the device crunches batch N, pyarrow decodes batch N+1.
"""

from __future__ import annotations

import concurrent.futures
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.dataset as pa_ds
import pyarrow.parquet as pq

from auron_tpu.columnar.arrow_bridge import schema_from_arrow, to_device
from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.columnar.schema import Schema
from auron_tpu.exprs import ir
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.utils.shapes import DEFAULT_BATCH_CAPACITY


def _expr_to_arrow_filter(e: ir.Expr, names: list[str]):
    """Best-effort translation of predicates to pyarrow dataset filters for
    row-group pruning; anything untranslatable is skipped (the device filter
    re-applies everything, so this is pruning-only — same contract as the
    reference's rowgroup pruning, conf.rs:43-46)."""
    import pyarrow.compute as pc
    try:
        if isinstance(e, ir.BinaryExpr) and e.op in ("==", "!=", "<", "<=", ">", ">="):
            l, r = e.left, e.right
            if isinstance(l, ir.ColumnRef) and isinstance(r, ir.Literal):
                f = pc.field(names[l.index])
                v = r.value
                return {"==": f == v, "!=": f != v, "<": f < v,
                        "<=": f <= v, ">": f > v, ">=": f >= v}[e.op]
        if isinstance(e, ir.BinaryExpr) and e.op == "and":
            a = _expr_to_arrow_filter(e.left, names)
            b = _expr_to_arrow_filter(e.right, names)
            if a is not None and b is not None:
                return a & b
            return a if a is not None else b
        if isinstance(e, ir.InList) and isinstance(e.child, ir.ColumnRef) and not e.negated:
            return pc.field(names[e.child.index]).isin(list(e.values))
        if isinstance(e, ir.IsNotNull) and isinstance(e.child, ir.ColumnRef):
            return ~pc.field(names[e.child.index]).is_null()
    except Exception:
        return None
    return None


class ParquetScanOp(PhysicalOp):
    name = "parquet_scan"
    #: pyarrow.dataset format — OrcScanOp subclasses with "orc"
    _format = "parquet"

    def __init__(self, files: list[str], schema: Optional[Schema] = None,
                 columns: Optional[list[str]] = None,
                 predicates: Optional[list[ir.Expr]] = None,
                 batch_rows: int = DEFAULT_BATCH_CAPACITY,
                 string_widths: Optional[dict[str, int]] = None):
        self.files = list(files)
        self.columns = columns
        self.predicates = predicates or []
        self.batch_rows = batch_rows
        # remote-FS seam (the reference reads through its JVM Hadoop
        # FileSystem wrapper; here io/fs.py resolves URIs to pyarrow
        # filesystems — hdfs://, s3://, gs://, registered providers)
        from auron_tpu.io.fs import resolve_many
        self._fs, self.files = resolve_many(self.files)
        ds = pa_ds.dataset(self.files, format=self._format,
                           filesystem=self._fs)
        arrow_schema = ds.schema
        if columns:
            arrow_schema = pa.schema([arrow_schema.field(c) for c in columns])
        self._arrow_schema = arrow_schema
        self._schema = schema or schema_from_arrow(arrow_schema)
        self._dataset = ds
        # Pre-size string widths from the data unless caller pinned them, so
        # every batch of a file lands in the same compiled kernel bucket.
        self.string_widths = dict(string_widths or {})

    @property
    def children(self):
        return []

    def schema(self) -> Schema:
        return self._schema

    def _partition_files(self, partition: int, num_partitions: int) -> list[str]:
        return [f for i, f in enumerate(self.files)
                if i % num_partitions == partition]

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        io_time = metrics.counter("io_time")
        files = self._partition_files(partition, max(ctx.num_partitions, 1))

        names = self._arrow_schema.names
        arrow_filter = None
        for p in self.predicates:
            f = _expr_to_arrow_filter(p, self._schema.names)
            if f is not None:
                arrow_filter = f if arrow_filter is None else (arrow_filter & f)

        def advised_rows(base: int) -> int:
            fn = getattr(ctx.mem_manager, "advised_batch_rows", None) \
                if ctx.mem_manager is not None else None
            return fn(base) if fn is not None else base

        def host_batches():
            if not files:
                return
            ds = pa_ds.dataset(files, format=self._format,
                               filesystem=self._fs)
            scanner = ds.scanner(columns=self.columns, filter=arrow_filter,
                                 batch_size=self.batch_rows)
            for rb in scanner.to_batches():
                if rb.num_rows == 0:
                    continue
                # split oversized batches (scanner batch_size is a
                # hint); under memory pressure the manager's shrink rung
                # advises smaller slices (memmgr degradation ladder) so
                # the scan stops ramming full-capacity batches into a
                # budget that just denied
                rows = advised_rows(self.batch_rows)
                for off in range(0, rb.num_rows, rows):
                    ctx.checkpoint("scan.decode")
                    yield rb.slice(off, min(rows, rb.num_rows - off))

        def stream():
            # Double buffering: decode/transfer next batch while caller
            # computes on the current one.
            with concurrent.futures.ThreadPoolExecutor(1) as pool:
                it = host_batches()

                def convert(rb):
                    # capacity stays pinned to batch_rows (ONE program
                    # shape per scan) unless the pressure ladder shrank
                    # the slices — smaller capacity is the point then
                    from auron_tpu.utils.shapes import bucket_rows
                    cap = self.batch_rows
                    if rb.num_rows < cap and advised_rows(cap) < cap:
                        cap = bucket_rows(rb.num_rows)
                    return to_device(rb, capacity=cap,
                                     string_widths=self._widths_for(rb))[0]

                pending = None
                for rb in it:
                    nxt = pool.submit(convert, rb)
                    if pending is not None:
                        with timer(io_time, bucket="convert"):
                            yield pending.result()
                    pending = nxt
                if pending is not None:
                    with timer(io_time, bucket="convert"):
                        yield pending.result()

        return count_output(stream(), metrics, timed=True)

    def _widths_for(self, rb: pa.RecordBatch) -> dict[str, int]:
        """Stable width buckets per string column, learned once per scan from
        parquet statistics / first batch and then pinned."""
        import pyarrow.compute as pc
        from auron_tpu.utils.shapes import bucket_string_width
        widths = self.string_widths
        for i, f in enumerate(rb.schema):
            if pa.types.is_string(f.type) or pa.types.is_large_string(f.type):
                if f.name not in widths:
                    col = rb.column(i)
                    max_len = pc.max(pc.binary_length(col)).as_py() or 1
                    widths[f.name] = bucket_string_width(max(max_len, 1))
                else:
                    col = rb.column(i)
                    max_len = pc.max(pc.binary_length(col)).as_py() or 0
                    if max_len > widths[f.name]:
                        widths[f.name] = bucket_string_width(max_len)
        return widths

    def __repr__(self):
        return f"{type(self).__name__}[{len(self.files)} files]"


class MemoryScanOp(PhysicalOp):
    """In-memory source (tests and broadcast-side plumbing)."""

    name = "memory_scan"

    def __init__(self, partitions: list[list[pa.RecordBatch]], schema: Schema,
                 capacity: int = DEFAULT_BATCH_CAPACITY,
                 string_widths: Optional[dict[str, int]] = None):
        self.partitions = partitions
        self._schema = schema
        self.capacity = capacity
        self.string_widths = string_widths

    @property
    def children(self):
        return []

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)

        def stream():
            for rb in self.partitions[partition]:
                if rb.num_rows:
                    yield to_device(rb, capacity=self.capacity,
                                    string_widths=self.string_widths)[0]

        return count_output(stream(), metrics, timed=True)


class DeviceBatchScanOp(PhysicalOp):
    """Source over already-device-resident batches (shuffle-read side)."""

    name = "device_scan"
    #: replays stored batches (broadcast builds, resource maps) that
    #: later readers share — consumers must never donate them
    owns_output = False

    def __init__(self, partitions, schema: Schema):
        self.partitions = partitions  # list[list[DeviceBatch]] or callable
        self._schema = schema

    @property
    def children(self):
        return []

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        parts = self.partitions(partition) if callable(self.partitions) \
            else self.partitions[partition]
        metrics = ctx.metrics_for(self)
        return count_output(iter(parts), metrics, timed=True)
