"""Native-coverage reporting — the Spark UI tab analogue.

The reference ships a Spark UI plugin visualizing, per query, which plan
nodes ran natively and which fell back to the host engine (reference:
auron-spark-ui/.../AuronSQLAppStatusListener.scala + the React/ECharts
front-end). This engine is host-UI-less, so the same information renders
as a markdown/JSON report from the converter's ConversionReport tags —
suitable for CI artifacts and terminal review.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass
class QueryCoverage:
    name: str
    tags: list                      # (node class, ok, reason)

    @property
    def native(self) -> int:
        return sum(1 for _c, ok, _r in self.tags if ok)

    @property
    def fallback(self) -> int:
        return sum(1 for _c, ok, _r in self.tags if not ok)

    @property
    def pct(self) -> float:
        total = len(self.tags)
        return 100.0 * self.native / total if total else 100.0


@dataclass
class CoverageReport:
    queries: list = field(default_factory=list)

    def add(self, name: str, conversion_report) -> QueryCoverage:
        qc = QueryCoverage(name, list(conversion_report.tags))
        self.queries.append(qc)
        return qc

    # -- renderers -----------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "queries": [{
                "name": q.name,
                "native_nodes": q.native,
                "fallback_nodes": q.fallback,
                "native_pct": round(q.pct, 1),
                "fallbacks": [
                    {"node": c, "reason": r}
                    for c, ok, r in q.tags if not ok],
            } for q in self.queries],
            "overall_native_pct": round(self.overall_pct, 1),
        }, indent=2)

    @property
    def overall_pct(self) -> float:
        total = sum(len(q.tags) for q in self.queries)
        native = sum(q.native for q in self.queries)
        return 100.0 * native / total if total else 100.0

    def to_markdown(self) -> str:
        lines = ["# Native coverage", "",
                 f"Overall: {self.overall_pct:.1f}% of plan nodes native",
                 "",
                 "| Query | Native | Fallback | Coverage |",
                 "|---|---|---|---|"]
        for q in self.queries:
            lines.append(f"| {q.name} | {q.native} | {q.fallback} "
                         f"| {q.pct:.1f}% |")
        fb = [(q.name, c, r) for q in self.queries
              for c, ok, r in q.tags if not ok]
        if fb:
            lines += ["", "## Fallback reasons", ""]
            for name, c, r in fb:
                lines.append(f"- **{name}** `{c}`: {r or 'unconvertible'}")
        return "\n".join(lines)
