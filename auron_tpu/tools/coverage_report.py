"""Native-coverage reporting — the Spark UI tab analogue.

The reference ships a Spark UI plugin visualizing, per query, which plan
nodes ran natively and which fell back to the host engine (reference:
auron-spark-ui/.../AuronSQLAppStatusListener.scala + the React/ECharts
front-end). This engine is host-UI-less, so the same information renders
as a markdown/JSON report from the converter's ConversionReport tags —
suitable for CI artifacts and terminal review.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass
class QueryCoverage:
    name: str
    tags: list                      # (node class, ok, reason)

    @property
    def native(self) -> int:
        return sum(1 for _c, ok, _r in self.tags if ok)

    @property
    def fallback(self) -> int:
        return sum(1 for _c, ok, _r in self.tags if not ok)

    @property
    def pct(self) -> float:
        total = len(self.tags)
        return 100.0 * self.native / total if total else 100.0


@dataclass
class CoverageReport:
    queries: list = field(default_factory=list)

    def add(self, name: str, conversion_report) -> QueryCoverage:
        qc = QueryCoverage(name, list(conversion_report.tags))
        self.queries.append(qc)
        return qc

    # -- renderers -----------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "queries": [{
                "name": q.name,
                "native_nodes": q.native,
                "fallback_nodes": q.fallback,
                "native_pct": round(q.pct, 1),
                "fallbacks": [
                    {"node": c, "reason": r}
                    for c, ok, r in q.tags if not ok],
            } for q in self.queries],
            "overall_native_pct": round(self.overall_pct, 1),
        }, indent=2)

    @property
    def overall_pct(self) -> float:
        total = sum(len(q.tags) for q in self.queries)
        native = sum(q.native for q in self.queries)
        return 100.0 * native / total if total else 100.0

    def to_markdown(self) -> str:
        lines = ["# Native coverage", "",
                 f"Overall: {self.overall_pct:.1f}% of plan nodes native",
                 "",
                 "| Query | Native | Fallback | Coverage |",
                 "|---|---|---|---|"]
        for q in self.queries:
            lines.append(f"| {q.name} | {q.native} | {q.fallback} "
                         f"| {q.pct:.1f}% |")
        fb = [(q.name, c, r) for q in self.queries
              for c, ok, r in q.tags if not ok]
        if fb:
            lines += ["", "## Fallback reasons", ""]
            for name, c, r in fb:
                lines.append(f"- **{name}** `{c}`: {r or 'unconvertible'}")
        return "\n".join(lines)

    def to_html(self) -> str:
        """Self-contained static page — the Spark-UI Auron tab analogue
        (reference: auron-spark-ui/src/ui React/ECharts front-end showing
        native vs fallback plan coverage). No external assets: inline CSS
        + SVG bars, so the file works as a CI artifact or `file://`
        open."""
        import html as _html

        def bar(pct: float) -> str:
            w = max(0.0, min(100.0, pct))
            color = "#2da44e" if w >= 99.5 else (
                "#bf8700" if w >= 80 else "#cf222e")
            return (f'<svg width="160" height="12" role="img">'
                    f'<rect width="160" height="12" fill="#eee" rx="2"/>'
                    f'<rect width="{w * 1.6:.1f}" height="12" '
                    f'fill="{color}" rx="2"/></svg> {w:.1f}%')

        rows = []
        for q in self.queries:
            fb = "".join(
                f"<li><code>{_html.escape(c)}</code> "
                f"{_html.escape(r or 'unconvertible')}</li>"
                for c, ok, r in q.tags if not ok)
            rows.append(
                f"<tr><td>{_html.escape(q.name)}</td>"
                f"<td>{q.native}</td><td>{q.fallback}</td>"
                f"<td>{bar(q.pct)}</td>"
                f"<td>{('<ul>' + fb + '</ul>') if fb else '—'}</td></tr>")
        return f"""<!doctype html>
<html><head><meta charset="utf-8"><title>Auron native coverage</title>
<style>
 body {{ font: 14px/1.5 system-ui, sans-serif; margin: 2rem; }}
 table {{ border-collapse: collapse; width: 100%; }}
 th, td {{ border: 1px solid #ddd; padding: 6px 10px;
           text-align: left; vertical-align: top; }}
 th {{ background: #f6f8fa; }}
 .overall {{ font-size: 1.2rem; margin-bottom: 1rem; }}
 ul {{ margin: 0; padding-left: 1.2rem; }}
</style></head><body>
<h1>Native plan coverage</h1>
<p class="overall">Overall: {bar(self.overall_pct)} of plan nodes
executed natively ({len(self.queries)} queries)</p>
<table><tr><th>Query</th><th>Native</th><th>Fallback</th>
<th>Coverage</th><th>Fallback reasons</th></tr>
{''.join(rows)}
</table></body></html>
"""

    def write_html(self, path: str) -> str:
        # explicit utf-8: CI runners with C/POSIX locales would otherwise
        # raise on non-ASCII node names despite the page's charset
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_html())
        return path
