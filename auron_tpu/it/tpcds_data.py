"""Synthetic TPC-DS-class dataset generator.

Five tables with the TPC-DS store-sales star-schema shape (fact table +
customer/item/store/date dims), written as multi-file parquet so scans
have real input splits. Sizes are driven by ``scale`` (1.0 ≈ 120k fact
rows — enough to exercise multi-batch execution, exchanges, and two-phase
aggregation while keeping the pandas oracle fast). Deterministic per
(seed, scale).

Reference dataset: the 1 GB TPC-DS checkout the reference's CI runs
(reference: .github/workflows/tpcds-reusable.yml:255-258).
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

FACT_FILES = 4


def generate(root: str, scale: float = 1.0, seed: int = 42) -> dict:
    """Write the dataset under ``root``; returns {table: [files]}."""
    rng = np.random.default_rng(seed)
    n_sales = int(120_000 * scale)
    n_customers = int(4_000 * scale) or 1
    n_items = int(1_000 * scale) or 1
    n_stores = max(int(12 * scale), 2)
    n_dates = 730   # two years

    os.makedirs(root, exist_ok=True)
    out: dict[str, list[str]] = {}

    # -- dims ---------------------------------------------------------------
    states = np.array(["CA", "TX", "NY", "WA", "GA", "OH", "IL", "MI"])
    customer = pa.table({
        "c_customer_sk": pa.array(np.arange(n_customers), pa.int64()),
        "c_birth_year": pa.array(
            rng.integers(1930, 2005, n_customers), pa.int64()),
        "c_state": pa.array(states[rng.integers(0, len(states),
                                                n_customers)]),
        # ~2% null emails exercise null join/agg semantics
        "c_email": pa.array(
            [None if rng.random() < 0.02 else f"c{i}@example.com"
             for i in range(n_customers)], pa.string()),
    })

    cats = np.array(["Books", "Music", "Shoes", "Home", "Sports",
                     "Electronics", "Jewelry"])
    item = pa.table({
        "i_item_sk": pa.array(np.arange(n_items), pa.int64()),
        "i_category": pa.array(cats[rng.integers(0, len(cats), n_items)]),
        "i_brand": pa.array([f"brand#{b:03d}" for b in
                             rng.integers(0, 50, n_items)], pa.string()),
        "i_current_price": pa.array(
            np.round(rng.uniform(0.5, 300.0, n_items), 2), pa.float64()),
    })

    store = pa.table({
        "s_store_sk": pa.array(np.arange(n_stores), pa.int64()),
        "s_state": pa.array(states[rng.integers(0, len(states), n_stores)]),
        "s_number_employees": pa.array(
            rng.integers(50, 300, n_stores), pa.int64()),
    })

    date_dim = pa.table({
        "d_date_sk": pa.array(np.arange(n_dates), pa.int64()),
        "d_year": pa.array(2000 + (np.arange(n_dates) // 365), pa.int64()),
        "d_moy": pa.array(1 + (np.arange(n_dates) % 365) // 31, pa.int64()),
    })

    # -- fact ---------------------------------------------------------------
    qty = rng.integers(1, 20, n_sales)
    price = np.round(rng.uniform(0.5, 300.0, n_sales), 2)
    profit = np.round(rng.normal(5.0, 40.0, n_sales), 2)
    # ~1.5% of net_paid is NULL (returns in flight)
    paid_null = rng.random(n_sales) < 0.015
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(0, n_dates, n_sales), pa.int64()),
        "ss_customer_sk": pa.array(
            rng.integers(0, n_customers, n_sales), pa.int64()),
        "ss_item_sk": pa.array(rng.integers(0, n_items, n_sales), pa.int64()),
        "ss_store_sk": pa.array(
            rng.integers(0, n_stores, n_sales), pa.int64()),
        "ss_quantity": pa.array(qty, pa.int64()),
        "ss_sales_price": pa.array(price, pa.float64()),
        "ss_net_profit": pa.array(profit, pa.float64()),
        "ss_net_paid": pa.array(np.where(paid_null, np.nan, price * qty),
                                pa.float64(), mask=paid_null),
    })

    def write(name: str, tbl: pa.Table, n_files: int = 1):
        files = []
        rows = tbl.num_rows
        per = (rows + n_files - 1) // n_files
        for i in range(n_files):
            path = os.path.join(root, f"{name}_{i}.parquet")
            pq.write_table(tbl.slice(i * per, per), path)
            files.append(path)
        out[name] = files

    write("store_sales", store_sales, FACT_FILES)
    write("customer", customer)
    write("item", item)
    write("store", store)
    write("date_dim", date_dim)
    return out


def load_pandas(tables: dict) -> dict:
    """The oracle's view: every table as a pandas DataFrame."""
    import pandas as pd
    out = {}
    for name, files in tables.items():
        out[name] = pa.concat_tables(
            [pq.read_table(f) for f in files]).to_pandas()
    return out
