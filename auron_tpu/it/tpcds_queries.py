"""Real TPC-DS queries over the real-schema dataset (tpcds.py).

99 genuine TPC-DS query shapes — star joins, multi-dimension filters,
two-phase aggregation, CASE buckets, scalar subqueries, EXISTS/IN as
semi/anti joins, ROLLUP/grouping-sets with grouping_id arithmetic,
three-channel UNIONs, and window ratios — expressed in the frontend
DataFrame DSL (which lowers to protobuf plans and runs the full engine
pipeline) and diffed against an INDEPENDENT pyarrow/Acero (or pandas)
oracle (DuckDB is not in this image). Query parameters are substituted
to match the generated data's value domains, exactly as dsdgen's
templates substitute parameters — and auto-tuned so every query returns
rows at CI scale (an empty result proves nothing about a query).

Reference gate being mirrored: all-99-query TPC-DS diff vs vanilla Spark
(reference: .github/workflows/tpcds-reusable.yml:70-83,
dev/auron-it/.../QueryResultComparator.scala:21-100).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from auron_tpu.columnar.schema import DataType
from auron_tpu.frontend.dataframe import (col, functions as F, lit,
                                          scalar_subquery)

DATE_SK0 = 2450815


@dataclass(frozen=True)
class Query:
    name: str
    description: str
    run: Callable      # (session, tables) -> pa.Table
    oracle: Callable   # (arrow_tables: {name: pa.Table}) -> pa.Table


QUERIES: list[Query] = []


def _q(name, description):
    def deco(fns):
        run, oracle = fns
        QUERIES.append(Query(name, description, run, oracle))
        return fns
    return deco


def _rd(s, t, name, partitions=1):
    parts = 4 if name in ("store_sales", "catalog_sales", "web_sales",
                          "store_returns", "inventory") else partitions
    return s.read_parquet(t[name], partitions=parts)


def _rename(df, **kw):
    """Rename columns (old=new) via a full-width select."""
    cols = []
    for f in df.schema:
        nm = kw.get(f.name, f.name)
        cols.append(col(f.name).alias(nm))
    return df.select(*cols)


def _join_dim(fact, dim, fact_key, dim_key, how="inner"):
    """fact ⋈ dim on fact.fact_key == dim.dim_key (USING-style: the dim
    key column is renamed to the fact key name and dropped after)."""
    return fact.join(_rename(dim, **{dim_key: fact_key}), on=fact_key,
                     how=how)


# --- oracle helpers (pyarrow / Acero) --------------------------------------

def _oj(a, b, left, right=None, how="inner"):
    right = right or left
    return a.join(b, keys=left, right_keys=right, join_type=how)


def _agg(t, keys, aggs, names=None):
    """group_by + aggregate with explicit output names."""
    res = t.group_by(keys, use_threads=False).aggregate(aggs)
    if names:
        res = res.rename_columns(list(res.column_names[:len(keys)])
                                 if False else
                                 [*names.get("keys", keys), *names["aggs"]]
                                 if isinstance(names, dict) else names)
    return res


def _topn(t, sort_keys, n=100):
    idx = pc.sort_indices(t, sort_keys=sort_keys)
    return t.take(idx.slice(0, n))




def _channel_buyers(s, t, dd):
    """(web, catalog) buyer frames for the 3-channel EXISTS queries
    (q10/q35/q69): each is the period's bill-customer keys aliased to
    c_customer_sk, ready for semi/anti/existence joins."""
    wbuy = _join_dim(
        _rd(s, t, "web_sales").select("ws_bill_customer_sk",
                                      "ws_sold_date_sk"),
        dd, "ws_sold_date_sk", "d_date_sk") \
        .select(col("ws_bill_customer_sk").alias("c_customer_sk"))
    cbuy = _join_dim(
        _rd(s, t, "catalog_sales").select("cs_bill_customer_sk",
                                          "cs_sold_date_sk"),
        dd, "cs_sold_date_sk", "d_date_sk") \
        .select(col("cs_bill_customer_sk").alias("c_customer_sk"))
    return wbuy, cbuy


def _oracle_channel_custs(a, dd):
    """Oracle twin of _channel_buyers: the set of customers with web or
    catalog activity in the period (dd = filtered date_dim table)."""
    ws = _oj(a["web_sales"], dd, ["ws_sold_date_sk"], ["d_date_sk"])
    cs = _oj(a["catalog_sales"], dd, ["cs_sold_date_sk"], ["d_date_sk"])
    wset = set(ws.to_pandas().ws_bill_customer_sk.dropna().astype(int))
    cset = set(cs.to_pandas().cs_bill_customer_sk.dropna().astype(int))
    return wset, cset


# ===========================================================================
# q3: ss ⋈ date_dim ⋈ item, manufacturer filter, yearly brand revenue
# ===========================================================================

def _q3_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_ext_sales_price")
    dd = _rd(s, t, "date_dim").filter(col("d_moy") == 11) \
        .select("d_date_sk", "d_year")
    it = _rd(s, t, "item").filter(col("i_manufact_id") == 128) \
        .select("i_item_sk", "i_brand_id", "i_brand")
    j = _join_dim(_join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk"),
                  it, "ss_item_sk", "i_item_sk")
    return (j.group_by("d_year", "i_brand_id", "i_brand")
            .agg(F.sum(col("ss_ext_sales_price")).alias("sum_agg"))
            .sort(col("d_year").asc(), col("sum_agg").desc(),
                  col("i_brand_id").asc())
            .limit(100).collect())


def _q3_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_moy"], 11)) \
        .select(["d_date_sk", "d_year"])
    it = a["item"].filter(pc.equal(a["item"]["i_manufact_id"], 128)) \
        .select(["i_item_sk", "i_brand_id", "i_brand"])
    j = _oj(_oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"]),
            it, ["ss_item_sk"], ["i_item_sk"])
    g = j.group_by(["d_year", "i_brand_id", "i_brand"]).aggregate(
        [("ss_ext_sales_price", "sum")]) \
        .rename_columns(["d_year", "i_brand_id", "i_brand", "sum_agg"])
    return _topn(g, [("d_year", "ascending"), ("sum_agg", "descending"),
                     ("i_brand_id", "ascending")])


_q("q3", "yearly brand revenue for one manufacturer in November")(
    (_q3_run, _q3_oracle))


# ===========================================================================
# q42: dd ⋈ ss ⋈ item, category revenue for one month
# ===========================================================================

def _cat_month_revenue(attr_id, attr, flt_col, flt_val):
    def run(s, t):
        ss = _rd(s, t, "store_sales").select("ss_sold_date_sk",
                                             "ss_item_sk",
                                             "ss_ext_sales_price")
        dd = _rd(s, t, "date_dim") \
            .filter((col("d_moy") == 11) & (col("d_year") == 2000)) \
            .select("d_date_sk", "d_year")
        it = _rd(s, t, "item").filter(col(flt_col) == flt_val) \
            .select("i_item_sk", attr_id, attr)
        j = _join_dim(_join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk"),
                      it, "ss_item_sk", "i_item_sk")
        return (j.group_by("d_year", attr_id, attr)
                .agg(F.sum(col("ss_ext_sales_price")).alias("sum_agg"))
                .sort(col("sum_agg").desc(), col(attr_id).asc())
                .limit(100).collect())

    def oracle(a):
        dd = a["date_dim"].filter(
            pc.and_(pc.equal(a["date_dim"]["d_moy"], 11),
                    pc.equal(a["date_dim"]["d_year"], 2000))) \
            .select(["d_date_sk", "d_year"])
        it = a["item"].filter(pc.equal(a["item"][flt_col], flt_val)) \
            .select(["i_item_sk", attr_id, attr])
        j = _oj(_oj(a["store_sales"], dd, ["ss_sold_date_sk"],
                    ["d_date_sk"]), it, ["ss_item_sk"], ["i_item_sk"])
        g = j.group_by(["d_year", attr_id, attr]).aggregate(
            [("ss_ext_sales_price", "sum")]) \
            .rename_columns(["d_year", attr_id, attr, "sum_agg"])
        return _topn(g, [("sum_agg", "descending"),
                         (attr_id, "ascending")])
    return run, oracle


_q("q42", "category revenue, one month, manager slice")(
    _cat_month_revenue("i_category_id", "i_category", "i_manager_id", 1))
_q("q52", "brand revenue, one month, manager slice")(
    _cat_month_revenue("i_brand_id", "i_brand", "i_manager_id", 1))
_q("q55", "brand revenue for one manager's items")(
    _cat_month_revenue("i_brand_id", "i_brand", "i_manager_id", 28))


# ===========================================================================
# q7: ss ⋈ cd ⋈ dd ⋈ item ⋈ promotion — demographic averages per item
# ===========================================================================

def _q7_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk", "ss_promo_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price")
    cd = _rd(s, t, "customer_demographics").filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College")) \
        .select("cd_demo_sk")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    pr = _rd(s, t, "promotion").filter(col("p_channel_email") == "N") \
        .select("p_promo_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id")
    j = _join_dim(ss, cd, "ss_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, pr, "ss_promo_sk", "p_promo_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    return (j.group_by("i_item_id")
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"))
            .sort(col("i_item_id").asc()).limit(100).collect())


def _q7_oracle(a):
    cd = a["customer_demographics"]
    cd = cd.filter(pc.and_(pc.and_(
        pc.equal(cd["cd_gender"], "M"),
        pc.equal(cd["cd_marital_status"], "S")),
        pc.equal(cd["cd_education_status"], "College"))) \
        .select(["cd_demo_sk"])
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk"])
    pr = a["promotion"].filter(
        pc.equal(a["promotion"]["p_channel_email"], "N")) \
        .select(["p_promo_sk"])
    it = a["item"].select(["i_item_sk", "i_item_id"])
    j = _oj(a["store_sales"], cd, ["ss_cdemo_sk"], ["cd_demo_sk"])
    j = _oj(j, dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, pr, ["ss_promo_sk"], ["p_promo_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    for c in ("ss_list_price", "ss_coupon_amt", "ss_sales_price"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = j.group_by(["i_item_id"]).aggregate(
        [("ss_quantity", "mean"), ("ss_list_price", "mean"),
         ("ss_coupon_amt", "mean"), ("ss_sales_price", "mean")]) \
        .rename_columns(["i_item_id", "agg1", "agg2", "agg3", "agg4"])
    return _topn(g, [("i_item_id", "ascending")])


_q("q7", "demographic purchase averages per item")((_q7_run, _q7_oracle))


# ===========================================================================
# q19: brand revenue where customer and store are in different zip areas
# ===========================================================================

def _q19_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_ext_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_moy") == 11) & (col("d_year") == 1999)) \
        .select("d_date_sk")
    it = _rd(s, t, "item").filter(col("i_manager_id") == 8) \
        .select("i_item_sk", "i_brand_id", "i_brand", "i_manufact_id",
                "i_manufact")
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_current_addr_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_zip")
    st = _rd(s, t, "store").select("s_store_sk", "s_zip")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    j = _join_dim(j, cu, "ss_customer_sk", "c_customer_sk")
    j = _join_dim(j, ca, "c_current_addr_sk", "ca_address_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = j.filter(F.substring(col("ca_zip"), lit(1), lit(5))
                 != F.substring(col("s_zip"), lit(1), lit(5)))
    return (j.group_by("i_brand_id", "i_brand", "i_manufact_id",
                       "i_manufact")
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .sort(col("ext_price").desc(), col("i_brand_id").asc())
            .limit(100).collect())


def _q19_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_moy"], 11),
        pc.equal(a["date_dim"]["d_year"], 1999))).select(["d_date_sk"])
    it = a["item"].filter(pc.equal(a["item"]["i_manager_id"], 8)) \
        .select(["i_item_sk", "i_brand_id", "i_brand", "i_manufact_id",
                 "i_manufact"])
    cu = a["customer"].select(["c_customer_sk", "c_current_addr_sk"])
    ca = a["customer_address"].select(["ca_address_sk", "ca_zip"])
    st = a["store"].select(["s_store_sk", "s_zip"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    j = _oj(j, cu, ["ss_customer_sk"], ["c_customer_sk"])
    j = _oj(j, ca, ["c_current_addr_sk"], ["ca_address_sk"])
    j = _oj(j, st, ["ss_store_sk"], ["s_store_sk"])
    j = j.filter(pc.not_equal(pc.utf8_slice_codeunits(j["ca_zip"], 0, 5),
                              pc.utf8_slice_codeunits(j["s_zip"], 0, 5)))
    g = j.group_by(["i_brand_id", "i_brand", "i_manufact_id",
                    "i_manufact"]).aggregate(
        [("ss_ext_sales_price", "sum")]) \
        .rename_columns(["i_brand_id", "i_brand", "i_manufact_id",
                         "i_manufact", "ext_price"])
    return _topn(g, [("ext_price", "descending"),
                     ("i_brand_id", "ascending")])


_q("q19", "brand revenue, customer zip != store zip")(
    (_q19_run, _q19_oracle))


# ===========================================================================
# q6: states where customers bought items priced 20%+ above the category
#     average (subquery-as-join)
# ===========================================================================

def _q6_run(s, t):
    it = _rd(s, t, "item").select("i_item_sk", "i_category",
                                  "i_current_price")
    cat_avg = (it.group_by("i_category")
               .agg(F.avg(col("i_current_price")).alias("cat_avg")))
    it2 = _join_dim(
        it.select(col("i_item_sk"), col("i_category").alias("cat2"),
                  col("i_current_price")),
        cat_avg, "cat2", "i_category")
    it2 = it2.filter(col("i_current_price").cast(DataType.FLOAT64)
                     > col("cat_avg") * lit(1.2))
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_customer_sk")
    # true q6 shape: d_month_seq = (select distinct d_month_seq from
    # date_dim where d_year = 2001 and d_moy = 1) — an uncorrelated
    # SCALAR SUBQUERY executed once per task, no join rewrite
    mseq = scalar_subquery(
        _rd(s, t, "date_dim")
        .filter((col("d_year") == 2001) & (col("d_moy") == 1))
        .group_by("d_month_seq").agg(F.count_star().alias("_c"))
        .select("d_month_seq"))
    dd = _rd(s, t, "date_dim").filter(col("d_month_seq") == mseq) \
        .select("d_date_sk")
    cu = _rd(s, t, "customer").select("c_customer_sk",
                                      "c_current_addr_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_state")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it2.select("i_item_sk"), "ss_item_sk", "i_item_sk")
    j = _join_dim(j, cu, "ss_customer_sk", "c_customer_sk")
    j = _join_dim(j, ca, "c_current_addr_sk", "ca_address_sk")
    g = (j.group_by("ca_state").agg(F.count_star().alias("cnt"))
         .filter(col("cnt") >= 10)
         .sort(col("cnt").asc(), col("ca_state").asc()).limit(100))
    return g.collect()


def _q6_oracle(a):
    it = a["item"].select(["i_item_sk", "i_category", "i_current_price"])
    itf = it.set_column(2, "i_current_price",
                        it["i_current_price"].cast(pa.float64()))
    cat_avg = itf.group_by(["i_category"]).aggregate(
        [("i_current_price", "mean")]) \
        .rename_columns(["i_category", "cat_avg"])
    it2 = _oj(itf, cat_avg, ["i_category"])
    it2 = it2.filter(pc.greater(it2["i_current_price"],
                                pc.multiply(it2["cat_avg"], 1.2))) \
        .select(["i_item_sk"])
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2001),
        pc.equal(a["date_dim"]["d_moy"], 1))).select(["d_date_sk"])
    cu = a["customer"].select(["c_customer_sk", "c_current_addr_sk"])
    ca = a["customer_address"].select(["ca_address_sk", "ca_state"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it2, ["ss_item_sk"], ["i_item_sk"])
    j = _oj(j, cu, ["ss_customer_sk"], ["c_customer_sk"])
    j = _oj(j, ca, ["c_current_addr_sk"], ["ca_address_sk"])
    g = j.group_by(["ca_state"]).aggregate([([], "count_all")]) \
        .rename_columns(["ca_state", "cnt"])
    g = g.filter(pc.greater_equal(g["cnt"], 10))
    g = g.set_column(1, "cnt", g["cnt"].cast(pa.int64()))
    return _topn(g, [("cnt", "ascending"), ("ca_state", "ascending")])


_q("q6", "states buying premium-priced items (scalar subquery + "
         "correlated-subquery-as-join)")(
    (_q6_run, _q6_oracle))


# ===========================================================================
# q12 / q20 / q98: revenue ratio within class (window over agg)
# ===========================================================================

def _channel_ratio(fact, date_col, item_col, price_col, qname):
    def run(s, t):
        fs = _rd(s, t, fact).select(date_col, item_col, price_col)
        dd = _rd(s, t, "date_dim").filter(
            (col("d_date_sk") >= DATE_SK0 + 730)
            & (col("d_date_sk") <= DATE_SK0 + 760)) \
            .select("d_date_sk")
        it = _rd(s, t, "item").filter(
            col("i_category").isin("Sports", "Books", "Home")) \
            .select("i_item_sk", "i_item_id", "i_item_desc", "i_category",
                    "i_class", "i_current_price")
        j = _join_dim(fs, dd, date_col, "d_date_sk")
        j = _join_dim(j, it, item_col, "i_item_sk")
        g = (j.group_by("i_item_id", "i_item_desc", "i_category",
                        "i_class", "i_current_price")
             .agg(F.sum(col(price_col)).alias("itemrevenue")))
        g = g.window([F.win_agg("sum", col("itemrevenue"))
                      .alias("classrev")],
                     partition_by=[col("i_class")])
        g = g.with_column(
            "revenueratio",
            col("itemrevenue").cast(DataType.FLOAT64) * lit(100.0)
            / col("classrev").cast(DataType.FLOAT64))
        return (g.select("i_item_id", "i_item_desc", "i_category",
                         "i_class", "i_current_price", "itemrevenue",
                         "revenueratio")
                .sort(col("i_category").asc(), col("i_class").asc(),
                      col("i_item_id").asc(), col("i_item_desc").asc(),
                      col("revenueratio").asc())
                .limit(100).collect())

    def oracle(a):
        dd = a["date_dim"].filter(pc.and_(
            pc.greater_equal(a["date_dim"]["d_date_sk"], DATE_SK0 + 730),
            pc.less_equal(a["date_dim"]["d_date_sk"], DATE_SK0 + 760))) \
            .select(["d_date_sk"])
        it = a["item"].filter(pc.is_in(
            a["item"]["i_category"],
            value_set=pa.array(["Sports", "Books", "Home"]))) \
            .select(["i_item_sk", "i_item_id", "i_item_desc", "i_category",
                     "i_class", "i_current_price"])
        j = _oj(a[fact], dd, [date_col], ["d_date_sk"])
        j = _oj(j, it, [item_col], ["i_item_sk"])
        g = j.group_by(["i_item_id", "i_item_desc", "i_category",
                        "i_class", "i_current_price"]).aggregate(
            [(price_col, "sum")]) \
            .rename_columns(["i_item_id", "i_item_desc", "i_category",
                             "i_class", "i_current_price", "itemrevenue"])
        cls = g.group_by(["i_class"]).aggregate(
            [("itemrevenue", "sum")]) \
            .rename_columns(["i_class", "classrev"])
        g = _oj(g, cls, ["i_class"])
        ratio = pc.divide(
            pc.multiply(g["itemrevenue"].cast(pa.float64()), 100.0),
            g["classrev"].cast(pa.float64()))
        g = g.append_column("revenueratio", ratio)
        g = g.select(["i_item_id", "i_item_desc", "i_category", "i_class",
                      "i_current_price", "itemrevenue", "revenueratio"])
        return _topn(g, [("i_category", "ascending"),
                         ("i_class", "ascending"),
                         ("i_item_id", "ascending"),
                         ("i_item_desc", "ascending"),
                         ("revenueratio", "ascending")])
    return run, oracle


_q("q12", "web revenue ratio within class")(_channel_ratio(
    "web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price",
    "q12"))
_q("q20", "catalog revenue ratio within class")(_channel_ratio(
    "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
    "cs_ext_sales_price", "q20"))
_q("q98", "store revenue ratio within class")(_channel_ratio(
    "store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price",
    "q98"))


# ===========================================================================
# q26: catalog demographic averages (q7's catalog twin)
# ===========================================================================

def _q26_run(s, t):
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk", "cs_promo_sk",
        "cs_quantity", "cs_list_price", "cs_coupon_amt", "cs_sales_price")
    cd = _rd(s, t, "customer_demographics").filter(
        (col("cd_gender") == "F") & (col("cd_marital_status") == "M")
        & (col("cd_education_status") == "College")).select("cd_demo_sk")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    pr = _rd(s, t, "promotion").filter(col("p_channel_tv") == "N") \
        .select("p_promo_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id")
    j = _join_dim(cs, cd, "cs_bill_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, dd, "cs_sold_date_sk", "d_date_sk")
    j = _join_dim(j, pr, "cs_promo_sk", "p_promo_sk")
    j = _join_dim(j, it, "cs_item_sk", "i_item_sk")
    return (j.group_by("i_item_id")
            .agg(F.avg(col("cs_quantity")).alias("agg1"),
                 F.avg(col("cs_list_price")).alias("agg2"),
                 F.avg(col("cs_coupon_amt")).alias("agg3"),
                 F.avg(col("cs_sales_price")).alias("agg4"))
            .sort(col("i_item_id").asc()).limit(100).collect())


def _q26_oracle(a):
    cd = a["customer_demographics"]
    cd = cd.filter(pc.and_(pc.and_(
        pc.equal(cd["cd_gender"], "F"),
        pc.equal(cd["cd_marital_status"], "M")),
        pc.equal(cd["cd_education_status"], "College"))) \
        .select(["cd_demo_sk"])
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk"])
    pr = a["promotion"].filter(
        pc.equal(a["promotion"]["p_channel_tv"], "N")) \
        .select(["p_promo_sk"])
    it = a["item"].select(["i_item_sk", "i_item_id"])
    j = _oj(a["catalog_sales"], cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"])
    j = _oj(j, dd, ["cs_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, pr, ["cs_promo_sk"], ["p_promo_sk"])
    j = _oj(j, it, ["cs_item_sk"], ["i_item_sk"])
    for c in ("cs_list_price", "cs_coupon_amt", "cs_sales_price"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = j.group_by(["i_item_id"]).aggregate(
        [("cs_quantity", "mean"), ("cs_list_price", "mean"),
         ("cs_coupon_amt", "mean"), ("cs_sales_price", "mean")]) \
        .rename_columns(["i_item_id", "agg1", "agg2", "agg3", "agg4"])
    return _topn(g, [("i_item_id", "ascending")])


_q("q26", "catalog demographic purchase averages")(
    (_q26_run, _q26_oracle))


# ===========================================================================
# q43: per-store day-of-week sales pivot (CASE buckets)
# ===========================================================================

_DAYS = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
         "Friday", "Saturday"]


def _q43_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_store_sk",
                                         "ss_sales_price")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk", "d_day_name")
    st = _rd(s, t, "store").select("s_store_sk", "s_store_id",
                                   "s_store_name")
    j = _join_dim(_join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk"),
                  st, "ss_store_sk", "s_store_sk")
    price_f = col("ss_sales_price").cast(DataType.FLOAT64)
    aggs = [F.sum(F.if_(col("d_day_name") == day, price_f, lit(0.0)))
            .alias(f"{day[:3].lower()}_sales") for day in _DAYS]
    return (j.group_by("s_store_name", "s_store_id").agg(*aggs)
            .sort(col("s_store_name").asc(), col("s_store_id").asc())
            .limit(100).collect())


def _q43_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk", "d_day_name"])
    st = a["store"].select(["s_store_sk", "s_store_id", "s_store_name"])
    j = _oj(_oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"]),
            st, ["ss_store_sk"], ["s_store_sk"])
    price = j["ss_sales_price"].cast(pa.float64())
    cols, names = [], []
    for day in _DAYS:
        cols.append(pc.if_else(pc.equal(j["d_day_name"], day), price, 0.0))
        names.append(f"{day[:3].lower()}_sales")
    base = pa.table({"s_store_name": j["s_store_name"],
                     "s_store_id": j["s_store_id"],
                     **{n: c for n, c in zip(names, cols)}})
    g = base.group_by(["s_store_name", "s_store_id"]).aggregate(
        [(n, "sum") for n in names]) \
        .rename_columns(["s_store_name", "s_store_id"] + names)
    return _topn(g, [("s_store_name", "ascending"),
                     ("s_store_id", "ascending")])


_q("q43", "per-store day-of-week sales pivot")((_q43_run, _q43_oracle))


# ===========================================================================
# q48: banded quantity sum with OR'd demographic/address predicates
# ===========================================================================

def _q48_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_cdemo_sk", "ss_addr_sk",
        "ss_quantity", "ss_sales_price", "ss_net_profit")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    price = col("ss_sales_price").cast(DataType.FLOAT64)
    cd = _rd(s, t, "customer_demographics").filter(
        (col("cd_marital_status") == "M")
        & (col("cd_education_status") == "4 yr Degree")) \
        .select("cd_demo_sk")
    ca = _rd(s, t, "customer_address").filter(
        (col("ca_country") == "United States")
        & col("ca_state").isin("CA", "TX", "NY", "OH", "GA", "WA")) \
        .select("ca_address_sk")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, cd, "ss_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, ca, "ss_addr_sk", "ca_address_sk")
    j = j.filter(((price >= lit(50.0)) & (price <= lit(100.0)))
                 | ((price >= lit(150.0)) & (price <= lit(200.0))))
    return (j.select(col("ss_quantity"))
            .group_by(lit(1).alias("g"))
            .agg(F.sum(col("ss_quantity")).alias("total_q"))
            .select("total_q").collect())


def _q48_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk"])
    cd = a["customer_demographics"]
    cd = cd.filter(pc.and_(
        pc.equal(cd["cd_marital_status"], "M"),
        pc.equal(cd["cd_education_status"], "4 yr Degree"))) \
        .select(["cd_demo_sk"])
    ca = a["customer_address"]
    ca = ca.filter(pc.and_(
        pc.equal(ca["ca_country"], "United States"),
        pc.is_in(ca["ca_state"], value_set=pa.array(
            ["CA", "TX", "NY", "OH", "GA", "WA"])))) \
        .select(["ca_address_sk"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    j = _oj(j, cd, ["ss_cdemo_sk"], ["cd_demo_sk"])
    j = _oj(j, ca, ["ss_addr_sk"], ["ca_address_sk"])
    price = j["ss_sales_price"].cast(pa.float64())
    band = pc.or_(
        pc.and_(pc.greater_equal(price, 50.0), pc.less_equal(price, 100.0)),
        pc.and_(pc.greater_equal(price, 150.0),
                pc.less_equal(price, 200.0)))
    j = j.filter(band)
    total = pc.sum(j["ss_quantity"]).as_py() or 0
    return pa.table({"total_q": pa.array([total], pa.int64())})


_q("q48", "banded quantity sum with OR'd predicate blocks")(
    (_q48_run, _q48_oracle))


# ===========================================================================
# q62 / q99: shipping-lag day buckets (catalog/web)
# ===========================================================================

def _ship_lag(fact, sold_col, ship_col, mode_col, wh_col, qname):
    def run(s, t):
        fs = _rd(s, t, fact).select(sold_col, ship_col, mode_col, wh_col)
        sm = _rd(s, t, "ship_mode").select("sm_ship_mode_sk", "sm_type")
        wh = _rd(s, t, "warehouse").select("w_warehouse_sk",
                                           "w_warehouse_name")
        dd = _rd(s, t, "date_dim").filter(
            (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
            .select("d_date_sk")
        j = _join_dim(fs, dd, ship_col, "d_date_sk")
        j = _join_dim(j, sm, mode_col, "sm_ship_mode_sk")
        j = _join_dim(j, wh, wh_col, "w_warehouse_sk")
        lag = col(ship_col) - col(sold_col)
        buckets = [
            ("d30", lag <= lit(30)),
            ("d60", (lag > lit(30)) & (lag <= lit(60))),
            ("d90", (lag > lit(60)) & (lag <= lit(90))),
            ("d120", (lag > lit(90)) & (lag <= lit(120))),
            ("dmore", lag > lit(120)),
        ]
        aggs = [F.sum(F.if_(cond, lit(1), lit(0))).alias(nm)
                for nm, cond in buckets]
        return (j.group_by("w_warehouse_name", "sm_type").agg(*aggs)
                .sort(col("w_warehouse_name").asc(), col("sm_type").asc())
                .limit(100).collect())

    def oracle(a):
        dd = a["date_dim"].filter(pc.and_(
            pc.greater_equal(a["date_dim"]["d_month_seq"], 24),
            pc.less_equal(a["date_dim"]["d_month_seq"], 35))) \
            .select(["d_date_sk"])
        j = _oj(a[fact], dd, [ship_col], ["d_date_sk"])
        j = _oj(j, a["ship_mode"].select(["sm_ship_mode_sk", "sm_type"]),
                [mode_col], ["sm_ship_mode_sk"])
        j = _oj(j, a["warehouse"].select(["w_warehouse_sk",
                                          "w_warehouse_name"]),
                [wh_col], ["w_warehouse_sk"])
        lag = pc.subtract(j[ship_col], j[sold_col])
        conds = [
            ("d30", pc.less_equal(lag, 30)),
            ("d60", pc.and_(pc.greater(lag, 30), pc.less_equal(lag, 60))),
            ("d90", pc.and_(pc.greater(lag, 60), pc.less_equal(lag, 90))),
            ("d120", pc.and_(pc.greater(lag, 90),
                             pc.less_equal(lag, 120))),
            ("dmore", pc.greater(lag, 120)),
        ]
        cols = {"w_warehouse_name": j["w_warehouse_name"],
                "sm_type": j["sm_type"]}
        for nm, c in conds:
            cols[nm] = pc.if_else(c, pa.scalar(1, pa.int64()),
                                  pa.scalar(0, pa.int64()))
        base = pa.table(cols)
        g = base.group_by(["w_warehouse_name", "sm_type"]).aggregate(
            [(nm, "sum") for nm, _ in conds]) \
            .rename_columns(["w_warehouse_name", "sm_type"]
                            + [nm for nm, _ in conds])
        return _topn(g, [("w_warehouse_name", "ascending"),
                         ("sm_type", "ascending")])
    return run, oracle


_q("q62", "web shipping-lag day buckets")(_ship_lag(
    "web_sales", "ws_sold_date_sk", "ws_ship_date_sk", "ws_ship_mode_sk",
    "ws_warehouse_sk", "q62"))
_q("q99", "catalog shipping-lag day buckets")(_ship_lag(
    "catalog_sales", "cs_sold_date_sk", "cs_ship_date_sk",
    "cs_ship_mode_sk", "cs_warehouse_sk", "q99"))


# ===========================================================================
# q73 / q79: per-ticket baskets joined back to customers
# ===========================================================================

def _q73_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_customer_sk",
        "ss_ticket_number")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_dom") >= 1) & (col("d_dom") <= 2)
        & col("d_year").isin(1999, 2000, 2001)) \
        .select("d_date_sk")
    hd = _rd(s, t, "household_demographics").filter(
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > 0)).select("hd_demo_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    g = (j.group_by("ss_ticket_number", "ss_customer_sk")
         .agg(F.count_star().alias("cnt"))
         .filter((col("cnt") >= 2) & (col("cnt") <= 5)))
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_last_name",
                                      "c_first_name")
    g = _join_dim(g, cu, "ss_customer_sk", "c_customer_sk")
    return (g.sort(col("cnt").desc(), col("c_last_name").asc(),
                   col("ss_ticket_number").asc())
            .limit(100).collect())


def _q73_oracle(a):
    dd = a["date_dim"]
    dd = dd.filter(pc.and_(pc.and_(
        pc.greater_equal(dd["d_dom"], 1), pc.less_equal(dd["d_dom"], 2)),
        pc.is_in(dd["d_year"], value_set=pa.array([1999, 2000, 2001])))) \
        .select(["d_date_sk"])
    hd = a["household_demographics"]
    hd = hd.filter(pc.and_(
        pc.is_in(hd["hd_buy_potential"],
                 value_set=pa.array([">10000", "Unknown"])),
        pc.greater(hd["hd_vehicle_count"], 0))).select(["hd_demo_sk"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    g = j.group_by(["ss_ticket_number", "ss_customer_sk"]).aggregate(
        [([], "count_all")]) \
        .rename_columns(["ss_ticket_number", "ss_customer_sk", "cnt"])
    g = g.filter(pc.and_(pc.greater_equal(g["cnt"], 2),
                         pc.less_equal(g["cnt"], 5)))
    g = g.set_column(2, "cnt", g["cnt"].cast(pa.int64()))
    cu = a["customer"].select(["c_customer_sk", "c_last_name",
                               "c_first_name"])
    g = _oj(g, cu, ["ss_customer_sk"], ["c_customer_sk"])
    return _topn(g, [("cnt", "descending"), ("c_last_name", "ascending"),
                     ("ss_ticket_number", "ascending")])


_q("q73", "frequent small baskets on month-start days")(
    (_q73_run, _q73_oracle))


def _q79_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_customer_sk",
        "ss_addr_sk", "ss_ticket_number", "ss_coupon_amt", "ss_net_profit")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_dom") >= 1) & (col("d_dom") <= 2)
        & col("d_year").isin(1999, 2000, 2001)).select("d_date_sk")
    hd = _rd(s, t, "household_demographics").filter(
        (col("hd_dep_count") == 6) | (col("hd_vehicle_count") > 2)) \
        .select("hd_demo_sk")
    st = _rd(s, t, "store").filter(col("s_number_employees") >= 200) \
        .select("s_store_sk", "s_city")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    g = (j.group_by("ss_ticket_number", "ss_customer_sk", "s_city")
         .agg(F.sum(col("ss_coupon_amt").cast(DataType.FLOAT64))
              .alias("amt"),
              F.sum(col("ss_net_profit").cast(DataType.FLOAT64))
              .alias("profit")))
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_last_name",
                                      "c_first_name")
    g = _join_dim(g, cu, "ss_customer_sk", "c_customer_sk")
    return (g.select("c_last_name", "c_first_name", "s_city", "profit",
                     "ss_ticket_number", "amt")
            .sort(col("c_last_name").asc(), col("c_first_name").asc(),
                  col("s_city").asc(), col("profit").desc(),
                  col("ss_ticket_number").asc())
            .limit(100).collect())


def _q79_oracle(a):
    dd = a["date_dim"]
    dd = dd.filter(pc.and_(pc.and_(
        pc.greater_equal(dd["d_dom"], 1), pc.less_equal(dd["d_dom"], 2)),
        pc.is_in(dd["d_year"], value_set=pa.array([1999, 2000, 2001])))) \
        .select(["d_date_sk"])
    hd = a["household_demographics"]
    hd = hd.filter(pc.or_(pc.equal(hd["hd_dep_count"], 6),
                          pc.greater(hd["hd_vehicle_count"], 2))) \
        .select(["hd_demo_sk"])
    st = a["store"].filter(
        pc.greater_equal(a["store"]["s_number_employees"], 200)) \
        .select(["s_store_sk", "s_city"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, st, ["ss_store_sk"], ["s_store_sk"])
    for c in ("ss_coupon_amt", "ss_net_profit"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = j.group_by(["ss_ticket_number", "ss_customer_sk", "s_city"]) \
        .aggregate([("ss_coupon_amt", "sum"), ("ss_net_profit", "sum")]) \
        .rename_columns(["ss_ticket_number", "ss_customer_sk", "s_city",
                         "amt", "profit"])
    cu = a["customer"].select(["c_customer_sk", "c_last_name",
                               "c_first_name"])
    g = _oj(g, cu, ["ss_customer_sk"], ["c_customer_sk"])
    g = g.select(["c_last_name", "c_first_name", "s_city", "profit",
                  "ss_ticket_number", "amt"])
    return _topn(g, [("c_last_name", "ascending"),
                     ("c_first_name", "ascending"),
                     ("s_city", "ascending"), ("profit", "descending"),
                     ("ss_ticket_number", "ascending")])


_q("q79", "per-ticket coupon/profit by city and customer")(
    (_q79_run, _q79_oracle))


# ===========================================================================
# q96: count of early-evening purchases by dependent-heavy households
# ===========================================================================

def _q96_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_time_sk", "ss_hdemo_sk",
                                         "ss_store_sk")
    hd = _rd(s, t, "household_demographics") \
        .filter(col("hd_dep_count") == 7).select("hd_demo_sk")
    td = _rd(s, t, "time_dim").filter(
        (col("t_hour") == 20) & (col("t_minute") >= 30)) \
        .select("t_time_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    j = _join_dim(ss, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, td, "ss_sold_time_sk", "t_time_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    return (j.select(col("ss_store_sk"))
            .group_by(lit(1).alias("g"))
            .agg(F.count_star().alias("cnt"))
            .select("cnt").collect())


def _q96_oracle(a):
    hd = a["household_demographics"]
    hd = hd.filter(pc.equal(hd["hd_dep_count"], 7)).select(["hd_demo_sk"])
    td = a["time_dim"]
    td = td.filter(pc.and_(pc.equal(td["t_hour"], 20),
                           pc.greater_equal(td["t_minute"], 30))) \
        .select(["t_time_sk"])
    j = _oj(a["store_sales"], hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, td, ["ss_sold_time_sk"], ["t_time_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    return pa.table({"cnt": pa.array([j.num_rows], pa.int64())})


_q("q96", "count of 20:30+ purchases by 7-dependent households")(
    (_q96_run, _q96_oracle))


# ===========================================================================
# q1: customers returning more than 1.2x their store's average
# ===========================================================================

def _q1_run(s, t):
    sr = _rd(s, t, "store_returns").select(
        "sr_returned_date_sk", "sr_customer_sk", "sr_store_sk",
        "sr_return_amt")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    ctr = (_join_dim(sr, dd, "sr_returned_date_sk", "d_date_sk")
           .group_by("sr_customer_sk", "sr_store_sk")
           .agg(F.sum(col("sr_return_amt").cast(DataType.FLOAT64))
                .alias("ctr_total_return")))
    avg_ctr = (ctr.group_by(col("sr_store_sk").alias("st2"))
               .agg(F.avg(col("ctr_total_return")).alias("avg_return")))
    j = _join_dim(ctr, avg_ctr, "sr_store_sk", "st2")
    j = j.filter(col("ctr_total_return") > col("avg_return") * lit(1.2))
    # parameter auto-tune: at CI scales the store table is 6 rows drawn
    # from 12 states, so the single-state 'TN' template parameter often
    # selects zero stores; a 4-state IN keeps the filter real AND the
    # result nonempty at every scale
    st = _rd(s, t, "store").filter(
        col("s_state").isin("TN", "CA", "TX", "NY")).select("s_store_sk")
    j = _join_dim(j, st, "sr_store_sk", "s_store_sk")
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_customer_id")
    j = _join_dim(j, cu, "sr_customer_sk", "c_customer_sk")
    return (j.select("c_customer_id")
            .sort(col("c_customer_id").asc()).limit(100).collect())


def _q1_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk"])
    sr = _oj(a["store_returns"], dd, ["sr_returned_date_sk"],
             ["d_date_sk"])
    sr = sr.set_column(sr.column_names.index("sr_return_amt"),
                       "sr_return_amt",
                       sr["sr_return_amt"].cast(pa.float64()))
    ctr = sr.group_by(["sr_customer_sk", "sr_store_sk"]).aggregate(
        [("sr_return_amt", "sum")]) \
        .rename_columns(["sr_customer_sk", "sr_store_sk",
                         "ctr_total_return"])
    avg_ctr = ctr.group_by(["sr_store_sk"]).aggregate(
        [("ctr_total_return", "mean")]) \
        .rename_columns(["st2", "avg_return"])
    j = _oj(ctr, avg_ctr, ["sr_store_sk"], ["st2"])
    j = j.filter(pc.greater(j["ctr_total_return"],
                            pc.multiply(j["avg_return"], 1.2)))
    st = a["store"].filter(pc.is_in(
        a["store"]["s_state"],
        value_set=pa.array(["TN", "CA", "TX", "NY"]))) \
        .select(["s_store_sk"])
    j = _oj(j, st, ["sr_store_sk"], ["s_store_sk"])
    cu = a["customer"].select(["c_customer_sk", "c_customer_id"])
    j = _oj(j, cu, ["sr_customer_sk"], ["c_customer_sk"])
    g = j.select(["c_customer_id"])
    return _topn(g, [("c_customer_id", "ascending")])


_q("q1", "above-average returners per store (subquery-as-join)")(
    (_q1_run, _q1_oracle))


# ===========================================================================
# q68: city baskets with extended sums
# ===========================================================================

def _q68_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_addr_sk",
        "ss_customer_sk", "ss_ticket_number", "ss_ext_sales_price",
        "ss_ext_list_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_dom") >= 1) & (col("d_dom") <= 2)
        & col("d_year").isin(1999, 2000)).select("d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    hd = _rd(s, t, "household_demographics").filter(
        (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3)) \
        .select("hd_demo_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_city")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, ca, "ss_addr_sk", "ca_address_sk")
    g = (j.group_by("ss_ticket_number", "ss_customer_sk", "ca_city")
         .agg(F.sum(col("ss_ext_sales_price").cast(DataType.FLOAT64))
              .alias("extended_price"),
              F.sum(col("ss_ext_list_price").cast(DataType.FLOAT64))
              .alias("list_price")))
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_last_name",
                                      "c_first_name")
    g = _join_dim(g, cu, "ss_customer_sk", "c_customer_sk")
    return (g.select("c_last_name", "c_first_name", "ca_city",
                     "extended_price", "list_price", "ss_ticket_number")
            .sort(col("c_last_name").asc(), col("ss_ticket_number").asc())
            .limit(100).collect())


def _q68_oracle(a):
    dd = a["date_dim"]
    dd = dd.filter(pc.and_(pc.and_(
        pc.greater_equal(dd["d_dom"], 1), pc.less_equal(dd["d_dom"], 2)),
        pc.is_in(dd["d_year"], value_set=pa.array([1999, 2000])))) \
        .select(["d_date_sk"])
    hd = a["household_demographics"]
    hd = hd.filter(pc.or_(pc.equal(hd["hd_dep_count"], 4),
                          pc.equal(hd["hd_vehicle_count"], 3))) \
        .select(["hd_demo_sk"])
    ca = a["customer_address"].select(["ca_address_sk", "ca_city"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    j = _oj(j, hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, ca, ["ss_addr_sk"], ["ca_address_sk"])
    for c in ("ss_ext_sales_price", "ss_ext_list_price"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = j.group_by(["ss_ticket_number", "ss_customer_sk", "ca_city"]) \
        .aggregate([("ss_ext_sales_price", "sum"),
                    ("ss_ext_list_price", "sum")]) \
        .rename_columns(["ss_ticket_number", "ss_customer_sk", "ca_city",
                         "extended_price", "list_price"])
    cu = a["customer"].select(["c_customer_sk", "c_last_name",
                               "c_first_name"])
    g = _oj(g, cu, ["ss_customer_sk"], ["c_customer_sk"])
    g = g.select(["c_last_name", "c_first_name", "ca_city",
                  "extended_price", "list_price", "ss_ticket_number"])
    return _topn(g, [("c_last_name", "ascending"),
                     ("ss_ticket_number", "ascending")])


_q("q68", "city baskets with extended price sums")(
    (_q68_run, _q68_oracle))


# ===========================================================================
# q82: items in a price band with mid-range inventory that actually sold
# ===========================================================================

def _q82_run(s, t):
    price = col("i_current_price").cast(DataType.FLOAT64)
    it = _rd(s, t, "item").filter(
        (price >= lit(30.0)) & (price <= lit(60.0))
        & col("i_manufact_id").isin(*range(100, 140))) \
        .select("i_item_sk", "i_item_id", "i_item_desc", "i_current_price")
    inv = _rd(s, t, "inventory").filter(
        (col("inv_quantity_on_hand") >= 100)
        & (col("inv_quantity_on_hand") <= 500)) \
        .select("inv_item_sk", "inv_date_sk")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_date_sk") >= DATE_SK0 + 800)
        & (col("d_date_sk") <= DATE_SK0 + 860)).select("d_date_sk")
    ss = _rd(s, t, "store_sales").select("ss_item_sk")
    j = _join_dim(it, inv, "i_item_sk", "inv_item_sk")
    j = _join_dim(j, dd, "inv_date_sk", "d_date_sk")
    j = _join_dim(j, ss.group_by(col("ss_item_sk").alias("sold_sk"))
                  .agg(F.count_star().alias("n")).select("sold_sk"),
                  "i_item_sk", "sold_sk")
    return (j.group_by("i_item_id", "i_item_desc", "i_current_price")
            .agg(F.count_star().alias("n"))
            .select("i_item_id", "i_item_desc", "i_current_price")
            .sort(col("i_item_id").asc()).limit(100).collect())


def _q82_oracle(a):
    it = a["item"]
    price = it["i_current_price"].cast(pa.float64())
    it = it.filter(pc.and_(pc.and_(
        pc.greater_equal(price, 30.0), pc.less_equal(price, 60.0)),
        pc.is_in(it["i_manufact_id"],
                 value_set=pa.array(list(range(100, 140)))))) \
        .select(["i_item_sk", "i_item_id", "i_item_desc",
                 "i_current_price"])
    inv = a["inventory"]
    inv = inv.filter(pc.and_(
        pc.greater_equal(inv["inv_quantity_on_hand"], 100),
        pc.less_equal(inv["inv_quantity_on_hand"], 500))) \
        .select(["inv_item_sk", "inv_date_sk"])
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_date_sk"], DATE_SK0 + 800),
        pc.less_equal(a["date_dim"]["d_date_sk"], DATE_SK0 + 860))) \
        .select(["d_date_sk"])
    sold = a["store_sales"].group_by(["ss_item_sk"]).aggregate(
        [([], "count_all")]).rename_columns(["sold_sk", "n"]) \
        .select(["sold_sk"])
    j = _oj(it, inv, ["i_item_sk"], ["inv_item_sk"])
    j = _oj(j, dd, ["inv_date_sk"], ["d_date_sk"])
    j = _oj(j, sold, ["i_item_sk"], ["sold_sk"])
    g = j.group_by(["i_item_id", "i_item_desc", "i_current_price"]) \
        .aggregate([([], "count_all")]) \
        .rename_columns(["i_item_id", "i_item_desc", "i_current_price",
                         "n"]).select(["i_item_id", "i_item_desc",
                                       "i_current_price"])
    return _topn(g, [("i_item_id", "ascending")])


_q("q82", "priced+stocked+sold item inventory slice")(
    (_q82_run, _q82_oracle))


# ===========================================================================
# q89: monthly category sales vs the partition average (window over agg)
# ===========================================================================

def _q89_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_store_sk", "ss_sales_price")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk", "d_moy")
    it = _rd(s, t, "item").filter(
        col("i_category").isin("Books", "Electronics", "Sports")) \
        .select("i_item_sk", "i_category", "i_class", "i_brand")
    st = _rd(s, t, "store").select("s_store_sk", "s_store_name")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    g = (j.group_by("i_category", "i_class", "i_brand", "s_store_name",
                    "d_moy")
         .agg(F.sum(col("ss_sales_price").cast(DataType.FLOAT64))
              .alias("sum_sales")))
    g = g.window([F.win_agg("avg", col("sum_sales"))
                  .alias("avg_monthly_sales")],
                 partition_by=[col("i_category"), col("i_brand"),
                               col("s_store_name")])
    g = g.filter((col("sum_sales") - col("avg_monthly_sales") > lit(0.1)
                  * col("avg_monthly_sales"))
                 | (col("avg_monthly_sales") - col("sum_sales")
                    > lit(0.1) * col("avg_monthly_sales")))
    return (g.sort(col("sum_sales").asc(), col("s_store_name").asc(),
                   col("i_brand").asc(), col("d_moy").asc())
            .limit(100).collect())


def _q89_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk", "d_moy"])
    it = a["item"].filter(pc.is_in(
        a["item"]["i_category"],
        value_set=pa.array(["Books", "Electronics", "Sports"]))) \
        .select(["i_item_sk", "i_category", "i_class", "i_brand"])
    st = a["store"].select(["s_store_sk", "s_store_name"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    j = _oj(j, st, ["ss_store_sk"], ["s_store_sk"])
    j = j.set_column(j.column_names.index("ss_sales_price"),
                     "ss_sales_price",
                     j["ss_sales_price"].cast(pa.float64()))
    g = j.group_by(["i_category", "i_class", "i_brand", "s_store_name",
                    "d_moy"]).aggregate([("ss_sales_price", "sum")]) \
        .rename_columns(["i_category", "i_class", "i_brand",
                         "s_store_name", "d_moy", "sum_sales"])
    avg = g.group_by(["i_category", "i_brand", "s_store_name"]) \
        .aggregate([("sum_sales", "mean")]) \
        .rename_columns(["i_category", "i_brand", "s_store_name",
                         "avg_monthly_sales"])
    g = _oj(g, avg, ["i_category", "i_brand", "s_store_name"])
    dev = pc.abs(pc.subtract(g["sum_sales"], g["avg_monthly_sales"]))
    g = g.filter(pc.greater(dev,
                            pc.multiply(g["avg_monthly_sales"], 0.1)))
    g = g.select(["i_category", "i_class", "i_brand", "s_store_name",
                  "d_moy", "sum_sales", "avg_monthly_sales"])
    return _topn(g, [("sum_sales", "ascending"),
                     ("s_store_name", "ascending"),
                     ("i_brand", "ascending"), ("d_moy", "ascending")])


_q("q89", "monthly sales deviating >10% from partition average")(
    (_q89_run, _q89_oracle))


# ===========================================================================
# q65: store/item pairs whose revenue is below 10% of the store average
# ===========================================================================

def _q65_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_store_sk", "ss_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
        .select("d_date_sk")
    sa = (_join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
          .group_by("ss_store_sk", "ss_item_sk")
          .agg(F.sum(col("ss_sales_price").cast(DataType.FLOAT64))
               .alias("revenue")))
    sb = (sa.group_by(col("ss_store_sk").alias("st2"))
          .agg(F.avg(col("revenue")).alias("ave")))
    j = _join_dim(sa, sb, "ss_store_sk", "st2")
    j = j.filter(col("revenue") <= col("ave") * lit(0.1))
    st = _rd(s, t, "store").select("s_store_sk", "s_store_name")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_desc",
                                  "i_current_price")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    return (j.select("s_store_name", "i_item_desc", "revenue",
                     "i_current_price")
            .sort(col("s_store_name").asc(), col("i_item_desc").asc())
            .limit(100).collect())


def _q65_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_month_seq"], 24),
        pc.less_equal(a["date_dim"]["d_month_seq"], 35))) \
        .select(["d_date_sk"])
    ssj = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    ssj = ssj.set_column(ssj.column_names.index("ss_sales_price"),
                         "ss_sales_price",
                         ssj["ss_sales_price"].cast(pa.float64()))
    sa = ssj.group_by(["ss_store_sk", "ss_item_sk"]).aggregate(
        [("ss_sales_price", "sum")]) \
        .rename_columns(["ss_store_sk", "ss_item_sk", "revenue"])
    sb = sa.group_by(["ss_store_sk"]).aggregate([("revenue", "mean")]) \
        .rename_columns(["st2", "ave"])
    j = _oj(sa, sb, ["ss_store_sk"], ["st2"])
    j = j.filter(pc.less_equal(j["revenue"],
                               pc.multiply(j["ave"], 0.1)))
    j = _oj(j, a["store"].select(["s_store_sk", "s_store_name"]),
            ["ss_store_sk"], ["s_store_sk"])
    j = _oj(j, a["item"].select(["i_item_sk", "i_item_desc",
                                 "i_current_price"]),
            ["ss_item_sk"], ["i_item_sk"])
    g = j.select(["s_store_name", "i_item_desc", "revenue",
                  "i_current_price"])
    return _topn(g, [("s_store_name", "ascending"),
                     ("i_item_desc", "ascending")])


_q("q65", "under-performing store/item pairs")((_q65_run, _q65_oracle))


# ===========================================================================
# q50: return-lag day buckets per store
# ===========================================================================

def _q50_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
        "ss_ticket_number", "ss_store_sk")
    sr = _rd(s, t, "store_returns").select(
        col("sr_returned_date_sk"), col("sr_item_sk").alias("ss_item_sk"),
        col("sr_customer_sk").alias("ss_customer_sk"),
        col("sr_ticket_number").alias("ss_ticket_number"))
    j = ss.join(sr, on=["ss_ticket_number", "ss_item_sk",
                        "ss_customer_sk"])
    dd2 = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2001) & (col("d_moy") == 8)) \
        .select("d_date_sk")
    j = _join_dim(j, dd2, "sr_returned_date_sk", "d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk", "s_store_name")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    lag = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    buckets = [("d30", lag <= lit(30)),
               ("d60", (lag > lit(30)) & (lag <= lit(60))),
               ("d90", (lag > lit(60)) & (lag <= lit(90))),
               ("d120", (lag > lit(90)) & (lag <= lit(120))),
               ("dmore", lag > lit(120))]
    aggs = [F.sum(F.if_(cond, lit(1), lit(0))).alias(nm)
            for nm, cond in buckets]
    return (j.group_by("s_store_name").agg(*aggs)
            .sort(col("s_store_name").asc()).limit(100).collect())


def _q50_oracle(a):
    sr = a["store_returns"].rename_columns(
        ["sr_returned_date_sk", "ss_item_sk", "ss_customer_sk",
         "ss_ticket_number", "sr_store_sk", "sr_return_quantity",
         "sr_return_amt", "sr_fee", "sr_net_loss"])
    sr = sr.select(["sr_returned_date_sk", "ss_item_sk", "ss_customer_sk",
                    "ss_ticket_number"])
    j = _oj(a["store_sales"], sr,
            ["ss_ticket_number", "ss_item_sk", "ss_customer_sk"])
    dd2 = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2001),
        pc.equal(a["date_dim"]["d_moy"], 8))).select(["d_date_sk"])
    j = _oj(j, dd2, ["sr_returned_date_sk"], ["d_date_sk"])
    j = _oj(j, a["store"].select(["s_store_sk", "s_store_name"]),
            ["ss_store_sk"], ["s_store_sk"])
    lag = pc.subtract(j["sr_returned_date_sk"], j["ss_sold_date_sk"])
    conds = [("d30", pc.less_equal(lag, 30)),
             ("d60", pc.and_(pc.greater(lag, 30), pc.less_equal(lag, 60))),
             ("d90", pc.and_(pc.greater(lag, 60), pc.less_equal(lag, 90))),
             ("d120", pc.and_(pc.greater(lag, 90),
                              pc.less_equal(lag, 120))),
             ("dmore", pc.greater(lag, 120))]
    cols = {"s_store_name": j["s_store_name"]}
    for nm, c in conds:
        cols[nm] = pc.if_else(c, pa.scalar(1, pa.int64()),
                              pa.scalar(0, pa.int64()))
    base = pa.table(cols)
    g = base.group_by(["s_store_name"]).aggregate(
        [(nm, "sum") for nm, _ in conds]) \
        .rename_columns(["s_store_name"] + [nm for nm, _ in conds])
    return _topn(g, [("s_store_name", "ascending")])


_q("q50", "return-lag day buckets per store")((_q50_run, _q50_oracle))


# ===========================================================================
# q33: manufacturer revenue by channel slice (store only, simplified to
#       the store-channel leg of the union)
# ===========================================================================

def _q33_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_addr_sk",
                                         "ss_ext_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 1999) & (col("d_moy") == 3)) \
        .select("d_date_sk")
    ca = _rd(s, t, "customer_address").filter(
        col("ca_gmt_offset") == -5.0).select("ca_address_sk")
    it = _rd(s, t, "item").filter(col("i_category") == "Electronics") \
        .select("i_item_sk", "i_manufact_id")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, ca, "ss_addr_sk", "ca_address_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    return (j.group_by("i_manufact_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("total_sales"))
            .sort(col("total_sales").asc(), col("i_manufact_id").asc())
            .limit(100).collect())


def _q33_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 1999),
        pc.equal(a["date_dim"]["d_moy"], 3))).select(["d_date_sk"])
    ca = a["customer_address"].filter(
        pc.equal(a["customer_address"]["ca_gmt_offset"], -5.0)) \
        .select(["ca_address_sk"])
    it = a["item"].filter(
        pc.equal(a["item"]["i_category"], "Electronics")) \
        .select(["i_item_sk", "i_manufact_id"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, ca, ["ss_addr_sk"], ["ca_address_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    g = j.group_by(["i_manufact_id"]).aggregate(
        [("ss_ext_sales_price", "sum")]) \
        .rename_columns(["i_manufact_id", "total_sales"])
    return _topn(g, [("total_sales", "ascending"),
                     ("i_manufact_id", "ascending")])


_q("q33", "manufacturer revenue in one region/month (store leg)")(
    (_q33_run, _q33_oracle))


# ===========================================================================
# q88: time-of-day purchase counts (four half-hour buckets as one agg)
# ===========================================================================

def _q88_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_time_sk", "ss_hdemo_sk",
                                         "ss_store_sk")
    hd = _rd(s, t, "household_demographics").filter(
        col("hd_dep_count") == 3).select("hd_demo_sk")
    td = _rd(s, t, "time_dim").filter(
        (col("t_hour") >= 8) & (col("t_hour") <= 11)) \
        .select("t_time_sk", "t_hour", "t_minute")
    st = _rd(s, t, "store").select("s_store_sk")
    j = _join_dim(ss, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, td, "ss_sold_time_sk", "t_time_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    half = (col("t_hour") - lit(8)) * lit(2) \
        + F.if_(col("t_minute") >= lit(30), lit(1), lit(0))
    aggs = [F.sum(F.if_(half == lit(k), lit(1), lit(0))).alias(f"h{k}")
            for k in range(8)]
    return (j.select(col("t_hour"), col("t_minute"))
            .with_column("half", half)
            .group_by(lit(1).alias("g")).agg(*aggs)
            .select(*[f"h{k}" for k in range(8)]).collect())


def _q88_oracle(a):
    hd = a["household_demographics"]
    hd = hd.filter(pc.equal(hd["hd_dep_count"], 3)).select(["hd_demo_sk"])
    td = a["time_dim"]
    td = td.filter(pc.and_(pc.greater_equal(td["t_hour"], 8),
                           pc.less_equal(td["t_hour"], 11))) \
        .select(["t_time_sk", "t_hour", "t_minute"])
    j = _oj(a["store_sales"], hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, td, ["ss_sold_time_sk"], ["t_time_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    half = pc.add(pc.multiply(pc.subtract(j["t_hour"], 8), 2),
                  pc.if_else(pc.greater_equal(j["t_minute"], 30), 1, 0))
    out = {}
    for k in range(8):
        out[f"h{k}"] = pa.array(
            [pc.sum(pc.cast(pc.equal(half, k), pa.int64())).as_py() or 0],
            pa.int64())
    return pa.table(out)


_q("q88", "morning half-hour purchase count buckets")(
    (_q88_run, _q88_oracle))


# ===========================================================================
# rollup / grouping-sets family (round-5 directive 6). The engine side uses
# DataFrame.rollup (Expand + grouping_id, Spark's own lowering); the oracle
# computes each grouping-set level independently in pyarrow and concats.
# ===========================================================================

def _oracle_rollup(t, keys, aggs, agg_names):
    """Per-prefix-level group_by, null-filled rolled-up keys + Spark
    grouping_id, concatenated (the independent rollup oracle)."""
    import pyarrow as _pa
    n = len(keys)
    outs = []
    for level in range(n, -1, -1):
        inc = keys[:level]
        gid = sum(1 << (n - 1 - i) for i in range(level, n))
        if inc:
            g = t.group_by(inc, use_threads=False).aggregate(aggs)
            g = g.rename_columns(list(inc) + agg_names)
        else:
            g = t.group_by([], use_threads=False).aggregate(aggs)
            g = g.rename_columns(agg_names)
        cols, names = [], []
        for i, k in enumerate(keys):
            if i < level:
                cols.append(g.column(k))
            else:
                cols.append(_pa.nulls(g.num_rows, t.schema.field(k).type))
            names.append(k)
        cols.append(_pa.array([gid] * g.num_rows, _pa.int32()))
        names.append("spark_grouping_id")
        for an in agg_names:
            cols.append(g.column(an))
            names.append(an)
        outs.append(_pa.table(dict(zip(names, cols))))
    return _pa.concat_tables(outs)


def _q18_run(s, t):
    # q18-class: catalog averages by demographic slice, ROLLUP over the
    # item hierarchy (the template rolls up buyer geography, which this
    # schema subset does not carry on catalog_sales)
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
        "cs_quantity", "cs_list_price", "cs_coupon_amt")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    cd = _rd(s, t, "customer_demographics").filter(
        (col("cd_gender") == "F")
        & (col("cd_education_status") == "College")) \
        .select("cd_demo_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_class")
    j = _join_dim(cs, dd, "cs_sold_date_sk", "d_date_sk")
    j = _join_dim(j, cd, "cs_bill_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, it, "cs_item_sk", "i_item_sk")
    g = (j.rollup("i_category", "i_class")
         .agg(F.avg(col("cs_quantity").cast(DataType.FLOAT64))
              .alias("agg1"),
              F.avg(col("cs_list_price").cast(DataType.FLOAT64))
              .alias("agg2"),
              F.avg(col("cs_coupon_amt").cast(DataType.FLOAT64))
              .alias("agg3")))
    return (g.sort(col("spark_grouping_id").asc(),
                   col("i_category").asc(), col("i_class").asc())
            .limit(200).collect())


def _q18_oracle(a):
    dd = a["date_dim"].filter(
        pc.equal(a["date_dim"]["d_year"], 2000)).select(["d_date_sk"])
    cd = a["customer_demographics"].filter(pc.and_(
        pc.equal(a["customer_demographics"]["cd_gender"], "F"),
        pc.equal(a["customer_demographics"]["cd_education_status"],
                 "College"))).select(["cd_demo_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_class"])
    j = _oj(a["catalog_sales"], dd, ["cs_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"])
    j = _oj(j, it, ["cs_item_sk"], ["i_item_sk"])
    for c in ("cs_quantity", "cs_list_price", "cs_coupon_amt"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = _oracle_rollup(j, ["i_category", "i_class"],
                       [("cs_quantity", "mean"), ("cs_list_price", "mean"),
                        ("cs_coupon_amt", "mean")],
                       ["agg1", "agg2", "agg3"])
    return _topn(g, [("spark_grouping_id", "ascending"),
                     ("i_category", "ascending"),
                     ("i_class", "ascending")], 200)


_q("q18", "catalog demographic averages, ROLLUP(i_category, i_class)")(
    (_q18_run, _q18_oracle))


def _q22_run(s, t):
    inv = _rd(s, t, "inventory").select("inv_date_sk", "inv_item_sk",
                                        "inv_quantity_on_hand")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_brand")
    j = _join_dim(inv, dd, "inv_date_sk", "d_date_sk")
    j = _join_dim(j, it, "inv_item_sk", "i_item_sk")
    g = (j.rollup("i_category", "i_brand")
         .agg(F.avg(col("inv_quantity_on_hand").cast(DataType.FLOAT64))
              .alias("qoh")))
    return (g.sort(col("qoh").asc(), col("i_category").asc(),
                   col("i_brand").asc()).limit(100).collect())


def _q22_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_month_seq"], 24),
        pc.less_equal(a["date_dim"]["d_month_seq"], 35))) \
        .select(["d_date_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_brand"])
    j = _oj(a["inventory"], dd, ["inv_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["inv_item_sk"], ["i_item_sk"])
    j = j.set_column(j.column_names.index("inv_quantity_on_hand"),
                     "inv_quantity_on_hand",
                     j["inv_quantity_on_hand"].cast(pa.float64()))
    g = _oracle_rollup(j, ["i_category", "i_brand"],
                       [("inv_quantity_on_hand", "mean")], ["qoh"])
    return _topn(g, [("qoh", "ascending"), ("i_category", "ascending"),
                     ("i_brand", "ascending")])


_q("q22", "average inventory on hand, ROLLUP(i_category, i_brand)")(
    (_q22_run, _q22_oracle))


def _q36_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
        "ss_ext_sales_price", "ss_net_profit")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2001) \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_class")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    g = (j.rollup("i_category", "i_class")
         .agg(F.sum(col("ss_net_profit").cast(DataType.FLOAT64))
              .alias("profit"),
              F.sum(col("ss_ext_sales_price").cast(DataType.FLOAT64))
              .alias("sales")))
    # gross margin + lochierarchy = grouping(category)+grouping(class),
    # computed from the Spark grouping id bits
    g = g.with_column("gross_margin", col("profit") / col("sales"))
    g = g.with_column(
        "lochierarchy",
        (col("spark_grouping_id") % lit(2, DataType.INT32))
        + (col("spark_grouping_id") / lit(2, DataType.INT32)))
    g = g.select("i_category", "i_class", "gross_margin", "lochierarchy")
    return (g.sort(col("lochierarchy").desc(), col("i_category").asc(),
                   col("i_class").asc()).limit(100).collect())


def _q36_oracle(a):
    dd = a["date_dim"].filter(
        pc.equal(a["date_dim"]["d_year"], 2001)).select(["d_date_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_class"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    for c in ("ss_net_profit", "ss_ext_sales_price"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = _oracle_rollup(j, ["i_category", "i_class"],
                       [("ss_net_profit", "sum"),
                        ("ss_ext_sales_price", "sum")],
                       ["profit", "sales"])
    gm = pc.divide(g["profit"], g["sales"])
    gid = g["spark_grouping_id"]
    loch = pc.add(pc.bit_wise_and(gid, 1),
                  pc.shift_right(gid, 1))
    g = pa.table({"i_category": g["i_category"], "i_class": g["i_class"],
                  "gross_margin": gm,
                  "lochierarchy": loch.cast(pa.int32())})
    return _topn(g, [("lochierarchy", "descending"),
                     ("i_category", "ascending"),
                     ("i_class", "ascending")])


_q("q36", "gross margin ROLLUP with grouping()-derived hierarchy level")(
    (_q36_run, _q36_oracle))


def _q67_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_quantity", "ss_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_class",
                                  "i_brand")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    j = j.with_column(
        "amt", col("ss_sales_price").cast(DataType.FLOAT64)
        * col("ss_quantity").cast(DataType.FLOAT64))
    g = (j.rollup("i_category", "i_class", "i_brand")
         .agg(F.sum(col("amt")).alias("sumsales")))
    # rank the hierarchy rows within each category by sales
    g = g.window([F.rank().alias("rk")],
                 partition_by=[col("i_category")],
                 order_by=[col("sumsales").desc()])
    g = g.filter(col("rk") <= 5) \
        .select("i_category", "i_class", "i_brand", "sumsales", "rk")
    return (g.sort(col("i_category").asc(), col("rk").asc(),
                   col("i_class").asc(), col("i_brand").asc())
            .limit(200).collect())


def _q67_oracle(a):
    import pandas as pd
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_month_seq"], 24),
        pc.less_equal(a["date_dim"]["d_month_seq"], 35))) \
        .select(["d_date_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_class",
                           "i_brand"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    amt = pc.multiply(j["ss_sales_price"].cast(pa.float64()),
                      j["ss_quantity"].cast(pa.float64()))
    j = j.append_column("amt", amt)
    g = _oracle_rollup(j, ["i_category", "i_class", "i_brand"],
                       [("amt", "sum")], ["sumsales"])
    df = g.to_pandas()
    # rank(method='min') over sumsales desc per category (NaN category =
    # the all-up row partitions together, like the engine's NULL keys)
    df["rk"] = df.groupby("i_category", dropna=False)["sumsales"] \
        .rank(method="min", ascending=False).astype("int64")
    df = df[df.rk <= 5][["i_category", "i_class", "i_brand",
                         "sumsales", "rk"]]
    out = pa.Table.from_pandas(df.reset_index(drop=True),
                               preserve_index=False)
    return _topn(out, [("i_category", "ascending"), ("rk", "ascending"),
                       ("i_class", "ascending"), ("i_brand", "ascending")],
                 200)


_q("q67", "top sales rows per category over ROLLUP(cat, class, brand)")(
    (_q67_run, _q67_oracle))


def _q86_run(s, t):
    ws = _rd(s, t, "web_sales").select("ws_sold_date_sk", "ws_item_sk",
                                       "ws_ext_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 12) & (col("d_month_seq") <= 23)) \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_class")
    j = _join_dim(ws, dd, "ws_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ws_item_sk", "i_item_sk")
    g = (j.rollup("i_category", "i_class")
         .agg(F.sum(col("ws_ext_sales_price").cast(DataType.FLOAT64))
              .alias("total_sum")))
    g = g.with_column(
        "lochierarchy",
        (col("spark_grouping_id") % lit(2, DataType.INT32))
        + (col("spark_grouping_id") / lit(2, DataType.INT32)))
    g = g.select("total_sum", "i_category", "i_class", "lochierarchy")
    return (g.sort(col("lochierarchy").desc(), col("total_sum").desc(),
                   col("i_category").asc(), col("i_class").asc())
            .limit(100).collect())


def _q86_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_month_seq"], 12),
        pc.less_equal(a["date_dim"]["d_month_seq"], 23))) \
        .select(["d_date_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_class"])
    j = _oj(a["web_sales"], dd, ["ws_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ws_item_sk"], ["i_item_sk"])
    j = j.set_column(j.column_names.index("ws_ext_sales_price"),
                     "ws_ext_sales_price",
                     j["ws_ext_sales_price"].cast(pa.float64()))
    g = _oracle_rollup(j, ["i_category", "i_class"],
                       [("ws_ext_sales_price", "sum")], ["total_sum"])
    gid = g["spark_grouping_id"]
    loch = pc.add(pc.bit_wise_and(gid, 1), pc.shift_right(gid, 1))
    g = pa.table({"total_sum": g["total_sum"],
                  "i_category": g["i_category"],
                  "i_class": g["i_class"],
                  "lochierarchy": loch.cast(pa.int32())})
    return _topn(g, [("lochierarchy", "descending"),
                     ("total_sum", "descending"),
                     ("i_category", "ascending"),
                     ("i_class", "ascending")])


_q("q86", "web revenue ROLLUP(i_category, i_class) with hierarchy level")(
    (_q86_run, _q86_oracle))


# ===========================================================================
# EXISTS / IN-correlated family: Spark lowers these to semi/anti joins
# before the physical plan (RewritePredicateSubquery), which is exactly
# what the engine's semi/anti hash joins execute.
# ===========================================================================

def _q10_run(s, t):
    # q10: demographics of customers in selected counties WITH a store
    # purchase AND (web OR catalog purchase) in the period — the genuine
    # template's three EXISTS legs
    c = _rd(s, t, "customer").select("c_customer_sk", "c_current_cdemo_sk",
                                     "c_current_addr_sk")
    ca = _rd(s, t, "customer_address").filter(
        col("ca_county").isin("Ziebach County", "Walker County",
                              "Daviess County")) \
        .select("ca_address_sk")
    c = _join_dim(c, ca, "c_current_addr_sk", "ca_address_sk")
    ss = _rd(s, t, "store_sales").select("ss_customer_sk",
                                         "ss_sold_date_sk")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") >= 1) & (col("d_moy") <= 4)) \
        .select("d_date_sk")
    buyers = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk") \
        .select(col("ss_customer_sk").alias("c_customer_sk"))
    c = c.join(buyers, on="c_customer_sk", how="semi")
    wbuy, cbuy = _channel_buyers(s, t, dd)
    c = c.join(wbuy, on="c_customer_sk", how="existence")
    c = c.select(col("c_customer_sk"), col("c_current_cdemo_sk"),
                 col("exists").alias("web_ex"))
    c = c.join(cbuy, on="c_customer_sk", how="existence")
    c = c.filter(col("web_ex") | col("exists")) \
        .select("c_customer_sk", "c_current_cdemo_sk")
    cd = _rd(s, t, "customer_demographics").select(
        "cd_demo_sk", "cd_gender", "cd_marital_status",
        "cd_education_status")
    j = _join_dim(c, cd, "c_current_cdemo_sk", "cd_demo_sk")
    g = (j.group_by("cd_gender", "cd_marital_status",
                    "cd_education_status")
         .agg(F.count_star().alias("cnt")))
    return (g.sort(col("cd_gender").asc(), col("cd_marital_status").asc(),
                   col("cd_education_status").asc()).limit(100).collect())


def _q10_oracle(a):
    ca = a["customer_address"].filter(pc.is_in(
        a["customer_address"]["ca_county"],
        value_set=pa.array(["Ziebach County", "Walker County",
                            "Daviess County"]))).select(["ca_address_sk"])
    c = _oj(a["customer"], ca, ["c_current_addr_sk"], ["ca_address_sk"])
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2000),
        pc.and_(pc.greater_equal(a["date_dim"]["d_moy"], 1),
                pc.less_equal(a["date_dim"]["d_moy"], 4)))) \
        .select(["d_date_sk"])
    ss = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    buyers = ss.select(["ss_customer_sk"]).rename_columns(
        ["c_customer_sk"])
    c = _oj(c, buyers, ["c_customer_sk"], how="left semi")
    wset, cset = _oracle_channel_custs(a, dd)
    active = pa.array(sorted(wset | cset), pa.int64())
    c = c.filter(pc.is_in(c["c_customer_sk"], value_set=active))
    cd = a["customer_demographics"].select(
        ["cd_demo_sk", "cd_gender", "cd_marital_status",
         "cd_education_status"])
    j = _oj(c, cd, ["c_current_cdemo_sk"], ["cd_demo_sk"])
    g = j.group_by(["cd_gender", "cd_marital_status",
                    "cd_education_status"]).aggregate([([], "count_all")]) \
        .rename_columns(["cd_gender", "cd_marital_status",
                         "cd_education_status", "cnt"])
    return _topn(g, [("cd_gender", "ascending"),
                     ("cd_marital_status", "ascending"),
                     ("cd_education_status", "ascending")])


_q("q10", "county customers active in store AND (web OR catalog) "
          "(EXISTS as semi join)")((_q10_run, _q10_oracle))


def _q35_run(s, t):
    # q35: purchase-active customers' demographic aggregate battery —
    # EXISTS store purchase AND (EXISTS web OR EXISTS catalog), the
    # genuine template's three EXISTS legs (the web/catalog facts carry
    # bill-customer keys as of the generator's order-coherence work)
    c = _rd(s, t, "customer").select("c_customer_sk", "c_current_cdemo_sk",
                                     "c_birth_month")
    ss = _rd(s, t, "store_sales").select("ss_customer_sk",
                                         "ss_sold_date_sk")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2001) & (col("d_qoy") < 4)).select("d_date_sk")
    buyers = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk") \
        .select(col("ss_customer_sk").alias("c_customer_sk"))
    c = c.join(buyers, on="c_customer_sk", how="semi")
    wbuy, cbuy = _channel_buyers(s, t, dd)
    c = c.join(wbuy, on="c_customer_sk", how="existence")
    c = c.select(col("c_customer_sk"), col("c_current_cdemo_sk"),
                 col("c_birth_month"), col("exists").alias("web_ex"))
    c = c.join(cbuy, on="c_customer_sk", how="existence")
    c = c.filter(col("web_ex") | col("exists")) \
        .select("c_customer_sk", "c_current_cdemo_sk", "c_birth_month")
    cd = _rd(s, t, "customer_demographics").select(
        "cd_demo_sk", "cd_gender", "cd_marital_status", "cd_dep_count")
    j = _join_dim(c, cd, "c_current_cdemo_sk", "cd_demo_sk")
    g = (j.group_by("cd_gender", "cd_marital_status")
         .agg(F.count_star().alias("cnt"),
              F.avg(col("cd_dep_count").cast(DataType.FLOAT64))
              .alias("avg_dep"),
              F.max(col("cd_dep_count")).alias("max_dep"),
              F.sum(col("cd_dep_count")).alias("sum_dep")))
    return (g.sort(col("cd_gender").asc(),
                   col("cd_marital_status").asc()).limit(100).collect())


def _q35_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2001),
        pc.less(a["date_dim"]["d_qoy"], 4))).select(["d_date_sk"])
    ss = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    buyers = ss.select(["ss_customer_sk"]).rename_columns(
        ["c_customer_sk"])
    c = _oj(a["customer"], buyers, ["c_customer_sk"], how="left semi")
    wset, cset = _oracle_channel_custs(a, dd)
    active = pa.array(sorted(wset | cset), pa.int64())
    c = c.filter(pc.is_in(c["c_customer_sk"], value_set=active))
    cd = a["customer_demographics"].select(
        ["cd_demo_sk", "cd_gender", "cd_marital_status", "cd_dep_count"])
    j = _oj(c, cd, ["c_current_cdemo_sk"], ["cd_demo_sk"])
    j = j.append_column("dep_f", j["cd_dep_count"].cast(pa.float64()))
    g = j.group_by(["cd_gender", "cd_marital_status"]).aggregate(
        [([], "count_all"), ("dep_f", "mean"), ("cd_dep_count", "max"),
         ("cd_dep_count", "sum")]) \
        .rename_columns(["cd_gender", "cd_marital_status", "cnt",
                         "avg_dep", "max_dep", "sum_dep"])
    return _topn(g, [("cd_gender", "ascending"),
                     ("cd_marital_status", "ascending")])


_q("q35", "demographic battery: store buyers also active on web or "
          "catalog (3-channel EXISTS)")((_q35_run, _q35_oracle))


def _q69_run(s, t):
    # q69: store buyers in the period with NO web and NO catalog activity
    # in the same period — the genuine EXISTS + two NOT EXISTS legs
    c = _rd(s, t, "customer").select("c_customer_sk",
                                     "c_current_cdemo_sk")
    ss = _rd(s, t, "store_sales").select("ss_customer_sk",
                                         "ss_sold_date_sk")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_qoy") <= 2)).select("d_date_sk")
    buyers = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk") \
        .select(col("ss_customer_sk").alias("c_customer_sk"))
    wbuy, cbuy = _channel_buyers(s, t, dd)
    c = c.join(buyers, on="c_customer_sk", how="semi")
    c = c.join(wbuy, on="c_customer_sk", how="anti")
    c = c.join(cbuy, on="c_customer_sk", how="anti")
    cd = _rd(s, t, "customer_demographics").select(
        "cd_demo_sk", "cd_gender", "cd_marital_status",
        "cd_education_status")
    j = _join_dim(c, cd, "c_current_cdemo_sk", "cd_demo_sk")
    g = (j.group_by("cd_gender", "cd_marital_status",
                    "cd_education_status")
         .agg(F.count_star().alias("cnt")))
    return (g.sort(col("cd_gender").asc(), col("cd_marital_status").asc(),
                   col("cd_education_status").asc()).limit(100).collect())


def _q69_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2000),
        pc.less_equal(a["date_dim"]["d_qoy"], 2))).select(["d_date_sk"])
    ss = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    buyers = ss.select(["ss_customer_sk"]).rename_columns(
        ["c_customer_sk"])
    wset, cset = _oracle_channel_custs(a, dd)
    c = _oj(a["customer"], buyers, ["c_customer_sk"], how="left semi")
    inactive = pa.array(
        sorted(set(c.to_pandas().c_customer_sk.astype(int))
               - wset - cset), pa.int64())
    c = c.filter(pc.is_in(c["c_customer_sk"], value_set=inactive))
    cd = a["customer_demographics"].select(
        ["cd_demo_sk", "cd_gender", "cd_marital_status",
         "cd_education_status"])
    j = _oj(c, cd, ["c_current_cdemo_sk"], ["cd_demo_sk"])
    g = j.group_by(["cd_gender", "cd_marital_status",
                    "cd_education_status"]).aggregate([([], "count_all")]) \
        .rename_columns(["cd_gender", "cd_marital_status",
                         "cd_education_status", "cnt"])
    return _topn(g, [("cd_gender", "ascending"),
                     ("cd_marital_status", "ascending"),
                     ("cd_education_status", "ascending")])


_q("q69", "store-only buyers by demographics (EXISTS + 2 NOT EXISTS)")(
    (_q69_run, _q69_oracle))


def _q93_run(s, t):
    # q93: actual sales after returns — ss LEFT JOIN sr on
    # (ticket, item); returned quantity reduces the paid amount
    ss = _rd(s, t, "store_sales").select(
        "ss_ticket_number", "ss_item_sk", "ss_customer_sk",
        "ss_quantity", "ss_sales_price")
    sr = _rd(s, t, "store_returns").select(
        col("sr_ticket_number").alias("ss_ticket_number"),
        col("sr_item_sk").alias("ss_item_sk"),
        col("sr_return_quantity"))
    j = ss.join(sr, on=["ss_ticket_number", "ss_item_sk"], how="left")
    qty = col("ss_quantity").cast(DataType.FLOAT64)
    ret = col("sr_return_quantity").cast(DataType.FLOAT64)
    price = col("ss_sales_price").cast(DataType.FLOAT64)
    act = F.if_(col("sr_return_quantity").is_not_null(),
                (qty - ret) * price, qty * price)
    j = j.with_column("act_sales", act)
    g = (j.group_by("ss_customer_sk")
         .agg(F.sum(col("act_sales")).alias("sumsales")))
    return (g.sort(col("sumsales").asc(), col("ss_customer_sk").asc())
            .limit(100).collect())


def _q93_oracle(a):
    import pandas as pd
    ss = a["store_sales"].select(
        ["ss_ticket_number", "ss_item_sk", "ss_customer_sk",
         "ss_quantity", "ss_sales_price"]).to_pandas()
    sr = a["store_returns"].select(
        ["sr_ticket_number", "sr_item_sk", "sr_return_quantity"]) \
        .to_pandas()
    j = ss.merge(sr, how="left",
                 left_on=["ss_ticket_number", "ss_item_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk"])
    price = j.ss_sales_price.astype(float)
    qty = j.ss_quantity.astype(float)
    act = np.where(j.sr_return_quantity.notna(),
                   (qty - j.sr_return_quantity.fillna(0)) * price,
                   qty * price)
    j["act_sales"] = act
    g = j.groupby("ss_customer_sk", dropna=False)["act_sales"] \
        .sum().reset_index().rename(columns={"act_sales": "sumsales"})
    out = pa.Table.from_pandas(g, preserve_index=False)
    return _topn(out, [("sumsales", "ascending"),
                       ("ss_customer_sk", "ascending")])


_q("q93", "actual sales after returns per customer (ss left-join sr)")(
    (_q93_run, _q93_oracle))


# ===========================================================================
# multi-channel UNION family
# ===========================================================================

def _channel_legs(s, t, year, moy_lo, moy_hi):
    """(ss, cs, ws) legs normalized to (item_sk, ext_price) within the
    date window — the common scaffold of q60/q71."""
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == year) & (col("d_moy") >= moy_lo)
        & (col("d_moy") <= moy_hi)).select("d_date_sk")
    legs = []
    for fact, dk, ik, pk in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price")):
        f = _rd(s, t, fact).select(dk, ik, pk)
        f = _join_dim(f, dd, dk, "d_date_sk")
        legs.append(f.select(
            col(ik).alias("item_sk"),
            col(pk).cast(DataType.FLOAT64).alias("ext_price")))
    return legs


def _oracle_channel_legs(a, year, moy_lo, moy_hi):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], year),
        pc.and_(pc.greater_equal(a["date_dim"]["d_moy"], moy_lo),
                pc.less_equal(a["date_dim"]["d_moy"], moy_hi)))) \
        .select(["d_date_sk"])
    legs = []
    for fact, dk, ik, pk in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price")):
        f = _oj(a[fact].select([dk, ik, pk]), dd, [dk], ["d_date_sk"])
        legs.append(pa.table({
            "item_sk": f[ik],
            "ext_price": f[pk].cast(pa.float64())}))
    return legs


def _q60_run(s, t):
    # q60: total cross-channel revenue per item id in one category/month
    legs = _channel_legs(s, t, 1999, 8, 9)
    u = legs[0].union(legs[1]).union(legs[2])
    it = _rd(s, t, "item").filter(col("i_category") == "Music") \
        .select(col("i_item_sk").alias("item_sk"), col("i_item_id"))
    j = u.join(it, on="item_sk", how="inner")
    g = (j.group_by("i_item_id")
         .agg(F.sum(col("ext_price")).alias("total_sales")))
    return (g.sort(col("i_item_id").asc(), col("total_sales").asc())
            .limit(100).collect())


def _q60_oracle(a):
    legs = _oracle_channel_legs(a, 1999, 8, 9)
    u = pa.concat_tables(legs)
    it = a["item"].filter(pc.equal(a["item"]["i_category"], "Music")) \
        .select(["i_item_sk", "i_item_id"]) \
        .rename_columns(["item_sk", "i_item_id"])
    j = _oj(u, it, ["item_sk"])
    g = j.group_by(["i_item_id"]).aggregate([("ext_price", "sum")]) \
        .rename_columns(["i_item_id", "total_sales"])
    return _topn(g, [("i_item_id", "ascending"),
                     ("total_sales", "ascending")])


_q("q60", "cross-channel item revenue in one category (3-way UNION)")(
    (_q60_run, _q60_oracle))


def _q71_run(s, t):
    # q71-class: brand revenue across all three channels for one month
    # under one manager (the template also splits by time-of-day; only
    # the store fact carries a time key in this subset)
    legs = _channel_legs(s, t, 2000, 12, 12)
    u = legs[0].union(legs[1]).union(legs[2])
    it = _rd(s, t, "item").filter(col("i_manager_id") == 1) \
        .select(col("i_item_sk").alias("item_sk"), col("i_brand_id"),
                col("i_brand"))
    j = u.join(it, on="item_sk", how="inner")
    g = (j.group_by("i_brand_id", "i_brand")
         .agg(F.sum(col("ext_price")).alias("ext_price_sum")))
    return (g.sort(col("ext_price_sum").desc(), col("i_brand_id").asc())
            .limit(100).collect())


def _q71_oracle(a):
    legs = _oracle_channel_legs(a, 2000, 12, 12)
    u = pa.concat_tables(legs)
    it = a["item"].filter(pc.equal(a["item"]["i_manager_id"], 1)) \
        .select(["i_item_sk", "i_brand_id", "i_brand"]) \
        .rename_columns(["item_sk", "i_brand_id", "i_brand"])
    j = _oj(u, it, ["item_sk"])
    g = j.group_by(["i_brand_id", "i_brand"]).aggregate(
        [("ext_price", "sum")]) \
        .rename_columns(["i_brand_id", "i_brand", "ext_price_sum"])
    return _topn(g, [("ext_price_sum", "descending"),
                     ("i_brand_id", "ascending")])


_q("q71", "brand revenue across three channels for one manager/month")(
    (_q71_run, _q71_oracle))


def _q76_run(s, t):
    # q76: per-channel sales rows whose surrogate key is NULL, unioned
    # and counted by (channel, null-column tag, year, quarter, category)
    it = _rd(s, t, "item").select("i_item_sk", "i_category")
    dd = _rd(s, t, "date_dim").select("d_date_sk", "d_year", "d_qoy")
    legs = []
    for fact, dk, ik, pk, nullk, chan in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price", "ss_promo_sk", "store"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price", "cs_warehouse_sk", "catalog"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price", "ws_ship_mode_sk", "web")):
        f = _rd(s, t, fact).select(dk, ik, pk, nullk)
        f = f.filter(col(nullk).is_null())
        f = _join_dim(f, it, ik, "i_item_sk")
        f = _join_dim(f, dd, dk, "d_date_sk")
        legs.append(f.select(
            lit(chan, DataType.STRING).alias("channel"),
            lit(nullk, DataType.STRING).alias("col_name"),
            col("d_year"), col("d_qoy"), col("i_category"),
            col(pk).cast(DataType.FLOAT64).alias("ext_price")))
    u = legs[0].union(legs[1]).union(legs[2])
    g = (u.group_by("channel", "col_name", "d_year", "d_qoy",
                    "i_category")
         .agg(F.count_star().alias("sales_cnt"),
              F.sum(col("ext_price")).alias("sales_amt")))
    return (g.sort(col("channel").asc(), col("col_name").asc(),
                   col("d_year").asc(), col("d_qoy").asc(),
                   col("i_category").asc()).limit(200).collect())


def _q76_oracle(a):
    it = a["item"].select(["i_item_sk", "i_category"])
    dd = a["date_dim"].select(["d_date_sk", "d_year", "d_qoy"])
    legs = []
    for fact, dk, ik, pk, nullk, chan in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price", "ss_promo_sk", "store"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price", "cs_warehouse_sk", "catalog"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price", "ws_ship_mode_sk", "web")):
        f = a[fact].select([dk, ik, pk, nullk])
        f = f.filter(pc.is_null(f[nullk]))
        f = _oj(f, it, [ik], ["i_item_sk"])
        f = _oj(f, dd, [dk], ["d_date_sk"])
        legs.append(pa.table({
            # explicit string type: an EMPTY leg would otherwise infer
            # null-typed columns and break concat_tables
            "channel": pa.array([chan] * f.num_rows, pa.string()),
            "col_name": pa.array([nullk] * f.num_rows, pa.string()),
            "d_year": f["d_year"], "d_qoy": f["d_qoy"],
            "i_category": f["i_category"],
            "ext_price": f[pk].cast(pa.float64())}))
    u = pa.concat_tables(legs)
    g = u.group_by(["channel", "col_name", "d_year", "d_qoy",
                    "i_category"]).aggregate(
        [([], "count_all"), ("ext_price", "sum")]) \
        .rename_columns(["channel", "col_name", "d_year", "d_qoy",
                         "i_category", "sales_cnt", "sales_amt"])
    return _topn(g, [("channel", "ascending"), ("col_name", "ascending"),
                     ("d_year", "ascending"), ("d_qoy", "ascending"),
                     ("i_category", "ascending")], 200)


_q("q76", "null-key sales rows by channel (3-way UNION, wide group)")(
    (_q76_run, _q76_oracle))


# ===========================================================================
# q9: CASE buckets chosen by scalar subqueries (one-row projection)
# ===========================================================================

def _q9_run(s, t):
    ss = _rd(s, t, "store_sales")
    buckets = []
    for lo, hi in ((1, 20), (21, 40), (41, 60)):
        b = ss.filter((col("ss_quantity") >= lo)
                      & (col("ss_quantity") <= hi))
        cnt = scalar_subquery(
            b.group_by().agg(F.count_star().alias("c")))
        avg_paid = scalar_subquery(
            b.group_by().agg(
                F.avg(col("ss_net_paid").cast(DataType.FLOAT64))
                .alias("a")))
        avg_list = scalar_subquery(
            b.group_by().agg(
                F.avg(col("ss_ext_list_price").cast(DataType.FLOAT64))
                .alias("a")))
        buckets.append(F.if_(cnt > lit(1000, DataType.INT64),
                             avg_paid, avg_list))
    one = _rd(s, t, "date_dim").limit(1)
    return one.select(buckets[0].alias("bucket1"),
                      buckets[1].alias("bucket2"),
                      buckets[2].alias("bucket3")).collect()


def _q9_oracle(a):
    ss = a["store_sales"]
    out = {}
    for i, (lo, hi) in enumerate(((1, 20), (21, 40), (41, 60)), 1):
        m = pc.and_(pc.greater_equal(ss["ss_quantity"], lo),
                    pc.less_equal(ss["ss_quantity"], hi))
        b = ss.filter(m)
        if b.num_rows > 1000:
            v = pc.mean(b["ss_net_paid"].cast(pa.float64())).as_py()
        else:
            v = pc.mean(b["ss_ext_list_price"].cast(pa.float64())).as_py()
        out[f"bucket{i}"] = [v]
    return pa.table(out)


_q("q9", "quantity-bucket averages selected by scalar subqueries")(
    (_q9_run, _q9_oracle))


# ===========================================================================
# q40: catalog sales around a pivot date by warehouse (CASE split)
# ===========================================================================

def _q40_run(s, t):
    pivot = DATE_SK0 + 730
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_warehouse_sk",
        "cs_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_date_sk") >= pivot - 30) & (col("d_date_sk") <= pivot + 30)) \
        .select("d_date_sk")
    w = _rd(s, t, "warehouse").select("w_warehouse_sk", "w_warehouse_name")
    it = _rd(s, t, "item").filter(
        (col("i_current_price") >= lit(0.99))
        & (col("i_current_price") <= lit(150.00))) \
        .select("i_item_sk", "i_item_id")
    j = _join_dim(cs, dd, "cs_sold_date_sk", "d_date_sk")
    j = _join_dim(j, w, "cs_warehouse_sk", "w_warehouse_sk")
    j = _join_dim(j, it, "cs_item_sk", "i_item_sk")
    price = col("cs_sales_price").cast(DataType.FLOAT64)
    before = F.if_(col("cs_sold_date_sk") < lit(pivot, DataType.INT64),
                   price, lit(0.0))
    after = F.if_(col("cs_sold_date_sk") >= lit(pivot, DataType.INT64),
                  price, lit(0.0))
    j = j.with_column("before_amt", before).with_column("after_amt", after)
    g = (j.group_by("w_warehouse_name", "i_item_id")
         .agg(F.sum(col("before_amt")).alias("sales_before"),
              F.sum(col("after_amt")).alias("sales_after")))
    return (g.sort(col("w_warehouse_name").asc(), col("i_item_id").asc())
            .limit(100).collect())


def _q40_oracle(a):
    pivot = DATE_SK0 + 730
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_date_sk"], pivot - 30),
        pc.less_equal(a["date_dim"]["d_date_sk"], pivot + 30))) \
        .select(["d_date_sk"])
    w = a["warehouse"].select(["w_warehouse_sk", "w_warehouse_name"])
    it = a["item"].filter(pc.and_(
        pc.greater_equal(a["item"]["i_current_price"].cast(pa.float64()),
                         0.99),
        pc.less_equal(a["item"]["i_current_price"].cast(pa.float64()),
                      150.0))).select(["i_item_sk", "i_item_id"])
    j = _oj(a["catalog_sales"], dd, ["cs_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, w, ["cs_warehouse_sk"], ["w_warehouse_sk"])
    j = _oj(j, it, ["cs_item_sk"], ["i_item_sk"])
    price = j["cs_sales_price"].cast(pa.float64())
    isb = pc.less(j["cs_sold_date_sk"], pivot)
    j = j.append_column("before_amt",
                        pc.if_else(isb, price, pa.scalar(0.0)))
    j = j.append_column("after_amt",
                        pc.if_else(pc.invert(isb), price, pa.scalar(0.0)))
    g = j.group_by(["w_warehouse_name", "i_item_id"]).aggregate(
        [("before_amt", "sum"), ("after_amt", "sum")]) \
        .rename_columns(["w_warehouse_name", "i_item_id",
                         "sales_before", "sales_after"])
    return _topn(g, [("w_warehouse_name", "ascending"),
                     ("i_item_id", "ascending")])


_q("q40", "catalog sales before/after a pivot date by warehouse (CASE)")(
    (_q40_run, _q40_oracle))


# ===========================================================================
# q47: monthly brand sales vs centered moving average (ROWS frame window)
# ===========================================================================

def _q47_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_sales_price", "ss_quantity")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") >= 1999) & (col("d_year") <= 2001)) \
        .select("d_date_sk", "d_year", "d_moy")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_brand")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    j = j.with_column(
        "amt", col("ss_sales_price").cast(DataType.FLOAT64)
        * col("ss_quantity").cast(DataType.FLOAT64))
    g = (j.group_by("i_category", "i_brand", "d_year", "d_moy")
         .agg(F.sum(col("amt")).alias("sum_sales")))
    # centered 3-month moving average within each brand's month series
    g = g.window(
        [F.win_agg("avg", col("sum_sales"), frame=(-1, 1)).alias("avg3")],
        partition_by=[col("i_category"), col("i_brand")],
        order_by=[col("d_year").asc(), col("d_moy").asc()])
    # q47 reports months deviating from their local average
    g = g.with_column("dev", col("sum_sales") - col("avg3"))
    g = g.filter((col("d_year") == 2000)
                 & ((col("dev") > lit(0.0)) | (col("dev") < lit(0.0))))
    return (g.select("i_category", "i_brand", "d_year", "d_moy",
                     "sum_sales", "avg3")
            .sort(col("i_category").asc(), col("i_brand").asc(),
                  col("d_year").asc(), col("d_moy").asc())
            .limit(100).collect())


def _q47_oracle(a):
    import pandas as pd
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_year"], 1999),
        pc.less_equal(a["date_dim"]["d_year"], 2001))) \
        .select(["d_date_sk", "d_year", "d_moy"])
    it = a["item"].select(["i_item_sk", "i_category", "i_brand"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    df = j.to_pandas()
    df["amt"] = df.ss_sales_price.astype(float) \
        * df.ss_quantity.astype(float)
    g = df.groupby(["i_category", "i_brand", "d_year", "d_moy"],
                   dropna=False)["amt"].sum().reset_index() \
        .rename(columns={"amt": "sum_sales"})
    g = g.sort_values(["i_category", "i_brand", "d_year", "d_moy"])
    g["avg3"] = g.groupby(["i_category", "i_brand"])["sum_sales"] \
        .transform(lambda x: x.rolling(3, center=True,
                                       min_periods=1).mean())
    g["dev"] = g.sum_sales - g.avg3
    g = g[(g.d_year == 2000) & (g.dev != 0.0)]
    g = g[["i_category", "i_brand", "d_year", "d_moy", "sum_sales",
           "avg3"]]
    g = g.sort_values(["i_category", "i_brand", "d_year", "d_moy"]) \
        .head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q47", "monthly brand sales vs centered moving average (ROWS frame)")(
    (_q47_run, _q47_oracle))


# ===========================================================================
# q13: store sales averages under OR-of-AND demographic/address triples
# ===========================================================================

def _q13_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_cdemo_sk", "ss_hdemo_sk",
        "ss_addr_sk", "ss_quantity", "ss_ext_sales_price",
        "ss_ext_wholesale_cost", "ss_sales_price", "ss_net_profit")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2001) \
        .select("d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    cd = _rd(s, t, "customer_demographics").select(
        "cd_demo_sk", "cd_marital_status", "cd_education_status")
    hd = _rd(s, t, "household_demographics").select(
        "hd_demo_sk", "hd_dep_count")
    ca = _rd(s, t, "customer_address").filter(
        col("ca_country") == "United States") \
        .select("ca_address_sk", "ca_state")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, cd, "ss_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, ca, "ss_addr_sk", "ca_address_sk")
    demo = (((col("cd_marital_status") == "M")
             & (col("cd_education_status") == "College")
             & (col("hd_dep_count") == 3))
            | ((col("cd_marital_status") == "S")
               & (col("cd_education_status") == "Primary")
               & (col("hd_dep_count") == 1))
            | ((col("cd_marital_status") == "W")
               & (col("cd_education_status") == "2 yr Degree")
               & (col("hd_dep_count") == 0)))
    geo = (col("ca_state").isin("TX", "OH", "KY")
           | col("ca_state").isin("CA", "WA", "GA")
           | col("ca_state").isin("NY", "IL", "MI"))
    j = j.filter(demo & geo)
    return (j.group_by()
            .agg(F.avg(col("ss_quantity")).alias("avg_qty"),
                 F.avg(col("ss_ext_sales_price").cast(DataType.FLOAT64))
                 .alias("avg_esp"),
                 F.avg(col("ss_ext_wholesale_cost").cast(DataType.FLOAT64))
                 .alias("avg_ewc"),
                 F.sum(col("ss_ext_wholesale_cost")).alias("sum_ewc"))
            .collect())


def _q13_oracle(a):
    import pandas as pd
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2001)) \
        .select(["d_date_sk"])
    cd = a["customer_demographics"].select(
        ["cd_demo_sk", "cd_marital_status", "cd_education_status"])
    hd = a["household_demographics"].select(["hd_demo_sk", "hd_dep_count"])
    ca = a["customer_address"].filter(
        pc.equal(a["customer_address"]["ca_country"], "United States")) \
        .select(["ca_address_sk", "ca_state"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, cd, ["ss_cdemo_sk"], ["cd_demo_sk"])
    j = _oj(j, hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, ca, ["ss_addr_sk"], ["ca_address_sk"])
    df = j.to_pandas()
    demo = (((df.cd_marital_status == "M")
             & (df.cd_education_status == "College")
             & (df.hd_dep_count == 3))
            | ((df.cd_marital_status == "S")
               & (df.cd_education_status == "Primary")
               & (df.hd_dep_count == 1))
            | ((df.cd_marital_status == "W")
               & (df.cd_education_status == "2 yr Degree")
               & (df.hd_dep_count == 0)))
    geo = df.ca_state.isin(["TX", "OH", "KY", "CA", "WA", "GA",
                            "NY", "IL", "MI"])
    df = df[demo & geo]
    return pa.Table.from_pydict({
        "avg_qty": [float(df.ss_quantity.mean())],
        "avg_esp": [float(df.ss_ext_sales_price.astype(float).mean())],
        "avg_ewc": [float(df.ss_ext_wholesale_cost.astype(float).mean())],
        "sum_ewc": [df.ss_ext_wholesale_cost.sum()],
    })


_q("q13", "store sales averages under OR'd demographic triples")(
    (_q13_run, _q13_oracle))


# ===========================================================================
# q15: catalog sales by customer zip (zip/state/price OR filter)
# ===========================================================================

def _q15_run(s, t):
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_bill_customer_sk", "cs_sales_price")
    c = _rd(s, t, "customer").select("c_customer_sk", "c_current_addr_sk")
    ca = _rd(s, t, "customer_address").select(
        "ca_address_sk", "ca_state", "ca_zip")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_qoy") == 2) & (col("d_year") == 2001)).select("d_date_sk")
    j = _join_dim(cs, c, "cs_bill_customer_sk", "c_customer_sk")
    j = _join_dim(j, ca, "c_current_addr_sk", "ca_address_sk")
    j = _join_dim(j, dd, "cs_sold_date_sk", "d_date_sk")
    keep = (F.substring(col("ca_zip"), lit(1), lit(2))
            .isin("85", "86", "88")
            | col("ca_state").isin("CA", "WA", "GA")
            | (col("cs_sales_price") > lit(250.00)))
    j = j.filter(keep)
    return (j.group_by("ca_zip")
            .agg(F.sum(col("cs_sales_price")).alias("total"))
            .sort(col("ca_zip").asc()).limit(100).collect())


def _q15_oracle(a):
    import pandas as pd
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_qoy"], 2),
        pc.equal(a["date_dim"]["d_year"], 2001))).select(["d_date_sk"])
    j = _oj(a["catalog_sales"], a["customer"],
            ["cs_bill_customer_sk"], ["c_customer_sk"])
    j = _oj(j, a["customer_address"], ["c_current_addr_sk"],
            ["ca_address_sk"])
    j = _oj(j, dd, ["cs_sold_date_sk"], ["d_date_sk"])
    df = j.to_pandas()
    keep = (df.ca_zip.str[:2].isin(["85", "86", "88"])
            | df.ca_state.isin(["CA", "WA", "GA"])
            | (df.cs_sales_price.astype(float) > 250.0))
    g = df[keep].groupby("ca_zip")["cs_sales_price"].sum().reset_index() \
        .rename(columns={"cs_sales_price": "total"}) \
        .sort_values("ca_zip").head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q15", "catalog sales by customer zip under zip/state/price OR")(
    (_q15_run, _q15_oracle))


# ===========================================================================
# q16: catalog orders shipped from one state with multi-warehouse EXISTS
#      and no-returns NOT EXISTS (count distinct orders)
# ===========================================================================

def _q16_run(s, t):
    d0 = DATE_SK0 + 3 * 365 + 31            # 2001-02-01 class
    cs = _rd(s, t, "catalog_sales").select(
        "cs_ship_date_sk", "cs_ship_addr_sk", "cs_call_center_sk",
        "cs_warehouse_sk", "cs_order_number", "cs_ext_ship_cost",
        "cs_net_profit")
    cs = cs.filter((col("cs_ship_date_sk") >= lit(d0, DataType.INT64))
                   & (col("cs_ship_date_sk") <= lit(d0 + 60,
                                                    DataType.INT64)))
    ca = _rd(s, t, "customer_address").filter(col("ca_state") == "CA") \
        .select("ca_address_sk")
    cc = _rd(s, t, "call_center").select("cc_call_center_sk")
    j = _join_dim(cs, ca, "cs_ship_addr_sk", "ca_address_sk")
    j = _join_dim(j, cc, "cs_call_center_sk", "cc_call_center_sk")
    # EXISTS cs2 with same order, different warehouse: orders whose
    # distinct-warehouse count exceeds 1 (the standard decorrelation)
    all_cs = _rd(s, t, "catalog_sales").select("cs_order_number",
                                               "cs_warehouse_sk")
    multi = (all_cs.group_by("cs_order_number")
             .agg(F.count(col("cs_warehouse_sk"), distinct=True)
                  .alias("n_wh"))
             .filter(col("n_wh") > 1).select("cs_order_number"))
    j = j.join(multi, on="cs_order_number", how="semi")
    # NOT EXISTS catalog return for the order
    cr = _rd(s, t, "catalog_returns").select(
        col("cr_order_number").alias("cs_order_number"))
    j = j.join(cr, on="cs_order_number", how="anti")
    return (j.group_by()
            .agg(F.count(col("cs_order_number"), distinct=True)
                 .alias("order_count"),
                 F.sum(col("cs_ext_ship_cost")).alias("total_ship"),
                 F.sum(col("cs_net_profit")).alias("total_profit"))
            .collect())


def _q16_oracle(a):
    import pandas as pd
    d0 = DATE_SK0 + 3 * 365 + 31
    cs = a["catalog_sales"].to_pandas()
    sel = cs[(cs.cs_ship_date_sk >= d0) & (cs.cs_ship_date_sk <= d0 + 60)]
    ca = a["customer_address"].to_pandas()
    ca_ok = set(ca[ca.ca_state == "CA"].ca_address_sk)
    sel = sel[sel.cs_ship_addr_sk.isin(ca_ok)
              & sel.cs_call_center_sk.notna()]
    nwh = cs.groupby("cs_order_number")["cs_warehouse_sk"].nunique()
    multi = set(nwh[nwh > 1].index)
    returned = set(a["catalog_returns"].to_pandas().cr_order_number)
    sel = sel[sel.cs_order_number.isin(multi)
              & ~sel.cs_order_number.isin(returned)]
    return pa.Table.from_pydict({
        "order_count": [sel.cs_order_number.nunique()],
        "total_ship": [sel.cs_ext_ship_cost.sum()],
        "total_profit": [sel.cs_net_profit.sum()],
    })


_q("q16", "shipped catalog orders: multi-warehouse EXISTS, no returns")(
    (_q16_run, _q16_oracle))


# ===========================================================================
# q21: inventory before/after a pivot date by warehouse/item, ratio band
# ===========================================================================

def _q21_run(s, t):
    pivot = DATE_SK0 + 2 * 365 + 60
    inv = _rd(s, t, "inventory").filter(
        (col("inv_date_sk") >= lit(pivot - 30, DataType.INT64))
        & (col("inv_date_sk") <= lit(pivot + 30, DataType.INT64)))
    w = _rd(s, t, "warehouse").select("w_warehouse_sk", "w_warehouse_name")
    it = _rd(s, t, "item").filter(
        (col("i_current_price") >= lit(5.00))
        & (col("i_current_price") <= lit(50.00))) \
        .select("i_item_sk", "i_item_id")
    j = _join_dim(inv, w, "inv_warehouse_sk", "w_warehouse_sk")
    j = _join_dim(j, it, "inv_item_sk", "i_item_sk")
    qty = col("inv_quantity_on_hand")
    before = F.if_(col("inv_date_sk") < lit(pivot, DataType.INT64), qty,
                   lit(0, DataType.INT64))
    after = F.if_(col("inv_date_sk") >= lit(pivot, DataType.INT64), qty,
                  lit(0, DataType.INT64))
    j = j.with_column("qb", before).with_column("qa", after)
    g = (j.group_by("w_warehouse_name", "i_item_id")
         .agg(F.sum(col("qb")).alias("inv_before"),
              F.sum(col("qa")).alias("inv_after")))
    ratio_ok = ((col("inv_before") > lit(0, DataType.INT64))
                & (col("inv_after").cast(DataType.FLOAT64)
                   / col("inv_before").cast(DataType.FLOAT64)
                   >= lit(2.0 / 3.0))
                & (col("inv_after").cast(DataType.FLOAT64)
                   / col("inv_before").cast(DataType.FLOAT64)
                   <= lit(3.0 / 2.0)))
    return (g.filter(ratio_ok)
            .sort(col("w_warehouse_name").asc(), col("i_item_id").asc())
            .limit(100).collect())


def _q21_oracle(a):
    import pandas as pd
    pivot = DATE_SK0 + 2 * 365 + 60
    inv = a["inventory"].to_pandas()
    inv = inv[(inv.inv_date_sk >= pivot - 30)
              & (inv.inv_date_sk <= pivot + 30)]
    it = a["item"].to_pandas()
    it = it[(it.i_current_price.astype(float) >= 5.00)
            & (it.i_current_price.astype(float) <= 50.00)]
    w = a["warehouse"].to_pandas()
    j = inv.merge(w, left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
    j = j.merge(it, left_on="inv_item_sk", right_on="i_item_sk")
    j["qb"] = j.inv_quantity_on_hand.where(j.inv_date_sk < pivot, 0)
    j["qa"] = j.inv_quantity_on_hand.where(j.inv_date_sk >= pivot, 0)
    g = j.groupby(["w_warehouse_name", "i_item_id"])[["qb", "qa"]] \
        .sum().reset_index() \
        .rename(columns={"qb": "inv_before", "qa": "inv_after"})
    r = g.inv_after / g.inv_before.where(g.inv_before > 0)
    g = g[(g.inv_before > 0) & (r >= 2.0 / 3.0) & (r <= 3.0 / 2.0)]
    g = g.sort_values(["w_warehouse_name", "i_item_id"]).head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q21", "inventory before/after pivot by warehouse/item, ratio band")(
    (_q21_run, _q21_oracle))


# ===========================================================================
# q25: customers who bought in store, returned, then bought by catalog
# ===========================================================================

def _q25_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_customer_sk",
        "ss_ticket_number", "ss_net_profit")
    sr = _rd(s, t, "store_returns").select(
        "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
        "sr_ticket_number", "sr_net_loss")
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk",
        "cs_net_profit")
    d1 = _rd(s, t, "date_dim").filter(
        (col("d_moy") >= 1) & (col("d_moy") <= 6)
        & (col("d_year") == 2000)).select(
        col("d_date_sk").alias("ss_sold_date_sk"))
    d2 = _rd(s, t, "date_dim").filter(
        (col("d_moy") >= 1) & (col("d_moy") <= 12)
        & (col("d_year") == 2000)).select(
        col("d_date_sk").alias("sr_returned_date_sk"))
    d3 = _rd(s, t, "date_dim").filter(
        (col("d_moy") >= 1) & (col("d_moy") <= 12)
        & (col("d_year").isin(2000, 2001))).select(
        col("d_date_sk").alias("cs_sold_date_sk"))
    st = _rd(s, t, "store").select("s_store_sk", "s_store_id",
                                   "s_store_name")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id", "i_item_desc")
    j = ss.join(d1, on="ss_sold_date_sk", how="inner")
    j = j.join(_rename(sr, sr_item_sk="ss_item_sk",
                       sr_customer_sk="ss_customer_sk",
                       sr_ticket_number="ss_ticket_number"),
               on=["ss_item_sk", "ss_customer_sk", "ss_ticket_number"],
               how="inner")
    j = j.join(d2, on="sr_returned_date_sk", how="inner")
    j = j.join(_rename(cs, cs_item_sk="ss_item_sk",
                       cs_bill_customer_sk="ss_customer_sk"),
               on=["ss_item_sk", "ss_customer_sk"], how="inner")
    j = j.join(d3, on="cs_sold_date_sk", how="inner")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    return (j.group_by("i_item_id", "i_item_desc", "s_store_id",
                       "s_store_name")
            .agg(F.sum(col("ss_net_profit")).alias("store_profit"),
                 F.sum(col("sr_net_loss")).alias("return_loss"),
                 F.sum(col("cs_net_profit")).alias("catalog_profit"))
            .sort(col("i_item_id").asc(), col("i_item_desc").asc(),
                  col("s_store_id").asc())
            .limit(100).collect())


def _q25_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    d1 = set(dd[(dd.d_moy >= 1) & (dd.d_moy <= 6)
                 & (dd.d_year == 2000)].d_date_sk)
    d2 = set(dd[(dd.d_year == 2000)].d_date_sk)
    d3 = set(dd[dd.d_year.isin([2000, 2001])].d_date_sk)
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(d1) & ss.ss_customer_sk.notna()]
    sr = a["store_returns"].to_pandas()
    sr = sr[sr.sr_returned_date_sk.isin(d2)
            & sr.sr_customer_sk.notna()]
    cs = a["catalog_sales"].to_pandas()
    cs = cs[cs.cs_sold_date_sk.isin(d3)
            & cs.cs_bill_customer_sk.notna()]
    j = ss.merge(sr, left_on=["ss_item_sk", "ss_customer_sk",
                              "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_customer_sk",
                           "sr_ticket_number"])
    j = j.merge(cs, left_on=["ss_item_sk", "ss_customer_sk"],
                right_on=["cs_item_sk", "cs_bill_customer_sk"])
    j = j.merge(a["store"].to_pandas(), left_on="ss_store_sk",
                right_on="s_store_sk")
    j = j.merge(a["item"].to_pandas(), left_on="ss_item_sk",
                right_on="i_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "s_store_id",
                   "s_store_name"])[
        ["ss_net_profit", "sr_net_loss", "cs_net_profit"]] \
        .sum().reset_index() \
        .rename(columns={"ss_net_profit": "store_profit",
                         "sr_net_loss": "return_loss",
                         "cs_net_profit": "catalog_profit"})
    g = g.sort_values(["i_item_id", "i_item_desc", "s_store_id"]) \
        .head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q25", "store buy -> return -> catalog re-buy profit by item/store")(
    (_q25_run, _q25_oracle))


# ===========================================================================
# q32: catalog discounts exceeding 1.3x the item's period average
# ===========================================================================

def _q32_run(s, t):
    d0 = DATE_SK0 + 2 * 365 + 26
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_ext_discount_amt")
    cs = cs.filter((col("cs_sold_date_sk") >= lit(d0, DataType.INT64))
                   & (col("cs_sold_date_sk") <= lit(d0 + 90,
                                                    DataType.INT64)))
    it = _rd(s, t, "item").filter(col("i_manufact_id") <= 100) \
        .select("i_item_sk")
    j = _join_dim(cs, it, "cs_item_sk", "i_item_sk")
    per_item = (j.group_by("cs_item_sk")
                .agg(F.avg(col("cs_ext_discount_amt")
                           .cast(DataType.FLOAT64)).alias("avg_disc")))
    j2 = j.join(per_item, on="cs_item_sk", how="inner")
    j2 = j2.filter(col("cs_ext_discount_amt").cast(DataType.FLOAT64)
                   > lit(1.3) * col("avg_disc"))
    return (j2.group_by()
            .agg(F.sum(col("cs_ext_discount_amt"))
                 .alias("excess_discount"))
            .collect())


def _q32_oracle(a):
    import pandas as pd
    d0 = DATE_SK0 + 2 * 365 + 26
    it = a["item"].to_pandas()
    ok_items = set(it[it.i_manufact_id <= 100].i_item_sk)
    cs = a["catalog_sales"].to_pandas()
    cs = cs[(cs.cs_sold_date_sk >= d0) & (cs.cs_sold_date_sk <= d0 + 90)
            & cs.cs_item_sk.isin(ok_items)].copy()
    cs["disc"] = cs.cs_ext_discount_amt.astype(float)
    avg = cs.groupby("cs_item_sk")["disc"].transform("mean")
    sel = cs[cs.disc > 1.3 * avg]
    return pa.Table.from_pydict(
        {"excess_discount": [sel.cs_ext_discount_amt.sum()]})


_q("q32", "catalog discounts exceeding 1.3x item-period average")(
    (_q32_run, _q32_oracle))


# ===========================================================================
# q34: 8..20-line tickets by household profile, with customer names
# (the genuine template counts 15..20; the bound is a tuned parameter so
# the generated tickets, averaging ~6 lines, keep the gate nonempty)
# ===========================================================================

def _q34_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_customer_sk",
        "ss_ticket_number")
    dd = _rd(s, t, "date_dim").filter(
        ((col("d_dom") >= 1) & (col("d_dom") <= 3)
         | (col("d_dom") >= 25) & (col("d_dom") <= 28))
        & col("d_year").isin(1999, 2000, 2001)).select("d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    hd = _rd(s, t, "household_demographics").filter(
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > 0)).select("hd_demo_sk")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, hd, "ss_hdemo_sk", "hd_demo_sk")
    g = (j.group_by("ss_ticket_number", "ss_customer_sk")
         .agg(F.count_star().alias("cnt"))
         .filter((col("cnt") >= 8) & (col("cnt") <= 20)))
    c = _rd(s, t, "customer").select(
        col("c_customer_sk").alias("ss_customer_sk"),
        col("c_first_name"), col("c_last_name"))
    g = g.join(c, on="ss_customer_sk", how="inner")
    return (g.sort(col("c_last_name").asc(), col("c_first_name").asc(),
                   col("cnt").desc(), col("ss_ticket_number").asc())
            .limit(200).collect())


def _q34_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(((dd.d_dom >= 1) & (dd.d_dom <= 3))
                   | ((dd.d_dom >= 25) & (dd.d_dom <= 28)))
                  & dd.d_year.isin([1999, 2000, 2001])].d_date_sk)
    hd = a["household_demographics"].to_pandas()
    hds = set(hd[hd.hd_buy_potential.isin([">10000", "Unknown"])
                 & (hd.hd_vehicle_count > 0)].hd_demo_sk)
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(days) & ss.ss_hdemo_sk.isin(hds)]
    g = ss.groupby(["ss_ticket_number", "ss_customer_sk"],
                   dropna=False).size().reset_index(name="cnt")
    g = g[(g.cnt >= 8) & (g.cnt <= 20)]
    c = a["customer"].to_pandas()[["c_customer_sk", "c_first_name",
                                   "c_last_name"]]
    g = g.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
    g = g[["ss_ticket_number", "ss_customer_sk", "cnt", "c_first_name",
           "c_last_name"]]
    g = g.sort_values(["c_last_name", "c_first_name", "cnt",
                       "ss_ticket_number"],
                      ascending=[True, True, False, True]).head(200)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q34", "8..20-line tickets by household profile with names")(
    (_q34_run, _q34_oracle))


# ===========================================================================
# q37: items with mid inventory on hand sold by catalog in the window
# ===========================================================================

def _q37_run(s, t):
    d0 = DATE_SK0 + 2 * 365 + 90
    it = _rd(s, t, "item").filter(
        (col("i_current_price") >= lit(10.00))
        & (col("i_current_price") <= lit(60.00))
        & (col("i_manufact_id") <= 400)) \
        .select("i_item_sk", "i_item_id", "i_item_desc", "i_current_price")
    inv = _rd(s, t, "inventory").filter(
        (col("inv_quantity_on_hand") >= 100)
        & (col("inv_quantity_on_hand") <= 500)
        & (col("inv_date_sk") >= lit(d0, DataType.INT64))
        & (col("inv_date_sk") <= lit(d0 + 60, DataType.INT64))) \
        .select("inv_item_sk")
    cs = _rd(s, t, "catalog_sales").select(
        col("cs_item_sk").alias("i_item_sk"))
    j = it.join(_rename(inv, inv_item_sk="i_item_sk"), on="i_item_sk",
                how="semi")
    j = j.join(cs, on="i_item_sk", how="semi")
    return (j.group_by("i_item_id", "i_item_desc", "i_current_price")
            .agg(F.count_star().alias("n"))
            .sort(col("i_item_id").asc()).limit(100)
            .select("i_item_id", "i_item_desc", "i_current_price")
            .collect())


def _q37_oracle(a):
    import pandas as pd
    d0 = DATE_SK0 + 2 * 365 + 90
    it = a["item"].to_pandas()
    it = it[(it.i_current_price.astype(float) >= 10.0)
            & (it.i_current_price.astype(float) <= 60.0)
            & (it.i_manufact_id <= 400)]
    inv = a["inventory"].to_pandas()
    inv_ok = set(inv[(inv.inv_quantity_on_hand >= 100)
                     & (inv.inv_quantity_on_hand <= 500)
                     & (inv.inv_date_sk >= d0)
                     & (inv.inv_date_sk <= d0 + 60)].inv_item_sk)
    cs_ok = set(a["catalog_sales"].to_pandas().cs_item_sk.dropna())
    it = it[it.i_item_sk.isin(inv_ok) & it.i_item_sk.isin(cs_ok)]
    g = it.drop_duplicates(
        subset=["i_item_id", "i_item_desc", "i_current_price"]) \
        .sort_values("i_item_id").head(100)
    g = g[["i_item_id", "i_item_desc", "i_current_price"]]
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q37", "mid-inventory catalog items in a 60-day window")(
    (_q37_run, _q37_oracle))


# ===========================================================================
# q90: web sales AM/PM ratio for a page/demographic slice
# ===========================================================================

def _q90_run(s, t):
    ws = _rd(s, t, "web_sales").select(
        "ws_sold_time_sk", "ws_ship_hdemo_sk", "ws_web_page_sk")
    hd = _rd(s, t, "household_demographics").filter(
        col("hd_dep_count") == 6).select("hd_demo_sk")
    wp = _rd(s, t, "web_page").filter(
        (col("wp_char_count") >= 2000) & (col("wp_char_count") <= 6000)) \
        .select("wp_web_page_sk")
    td_am = _rd(s, t, "time_dim").filter(
        (col("t_hour") >= 8) & (col("t_hour") <= 9)) \
        .select(col("t_time_sk").alias("ws_sold_time_sk"))
    td_pm = _rd(s, t, "time_dim").filter(
        (col("t_hour") >= 19) & (col("t_hour") <= 20)) \
        .select(col("t_time_sk").alias("ws_sold_time_sk"))
    base = _join_dim(ws, hd, "ws_ship_hdemo_sk", "hd_demo_sk")
    base = _join_dim(base, wp, "ws_web_page_sk", "wp_web_page_sk")
    am = base.join(td_am, on="ws_sold_time_sk", how="semi") \
        .group_by().agg(F.count_star().alias("amc"))
    pm = base.join(td_pm, on="ws_sold_time_sk", how="semi") \
        .group_by().agg(F.count_star().alias("pmc"))
    from auron_tpu.frontend.dataframe import scalar_subquery
    ratio = (base.group_by()
             .agg(F.count_star().alias("n"))
             .select((scalar_subquery(am).cast(DataType.FLOAT64)
                      / scalar_subquery(pm).cast(DataType.FLOAT64))
                     .alias("am_pm_ratio")))
    return ratio.collect()


def _q90_oracle(a):
    import pandas as pd
    hd = a["household_demographics"].to_pandas()
    hds = set(hd[hd.hd_dep_count == 6].hd_demo_sk)
    wp = a["web_page"].to_pandas()
    wps = set(wp[(wp.wp_char_count >= 2000)
                 & (wp.wp_char_count <= 6000)].wp_web_page_sk)
    ws = a["web_sales"].to_pandas()
    base = ws[ws.ws_ship_hdemo_sk.isin(hds)
              & ws.ws_web_page_sk.isin(wps)]
    am = ((base.ws_sold_time_sk // 60 >= 8)
          & (base.ws_sold_time_sk // 60 <= 9)).sum()
    pm = ((base.ws_sold_time_sk // 60 >= 19)
          & (base.ws_sold_time_sk // 60 <= 20)).sum()
    return pa.Table.from_pydict(
        {"am_pm_ratio": [float(am) / float(pm)]})


_q("q90", "web sales AM/PM ratio for a page/demographic slice")(
    (_q90_run, _q90_oracle))


# ===========================================================================
# q44: best and worst performing items by store net profit (rank windows)
# ===========================================================================

def _q44_run(s, t):
    ss = _rd(s, t, "store_sales").filter(col("ss_store_sk") == 4) \
        .select("ss_item_sk", "ss_net_profit")
    g = (ss.group_by("ss_item_sk")
         .agg(F.avg(col("ss_net_profit").cast(DataType.FLOAT64))
              .alias("rank_col")))
    ranked_best = g.window([F.rank().alias("rnk")],
                           order_by=[col("rank_col").desc()])
    ranked_worst = g.window([F.rank().alias("rnk")],
                            order_by=[col("rank_col").asc()])
    best = ranked_best.filter(col("rnk") <= 10) \
        .select(col("rnk"), col("ss_item_sk").alias("best_performing"))
    worst = ranked_worst.filter(col("rnk") <= 10) \
        .select(col("rnk"), col("ss_item_sk").alias("worst_performing"))
    j = best.join(worst, on="rnk", how="inner")
    it1 = _rd(s, t, "item").select(
        col("i_item_sk").alias("best_performing"),
        col("i_item_id").alias("best_id"))
    it2 = _rd(s, t, "item").select(
        col("i_item_sk").alias("worst_performing"),
        col("i_item_id").alias("worst_id"))
    j = j.join(it1, on="best_performing", how="inner")
    j = j.join(it2, on="worst_performing", how="inner")
    return (j.select("rnk", "best_id", "worst_id")
            .sort(col("rnk").asc()).collect())


def _q44_oracle(a):
    import pandas as pd
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_store_sk == 4]
    g = ss.groupby("ss_item_sk")["ss_net_profit"].apply(
        lambda x: x.astype(float).mean()).reset_index(name="rank_col")
    g_best = g.sort_values(["rank_col", "ss_item_sk"],
                           ascending=[False, True]).reset_index(drop=True)
    g_best["rnk"] = g_best.rank_col.rank(method="min", ascending=False) \
        .astype(int)
    g_worst = g.copy()
    g_worst["rnk"] = g_worst.rank_col.rank(method="min", ascending=True) \
        .astype(int)
    b = g_best[g_best.rnk <= 10][["rnk", "ss_item_sk"]] \
        .rename(columns={"ss_item_sk": "best_performing"})
    w = g_worst[g_worst.rnk <= 10][["rnk", "ss_item_sk"]] \
        .rename(columns={"ss_item_sk": "worst_performing"})
    j = b.merge(w, on="rnk")
    it = a["item"].to_pandas()[["i_item_sk", "i_item_id"]]
    j = j.merge(it.rename(columns={"i_item_sk": "best_performing",
                                   "i_item_id": "best_id"}),
                on="best_performing")
    j = j.merge(it.rename(columns={"i_item_sk": "worst_performing",
                                   "i_item_id": "worst_id"}),
                on="worst_performing")
    j = j[["rnk", "best_id", "worst_id"]].sort_values("rnk")
    return pa.Table.from_pandas(j.reset_index(drop=True),
                                preserve_index=False)


_q("q44", "best/worst items by one store's avg net profit (rank)")(
    (_q44_run, _q44_oracle))


# ===========================================================================
# q53: manufacturer quarterly sales vs their yearly average (window)
# ===========================================================================

def _q53_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_sales_price",
        "ss_quantity")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk", "d_qoy")
    st = _rd(s, t, "store").select("s_store_sk")
    it = _rd(s, t, "item").filter(
        col("i_category").isin("Books", "Home", "Sports")
        & (col("i_manufact_id") <= 300)) \
        .select("i_item_sk", "i_manufact_id")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    amt = (col("ss_sales_price").cast(DataType.FLOAT64)
           * col("ss_quantity").cast(DataType.FLOAT64))
    g = (j.with_column("amt", amt)
         .group_by("i_manufact_id", "d_qoy")
         .agg(F.sum(col("amt")).alias("sum_sales")))
    w = g.window([F.win_agg("avg", col("sum_sales"))
                  .alias("avg_quarterly_sales")],
                 partition_by=[col("i_manufact_id")])
    dev = (F.abs(col("sum_sales") - col("avg_quarterly_sales"))
           / col("avg_quarterly_sales"))
    out = w.filter((col("avg_quarterly_sales") > lit(0.0))
                   & (dev > lit(0.1)))
    return (out.select("i_manufact_id", "d_qoy", "sum_sales",
                       "avg_quarterly_sales")
            .sort(col("avg_quarterly_sales").desc(),
                  col("sum_sales").asc(), col("i_manufact_id").asc(),
                  col("d_qoy").asc())
            .limit(100).collect())


def _q53_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    dd = dd[dd.d_year == 2000][["d_date_sk", "d_qoy"]]
    it = a["item"].to_pandas()
    it = it[it.i_category.isin(["Books", "Home", "Sports"])
            & (it.i_manufact_id <= 300)][["i_item_sk", "i_manufact_id"]]
    ss = a["store_sales"].to_pandas()
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j["amt"] = j.ss_sales_price.astype(float) * j.ss_quantity
    g = j.groupby(["i_manufact_id", "d_qoy"])["amt"].sum() \
        .reset_index(name="sum_sales")
    g["avg_quarterly_sales"] = g.groupby("i_manufact_id")["sum_sales"] \
        .transform("mean")
    dev = (g.sum_sales - g.avg_quarterly_sales).abs() \
        / g.avg_quarterly_sales
    g = g[(g.avg_quarterly_sales > 0) & (dev > 0.1)]
    g = g.sort_values(["avg_quarterly_sales", "sum_sales",
                       "i_manufact_id", "d_qoy"],
                      ascending=[False, True, True, True]).head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q53", "manufacturer quarterly sales vs yearly average (window)")(
    (_q53_run, _q53_oracle))


# ===========================================================================
# q56: 3-channel item revenue for timezone-sliced buyers
# ===========================================================================

def _q56_run(s, t):
    it = _rd(s, t, "item").filter(
        col("i_category").isin("Music", "Jewelry")) \
        .select("i_item_sk", "i_item_id")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") == 2)).select("d_date_sk")
    ca = _rd(s, t, "customer_address").filter(
        col("ca_gmt_offset") == lit(-5.0)).select("ca_address_sk")

    def chan(fact, date_k, addr_k, item_k, price):
        f = _rd(s, t, fact).select(date_k, addr_k, item_k, price)
        j = _join_dim(f, dd, date_k, "d_date_sk")
        j = _join_dim(j, ca, addr_k, "ca_address_sk")
        j = _join_dim(j, it, item_k, "i_item_sk")
        return (j.group_by("i_item_id")
                .agg(F.sum(col(price)).alias("total_sales")))

    u = chan("store_sales", "ss_sold_date_sk", "ss_addr_sk",
             "ss_item_sk", "ss_ext_sales_price") \
        .union(chan("catalog_sales", "cs_sold_date_sk", "cs_bill_addr_sk",
                    "cs_item_sk", "cs_ext_sales_price")) \
        .union(chan("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                    "ws_item_sk", "ws_ext_sales_price"))
    return (u.group_by("i_item_id")
            .agg(F.sum(col("total_sales")).alias("total_sales"))
            .sort(col("total_sales").asc(), col("i_item_id").asc())
            .limit(100).collect())


def _q56_oracle(a):
    import pandas as pd
    it = a["item"].to_pandas()
    it = it[it.i_category.isin(["Music", "Jewelry"])][
        ["i_item_sk", "i_item_id"]]
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_year == 2000) & (dd.d_moy == 2)].d_date_sk)
    ca = a["customer_address"].to_pandas()
    addrs = set(ca[ca.ca_gmt_offset == -5.0].ca_address_sk)

    def chan(name, date_k, addr_k, item_k, price):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days) & f[addr_k].isin(addrs)]
        j = f.merge(it, left_on=item_k, right_on="i_item_sk")
        return j.groupby("i_item_id")[price].apply(
            lambda x: x.astype(float).sum()).reset_index(name="t")

    u = pd.concat([
        chan("store_sales", "ss_sold_date_sk", "ss_addr_sk",
             "ss_item_sk", "ss_ext_sales_price"),
        chan("catalog_sales", "cs_sold_date_sk", "cs_bill_addr_sk",
             "cs_item_sk", "cs_ext_sales_price"),
        chan("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
             "ws_item_sk", "ws_ext_sales_price")])
    g = u.groupby("i_item_id")["t"].sum().reset_index(name="total_sales")
    g = g.sort_values(["total_sales", "i_item_id"]).head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q56", "3-channel item revenue for one timezone's buyers")(
    (_q56_run, _q56_oracle))


# ===========================================================================
# q59: weekly store sales, year-over-year by day of week
# ===========================================================================

def _q59_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_sales_price")
    dd = _rd(s, t, "date_dim").select("d_date_sk", "d_week_seq",
                                      "d_day_name")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    price = col("ss_sales_price").cast(DataType.FLOAT64)
    for day, nm in (("Sunday", "sun"), ("Monday", "mon"),
                    ("Wednesday", "wed"), ("Friday", "fri")):
        j = j.with_column(
            nm, F.if_(col("d_day_name") == day, price, lit(0.0)))
    wk = (j.group_by("d_week_seq", "ss_store_sk")
          .agg(F.sum(col("sun")).alias("sun_sales"),
               F.sum(col("mon")).alias("mon_sales"),
               F.sum(col("wed")).alias("wed_sales"),
               F.sum(col("fri")).alias("fri_sales")))
    y1 = wk.filter((col("d_week_seq") >= 5270 + 52)
                   & (col("d_week_seq") < 5270 + 104)) \
        .select(col("ss_store_sk"), col("d_week_seq").alias("wk1"),
                col("sun_sales").alias("sun1"),
                col("mon_sales").alias("mon1"),
                col("wed_sales").alias("wed1"),
                col("fri_sales").alias("fri1"))
    y2 = wk.filter((col("d_week_seq") >= 5270 + 104)
                   & (col("d_week_seq") < 5270 + 156)) \
        .select(col("ss_store_sk"),
                (col("d_week_seq") - lit(52, DataType.INT64))
                .alias("wk1"),
                col("sun_sales").alias("sun2"),
                col("mon_sales").alias("mon2"),
                col("wed_sales").alias("wed2"),
                col("fri_sales").alias("fri2"))
    j2 = y1.join(y2, on=["ss_store_sk", "wk1"], how="inner")
    out = j2.select(
        col("ss_store_sk"), col("wk1"),
        (col("sun1") / col("sun2")).alias("sun_r"),
        (col("mon1") / col("mon2")).alias("mon_r"),
        (col("wed1") / col("wed2")).alias("wed_r"),
        (col("fri1") / col("fri2")).alias("fri_r"))
    return (out.sort(col("ss_store_sk").asc(), col("wk1").asc())
            .limit(100).collect())


def _q59_oracle(a):
    import numpy as _np
    import pandas as pd
    ss = a["store_sales"].to_pandas()
    dd = a["date_dim"].to_pandas()[["d_date_sk", "d_week_seq",
                                    "d_day_name"]]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j["p"] = j.ss_sales_price.astype(float)
    for day, nm in (("Sunday", "sun"), ("Monday", "mon"),
                    ("Wednesday", "wed"), ("Friday", "fri")):
        j[nm] = j.p.where(j.d_day_name == day, 0.0)
    wk = j.groupby(["d_week_seq", "ss_store_sk"])[
        ["sun", "mon", "wed", "fri"]].sum().reset_index()
    y1 = wk[(wk.d_week_seq >= 5270 + 52) & (wk.d_week_seq < 5270 + 104)] \
        .copy()
    y1["wk1"] = y1.d_week_seq
    y2 = wk[(wk.d_week_seq >= 5270 + 104)
            & (wk.d_week_seq < 5270 + 156)].copy()
    y2["wk1"] = y2.d_week_seq - 52
    j2 = y1.merge(y2, on=["ss_store_sk", "wk1"], suffixes=("1", "2"))
    with _np.errstate(divide="ignore", invalid="ignore"):
        for nm in ("sun", "mon", "wed", "fri"):
            # Spark Divide: zero divisor -> NULL (doubles included)
            j2[nm + "_r"] = _np.where(j2[nm + "2"] == 0.0, _np.nan,
                                      j2[nm + "1"] / j2[nm + "2"])
    out = j2[["ss_store_sk", "wk1", "sun_r", "mon_r", "wed_r", "fri_r"]]
    out = out.sort_values(["ss_store_sk", "wk1"]).head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q59", "weekly store sales year-over-year by day of week")(
    (_q59_run, _q59_oracle))


# ===========================================================================
# q61: promotional vs total store revenue for one month/timezone
# ===========================================================================

def _q61_run(s, t):
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") == 11)) \
        .select("d_date_sk")
    ca = _rd(s, t, "customer_address").filter(
        col("ca_gmt_offset") == lit(-6.0)).select("ca_address_sk")
    it = _rd(s, t, "item").filter(col("i_category") == "Books") \
        .select("i_item_sk")
    c = _rd(s, t, "customer").select("c_customer_sk", "c_current_addr_sk")

    def base():
        ss = _rd(s, t, "store_sales").select(
            "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
            "ss_promo_sk", "ss_ext_sales_price")
        j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
        j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
        j = _join_dim(j, c, "ss_customer_sk", "c_customer_sk")
        j = _join_dim(j, ca, "c_current_addr_sk", "ca_address_sk")
        return j

    pr = _rd(s, t, "promotion").filter(
        (col("p_channel_dmail") == "Y") | (col("p_channel_email") == "Y")
        | (col("p_channel_tv") == "Y")).select("p_promo_sk")
    promo = _join_dim(base(), pr, "ss_promo_sk", "p_promo_sk") \
        .group_by().agg(F.sum(col("ss_ext_sales_price")).alias("p"))
    total = base().group_by() \
        .agg(F.sum(col("ss_ext_sales_price")).alias("t"))
    from auron_tpu.frontend.dataframe import scalar_subquery
    out = (total.select(
        scalar_subquery(promo).cast(DataType.FLOAT64).alias("promotions"),
        col("t").cast(DataType.FLOAT64).alias("total"),
        (scalar_subquery(promo).cast(DataType.FLOAT64)
         / col("t").cast(DataType.FLOAT64) * lit(100.0)).alias("pct")))
    return out.collect()


def _q61_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_year == 2000) & (dd.d_moy == 11)].d_date_sk)
    ca = a["customer_address"].to_pandas()
    addrs = set(ca[ca.ca_gmt_offset == -6.0].ca_address_sk)
    it = a["item"].to_pandas()
    items = set(it[it.i_category == "Books"].i_item_sk)
    c = a["customer"].to_pandas()
    c = c[c.c_current_addr_sk.isin(addrs)]
    custs = set(c.c_customer_sk)
    ss = a["store_sales"].to_pandas()
    b = ss[ss.ss_sold_date_sk.isin(days) & ss.ss_item_sk.isin(items)
           & ss.ss_customer_sk.isin(custs)]
    pr = a["promotion"].to_pandas()
    promos = set(pr[(pr.p_channel_dmail == "Y")
                    | (pr.p_channel_email == "Y")
                    | (pr.p_channel_tv == "Y")].p_promo_sk)
    p = b[b.ss_promo_sk.isin(promos)].ss_ext_sales_price.astype(
        float).sum()
    tt = b.ss_ext_sales_price.astype(float).sum()
    return pa.Table.from_pydict({
        "promotions": [p], "total": [tt], "pct": [p / tt * 100.0]})


_q("q61", "promotional share of one month's store revenue")(
    (_q61_run, _q61_oracle))


# ===========================================================================
# q74: customers whose web growth outpaced store growth year-over-year
# ===========================================================================

def _q74_run(s, t):
    c = _rd(s, t, "customer").select("c_customer_sk", "c_customer_id",
                                     "c_first_name", "c_last_name")

    def totals(fact, cust_k, date_k, paid_k, years, alias):
        f = _rd(s, t, fact).select(cust_k, date_k, paid_k)
        dd = _rd(s, t, "date_dim").filter(col("d_year").isin(*years)) \
            .select("d_date_sk")
        j = _join_dim(f, dd, date_k, "d_date_sk")
        return (j.group_by(cust_k)
                .agg(F.sum(col(paid_k)).alias(alias))
                .select(col(cust_k).alias("c_customer_sk"), col(alias)))

    # tuned parameter: the year windows widen to 1998-2000 vs 2001-2002
    # so CI-scale customers have activity in both windows of both
    # channels (per-customer yearly web activity is sparse)
    ss1 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_net_paid", (1998, 1999, 2000), "ss1")
    ss2 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_net_paid", (2001, 2002), "ss2")
    ws1 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_net_paid", (1998, 1999, 2000), "ws1")
    ws2 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_net_paid", (2001, 2002), "ws2")
    j = c.join(ss1, on="c_customer_sk", how="inner")
    j = j.join(ss2, on="c_customer_sk", how="inner")
    j = j.join(ws1, on="c_customer_sk", how="inner")
    j = j.join(ws2, on="c_customer_sk", how="inner")
    f = lambda nm: col(nm).cast(DataType.FLOAT64)
    j = j.filter((f("ss1") > lit(0.0)) & (f("ws1") > lit(0.0))
                 & (f("ws2") / f("ws1") > f("ss2") / f("ss1")))
    return (j.select("c_customer_id", "c_first_name", "c_last_name")
            .sort(col("c_customer_id").asc()).limit(100).collect())


def _q74_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    y99 = set(dd[dd.d_year.isin([1998, 1999, 2000])].d_date_sk)
    y00 = set(dd[dd.d_year.isin([2001, 2002])].d_date_sk)

    def totals(name, cust_k, date_k, paid_k, days):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days) & f[cust_k].notna()].copy()
        f["v"] = f[paid_k].astype(float)
        return f.groupby(cust_k)["v"].sum()

    ss1 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_net_paid", y99)
    ss2 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_net_paid", y00)
    ws1 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_net_paid", y99)
    ws2 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_net_paid", y00)
    df = pd.concat([ss1.rename("ss1"), ss2.rename("ss2"),
                    ws1.rename("ws1"), ws2.rename("ws2")], axis=1) \
        .dropna()
    df = df[(df.ss1 > 0) & (df.ws1 > 0)
            & (df.ws2 / df.ws1 > df.ss2 / df.ss1)]
    c = a["customer"].to_pandas().set_index("c_customer_sk")
    out = c.loc[c.index.intersection(df.index)][
        ["c_customer_id", "c_first_name", "c_last_name"]] \
        .sort_values("c_customer_id").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q74", "customers whose web growth beat store growth YoY")(
    (_q74_run, _q74_oracle))


# ===========================================================================
# q84: customers in one city within an income band (5-dim lookup chain)
# ===========================================================================

def _q84_run(s, t):
    ca = _rd(s, t, "customer_address").filter(
        col("ca_city") == "Fairview").select("ca_address_sk")
    ib = _rd(s, t, "income_band").filter(
        (col("ib_lower_bound") >= 30000)
        & (col("ib_upper_bound") <= 80000)).select("ib_income_band_sk")
    hd = _rd(s, t, "household_demographics").select(
        "hd_demo_sk", "hd_income_band_sk")
    hd = _join_dim(hd, ib, "hd_income_band_sk", "ib_income_band_sk")
    c = _rd(s, t, "customer").select(
        "c_customer_sk", "c_customer_id", "c_first_name", "c_last_name",
        "c_current_addr_sk", "c_current_hdemo_sk", "c_current_cdemo_sk")
    j = _join_dim(c, ca, "c_current_addr_sk", "ca_address_sk")
    j = _join_dim(j, hd, "c_current_hdemo_sk", "hd_demo_sk")
    cd = _rd(s, t, "customer_demographics").select("cd_demo_sk")
    j = _join_dim(j, cd, "c_current_cdemo_sk", "cd_demo_sk")
    return (j.select("c_customer_id", "c_first_name", "c_last_name")
            .sort(col("c_customer_id").asc()).limit(100).collect())


def _q84_oracle(a):
    import pandas as pd
    ca = a["customer_address"].to_pandas()
    addrs = set(ca[ca.ca_city == "Fairview"].ca_address_sk)
    ib = a["income_band"].to_pandas()
    ibs = set(ib[(ib.ib_lower_bound >= 30000)
                 & (ib.ib_upper_bound <= 80000)].ib_income_band_sk)
    hd = a["household_demographics"].to_pandas()
    hds = set(hd[hd.hd_income_band_sk.isin(ibs)].hd_demo_sk)
    cds = set(a["customer_demographics"].to_pandas().cd_demo_sk)
    c = a["customer"].to_pandas()
    c = c[c.c_current_addr_sk.isin(addrs)
          & c.c_current_hdemo_sk.isin(hds)
          & c.c_current_cdemo_sk.isin(cds)]
    out = c[["c_customer_id", "c_first_name", "c_last_name"]] \
        .sort_values("c_customer_id").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q84", "one city's customers in an income band (dim chain)")(
    (_q84_run, _q84_oracle))


# ===========================================================================
# q91: call center catalog-return losses for a demographic slice
# ===========================================================================

def _q91_run(s, t):
    cc = _rd(s, t, "call_center").select("cc_call_center_sk", "cc_name")
    cr = _rd(s, t, "catalog_returns").select(
        "cr_returned_date_sk", "cr_returning_customer_sk",
        "cr_call_center_sk", "cr_net_loss")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    c = _rd(s, t, "customer").select(
        "c_customer_sk", "c_current_cdemo_sk", "c_current_hdemo_sk",
        "c_current_addr_sk")
    cd = _rd(s, t, "customer_demographics").filter(
        ((col("cd_marital_status") == "M")
         & (col("cd_education_status") == "Unknown"))
        | ((col("cd_marital_status") == "W")
           & (col("cd_education_status") == "Advanced Degree"))) \
        .select("cd_demo_sk", "cd_marital_status", "cd_education_status")
    hd = _rd(s, t, "household_demographics").filter(
        col("hd_buy_potential").like("Unknown%")
        | col("hd_buy_potential").like(">10000%")).select("hd_demo_sk")
    ca = _rd(s, t, "customer_address").filter(
        col("ca_gmt_offset").isin(-6.0, -7.0, -8.0)) \
        .select("ca_address_sk")
    j = _join_dim(cr, cc, "cr_call_center_sk", "cc_call_center_sk")
    j = _join_dim(j, dd, "cr_returned_date_sk", "d_date_sk")
    j = _join_dim(j, c, "cr_returning_customer_sk", "c_customer_sk")
    j = _join_dim(j, cd, "c_current_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, hd, "c_current_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, ca, "c_current_addr_sk", "ca_address_sk")
    return (j.group_by("cc_name", "cd_marital_status",
                       "cd_education_status")
            .agg(F.sum(col("cr_net_loss")).alias("returns_loss"))
            .sort(col("returns_loss").desc(), col("cc_name").asc())
            .collect())


def _q91_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[dd.d_year == 2000].d_date_sk)
    cd = a["customer_demographics"].to_pandas()
    cd = cd[((cd.cd_marital_status == "M")
             & (cd.cd_education_status == "Unknown"))
            | ((cd.cd_marital_status == "W")
               & (cd.cd_education_status == "Advanced Degree"))]
    hd = a["household_demographics"].to_pandas()
    hds = set(hd[hd.hd_buy_potential.str.startswith(("Unknown",
                                                     ">10000"))]
              .hd_demo_sk)
    ca = a["customer_address"].to_pandas()
    addrs = set(ca[ca.ca_gmt_offset.isin([-6.0, -7.0, -8.0])]
                .ca_address_sk)
    c = a["customer"].to_pandas()
    cr = a["catalog_returns"].to_pandas()
    j = cr[cr.cr_returned_date_sk.isin(days)]
    j = j.merge(a["call_center"].to_pandas(), left_on="cr_call_center_sk",
                right_on="cc_call_center_sk")
    j = j.merge(c, left_on="cr_returning_customer_sk",
                right_on="c_customer_sk")
    j = j.merge(cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
    j = j[j.c_current_hdemo_sk.isin(hds)
          & j.c_current_addr_sk.isin(addrs)]
    g = j.groupby(["cc_name", "cd_marital_status",
                   "cd_education_status"])["cr_net_loss"].sum() \
        .reset_index(name="returns_loss")
    g = g.sort_values(["returns_loss", "cc_name"],
                      ascending=[False, True])
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q91", "call-center catalog return losses for a demographic slice")(
    (_q91_run, _q91_oracle))


# ===========================================================================
# q94: web orders shipped from one state, multi-site EXISTS, no returns
# ===========================================================================

def _q94_run(s, t):
    d0 = DATE_SK0 + 3 * 365 + 31
    ws = _rd(s, t, "web_sales").select(
        "ws_ship_date_sk", "ws_ship_addr_sk", "ws_warehouse_sk",
        "ws_order_number", "ws_ext_ship_cost", "ws_net_profit")
    ws = ws.filter((col("ws_ship_date_sk") >= lit(d0, DataType.INT64))
                   & (col("ws_ship_date_sk") <= lit(d0 + 60,
                                                    DataType.INT64)))
    ca = _rd(s, t, "customer_address").filter(col("ca_state") == "TX") \
        .select("ca_address_sk")
    j = _join_dim(ws, ca, "ws_ship_addr_sk", "ca_address_sk")
    all_ws = _rd(s, t, "web_sales").select("ws_order_number",
                                           "ws_warehouse_sk")
    multi = (all_ws.group_by("ws_order_number")
             .agg(F.count(col("ws_warehouse_sk"), distinct=True)
                  .alias("n_wh"))
             .filter(col("n_wh") > 1).select("ws_order_number"))
    j = j.join(multi, on="ws_order_number", how="semi")
    wr = _rd(s, t, "web_returns").select(
        col("wr_order_number").alias("ws_order_number"))
    j = j.join(wr, on="ws_order_number", how="anti")
    return (j.group_by()
            .agg(F.count(col("ws_order_number"), distinct=True)
                 .alias("order_count"),
                 F.sum(col("ws_ext_ship_cost")).alias("total_ship"),
                 F.sum(col("ws_net_profit")).alias("total_profit"))
            .collect())


def _q94_oracle(a):
    import pandas as pd
    d0 = DATE_SK0 + 3 * 365 + 31
    ws = a["web_sales"].to_pandas()
    sel = ws[(ws.ws_ship_date_sk >= d0) & (ws.ws_ship_date_sk <= d0 + 60)]
    ca = a["customer_address"].to_pandas()
    ok = set(ca[ca.ca_state == "TX"].ca_address_sk)
    sel = sel[sel.ws_ship_addr_sk.isin(ok)]
    nwh = ws.groupby("ws_order_number")["ws_warehouse_sk"].nunique()
    multi = set(nwh[nwh > 1].index)
    returned = set(a["web_returns"].to_pandas().wr_order_number)
    sel = sel[sel.ws_order_number.isin(multi)
              & ~sel.ws_order_number.isin(returned)]
    return pa.Table.from_pydict({
        "order_count": [sel.ws_order_number.nunique()],
        "total_ship": [sel.ws_ext_ship_cost.sum()],
        "total_profit": [sel.ws_net_profit.sum()],
    })


_q("q94", "shipped web orders: multi-warehouse EXISTS, no returns")(
    (_q94_run, _q94_oracle))


# ===========================================================================
# q97: store/catalog buyer-item overlap (pairs in one, other, both)
# ===========================================================================

def _q97_run(s, t):
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    ssp = _join_dim(
        _rd(s, t, "store_sales").select("ss_sold_date_sk",
                                        "ss_customer_sk", "ss_item_sk"),
        dd, "ss_sold_date_sk", "d_date_sk") \
        .filter(col("ss_customer_sk").is_not_null()) \
        .group_by("ss_customer_sk", "ss_item_sk").agg() \
        .select(col("ss_customer_sk").alias("cust"),
                col("ss_item_sk").alias("item"))
    csp = _join_dim(
        _rd(s, t, "catalog_sales").select(
            "cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk"),
        dd, "cs_sold_date_sk", "d_date_sk") \
        .filter(col("cs_bill_customer_sk").is_not_null()) \
        .group_by("cs_bill_customer_sk", "cs_item_sk").agg() \
        .select(col("cs_bill_customer_sk").alias("cust"),
                col("cs_item_sk").alias("item"))
    from auron_tpu.frontend.dataframe import scalar_subquery
    store_only = ssp.join(csp, on=["cust", "item"], how="anti") \
        .group_by().agg(F.count_star().alias("n"))
    cat_only = csp.join(ssp, on=["cust", "item"], how="anti") \
        .group_by().agg(F.count_star().alias("n"))
    both = ssp.join(csp, on=["cust", "item"], how="semi") \
        .group_by().agg(F.count_star().alias("n"))
    out = (store_only.select(
        col("n").alias("store_only"),
        scalar_subquery(cat_only).alias("catalog_only"),
        scalar_subquery(both).alias("store_and_catalog")))
    return out.collect()


def _q97_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[dd.d_year == 2000].d_date_sk)
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(days) & ss.ss_customer_sk.notna()]
    sp = set(zip(ss.ss_customer_sk.astype(int), ss.ss_item_sk))
    cs = a["catalog_sales"].to_pandas()
    cs = cs[cs.cs_sold_date_sk.isin(days)
            & cs.cs_bill_customer_sk.notna()]
    cp = set(zip(cs.cs_bill_customer_sk.astype(int), cs.cs_item_sk))
    return pa.Table.from_pydict({
        "store_only": [len(sp - cp)],
        "catalog_only": [len(cp - sp)],
        "store_and_catalog": [len(sp & cp)],
    })


_q("q97", "store/catalog buyer-item overlap counts")(
    (_q97_run, _q97_oracle))


# ===========================================================================
# q30: web returners whose return total exceeds 1.2x their state average
# ===========================================================================

def _q30_run(s, t):
    wr = _rd(s, t, "web_returns").select(
        "wr_returned_date_sk", "wr_returning_customer_sk",
        "wr_refunded_addr_sk", "wr_return_amt")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_state")
    j = _join_dim(wr, dd, "wr_returned_date_sk", "d_date_sk")
    j = _join_dim(j, ca, "wr_refunded_addr_sk", "ca_address_sk")
    per_cust = (j.filter(col("wr_returning_customer_sk").is_not_null())
                .group_by("wr_returning_customer_sk", "ca_state")
                .agg(F.sum(col("wr_return_amt")).alias("ctr_total")))
    per_state_avg = (per_cust.group_by("ca_state")
                     .agg(F.avg(col("ctr_total").cast(DataType.FLOAT64))
                          .alias("state_avg")))
    j2 = per_cust.join(per_state_avg, on="ca_state", how="inner")
    j2 = j2.filter(col("ctr_total").cast(DataType.FLOAT64)
                   > lit(1.2) * col("state_avg"))
    c = _rd(s, t, "customer").select(
        col("c_customer_sk").alias("wr_returning_customer_sk"),
        col("c_customer_id"), col("c_first_name"), col("c_last_name"))
    j2 = j2.join(c, on="wr_returning_customer_sk", how="inner")
    return (j2.select("c_customer_id", "c_first_name", "c_last_name",
                      "ctr_total")
            .sort(col("c_customer_id").asc()).limit(100).collect())


def _q30_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[dd.d_year == 2000].d_date_sk)
    wr = a["web_returns"].to_pandas()
    wr = wr[wr.wr_returned_date_sk.isin(days)
            & wr.wr_returning_customer_sk.notna()]
    ca = a["customer_address"].to_pandas()[["ca_address_sk", "ca_state"]]
    j = wr.merge(ca, left_on="wr_refunded_addr_sk",
                 right_on="ca_address_sk")
    j["amt"] = j.wr_return_amt.astype(float)
    per = j.groupby(["wr_returning_customer_sk", "ca_state"])["amt"] \
        .sum().reset_index(name="ctr_total")
    per["state_avg"] = per.groupby("ca_state")["ctr_total"] \
        .transform("mean")
    sel = per[per.ctr_total > 1.2 * per.state_avg]
    c = a["customer"].to_pandas()
    sel = sel.merge(c, left_on="wr_returning_customer_sk",
                    right_on="c_customer_sk")
    out = sel[["c_customer_id", "c_first_name", "c_last_name",
               "ctr_total"]].sort_values("c_customer_id").head(100)
    # engine emits the decimal total; compare as float
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q30", "web returners above 1.2x their state's average return")(
    (_q30_run, _q30_oracle))


# ===========================================================================
# q38: customers active in ALL THREE channels in the period (INTERSECT)
# ===========================================================================

def _q38_run(s, t):
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
        .select("d_date_sk")

    def chan(fact, date_k, cust_k):
        f = _rd(s, t, fact).select(date_k, cust_k)
        j = _join_dim(f, dd, date_k, "d_date_sk")
        return (j.filter(col(cust_k).is_not_null())
                .group_by(cust_k).agg()
                .select(col(cust_k).alias("c_customer_sk")))

    ssb = chan("store_sales", "ss_sold_date_sk", "ss_customer_sk")
    csb = chan("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk")
    wsb = chan("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk")
    both = ssb.join(csb, on="c_customer_sk", how="semi") \
        .join(wsb, on="c_customer_sk", how="semi")
    return both.group_by().agg(F.count_star().alias("n")).collect()


def _q38_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_month_seq >= 24)
                  & (dd.d_month_seq <= 35)].d_date_sk)

    def chan(name, date_k, cust_k):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days) & f[cust_k].notna()]
        return set(f[cust_k].astype(int))

    inter = (chan("store_sales", "ss_sold_date_sk", "ss_customer_sk")
             & chan("catalog_sales", "cs_sold_date_sk",
                    "cs_bill_customer_sk")
             & chan("web_sales", "ws_sold_date_sk",
                    "ws_bill_customer_sk"))
    return pa.Table.from_pydict({"n": [len(inter)]})


_q("q38", "customers active in all three channels (INTERSECT)")(
    (_q38_run, _q38_oracle))


# ===========================================================================
# q87: store customers NOT active on catalog or web (EXCEPT chain)
# ===========================================================================

def _q87_run(s, t):
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
        .select("d_date_sk")

    def chan(fact, date_k, cust_k):
        f = _rd(s, t, fact).select(date_k, cust_k)
        j = _join_dim(f, dd, date_k, "d_date_sk")
        return (j.filter(col(cust_k).is_not_null())
                .group_by(cust_k).agg()
                .select(col(cust_k).alias("c_customer_sk")))

    ssb = chan("store_sales", "ss_sold_date_sk", "ss_customer_sk")
    csb = chan("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk")
    wsb = chan("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk")
    only = ssb.join(csb, on="c_customer_sk", how="anti") \
        .join(wsb, on="c_customer_sk", how="anti")
    return only.group_by().agg(F.count_star().alias("n")).collect()


def _q87_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_month_seq >= 24)
                  & (dd.d_month_seq <= 35)].d_date_sk)

    def chan(name, date_k, cust_k):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days) & f[cust_k].notna()]
        return set(f[cust_k].astype(int))

    only = (chan("store_sales", "ss_sold_date_sk", "ss_customer_sk")
            - chan("catalog_sales", "cs_sold_date_sk",
                   "cs_bill_customer_sk")
            - chan("web_sales", "ws_sold_date_sk",
                   "ws_bill_customer_sk"))
    return pa.Table.from_pydict({"n": [len(only)]})


_q("q87", "store-only customers in the period (EXCEPT chain)")(
    (_q87_run, _q87_oracle))


# ===========================================================================
# q41: distinct item descriptions under OR'd attribute quads
# ===========================================================================

def _q41_run(s, t):
    it = _rd(s, t, "item")
    manuf = (col("i_manufact_id") >= 700) & (col("i_manufact_id") <= 740)
    quads = (((col("i_category") == "Women")
              & col("i_class").isin("class01", "class02"))
             | ((col("i_category") == "Men")
                & col("i_class").isin("class03", "class04"))
             | ((col("i_category") == "Books")
                & col("i_class").isin("class05", "class06")))
    j = it.filter(manuf & quads)
    return (j.group_by("i_item_desc").agg()
            .sort(col("i_item_desc").asc()).limit(100).collect())


def _q41_oracle(a):
    import pandas as pd
    it = a["item"].to_pandas()
    sel = it[(it.i_manufact_id >= 700) & (it.i_manufact_id <= 740)
             & (((it.i_category == "Women")
                 & it.i_class.isin(["class01", "class02"]))
                | ((it.i_category == "Men")
                   & it.i_class.isin(["class03", "class04"]))
                | ((it.i_category == "Books")
                   & it.i_class.isin(["class05", "class06"])))]
    out = sel[["i_item_desc"]].drop_duplicates() \
        .sort_values("i_item_desc").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q41", "distinct item descriptions under OR'd attribute quads")(
    (_q41_run, _q41_oracle))


# ===========================================================================
# q63: manager monthly sales vs yearly average (q53's twin shape)
# ===========================================================================

def _q63_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_sales_price",
        "ss_quantity")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk", "d_moy")
    st = _rd(s, t, "store").select("s_store_sk")
    it = _rd(s, t, "item").filter(
        col("i_category").isin("Electronics", "Children")
        & (col("i_manager_id") <= 50)) \
        .select("i_item_sk", "i_manager_id")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    amt = (col("ss_sales_price").cast(DataType.FLOAT64)
           * col("ss_quantity").cast(DataType.FLOAT64))
    g = (j.with_column("amt", amt)
         .group_by("i_manager_id", "d_moy")
         .agg(F.sum(col("amt")).alias("sum_sales")))
    w = g.window([F.win_agg("avg", col("sum_sales"))
                  .alias("avg_monthly_sales")],
                 partition_by=[col("i_manager_id")])
    dev = (F.abs(col("sum_sales") - col("avg_monthly_sales"))
           / col("avg_monthly_sales"))
    out = w.filter((col("avg_monthly_sales") > lit(0.0))
                   & (dev > lit(0.1)))
    return (out.select("i_manager_id", "d_moy", "sum_sales",
                       "avg_monthly_sales")
            .sort(col("i_manager_id").asc(), col("avg_monthly_sales").desc(),
                  col("sum_sales").asc(), col("d_moy").asc())
            .limit(100).collect())


def _q63_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    dd = dd[dd.d_year == 2000][["d_date_sk", "d_moy"]]
    it = a["item"].to_pandas()
    it = it[it.i_category.isin(["Electronics", "Children"])
            & (it.i_manager_id <= 50)][["i_item_sk", "i_manager_id"]]
    ss = a["store_sales"].to_pandas()
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j["amt"] = j.ss_sales_price.astype(float) * j.ss_quantity
    g = j.groupby(["i_manager_id", "d_moy"])["amt"].sum() \
        .reset_index(name="sum_sales")
    g["avg_monthly_sales"] = g.groupby("i_manager_id")["sum_sales"] \
        .transform("mean")
    dev = (g.sum_sales - g.avg_monthly_sales).abs() / g.avg_monthly_sales
    g = g[(g.avg_monthly_sales > 0) & (dev > 0.1)]
    g = g.sort_values(["i_manager_id", "avg_monthly_sales", "sum_sales",
                       "d_moy"],
                      ascending=[True, False, True, True]).head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q63", "manager monthly sales vs yearly average (window)")(
    (_q63_run, _q63_oracle))


# ===========================================================================
# q70: store profit by state/county ROLLUP with in-state rank
# ===========================================================================

def _q70_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_net_profit")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
        .select("d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk", "s_state", "s_county")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    # the template picks the top-5-profit states via a ranked subquery;
    # with the generator's dozen states a top-3 keeps the gate selective
    per_state = (j.group_by("s_state")
                 .agg(F.sum(col("ss_net_profit")).alias("sp")))
    ranked = per_state.window([F.rank().alias("r")],
                              order_by=[col("sp").desc()])
    top = ranked.filter(col("r") <= 3).select("s_state")
    j = j.join(top, on="s_state", how="semi")
    g = (j.rollup(col("s_state"), col("s_county"))
         .agg(F.sum(col("ss_net_profit")).alias("total_sum")))
    return (g.select("s_state", "s_county", "total_sum")
            .sort(col("s_state").asc(), col("s_county").asc(),
                  col("total_sum").desc())
            .limit(100).collect())


def _q70_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_month_seq >= 24)
                  & (dd.d_month_seq <= 35)].d_date_sk)
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(days)]
    st = a["store"].to_pandas()[["s_store_sk", "s_state", "s_county"]]
    j = ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j["p"] = j.ss_net_profit.astype(float)
    per_state = j.groupby("s_state")["p"].sum().reset_index(name="sp")
    per_state["r"] = per_state.sp.rank(method="min", ascending=False)
    top = set(per_state[per_state.r <= 3].s_state)
    j = j[j.s_state.isin(top)]
    lv2 = j.groupby(["s_state", "s_county"])["p"].sum() \
        .reset_index(name="total_sum")
    lv1 = j.groupby(["s_state"])["p"].sum().reset_index(name="total_sum")
    lv1["s_county"] = None
    lv0 = pd.DataFrame({"s_state": [None], "s_county": [None],
                        "total_sum": [j.p.sum()]})
    g = pd.concat([lv2, lv1, lv0], ignore_index=True)
    # engine sort: ASC defaults to NULLS FIRST (Spark), so the rollup
    # super-aggregate rows lead their groups
    g = g.sort_values(["s_state", "s_county", "total_sum"],
                      ascending=[True, True, False],
                      na_position="first").head(100)
    return pa.Table.from_pandas(
        g[["s_state", "s_county", "total_sum"]].reset_index(drop=True),
        preserve_index=False)


_q("q70", "store profit by state/county ROLLUP over top-ranked states")(
    (_q70_run, _q70_oracle))


# ===========================================================================
# q81: catalog returners above 1.2x their state's average return
# ===========================================================================

def _q81_run(s, t):
    cr = _rd(s, t, "catalog_returns").select(
        "cr_returned_date_sk", "cr_returning_customer_sk",
        "cr_returning_addr_sk", "cr_return_amount")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_state")
    j = _join_dim(cr, dd, "cr_returned_date_sk", "d_date_sk")
    j = _join_dim(j, ca, "cr_returning_addr_sk", "ca_address_sk")
    per_cust = (j.filter(col("cr_returning_customer_sk").is_not_null())
                .group_by("cr_returning_customer_sk", "ca_state")
                .agg(F.sum(col("cr_return_amount")).alias("ctr_total")))
    per_state = (per_cust.group_by("ca_state")
                 .agg(F.avg(col("ctr_total").cast(DataType.FLOAT64))
                      .alias("state_avg")))
    j2 = per_cust.join(per_state, on="ca_state", how="inner")
    j2 = j2.filter(col("ctr_total").cast(DataType.FLOAT64)
                   > lit(1.2) * col("state_avg"))
    c = _rd(s, t, "customer").select(
        col("c_customer_sk").alias("cr_returning_customer_sk"),
        col("c_customer_id"))
    j2 = j2.join(c, on="cr_returning_customer_sk", how="inner")
    return (j2.select("c_customer_id", "ca_state", "ctr_total")
            .sort(col("c_customer_id").asc()).limit(100).collect())


def _q81_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[dd.d_year == 2000].d_date_sk)
    cr = a["catalog_returns"].to_pandas()
    cr = cr[cr.cr_returned_date_sk.isin(days)
            & cr.cr_returning_customer_sk.notna()]
    ca = a["customer_address"].to_pandas()[["ca_address_sk", "ca_state"]]
    j = cr.merge(ca, left_on="cr_returning_addr_sk",
                 right_on="ca_address_sk")
    j["amt"] = j.cr_return_amount.astype(float)
    per = j.groupby(["cr_returning_customer_sk", "ca_state"])["amt"] \
        .sum().reset_index(name="ctr_total")
    per["state_avg"] = per.groupby("ca_state")["ctr_total"] \
        .transform("mean")
    sel = per[per.ctr_total > 1.2 * per.state_avg]
    c = a["customer"].to_pandas()[["c_customer_sk", "c_customer_id"]]
    sel = sel.merge(c, left_on="cr_returning_customer_sk",
                    right_on="c_customer_sk")
    out = sel[["c_customer_id", "ca_state", "ctr_total"]] \
        .sort_values("c_customer_id").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q81", "catalog returners above 1.2x their state's average")(
    (_q81_run, _q81_oracle))


# ===========================================================================
# q95: web orders appearing in >1 line with a return (both-EXISTS form)
# ===========================================================================

def _q95_run(s, t):
    d0 = DATE_SK0 + 3 * 365 + 31
    ws = _rd(s, t, "web_sales").select(
        "ws_ship_date_sk", "ws_ship_addr_sk", "ws_order_number",
        "ws_ext_ship_cost", "ws_net_profit")
    ws = ws.filter((col("ws_ship_date_sk") >= lit(d0, DataType.INT64))
                   & (col("ws_ship_date_sk") <= lit(d0 + 60,
                                                    DataType.INT64)))
    ca = _rd(s, t, "customer_address").filter(col("ca_state") == "CA") \
        .select("ca_address_sk")
    j = _join_dim(ws, ca, "ws_ship_addr_sk", "ca_address_sk")
    # ws_wh: orders with at least two lines (any warehouses) — the q95
    # self-join EXISTS; then EXISTS a web return for the order
    all_ws = _rd(s, t, "web_sales").select("ws_order_number")
    multi = (all_ws.group_by("ws_order_number")
             .agg(F.count_star().alias("n"))
             .filter(col("n") > 1).select("ws_order_number"))
    j = j.join(multi, on="ws_order_number", how="semi")
    wr = _rd(s, t, "web_returns").select(
        col("wr_order_number").alias("ws_order_number"))
    j = j.join(wr, on="ws_order_number", how="semi")
    return (j.group_by()
            .agg(F.count(col("ws_order_number"), distinct=True)
                 .alias("order_count"),
                 F.sum(col("ws_ext_ship_cost")).alias("total_ship"),
                 F.sum(col("ws_net_profit")).alias("total_profit"))
            .collect())


def _q95_oracle(a):
    import pandas as pd
    d0 = DATE_SK0 + 3 * 365 + 31
    ws = a["web_sales"].to_pandas()
    sel = ws[(ws.ws_ship_date_sk >= d0) & (ws.ws_ship_date_sk <= d0 + 60)]
    ca = a["customer_address"].to_pandas()
    ok = set(ca[ca.ca_state == "CA"].ca_address_sk)
    sel = sel[sel.ws_ship_addr_sk.isin(ok)]
    counts = ws.groupby("ws_order_number").size()
    multi = set(counts[counts > 1].index)
    returned = set(a["web_returns"].to_pandas().wr_order_number)
    sel = sel[sel.ws_order_number.isin(multi)
              & sel.ws_order_number.isin(returned)]
    return pa.Table.from_pydict({
        "order_count": [sel.ws_order_number.nunique()],
        "total_ship": [sel.ws_ext_ship_cost.sum()],
        "total_profit": [sel.ws_net_profit.sum()],
    })


_q("q95", "returned multi-line web orders shipped to one state")(
    (_q95_run, _q95_oracle))


# ===========================================================================
# q45: web sales by customer zip: zip prefix list OR item-id subquery
# ===========================================================================

def _q45_run(s, t):
    ws = _rd(s, t, "web_sales").select(
        "ws_sold_date_sk", "ws_bill_customer_sk", "ws_item_sk",
        "ws_sales_price")
    c = _rd(s, t, "customer").select("c_customer_sk", "c_current_addr_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_city",
                                              "ca_zip")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_qoy") == 2) & (col("d_year") == 2001)).select("d_date_sk")
    j = _join_dim(ws, c, "ws_bill_customer_sk", "c_customer_sk")
    j = _join_dim(j, ca, "c_current_addr_sk", "ca_address_sk")
    j = _join_dim(j, dd, "ws_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ws_item_sk", "i_item_sk")
    # items whose sk is in the template's small list → their item_ids
    special = _rd(s, t, "item").filter(
        col("i_item_sk").isin(2, 3, 5, 7, 11, 13, 17, 19, 23, 29)) \
        .select(col("i_item_id").alias("special_id"))
    j = j.join(_rename(special, special_id="i_item_id"), on="i_item_id",
               how="existence")
    keep = (F.substring(col("ca_zip"), lit(1), lit(2))
            .isin("85", "86", "88", "90", "91")
            | col("exists"))
    j = j.filter(keep)
    return (j.group_by("ca_zip", "ca_city")
            .agg(F.sum(col("ws_sales_price")).alias("total"))
            .sort(col("ca_zip").asc(), col("ca_city").asc())
            .limit(100).collect())


def _q45_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_qoy == 2) & (dd.d_year == 2001)].d_date_sk)
    ws = a["web_sales"].to_pandas()
    ws = ws[ws.ws_sold_date_sk.isin(days) & ws.ws_bill_customer_sk.notna()]
    c = a["customer"].to_pandas()[["c_customer_sk", "c_current_addr_sk"]]
    ca = a["customer_address"].to_pandas()[["ca_address_sk", "ca_city",
                                            "ca_zip"]]
    it = a["item"].to_pandas()[["i_item_sk", "i_item_id"]]
    j = ws.merge(c, left_on="ws_bill_customer_sk",
                 right_on="c_customer_sk")
    j = j.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    j = j.merge(it, left_on="ws_item_sk", right_on="i_item_sk")
    special = set(it[it.i_item_sk.isin(
        [2, 3, 5, 7, 11, 13, 17, 19, 23, 29])].i_item_id)
    keep = (j.ca_zip.str[:2].isin(["85", "86", "88", "90", "91"])
            | j.i_item_id.isin(special))
    j = j[keep]
    j["p"] = j.ws_sales_price.astype(float)
    g = j.groupby(["ca_zip", "ca_city"])["p"].sum() \
        .reset_index(name="total")
    g = g.sort_values(["ca_zip", "ca_city"]).head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q45", "web revenue by zip: prefix list OR special-item subquery")(
    (_q45_run, _q45_oracle))


# ===========================================================================
# q31: counties where web sales growth outpaced store sales growth
# ===========================================================================

def _q31_run(s, t):
    def chan_q(fact, date_k, addr_k, price, year, qoy, alias):
        f = _rd(s, t, fact).select(date_k, addr_k, price)
        dd = _rd(s, t, "date_dim").filter(
            (col("d_year") == year) & (col("d_qoy") == qoy)) \
            .select("d_date_sk")
        ca = _rd(s, t, "customer_address").select("ca_address_sk",
                                                  "ca_county")
        j = _join_dim(f, dd, date_k, "d_date_sk")
        j = _join_dim(j, ca, addr_k, "ca_address_sk")
        return (j.group_by("ca_county")
                .agg(F.sum(col(price)).alias(alias)))

    ss1 = chan_q("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                 "ss_ext_sales_price", 2000, 1, "ss1")
    ss2 = chan_q("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                 "ss_ext_sales_price", 2000, 2, "ss2")
    ws1 = chan_q("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                 "ws_ext_sales_price", 2000, 1, "ws1")
    ws2 = chan_q("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                 "ws_ext_sales_price", 2000, 2, "ws2")
    j = ss1.join(ss2, on="ca_county", how="inner")
    j = j.join(ws1, on="ca_county", how="inner")
    j = j.join(ws2, on="ca_county", how="inner")
    f = lambda nm: col(nm).cast(DataType.FLOAT64)
    j = j.filter((f("ss1") > lit(0.0)) & (f("ws1") > lit(0.0))
                 & (f("ws2") / f("ws1") > f("ss2") / f("ss1")))
    return (j.select("ca_county",
                     (f("ws2") / f("ws1")).alias("web_g"),
                     (f("ss2") / f("ss1")).alias("store_g"))
            .sort(col("ca_county").asc()).collect())


def _q31_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    ca = a["customer_address"].to_pandas()[["ca_address_sk", "ca_county"]]

    def chan_q(name, date_k, addr_k, price, year, qoy):
        days = set(dd[(dd.d_year == year) & (dd.d_qoy == qoy)].d_date_sk)
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days)]
        j = f.merge(ca, left_on=addr_k, right_on="ca_address_sk")
        j["p"] = j[price].astype(float)
        return j.groupby("ca_county")["p"].sum()

    ss1 = chan_q("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                 "ss_ext_sales_price", 2000, 1)
    ss2 = chan_q("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                 "ss_ext_sales_price", 2000, 2)
    ws1 = chan_q("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                 "ws_ext_sales_price", 2000, 1)
    ws2 = chan_q("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                 "ws_ext_sales_price", 2000, 2)
    df = pd.concat([ss1.rename("ss1"), ss2.rename("ss2"),
                    ws1.rename("ws1"), ws2.rename("ws2")], axis=1) \
        .dropna()
    df = df[(df.ss1 > 0) & (df.ws1 > 0)
            & (df.ws2 / df.ws1 > df.ss2 / df.ss1)].copy()
    df["web_g"] = df.ws2 / df.ws1
    df["store_g"] = df.ss2 / df.ss1
    out = df[["web_g", "store_g"]].reset_index() \
        .rename(columns={"index": "ca_county"}).sort_values("ca_county")
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q31", "counties where web growth beat store growth quarter/quarter")(
    (_q31_run, _q31_oracle))


# ===========================================================================
# q46: out-of-town weekend shoppers' tickets by city
# ===========================================================================

def _q46_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_addr_sk",
        "ss_customer_sk", "ss_ticket_number", "ss_coupon_amt",
        "ss_net_profit")
    dd = _rd(s, t, "date_dim").filter(
        col("d_day_name").isin("Saturday", "Sunday")
        & col("d_year").isin(1999, 2000, 2001)).select("d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    hd = _rd(s, t, "household_demographics").filter(
        (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3)) \
        .select("hd_demo_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_city")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, ca, "ss_addr_sk", "ca_address_sk")
    g = (j.group_by("ss_ticket_number", "ss_customer_sk", "ca_city")
         .agg(F.sum(col("ss_coupon_amt")).alias("amt"),
              F.sum(col("ss_net_profit")).alias("profit")))
    c = _rd(s, t, "customer").select(
        col("c_customer_sk").alias("ss_customer_sk"),
        col("c_current_addr_sk"), col("c_first_name"),
        col("c_last_name"))
    g = g.join(c, on="ss_customer_sk", how="inner")
    cur = _rd(s, t, "customer_address").select(
        col("ca_address_sk").alias("c_current_addr_sk"),
        col("ca_city").alias("current_city"))
    g = g.join(cur, on="c_current_addr_sk", how="inner")
    g = g.filter(col("current_city") != col("ca_city"))
    return (g.select("c_last_name", "c_first_name", "ca_city",
                     "current_city", "ss_ticket_number", "amt", "profit")
            .sort(col("c_last_name").asc(), col("c_first_name").asc(),
                  col("ca_city").asc(), col("ss_ticket_number").asc())
            .limit(100).collect())


def _q46_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[dd.d_day_name.isin(["Saturday", "Sunday"])
                  & dd.d_year.isin([1999, 2000, 2001])].d_date_sk)
    hd = a["household_demographics"].to_pandas()
    hds = set(hd[(hd.hd_dep_count == 4)
                 | (hd.hd_vehicle_count == 3)].hd_demo_sk)
    ca = a["customer_address"].to_pandas()[["ca_address_sk", "ca_city"]]
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(days) & ss.ss_hdemo_sk.isin(hds)
            & ss.ss_customer_sk.notna()]
    j = ss.merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
    j["amt_f"] = j.ss_coupon_amt.astype(float)
    j["pro_f"] = j.ss_net_profit.astype(float)
    g = j.groupby(["ss_ticket_number", "ss_customer_sk", "ca_city"])[
        ["amt_f", "pro_f"]].sum().reset_index() \
        .rename(columns={"amt_f": "amt", "pro_f": "profit"})
    c = a["customer"].to_pandas()[
        ["c_customer_sk", "c_current_addr_sk", "c_first_name",
         "c_last_name"]]
    g = g.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
    cur = ca.rename(columns={"ca_address_sk": "cur_sk",
                             "ca_city": "current_city"})
    g = g.merge(cur, left_on="c_current_addr_sk", right_on="cur_sk")
    g = g[g.current_city != g.ca_city]
    out = g[["c_last_name", "c_first_name", "ca_city", "current_city",
             "ss_ticket_number", "amt", "profit"]]
    out = out.sort_values(["c_last_name", "c_first_name", "ca_city",
                           "ss_ticket_number"]).head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q46", "out-of-town weekend shoppers' tickets by city")(
    (_q46_run, _q46_oracle))


# ===========================================================================
# q66: warehouse monthly shipping totals, CASE-pivoted by month
# ===========================================================================

def _q66_run(s, t):
    w = _rd(s, t, "warehouse").select("w_warehouse_sk", "w_warehouse_name")
    sm = _rd(s, t, "ship_mode").filter(
        col("sm_type").isin("EXPRESS", "REGULAR")).select("sm_ship_mode_sk")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk", "d_moy")

    def chan(fact, date_k, sm_k, wh_k, price, qty):
        f = _rd(s, t, fact).select(date_k, sm_k, wh_k, price, qty)
        j = _join_dim(f, dd, date_k, "d_date_sk")
        j = _join_dim(j, sm, sm_k, "sm_ship_mode_sk")
        j = _join_dim(j, w, wh_k, "w_warehouse_sk")
        amt = (col(price).cast(DataType.FLOAT64)
               * col(qty).cast(DataType.FLOAT64))
        j = j.with_column("amt", amt)
        for m in (1, 4, 7, 10):
            j = j.with_column(
                f"m{m}", F.if_(col("d_moy") == m, col("amt"), lit(0.0)))
        return (j.group_by("w_warehouse_name")
                .agg(F.sum(col("m1")).alias("jan"),
                     F.sum(col("m4")).alias("apr"),
                     F.sum(col("m7")).alias("jul"),
                     F.sum(col("m10")).alias("oct_")))

    u = chan("web_sales", "ws_sold_date_sk", "ws_ship_mode_sk",
             "ws_warehouse_sk", "ws_sales_price", "ws_quantity") \
        .union(chan("catalog_sales", "cs_sold_date_sk", "cs_ship_mode_sk",
                    "cs_warehouse_sk", "cs_sales_price", "cs_quantity"))
    g = (u.group_by("w_warehouse_name")
         .agg(F.sum(col("jan")).alias("jan"),
              F.sum(col("apr")).alias("apr"),
              F.sum(col("jul")).alias("jul"),
              F.sum(col("oct_")).alias("oct_")))
    return g.sort(col("w_warehouse_name").asc()).limit(100).collect()


def _q66_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    dd = dd[dd.d_year == 2000][["d_date_sk", "d_moy"]]
    sm = a["ship_mode"].to_pandas()
    sms = set(sm[sm.sm_type.isin(["EXPRESS", "REGULAR"])].sm_ship_mode_sk)
    w = a["warehouse"].to_pandas()[["w_warehouse_sk", "w_warehouse_name"]]

    def chan(name, date_k, sm_k, wh_k, price, qty):
        f = a[name].to_pandas()
        f = f[f[sm_k].isin(sms)]
        j = f.merge(dd, left_on=date_k, right_on="d_date_sk")
        j = j.merge(w, left_on=wh_k, right_on="w_warehouse_sk")
        j["amt"] = j[price].astype(float) * j[qty]
        for m, nm in ((1, "jan"), (4, "apr"), (7, "jul"), (10, "oct_")):
            j[nm] = j.amt.where(j.d_moy == m, 0.0)
        return j.groupby("w_warehouse_name")[
            ["jan", "apr", "jul", "oct_"]].sum()

    u = chan("web_sales", "ws_sold_date_sk", "ws_ship_mode_sk",
             "ws_warehouse_sk", "ws_sales_price", "ws_quantity") \
        .add(chan("catalog_sales", "cs_sold_date_sk", "cs_ship_mode_sk",
                  "cs_warehouse_sk", "cs_sales_price", "cs_quantity"),
             fill_value=0.0)
    out = u.reset_index().sort_values("w_warehouse_name").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q66", "warehouse shipping totals CASE-pivoted by month, 2 channels")(
    (_q66_run, _q66_oracle))


# ===========================================================================
# q77: per-channel sales vs returns profit summary
# ===========================================================================

def _q77_run(s, t):
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")

    def side(fact, date_k, key_k, amt_k, alias_k, alias_a):
        f = _rd(s, t, fact).select(date_k, key_k, amt_k)
        j = _join_dim(f, dd, date_k, "d_date_sk")
        return (j.filter(col(key_k).is_not_null())
                .group_by(key_k)
                .agg(F.sum(col(amt_k)).alias(alias_a))
                .select(col(key_k).alias(alias_k), col(alias_a)))

    ss = side("store_sales", "ss_sold_date_sk", "ss_store_sk",
              "ss_net_profit", "sk", "sales_profit")
    sr = side("store_returns", "sr_returned_date_sk", "sr_store_sk",
              "sr_net_loss", "sk", "return_loss")
    j = ss.join(sr, on="sk", how="left")
    out = j.select(
        col("sk"),
        col("sales_profit").cast(DataType.FLOAT64).alias("profit"),
        F.coalesce(col("return_loss").cast(DataType.FLOAT64), lit(0.0))
        .alias("loss"))
    return out.sort(col("sk").asc()).limit(100).collect()


def _q77_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[dd.d_year == 2000].d_date_sk)
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(days)]
    g1 = ss.groupby("ss_store_sk")["ss_net_profit"].apply(
        lambda x: x.astype(float).sum()).rename("profit")
    sr = a["store_returns"].to_pandas()
    sr = sr[sr.sr_returned_date_sk.isin(days)]
    g2 = sr.groupby("sr_store_sk")["sr_net_loss"].apply(
        lambda x: x.astype(float).sum()).rename("loss")
    df = pd.concat([g1, g2], axis=1)
    df = df[df.profit.notna()]
    df["loss"] = df.loss.fillna(0.0)
    out = df.reset_index().rename(columns={"index": "sk",
                                           "ss_store_sk": "sk"})
    out = out[["sk", "profit", "loss"]].sort_values("sk").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q77", "store sales profit vs return loss per store (left join)")(
    (_q77_run, _q77_oracle))


# ===========================================================================
# q80: 3-channel sales and returns by entity for one month
# ===========================================================================

def _q80_run(s, t):
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") >= 8)
        & (col("d_moy") <= 9)).select("d_date_sk")

    # store channel: sales joined LEFT to returns on (item, ticket)
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_item_sk",
        "ss_ticket_number", "ss_ext_sales_price", "ss_net_profit")
    sr = _rd(s, t, "store_returns").select(
        col("sr_item_sk").alias("ss_item_sk"),
        col("sr_ticket_number").alias("ss_ticket_number"),
        col("sr_return_amt"), col("sr_net_loss"))
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = j.join(sr, on=["ss_item_sk", "ss_ticket_number"], how="left")
    j = j.with_column(
        "ret", F.coalesce(col("sr_return_amt").cast(DataType.FLOAT64),
                          lit(0.0)))
    store = (j.group_by("ss_store_sk")
             .agg(F.sum(col("ss_ext_sales_price")).alias("sales"),
                  F.sum(col("ret")).alias("returns_")))
    return (store.select(col("ss_store_sk").alias("entity"),
                         col("sales").cast(DataType.FLOAT64)
                         .alias("sales"),
                         col("returns_"))
            .sort(col("entity").asc()).limit(100).collect())


def _q80_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_year == 2000) & (dd.d_moy >= 8)
                  & (dd.d_moy <= 9)].d_date_sk)
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(days)]
    sr = a["store_returns"].to_pandas()[
        ["sr_item_sk", "sr_ticket_number", "sr_return_amt"]]
    j = ss.merge(sr, left_on=["ss_item_sk", "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_ticket_number"], how="left")
    j["ret"] = j.sr_return_amt.astype(float).fillna(0.0)
    j["sales_f"] = j.ss_ext_sales_price.astype(float)
    g = j.groupby("ss_store_sk").agg(
        sales=("sales_f", "sum"), returns_=("ret", "sum")).reset_index() \
        .rename(columns={"ss_store_sk": "entity"})
    g = g.sort_values("entity").head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q80", "store sales with LEFT-joined returns by store, one period")(
    (_q80_run, _q80_oracle))


# ===========================================================================
# q28: six price-band value profiles of store sales (scalar subqueries)
# ===========================================================================

def _q28_run(s, t):
    from auron_tpu.frontend.dataframe import scalar_subquery
    ss = _rd(s, t, "store_sales").select("ss_quantity", "ss_list_price")

    def band(lo_q, hi_q, name):
        b = ss.filter((col("ss_quantity") >= lo_q)
                      & (col("ss_quantity") <= hi_q))
        return (b.group_by()
                .agg(F.avg(col("ss_list_price").cast(DataType.FLOAT64))
                     .alias(f"avg{name}"),
                     F.count(col("ss_list_price"), distinct=True)
                     .alias(f"cnt{name}")))

    b1 = band(0, 5, "1")
    b2 = band(6, 10, "2")
    b3 = band(11, 15, "3")
    out = b1.select(
        col("avg1"), col("cnt1"),
        scalar_subquery(b2.select("avg2")).alias("avg2"),
        scalar_subquery(b2.select(col("cnt2").alias("c"))).alias("cnt2"),
        scalar_subquery(b3.select("avg3")).alias("avg3"),
        scalar_subquery(b3.select(col("cnt3").alias("c"))).alias("cnt3"))
    return out.collect()


def _q28_oracle(a):
    import pandas as pd
    ss = a["store_sales"].to_pandas()
    ss["lp"] = ss.ss_list_price.astype(float)

    def band(lo_q, hi_q):
        b = ss[(ss.ss_quantity >= lo_q) & (ss.ss_quantity <= hi_q)]
        return float(b.lp.mean()), int(b.ss_list_price.nunique())

    a1, c1 = band(0, 5)
    a2, c2 = band(6, 10)
    a3, c3 = band(11, 15)
    return pa.Table.from_pydict({
        "avg1": [a1], "cnt1": [c1], "avg2": [a2], "cnt2": [c2],
        "avg3": [a3], "cnt3": [c3]})


_q("q28", "price-band value profiles via scalar subqueries")(
    (_q28_run, _q28_oracle))


# ===========================================================================
# q51: cumulative channel maxima — ss vs ws running totals by item/day
# ===========================================================================

def _q51_run(s, t):
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 27)) \
        .select("d_date_sk")
    it_keep = _rd(s, t, "item").filter(col("i_item_sk") <= 40) \
        .select("i_item_sk")

    def daily(fact, date_k, item_k, price, alias):
        f = _rd(s, t, fact).select(date_k, item_k, price)
        j = _join_dim(f, dd, date_k, "d_date_sk")
        j = _join_dim(j, it_keep, item_k, "i_item_sk")
        return (j.group_by(item_k, date_k)
                .agg(F.sum(col(price)).alias(alias))
                .select(col(item_k).alias("item_sk"),
                        col(date_k).alias("date_sk"), col(alias)))

    web = daily("web_sales", "ws_sold_date_sk", "ws_item_sk",
                "ws_ext_sales_price", "web_sales")
    store = daily("store_sales", "ss_sold_date_sk", "ss_item_sk",
                  "ss_ext_sales_price", "store_sales_")
    j = web.join(store, on=["item_sk", "date_sk"], how="inner")
    w = j.window(
        [F.win_agg("sum", col("web_sales").cast(DataType.FLOAT64))
         .alias("cume_web"),
         F.win_agg("sum", col("store_sales_").cast(DataType.FLOAT64))
         .alias("cume_store")],
        partition_by=[col("item_sk")], order_by=[col("date_sk")])
    w = w.filter(col("cume_web") > col("cume_store"))
    return (w.select("item_sk", "date_sk", "cume_web", "cume_store")
            .sort(col("item_sk").asc(), col("date_sk").asc())
            .limit(100).collect())


def _q51_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_month_seq >= 24) & (dd.d_month_seq <= 27)]
               .d_date_sk)

    def daily(name, date_k, item_k, price, alias):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days) & (f[item_k] <= 40)].copy()
        f["p"] = f[price].astype(float)
        return f.groupby([item_k, date_k])["p"].sum() \
            .reset_index(name=alias) \
            .rename(columns={item_k: "item_sk", date_k: "date_sk"})

    web = daily("web_sales", "ws_sold_date_sk", "ws_item_sk",
                "ws_ext_sales_price", "web_sales")
    store = daily("store_sales", "ss_sold_date_sk", "ss_item_sk",
                  "ss_ext_sales_price", "store_sales_")
    j = web.merge(store, on=["item_sk", "date_sk"])
    j = j.sort_values(["item_sk", "date_sk"])
    j["cume_web"] = j.groupby("item_sk")["web_sales"].cumsum()
    j["cume_store"] = j.groupby("item_sk")["store_sales_"].cumsum()
    j = j[j.cume_web > j.cume_store]
    out = j[["item_sk", "date_sk", "cume_web", "cume_store"]] \
        .sort_values(["item_sk", "date_sk"]).head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q51", "items where web running total overtakes store (windows)")(
    (_q51_run, _q51_oracle))


# ===========================================================================
# q85: web returns by reason for demographic/address refund slices
# ===========================================================================

def _q85_run(s, t):
    wr = _rd(s, t, "web_returns").select(
        "wr_returned_date_sk", "wr_item_sk", "wr_order_number",
        "wr_refunded_cdemo_sk", "wr_refunded_addr_sk", "wr_reason_sk",
        "wr_return_amt", "wr_fee")
    ws = _rd(s, t, "web_sales").select(
        col("ws_item_sk").alias("wr_item_sk"),
        col("ws_order_number").alias("wr_order_number"),
        col("ws_quantity"), col("ws_sales_price"))
    j = wr.join(ws, on=["wr_item_sk", "wr_order_number"], how="inner")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    j = _join_dim(j, dd, "wr_returned_date_sk", "d_date_sk")
    cd = _rd(s, t, "customer_demographics").filter(
        col("cd_education_status").isin("College", "Primary")
        & col("cd_marital_status").isin("M", "S")) \
        .select("cd_demo_sk")
    j = _join_dim(j, cd, "wr_refunded_cdemo_sk", "cd_demo_sk")
    ca = _rd(s, t, "customer_address").filter(
        col("ca_state").isin("CA", "TX", "NY", "OH", "GA", "WA")) \
        .select("ca_address_sk")
    j = _join_dim(j, ca, "wr_refunded_addr_sk", "ca_address_sk")
    r = _rd(s, t, "reason").select("r_reason_sk", "r_reason_desc")
    j = _join_dim(j, r, "wr_reason_sk", "r_reason_sk")
    return (j.group_by("r_reason_desc")
            .agg(F.avg(col("ws_quantity").cast(DataType.FLOAT64))
                 .alias("avg_qty"),
                 F.avg(col("wr_return_amt").cast(DataType.FLOAT64))
                 .alias("avg_amt"),
                 F.avg(col("wr_fee").cast(DataType.FLOAT64))
                 .alias("avg_fee"))
            .sort(col("r_reason_desc").asc()).limit(100).collect())


def _q85_oracle(a):
    import pandas as pd
    wr = a["web_returns"].to_pandas()
    ws = a["web_sales"].to_pandas()[
        ["ws_item_sk", "ws_order_number", "ws_quantity",
         "ws_sales_price"]]
    j = wr.merge(ws, left_on=["wr_item_sk", "wr_order_number"],
                 right_on=["ws_item_sk", "ws_order_number"])
    dd = a["date_dim"].to_pandas()
    days = set(dd[dd.d_year == 2000].d_date_sk)
    j = j[j.wr_returned_date_sk.isin(days)]
    cd = a["customer_demographics"].to_pandas()
    cds = set(cd[cd.cd_education_status.isin(["College", "Primary"])
                 & cd.cd_marital_status.isin(["M", "S"])].cd_demo_sk)
    j = j[j.wr_refunded_cdemo_sk.isin(cds)]
    ca = a["customer_address"].to_pandas()
    cas = set(ca[ca.ca_state.isin(["CA", "TX", "NY", "OH", "GA",
                                   "WA"])].ca_address_sk)
    j = j[j.wr_refunded_addr_sk.isin(cas)]
    r = a["reason"].to_pandas()
    j = j.merge(r, left_on="wr_reason_sk", right_on="r_reason_sk")
    j["q"] = j.ws_quantity.astype(float)
    j["amt"] = j.wr_return_amt.astype(float)
    j["fee"] = j.wr_fee.astype(float)
    g = j.groupby("r_reason_desc").agg(
        avg_qty=("q", "mean"), avg_amt=("amt", "mean"),
        avg_fee=("fee", "mean")).reset_index()
    g = g.sort_values("r_reason_desc").head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q85", "web return profiles by reason for refund slices")(
    (_q85_run, _q85_oracle))


# ===========================================================================
# q83: 3-channel return totals for one set of weeks (week_seq subquery)
# ===========================================================================

def _q83_run(s, t):
    weeks = _rd(s, t, "date_dim").filter(
        col("d_moy").isin(2, 5, 8) & (col("d_year") == 2000)
        & (col("d_dom") == 15)).select("d_week_seq")
    dd = _rd(s, t, "date_dim").select("d_date_sk", "d_week_seq")
    sel_days = dd.join(weeks, on="d_week_seq", how="semi") \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id")

    def chan(fact, date_k, item_k, qty, alias):
        f = _rd(s, t, fact).select(date_k, item_k, qty)
        j = f.join(_rename(sel_days, d_date_sk=date_k), on=date_k,
                   how="semi")
        j = _join_dim(j, it, item_k, "i_item_sk")
        return (j.group_by("i_item_id")
                .agg(F.sum(col(qty)).alias(alias)))

    sr = chan("store_returns", "sr_returned_date_sk", "sr_item_sk",
              "sr_return_quantity", "sr_qty")
    cr = chan("catalog_returns", "cr_returned_date_sk", "cr_item_sk",
              "cr_return_quantity", "cr_qty")
    wr = chan("web_returns", "wr_returned_date_sk", "wr_item_sk",
              "wr_return_quantity", "wr_qty")
    j = sr.join(cr, on="i_item_id", how="inner")
    j = j.join(wr, on="i_item_id", how="inner")
    return (j.select("i_item_id", "sr_qty", "cr_qty", "wr_qty")
            .sort(col("i_item_id").asc()).limit(100).collect())


def _q83_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    weeks = set(dd[dd.d_moy.isin([2, 5, 8]) & (dd.d_year == 2000)
                   & (dd.d_dom == 15)].d_week_seq)
    days = set(dd[dd.d_week_seq.isin(weeks)].d_date_sk)
    it = a["item"].to_pandas()[["i_item_sk", "i_item_id"]]

    def chan(name, date_k, item_k, qty, alias):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days)]
        j = f.merge(it, left_on=item_k, right_on="i_item_sk")
        return j.groupby("i_item_id")[qty].sum().rename(alias)

    sr = chan("store_returns", "sr_returned_date_sk", "sr_item_sk",
              "sr_return_quantity", "sr_qty")
    cr = chan("catalog_returns", "cr_returned_date_sk", "cr_item_sk",
              "cr_return_quantity", "cr_qty")
    wr = chan("web_returns", "wr_returned_date_sk", "wr_item_sk",
              "wr_return_quantity", "wr_qty")
    j = pd.concat([sr, cr, wr], axis=1).dropna().reset_index()
    j = j.sort_values("i_item_id").head(100)
    j[["sr_qty", "cr_qty", "wr_qty"]] = \
        j[["sr_qty", "cr_qty", "wr_qty"]].astype("int64")
    return pa.Table.from_pandas(j.reset_index(drop=True),
                                preserve_index=False)


_q("q83", "items returned in all 3 channels in chosen weeks")(
    (_q83_run, _q83_oracle))


# ===========================================================================
# q2: web+catalog weekly sales, year-over-year day-of-week ratios
# ===========================================================================

def _q2_run(s, t):
    dd = _rd(s, t, "date_dim").select("d_date_sk", "d_week_seq",
                                      "d_day_name")

    def chan(fact, date_k, price):
        f = _rd(s, t, fact).select(col(date_k).alias("d_date_sk"),
                                   col(price).alias("p"))
        return f

    u = chan("web_sales", "ws_sold_date_sk", "ws_ext_sales_price") \
        .union(chan("catalog_sales", "cs_sold_date_sk",
                    "cs_ext_sales_price"))
    j = u.join(dd, on="d_date_sk", how="inner")
    price = col("p").cast(DataType.FLOAT64)
    for day, nm in (("Sunday", "sun"), ("Monday", "mon"),
                    ("Thursday", "thu"), ("Saturday", "sat")):
        j = j.with_column(nm, F.if_(col("d_day_name") == day, price,
                                    lit(0.0)))
    wk = (j.group_by("d_week_seq")
          .agg(F.sum(col("sun")).alias("sun_s"),
               F.sum(col("mon")).alias("mon_s"),
               F.sum(col("thu")).alias("thu_s"),
               F.sum(col("sat")).alias("sat_s")))
    y1 = wk.filter((col("d_week_seq") >= 5270 + 52)
                   & (col("d_week_seq") < 5270 + 104)) \
        .select(col("d_week_seq").alias("wk"), col("sun_s").alias("s1"),
                col("mon_s").alias("m1"), col("thu_s").alias("t1"),
                col("sat_s").alias("a1"))
    y2 = wk.filter((col("d_week_seq") >= 5270 + 104)
                   & (col("d_week_seq") < 5270 + 156)) \
        .select((col("d_week_seq") - lit(52, DataType.INT64)).alias("wk"),
                col("sun_s").alias("s2"), col("mon_s").alias("m2"),
                col("thu_s").alias("t2"), col("sat_s").alias("a2"))
    j2 = y1.join(y2, on="wk", how="inner")
    safe = lambda a, b: F.if_(col(b) > lit(0.0), col(a) / col(b),
                              lit(None, DataType.FLOAT64))
    out = j2.select(col("wk"), safe("s1", "s2").alias("sun_r"),
                    safe("m1", "m2").alias("mon_r"),
                    safe("t1", "t2").alias("thu_r"),
                    safe("a1", "a2").alias("sat_r"))
    return out.sort(col("wk").asc()).limit(100).collect()


def _q2_oracle(a):
    import numpy as _np
    import pandas as pd
    dd = a["date_dim"].to_pandas()[["d_date_sk", "d_week_seq",
                                    "d_day_name"]]
    frames = []
    for name, date_k, price in (
            ("web_sales", "ws_sold_date_sk", "ws_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_ext_sales_price")):
        f = a[name].to_pandas()[[date_k, price]]
        f.columns = ["d_date_sk", "p"]
        frames.append(f)
    u = pd.concat(frames).merge(dd, on="d_date_sk")
    u["pf"] = u.p.astype(float)
    for day, nm in (("Sunday", "sun"), ("Monday", "mon"),
                    ("Thursday", "thu"), ("Saturday", "sat")):
        u[nm] = u.pf.where(u.d_day_name == day, 0.0)
    wk = u.groupby("d_week_seq")[["sun", "mon", "thu", "sat"]].sum()
    y1 = wk[(wk.index >= 5270 + 52) & (wk.index < 5270 + 104)].copy()
    y2 = wk[(wk.index >= 5270 + 104) & (wk.index < 5270 + 156)].copy()
    y2.index = y2.index - 52
    j = y1.join(y2, lsuffix="1", rsuffix="2", how="inner")
    out = pd.DataFrame(index=j.index)
    for nm, r in (("sun", "sun_r"), ("mon", "mon_r"), ("thu", "thu_r"),
                  ("sat", "sat_r")):
        out[r] = _np.where(j[nm + "2"] > 0, j[nm + "1"] / j[nm + "2"],
                           _np.nan)
    out = out.reset_index().rename(columns={"d_week_seq": "wk"})
    out = out.sort_values("wk").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q2", "web+catalog weekly sales YoY day-of-week ratios")(
    (_q2_run, _q2_oracle))


# ===========================================================================
# q8: store sales for stores whose zip prefix matches active-buyer zips
# ===========================================================================

def _q8_run(s, t):
    dd = _rd(s, t, "date_dim").filter(
        (col("d_qoy") == 2) & (col("d_year") == 1998)).select("d_date_sk")
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_net_profit")
    # zips of customers who buy (preference slice), as 2-char prefixes
    c = _rd(s, t, "customer").select("c_current_addr_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_zip")
    buyers = _join_dim(c, ca, "c_current_addr_sk", "ca_address_sk") \
        .select(F.substring(col("ca_zip"), lit(1), lit(2)).alias("zp")) \
        .group_by("zp").agg()
    st = _rd(s, t, "store").select("s_store_sk", "s_store_name", "s_zip")
    st = st.with_column("zp", F.substring(col("s_zip"), lit(1), lit(2)))
    st = st.join(buyers, on="zp", how="semi")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    return (j.group_by("s_store_name")
            .agg(F.sum(col("ss_net_profit")).alias("profit"))
            .sort(col("s_store_name").asc()).limit(100).collect())


def _q8_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_qoy == 2) & (dd.d_year == 1998)].d_date_sk)
    c = a["customer"].to_pandas()
    ca = a["customer_address"].to_pandas()[["ca_address_sk", "ca_zip"]]
    j = c.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    zps = set(j.ca_zip.str[:2])
    st = a["store"].to_pandas()
    st = st[st.s_zip.str[:2].isin(zps)]
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(days)
            & ss.ss_store_sk.isin(set(st.s_store_sk))]
    j2 = ss.merge(st[["s_store_sk", "s_store_name"]], left_on="ss_store_sk",
                  right_on="s_store_sk")
    g = j2.groupby("s_store_name")["ss_net_profit"].sum() \
        .reset_index(name="profit")
    g = g.sort_values("s_store_name").head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q8", "store profits where store zip matches buyer zip prefixes")(
    (_q8_run, _q8_oracle))


# ===========================================================================
# q11: customers whose web yearly growth beat store growth (q74 on ids)
# ===========================================================================

def _q11_run(s, t):
    c = _rd(s, t, "customer").select("c_customer_sk", "c_customer_id")

    def totals(fact, cust_k, date_k, paid_k, years, alias):
        f = _rd(s, t, fact).select(cust_k, date_k, paid_k)
        dd = _rd(s, t, "date_dim").filter(col("d_year").isin(*years)) \
            .select("d_date_sk")
        j = _join_dim(f, dd, date_k, "d_date_sk")
        return (j.group_by(cust_k)
                .agg(F.sum(col(paid_k)).alias(alias))
                .select(col(cust_k).alias("c_customer_sk"), col(alias)))

    ss1 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_ext_list_price", (1998, 1999, 2000), "ss1")
    ss2 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_ext_list_price", (2001, 2002), "ss2")
    ws1 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_ext_sales_price", (1998, 1999, 2000), "ws1")
    ws2 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_ext_sales_price", (2001, 2002), "ws2")
    j = c.join(ss1, on="c_customer_sk", how="inner")
    j = j.join(ss2, on="c_customer_sk", how="inner")
    j = j.join(ws1, on="c_customer_sk", how="inner")
    j = j.join(ws2, on="c_customer_sk", how="inner")
    f = lambda nm: col(nm).cast(DataType.FLOAT64)
    j = j.filter((f("ss1") > lit(0.0)) & (f("ws1") > lit(0.0))
                 & (f("ws2") / f("ws1") > f("ss2") / f("ss1")))
    return (j.select("c_customer_id")
            .sort(col("c_customer_id").asc()).limit(100).collect())


def _q11_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    y1 = set(dd[dd.d_year.isin([1998, 1999, 2000])].d_date_sk)
    y2 = set(dd[dd.d_year.isin([2001, 2002])].d_date_sk)

    def totals(name, cust_k, date_k, paid_k, days):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days) & f[cust_k].notna()].copy()
        f["v"] = f[paid_k].astype(float)
        return f.groupby(cust_k)["v"].sum()

    ss1 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_ext_list_price", y1)
    ss2 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_ext_list_price", y2)
    ws1 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_ext_sales_price", y1)
    ws2 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_ext_sales_price", y2)
    df = pd.concat([ss1.rename("ss1"), ss2.rename("ss2"),
                    ws1.rename("ws1"), ws2.rename("ws2")], axis=1).dropna()
    df = df[(df.ss1 > 0) & (df.ws1 > 0)
            & (df.ws2 / df.ws1 > df.ss2 / df.ss1)]
    c = a["customer"].to_pandas().set_index("c_customer_sk")
    out = c.loc[c.index.intersection(df.index)][["c_customer_id"]] \
        .sort_values("c_customer_id").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q11", "customers whose web growth beat store growth (list-price)")(
    (_q11_run, _q11_oracle))


# ===========================================================================
# q27: demographic item averages with state ROLLUP
# ===========================================================================

def _q27_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_cdemo_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt",
        "ss_sales_price")
    cd = _rd(s, t, "customer_demographics").filter(
        (col("cd_gender") == "F") & (col("cd_marital_status") == "D")
        & (col("cd_education_status") == "College")) \
        .select("cd_demo_sk")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    st = _rd(s, t, "store").filter(
        col("s_state").isin("CA", "TX", "NY", "OH")) \
        .select("s_store_sk", "s_state")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id")
    j = _join_dim(ss, cd, "ss_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    g = (j.rollup(col("i_item_id"), col("s_state"))
         .agg(F.avg(col("ss_quantity").cast(DataType.FLOAT64))
              .alias("agg1"),
              F.avg(col("ss_list_price").cast(DataType.FLOAT64))
              .alias("agg2"),
              F.avg(col("ss_coupon_amt").cast(DataType.FLOAT64))
              .alias("agg3"),
              F.avg(col("ss_sales_price").cast(DataType.FLOAT64))
              .alias("agg4")))
    return (g.select("i_item_id", "s_state", "agg1", "agg2", "agg3",
                     "agg4")
            .sort(col("i_item_id").asc(), col("s_state").asc())
            .limit(100).collect())


def _q27_oracle(a):
    import pandas as pd
    cd = a["customer_demographics"].to_pandas()
    cds = set(cd[(cd.cd_gender == "F") & (cd.cd_marital_status == "D")
                 & (cd.cd_education_status == "College")].cd_demo_sk)
    dd = a["date_dim"].to_pandas()
    days = set(dd[dd.d_year == 2000].d_date_sk)
    st = a["store"].to_pandas()
    st = st[st.s_state.isin(["CA", "TX", "NY", "OH"])][
        ["s_store_sk", "s_state"]]
    it = a["item"].to_pandas()[["i_item_sk", "i_item_id"]]
    ss = a["store_sales"].to_pandas()
    j = ss[ss.ss_cdemo_sk.isin(cds) & ss.ss_sold_date_sk.isin(days)]
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    for src_c, nm in (("ss_quantity", "q"), ("ss_list_price", "lp"),
                      ("ss_coupon_amt", "cp"), ("ss_sales_price", "sp")):
        j[nm] = j[src_c].astype(float)
    lv2 = j.groupby(["i_item_id", "s_state"])[
        ["q", "lp", "cp", "sp"]].mean().reset_index()
    lv1 = j.groupby(["i_item_id"])[["q", "lp", "cp", "sp"]] \
        .mean().reset_index()
    lv1["s_state"] = None
    lv0 = pd.DataFrame([{"i_item_id": None, "s_state": None,
                         "q": j.q.mean(), "lp": j.lp.mean(),
                         "cp": j.cp.mean(), "sp": j.sp.mean()}])
    g = pd.concat([lv2, lv1, lv0], ignore_index=True).rename(
        columns={"q": "agg1", "lp": "agg2", "cp": "agg3", "sp": "agg4"})
    g = g[["i_item_id", "s_state", "agg1", "agg2", "agg3", "agg4"]]
    g = g.sort_values(["i_item_id", "s_state"],
                      na_position="first").head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q27", "demographic item averages with state ROLLUP")(
    (_q27_run, _q27_oracle))


# ===========================================================================
# q29: store buy -> return -> store re-buy quantities (q25's qty twin)
# ===========================================================================

def _q29_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_customer_sk",
        "ss_ticket_number", "ss_quantity")
    sr = _rd(s, t, "store_returns").select(
        "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
        "sr_ticket_number", "sr_return_quantity")
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk",
        "cs_quantity")
    d1 = _rd(s, t, "date_dim").filter(
        (col("d_moy") >= 1) & (col("d_moy") <= 6)
        & (col("d_year") == 2000)).select(
        col("d_date_sk").alias("ss_sold_date_sk"))
    d2 = _rd(s, t, "date_dim").filter(col("d_year") == 2000).select(
        col("d_date_sk").alias("sr_returned_date_sk"))
    d3 = _rd(s, t, "date_dim").filter(
        col("d_year").isin(2000, 2001, 2002)).select(
        col("d_date_sk").alias("cs_sold_date_sk"))
    st = _rd(s, t, "store").select("s_store_sk", "s_store_id",
                                   "s_store_name")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id", "i_item_desc")
    j = ss.join(d1, on="ss_sold_date_sk", how="inner")
    j = j.join(_rename(sr, sr_item_sk="ss_item_sk",
                       sr_customer_sk="ss_customer_sk",
                       sr_ticket_number="ss_ticket_number"),
               on=["ss_item_sk", "ss_customer_sk", "ss_ticket_number"],
               how="inner")
    j = j.join(d2, on="sr_returned_date_sk", how="inner")
    j = j.join(_rename(cs, cs_item_sk="ss_item_sk",
                       cs_bill_customer_sk="ss_customer_sk"),
               on=["ss_item_sk", "ss_customer_sk"], how="inner")
    j = j.join(d3, on="cs_sold_date_sk", how="inner")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    return (j.group_by("i_item_id", "i_item_desc", "s_store_id",
                       "s_store_name")
            .agg(F.sum(col("ss_quantity")).alias("store_qty"),
                 F.sum(col("sr_return_quantity")).alias("return_qty"),
                 F.sum(col("cs_quantity")).alias("catalog_qty"))
            .sort(col("i_item_id").asc(), col("i_item_desc").asc(),
                  col("s_store_id").asc())
            .limit(100).collect())


def _q29_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    d1 = set(dd[(dd.d_moy >= 1) & (dd.d_moy <= 6)
                & (dd.d_year == 2000)].d_date_sk)
    d2 = set(dd[dd.d_year == 2000].d_date_sk)
    d3 = set(dd[dd.d_year.isin([2000, 2001, 2002])].d_date_sk)
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(d1) & ss.ss_customer_sk.notna()]
    sr = a["store_returns"].to_pandas()
    sr = sr[sr.sr_returned_date_sk.isin(d2) & sr.sr_customer_sk.notna()]
    cs = a["catalog_sales"].to_pandas()
    cs = cs[cs.cs_sold_date_sk.isin(d3) & cs.cs_bill_customer_sk.notna()]
    j = ss.merge(sr, left_on=["ss_item_sk", "ss_customer_sk",
                              "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_customer_sk",
                           "sr_ticket_number"])
    j = j.merge(cs, left_on=["ss_item_sk", "ss_customer_sk"],
                right_on=["cs_item_sk", "cs_bill_customer_sk"])
    j = j.merge(a["store"].to_pandas(), left_on="ss_store_sk",
                right_on="s_store_sk")
    j = j.merge(a["item"].to_pandas(), left_on="ss_item_sk",
                right_on="i_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "s_store_id",
                   "s_store_name"])[
        ["ss_quantity", "sr_return_quantity", "cs_quantity"]] \
        .sum().reset_index() \
        .rename(columns={"ss_quantity": "store_qty",
                         "sr_return_quantity": "return_qty",
                         "cs_quantity": "catalog_qty"})
    g = g.sort_values(["i_item_id", "i_item_desc", "s_store_id"]) \
        .head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q29", "store buy -> return -> catalog re-buy quantities")(
    (_q29_run, _q29_oracle))


# ===========================================================================
# q57: monthly call-center sales vs centered moving average (q47 twin)
# ===========================================================================

def _q57_run(s, t):
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_call_center_sk",
        "cs_sales_price", "cs_quantity")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") >= 1999) & (col("d_year") <= 2001)) \
        .select("d_date_sk", "d_year", "d_moy")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_brand")
    cc = _rd(s, t, "call_center").select("cc_call_center_sk", "cc_name")
    j = _join_dim(cs, dd, "cs_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "cs_item_sk", "i_item_sk")
    j = _join_dim(j, cc, "cs_call_center_sk", "cc_call_center_sk")
    amt = (col("cs_sales_price").cast(DataType.FLOAT64)
           * col("cs_quantity").cast(DataType.FLOAT64))
    g = (j.with_column("amt", amt)
         .group_by("i_category", "i_brand", "cc_name", "d_year", "d_moy")
         .agg(F.sum(col("amt")).alias("sum_sales")))
    w = g.window([F.win_agg("avg", col("sum_sales"), frame=(-1, 1))
                  .alias("avg3")],
                 partition_by=[col("i_category"), col("i_brand"),
                               col("cc_name")],
                 order_by=[col("d_year"), col("d_moy")])
    out = w.filter((col("d_year") == 2000)
                   & (col("sum_sales") - col("avg3") != lit(0.0)))
    return (out.select("i_category", "i_brand", "cc_name", "d_year",
                       "d_moy", "sum_sales", "avg3")
            .sort(col("i_category").asc(), col("i_brand").asc(),
                  col("cc_name").asc(), col("d_year").asc(),
                  col("d_moy").asc())
            .limit(100).collect())


def _q57_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    dd = dd[(dd.d_year >= 1999) & (dd.d_year <= 2001)][
        ["d_date_sk", "d_year", "d_moy"]]
    it = a["item"].to_pandas()[["i_item_sk", "i_category", "i_brand"]]
    cc = a["call_center"].to_pandas()[["cc_call_center_sk", "cc_name"]]
    cs = a["catalog_sales"].to_pandas()
    j = cs.merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it, left_on="cs_item_sk", right_on="i_item_sk")
    j = j.merge(cc, left_on="cs_call_center_sk",
                right_on="cc_call_center_sk")
    j["amt"] = j.cs_sales_price.astype(float) * j.cs_quantity
    g = j.groupby(["i_category", "i_brand", "cc_name", "d_year",
                   "d_moy"])["amt"].sum().reset_index(name="sum_sales")
    g = g.sort_values(["i_category", "i_brand", "cc_name", "d_year",
                       "d_moy"])
    g["avg3"] = g.groupby(["i_category", "i_brand", "cc_name"])[
        "sum_sales"].transform(
        lambda x: x.rolling(3, center=True, min_periods=1).mean())
    g = g[(g.d_year == 2000) & (g.sum_sales - g.avg3 != 0.0)]
    g = g[["i_category", "i_brand", "cc_name", "d_year", "d_moy",
           "sum_sales", "avg3"]]
    g = g.sort_values(["i_category", "i_brand", "cc_name", "d_year",
                       "d_moy"]).head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q57", "monthly call-center sales vs centered moving average")(
    (_q57_run, _q57_oracle))


# ===========================================================================
# q92: web discounts exceeding 1.3x the item-period average (q32 twin)
# ===========================================================================

def _q92_run(s, t):
    d0 = DATE_SK0 + 2 * 365 + 26
    ws = _rd(s, t, "web_sales").select(
        "ws_sold_date_sk", "ws_item_sk", "ws_ext_discount_amt")
    ws = ws.filter((col("ws_sold_date_sk") >= lit(d0, DataType.INT64))
                   & (col("ws_sold_date_sk") <= lit(d0 + 90,
                                                    DataType.INT64)))
    it = _rd(s, t, "item").filter(col("i_manufact_id") <= 200) \
        .select("i_item_sk")
    j = _join_dim(ws, it, "ws_item_sk", "i_item_sk")
    per_item = (j.group_by("ws_item_sk")
                .agg(F.avg(col("ws_ext_discount_amt")
                           .cast(DataType.FLOAT64)).alias("avg_disc")))
    j2 = j.join(per_item, on="ws_item_sk", how="inner")
    j2 = j2.filter(col("ws_ext_discount_amt").cast(DataType.FLOAT64)
                   > lit(1.3) * col("avg_disc"))
    return (j2.group_by()
            .agg(F.sum(col("ws_ext_discount_amt"))
                 .alias("excess_discount"))
            .collect())


def _q92_oracle(a):
    import pandas as pd
    d0 = DATE_SK0 + 2 * 365 + 26
    it = a["item"].to_pandas()
    ok_items = set(it[it.i_manufact_id <= 200].i_item_sk)
    ws = a["web_sales"].to_pandas()
    ws = ws[(ws.ws_sold_date_sk >= d0) & (ws.ws_sold_date_sk <= d0 + 90)
            & ws.ws_item_sk.isin(ok_items)].copy()
    ws["disc"] = ws.ws_ext_discount_amt.astype(float)
    avg = ws.groupby("ws_item_sk")["disc"].transform("mean")
    sel = ws[ws.disc > 1.3 * avg]
    return pa.Table.from_pydict(
        {"excess_discount": [sel.ws_ext_discount_amt.sum()]})


_q("q92", "web discounts exceeding 1.3x item-period average")(
    (_q92_run, _q92_oracle))


# ===========================================================================
# q17: cross-channel quantity statistics incl. stdev (sum-of-squares)
# ===========================================================================

def _q17_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_customer_sk",
        "ss_ticket_number", "ss_quantity")
    sr = _rd(s, t, "store_returns").select(
        "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
        "sr_ticket_number", "sr_return_quantity")
    d1 = _rd(s, t, "date_dim").filter(
        (col("d_qoy") == 1) & (col("d_year") == 2000)).select(
        col("d_date_sk").alias("ss_sold_date_sk"))
    d2 = _rd(s, t, "date_dim").filter(
        col("d_year").isin(2000, 2001)).select(
        col("d_date_sk").alias("sr_returned_date_sk"))
    st = _rd(s, t, "store").select("s_store_sk", "s_state")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id", "i_item_desc")
    j = ss.join(d1, on="ss_sold_date_sk", how="inner")
    j = j.join(_rename(sr, sr_item_sk="ss_item_sk",
                       sr_customer_sk="ss_customer_sk",
                       sr_ticket_number="ss_ticket_number"),
               on=["ss_item_sk", "ss_customer_sk", "ss_ticket_number"],
               how="inner")
    j = j.join(d2, on="sr_returned_date_sk", how="inner")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    q = col("ss_quantity").cast(DataType.FLOAT64)
    j = j.with_column("q", q).with_column("q2", q * q)
    g = (j.group_by("i_item_id", "i_item_desc", "s_state")
         .agg(F.count(col("q")).alias("cnt"),
              F.avg(col("q")).alias("mean_q"),
              F.sum(col("q")).alias("sum_q"),
              F.sum(col("q2")).alias("sumsq_q")))
    # sample stdev via the sum-of-squares identity (the engine's agg set
    # composes it; genuine q17 calls stdev directly)
    n = col("cnt").cast(DataType.FLOAT64)
    var = ((col("sumsq_q") - col("sum_q") * col("sum_q") / n)
           / (n - lit(1.0)))
    g = g.filter(col("cnt") > 1).with_column("stdev_q", F.sqrt(var))
    return (g.select("i_item_id", "i_item_desc", "s_state", "cnt",
                     "mean_q", "stdev_q")
            .sort(col("i_item_id").asc(), col("s_state").asc())
            .limit(100).collect())


def _q17_oracle(a):
    import numpy as _np
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    d1 = set(dd[(dd.d_qoy == 1) & (dd.d_year == 2000)].d_date_sk)
    d2 = set(dd[dd.d_year.isin([2000, 2001])].d_date_sk)
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(d1) & ss.ss_customer_sk.notna()]
    sr = a["store_returns"].to_pandas()
    sr = sr[sr.sr_returned_date_sk.isin(d2) & sr.sr_customer_sk.notna()]
    j = ss.merge(sr, left_on=["ss_item_sk", "ss_customer_sk",
                              "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_customer_sk",
                           "sr_ticket_number"])
    j = j.merge(a["store"].to_pandas()[["s_store_sk", "s_state"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(a["item"].to_pandas()[
        ["i_item_sk", "i_item_id", "i_item_desc"]],
        left_on="ss_item_sk", right_on="i_item_sk")
    j["q"] = j.ss_quantity.astype(float)
    g = j.groupby(["i_item_id", "i_item_desc", "s_state"])["q"].agg(
        ["count", "mean", "std"]).reset_index() \
        .rename(columns={"count": "cnt", "mean": "mean_q",
                         "std": "stdev_q"})
    g = g[g.cnt > 1]
    g = g.sort_values(["i_item_id", "s_state"]).head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q17", "returned-item quantity statistics incl. stdev")(
    (_q17_run, _q17_oracle))


# ===========================================================================
# q4: customers whose catalog growth beat store growth (3-channel totals)
# ===========================================================================

def _q4_run(s, t):
    c = _rd(s, t, "customer").select("c_customer_sk", "c_customer_id")

    def totals(fact, cust_k, date_k, price_k, years, alias):
        f = _rd(s, t, fact).select(cust_k, date_k, price_k)
        dd = _rd(s, t, "date_dim").filter(col("d_year").isin(*years)) \
            .select("d_date_sk")
        j = _join_dim(f, dd, date_k, "d_date_sk")
        return (j.group_by(cust_k)
                .agg(F.sum(col(price_k)).alias(alias))
                .select(col(cust_k).alias("c_customer_sk"), col(alias)))

    y1, y2 = (1998, 1999, 2000), (2001, 2002)
    ss1 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_net_paid", y1, "ss1")
    ss2 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_net_paid", y2, "ss2")
    cs1 = totals("catalog_sales", "cs_bill_customer_sk",
                 "cs_sold_date_sk", "cs_ext_sales_price", y1, "cs1")
    cs2 = totals("catalog_sales", "cs_bill_customer_sk",
                 "cs_sold_date_sk", "cs_ext_sales_price", y2, "cs2")
    ws1 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_net_paid", y1, "ws1")
    ws2 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_net_paid", y2, "ws2")
    j = c
    for tbl in (ss1, ss2, cs1, cs2, ws1, ws2):
        j = j.join(tbl, on="c_customer_sk", how="inner")
    f = lambda nm: col(nm).cast(DataType.FLOAT64)
    j = j.filter((f("ss1") > lit(0.0)) & (f("cs1") > lit(0.0))
                 & (f("ws1") > lit(0.0))
                 & (f("cs2") / f("cs1") > f("ss2") / f("ss1"))
                 & (f("cs2") / f("cs1") > f("ws2") / f("ws1")))
    return (j.select("c_customer_id")
            .sort(col("c_customer_id").asc()).limit(100).collect())


def _q4_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    y1 = set(dd[dd.d_year.isin([1998, 1999, 2000])].d_date_sk)
    y2 = set(dd[dd.d_year.isin([2001, 2002])].d_date_sk)

    def totals(name, cust_k, date_k, price_k, days):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days) & f[cust_k].notna()].copy()
        f["v"] = f[price_k].astype(float)
        return f.groupby(cust_k)["v"].sum()

    ss1 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_net_paid", y1)
    ss2 = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_net_paid", y2)
    cs1 = totals("catalog_sales", "cs_bill_customer_sk",
                 "cs_sold_date_sk", "cs_ext_sales_price", y1)
    cs2 = totals("catalog_sales", "cs_bill_customer_sk",
                 "cs_sold_date_sk", "cs_ext_sales_price", y2)
    ws1 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_net_paid", y1)
    ws2 = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_net_paid", y2)
    df = pd.concat([ss1.rename("ss1"), ss2.rename("ss2"),
                    cs1.rename("cs1"), cs2.rename("cs2"),
                    ws1.rename("ws1"), ws2.rename("ws2")], axis=1) \
        .dropna()
    df = df[(df.ss1 > 0) & (df.cs1 > 0) & (df.ws1 > 0)
            & (df.cs2 / df.cs1 > df.ss2 / df.ss1)
            & (df.cs2 / df.cs1 > df.ws2 / df.ws1)]
    c = a["customer"].to_pandas().set_index("c_customer_sk")
    out = c.loc[c.index.intersection(df.index)][["c_customer_id"]] \
        .sort_values("c_customer_id").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q4", "customers whose catalog growth beat store AND web growth")(
    (_q4_run, _q4_oracle))


# ===========================================================================
# q5: per-store sales vs returned-amount summary for one fortnight
# ===========================================================================

def _q5_run(s, t):
    d0 = DATE_SK0 + 2 * 365 + 220
    dd = _rd(s, t, "date_dim").filter(
        (col("d_date_sk") >= lit(d0, DataType.INT64))
        & (col("d_date_sk") <= lit(d0 + 14, DataType.INT64))) \
        .select("d_date_sk")
    ss = _rd(s, t, "store_sales").select(
        col("ss_sold_date_sk").alias("d_date_sk"),
        col("ss_store_sk").alias("store_sk"),
        col("ss_ext_sales_price").alias("sales_price"))
    sr = _rd(s, t, "store_returns").select(
        col("sr_returned_date_sk").alias("d_date_sk"),
        col("sr_store_sk").alias("store_sk"),
        col("sr_return_amt").alias("return_amt"))
    sales = ss.join(dd, on="d_date_sk", how="semi") \
        .group_by("store_sk") \
        .agg(F.sum(col("sales_price")).alias("sales"))
    rets = sr.join(dd, on="d_date_sk", how="semi") \
        .group_by("store_sk") \
        .agg(F.sum(col("return_amt")).alias("returns_"))
    j = sales.join(rets, on="store_sk", how="left")
    st = _rd(s, t, "store").select(col("s_store_sk").alias("store_sk"),
                                   col("s_store_id"))
    j = j.join(st, on="store_sk", how="inner")
    out = j.select(
        col("s_store_id"),
        col("sales").cast(DataType.FLOAT64).alias("sales"),
        F.coalesce(col("returns_").cast(DataType.FLOAT64), lit(0.0))
        .alias("returns_"),
        (col("sales").cast(DataType.FLOAT64)
         - F.coalesce(col("returns_").cast(DataType.FLOAT64), lit(0.0)))
        .alias("net"))
    return out.sort(col("s_store_id").asc()).limit(100).collect()


def _q5_oracle(a):
    import pandas as pd
    d0 = DATE_SK0 + 2 * 365 + 220
    ss = a["store_sales"].to_pandas()
    ss = ss[(ss.ss_sold_date_sk >= d0) & (ss.ss_sold_date_sk <= d0 + 14)]
    sales = ss.groupby("ss_store_sk")["ss_ext_sales_price"].apply(
        lambda x: x.astype(float).sum()).rename("sales")
    sr = a["store_returns"].to_pandas()
    sr = sr[(sr.sr_returned_date_sk >= d0)
            & (sr.sr_returned_date_sk <= d0 + 14)]
    rets = sr.groupby("sr_store_sk")["sr_return_amt"].apply(
        lambda x: x.astype(float).sum()).rename("returns_")
    df = pd.concat([sales, rets], axis=1)
    df = df[df.sales.notna()]
    df["returns_"] = df.returns_.fillna(0.0)
    df["net"] = df.sales - df.returns_
    st = a["store"].to_pandas()[["s_store_sk", "s_store_id"]]
    out = df.reset_index().rename(columns={"index": "sk"})
    key = out.columns[0]
    out = out.merge(st, left_on=key, right_on="s_store_sk")
    out = out[["s_store_id", "sales", "returns_", "net"]] \
        .sort_values("s_store_id").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q5", "per-store sales vs returns summary for one fortnight")(
    (_q5_run, _q5_oracle))


# ===========================================================================
# q39: warehouse/item inventory variance screen (stdev/mean > 1)
# ===========================================================================

def _q39_run(s, t):
    inv = _rd(s, t, "inventory").select(
        "inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
        "inv_quantity_on_hand")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy").isin(1, 2))) \
        .select("d_date_sk", "d_moy")
    j = _join_dim(inv, dd, "inv_date_sk", "d_date_sk")
    q = col("inv_quantity_on_hand").cast(DataType.FLOAT64)
    j = j.with_column("q", q).with_column("q2", q * q)
    g = (j.group_by("inv_warehouse_sk", "inv_item_sk", "d_moy")
         .agg(F.count(col("q")).alias("cnt"),
              F.avg(col("q")).alias("mean_q"),
              F.sum(col("q")).alias("sum_q"),
              F.sum(col("q2")).alias("sumsq_q")))
    n = col("cnt").cast(DataType.FLOAT64)
    var = ((col("sumsq_q") - col("sum_q") * col("sum_q") / n)
           / (n - lit(1.0)))
    g = g.filter((col("cnt") > 1) & (col("mean_q") > lit(0.0)))
    g = g.with_column("cov", F.sqrt(var) / col("mean_q"))
    g = g.filter(col("cov") > lit(0.3))
    return (g.select("inv_warehouse_sk", "inv_item_sk", "d_moy",
                     "mean_q", "cov")
            .sort(col("inv_warehouse_sk").asc(), col("inv_item_sk").asc(),
                  col("d_moy").asc())
            .limit(100).collect())


def _q39_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    dd = dd[(dd.d_year == 2000) & dd.d_moy.isin([1, 2])][
        ["d_date_sk", "d_moy"]]
    inv = a["inventory"].to_pandas()
    j = inv.merge(dd, left_on="inv_date_sk", right_on="d_date_sk")
    j["q"] = j.inv_quantity_on_hand.astype(float)
    g = j.groupby(["inv_warehouse_sk", "inv_item_sk", "d_moy"])["q"] \
        .agg(["count", "mean", "std"]).reset_index() \
        .rename(columns={"count": "cnt", "mean": "mean_q"})
    g = g[(g.cnt > 1) & (g.mean_q > 0)].copy()
    g["cov"] = g["std"] / g.mean_q       # NB: g.cov is DataFrame.cov()
    g = g[g["cov"] > 0.3]
    g = g[["inv_warehouse_sk", "inv_item_sk", "d_moy", "mean_q", "cov"]]
    g = g.sort_values(["inv_warehouse_sk", "inv_item_sk", "d_moy"]) \
        .head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q39", "warehouse/item inventory variance screen (cov > k)")(
    (_q39_run, _q39_oracle))


# ===========================================================================
# q49: worst return ratios per channel with dual ranks
# ===========================================================================

def _q49_run(s, t):
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") == 12)) \
        .select("d_date_sk")
    ws = _rd(s, t, "web_sales").select(
        "ws_sold_date_sk", "ws_item_sk", "ws_order_number",
        "ws_quantity", "ws_net_paid")
    wr = _rd(s, t, "web_returns").select(
        col("wr_item_sk").alias("ws_item_sk"),
        col("wr_order_number").alias("ws_order_number"),
        col("wr_return_quantity"), col("wr_return_amt"))
    j = _join_dim(ws, dd, "ws_sold_date_sk", "d_date_sk")
    j = j.join(wr, on=["ws_item_sk", "ws_order_number"], how="left")
    j = j.with_column(
        "ret_q", F.coalesce(col("wr_return_quantity"),
                            lit(0, DataType.INT64)))
    j = j.with_column(
        "ret_a", F.coalesce(col("wr_return_amt").cast(DataType.FLOAT64),
                            lit(0.0)))
    g = (j.group_by("ws_item_sk")
         .agg(F.sum(col("ret_q")).alias("rq"),
              F.sum(col("ws_quantity")).alias("sq"),
              F.sum(col("ret_a")).alias("ra"),
              F.sum(col("ws_net_paid")).alias("sa")))
    g = g.filter(col("sq") > 0)
    g = g.with_column("qty_ratio",
                      col("rq").cast(DataType.FLOAT64)
                      / col("sq").cast(DataType.FLOAT64))
    w = g.window([F.rank().alias("rnk")],
                 order_by=[col("qty_ratio").desc(),
                           col("ws_item_sk").asc()])
    out = w.filter(col("rnk") <= 10)
    return (out.select("ws_item_sk", "qty_ratio", "rnk")
            .sort(col("rnk").asc(), col("ws_item_sk").asc())
            .collect())


def _q49_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_year == 2000) & (dd.d_moy == 12)].d_date_sk)
    ws = a["web_sales"].to_pandas()
    ws = ws[ws.ws_sold_date_sk.isin(days)]
    wr = a["web_returns"].to_pandas()[
        ["wr_item_sk", "wr_order_number", "wr_return_quantity",
         "wr_return_amt"]]
    j = ws.merge(wr, left_on=["ws_item_sk", "ws_order_number"],
                 right_on=["wr_item_sk", "wr_order_number"], how="left")
    j["ret_q"] = j.wr_return_quantity.fillna(0)
    g = j.groupby("ws_item_sk").agg(
        rq=("ret_q", "sum"), sq=("ws_quantity", "sum")).reset_index()
    g = g[g.sq > 0].copy()
    g["qty_ratio"] = g.rq / g.sq
    g = g.sort_values(["qty_ratio", "ws_item_sk"],
                      ascending=[False, True]).reset_index(drop=True)
    # engine rank() orders by (ratio desc, item asc): the unique item
    # tiebreaker makes ranks strictly positional, so mirror that
    g["rnk"] = g.index + 1
    g = g[g.rnk <= 10]
    out = g[["ws_item_sk", "qty_ratio", "rnk"]] \
        .sort_values(["rnk", "ws_item_sk"])
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q49", "worst web return quantity ratios with ranks")(
    (_q49_run, _q49_oracle))


# ===========================================================================
# q58: items with near-equal revenue share across all three channels
# ===========================================================================

def _q58_run(s, t):
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") == 11)) \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id")

    def chan(fact, date_k, item_k, price, alias):
        f = _rd(s, t, fact).select(date_k, item_k, price)
        j = _join_dim(f, dd, date_k, "d_date_sk")
        j = _join_dim(j, it, item_k, "i_item_sk")
        return (j.group_by("i_item_id")
                .agg(F.sum(col(price)).alias(alias)))

    ssr = chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
               "ss_ext_sales_price", "ss_rev")
    csr = chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
               "cs_ext_sales_price", "cs_rev")
    wsr = chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
               "ws_ext_sales_price", "ws_rev")
    j = ssr.join(csr, on="i_item_id", how="inner")
    j = j.join(wsr, on="i_item_id", how="inner")
    f = lambda nm: col(nm).cast(DataType.FLOAT64)
    avg_rev = (f("ss_rev") + f("cs_rev") + f("ws_rev")) / lit(3.0)
    j = j.with_column("avg_rev", avg_rev)
    band = lambda nm: ((f(nm) >= lit(0.5) * col("avg_rev"))
                       & (f(nm) <= lit(1.5) * col("avg_rev")))
    j = j.filter(band("ss_rev") & band("cs_rev") & band("ws_rev"))
    return (j.select("i_item_id", "ss_rev", "cs_rev", "ws_rev",
                     "avg_rev")
            .sort(col("i_item_id").asc()).limit(100).collect())


def _q58_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[(dd.d_year == 2000) & (dd.d_moy == 11)].d_date_sk)
    it = a["item"].to_pandas()[["i_item_sk", "i_item_id"]]

    def chan(name, date_k, item_k, price, alias):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(days)]
        j = f.merge(it, left_on=item_k, right_on="i_item_sk")
        return j.groupby("i_item_id")[price].apply(
            lambda x: x.sum()).rename(alias)

    df = pd.concat([
        chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price", "ss_rev"),
        chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price", "cs_rev"),
        chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price", "ws_rev")], axis=1).dropna()
    f = df.astype(float)
    f["avg_rev"] = (f.ss_rev + f.cs_rev + f.ws_rev) / 3.0
    keep = ((f.ss_rev >= 0.5 * f.avg_rev) & (f.ss_rev <= 1.5 * f.avg_rev)
            & (f.cs_rev >= 0.5 * f.avg_rev)
            & (f.cs_rev <= 1.5 * f.avg_rev)
            & (f.ws_rev >= 0.5 * f.avg_rev)
            & (f.ws_rev <= 1.5 * f.avg_rev))
    out = f[keep].reset_index().sort_values("i_item_id").head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q58", "items with near-equal revenue across all three channels")(
    (_q58_run, _q58_oracle))


# ===========================================================================
# q72: catalog orders promising inventory coverage in the ship week
# ===========================================================================

def _q72_run(s, t):
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_ship_date_sk", "cs_item_sk",
        "cs_bill_cdemo_sk", "cs_quantity")
    cd = _rd(s, t, "customer_demographics").filter(
        col("cd_marital_status") == "D").select("cd_demo_sk")
    d1 = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk", col("d_week_seq").alias("sold_week"))
    inv = _rd(s, t, "inventory").select(
        col("inv_item_sk").alias("cs_item_sk"),
        col("inv_date_sk"), col("inv_quantity_on_hand"))
    dd_inv = _rd(s, t, "date_dim").select(
        col("d_date_sk").alias("inv_date_sk"),
        col("d_week_seq").alias("sold_week"))
    inv = inv.join(dd_inv, on="inv_date_sk", how="inner")
    j = _join_dim(cs, cd, "cs_bill_cdemo_sk", "cd_demo_sk")
    j = j.join(_rename(d1, d_date_sk="cs_sold_date_sk"),
               on="cs_sold_date_sk", how="inner")
    # inventory row for the same item in the SOLD week with qoh below
    # the ordered quantity (the q72 shortage probe)
    j = j.join(inv, on=["cs_item_sk", "sold_week"], how="inner")
    j = j.filter(col("inv_quantity_on_hand") < col("cs_quantity"))
    it = _rd(s, t, "item").select("i_item_sk", "i_item_desc")
    j = _join_dim(j, it, "cs_item_sk", "i_item_sk")
    g = (j.group_by("i_item_desc", "sold_week")
         .agg(F.count_star().alias("n_short")))
    return (g.sort(col("n_short").desc(), col("i_item_desc").asc(),
                   col("sold_week").asc())
            .limit(100).collect())


def _q72_oracle(a):
    import pandas as pd
    cd = a["customer_demographics"].to_pandas()
    cds = set(cd[cd.cd_marital_status == "D"].cd_demo_sk)
    dd = a["date_dim"].to_pandas()[["d_date_sk", "d_week_seq", "d_year"]]
    cs = a["catalog_sales"].to_pandas()
    cs = cs[cs.cs_bill_cdemo_sk.isin(cds)]
    j = cs.merge(dd[dd.d_year == 2000], left_on="cs_sold_date_sk",
                 right_on="d_date_sk")
    j = j.rename(columns={"d_week_seq": "sold_week"})
    inv = a["inventory"].to_pandas()
    inv = inv.merge(dd[["d_date_sk", "d_week_seq"]],
                    left_on="inv_date_sk", right_on="d_date_sk")
    inv = inv.rename(columns={"d_week_seq": "sold_week"})
    j = j.merge(inv, left_on=["cs_item_sk", "sold_week"],
                right_on=["inv_item_sk", "sold_week"])
    j = j[j.inv_quantity_on_hand < j.cs_quantity]
    it = a["item"].to_pandas()[["i_item_sk", "i_item_desc"]]
    j = j.merge(it, left_on="cs_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_item_desc", "sold_week"]).size() \
        .reset_index(name="n_short")
    g = g.sort_values(["n_short", "i_item_desc", "sold_week"],
                      ascending=[False, True, True]).head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q72", "catalog orders exceeding same-week inventory on hand")(
    (_q72_run, _q72_oracle))


# ===========================================================================
# q75: catalog yearly item-attribute sales vs prior year (net of returns)
# ===========================================================================

def _q75_run(s, t):
    it = _rd(s, t, "item").filter(col("i_category") == "Home") \
        .select("i_item_sk", "i_brand_id", "i_class_id", "i_category_id")
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_order_number",
        "cs_quantity", "cs_ext_sales_price")
    cr = _rd(s, t, "catalog_returns").select(
        col("cr_item_sk").alias("cs_item_sk"),
        col("cr_order_number").alias("cs_order_number"),
        col("cr_return_quantity"), col("cr_return_amount"))
    j = cs.join(cr, on=["cs_item_sk", "cs_order_number"], how="left")
    dd = _rd(s, t, "date_dim").select("d_date_sk", "d_year")
    j = j.join(_rename(dd, d_date_sk="cs_sold_date_sk"),
               on="cs_sold_date_sk", how="inner")
    j = _join_dim(j, it, "cs_item_sk", "i_item_sk")
    qty = (col("cs_quantity")
           - F.coalesce(col("cr_return_quantity"), lit(0, DataType.INT64)))
    amt = (col("cs_ext_sales_price").cast(DataType.FLOAT64)
           - F.coalesce(col("cr_return_amount").cast(DataType.FLOAT64),
                        lit(0.0)))
    j = j.with_column("net_qty", qty).with_column("net_amt", amt)
    g = (j.group_by("d_year", "i_brand_id", "i_class_id", "i_category_id")
         .agg(F.sum(col("net_qty")).alias("qty"),
              F.sum(col("net_amt")).alias("amt")))
    y1 = g.filter(col("d_year") == 2000).select(
        col("i_brand_id"), col("i_class_id"), col("i_category_id"),
        col("qty").alias("qty1"), col("amt").alias("amt1"))
    y2 = g.filter(col("d_year") == 2001).select(
        col("i_brand_id"), col("i_class_id"), col("i_category_id"),
        col("qty").alias("qty2"), col("amt").alias("amt2"))
    j2 = y1.join(y2, on=["i_brand_id", "i_class_id", "i_category_id"],
                 how="inner")
    j2 = j2.filter(col("qty2").cast(DataType.FLOAT64)
                   < lit(0.9) * col("qty1").cast(DataType.FLOAT64))
    return (j2.select("i_brand_id", "i_class_id", "i_category_id",
                      "qty1", "qty2", "amt1", "amt2")
            .sort(col("i_brand_id").asc(), col("i_class_id").asc())
            .limit(100).collect())


def _q75_oracle(a):
    import pandas as pd
    it = a["item"].to_pandas()
    it = it[it.i_category == "Home"][
        ["i_item_sk", "i_brand_id", "i_class_id", "i_category_id"]]
    cs = a["catalog_sales"].to_pandas()
    cr = a["catalog_returns"].to_pandas()[
        ["cr_item_sk", "cr_order_number", "cr_return_quantity",
         "cr_return_amount"]]
    j = cs.merge(cr, left_on=["cs_item_sk", "cs_order_number"],
                 right_on=["cr_item_sk", "cr_order_number"], how="left")
    dd = a["date_dim"].to_pandas()[["d_date_sk", "d_year"]]
    j = j.merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it, left_on="cs_item_sk", right_on="i_item_sk")
    j["net_qty"] = j.cs_quantity - j.cr_return_quantity.fillna(0)
    j["net_amt"] = (j.cs_ext_sales_price.astype(float)
                    - j.cr_return_amount.astype(float).fillna(0.0))
    g = j.groupby(["d_year", "i_brand_id", "i_class_id",
                   "i_category_id"]).agg(
        qty=("net_qty", "sum"), amt=("net_amt", "sum")).reset_index()
    y1 = g[g.d_year == 2000].drop(columns="d_year") \
        .rename(columns={"qty": "qty1", "amt": "amt1"})
    y2 = g[g.d_year == 2001].drop(columns="d_year") \
        .rename(columns={"qty": "qty2", "amt": "amt2"})
    j2 = y1.merge(y2, on=["i_brand_id", "i_class_id", "i_category_id"])
    j2 = j2[j2.qty2 < 0.9 * j2.qty1]
    out = j2[["i_brand_id", "i_class_id", "i_category_id", "qty1",
              "qty2", "amt1", "amt2"]] \
        .sort_values(["i_brand_id", "i_class_id"]).head(100)
    out[["qty1", "qty2"]] = out[["qty1", "qty2"]].astype("int64")
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q75", "catalog item-attribute sales net of returns, YoY decline")(
    (_q75_run, _q75_oracle))


# ===========================================================================
# q78: customer/item store-vs-web loyalty ratios, no returned store lines
# ===========================================================================

def _q78_run(s, t):
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
        "ss_ticket_number", "ss_quantity")
    sr = _rd(s, t, "store_returns").select(
        col("sr_item_sk").alias("ss_item_sk"),
        col("sr_ticket_number").alias("ss_ticket_number"))
    ss = ss.join(sr, on=["ss_item_sk", "ss_ticket_number"], how="anti")
    ss = ss.join(_rename(dd, d_date_sk="ss_sold_date_sk"),
                 on="ss_sold_date_sk", how="semi")
    ssg = (ss.filter(col("ss_customer_sk").is_not_null())
           .group_by("ss_customer_sk", "ss_item_sk")
           .agg(F.sum(col("ss_quantity")).alias("ss_qty"))
           .select(col("ss_customer_sk").alias("cust"),
                   col("ss_item_sk").alias("item"), col("ss_qty")))
    ws = _rd(s, t, "web_sales").select(
        "ws_sold_date_sk", "ws_item_sk", "ws_bill_customer_sk",
        "ws_order_number", "ws_quantity")
    wr = _rd(s, t, "web_returns").select(
        col("wr_item_sk").alias("ws_item_sk"),
        col("wr_order_number").alias("ws_order_number"))
    ws = ws.join(wr, on=["ws_item_sk", "ws_order_number"], how="anti")
    ws = ws.join(_rename(dd, d_date_sk="ws_sold_date_sk"),
                 on="ws_sold_date_sk", how="semi")
    wsg = (ws.filter(col("ws_bill_customer_sk").is_not_null())
           .group_by("ws_bill_customer_sk", "ws_item_sk")
           .agg(F.sum(col("ws_quantity")).alias("ws_qty"))
           .select(col("ws_bill_customer_sk").alias("cust"),
                   col("ws_item_sk").alias("item"), col("ws_qty")))
    j = ssg.join(wsg, on=["cust", "item"], how="inner")
    ratio = (col("ss_qty").cast(DataType.FLOAT64)
             / col("ws_qty").cast(DataType.FLOAT64))
    j = j.with_column("ratio", ratio)
    return (j.select("cust", "item", "ss_qty", "ws_qty", "ratio")
            .sort(col("ratio").desc(), col("cust").asc(),
                  col("item").asc())
            .limit(100).collect())


def _q78_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    days = set(dd[dd.d_year == 2000].d_date_sk)
    ss = a["store_sales"].to_pandas()
    sr = a["store_returns"].to_pandas()
    sr_keys = set(zip(sr.sr_item_sk, sr.sr_ticket_number))
    ss = ss[~pd.Series(list(zip(ss.ss_item_sk, ss.ss_ticket_number)),
                       index=ss.index).isin(sr_keys)]
    ss = ss[ss.ss_sold_date_sk.isin(days) & ss.ss_customer_sk.notna()]
    ssg = ss.groupby(["ss_customer_sk", "ss_item_sk"])["ss_quantity"] \
        .sum().reset_index(name="ss_qty") \
        .rename(columns={"ss_customer_sk": "cust", "ss_item_sk": "item"})
    ws = a["web_sales"].to_pandas()
    wr = a["web_returns"].to_pandas()
    wr_keys = set(zip(wr.wr_item_sk, wr.wr_order_number))
    ws = ws[~pd.Series(list(zip(ws.ws_item_sk, ws.ws_order_number)),
                       index=ws.index).isin(wr_keys)]
    ws = ws[ws.ws_sold_date_sk.isin(days)
            & ws.ws_bill_customer_sk.notna()]
    wsg = ws.groupby(["ws_bill_customer_sk", "ws_item_sk"])[
        "ws_quantity"].sum().reset_index(name="ws_qty") \
        .rename(columns={"ws_bill_customer_sk": "cust",
                         "ws_item_sk": "item"})
    j = ssg.merge(wsg, on=["cust", "item"])
    j["ratio"] = j.ss_qty / j.ws_qty
    j["cust"] = j.cust.astype("int64")
    out = j.sort_values(["ratio", "cust", "item"],
                        ascending=[False, True, True]).head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q78", "customer/item store-vs-web ratios on unreturned lines")(
    (_q78_run, _q78_oracle))


# ===========================================================================
# q23: monthly channel sales from best customers on frequently-sold items
# ===========================================================================

def _q23_run(s, t):
    from auron_tpu.frontend.dataframe import scalar_subquery
    dd_years = _rd(s, t, "date_dim").filter(
        col("d_year").isin(1999, 2000, 2001)) \
        .select("d_date_sk", "d_date")
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
        "ss_quantity", "ss_sales_price")
    # frequent items: sold on many lines of one (item, date) pair
    withdates = ss.join(_rename(dd_years, d_date_sk="ss_sold_date_sk"),
                        on="ss_sold_date_sk", how="inner")
    freq = (withdates.group_by("ss_item_sk", "d_date")
            .agg(F.count_star().alias("cnt"))
            .filter(col("cnt") > 4)
            .group_by("ss_item_sk").agg()
            .select(col("ss_item_sk")))
    # best customers: total quantity*price above 95% of the maximum
    spend = (ss.filter(col("ss_customer_sk").is_not_null())
             .group_by("ss_customer_sk")
             .agg(F.sum(col("ss_quantity").cast(DataType.FLOAT64)
                        * col("ss_sales_price").cast(DataType.FLOAT64))
                  .alias("ssales")))
    max_spend = spend.group_by().agg(F.max(col("ssales")).alias("m"))
    best = spend.filter(
        col("ssales") > lit(0.95) * scalar_subquery(max_spend)) \
        .select(col("ss_customer_sk"))
    # chosen month's catalog + web sales from best customers on
    # frequent items
    dd_m = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") == 3)) \
        .select("d_date_sk")

    def chan(fact, date_k, cust_k, item_k, qty_k, price_k):
        f = _rd(s, t, fact).select(date_k, cust_k, item_k, qty_k,
                                   price_k)
        j = f.join(_rename(dd_m, d_date_sk=date_k), on=date_k,
                   how="semi")
        j = j.join(_rename(freq, ss_item_sk=item_k), on=item_k,
                   how="semi")
        j = j.join(_rename(best, ss_customer_sk=cust_k), on=cust_k,
                   how="semi")
        amt = (col(qty_k).cast(DataType.FLOAT64)
               * col(price_k).cast(DataType.FLOAT64))
        return j.with_column("amt", amt).group_by() \
            .agg(F.sum(col("amt")).alias("t"))

    cs_t = chan("catalog_sales", "cs_sold_date_sk",
                "cs_bill_customer_sk", "cs_item_sk", "cs_quantity",
                "cs_sales_price")
    ws_t = chan("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
                "ws_item_sk", "ws_quantity", "ws_sales_price")
    out = cs_t.select(
        (F.coalesce(col("t"), lit(0.0))
         + F.coalesce(scalar_subquery(ws_t), lit(0.0))).alias("total"))
    return out.collect()


def _q23_oracle(a):
    import pandas as pd
    dd = a["date_dim"].to_pandas()
    ydays = dd[dd.d_year.isin([1999, 2000, 2001])][
        ["d_date_sk", "d_date"]]
    ss = a["store_sales"].to_pandas()
    w = ss.merge(ydays, left_on="ss_sold_date_sk", right_on="d_date_sk")
    cnt = w.groupby(["ss_item_sk", "d_date"]).size()
    freq = set(cnt[cnt > 4].reset_index().ss_item_sk)
    ssn = ss[ss.ss_customer_sk.notna()].copy()
    ssn["amt"] = ssn.ss_quantity * ssn.ss_sales_price.astype(float)
    spend = ssn.groupby("ss_customer_sk")["amt"].sum()
    best = set(spend[spend > 0.95 * spend.max()].index)
    mdays = set(dd[(dd.d_year == 2000) & (dd.d_moy == 3)].d_date_sk)

    def chan(name, date_k, cust_k, item_k, qty_k, price_k):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(mdays) & f[item_k].isin(freq)
              & f[cust_k].isin(best)]
        return float((f[qty_k] * f[price_k].astype(float)).sum())

    total = (chan("catalog_sales", "cs_sold_date_sk",
                  "cs_bill_customer_sk", "cs_item_sk", "cs_quantity",
                  "cs_sales_price")
             + chan("web_sales", "ws_sold_date_sk",
                    "ws_bill_customer_sk", "ws_item_sk", "ws_quantity",
                    "ws_sales_price"))
    return pa.Table.from_pydict({"total": [total]})


_q("q23", "monthly channel sales: best customers x frequent items")(
    (_q23_run, _q23_oracle))


# ===========================================================================
# q14: cross-channel items sold above the all-channel average (INTERSECT
#      of brand/class/category triples + scalar average threshold)
# ===========================================================================

def _q14_run(s, t):
    from auron_tpu.frontend.dataframe import scalar_subquery
    it = _rd(s, t, "item").select("i_item_sk", "i_brand_id",
                                  "i_class_id", "i_category_id")
    dd = _rd(s, t, "date_dim").filter(
        col("d_year").isin(1999, 2000, 2001)).select("d_date_sk")

    def chan_triples(fact, date_k, item_k):
        f = _rd(s, t, fact).select(date_k, item_k)
        j = f.join(_rename(dd, d_date_sk=date_k), on=date_k, how="semi")
        j = j.join(_rename(it, i_item_sk=item_k), on=item_k, how="inner")
        return (j.group_by("i_brand_id", "i_class_id", "i_category_id")
                .agg())

    sst = chan_triples("store_sales", "ss_sold_date_sk", "ss_item_sk")
    cst = chan_triples("catalog_sales", "cs_sold_date_sk", "cs_item_sk")
    wst = chan_triples("web_sales", "ws_sold_date_sk", "ws_item_sk")
    keys = ["i_brand_id", "i_class_id", "i_category_id"]
    cross = sst.join(cst, on=keys, how="semi").join(wst, on=keys,
                                                    how="semi")
    cross_items = it.join(cross, on=keys, how="semi") \
        .select("i_item_sk")

    # average (quantity * price) across ALL three channels; the web leg
    # uses ws_sales_price (the generator carries no ws_list_price) — the
    # oracle applies the same substitution
    def chan_amt(fact, date_k, qty_k, price_k):
        f = _rd(s, t, fact).select(date_k, qty_k, price_k)
        j = f.join(_rename(dd, d_date_sk=date_k), on=date_k, how="semi")
        return j.select((col(qty_k).cast(DataType.FLOAT64)
                         * col(price_k).cast(DataType.FLOAT64))
                        .alias("amt"))

    allamt = chan_amt("store_sales", "ss_sold_date_sk", "ss_quantity",
                      "ss_list_price") \
        .union(chan_amt("catalog_sales", "cs_sold_date_sk",
                        "cs_quantity", "cs_list_price")) \
        .union(chan_amt("web_sales", "ws_sold_date_sk", "ws_quantity",
                        "ws_sales_price"))
    avg_sales = allamt.group_by().agg(F.avg(col("amt")).alias("a"))

    # one month's store sales of cross items, grouped by item attrs,
    # HAVING sum > the all-channel average
    dd_m = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") == 11)) \
        .select("d_date_sk")
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_quantity", "ss_list_price")
    j = ss.join(_rename(dd_m, d_date_sk="ss_sold_date_sk"),
                on="ss_sold_date_sk", how="semi")
    j = j.join(_rename(cross_items, i_item_sk="ss_item_sk"),
               on="ss_item_sk", how="semi")
    j = j.join(_rename(it, i_item_sk="ss_item_sk"), on="ss_item_sk",
               how="inner")
    amt = (col("ss_quantity").cast(DataType.FLOAT64)
           * col("ss_list_price").cast(DataType.FLOAT64))
    g = (j.with_column("amt", amt)
         .group_by("i_brand_id", "i_class_id", "i_category_id")
         .agg(F.sum(col("amt")).alias("sales"),
              F.count_star().alias("n")))
    g = g.filter(col("sales") > scalar_subquery(avg_sales))
    return (g.select("i_brand_id", "i_class_id", "i_category_id",
                     "sales", "n")
            .sort(col("i_brand_id").asc(), col("i_class_id").asc(),
                  col("i_category_id").asc())
            .limit(100).collect())


def _q14_oracle(a):
    import pandas as pd
    it = a["item"].to_pandas()[
        ["i_item_sk", "i_brand_id", "i_class_id", "i_category_id"]]
    dd = a["date_dim"].to_pandas()
    ydays = set(dd[dd.d_year.isin([1999, 2000, 2001])].d_date_sk)

    def triples(name, date_k, item_k):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(ydays)]
        j = f.merge(it, left_on=item_k, right_on="i_item_sk")
        return set(map(tuple, j[["i_brand_id", "i_class_id",
                                 "i_category_id"]].drop_duplicates()
                       .itertuples(index=False)))

    cross = (triples("store_sales", "ss_sold_date_sk", "ss_item_sk")
             & triples("catalog_sales", "cs_sold_date_sk", "cs_item_sk")
             & triples("web_sales", "ws_sold_date_sk", "ws_item_sk"))
    it_t = it.copy()
    it_t["trip"] = list(map(tuple, it_t[["i_brand_id", "i_class_id",
                                         "i_category_id"]]
                            .itertuples(index=False)))
    cross_items = set(it_t[it_t.trip.isin(cross)].i_item_sk)

    def amounts(name, date_k, qty_k, price_k):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(ydays)]
        return f[qty_k] * f[price_k].astype(float)

    import numpy as _np
    allamt = _np.concatenate([
        amounts("store_sales", "ss_sold_date_sk", "ss_quantity",
                "ss_list_price").values,
        amounts("catalog_sales", "cs_sold_date_sk", "cs_quantity",
                "cs_list_price").values,
        amounts("web_sales", "ws_sold_date_sk", "ws_quantity",
                "ws_sales_price").values])
    avg_sales = float(allamt.mean())

    mdays = set(dd[(dd.d_year == 2000) & (dd.d_moy == 11)].d_date_sk)
    ss = a["store_sales"].to_pandas()
    j = ss[ss.ss_sold_date_sk.isin(mdays)
           & ss.ss_item_sk.isin(cross_items)]
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.copy()
    j["amt"] = j.ss_quantity * j.ss_list_price.astype(float)
    g = j.groupby(["i_brand_id", "i_class_id", "i_category_id"]).agg(
        sales=("amt", "sum"), n=("amt", "size")).reset_index()
    g = g[g.sales > avg_sales]
    g = g.sort_values(["i_brand_id", "i_class_id", "i_category_id"]) \
        .head(100)
    g["n"] = g.n.astype("int64")
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q14", "cross-channel items selling above the all-channel average")(
    (_q14_run, _q14_oracle))


# ===========================================================================
# q24: one color's returned-line store sales by customer, above 5% of
#      the per-market average (the market-basket chain)
# ===========================================================================

def _q24_run(s, t):
    from auron_tpu.frontend.dataframe import scalar_subquery
    ss = _rd(s, t, "store_sales").select(
        "ss_item_sk", "ss_ticket_number", "ss_customer_sk",
        "ss_store_sk", "ss_net_paid")
    sr = _rd(s, t, "store_returns").select(
        col("sr_item_sk").alias("ss_item_sk"),
        col("sr_ticket_number").alias("ss_ticket_number"))
    # only sold lines that were later returned (the q24 ss ⋈ sr core)
    ss = ss.join(sr, on=["ss_item_sk", "ss_ticket_number"], how="semi")
    st = _rd(s, t, "store").filter(col("s_market_id") <= 5) \
        .select("s_store_sk", "s_store_name", "s_state", "s_zip")
    c = _rd(s, t, "customer").select(
        col("c_customer_sk").alias("ss_customer_sk"),
        col("c_first_name"), col("c_last_name"),
        col("c_current_addr_sk"))
    ca = _rd(s, t, "customer_address").select(
        col("ca_address_sk").alias("c_current_addr_sk"), col("ca_zip"))
    it = _rd(s, t, "item").select("i_item_sk", "i_color")
    j = _join_dim(ss, st, "ss_store_sk", "s_store_sk")
    j = j.join(c, on="ss_customer_sk", how="inner")
    j = j.join(ca, on="c_current_addr_sk", how="inner")
    # q24's cross-state correlation: bought where the customer does NOT
    # live (zip mismatch keeps the out-of-area shape)
    j = j.filter(col("ca_zip") != col("s_zip"))
    j = j.join(_rename(it, i_item_sk="ss_item_sk"), on="ss_item_sk",
               how="inner")
    per = (j.group_by("c_last_name", "c_first_name", "s_store_name",
                      "i_color")
           .agg(F.sum(col("ss_net_paid")).alias("netpaid")))
    avg_all = per.group_by().agg(
        F.avg(col("netpaid").cast(DataType.FLOAT64)).alias("a"))
    sel = per.filter(col("i_color") == "plum")
    sel = sel.filter(col("netpaid").cast(DataType.FLOAT64)
                     > lit(0.05) * scalar_subquery(avg_all))
    return (sel.select("c_last_name", "c_first_name", "s_store_name",
                       "netpaid")
            .sort(col("c_last_name").asc(), col("c_first_name").asc(),
                  col("s_store_name").asc())
            .limit(100).collect())


def _q24_oracle(a):
    import pandas as pd
    ss = a["store_sales"].to_pandas()
    sr = a["store_returns"].to_pandas()
    keys = set(zip(sr.sr_item_sk, sr.sr_ticket_number))
    ss = ss[pd.Series(list(zip(ss.ss_item_sk, ss.ss_ticket_number)),
                      index=ss.index).isin(keys)
            & ss.ss_customer_sk.notna()]
    st = a["store"].to_pandas()
    st = st[st.s_market_id <= 5][
        ["s_store_sk", "s_store_name", "s_zip"]]
    c = a["customer"].to_pandas()[
        ["c_customer_sk", "c_first_name", "c_last_name",
         "c_current_addr_sk"]]
    ca = a["customer_address"].to_pandas()[["ca_address_sk", "ca_zip"]]
    it = a["item"].to_pandas()[["i_item_sk", "i_color"]]
    j = ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
    j = j.merge(ca, left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    j = j[j.ca_zip != j.s_zip]
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j["np"] = j.ss_net_paid.astype(float)
    per = j.groupby(["c_last_name", "c_first_name", "s_store_name",
                     "i_color"])["np"].sum().reset_index(name="netpaid")
    thresh = 0.05 * per.netpaid.mean()
    sel = per[(per.i_color == "plum") & (per.netpaid > thresh)]
    out = sel[["c_last_name", "c_first_name", "s_store_name",
               "netpaid"]].sort_values(
        ["c_last_name", "c_first_name", "s_store_name"]).head(100)
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q24", "returned plum-color sales by out-of-area customers > 5% avg")(
    (_q24_run, _q24_oracle))


# ===========================================================================
# q54: revenue-segment histogram of one month's cross-channel category
#      buyers over their following-quarter store spend
# ===========================================================================

def _q54_run(s, t):
    it = _rd(s, t, "item").filter(col("i_category") == "Sports") \
        .select("i_item_sk")
    dd_m = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") >= 2)
        & (col("d_moy") <= 4)).select("d_date_sk")

    def buyers(fact, date_k, cust_k, item_k):
        f = _rd(s, t, fact).select(date_k, cust_k, item_k)
        j = f.join(_rename(dd_m, d_date_sk=date_k), on=date_k,
                   how="semi")
        j = j.join(_rename(it, i_item_sk=item_k), on=item_k, how="semi")
        return (j.filter(col(cust_k).is_not_null())
                .group_by(cust_k).agg()
                .select(col(cust_k).alias("c_customer_sk")))

    my_customers = buyers("catalog_sales", "cs_sold_date_sk",
                          "cs_bill_customer_sk", "cs_item_sk") \
        .union(buyers("web_sales", "ws_sold_date_sk",
                      "ws_bill_customer_sk", "ws_item_sk")) \
        .group_by("c_customer_sk").agg() \
        .select(col("c_customer_sk"))
    # the following six months' store revenue of those customers (the
    # genuine template uses month+1..+3; the window is a tuned parameter
    # so CI-scale data keeps the histogram nonempty)
    dd_q = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") >= 5)
        & (col("d_moy") <= 10)).select("d_date_sk")
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_customer_sk", "ss_ext_sales_price")
    j = ss.join(_rename(dd_q, d_date_sk="ss_sold_date_sk"),
                on="ss_sold_date_sk", how="semi")
    j = j.join(_rename(my_customers, c_customer_sk="ss_customer_sk"),
               on="ss_customer_sk", how="semi")
    rev = (j.group_by("ss_customer_sk")
           .agg(F.sum(col("ss_ext_sales_price")).alias("revenue")))
    seg = (col("revenue").cast(DataType.FLOAT64) / lit(50.0)) \
        .cast(DataType.INT64)
    g = (rev.with_column("segment", seg)
         .group_by("segment").agg(F.count_star().alias("num_customers")))
    return (g.select("segment", "num_customers",
                     (col("segment") * lit(50, DataType.INT64))
                     .alias("segment_base"))
            .sort(col("segment").asc()).limit(100).collect())


def _q54_oracle(a):
    import pandas as pd
    it = a["item"].to_pandas()
    items = set(it[it.i_category == "Sports"].i_item_sk)
    dd = a["date_dim"].to_pandas()
    mdays = set(dd[(dd.d_year == 2000) & (dd.d_moy >= 2)
                   & (dd.d_moy <= 4)].d_date_sk)
    qdays = set(dd[(dd.d_year == 2000) & (dd.d_moy >= 5)
                   & (dd.d_moy <= 10)].d_date_sk)

    def buyers(name, date_k, cust_k, item_k):
        f = a[name].to_pandas()
        f = f[f[date_k].isin(mdays) & f[item_k].isin(items)
              & f[cust_k].notna()]
        return set(f[cust_k].astype(int))

    custs = (buyers("catalog_sales", "cs_sold_date_sk",
                    "cs_bill_customer_sk", "cs_item_sk")
             | buyers("web_sales", "ws_sold_date_sk",
                      "ws_bill_customer_sk", "ws_item_sk"))
    ss = a["store_sales"].to_pandas()
    ss = ss[ss.ss_sold_date_sk.isin(qdays)
            & ss.ss_customer_sk.isin(custs)].copy()
    ss["p"] = ss.ss_ext_sales_price.astype(float)
    rev = ss.groupby("ss_customer_sk")["p"].sum()
    seg = (rev / 50.0).astype(int)
    g = seg.value_counts().sort_index().reset_index()
    g.columns = ["segment", "num_customers"]
    g["segment_base"] = g.segment * 50
    g = g.sort_values("segment").head(100)
    g["segment"] = g.segment.astype("int64")
    g["num_customers"] = g.num_customers.astype("int64")
    g["segment_base"] = g.segment_base.astype("int64")
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q54", "revenue-segment histogram of cross-channel category buyers")(
    (_q54_run, _q54_oracle))


# ===========================================================================
# q64: returned-item store purchase chains, self-joined across two years
# ===========================================================================

def _q64_cross_sales(s, t, year):
    """One pass of the q64 CTE: per (item, store) sales stats for lines
    that were RETURNED (ss ⋈ sr), in one year, for a color slice."""
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_ticket_number",
        "ss_store_sk", "ss_wholesale_cost", "ss_list_price",
        "ss_coupon_amt")
    sr = _rd(s, t, "store_returns").select(
        col("sr_item_sk").alias("ss_item_sk"),
        col("sr_ticket_number").alias("ss_ticket_number"))
    ss = ss.join(sr, on=["ss_item_sk", "ss_ticket_number"], how="semi")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == year) \
        .select("d_date_sk")
    ss = ss.join(_rename(dd, d_date_sk="ss_sold_date_sk"),
                 on="ss_sold_date_sk", how="semi")
    it = _rd(s, t, "item").filter(
        col("i_color").isin("plum", "orchid", "slate")) \
        .select("i_item_sk", "i_item_id")
    ss = ss.join(_rename(it, i_item_sk="ss_item_sk"), on="ss_item_sk",
                 how="inner")
    st = _rd(s, t, "store").select("s_store_sk", "s_store_name")
    ss = _join_dim(ss, st, "ss_store_sk", "s_store_sk")
    return (ss.group_by("i_item_id", "s_store_name")
            .agg(F.count_star().alias("cnt"),
                 F.sum(col("ss_wholesale_cost")).alias("s1"),
                 F.sum(col("ss_list_price")).alias("s2"),
                 F.sum(col("ss_coupon_amt")).alias("s3")))


def _q64_run(s, t):
    cs1 = _q64_cross_sales(s, t, 1999).select(
        col("i_item_id"), col("s_store_name"), col("cnt").alias("cnt1"),
        col("s1").alias("s1_1"), col("s2").alias("s2_1"),
        col("s3").alias("s3_1"))
    cs2 = _q64_cross_sales(s, t, 2000).select(
        col("i_item_id"), col("s_store_name"), col("cnt").alias("cnt2"),
        col("s1").alias("s1_2"), col("s2").alias("s2_2"),
        col("s3").alias("s3_2"))
    j = cs1.join(cs2, on=["i_item_id", "s_store_name"], how="inner")
    j = j.filter(col("cnt2") >= col("cnt1"))
    return (j.select("i_item_id", "s_store_name", "cnt1", "s1_1", "s2_1",
                     "s3_1", "cnt2", "s1_2", "s2_2", "s3_2")
            .sort(col("i_item_id").asc(), col("s_store_name").asc())
            .limit(100).collect())


def _q64_oracle(a):
    import pandas as pd

    def cross_sales(year):
        ss = a["store_sales"].to_pandas()
        sr = a["store_returns"].to_pandas()
        keys = set(zip(sr.sr_item_sk, sr.sr_ticket_number))
        ss = ss[pd.Series(list(zip(ss.ss_item_sk, ss.ss_ticket_number)),
                          index=ss.index).isin(keys)]
        dd = a["date_dim"].to_pandas()
        days = set(dd[dd.d_year == year].d_date_sk)
        ss = ss[ss.ss_sold_date_sk.isin(days)]
        it = a["item"].to_pandas()
        it = it[it.i_color.isin(["plum", "orchid", "slate"])][
            ["i_item_sk", "i_item_id"]]
        j = ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        st = a["store"].to_pandas()[["s_store_sk", "s_store_name"]]
        j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        for c_, nm in (("ss_wholesale_cost", "s1"),
                       ("ss_list_price", "s2"), ("ss_coupon_amt", "s3")):
            j[nm] = j[c_]
        g = j.groupby(["i_item_id", "s_store_name"]).agg(
            cnt=("s1", "size"), s1=("s1", "sum"), s2=("s2", "sum"),
            s3=("s3", "sum")).reset_index()
        return g

    c1 = cross_sales(1999).rename(columns={
        "cnt": "cnt1", "s1": "s1_1", "s2": "s2_1", "s3": "s3_1"})
    c2 = cross_sales(2000).rename(columns={
        "cnt": "cnt2", "s1": "s1_2", "s2": "s2_2", "s3": "s3_2"})
    j = c1.merge(c2, on=["i_item_id", "s_store_name"])
    j = j[j.cnt2 >= j.cnt1]
    out = j[["i_item_id", "s_store_name", "cnt1", "s1_1", "s2_1",
             "s3_1", "cnt2", "s1_2", "s2_2", "s3_2"]]
    out = out.sort_values(["i_item_id", "s_store_name"]).head(100)
    for c_ in ("cnt1", "cnt2"):
        out[c_] = out[c_].astype("int64")
    return pa.Table.from_pandas(out.reset_index(drop=True),
                                preserve_index=False)


_q("q64", "returned-item purchase chains self-joined across two years")(
    (_q64_run, _q64_oracle))
