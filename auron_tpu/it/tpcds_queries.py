"""Real TPC-DS queries over the real-schema dataset (tpcds.py).

41 genuine TPC-DS query shapes — star joins, multi-dimension filters,
two-phase aggregation, CASE buckets, scalar subqueries, EXISTS/IN as
semi/anti joins, ROLLUP/grouping-sets with grouping_id arithmetic,
three-channel UNIONs, and window ratios — expressed in the frontend
DataFrame DSL (which lowers to protobuf plans and runs the full engine
pipeline) and diffed against an INDEPENDENT pyarrow/Acero (or pandas)
oracle (DuckDB is not in this image). Query parameters are substituted
to match the generated data's value domains, exactly as dsdgen's
templates substitute parameters — and auto-tuned so every query returns
rows at CI scale (an empty result proves nothing about a query).

Reference gate being mirrored: all-99-query TPC-DS diff vs vanilla Spark
(reference: .github/workflows/tpcds-reusable.yml:70-83,
dev/auron-it/.../QueryResultComparator.scala:21-100).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from auron_tpu.columnar.schema import DataType
from auron_tpu.frontend.dataframe import (col, functions as F, lit,
                                          scalar_subquery)

DATE_SK0 = 2450815


@dataclass(frozen=True)
class Query:
    name: str
    description: str
    run: Callable      # (session, tables) -> pa.Table
    oracle: Callable   # (arrow_tables: {name: pa.Table}) -> pa.Table


QUERIES: list[Query] = []


def _q(name, description):
    def deco(fns):
        run, oracle = fns
        QUERIES.append(Query(name, description, run, oracle))
        return fns
    return deco


def _rd(s, t, name, partitions=1):
    parts = 4 if name in ("store_sales", "catalog_sales", "web_sales",
                          "store_returns", "inventory") else partitions
    return s.read_parquet(t[name], partitions=parts)


def _rename(df, **kw):
    """Rename columns (old=new) via a full-width select."""
    cols = []
    for f in df.schema:
        nm = kw.get(f.name, f.name)
        cols.append(col(f.name).alias(nm))
    return df.select(*cols)


def _join_dim(fact, dim, fact_key, dim_key, how="inner"):
    """fact ⋈ dim on fact.fact_key == dim.dim_key (USING-style: the dim
    key column is renamed to the fact key name and dropped after)."""
    return fact.join(_rename(dim, **{dim_key: fact_key}), on=fact_key,
                     how=how)


# --- oracle helpers (pyarrow / Acero) --------------------------------------

def _oj(a, b, left, right=None, how="inner"):
    right = right or left
    return a.join(b, keys=left, right_keys=right, join_type=how)


def _agg(t, keys, aggs, names=None):
    """group_by + aggregate with explicit output names."""
    res = t.group_by(keys, use_threads=False).aggregate(aggs)
    if names:
        res = res.rename_columns(list(res.column_names[:len(keys)])
                                 if False else
                                 [*names.get("keys", keys), *names["aggs"]]
                                 if isinstance(names, dict) else names)
    return res


def _topn(t, sort_keys, n=100):
    idx = pc.sort_indices(t, sort_keys=sort_keys)
    return t.take(idx.slice(0, n))


# ===========================================================================
# q3: ss ⋈ date_dim ⋈ item, manufacturer filter, yearly brand revenue
# ===========================================================================

def _q3_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_ext_sales_price")
    dd = _rd(s, t, "date_dim").filter(col("d_moy") == 11) \
        .select("d_date_sk", "d_year")
    it = _rd(s, t, "item").filter(col("i_manufact_id") == 128) \
        .select("i_item_sk", "i_brand_id", "i_brand")
    j = _join_dim(_join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk"),
                  it, "ss_item_sk", "i_item_sk")
    return (j.group_by("d_year", "i_brand_id", "i_brand")
            .agg(F.sum(col("ss_ext_sales_price")).alias("sum_agg"))
            .sort(col("d_year").asc(), col("sum_agg").desc(),
                  col("i_brand_id").asc())
            .limit(100).collect())


def _q3_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_moy"], 11)) \
        .select(["d_date_sk", "d_year"])
    it = a["item"].filter(pc.equal(a["item"]["i_manufact_id"], 128)) \
        .select(["i_item_sk", "i_brand_id", "i_brand"])
    j = _oj(_oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"]),
            it, ["ss_item_sk"], ["i_item_sk"])
    g = j.group_by(["d_year", "i_brand_id", "i_brand"]).aggregate(
        [("ss_ext_sales_price", "sum")]) \
        .rename_columns(["d_year", "i_brand_id", "i_brand", "sum_agg"])
    return _topn(g, [("d_year", "ascending"), ("sum_agg", "descending"),
                     ("i_brand_id", "ascending")])


_q("q3", "yearly brand revenue for one manufacturer in November")(
    (_q3_run, _q3_oracle))


# ===========================================================================
# q42: dd ⋈ ss ⋈ item, category revenue for one month
# ===========================================================================

def _cat_month_revenue(attr_id, attr, flt_col, flt_val):
    def run(s, t):
        ss = _rd(s, t, "store_sales").select("ss_sold_date_sk",
                                             "ss_item_sk",
                                             "ss_ext_sales_price")
        dd = _rd(s, t, "date_dim") \
            .filter((col("d_moy") == 11) & (col("d_year") == 2000)) \
            .select("d_date_sk", "d_year")
        it = _rd(s, t, "item").filter(col(flt_col) == flt_val) \
            .select("i_item_sk", attr_id, attr)
        j = _join_dim(_join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk"),
                      it, "ss_item_sk", "i_item_sk")
        return (j.group_by("d_year", attr_id, attr)
                .agg(F.sum(col("ss_ext_sales_price")).alias("sum_agg"))
                .sort(col("sum_agg").desc(), col(attr_id).asc())
                .limit(100).collect())

    def oracle(a):
        dd = a["date_dim"].filter(
            pc.and_(pc.equal(a["date_dim"]["d_moy"], 11),
                    pc.equal(a["date_dim"]["d_year"], 2000))) \
            .select(["d_date_sk", "d_year"])
        it = a["item"].filter(pc.equal(a["item"][flt_col], flt_val)) \
            .select(["i_item_sk", attr_id, attr])
        j = _oj(_oj(a["store_sales"], dd, ["ss_sold_date_sk"],
                    ["d_date_sk"]), it, ["ss_item_sk"], ["i_item_sk"])
        g = j.group_by(["d_year", attr_id, attr]).aggregate(
            [("ss_ext_sales_price", "sum")]) \
            .rename_columns(["d_year", attr_id, attr, "sum_agg"])
        return _topn(g, [("sum_agg", "descending"),
                         (attr_id, "ascending")])
    return run, oracle


_q("q42", "category revenue, one month, manager slice")(
    _cat_month_revenue("i_category_id", "i_category", "i_manager_id", 1))
_q("q52", "brand revenue, one month, manager slice")(
    _cat_month_revenue("i_brand_id", "i_brand", "i_manager_id", 1))
_q("q55", "brand revenue for one manager's items")(
    _cat_month_revenue("i_brand_id", "i_brand", "i_manager_id", 28))


# ===========================================================================
# q7: ss ⋈ cd ⋈ dd ⋈ item ⋈ promotion — demographic averages per item
# ===========================================================================

def _q7_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk", "ss_promo_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price")
    cd = _rd(s, t, "customer_demographics").filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College")) \
        .select("cd_demo_sk")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    pr = _rd(s, t, "promotion").filter(col("p_channel_email") == "N") \
        .select("p_promo_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id")
    j = _join_dim(ss, cd, "ss_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, pr, "ss_promo_sk", "p_promo_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    return (j.group_by("i_item_id")
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"))
            .sort(col("i_item_id").asc()).limit(100).collect())


def _q7_oracle(a):
    cd = a["customer_demographics"]
    cd = cd.filter(pc.and_(pc.and_(
        pc.equal(cd["cd_gender"], "M"),
        pc.equal(cd["cd_marital_status"], "S")),
        pc.equal(cd["cd_education_status"], "College"))) \
        .select(["cd_demo_sk"])
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk"])
    pr = a["promotion"].filter(
        pc.equal(a["promotion"]["p_channel_email"], "N")) \
        .select(["p_promo_sk"])
    it = a["item"].select(["i_item_sk", "i_item_id"])
    j = _oj(a["store_sales"], cd, ["ss_cdemo_sk"], ["cd_demo_sk"])
    j = _oj(j, dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, pr, ["ss_promo_sk"], ["p_promo_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    for c in ("ss_list_price", "ss_coupon_amt", "ss_sales_price"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = j.group_by(["i_item_id"]).aggregate(
        [("ss_quantity", "mean"), ("ss_list_price", "mean"),
         ("ss_coupon_amt", "mean"), ("ss_sales_price", "mean")]) \
        .rename_columns(["i_item_id", "agg1", "agg2", "agg3", "agg4"])
    return _topn(g, [("i_item_id", "ascending")])


_q("q7", "demographic purchase averages per item")((_q7_run, _q7_oracle))


# ===========================================================================
# q19: brand revenue where customer and store are in different zip areas
# ===========================================================================

def _q19_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_ext_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_moy") == 11) & (col("d_year") == 1999)) \
        .select("d_date_sk")
    it = _rd(s, t, "item").filter(col("i_manager_id") == 8) \
        .select("i_item_sk", "i_brand_id", "i_brand", "i_manufact_id",
                "i_manufact")
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_current_addr_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_zip")
    st = _rd(s, t, "store").select("s_store_sk", "s_zip")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    j = _join_dim(j, cu, "ss_customer_sk", "c_customer_sk")
    j = _join_dim(j, ca, "c_current_addr_sk", "ca_address_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = j.filter(F.substring(col("ca_zip"), lit(1), lit(5))
                 != F.substring(col("s_zip"), lit(1), lit(5)))
    return (j.group_by("i_brand_id", "i_brand", "i_manufact_id",
                       "i_manufact")
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .sort(col("ext_price").desc(), col("i_brand_id").asc())
            .limit(100).collect())


def _q19_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_moy"], 11),
        pc.equal(a["date_dim"]["d_year"], 1999))).select(["d_date_sk"])
    it = a["item"].filter(pc.equal(a["item"]["i_manager_id"], 8)) \
        .select(["i_item_sk", "i_brand_id", "i_brand", "i_manufact_id",
                 "i_manufact"])
    cu = a["customer"].select(["c_customer_sk", "c_current_addr_sk"])
    ca = a["customer_address"].select(["ca_address_sk", "ca_zip"])
    st = a["store"].select(["s_store_sk", "s_zip"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    j = _oj(j, cu, ["ss_customer_sk"], ["c_customer_sk"])
    j = _oj(j, ca, ["c_current_addr_sk"], ["ca_address_sk"])
    j = _oj(j, st, ["ss_store_sk"], ["s_store_sk"])
    j = j.filter(pc.not_equal(pc.utf8_slice_codeunits(j["ca_zip"], 0, 5),
                              pc.utf8_slice_codeunits(j["s_zip"], 0, 5)))
    g = j.group_by(["i_brand_id", "i_brand", "i_manufact_id",
                    "i_manufact"]).aggregate(
        [("ss_ext_sales_price", "sum")]) \
        .rename_columns(["i_brand_id", "i_brand", "i_manufact_id",
                         "i_manufact", "ext_price"])
    return _topn(g, [("ext_price", "descending"),
                     ("i_brand_id", "ascending")])


_q("q19", "brand revenue, customer zip != store zip")(
    (_q19_run, _q19_oracle))


# ===========================================================================
# q6: states where customers bought items priced 20%+ above the category
#     average (subquery-as-join)
# ===========================================================================

def _q6_run(s, t):
    it = _rd(s, t, "item").select("i_item_sk", "i_category",
                                  "i_current_price")
    cat_avg = (it.group_by("i_category")
               .agg(F.avg(col("i_current_price")).alias("cat_avg")))
    it2 = _join_dim(
        it.select(col("i_item_sk"), col("i_category").alias("cat2"),
                  col("i_current_price")),
        cat_avg, "cat2", "i_category")
    it2 = it2.filter(col("i_current_price").cast(DataType.FLOAT64)
                     > col("cat_avg") * lit(1.2))
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_customer_sk")
    # true q6 shape: d_month_seq = (select distinct d_month_seq from
    # date_dim where d_year = 2001 and d_moy = 1) — an uncorrelated
    # SCALAR SUBQUERY executed once per task, no join rewrite
    mseq = scalar_subquery(
        _rd(s, t, "date_dim")
        .filter((col("d_year") == 2001) & (col("d_moy") == 1))
        .group_by("d_month_seq").agg(F.count_star().alias("_c"))
        .select("d_month_seq"))
    dd = _rd(s, t, "date_dim").filter(col("d_month_seq") == mseq) \
        .select("d_date_sk")
    cu = _rd(s, t, "customer").select("c_customer_sk",
                                      "c_current_addr_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_state")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it2.select("i_item_sk"), "ss_item_sk", "i_item_sk")
    j = _join_dim(j, cu, "ss_customer_sk", "c_customer_sk")
    j = _join_dim(j, ca, "c_current_addr_sk", "ca_address_sk")
    g = (j.group_by("ca_state").agg(F.count_star().alias("cnt"))
         .filter(col("cnt") >= 10)
         .sort(col("cnt").asc(), col("ca_state").asc()).limit(100))
    return g.collect()


def _q6_oracle(a):
    it = a["item"].select(["i_item_sk", "i_category", "i_current_price"])
    itf = it.set_column(2, "i_current_price",
                        it["i_current_price"].cast(pa.float64()))
    cat_avg = itf.group_by(["i_category"]).aggregate(
        [("i_current_price", "mean")]) \
        .rename_columns(["i_category", "cat_avg"])
    it2 = _oj(itf, cat_avg, ["i_category"])
    it2 = it2.filter(pc.greater(it2["i_current_price"],
                                pc.multiply(it2["cat_avg"], 1.2))) \
        .select(["i_item_sk"])
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2001),
        pc.equal(a["date_dim"]["d_moy"], 1))).select(["d_date_sk"])
    cu = a["customer"].select(["c_customer_sk", "c_current_addr_sk"])
    ca = a["customer_address"].select(["ca_address_sk", "ca_state"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it2, ["ss_item_sk"], ["i_item_sk"])
    j = _oj(j, cu, ["ss_customer_sk"], ["c_customer_sk"])
    j = _oj(j, ca, ["c_current_addr_sk"], ["ca_address_sk"])
    g = j.group_by(["ca_state"]).aggregate([([], "count_all")]) \
        .rename_columns(["ca_state", "cnt"])
    g = g.filter(pc.greater_equal(g["cnt"], 10))
    g = g.set_column(1, "cnt", g["cnt"].cast(pa.int64()))
    return _topn(g, [("cnt", "ascending"), ("ca_state", "ascending")])


_q("q6", "states buying premium-priced items (scalar subquery + "
         "correlated-subquery-as-join)")(
    (_q6_run, _q6_oracle))


# ===========================================================================
# q12 / q20 / q98: revenue ratio within class (window over agg)
# ===========================================================================

def _channel_ratio(fact, date_col, item_col, price_col, qname):
    def run(s, t):
        fs = _rd(s, t, fact).select(date_col, item_col, price_col)
        dd = _rd(s, t, "date_dim").filter(
            (col("d_date_sk") >= DATE_SK0 + 730)
            & (col("d_date_sk") <= DATE_SK0 + 760)) \
            .select("d_date_sk")
        it = _rd(s, t, "item").filter(
            col("i_category").isin("Sports", "Books", "Home")) \
            .select("i_item_sk", "i_item_id", "i_item_desc", "i_category",
                    "i_class", "i_current_price")
        j = _join_dim(fs, dd, date_col, "d_date_sk")
        j = _join_dim(j, it, item_col, "i_item_sk")
        g = (j.group_by("i_item_id", "i_item_desc", "i_category",
                        "i_class", "i_current_price")
             .agg(F.sum(col(price_col)).alias("itemrevenue")))
        g = g.window([F.win_agg("sum", col("itemrevenue"))
                      .alias("classrev")],
                     partition_by=[col("i_class")])
        g = g.with_column(
            "revenueratio",
            col("itemrevenue").cast(DataType.FLOAT64) * lit(100.0)
            / col("classrev").cast(DataType.FLOAT64))
        return (g.select("i_item_id", "i_item_desc", "i_category",
                         "i_class", "i_current_price", "itemrevenue",
                         "revenueratio")
                .sort(col("i_category").asc(), col("i_class").asc(),
                      col("i_item_id").asc(), col("i_item_desc").asc(),
                      col("revenueratio").asc())
                .limit(100).collect())

    def oracle(a):
        dd = a["date_dim"].filter(pc.and_(
            pc.greater_equal(a["date_dim"]["d_date_sk"], DATE_SK0 + 730),
            pc.less_equal(a["date_dim"]["d_date_sk"], DATE_SK0 + 760))) \
            .select(["d_date_sk"])
        it = a["item"].filter(pc.is_in(
            a["item"]["i_category"],
            value_set=pa.array(["Sports", "Books", "Home"]))) \
            .select(["i_item_sk", "i_item_id", "i_item_desc", "i_category",
                     "i_class", "i_current_price"])
        j = _oj(a[fact], dd, [date_col], ["d_date_sk"])
        j = _oj(j, it, [item_col], ["i_item_sk"])
        g = j.group_by(["i_item_id", "i_item_desc", "i_category",
                        "i_class", "i_current_price"]).aggregate(
            [(price_col, "sum")]) \
            .rename_columns(["i_item_id", "i_item_desc", "i_category",
                             "i_class", "i_current_price", "itemrevenue"])
        cls = g.group_by(["i_class"]).aggregate(
            [("itemrevenue", "sum")]) \
            .rename_columns(["i_class", "classrev"])
        g = _oj(g, cls, ["i_class"])
        ratio = pc.divide(
            pc.multiply(g["itemrevenue"].cast(pa.float64()), 100.0),
            g["classrev"].cast(pa.float64()))
        g = g.append_column("revenueratio", ratio)
        g = g.select(["i_item_id", "i_item_desc", "i_category", "i_class",
                      "i_current_price", "itemrevenue", "revenueratio"])
        return _topn(g, [("i_category", "ascending"),
                         ("i_class", "ascending"),
                         ("i_item_id", "ascending"),
                         ("i_item_desc", "ascending"),
                         ("revenueratio", "ascending")])
    return run, oracle


_q("q12", "web revenue ratio within class")(_channel_ratio(
    "web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price",
    "q12"))
_q("q20", "catalog revenue ratio within class")(_channel_ratio(
    "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
    "cs_ext_sales_price", "q20"))
_q("q98", "store revenue ratio within class")(_channel_ratio(
    "store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price",
    "q98"))


# ===========================================================================
# q26: catalog demographic averages (q7's catalog twin)
# ===========================================================================

def _q26_run(s, t):
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk", "cs_promo_sk",
        "cs_quantity", "cs_list_price", "cs_coupon_amt", "cs_sales_price")
    cd = _rd(s, t, "customer_demographics").filter(
        (col("cd_gender") == "F") & (col("cd_marital_status") == "M")
        & (col("cd_education_status") == "College")).select("cd_demo_sk")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    pr = _rd(s, t, "promotion").filter(col("p_channel_tv") == "N") \
        .select("p_promo_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_id")
    j = _join_dim(cs, cd, "cs_bill_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, dd, "cs_sold_date_sk", "d_date_sk")
    j = _join_dim(j, pr, "cs_promo_sk", "p_promo_sk")
    j = _join_dim(j, it, "cs_item_sk", "i_item_sk")
    return (j.group_by("i_item_id")
            .agg(F.avg(col("cs_quantity")).alias("agg1"),
                 F.avg(col("cs_list_price")).alias("agg2"),
                 F.avg(col("cs_coupon_amt")).alias("agg3"),
                 F.avg(col("cs_sales_price")).alias("agg4"))
            .sort(col("i_item_id").asc()).limit(100).collect())


def _q26_oracle(a):
    cd = a["customer_demographics"]
    cd = cd.filter(pc.and_(pc.and_(
        pc.equal(cd["cd_gender"], "F"),
        pc.equal(cd["cd_marital_status"], "M")),
        pc.equal(cd["cd_education_status"], "College"))) \
        .select(["cd_demo_sk"])
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk"])
    pr = a["promotion"].filter(
        pc.equal(a["promotion"]["p_channel_tv"], "N")) \
        .select(["p_promo_sk"])
    it = a["item"].select(["i_item_sk", "i_item_id"])
    j = _oj(a["catalog_sales"], cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"])
    j = _oj(j, dd, ["cs_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, pr, ["cs_promo_sk"], ["p_promo_sk"])
    j = _oj(j, it, ["cs_item_sk"], ["i_item_sk"])
    for c in ("cs_list_price", "cs_coupon_amt", "cs_sales_price"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = j.group_by(["i_item_id"]).aggregate(
        [("cs_quantity", "mean"), ("cs_list_price", "mean"),
         ("cs_coupon_amt", "mean"), ("cs_sales_price", "mean")]) \
        .rename_columns(["i_item_id", "agg1", "agg2", "agg3", "agg4"])
    return _topn(g, [("i_item_id", "ascending")])


_q("q26", "catalog demographic purchase averages")(
    (_q26_run, _q26_oracle))


# ===========================================================================
# q43: per-store day-of-week sales pivot (CASE buckets)
# ===========================================================================

_DAYS = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
         "Friday", "Saturday"]


def _q43_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_store_sk",
                                         "ss_sales_price")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk", "d_day_name")
    st = _rd(s, t, "store").select("s_store_sk", "s_store_id",
                                   "s_store_name")
    j = _join_dim(_join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk"),
                  st, "ss_store_sk", "s_store_sk")
    price_f = col("ss_sales_price").cast(DataType.FLOAT64)
    aggs = [F.sum(F.if_(col("d_day_name") == day, price_f, lit(0.0)))
            .alias(f"{day[:3].lower()}_sales") for day in _DAYS]
    return (j.group_by("s_store_name", "s_store_id").agg(*aggs)
            .sort(col("s_store_name").asc(), col("s_store_id").asc())
            .limit(100).collect())


def _q43_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk", "d_day_name"])
    st = a["store"].select(["s_store_sk", "s_store_id", "s_store_name"])
    j = _oj(_oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"]),
            st, ["ss_store_sk"], ["s_store_sk"])
    price = j["ss_sales_price"].cast(pa.float64())
    cols, names = [], []
    for day in _DAYS:
        cols.append(pc.if_else(pc.equal(j["d_day_name"], day), price, 0.0))
        names.append(f"{day[:3].lower()}_sales")
    base = pa.table({"s_store_name": j["s_store_name"],
                     "s_store_id": j["s_store_id"],
                     **{n: c for n, c in zip(names, cols)}})
    g = base.group_by(["s_store_name", "s_store_id"]).aggregate(
        [(n, "sum") for n in names]) \
        .rename_columns(["s_store_name", "s_store_id"] + names)
    return _topn(g, [("s_store_name", "ascending"),
                     ("s_store_id", "ascending")])


_q("q43", "per-store day-of-week sales pivot")((_q43_run, _q43_oracle))


# ===========================================================================
# q48: banded quantity sum with OR'd demographic/address predicates
# ===========================================================================

def _q48_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_cdemo_sk", "ss_addr_sk",
        "ss_quantity", "ss_sales_price", "ss_net_profit")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    price = col("ss_sales_price").cast(DataType.FLOAT64)
    cd = _rd(s, t, "customer_demographics").filter(
        (col("cd_marital_status") == "M")
        & (col("cd_education_status") == "4 yr Degree")) \
        .select("cd_demo_sk")
    ca = _rd(s, t, "customer_address").filter(
        (col("ca_country") == "United States")
        & col("ca_state").isin("CA", "TX", "NY", "OH", "GA", "WA")) \
        .select("ca_address_sk")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, cd, "ss_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, ca, "ss_addr_sk", "ca_address_sk")
    j = j.filter(((price >= lit(50.0)) & (price <= lit(100.0)))
                 | ((price >= lit(150.0)) & (price <= lit(200.0))))
    return (j.select(col("ss_quantity"))
            .group_by(lit(1).alias("g"))
            .agg(F.sum(col("ss_quantity")).alias("total_q"))
            .select("total_q").collect())


def _q48_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk"])
    cd = a["customer_demographics"]
    cd = cd.filter(pc.and_(
        pc.equal(cd["cd_marital_status"], "M"),
        pc.equal(cd["cd_education_status"], "4 yr Degree"))) \
        .select(["cd_demo_sk"])
    ca = a["customer_address"]
    ca = ca.filter(pc.and_(
        pc.equal(ca["ca_country"], "United States"),
        pc.is_in(ca["ca_state"], value_set=pa.array(
            ["CA", "TX", "NY", "OH", "GA", "WA"])))) \
        .select(["ca_address_sk"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    j = _oj(j, cd, ["ss_cdemo_sk"], ["cd_demo_sk"])
    j = _oj(j, ca, ["ss_addr_sk"], ["ca_address_sk"])
    price = j["ss_sales_price"].cast(pa.float64())
    band = pc.or_(
        pc.and_(pc.greater_equal(price, 50.0), pc.less_equal(price, 100.0)),
        pc.and_(pc.greater_equal(price, 150.0),
                pc.less_equal(price, 200.0)))
    j = j.filter(band)
    total = pc.sum(j["ss_quantity"]).as_py() or 0
    return pa.table({"total_q": pa.array([total], pa.int64())})


_q("q48", "banded quantity sum with OR'd predicate blocks")(
    (_q48_run, _q48_oracle))


# ===========================================================================
# q62 / q99: shipping-lag day buckets (catalog/web)
# ===========================================================================

def _ship_lag(fact, sold_col, ship_col, mode_col, wh_col, qname):
    def run(s, t):
        fs = _rd(s, t, fact).select(sold_col, ship_col, mode_col, wh_col)
        sm = _rd(s, t, "ship_mode").select("sm_ship_mode_sk", "sm_type")
        wh = _rd(s, t, "warehouse").select("w_warehouse_sk",
                                           "w_warehouse_name")
        dd = _rd(s, t, "date_dim").filter(
            (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
            .select("d_date_sk")
        j = _join_dim(fs, dd, ship_col, "d_date_sk")
        j = _join_dim(j, sm, mode_col, "sm_ship_mode_sk")
        j = _join_dim(j, wh, wh_col, "w_warehouse_sk")
        lag = col(ship_col) - col(sold_col)
        buckets = [
            ("d30", lag <= lit(30)),
            ("d60", (lag > lit(30)) & (lag <= lit(60))),
            ("d90", (lag > lit(60)) & (lag <= lit(90))),
            ("d120", (lag > lit(90)) & (lag <= lit(120))),
            ("dmore", lag > lit(120)),
        ]
        aggs = [F.sum(F.if_(cond, lit(1), lit(0))).alias(nm)
                for nm, cond in buckets]
        return (j.group_by("w_warehouse_name", "sm_type").agg(*aggs)
                .sort(col("w_warehouse_name").asc(), col("sm_type").asc())
                .limit(100).collect())

    def oracle(a):
        dd = a["date_dim"].filter(pc.and_(
            pc.greater_equal(a["date_dim"]["d_month_seq"], 24),
            pc.less_equal(a["date_dim"]["d_month_seq"], 35))) \
            .select(["d_date_sk"])
        j = _oj(a[fact], dd, [ship_col], ["d_date_sk"])
        j = _oj(j, a["ship_mode"].select(["sm_ship_mode_sk", "sm_type"]),
                [mode_col], ["sm_ship_mode_sk"])
        j = _oj(j, a["warehouse"].select(["w_warehouse_sk",
                                          "w_warehouse_name"]),
                [wh_col], ["w_warehouse_sk"])
        lag = pc.subtract(j[ship_col], j[sold_col])
        conds = [
            ("d30", pc.less_equal(lag, 30)),
            ("d60", pc.and_(pc.greater(lag, 30), pc.less_equal(lag, 60))),
            ("d90", pc.and_(pc.greater(lag, 60), pc.less_equal(lag, 90))),
            ("d120", pc.and_(pc.greater(lag, 90),
                             pc.less_equal(lag, 120))),
            ("dmore", pc.greater(lag, 120)),
        ]
        cols = {"w_warehouse_name": j["w_warehouse_name"],
                "sm_type": j["sm_type"]}
        for nm, c in conds:
            cols[nm] = pc.if_else(c, pa.scalar(1, pa.int64()),
                                  pa.scalar(0, pa.int64()))
        base = pa.table(cols)
        g = base.group_by(["w_warehouse_name", "sm_type"]).aggregate(
            [(nm, "sum") for nm, _ in conds]) \
            .rename_columns(["w_warehouse_name", "sm_type"]
                            + [nm for nm, _ in conds])
        return _topn(g, [("w_warehouse_name", "ascending"),
                         ("sm_type", "ascending")])
    return run, oracle


_q("q62", "web shipping-lag day buckets")(_ship_lag(
    "web_sales", "ws_sold_date_sk", "ws_ship_date_sk", "ws_ship_mode_sk",
    "ws_warehouse_sk", "q62"))
_q("q99", "catalog shipping-lag day buckets")(_ship_lag(
    "catalog_sales", "cs_sold_date_sk", "cs_ship_date_sk",
    "cs_ship_mode_sk", "cs_warehouse_sk", "q99"))


# ===========================================================================
# q73 / q79: per-ticket baskets joined back to customers
# ===========================================================================

def _q73_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_customer_sk",
        "ss_ticket_number")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_dom") >= 1) & (col("d_dom") <= 2)
        & col("d_year").isin(1999, 2000, 2001)) \
        .select("d_date_sk")
    hd = _rd(s, t, "household_demographics").filter(
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > 0)).select("hd_demo_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    g = (j.group_by("ss_ticket_number", "ss_customer_sk")
         .agg(F.count_star().alias("cnt"))
         .filter((col("cnt") >= 2) & (col("cnt") <= 5)))
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_last_name",
                                      "c_first_name")
    g = _join_dim(g, cu, "ss_customer_sk", "c_customer_sk")
    return (g.sort(col("cnt").desc(), col("c_last_name").asc(),
                   col("ss_ticket_number").asc())
            .limit(100).collect())


def _q73_oracle(a):
    dd = a["date_dim"]
    dd = dd.filter(pc.and_(pc.and_(
        pc.greater_equal(dd["d_dom"], 1), pc.less_equal(dd["d_dom"], 2)),
        pc.is_in(dd["d_year"], value_set=pa.array([1999, 2000, 2001])))) \
        .select(["d_date_sk"])
    hd = a["household_demographics"]
    hd = hd.filter(pc.and_(
        pc.is_in(hd["hd_buy_potential"],
                 value_set=pa.array([">10000", "Unknown"])),
        pc.greater(hd["hd_vehicle_count"], 0))).select(["hd_demo_sk"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    g = j.group_by(["ss_ticket_number", "ss_customer_sk"]).aggregate(
        [([], "count_all")]) \
        .rename_columns(["ss_ticket_number", "ss_customer_sk", "cnt"])
    g = g.filter(pc.and_(pc.greater_equal(g["cnt"], 2),
                         pc.less_equal(g["cnt"], 5)))
    g = g.set_column(2, "cnt", g["cnt"].cast(pa.int64()))
    cu = a["customer"].select(["c_customer_sk", "c_last_name",
                               "c_first_name"])
    g = _oj(g, cu, ["ss_customer_sk"], ["c_customer_sk"])
    return _topn(g, [("cnt", "descending"), ("c_last_name", "ascending"),
                     ("ss_ticket_number", "ascending")])


_q("q73", "frequent small baskets on month-start days")(
    (_q73_run, _q73_oracle))


def _q79_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_customer_sk",
        "ss_addr_sk", "ss_ticket_number", "ss_coupon_amt", "ss_net_profit")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_dom") >= 1) & (col("d_dom") <= 2)
        & col("d_year").isin(1999, 2000, 2001)).select("d_date_sk")
    hd = _rd(s, t, "household_demographics").filter(
        (col("hd_dep_count") == 6) | (col("hd_vehicle_count") > 2)) \
        .select("hd_demo_sk")
    st = _rd(s, t, "store").filter(col("s_number_employees") >= 200) \
        .select("s_store_sk", "s_city")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    g = (j.group_by("ss_ticket_number", "ss_customer_sk", "s_city")
         .agg(F.sum(col("ss_coupon_amt").cast(DataType.FLOAT64))
              .alias("amt"),
              F.sum(col("ss_net_profit").cast(DataType.FLOAT64))
              .alias("profit")))
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_last_name",
                                      "c_first_name")
    g = _join_dim(g, cu, "ss_customer_sk", "c_customer_sk")
    return (g.select("c_last_name", "c_first_name", "s_city", "profit",
                     "ss_ticket_number", "amt")
            .sort(col("c_last_name").asc(), col("c_first_name").asc(),
                  col("s_city").asc(), col("profit").desc(),
                  col("ss_ticket_number").asc())
            .limit(100).collect())


def _q79_oracle(a):
    dd = a["date_dim"]
    dd = dd.filter(pc.and_(pc.and_(
        pc.greater_equal(dd["d_dom"], 1), pc.less_equal(dd["d_dom"], 2)),
        pc.is_in(dd["d_year"], value_set=pa.array([1999, 2000, 2001])))) \
        .select(["d_date_sk"])
    hd = a["household_demographics"]
    hd = hd.filter(pc.or_(pc.equal(hd["hd_dep_count"], 6),
                          pc.greater(hd["hd_vehicle_count"], 2))) \
        .select(["hd_demo_sk"])
    st = a["store"].filter(
        pc.greater_equal(a["store"]["s_number_employees"], 200)) \
        .select(["s_store_sk", "s_city"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, st, ["ss_store_sk"], ["s_store_sk"])
    for c in ("ss_coupon_amt", "ss_net_profit"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = j.group_by(["ss_ticket_number", "ss_customer_sk", "s_city"]) \
        .aggregate([("ss_coupon_amt", "sum"), ("ss_net_profit", "sum")]) \
        .rename_columns(["ss_ticket_number", "ss_customer_sk", "s_city",
                         "amt", "profit"])
    cu = a["customer"].select(["c_customer_sk", "c_last_name",
                               "c_first_name"])
    g = _oj(g, cu, ["ss_customer_sk"], ["c_customer_sk"])
    g = g.select(["c_last_name", "c_first_name", "s_city", "profit",
                  "ss_ticket_number", "amt"])
    return _topn(g, [("c_last_name", "ascending"),
                     ("c_first_name", "ascending"),
                     ("s_city", "ascending"), ("profit", "descending"),
                     ("ss_ticket_number", "ascending")])


_q("q79", "per-ticket coupon/profit by city and customer")(
    (_q79_run, _q79_oracle))


# ===========================================================================
# q96: count of early-evening purchases by dependent-heavy households
# ===========================================================================

def _q96_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_time_sk", "ss_hdemo_sk",
                                         "ss_store_sk")
    hd = _rd(s, t, "household_demographics") \
        .filter(col("hd_dep_count") == 7).select("hd_demo_sk")
    td = _rd(s, t, "time_dim").filter(
        (col("t_hour") == 20) & (col("t_minute") >= 30)) \
        .select("t_time_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    j = _join_dim(ss, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, td, "ss_sold_time_sk", "t_time_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    return (j.select(col("ss_store_sk"))
            .group_by(lit(1).alias("g"))
            .agg(F.count_star().alias("cnt"))
            .select("cnt").collect())


def _q96_oracle(a):
    hd = a["household_demographics"]
    hd = hd.filter(pc.equal(hd["hd_dep_count"], 7)).select(["hd_demo_sk"])
    td = a["time_dim"]
    td = td.filter(pc.and_(pc.equal(td["t_hour"], 20),
                           pc.greater_equal(td["t_minute"], 30))) \
        .select(["t_time_sk"])
    j = _oj(a["store_sales"], hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, td, ["ss_sold_time_sk"], ["t_time_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    return pa.table({"cnt": pa.array([j.num_rows], pa.int64())})


_q("q96", "count of 20:30+ purchases by 7-dependent households")(
    (_q96_run, _q96_oracle))


# ===========================================================================
# q1: customers returning more than 1.2x their store's average
# ===========================================================================

def _q1_run(s, t):
    sr = _rd(s, t, "store_returns").select(
        "sr_returned_date_sk", "sr_customer_sk", "sr_store_sk",
        "sr_return_amt")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    ctr = (_join_dim(sr, dd, "sr_returned_date_sk", "d_date_sk")
           .group_by("sr_customer_sk", "sr_store_sk")
           .agg(F.sum(col("sr_return_amt").cast(DataType.FLOAT64))
                .alias("ctr_total_return")))
    avg_ctr = (ctr.group_by(col("sr_store_sk").alias("st2"))
               .agg(F.avg(col("ctr_total_return")).alias("avg_return")))
    j = _join_dim(ctr, avg_ctr, "sr_store_sk", "st2")
    j = j.filter(col("ctr_total_return") > col("avg_return") * lit(1.2))
    # parameter auto-tune: at CI scales the store table is 6 rows drawn
    # from 12 states, so the single-state 'TN' template parameter often
    # selects zero stores; a 4-state IN keeps the filter real AND the
    # result nonempty at every scale
    st = _rd(s, t, "store").filter(
        col("s_state").isin("TN", "CA", "TX", "NY")).select("s_store_sk")
    j = _join_dim(j, st, "sr_store_sk", "s_store_sk")
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_customer_id")
    j = _join_dim(j, cu, "sr_customer_sk", "c_customer_sk")
    return (j.select("c_customer_id")
            .sort(col("c_customer_id").asc()).limit(100).collect())


def _q1_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk"])
    sr = _oj(a["store_returns"], dd, ["sr_returned_date_sk"],
             ["d_date_sk"])
    sr = sr.set_column(sr.column_names.index("sr_return_amt"),
                       "sr_return_amt",
                       sr["sr_return_amt"].cast(pa.float64()))
    ctr = sr.group_by(["sr_customer_sk", "sr_store_sk"]).aggregate(
        [("sr_return_amt", "sum")]) \
        .rename_columns(["sr_customer_sk", "sr_store_sk",
                         "ctr_total_return"])
    avg_ctr = ctr.group_by(["sr_store_sk"]).aggregate(
        [("ctr_total_return", "mean")]) \
        .rename_columns(["st2", "avg_return"])
    j = _oj(ctr, avg_ctr, ["sr_store_sk"], ["st2"])
    j = j.filter(pc.greater(j["ctr_total_return"],
                            pc.multiply(j["avg_return"], 1.2)))
    st = a["store"].filter(pc.is_in(
        a["store"]["s_state"],
        value_set=pa.array(["TN", "CA", "TX", "NY"]))) \
        .select(["s_store_sk"])
    j = _oj(j, st, ["sr_store_sk"], ["s_store_sk"])
    cu = a["customer"].select(["c_customer_sk", "c_customer_id"])
    j = _oj(j, cu, ["sr_customer_sk"], ["c_customer_sk"])
    g = j.select(["c_customer_id"])
    return _topn(g, [("c_customer_id", "ascending")])


_q("q1", "above-average returners per store (subquery-as-join)")(
    (_q1_run, _q1_oracle))


# ===========================================================================
# q68: city baskets with extended sums
# ===========================================================================

def _q68_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_addr_sk",
        "ss_customer_sk", "ss_ticket_number", "ss_ext_sales_price",
        "ss_ext_list_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_dom") >= 1) & (col("d_dom") <= 2)
        & col("d_year").isin(1999, 2000)).select("d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk")
    hd = _rd(s, t, "household_demographics").filter(
        (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3)) \
        .select("hd_demo_sk")
    ca = _rd(s, t, "customer_address").select("ca_address_sk", "ca_city")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, ca, "ss_addr_sk", "ca_address_sk")
    g = (j.group_by("ss_ticket_number", "ss_customer_sk", "ca_city")
         .agg(F.sum(col("ss_ext_sales_price").cast(DataType.FLOAT64))
              .alias("extended_price"),
              F.sum(col("ss_ext_list_price").cast(DataType.FLOAT64))
              .alias("list_price")))
    cu = _rd(s, t, "customer").select("c_customer_sk", "c_last_name",
                                      "c_first_name")
    g = _join_dim(g, cu, "ss_customer_sk", "c_customer_sk")
    return (g.select("c_last_name", "c_first_name", "ca_city",
                     "extended_price", "list_price", "ss_ticket_number")
            .sort(col("c_last_name").asc(), col("ss_ticket_number").asc())
            .limit(100).collect())


def _q68_oracle(a):
    dd = a["date_dim"]
    dd = dd.filter(pc.and_(pc.and_(
        pc.greater_equal(dd["d_dom"], 1), pc.less_equal(dd["d_dom"], 2)),
        pc.is_in(dd["d_year"], value_set=pa.array([1999, 2000])))) \
        .select(["d_date_sk"])
    hd = a["household_demographics"]
    hd = hd.filter(pc.or_(pc.equal(hd["hd_dep_count"], 4),
                          pc.equal(hd["hd_vehicle_count"], 3))) \
        .select(["hd_demo_sk"])
    ca = a["customer_address"].select(["ca_address_sk", "ca_city"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    j = _oj(j, hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, ca, ["ss_addr_sk"], ["ca_address_sk"])
    for c in ("ss_ext_sales_price", "ss_ext_list_price"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = j.group_by(["ss_ticket_number", "ss_customer_sk", "ca_city"]) \
        .aggregate([("ss_ext_sales_price", "sum"),
                    ("ss_ext_list_price", "sum")]) \
        .rename_columns(["ss_ticket_number", "ss_customer_sk", "ca_city",
                         "extended_price", "list_price"])
    cu = a["customer"].select(["c_customer_sk", "c_last_name",
                               "c_first_name"])
    g = _oj(g, cu, ["ss_customer_sk"], ["c_customer_sk"])
    g = g.select(["c_last_name", "c_first_name", "ca_city",
                  "extended_price", "list_price", "ss_ticket_number"])
    return _topn(g, [("c_last_name", "ascending"),
                     ("ss_ticket_number", "ascending")])


_q("q68", "city baskets with extended price sums")(
    (_q68_run, _q68_oracle))


# ===========================================================================
# q82: items in a price band with mid-range inventory that actually sold
# ===========================================================================

def _q82_run(s, t):
    price = col("i_current_price").cast(DataType.FLOAT64)
    it = _rd(s, t, "item").filter(
        (price >= lit(30.0)) & (price <= lit(60.0))
        & col("i_manufact_id").isin(*range(100, 140))) \
        .select("i_item_sk", "i_item_id", "i_item_desc", "i_current_price")
    inv = _rd(s, t, "inventory").filter(
        (col("inv_quantity_on_hand") >= 100)
        & (col("inv_quantity_on_hand") <= 500)) \
        .select("inv_item_sk", "inv_date_sk")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_date_sk") >= DATE_SK0 + 800)
        & (col("d_date_sk") <= DATE_SK0 + 860)).select("d_date_sk")
    ss = _rd(s, t, "store_sales").select("ss_item_sk")
    j = _join_dim(it, inv, "i_item_sk", "inv_item_sk")
    j = _join_dim(j, dd, "inv_date_sk", "d_date_sk")
    j = _join_dim(j, ss.group_by(col("ss_item_sk").alias("sold_sk"))
                  .agg(F.count_star().alias("n")).select("sold_sk"),
                  "i_item_sk", "sold_sk")
    return (j.group_by("i_item_id", "i_item_desc", "i_current_price")
            .agg(F.count_star().alias("n"))
            .select("i_item_id", "i_item_desc", "i_current_price")
            .sort(col("i_item_id").asc()).limit(100).collect())


def _q82_oracle(a):
    it = a["item"]
    price = it["i_current_price"].cast(pa.float64())
    it = it.filter(pc.and_(pc.and_(
        pc.greater_equal(price, 30.0), pc.less_equal(price, 60.0)),
        pc.is_in(it["i_manufact_id"],
                 value_set=pa.array(list(range(100, 140)))))) \
        .select(["i_item_sk", "i_item_id", "i_item_desc",
                 "i_current_price"])
    inv = a["inventory"]
    inv = inv.filter(pc.and_(
        pc.greater_equal(inv["inv_quantity_on_hand"], 100),
        pc.less_equal(inv["inv_quantity_on_hand"], 500))) \
        .select(["inv_item_sk", "inv_date_sk"])
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_date_sk"], DATE_SK0 + 800),
        pc.less_equal(a["date_dim"]["d_date_sk"], DATE_SK0 + 860))) \
        .select(["d_date_sk"])
    sold = a["store_sales"].group_by(["ss_item_sk"]).aggregate(
        [([], "count_all")]).rename_columns(["sold_sk", "n"]) \
        .select(["sold_sk"])
    j = _oj(it, inv, ["i_item_sk"], ["inv_item_sk"])
    j = _oj(j, dd, ["inv_date_sk"], ["d_date_sk"])
    j = _oj(j, sold, ["i_item_sk"], ["sold_sk"])
    g = j.group_by(["i_item_id", "i_item_desc", "i_current_price"]) \
        .aggregate([([], "count_all")]) \
        .rename_columns(["i_item_id", "i_item_desc", "i_current_price",
                         "n"]).select(["i_item_id", "i_item_desc",
                                       "i_current_price"])
    return _topn(g, [("i_item_id", "ascending")])


_q("q82", "priced+stocked+sold item inventory slice")(
    (_q82_run, _q82_oracle))


# ===========================================================================
# q89: monthly category sales vs the partition average (window over agg)
# ===========================================================================

def _q89_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_store_sk", "ss_sales_price")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk", "d_moy")
    it = _rd(s, t, "item").filter(
        col("i_category").isin("Books", "Electronics", "Sports")) \
        .select("i_item_sk", "i_category", "i_class", "i_brand")
    st = _rd(s, t, "store").select("s_store_sk", "s_store_name")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    g = (j.group_by("i_category", "i_class", "i_brand", "s_store_name",
                    "d_moy")
         .agg(F.sum(col("ss_sales_price").cast(DataType.FLOAT64))
              .alias("sum_sales")))
    g = g.window([F.win_agg("avg", col("sum_sales"))
                  .alias("avg_monthly_sales")],
                 partition_by=[col("i_category"), col("i_brand"),
                               col("s_store_name")])
    g = g.filter((col("sum_sales") - col("avg_monthly_sales") > lit(0.1)
                  * col("avg_monthly_sales"))
                 | (col("avg_monthly_sales") - col("sum_sales")
                    > lit(0.1) * col("avg_monthly_sales")))
    return (g.sort(col("sum_sales").asc(), col("s_store_name").asc(),
                   col("i_brand").asc(), col("d_moy").asc())
            .limit(100).collect())


def _q89_oracle(a):
    dd = a["date_dim"].filter(pc.equal(a["date_dim"]["d_year"], 2000)) \
        .select(["d_date_sk", "d_moy"])
    it = a["item"].filter(pc.is_in(
        a["item"]["i_category"],
        value_set=pa.array(["Books", "Electronics", "Sports"]))) \
        .select(["i_item_sk", "i_category", "i_class", "i_brand"])
    st = a["store"].select(["s_store_sk", "s_store_name"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    j = _oj(j, st, ["ss_store_sk"], ["s_store_sk"])
    j = j.set_column(j.column_names.index("ss_sales_price"),
                     "ss_sales_price",
                     j["ss_sales_price"].cast(pa.float64()))
    g = j.group_by(["i_category", "i_class", "i_brand", "s_store_name",
                    "d_moy"]).aggregate([("ss_sales_price", "sum")]) \
        .rename_columns(["i_category", "i_class", "i_brand",
                         "s_store_name", "d_moy", "sum_sales"])
    avg = g.group_by(["i_category", "i_brand", "s_store_name"]) \
        .aggregate([("sum_sales", "mean")]) \
        .rename_columns(["i_category", "i_brand", "s_store_name",
                         "avg_monthly_sales"])
    g = _oj(g, avg, ["i_category", "i_brand", "s_store_name"])
    dev = pc.abs(pc.subtract(g["sum_sales"], g["avg_monthly_sales"]))
    g = g.filter(pc.greater(dev,
                            pc.multiply(g["avg_monthly_sales"], 0.1)))
    g = g.select(["i_category", "i_class", "i_brand", "s_store_name",
                  "d_moy", "sum_sales", "avg_monthly_sales"])
    return _topn(g, [("sum_sales", "ascending"),
                     ("s_store_name", "ascending"),
                     ("i_brand", "ascending"), ("d_moy", "ascending")])


_q("q89", "monthly sales deviating >10% from partition average")(
    (_q89_run, _q89_oracle))


# ===========================================================================
# q65: store/item pairs whose revenue is below 10% of the store average
# ===========================================================================

def _q65_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_store_sk", "ss_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
        .select("d_date_sk")
    sa = (_join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
          .group_by("ss_store_sk", "ss_item_sk")
          .agg(F.sum(col("ss_sales_price").cast(DataType.FLOAT64))
               .alias("revenue")))
    sb = (sa.group_by(col("ss_store_sk").alias("st2"))
          .agg(F.avg(col("revenue")).alias("ave")))
    j = _join_dim(sa, sb, "ss_store_sk", "st2")
    j = j.filter(col("revenue") <= col("ave") * lit(0.1))
    st = _rd(s, t, "store").select("s_store_sk", "s_store_name")
    it = _rd(s, t, "item").select("i_item_sk", "i_item_desc",
                                  "i_current_price")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    return (j.select("s_store_name", "i_item_desc", "revenue",
                     "i_current_price")
            .sort(col("s_store_name").asc(), col("i_item_desc").asc())
            .limit(100).collect())


def _q65_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_month_seq"], 24),
        pc.less_equal(a["date_dim"]["d_month_seq"], 35))) \
        .select(["d_date_sk"])
    ssj = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    ssj = ssj.set_column(ssj.column_names.index("ss_sales_price"),
                         "ss_sales_price",
                         ssj["ss_sales_price"].cast(pa.float64()))
    sa = ssj.group_by(["ss_store_sk", "ss_item_sk"]).aggregate(
        [("ss_sales_price", "sum")]) \
        .rename_columns(["ss_store_sk", "ss_item_sk", "revenue"])
    sb = sa.group_by(["ss_store_sk"]).aggregate([("revenue", "mean")]) \
        .rename_columns(["st2", "ave"])
    j = _oj(sa, sb, ["ss_store_sk"], ["st2"])
    j = j.filter(pc.less_equal(j["revenue"],
                               pc.multiply(j["ave"], 0.1)))
    j = _oj(j, a["store"].select(["s_store_sk", "s_store_name"]),
            ["ss_store_sk"], ["s_store_sk"])
    j = _oj(j, a["item"].select(["i_item_sk", "i_item_desc",
                                 "i_current_price"]),
            ["ss_item_sk"], ["i_item_sk"])
    g = j.select(["s_store_name", "i_item_desc", "revenue",
                  "i_current_price"])
    return _topn(g, [("s_store_name", "ascending"),
                     ("i_item_desc", "ascending")])


_q("q65", "under-performing store/item pairs")((_q65_run, _q65_oracle))


# ===========================================================================
# q50: return-lag day buckets per store
# ===========================================================================

def _q50_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
        "ss_ticket_number", "ss_store_sk")
    sr = _rd(s, t, "store_returns").select(
        col("sr_returned_date_sk"), col("sr_item_sk").alias("ss_item_sk"),
        col("sr_customer_sk").alias("ss_customer_sk"),
        col("sr_ticket_number").alias("ss_ticket_number"))
    j = ss.join(sr, on=["ss_ticket_number", "ss_item_sk",
                        "ss_customer_sk"])
    dd2 = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2001) & (col("d_moy") == 8)) \
        .select("d_date_sk")
    j = _join_dim(j, dd2, "sr_returned_date_sk", "d_date_sk")
    st = _rd(s, t, "store").select("s_store_sk", "s_store_name")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    lag = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    buckets = [("d30", lag <= lit(30)),
               ("d60", (lag > lit(30)) & (lag <= lit(60))),
               ("d90", (lag > lit(60)) & (lag <= lit(90))),
               ("d120", (lag > lit(90)) & (lag <= lit(120))),
               ("dmore", lag > lit(120))]
    aggs = [F.sum(F.if_(cond, lit(1), lit(0))).alias(nm)
            for nm, cond in buckets]
    return (j.group_by("s_store_name").agg(*aggs)
            .sort(col("s_store_name").asc()).limit(100).collect())


def _q50_oracle(a):
    sr = a["store_returns"].rename_columns(
        ["sr_returned_date_sk", "ss_item_sk", "ss_customer_sk",
         "ss_ticket_number", "sr_store_sk", "sr_return_quantity",
         "sr_return_amt", "sr_fee", "sr_net_loss"])
    sr = sr.select(["sr_returned_date_sk", "ss_item_sk", "ss_customer_sk",
                    "ss_ticket_number"])
    j = _oj(a["store_sales"], sr,
            ["ss_ticket_number", "ss_item_sk", "ss_customer_sk"])
    dd2 = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2001),
        pc.equal(a["date_dim"]["d_moy"], 8))).select(["d_date_sk"])
    j = _oj(j, dd2, ["sr_returned_date_sk"], ["d_date_sk"])
    j = _oj(j, a["store"].select(["s_store_sk", "s_store_name"]),
            ["ss_store_sk"], ["s_store_sk"])
    lag = pc.subtract(j["sr_returned_date_sk"], j["ss_sold_date_sk"])
    conds = [("d30", pc.less_equal(lag, 30)),
             ("d60", pc.and_(pc.greater(lag, 30), pc.less_equal(lag, 60))),
             ("d90", pc.and_(pc.greater(lag, 60), pc.less_equal(lag, 90))),
             ("d120", pc.and_(pc.greater(lag, 90),
                              pc.less_equal(lag, 120))),
             ("dmore", pc.greater(lag, 120))]
    cols = {"s_store_name": j["s_store_name"]}
    for nm, c in conds:
        cols[nm] = pc.if_else(c, pa.scalar(1, pa.int64()),
                              pa.scalar(0, pa.int64()))
    base = pa.table(cols)
    g = base.group_by(["s_store_name"]).aggregate(
        [(nm, "sum") for nm, _ in conds]) \
        .rename_columns(["s_store_name"] + [nm for nm, _ in conds])
    return _topn(g, [("s_store_name", "ascending")])


_q("q50", "return-lag day buckets per store")((_q50_run, _q50_oracle))


# ===========================================================================
# q33: manufacturer revenue by channel slice (store only, simplified to
#       the store-channel leg of the union)
# ===========================================================================

def _q33_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_addr_sk",
                                         "ss_ext_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 1999) & (col("d_moy") == 3)) \
        .select("d_date_sk")
    ca = _rd(s, t, "customer_address").filter(
        col("ca_gmt_offset") == -5.0).select("ca_address_sk")
    it = _rd(s, t, "item").filter(col("i_category") == "Electronics") \
        .select("i_item_sk", "i_manufact_id")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, ca, "ss_addr_sk", "ca_address_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    return (j.group_by("i_manufact_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("total_sales"))
            .sort(col("total_sales").asc(), col("i_manufact_id").asc())
            .limit(100).collect())


def _q33_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 1999),
        pc.equal(a["date_dim"]["d_moy"], 3))).select(["d_date_sk"])
    ca = a["customer_address"].filter(
        pc.equal(a["customer_address"]["ca_gmt_offset"], -5.0)) \
        .select(["ca_address_sk"])
    it = a["item"].filter(
        pc.equal(a["item"]["i_category"], "Electronics")) \
        .select(["i_item_sk", "i_manufact_id"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, ca, ["ss_addr_sk"], ["ca_address_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    g = j.group_by(["i_manufact_id"]).aggregate(
        [("ss_ext_sales_price", "sum")]) \
        .rename_columns(["i_manufact_id", "total_sales"])
    return _topn(g, [("total_sales", "ascending"),
                     ("i_manufact_id", "ascending")])


_q("q33", "manufacturer revenue in one region/month (store leg)")(
    (_q33_run, _q33_oracle))


# ===========================================================================
# q88: time-of-day purchase counts (four half-hour buckets as one agg)
# ===========================================================================

def _q88_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_time_sk", "ss_hdemo_sk",
                                         "ss_store_sk")
    hd = _rd(s, t, "household_demographics").filter(
        col("hd_dep_count") == 3).select("hd_demo_sk")
    td = _rd(s, t, "time_dim").filter(
        (col("t_hour") >= 8) & (col("t_hour") <= 11)) \
        .select("t_time_sk", "t_hour", "t_minute")
    st = _rd(s, t, "store").select("s_store_sk")
    j = _join_dim(ss, hd, "ss_hdemo_sk", "hd_demo_sk")
    j = _join_dim(j, td, "ss_sold_time_sk", "t_time_sk")
    j = _join_dim(j, st, "ss_store_sk", "s_store_sk")
    half = (col("t_hour") - lit(8)) * lit(2) \
        + F.if_(col("t_minute") >= lit(30), lit(1), lit(0))
    aggs = [F.sum(F.if_(half == lit(k), lit(1), lit(0))).alias(f"h{k}")
            for k in range(8)]
    return (j.select(col("t_hour"), col("t_minute"))
            .with_column("half", half)
            .group_by(lit(1).alias("g")).agg(*aggs)
            .select(*[f"h{k}" for k in range(8)]).collect())


def _q88_oracle(a):
    hd = a["household_demographics"]
    hd = hd.filter(pc.equal(hd["hd_dep_count"], 3)).select(["hd_demo_sk"])
    td = a["time_dim"]
    td = td.filter(pc.and_(pc.greater_equal(td["t_hour"], 8),
                           pc.less_equal(td["t_hour"], 11))) \
        .select(["t_time_sk", "t_hour", "t_minute"])
    j = _oj(a["store_sales"], hd, ["ss_hdemo_sk"], ["hd_demo_sk"])
    j = _oj(j, td, ["ss_sold_time_sk"], ["t_time_sk"])
    j = _oj(j, a["store"].select(["s_store_sk"]), ["ss_store_sk"],
            ["s_store_sk"])
    half = pc.add(pc.multiply(pc.subtract(j["t_hour"], 8), 2),
                  pc.if_else(pc.greater_equal(j["t_minute"], 30), 1, 0))
    out = {}
    for k in range(8):
        out[f"h{k}"] = pa.array(
            [pc.sum(pc.cast(pc.equal(half, k), pa.int64())).as_py() or 0],
            pa.int64())
    return pa.table(out)


_q("q88", "morning half-hour purchase count buckets")(
    (_q88_run, _q88_oracle))


# ===========================================================================
# rollup / grouping-sets family (round-5 directive 6). The engine side uses
# DataFrame.rollup (Expand + grouping_id, Spark's own lowering); the oracle
# computes each grouping-set level independently in pyarrow and concats.
# ===========================================================================

def _oracle_rollup(t, keys, aggs, agg_names):
    """Per-prefix-level group_by, null-filled rolled-up keys + Spark
    grouping_id, concatenated (the independent rollup oracle)."""
    import pyarrow as _pa
    n = len(keys)
    outs = []
    for level in range(n, -1, -1):
        inc = keys[:level]
        gid = sum(1 << (n - 1 - i) for i in range(level, n))
        if inc:
            g = t.group_by(inc, use_threads=False).aggregate(aggs)
            g = g.rename_columns(list(inc) + agg_names)
        else:
            g = t.group_by([], use_threads=False).aggregate(aggs)
            g = g.rename_columns(agg_names)
        cols, names = [], []
        for i, k in enumerate(keys):
            if i < level:
                cols.append(g.column(k))
            else:
                cols.append(_pa.nulls(g.num_rows, t.schema.field(k).type))
            names.append(k)
        cols.append(_pa.array([gid] * g.num_rows, _pa.int32()))
        names.append("spark_grouping_id")
        for an in agg_names:
            cols.append(g.column(an))
            names.append(an)
        outs.append(_pa.table(dict(zip(names, cols))))
    return _pa.concat_tables(outs)


def _q18_run(s, t):
    # q18-class: catalog averages by demographic slice, ROLLUP over the
    # item hierarchy (the template rolls up buyer geography, which this
    # schema subset does not carry on catalog_sales)
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
        "cs_quantity", "cs_list_price", "cs_coupon_amt")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2000) \
        .select("d_date_sk")
    cd = _rd(s, t, "customer_demographics").filter(
        (col("cd_gender") == "F")
        & (col("cd_education_status") == "College")) \
        .select("cd_demo_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_class")
    j = _join_dim(cs, dd, "cs_sold_date_sk", "d_date_sk")
    j = _join_dim(j, cd, "cs_bill_cdemo_sk", "cd_demo_sk")
    j = _join_dim(j, it, "cs_item_sk", "i_item_sk")
    g = (j.rollup("i_category", "i_class")
         .agg(F.avg(col("cs_quantity").cast(DataType.FLOAT64))
              .alias("agg1"),
              F.avg(col("cs_list_price").cast(DataType.FLOAT64))
              .alias("agg2"),
              F.avg(col("cs_coupon_amt").cast(DataType.FLOAT64))
              .alias("agg3")))
    return (g.sort(col("spark_grouping_id").asc(),
                   col("i_category").asc(), col("i_class").asc())
            .limit(200).collect())


def _q18_oracle(a):
    dd = a["date_dim"].filter(
        pc.equal(a["date_dim"]["d_year"], 2000)).select(["d_date_sk"])
    cd = a["customer_demographics"].filter(pc.and_(
        pc.equal(a["customer_demographics"]["cd_gender"], "F"),
        pc.equal(a["customer_demographics"]["cd_education_status"],
                 "College"))).select(["cd_demo_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_class"])
    j = _oj(a["catalog_sales"], dd, ["cs_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, cd, ["cs_bill_cdemo_sk"], ["cd_demo_sk"])
    j = _oj(j, it, ["cs_item_sk"], ["i_item_sk"])
    for c in ("cs_quantity", "cs_list_price", "cs_coupon_amt"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = _oracle_rollup(j, ["i_category", "i_class"],
                       [("cs_quantity", "mean"), ("cs_list_price", "mean"),
                        ("cs_coupon_amt", "mean")],
                       ["agg1", "agg2", "agg3"])
    return _topn(g, [("spark_grouping_id", "ascending"),
                     ("i_category", "ascending"),
                     ("i_class", "ascending")], 200)


_q("q18", "catalog demographic averages, ROLLUP(i_category, i_class)")(
    (_q18_run, _q18_oracle))


def _q22_run(s, t):
    inv = _rd(s, t, "inventory").select("inv_date_sk", "inv_item_sk",
                                        "inv_quantity_on_hand")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_brand")
    j = _join_dim(inv, dd, "inv_date_sk", "d_date_sk")
    j = _join_dim(j, it, "inv_item_sk", "i_item_sk")
    g = (j.rollup("i_category", "i_brand")
         .agg(F.avg(col("inv_quantity_on_hand").cast(DataType.FLOAT64))
              .alias("qoh")))
    return (g.sort(col("qoh").asc(), col("i_category").asc(),
                   col("i_brand").asc()).limit(100).collect())


def _q22_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_month_seq"], 24),
        pc.less_equal(a["date_dim"]["d_month_seq"], 35))) \
        .select(["d_date_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_brand"])
    j = _oj(a["inventory"], dd, ["inv_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["inv_item_sk"], ["i_item_sk"])
    j = j.set_column(j.column_names.index("inv_quantity_on_hand"),
                     "inv_quantity_on_hand",
                     j["inv_quantity_on_hand"].cast(pa.float64()))
    g = _oracle_rollup(j, ["i_category", "i_brand"],
                       [("inv_quantity_on_hand", "mean")], ["qoh"])
    return _topn(g, [("qoh", "ascending"), ("i_category", "ascending"),
                     ("i_brand", "ascending")])


_q("q22", "average inventory on hand, ROLLUP(i_category, i_brand)")(
    (_q22_run, _q22_oracle))


def _q36_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
        "ss_ext_sales_price", "ss_net_profit")
    dd = _rd(s, t, "date_dim").filter(col("d_year") == 2001) \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_class")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    g = (j.rollup("i_category", "i_class")
         .agg(F.sum(col("ss_net_profit").cast(DataType.FLOAT64))
              .alias("profit"),
              F.sum(col("ss_ext_sales_price").cast(DataType.FLOAT64))
              .alias("sales")))
    # gross margin + lochierarchy = grouping(category)+grouping(class),
    # computed from the Spark grouping id bits
    g = g.with_column("gross_margin", col("profit") / col("sales"))
    g = g.with_column(
        "lochierarchy",
        (col("spark_grouping_id") % lit(2, DataType.INT32))
        + (col("spark_grouping_id") / lit(2, DataType.INT32)))
    g = g.select("i_category", "i_class", "gross_margin", "lochierarchy")
    return (g.sort(col("lochierarchy").desc(), col("i_category").asc(),
                   col("i_class").asc()).limit(100).collect())


def _q36_oracle(a):
    dd = a["date_dim"].filter(
        pc.equal(a["date_dim"]["d_year"], 2001)).select(["d_date_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_class"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    for c in ("ss_net_profit", "ss_ext_sales_price"):
        j = j.set_column(j.column_names.index(c), c,
                         j[c].cast(pa.float64()))
    g = _oracle_rollup(j, ["i_category", "i_class"],
                       [("ss_net_profit", "sum"),
                        ("ss_ext_sales_price", "sum")],
                       ["profit", "sales"])
    gm = pc.divide(g["profit"], g["sales"])
    gid = g["spark_grouping_id"]
    loch = pc.add(pc.bit_wise_and(gid, 1),
                  pc.shift_right(gid, 1))
    g = pa.table({"i_category": g["i_category"], "i_class": g["i_class"],
                  "gross_margin": gm,
                  "lochierarchy": loch.cast(pa.int32())})
    return _topn(g, [("lochierarchy", "descending"),
                     ("i_category", "ascending"),
                     ("i_class", "ascending")])


_q("q36", "gross margin ROLLUP with grouping()-derived hierarchy level")(
    (_q36_run, _q36_oracle))


def _q67_run(s, t):
    ss = _rd(s, t, "store_sales").select(
        "ss_sold_date_sk", "ss_item_sk", "ss_quantity", "ss_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 24) & (col("d_month_seq") <= 35)) \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_class",
                                  "i_brand")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    j = j.with_column(
        "amt", col("ss_sales_price").cast(DataType.FLOAT64)
        * col("ss_quantity").cast(DataType.FLOAT64))
    g = (j.rollup("i_category", "i_class", "i_brand")
         .agg(F.sum(col("amt")).alias("sumsales")))
    # rank the hierarchy rows within each category by sales
    g = g.window([F.rank().alias("rk")],
                 partition_by=[col("i_category")],
                 order_by=[col("sumsales").desc()])
    g = g.filter(col("rk") <= 5) \
        .select("i_category", "i_class", "i_brand", "sumsales", "rk")
    return (g.sort(col("i_category").asc(), col("rk").asc(),
                   col("i_class").asc(), col("i_brand").asc())
            .limit(200).collect())


def _q67_oracle(a):
    import pandas as pd
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_month_seq"], 24),
        pc.less_equal(a["date_dim"]["d_month_seq"], 35))) \
        .select(["d_date_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_class",
                           "i_brand"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    amt = pc.multiply(j["ss_sales_price"].cast(pa.float64()),
                      j["ss_quantity"].cast(pa.float64()))
    j = j.append_column("amt", amt)
    g = _oracle_rollup(j, ["i_category", "i_class", "i_brand"],
                       [("amt", "sum")], ["sumsales"])
    df = g.to_pandas()
    # rank(method='min') over sumsales desc per category (NaN category =
    # the all-up row partitions together, like the engine's NULL keys)
    df["rk"] = df.groupby("i_category", dropna=False)["sumsales"] \
        .rank(method="min", ascending=False).astype("int64")
    df = df[df.rk <= 5][["i_category", "i_class", "i_brand",
                         "sumsales", "rk"]]
    out = pa.Table.from_pandas(df.reset_index(drop=True),
                               preserve_index=False)
    return _topn(out, [("i_category", "ascending"), ("rk", "ascending"),
                       ("i_class", "ascending"), ("i_brand", "ascending")],
                 200)


_q("q67", "top sales rows per category over ROLLUP(cat, class, brand)")(
    (_q67_run, _q67_oracle))


def _q86_run(s, t):
    ws = _rd(s, t, "web_sales").select("ws_sold_date_sk", "ws_item_sk",
                                       "ws_ext_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_month_seq") >= 12) & (col("d_month_seq") <= 23)) \
        .select("d_date_sk")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_class")
    j = _join_dim(ws, dd, "ws_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ws_item_sk", "i_item_sk")
    g = (j.rollup("i_category", "i_class")
         .agg(F.sum(col("ws_ext_sales_price").cast(DataType.FLOAT64))
              .alias("total_sum")))
    g = g.with_column(
        "lochierarchy",
        (col("spark_grouping_id") % lit(2, DataType.INT32))
        + (col("spark_grouping_id") / lit(2, DataType.INT32)))
    g = g.select("total_sum", "i_category", "i_class", "lochierarchy")
    return (g.sort(col("lochierarchy").desc(), col("total_sum").desc(),
                   col("i_category").asc(), col("i_class").asc())
            .limit(100).collect())


def _q86_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_month_seq"], 12),
        pc.less_equal(a["date_dim"]["d_month_seq"], 23))) \
        .select(["d_date_sk"])
    it = a["item"].select(["i_item_sk", "i_category", "i_class"])
    j = _oj(a["web_sales"], dd, ["ws_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ws_item_sk"], ["i_item_sk"])
    j = j.set_column(j.column_names.index("ws_ext_sales_price"),
                     "ws_ext_sales_price",
                     j["ws_ext_sales_price"].cast(pa.float64()))
    g = _oracle_rollup(j, ["i_category", "i_class"],
                       [("ws_ext_sales_price", "sum")], ["total_sum"])
    gid = g["spark_grouping_id"]
    loch = pc.add(pc.bit_wise_and(gid, 1), pc.shift_right(gid, 1))
    g = pa.table({"total_sum": g["total_sum"],
                  "i_category": g["i_category"],
                  "i_class": g["i_class"],
                  "lochierarchy": loch.cast(pa.int32())})
    return _topn(g, [("lochierarchy", "descending"),
                     ("total_sum", "descending"),
                     ("i_category", "ascending"),
                     ("i_class", "ascending")])


_q("q86", "web revenue ROLLUP(i_category, i_class) with hierarchy level")(
    (_q86_run, _q86_oracle))


# ===========================================================================
# EXISTS / IN-correlated family: Spark lowers these to semi/anti joins
# before the physical plan (RewritePredicateSubquery), which is exactly
# what the engine's semi/anti hash joins execute.
# ===========================================================================

def _q10_run(s, t):
    # q10-class: demographics of customers in selected counties WITH a
    # store purchase in the period (EXISTS → semi join). The template's
    # web/catalog EXISTS legs need customer keys those facts don't carry
    # in this schema subset.
    c = _rd(s, t, "customer").select("c_customer_sk", "c_current_cdemo_sk",
                                     "c_current_addr_sk")
    ca = _rd(s, t, "customer_address").filter(
        col("ca_county").isin("Ziebach County", "Walker County",
                              "Daviess County")) \
        .select("ca_address_sk")
    c = _join_dim(c, ca, "c_current_addr_sk", "ca_address_sk")
    ss = _rd(s, t, "store_sales").select("ss_customer_sk",
                                         "ss_sold_date_sk")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_moy") >= 1) & (col("d_moy") <= 4)) \
        .select("d_date_sk")
    buyers = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk") \
        .select(col("ss_customer_sk").alias("c_customer_sk"))
    c = c.join(buyers, on="c_customer_sk", how="semi")
    cd = _rd(s, t, "customer_demographics").select(
        "cd_demo_sk", "cd_gender", "cd_marital_status",
        "cd_education_status")
    j = _join_dim(c, cd, "c_current_cdemo_sk", "cd_demo_sk")
    g = (j.group_by("cd_gender", "cd_marital_status",
                    "cd_education_status")
         .agg(F.count_star().alias("cnt")))
    return (g.sort(col("cd_gender").asc(), col("cd_marital_status").asc(),
                   col("cd_education_status").asc()).limit(100).collect())


def _q10_oracle(a):
    ca = a["customer_address"].filter(pc.is_in(
        a["customer_address"]["ca_county"],
        value_set=pa.array(["Ziebach County", "Walker County",
                            "Daviess County"]))).select(["ca_address_sk"])
    c = _oj(a["customer"], ca, ["c_current_addr_sk"], ["ca_address_sk"])
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2000),
        pc.and_(pc.greater_equal(a["date_dim"]["d_moy"], 1),
                pc.less_equal(a["date_dim"]["d_moy"], 4)))) \
        .select(["d_date_sk"])
    ss = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    buyers = ss.select(["ss_customer_sk"]).rename_columns(
        ["c_customer_sk"])
    c = _oj(c, buyers, ["c_customer_sk"], how="left semi")
    cd = a["customer_demographics"].select(
        ["cd_demo_sk", "cd_gender", "cd_marital_status",
         "cd_education_status"])
    j = _oj(c, cd, ["c_current_cdemo_sk"], ["cd_demo_sk"])
    g = j.group_by(["cd_gender", "cd_marital_status",
                    "cd_education_status"]).aggregate([([], "count_all")]) \
        .rename_columns(["cd_gender", "cd_marital_status",
                         "cd_education_status", "cnt"])
    return _topn(g, [("cd_gender", "ascending"),
                     ("cd_marital_status", "ascending"),
                     ("cd_education_status", "ascending")])


_q("q10", "demographics of county customers with store purchases "
          "(EXISTS as semi join)")((_q10_run, _q10_oracle))


def _q35_run(s, t):
    # q35-class: purchase-active customers' demographic aggregate battery
    c = _rd(s, t, "customer").select("c_customer_sk", "c_current_cdemo_sk",
                                     "c_birth_month")
    ss = _rd(s, t, "store_sales").select("ss_customer_sk",
                                         "ss_sold_date_sk")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2001) & (col("d_qoy") < 4)).select("d_date_sk")
    buyers = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk") \
        .select(col("ss_customer_sk").alias("c_customer_sk"))
    c = c.join(buyers, on="c_customer_sk", how="semi")
    cd = _rd(s, t, "customer_demographics").select(
        "cd_demo_sk", "cd_gender", "cd_marital_status", "cd_dep_count")
    j = _join_dim(c, cd, "c_current_cdemo_sk", "cd_demo_sk")
    g = (j.group_by("cd_gender", "cd_marital_status")
         .agg(F.count_star().alias("cnt"),
              F.avg(col("cd_dep_count").cast(DataType.FLOAT64))
              .alias("avg_dep"),
              F.max(col("cd_dep_count")).alias("max_dep"),
              F.sum(col("cd_dep_count")).alias("sum_dep")))
    return (g.sort(col("cd_gender").asc(),
                   col("cd_marital_status").asc()).limit(100).collect())


def _q35_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2001),
        pc.less(a["date_dim"]["d_qoy"], 4))).select(["d_date_sk"])
    ss = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    buyers = ss.select(["ss_customer_sk"]).rename_columns(
        ["c_customer_sk"])
    c = _oj(a["customer"], buyers, ["c_customer_sk"], how="left semi")
    cd = a["customer_demographics"].select(
        ["cd_demo_sk", "cd_gender", "cd_marital_status", "cd_dep_count"])
    j = _oj(c, cd, ["c_current_cdemo_sk"], ["cd_demo_sk"])
    j = j.append_column("dep_f", j["cd_dep_count"].cast(pa.float64()))
    g = j.group_by(["cd_gender", "cd_marital_status"]).aggregate(
        [([], "count_all"), ("dep_f", "mean"), ("cd_dep_count", "max"),
         ("cd_dep_count", "sum")]) \
        .rename_columns(["cd_gender", "cd_marital_status", "cnt",
                         "avg_dep", "max_dep", "sum_dep"])
    return _topn(g, [("cd_gender", "ascending"),
                     ("cd_marital_status", "ascending")])


_q("q35", "demographic aggregate battery over purchase-active customers "
          "(IN as semi join)")((_q35_run, _q35_oracle))


def _q69_run(s, t):
    # q69-class: customers WITH a purchase in the period but WITHOUT any
    # return (EXISTS + NOT EXISTS → semi + anti). The template excludes
    # web/catalog activity, which this subset's facts cannot key by
    # customer; store returns carry the NOT-EXISTS role.
    c = _rd(s, t, "customer").select("c_customer_sk",
                                     "c_current_cdemo_sk")
    ss = _rd(s, t, "store_sales").select("ss_customer_sk",
                                         "ss_sold_date_sk")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == 2000) & (col("d_qoy") <= 2)).select("d_date_sk")
    buyers = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk") \
        .select(col("ss_customer_sk").alias("c_customer_sk"))
    returners = _rd(s, t, "store_returns") \
        .select(col("sr_customer_sk").alias("c_customer_sk"))
    c = c.join(buyers, on="c_customer_sk", how="semi")
    c = c.join(returners, on="c_customer_sk", how="anti")
    cd = _rd(s, t, "customer_demographics").select(
        "cd_demo_sk", "cd_gender", "cd_marital_status",
        "cd_education_status")
    j = _join_dim(c, cd, "c_current_cdemo_sk", "cd_demo_sk")
    g = (j.group_by("cd_gender", "cd_marital_status",
                    "cd_education_status")
         .agg(F.count_star().alias("cnt")))
    return (g.sort(col("cd_gender").asc(), col("cd_marital_status").asc(),
                   col("cd_education_status").asc()).limit(100).collect())


def _q69_oracle(a):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], 2000),
        pc.less_equal(a["date_dim"]["d_qoy"], 2))).select(["d_date_sk"])
    ss = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    buyers = ss.select(["ss_customer_sk"]).rename_columns(
        ["c_customer_sk"])
    returners = a["store_returns"].select(["sr_customer_sk"]) \
        .rename_columns(["c_customer_sk"])
    c = _oj(a["customer"], buyers, ["c_customer_sk"], how="left semi")
    c = _oj(c, returners, ["c_customer_sk"], how="left anti")
    cd = a["customer_demographics"].select(
        ["cd_demo_sk", "cd_gender", "cd_marital_status",
         "cd_education_status"])
    j = _oj(c, cd, ["c_current_cdemo_sk"], ["cd_demo_sk"])
    g = j.group_by(["cd_gender", "cd_marital_status",
                    "cd_education_status"]).aggregate([([], "count_all")]) \
        .rename_columns(["cd_gender", "cd_marital_status",
                         "cd_education_status", "cnt"])
    return _topn(g, [("cd_gender", "ascending"),
                     ("cd_marital_status", "ascending"),
                     ("cd_education_status", "ascending")])


_q("q69", "buyers with no returns by demographics (semi + anti join)")(
    (_q69_run, _q69_oracle))


def _q93_run(s, t):
    # q93: actual sales after returns — ss LEFT JOIN sr on
    # (ticket, item); returned quantity reduces the paid amount
    ss = _rd(s, t, "store_sales").select(
        "ss_ticket_number", "ss_item_sk", "ss_customer_sk",
        "ss_quantity", "ss_sales_price")
    sr = _rd(s, t, "store_returns").select(
        col("sr_ticket_number").alias("ss_ticket_number"),
        col("sr_item_sk").alias("ss_item_sk"),
        col("sr_return_quantity"))
    j = ss.join(sr, on=["ss_ticket_number", "ss_item_sk"], how="left")
    qty = col("ss_quantity").cast(DataType.FLOAT64)
    ret = col("sr_return_quantity").cast(DataType.FLOAT64)
    price = col("ss_sales_price").cast(DataType.FLOAT64)
    act = F.if_(col("sr_return_quantity").is_not_null(),
                (qty - ret) * price, qty * price)
    j = j.with_column("act_sales", act)
    g = (j.group_by("ss_customer_sk")
         .agg(F.sum(col("act_sales")).alias("sumsales")))
    return (g.sort(col("sumsales").asc(), col("ss_customer_sk").asc())
            .limit(100).collect())


def _q93_oracle(a):
    import pandas as pd
    ss = a["store_sales"].select(
        ["ss_ticket_number", "ss_item_sk", "ss_customer_sk",
         "ss_quantity", "ss_sales_price"]).to_pandas()
    sr = a["store_returns"].select(
        ["sr_ticket_number", "sr_item_sk", "sr_return_quantity"]) \
        .to_pandas()
    j = ss.merge(sr, how="left",
                 left_on=["ss_ticket_number", "ss_item_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk"])
    price = j.ss_sales_price.astype(float)
    qty = j.ss_quantity.astype(float)
    act = np.where(j.sr_return_quantity.notna(),
                   (qty - j.sr_return_quantity.fillna(0)) * price,
                   qty * price)
    j["act_sales"] = act
    g = j.groupby("ss_customer_sk", dropna=False)["act_sales"] \
        .sum().reset_index().rename(columns={"act_sales": "sumsales"})
    out = pa.Table.from_pandas(g, preserve_index=False)
    return _topn(out, [("sumsales", "ascending"),
                       ("ss_customer_sk", "ascending")])


_q("q93", "actual sales after returns per customer (ss left-join sr)")(
    (_q93_run, _q93_oracle))


# ===========================================================================
# multi-channel UNION family
# ===========================================================================

def _channel_legs(s, t, year, moy_lo, moy_hi):
    """(ss, cs, ws) legs normalized to (item_sk, ext_price) within the
    date window — the common scaffold of q60/q71."""
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") == year) & (col("d_moy") >= moy_lo)
        & (col("d_moy") <= moy_hi)).select("d_date_sk")
    legs = []
    for fact, dk, ik, pk in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price")):
        f = _rd(s, t, fact).select(dk, ik, pk)
        f = _join_dim(f, dd, dk, "d_date_sk")
        legs.append(f.select(
            col(ik).alias("item_sk"),
            col(pk).cast(DataType.FLOAT64).alias("ext_price")))
    return legs


def _oracle_channel_legs(a, year, moy_lo, moy_hi):
    dd = a["date_dim"].filter(pc.and_(
        pc.equal(a["date_dim"]["d_year"], year),
        pc.and_(pc.greater_equal(a["date_dim"]["d_moy"], moy_lo),
                pc.less_equal(a["date_dim"]["d_moy"], moy_hi)))) \
        .select(["d_date_sk"])
    legs = []
    for fact, dk, ik, pk in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price")):
        f = _oj(a[fact].select([dk, ik, pk]), dd, [dk], ["d_date_sk"])
        legs.append(pa.table({
            "item_sk": f[ik],
            "ext_price": f[pk].cast(pa.float64())}))
    return legs


def _q60_run(s, t):
    # q60: total cross-channel revenue per item id in one category/month
    legs = _channel_legs(s, t, 1999, 8, 9)
    u = legs[0].union(legs[1]).union(legs[2])
    it = _rd(s, t, "item").filter(col("i_category") == "Music") \
        .select(col("i_item_sk").alias("item_sk"), col("i_item_id"))
    j = u.join(it, on="item_sk", how="inner")
    g = (j.group_by("i_item_id")
         .agg(F.sum(col("ext_price")).alias("total_sales")))
    return (g.sort(col("i_item_id").asc(), col("total_sales").asc())
            .limit(100).collect())


def _q60_oracle(a):
    legs = _oracle_channel_legs(a, 1999, 8, 9)
    u = pa.concat_tables(legs)
    it = a["item"].filter(pc.equal(a["item"]["i_category"], "Music")) \
        .select(["i_item_sk", "i_item_id"]) \
        .rename_columns(["item_sk", "i_item_id"])
    j = _oj(u, it, ["item_sk"])
    g = j.group_by(["i_item_id"]).aggregate([("ext_price", "sum")]) \
        .rename_columns(["i_item_id", "total_sales"])
    return _topn(g, [("i_item_id", "ascending"),
                     ("total_sales", "ascending")])


_q("q60", "cross-channel item revenue in one category (3-way UNION)")(
    (_q60_run, _q60_oracle))


def _q71_run(s, t):
    # q71-class: brand revenue across all three channels for one month
    # under one manager (the template also splits by time-of-day; only
    # the store fact carries a time key in this subset)
    legs = _channel_legs(s, t, 2000, 12, 12)
    u = legs[0].union(legs[1]).union(legs[2])
    it = _rd(s, t, "item").filter(col("i_manager_id") == 1) \
        .select(col("i_item_sk").alias("item_sk"), col("i_brand_id"),
                col("i_brand"))
    j = u.join(it, on="item_sk", how="inner")
    g = (j.group_by("i_brand_id", "i_brand")
         .agg(F.sum(col("ext_price")).alias("ext_price_sum")))
    return (g.sort(col("ext_price_sum").desc(), col("i_brand_id").asc())
            .limit(100).collect())


def _q71_oracle(a):
    legs = _oracle_channel_legs(a, 2000, 12, 12)
    u = pa.concat_tables(legs)
    it = a["item"].filter(pc.equal(a["item"]["i_manager_id"], 1)) \
        .select(["i_item_sk", "i_brand_id", "i_brand"]) \
        .rename_columns(["item_sk", "i_brand_id", "i_brand"])
    j = _oj(u, it, ["item_sk"])
    g = j.group_by(["i_brand_id", "i_brand"]).aggregate(
        [("ext_price", "sum")]) \
        .rename_columns(["i_brand_id", "i_brand", "ext_price_sum"])
    return _topn(g, [("ext_price_sum", "descending"),
                     ("i_brand_id", "ascending")])


_q("q71", "brand revenue across three channels for one manager/month")(
    (_q71_run, _q71_oracle))


def _q76_run(s, t):
    # q76: per-channel sales rows whose surrogate key is NULL, unioned
    # and counted by (channel, null-column tag, year, quarter, category)
    it = _rd(s, t, "item").select("i_item_sk", "i_category")
    dd = _rd(s, t, "date_dim").select("d_date_sk", "d_year", "d_qoy")
    legs = []
    for fact, dk, ik, pk, nullk, chan in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price", "ss_promo_sk", "store"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price", "cs_warehouse_sk", "catalog"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price", "ws_ship_mode_sk", "web")):
        f = _rd(s, t, fact).select(dk, ik, pk, nullk)
        f = f.filter(col(nullk).is_null())
        f = _join_dim(f, it, ik, "i_item_sk")
        f = _join_dim(f, dd, dk, "d_date_sk")
        legs.append(f.select(
            lit(chan, DataType.STRING).alias("channel"),
            lit(nullk, DataType.STRING).alias("col_name"),
            col("d_year"), col("d_qoy"), col("i_category"),
            col(pk).cast(DataType.FLOAT64).alias("ext_price")))
    u = legs[0].union(legs[1]).union(legs[2])
    g = (u.group_by("channel", "col_name", "d_year", "d_qoy",
                    "i_category")
         .agg(F.count_star().alias("sales_cnt"),
              F.sum(col("ext_price")).alias("sales_amt")))
    return (g.sort(col("channel").asc(), col("col_name").asc(),
                   col("d_year").asc(), col("d_qoy").asc(),
                   col("i_category").asc()).limit(200).collect())


def _q76_oracle(a):
    it = a["item"].select(["i_item_sk", "i_category"])
    dd = a["date_dim"].select(["d_date_sk", "d_year", "d_qoy"])
    legs = []
    for fact, dk, ik, pk, nullk, chan in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price", "ss_promo_sk", "store"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price", "cs_warehouse_sk", "catalog"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price", "ws_ship_mode_sk", "web")):
        f = a[fact].select([dk, ik, pk, nullk])
        f = f.filter(pc.is_null(f[nullk]))
        f = _oj(f, it, [ik], ["i_item_sk"])
        f = _oj(f, dd, [dk], ["d_date_sk"])
        legs.append(pa.table({
            # explicit string type: an EMPTY leg would otherwise infer
            # null-typed columns and break concat_tables
            "channel": pa.array([chan] * f.num_rows, pa.string()),
            "col_name": pa.array([nullk] * f.num_rows, pa.string()),
            "d_year": f["d_year"], "d_qoy": f["d_qoy"],
            "i_category": f["i_category"],
            "ext_price": f[pk].cast(pa.float64())}))
    u = pa.concat_tables(legs)
    g = u.group_by(["channel", "col_name", "d_year", "d_qoy",
                    "i_category"]).aggregate(
        [([], "count_all"), ("ext_price", "sum")]) \
        .rename_columns(["channel", "col_name", "d_year", "d_qoy",
                         "i_category", "sales_cnt", "sales_amt"])
    return _topn(g, [("channel", "ascending"), ("col_name", "ascending"),
                     ("d_year", "ascending"), ("d_qoy", "ascending"),
                     ("i_category", "ascending")], 200)


_q("q76", "null-key sales rows by channel (3-way UNION, wide group)")(
    (_q76_run, _q76_oracle))


# ===========================================================================
# q9: CASE buckets chosen by scalar subqueries (one-row projection)
# ===========================================================================

def _q9_run(s, t):
    ss = _rd(s, t, "store_sales")
    buckets = []
    for lo, hi in ((1, 20), (21, 40), (41, 60)):
        b = ss.filter((col("ss_quantity") >= lo)
                      & (col("ss_quantity") <= hi))
        cnt = scalar_subquery(
            b.group_by().agg(F.count_star().alias("c")))
        avg_paid = scalar_subquery(
            b.group_by().agg(
                F.avg(col("ss_net_paid").cast(DataType.FLOAT64))
                .alias("a")))
        avg_list = scalar_subquery(
            b.group_by().agg(
                F.avg(col("ss_ext_list_price").cast(DataType.FLOAT64))
                .alias("a")))
        buckets.append(F.if_(cnt > lit(1000, DataType.INT64),
                             avg_paid, avg_list))
    one = _rd(s, t, "date_dim").limit(1)
    return one.select(buckets[0].alias("bucket1"),
                      buckets[1].alias("bucket2"),
                      buckets[2].alias("bucket3")).collect()


def _q9_oracle(a):
    ss = a["store_sales"]
    out = {}
    for i, (lo, hi) in enumerate(((1, 20), (21, 40), (41, 60)), 1):
        m = pc.and_(pc.greater_equal(ss["ss_quantity"], lo),
                    pc.less_equal(ss["ss_quantity"], hi))
        b = ss.filter(m)
        if b.num_rows > 1000:
            v = pc.mean(b["ss_net_paid"].cast(pa.float64())).as_py()
        else:
            v = pc.mean(b["ss_ext_list_price"].cast(pa.float64())).as_py()
        out[f"bucket{i}"] = [v]
    return pa.table(out)


_q("q9", "quantity-bucket averages selected by scalar subqueries")(
    (_q9_run, _q9_oracle))


# ===========================================================================
# q40: catalog sales around a pivot date by warehouse (CASE split)
# ===========================================================================

def _q40_run(s, t):
    pivot = DATE_SK0 + 730
    cs = _rd(s, t, "catalog_sales").select(
        "cs_sold_date_sk", "cs_item_sk", "cs_warehouse_sk",
        "cs_sales_price")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_date_sk") >= pivot - 30) & (col("d_date_sk") <= pivot + 30)) \
        .select("d_date_sk")
    w = _rd(s, t, "warehouse").select("w_warehouse_sk", "w_warehouse_name")
    it = _rd(s, t, "item").filter(
        (col("i_current_price") >= lit(0.99))
        & (col("i_current_price") <= lit(150.00))) \
        .select("i_item_sk", "i_item_id")
    j = _join_dim(cs, dd, "cs_sold_date_sk", "d_date_sk")
    j = _join_dim(j, w, "cs_warehouse_sk", "w_warehouse_sk")
    j = _join_dim(j, it, "cs_item_sk", "i_item_sk")
    price = col("cs_sales_price").cast(DataType.FLOAT64)
    before = F.if_(col("cs_sold_date_sk") < lit(pivot, DataType.INT64),
                   price, lit(0.0))
    after = F.if_(col("cs_sold_date_sk") >= lit(pivot, DataType.INT64),
                  price, lit(0.0))
    j = j.with_column("before_amt", before).with_column("after_amt", after)
    g = (j.group_by("w_warehouse_name", "i_item_id")
         .agg(F.sum(col("before_amt")).alias("sales_before"),
              F.sum(col("after_amt")).alias("sales_after")))
    return (g.sort(col("w_warehouse_name").asc(), col("i_item_id").asc())
            .limit(100).collect())


def _q40_oracle(a):
    pivot = DATE_SK0 + 730
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_date_sk"], pivot - 30),
        pc.less_equal(a["date_dim"]["d_date_sk"], pivot + 30))) \
        .select(["d_date_sk"])
    w = a["warehouse"].select(["w_warehouse_sk", "w_warehouse_name"])
    it = a["item"].filter(pc.and_(
        pc.greater_equal(a["item"]["i_current_price"].cast(pa.float64()),
                         0.99),
        pc.less_equal(a["item"]["i_current_price"].cast(pa.float64()),
                      150.0))).select(["i_item_sk", "i_item_id"])
    j = _oj(a["catalog_sales"], dd, ["cs_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, w, ["cs_warehouse_sk"], ["w_warehouse_sk"])
    j = _oj(j, it, ["cs_item_sk"], ["i_item_sk"])
    price = j["cs_sales_price"].cast(pa.float64())
    isb = pc.less(j["cs_sold_date_sk"], pivot)
    j = j.append_column("before_amt",
                        pc.if_else(isb, price, pa.scalar(0.0)))
    j = j.append_column("after_amt",
                        pc.if_else(pc.invert(isb), price, pa.scalar(0.0)))
    g = j.group_by(["w_warehouse_name", "i_item_id"]).aggregate(
        [("before_amt", "sum"), ("after_amt", "sum")]) \
        .rename_columns(["w_warehouse_name", "i_item_id",
                         "sales_before", "sales_after"])
    return _topn(g, [("w_warehouse_name", "ascending"),
                     ("i_item_id", "ascending")])


_q("q40", "catalog sales before/after a pivot date by warehouse (CASE)")(
    (_q40_run, _q40_oracle))


# ===========================================================================
# q47: monthly brand sales vs centered moving average (ROWS frame window)
# ===========================================================================

def _q47_run(s, t):
    ss = _rd(s, t, "store_sales").select("ss_sold_date_sk", "ss_item_sk",
                                         "ss_sales_price", "ss_quantity")
    dd = _rd(s, t, "date_dim").filter(
        (col("d_year") >= 1999) & (col("d_year") <= 2001)) \
        .select("d_date_sk", "d_year", "d_moy")
    it = _rd(s, t, "item").select("i_item_sk", "i_category", "i_brand")
    j = _join_dim(ss, dd, "ss_sold_date_sk", "d_date_sk")
    j = _join_dim(j, it, "ss_item_sk", "i_item_sk")
    j = j.with_column(
        "amt", col("ss_sales_price").cast(DataType.FLOAT64)
        * col("ss_quantity").cast(DataType.FLOAT64))
    g = (j.group_by("i_category", "i_brand", "d_year", "d_moy")
         .agg(F.sum(col("amt")).alias("sum_sales")))
    # centered 3-month moving average within each brand's month series
    g = g.window(
        [F.win_agg("avg", col("sum_sales"), frame=(-1, 1)).alias("avg3")],
        partition_by=[col("i_category"), col("i_brand")],
        order_by=[col("d_year").asc(), col("d_moy").asc()])
    # q47 reports months deviating from their local average
    g = g.with_column("dev", col("sum_sales") - col("avg3"))
    g = g.filter((col("d_year") == 2000)
                 & ((col("dev") > lit(0.0)) | (col("dev") < lit(0.0))))
    return (g.select("i_category", "i_brand", "d_year", "d_moy",
                     "sum_sales", "avg3")
            .sort(col("i_category").asc(), col("i_brand").asc(),
                  col("d_year").asc(), col("d_moy").asc())
            .limit(100).collect())


def _q47_oracle(a):
    import pandas as pd
    dd = a["date_dim"].filter(pc.and_(
        pc.greater_equal(a["date_dim"]["d_year"], 1999),
        pc.less_equal(a["date_dim"]["d_year"], 2001))) \
        .select(["d_date_sk", "d_year", "d_moy"])
    it = a["item"].select(["i_item_sk", "i_category", "i_brand"])
    j = _oj(a["store_sales"], dd, ["ss_sold_date_sk"], ["d_date_sk"])
    j = _oj(j, it, ["ss_item_sk"], ["i_item_sk"])
    df = j.to_pandas()
    df["amt"] = df.ss_sales_price.astype(float) \
        * df.ss_quantity.astype(float)
    g = df.groupby(["i_category", "i_brand", "d_year", "d_moy"],
                   dropna=False)["amt"].sum().reset_index() \
        .rename(columns={"amt": "sum_sales"})
    g = g.sort_values(["i_category", "i_brand", "d_year", "d_moy"])
    g["avg3"] = g.groupby(["i_category", "i_brand"])["sum_sales"] \
        .transform(lambda x: x.rolling(3, center=True,
                                       min_periods=1).mean())
    g["dev"] = g.sum_sales - g.avg3
    g = g[(g.d_year == 2000) & (g.dev != 0.0)]
    g = g[["i_category", "i_brand", "d_year", "d_moy", "sum_sales",
           "avg3"]]
    g = g.sort_values(["i_category", "i_brand", "d_year", "d_moy"]) \
        .head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q47", "monthly brand sales vs centered moving average (ROWS frame)")(
    (_q47_run, _q47_oracle))
