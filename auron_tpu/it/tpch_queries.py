"""TPC-H q5 / q9 / q18 over the it/tpch.py dataset — the join-heavy
BASELINE.md targets, expressed in the DataFrame DSL and diffed against
independent pandas oracles (reference gate analogue:
dev/auron-it's TPC-DS differ, Main.scala:60-128)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np
import pyarrow as pa

from auron_tpu.columnar.schema import DataType
from auron_tpu.frontend.dataframe import col, functions as F, lit

#: epoch days of the q5/q9 date parameters
_D1994 = (np.datetime64("1994-01-01")
          - np.datetime64("1970-01-01")).astype(int)
_D1995 = (np.datetime64("1995-01-01")
          - np.datetime64("1970-01-01")).astype(int)


@dataclass(frozen=True)
class Query:
    name: str
    description: str
    run: Callable
    oracle: Callable


QUERIES: list = []


def _q(name, description):
    def deco(fns):
        run, oracle = fns
        QUERIES.append(Query(name, description, run, oracle))
        return fns
    return deco


def _rd(s, t, name):
    parts = 4 if name == "lineitem" else (2 if name == "orders" else 1)
    return s.read_parquet(t[name], partitions=parts)


def _rename(df, **kw):
    cols = []
    for f in df.schema:
        cols.append(col(f.name).alias(kw.get(f.name, f.name)))
    return df.select(*cols)


def _join(fact, dim, fk, dk, how="inner"):
    return fact.join(_rename(dim, **{dk: fk}), on=fk, how=how)


def _pd(a):
    return {k: t.to_pandas() for k, t in a.items()}


# --- q5: local supplier volume (6-way join, region+year filters) ----------

def _q5_run(s, t):
    li = _rd(s, t, "lineitem").select("l_orderkey", "l_suppkey",
                                      "l_extendedprice", "l_discount")
    o = _rd(s, t, "orders").filter(
        (col("o_orderdate") >= lit(int(_D1994), DataType.DATE32))
        & (col("o_orderdate") < lit(int(_D1995), DataType.DATE32))) \
        .select("o_orderkey", "o_custkey")
    c = _rd(s, t, "customer").select("c_custkey", "c_nationkey")
    su = _rd(s, t, "supplier").select("s_suppkey", "s_nationkey")
    n = _rd(s, t, "nation").select("n_nationkey", "n_name", "n_regionkey")
    r = _rd(s, t, "region").filter(col("r_name") == "ASIA") \
        .select("r_regionkey")
    j = _join(li, o, "l_orderkey", "o_orderkey")
    j = _join(j, c, "o_custkey", "c_custkey")
    j = _join(j, su, "l_suppkey", "s_suppkey")
    # TPC-H q5: customer and supplier must share the nation
    j = j.filter(col("c_nationkey") == col("s_nationkey"))
    j = _join(j, n, "s_nationkey", "n_nationkey")
    j = _join(j, r, "n_regionkey", "r_regionkey")
    rev = (col("l_extendedprice").cast(DataType.FLOAT64)
           * (lit(1.0) - col("l_discount").cast(DataType.FLOAT64)))
    j = j.with_column("rev", rev)
    return (j.group_by("n_name").agg(F.sum(col("rev")).alias("revenue"))
            .sort(col("revenue").desc(), col("n_name").asc())
            .limit(100).collect())


def _q5_oracle(a):
    p = _pd(a)
    o = p["orders"]
    o = o[(o.o_orderdate >= np.datetime64("1994-01-01"))
          & (o.o_orderdate < np.datetime64("1995-01-01"))]
    j = p["lineitem"].merge(o, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(p["customer"], left_on="o_custkey", right_on="c_custkey")
    j = j.merge(p["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(p["nation"], left_on="s_nationkey",
                right_on="n_nationkey")
    r = p["region"]
    j = j.merge(r[r.r_name == "ASIA"], left_on="n_regionkey",
                right_on="r_regionkey")
    j["rev"] = j.l_extendedprice.astype(float) \
        * (1.0 - j.l_discount.astype(float))
    g = j.groupby("n_name")["rev"].sum().reset_index() \
        .rename(columns={"rev": "revenue"})
    g = g.sort_values(["revenue", "n_name"],
                      ascending=[False, True]).head(100)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q5", "local supplier volume in ASIA (6-way join)")(
    (_q5_run, _q5_oracle))


# --- q9: product-type profit by nation and year ---------------------------

def _q9_run(s, t):
    li = _rd(s, t, "lineitem").select(
        "l_orderkey", "l_partkey", "l_suppkey", "l_quantity",
        "l_extendedprice", "l_discount")
    pt = _rd(s, t, "part").filter(col("p_name").contains("green")) \
        .select("p_partkey")
    su = _rd(s, t, "supplier").select("s_suppkey", "s_nationkey")
    ps = _rd(s, t, "partsupp").select("ps_partkey", "ps_suppkey",
                                      "ps_supplycost")
    o = _rd(s, t, "orders").select("o_orderkey", "o_orderdate")
    n = _rd(s, t, "nation").select("n_nationkey", "n_name")
    j = _join(li, pt, "l_partkey", "p_partkey")
    j = _join(j, su, "l_suppkey", "s_suppkey")
    # partsupp join on BOTH keys
    ps2 = _rename(ps, ps_partkey="l_partkey", ps_suppkey="l_suppkey")
    j = j.join(ps2, on=["l_partkey", "l_suppkey"], how="inner")
    j = _join(j, o, "l_orderkey", "o_orderkey")
    j = _join(j, n, "s_nationkey", "n_nationkey")
    amount = (col("l_extendedprice").cast(DataType.FLOAT64)
              * (lit(1.0) - col("l_discount").cast(DataType.FLOAT64))
              - col("ps_supplycost").cast(DataType.FLOAT64)
              * col("l_quantity").cast(DataType.FLOAT64))
    j = j.with_column("amount", amount)
    j = j.with_column("o_year",
                      F.year(col("o_orderdate").cast(DataType.DATE32)))
    g = (j.group_by("n_name", "o_year")
         .agg(F.sum(col("amount")).alias("sum_profit")))
    return (g.sort(col("n_name").asc(), col("o_year").desc())
            .limit(200).collect())


def _q9_oracle(a):
    p = _pd(a)
    pt = p["part"]
    pt = pt[pt.p_name.str.contains("green")]
    j = p["lineitem"].merge(pt[["p_partkey"]], left_on="l_partkey",
                            right_on="p_partkey")
    j = j.merge(p["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(p["partsupp"],
                left_on=["l_partkey", "l_suppkey"],
                right_on=["ps_partkey", "ps_suppkey"])
    j = j.merge(p["orders"], left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(p["nation"], left_on="s_nationkey",
                right_on="n_nationkey")
    j["amount"] = (j.l_extendedprice.astype(float)
                   * (1.0 - j.l_discount.astype(float))
                   - j.ps_supplycost.astype(float)
                   * j.l_quantity.astype(float))
    j["o_year"] = j.o_orderdate.map(lambda d: d.year).astype("int64")
    g = j.groupby(["n_name", "o_year"])["amount"].sum().reset_index() \
        .rename(columns={"amount": "sum_profit"})
    g = g.sort_values(["n_name", "o_year"],
                      ascending=[True, False]).head(200)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q9", "product-type profit by nation/year ('green' parts, 6-way)")(
    (_q9_run, _q9_oracle))


# --- q18: large-volume customers (agg-filtered IN as semi join) -----------

_Q18_QTY = 180


def _q18_run(s, t):
    li = _rd(s, t, "lineitem").select("l_orderkey", "l_quantity")
    big = (li.group_by("l_orderkey")
           .agg(F.sum(col("l_quantity")).alias("sum_qty"))
           .filter(col("sum_qty") > lit(_Q18_QTY, DataType.INT64))
           .select("l_orderkey"))
    o = _rd(s, t, "orders").select("o_orderkey", "o_custkey",
                                   "o_orderdate", "o_totalprice")
    o = o.join(_rename(big, l_orderkey="o_orderkey"), on="o_orderkey",
               how="semi")
    c = _rd(s, t, "customer").select("c_custkey", "c_name")
    j = _join(o, c, "o_custkey", "c_custkey")
    li2 = _rd(s, t, "lineitem").select(
        col("l_orderkey").alias("o_orderkey"), col("l_quantity"))
    j = j.join(li2, on="o_orderkey", how="inner")
    # the USING-style join dropped c_custkey; o_custkey carries the value
    g = (j.group_by("c_name", col("o_custkey").alias("c_custkey"),
                    "o_orderkey", "o_orderdate", "o_totalprice")
         .agg(F.sum(col("l_quantity")).alias("sum_qty")))
    return (g.sort(col("o_totalprice").cast(DataType.FLOAT64).desc(),
                   col("o_orderdate").asc(), col("o_orderkey").asc())
            .limit(100).collect())


def _q18_oracle(a):
    p = _pd(a)
    li = p["lineitem"]
    big = li.groupby("l_orderkey")["l_quantity"].sum()
    big = big[big > _Q18_QTY].index
    o = p["orders"]
    o = o[o.o_orderkey.isin(big)]
    j = o.merge(p["customer"], left_on="o_custkey", right_on="c_custkey")
    j = j.merge(li[["l_orderkey", "l_quantity"]],
                left_on="o_orderkey", right_on="l_orderkey")
    g = j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                   "o_totalprice"])["l_quantity"].sum().reset_index() \
        .rename(columns={"l_quantity": "sum_qty"})
    g["tp"] = g.o_totalprice.astype(float)
    g = g.sort_values(["tp", "o_orderdate", "o_orderkey"],
                      ascending=[False, True, True]).head(100) \
        .drop(columns=["tp"])
    g["o_orderdate"] = g["o_orderdate"].astype("datetime64[s]").dt.date
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q18", "large-volume customers (agg-filtered semi join)")(
    (_q18_run, _q18_oracle))


# --- q1: pricing summary report -------------------------------------------

def _q1_run(s, t):
    li = _rd(s, t, "lineitem").select(
        "l_quantity", "l_extendedprice", "l_discount", "l_tax",
        "l_returnflag", "l_linestatus", "l_shipdate")
    cutoff = int(np.datetime64("1998-06-02").astype("datetime64[D]")
                 .astype(int))
    li = li.filter(col("l_shipdate") <= lit(cutoff, DataType.DATE32))
    price = col("l_extendedprice").cast(DataType.FLOAT64)
    disc = col("l_discount").cast(DataType.FLOAT64)
    tax = col("l_tax").cast(DataType.FLOAT64)
    li = li.with_column("disc_price", price * (lit(1.0) - disc))
    li = li.with_column("charge",
                        col("disc_price") * (lit(1.0) + tax))
    g = (li.group_by("l_returnflag", "l_linestatus")
         .agg(F.sum(col("l_quantity")).alias("sum_qty"),
              F.sum(price).alias("sum_base_price"),
              F.sum(col("disc_price")).alias("sum_disc_price"),
              F.sum(col("charge")).alias("sum_charge"),
              F.avg(col("l_quantity").cast(DataType.FLOAT64))
              .alias("avg_qty"),
              F.avg(price).alias("avg_price"),
              F.avg(disc).alias("avg_disc"),
              F.count_star().alias("count_order")))
    return (g.sort(col("l_returnflag").asc(), col("l_linestatus").asc())
            .collect())


def _q1_oracle(a):
    p = _pd(a)
    li = p["lineitem"]
    li = li[li.l_shipdate <= np.datetime64("1998-06-02")].copy()
    li["price"] = li.l_extendedprice.astype(float)
    li["disc"] = li.l_discount.astype(float)
    li["tax"] = li.l_tax.astype(float)
    li["disc_price"] = li.price * (1.0 - li.disc)
    li["charge"] = li.disc_price * (1.0 + li.tax)
    g = li.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("price", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("price", "mean"),
        avg_disc=("disc", "mean"),
        count_order=("price", "size")).reset_index()
    g = g.sort_values(["l_returnflag", "l_linestatus"])
    g["count_order"] = g.count_order.astype("int64")
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q1", "pricing summary report (8-agg scan)")((_q1_run, _q1_oracle))


# --- q3: shipping-priority revenue ----------------------------------------

def _q3_run(s, t):
    cutoff = int(np.datetime64("1995-03-15").astype("datetime64[D]")
                 .astype(int))
    c = _rd(s, t, "customer").filter(
        col("c_mktsegment") == "BUILDING").select("c_custkey")
    o = _rd(s, t, "orders").select("o_orderkey", "o_custkey",
                                   "o_orderdate", "o_shippriority")
    o = o.filter(col("o_orderdate") < lit(cutoff, DataType.DATE32))
    li = _rd(s, t, "lineitem").select(
        "l_orderkey", "l_extendedprice", "l_discount", "l_shipdate")
    li = li.filter(col("l_shipdate") > lit(cutoff, DataType.DATE32))
    j = _join(o, c, "o_custkey", "c_custkey")
    j = j.join(_rename(li, l_orderkey="o_orderkey"), on="o_orderkey",
               how="inner")
    rev = (col("l_extendedprice").cast(DataType.FLOAT64)
           * (lit(1.0) - col("l_discount").cast(DataType.FLOAT64)))
    g = (j.with_column("rev", rev)
         .group_by("o_orderkey", "o_orderdate", "o_shippriority")
         .agg(F.sum(col("rev")).alias("revenue")))
    return (g.sort(col("revenue").desc(), col("o_orderdate").asc(),
                   col("o_orderkey").asc())
            .limit(10).collect())


def _q3_oracle(a):
    p = _pd(a)
    cutoff = np.datetime64("1995-03-15")
    c = p["customer"]
    c = c[c.c_mktsegment == "BUILDING"][["c_custkey"]]
    o = p["orders"]
    o = o[o.o_orderdate < cutoff]
    li = p["lineitem"]
    li = li[li.l_shipdate > cutoff]
    j = o.merge(c, left_on="o_custkey", right_on="c_custkey")
    j = j.merge(li, left_on="o_orderkey", right_on="l_orderkey")
    j["rev"] = j.l_extendedprice.astype(float) \
        * (1.0 - j.l_discount.astype(float))
    g = j.groupby(["o_orderkey", "o_orderdate", "o_shippriority"])[
        "rev"].sum().reset_index(name="revenue")
    g = g.sort_values(["revenue", "o_orderdate", "o_orderkey"],
                      ascending=[False, True, True]).head(10)
    return pa.Table.from_pandas(g.reset_index(drop=True),
                                preserve_index=False)


_q("q3", "shipping-priority revenue (BUILDING segment top 10)")(
    (_q3_run, _q3_oracle))


# --- q6: forecast revenue change ------------------------------------------

def _q6_run(s, t):
    li = _rd(s, t, "lineitem").select(
        "l_shipdate", "l_quantity", "l_extendedprice", "l_discount")
    lo, hi = int(_D1994), int(_D1995)
    disc = col("l_discount").cast(DataType.FLOAT64)
    j = li.filter((col("l_shipdate") >= lit(lo, DataType.DATE32))
                  & (col("l_shipdate") < lit(hi, DataType.DATE32))
                  & (disc >= lit(0.03)) & (disc <= lit(0.07))
                  & (col("l_quantity") < 24))
    rev = col("l_extendedprice").cast(DataType.FLOAT64) * disc
    return (j.with_column("rev", rev).group_by()
            .agg(F.sum(col("rev")).alias("revenue")).collect())


def _q6_oracle(a):
    p = _pd(a)
    li = p["lineitem"]
    d = li.l_discount.astype(float)
    sel = li[(li.l_shipdate >= np.datetime64("1994-01-01"))
             & (li.l_shipdate < np.datetime64("1995-01-01"))
             & (d >= 0.03) & (d <= 0.07) & (li.l_quantity < 24)]
    rev = (sel.l_extendedprice.astype(float)
           * sel.l_discount.astype(float)).sum()
    return pa.Table.from_pydict({"revenue": [float(rev)]})


_q("q6", "forecast revenue change (selective filter-agg)")(
    (_q6_run, _q6_oracle))
