"""Integration-harness runner.

CLI analogue of the reference's auron-it Main (reference:
dev/auron-it/.../Main.scala:60-128, flags --auron-only/--result-check):

    python -m auron_tpu.it.runner [--scale 1.0] [--queries q01,q03] [--data DIR]

Exit code 0 iff every query's result matches the pandas oracle.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

# The integration harness is a CORRECTNESS gate: run it on the virtual
# 8-device CPU mesh (like tests/conftest.py) unless the caller explicitly
# picks a platform (AURON_IT_PLATFORM=ambient). Setting env here helps
# plain interpreters; a hostile accelerator site hook that patches jax's
# backend init ignores JAX_PLATFORMS entirely, so main() additionally
# re-execs under a sanitized env when such a hook is on PYTHONPATH
# (see _maybe_reexec_cpu; same contract as bench.py's CPU fallback).
#: the ambient platform BEFORE this module pins cpu — if jax was already
#: imported (package __init__ chains can do it) the ambient value is
#: latched into jax.config and only a re-exec can undo it
_AMBIENT_JAX_PLATFORMS = os.environ.get("JAX_PLATFORMS", "")
if os.environ.get("AURON_IT_PLATFORM", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    _xf = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _xf:
        os.environ["XLA_FLAGS"] = (
            _xf + " --xla_force_host_platform_device_count=8").strip()

from auron_tpu.it.comparator import ComparisonResult, QueryResultComparator
from auron_tpu.it.queries import QUERIES
from auron_tpu.it.tpcds_data import generate, load_pandas


def _fresh_session():
    from auron_tpu.frontend.session import Session
    return Session()


def run_query(query, tables, pd_tables,
              comparator=None) -> ComparisonResult:
    comparator = comparator or QueryResultComparator()
    session = _fresh_session()
    t0 = time.perf_counter()
    try:
        got = query.run(session, tables)
    except Exception as e:  # a crash is a FAIL with the error recorded
        import traceback
        return ComparisonResult(query.name, False, 0,
                                error=traceback.format_exc(limit=8))
    elapsed = time.perf_counter() - t0
    expected = query.expected(pd_tables)
    res = comparator.compare(query.name, got, expected)
    res.elapsed_s = round(elapsed, 3)
    return res


def run_all(data_dir=None, scale: float = 1.0, names=None,
            verbose: bool = True) -> list[ComparisonResult]:
    if data_dir is None:
        data_dir = tempfile.mkdtemp(prefix="auron_it_")
    tables = generate(data_dir, scale=scale)
    pd_tables = load_pandas(tables)
    results = []
    for q in QUERIES:
        if names and q.name not in names and q.name.split("_")[0] not in names:
            continue
        res = run_query(q, tables, pd_tables)
        results.append(res)
        if verbose:
            took = getattr(res, "elapsed_s", None)
            suffix = f" ({took}s)" if took is not None else ""
            print(res.report() + suffix, flush=True)
    return results


def _defloat_decimals(tbl):
    """Cast decimal columns to float64 so engine decimals (exact,
    Spark-typed) and the Acero oracle's mixed decimal/float outputs
    compare under the double tolerance. Money sums at TPC-DS scale stay
    within float64's 2^53 exact-integer range."""
    import pyarrow as pa
    cols = []
    for i, f in enumerate(tbl.schema):
        c = tbl.column(i)
        if pa.types.is_decimal(f.type):
            c = c.cast(pa.float64())
        cols.append(c)
    return pa.table({f.name: c for f, c in zip(tbl.schema, cols)})


def _run_suite(queries, tables, arrow, comparator, names=None,
               verbose: bool = True, budget_note: bool = True):
    """Shared per-query loop: fresh session, compile attribution, oracle
    diff, verbose report, suite compile-budget summary."""
    from auron_tpu.utils import compile_stats
    results = []
    suite_start = compile_stats.snapshot()
    clears_start = compile_stats.clears()
    for q in queries:
        if names and q.name not in names:
            continue
        compile_stats.maybe_clear()   # bound live programs per process
        session = _fresh_session()
        t0 = time.perf_counter()
        c0 = compile_stats.snapshot()
        try:
            got = q.run(session, tables)
        except Exception:
            import traceback
            results.append(ComparisonResult(
                q.name, False, 0, error=traceback.format_exc(limit=8)))
            if verbose:
                print(results[-1].report(), flush=True)
            continue
        elapsed = time.perf_counter() - t0
        cd = compile_stats.delta(c0)
        expected = q.oracle(arrow)
        res = comparator.compare(q.name, _defloat_decimals(got),
                                 _defloat_decimals(expected))
        res.elapsed_s = round(elapsed, 3)
        res.compiles = cd.count
        res.compile_s = round(cd.seconds, 3)
        results.append(res)
        if verbose:
            print(res.report() + f" ({res.elapsed_s}s, "
                  f"{cd.count} compiles {res.compile_s}s)", flush=True)
    total = compile_stats.delta(suite_start)
    if verbose and budget_note:
        wall = sum(getattr(r, "elapsed_s", 0) or 0 for r in results)
        n_clears = compile_stats.clears() - clears_start
        note = ("a second run in this process should compile ~0"
                if n_clears == 0 else
                f"{n_clears} cache clears hit the auron.max_live_programs "
                "ceiling, so warm reruns recompile cleared kernels")
        print(f"compile budget: {total.count} XLA programs, "
              f"{total.seconds:.1f}s compiling / {wall:.1f}s total "
              f"({note})", flush=True)
    return results


def run_tpcds(data_dir=None, scale: float = 1.0, names=None,
              verbose: bool = True) -> list[ComparisonResult]:
    """The real-schema TPC-DS gate: 99 genuine TPC-DS query shapes over a
    scale-1.0 = 1M-fact-row dataset, diffed against the pyarrow/Acero
    oracle (reference gate: .github/workflows/tpcds-reusable.yml:70-83)."""
    from auron_tpu.it.tpcds import generate, load_arrow
    from auron_tpu.it.tpcds_queries import QUERIES as TQ
    if data_dir is None:
        data_dir = tempfile.mkdtemp(prefix="auron_tpcds_")
    tables = generate(data_dir, scale=scale)
    arrow = load_arrow(tables)
    return _run_suite(TQ, tables, arrow,
                      QueryResultComparator(double_rel_tol=1e-7,
                                            double_abs_tol=1e-6),
                      names=names, verbose=verbose)


def run_tpch(data_dir=None, scale: float = 1.0, names=None,
             verbose: bool = True) -> list[ComparisonResult]:
    """TPC-H q1/q3/q5/q6/q9/q18 (incl. the BASELINE.md join-heavy
    targets) vs pandas
    oracles."""
    from auron_tpu.it.tpch import generate, load_arrow
    from auron_tpu.it.tpch_queries import QUERIES as HQ
    if data_dir is None:
        data_dir = tempfile.mkdtemp(prefix="auron_tpch_")
    tables = generate(data_dir, scale=scale)
    arrow = load_arrow(tables)
    return _run_suite(HQ, tables, arrow,
                      QueryResultComparator(double_rel_tol=1e-7,
                                            double_abs_tol=1e-5),
                      names=names, verbose=verbose)


def _maybe_reexec_cpu(argv) -> int | None:
    """If an accelerator site hook rode in on PYTHONPATH, its patched
    backend init would drag the gate onto the (possibly wedged) remote
    accelerator no matter what JAX_PLATFORMS says — re-exec this exact
    command under a sanitized CPU env instead. Returns the child's exit
    code, or None when no re-exec is needed."""
    import subprocess
    from auron_tpu.utils.envsafe import cpu_child_env
    if os.environ.get("AURON_IT_PLATFORM", "cpu") != "cpu" \
            or os.environ.get("_AURON_IT_SANITIZED") == "1":
        return None
    env = cpu_child_env(os.getcwd(), n_devices=8)
    ambient_noncpu = _AMBIENT_JAX_PLATFORMS not in ("", "cpu")
    if env.get("PYTHONPATH") == os.environ.get("PYTHONPATH") \
            and not ambient_noncpu:
        return None   # nothing stripped: the in-process pinning suffices
    # ambient JAX_PLATFORMS pointed at an accelerator: if anything
    # imported jax before this module pinned cpu, the value is latched
    # into jax.config — only a fresh process can unlatch it
    env["_AURON_IT_SANITIZED"] = "1"
    args = list(argv) if argv is not None else sys.argv[1:]
    proc = subprocess.run(
        [sys.executable, "-m", "auron_tpu.it.runner", *args], env=env)
    return proc.returncode


def main(argv=None) -> int:
    import argparse
    rc = _maybe_reexec_cpu(argv)
    if rc is not None:
        return rc
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--suite", default="synth",
                    choices=["synth", "tpcds", "tpch"],
                    help="synth: the synthetic-star queries; tpcds: the "
                         "real-schema TPC-DS battery (see tpcds_queries) "
                         "vs the Acero oracle; "
                         "tpch: q1/q3/q5/q6/q9/q18 incl. the BASELINE targets")
    ap.add_argument("--queries", default="",
                    help="comma-separated names (q01 or full name)")
    ap.add_argument("--data", default=None,
                    help="reuse/create dataset in this directory")
    args = ap.parse_args(argv)
    names = [n.strip() for n in args.queries.split(",") if n.strip()] or None
    if args.suite == "tpcds":
        results = run_tpcds(data_dir=args.data, scale=args.scale,
                            names=names)
    elif args.suite == "tpch":
        results = run_tpch(data_dir=args.data, scale=args.scale,
                           names=names)
    else:
        results = run_all(data_dir=args.data, scale=args.scale, names=names)
    if not results:
        print(f"no queries matched --queries {args.queries!r} in suite "
              f"{args.suite!r} — nothing ran", file=sys.stderr)
        return 2
    failed = [r for r in results if not r.ok]
    print(f"{len(results) - len(failed)}/{len(results)} queries passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
