"""Integration-harness runner.

CLI analogue of the reference's auron-it Main (reference:
dev/auron-it/.../Main.scala:60-128, flags --auron-only/--result-check):

    python -m auron_tpu.it.runner [--scale 1.0] [--queries q01,q03] [--data DIR]

Exit code 0 iff every query's result matches the pandas oracle.
"""

from __future__ import annotations

import sys
import tempfile
import time

from auron_tpu.it.comparator import ComparisonResult, QueryResultComparator
from auron_tpu.it.queries import QUERIES
from auron_tpu.it.tpcds_data import generate, load_pandas


def _fresh_session():
    from auron_tpu.frontend.session import Session
    return Session()


def run_query(query, tables, pd_tables,
              comparator=None) -> ComparisonResult:
    comparator = comparator or QueryResultComparator()
    session = _fresh_session()
    t0 = time.perf_counter()
    try:
        got = query.run(session, tables)
    except Exception as e:  # a crash is a FAIL with the error recorded
        import traceback
        return ComparisonResult(query.name, False, 0,
                                error=traceback.format_exc(limit=8))
    elapsed = time.perf_counter() - t0
    expected = query.expected(pd_tables)
    res = comparator.compare(query.name, got, expected)
    res.elapsed_s = round(elapsed, 3)
    return res


def run_all(data_dir=None, scale: float = 1.0, names=None,
            verbose: bool = True) -> list[ComparisonResult]:
    if data_dir is None:
        data_dir = tempfile.mkdtemp(prefix="auron_it_")
    tables = generate(data_dir, scale=scale)
    pd_tables = load_pandas(tables)
    results = []
    for q in QUERIES:
        if names and q.name not in names and q.name.split("_")[0] not in names:
            continue
        res = run_query(q, tables, pd_tables)
        results.append(res)
        if verbose:
            took = getattr(res, "elapsed_s", None)
            suffix = f" ({took}s)" if took is not None else ""
            print(res.report() + suffix, flush=True)
    return results


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--queries", default="",
                    help="comma-separated names (q01 or full name)")
    ap.add_argument("--data", default=None,
                    help="reuse/create dataset in this directory")
    args = ap.parse_args(argv)
    names = [n.strip() for n in args.queries.split(",") if n.strip()] or None
    results = run_all(data_dir=args.data, scale=args.scale, names=names)
    failed = [r for r in results if not r.ok]
    print(f"{len(results) - len(failed)}/{len(results)} queries passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
