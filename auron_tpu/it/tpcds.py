"""Real-schema TPC-DS dataset generator.

Emits the TPC-DS star schema (fact + dimension tables with the spec's
table/column names and types — money as decimal(7,2), surrogate-key
joins, nullable foreign keys) at a row scale where ``scale=1.0`` is a
1M-row store_sales fact table. Deterministic per (seed, scale); written
as multi-file parquet so scans have real input splits.

This backs the ``tpcds`` integration suite (tpcds_queries.py): the same
query shapes the reference gates on with its 1 GB TPC-DS checkout
(reference: .github/workflows/tpcds-reusable.yml:70-83,
dev/auron-it/.../QueryResultComparator.scala:21-100). dsdgen itself is
not in this image, so the generator reproduces the *schema and
distribution shape* (skewed FKs, null FK fractions, seasonal dates,
price/cost relationships), not dsdgen's exact rows.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

FACT_FILES = 8

#: TPC-DS Julian-ish date surrogate keys: d_date_sk for 1998-01-01
DATE_SK0 = 2450815
N_DATES = 365 * 5 + 2          # 1998-01-01 .. 2002-12-31


def _money_from_cents(cents, precision=7, scale=2):
    """decimal128(p, s) array straight from unscaled int64 cents — the
    arrow buffer layout is 128-bit little-endian unscaled ints, so two
    int64 limbs per value (high limb = sign extension)."""
    cents = np.asarray(cents, np.int64)
    limbs = np.zeros((len(cents), 2), np.int64)
    limbs[:, 0] = cents
    limbs[:, 1] = cents >> 63          # arithmetic: 0 or -1
    return pa.Array.from_buffers(
        pa.decimal128(precision, scale), len(cents),
        [None, pa.py_buffer(np.ascontiguousarray(limbs).tobytes())])


def _money(rng, n, lo=0.5, hi=300.0):
    return _money_from_cents(rng.integers(int(lo * 100), int(hi * 100), n))


def _nullable_fk(rng, n, n_dim, null_frac=0.03):
    fk = rng.integers(1, n_dim + 1, n)
    mask = rng.random(n) < null_frac
    return pa.array(np.where(mask, 0, fk), pa.int64()).filter(
        pa.array(np.ones(n, bool))) if False else pa.array(
        [None if m else int(v) for v, m in zip(fk, mask)], pa.int64())


def _fk_array(rng, n, n_dim, null_frac=0.0, skew=False):
    """Surrogate-key FK column 1..n_dim, optionally zipf-skewed, with a
    null fraction (TPC-DS fact FKs are nullable)."""
    if skew:
        ranks = rng.zipf(1.3, n).astype(np.int64)
        fk = (ranks - 1) % n_dim + 1
    else:
        fk = rng.integers(1, n_dim + 1, n).astype(np.int64)
    if null_frac:
        mask = rng.random(n) < null_frac
        out = fk.astype(object)
        out[mask] = None
        return pa.array(out.tolist(), pa.int64())
    return pa.array(fk, pa.int64())


def _write(root, name, table, n_files=1):
    paths = []
    n = table.num_rows
    per = max(1, (n + n_files - 1) // n_files)
    for i in range(0, max(n_files, 1)):
        lo = i * per
        if lo >= n and i > 0:
            break
        chunk = table.slice(lo, per)
        p = os.path.join(root, f"{name}_{i}.parquet")
        pq.write_table(chunk, p, row_group_size=64 * 1024)
        paths.append(p)
    return paths


def generate(root: str, scale: float = 1.0, seed: int = 7) -> dict:
    """Write the dataset; returns {table: [parquet files]}."""
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    out: dict[str, list[str]] = {}

    n_ss = int(1_000_000 * scale)
    n_sr = n_ss // 10
    n_cs = n_ss // 2
    n_ws = n_ss // 4
    n_inv = n_ss // 2
    n_item = max(int(18_000 * min(scale, 1.0)), 200)
    n_cust = max(int(100_000 * min(scale, 1.0)), 500)
    n_addr = max(n_cust // 2, 250)
    n_store = max(int(12 * max(scale, 0.5)), 6)
    n_wh = 5
    n_web = 6
    n_cc = 4
    n_cd = 1920     # TPC-DS customer_demographics cross product size class
    n_hd = 7200

    # -- date_dim -----------------------------------------------------------
    doff = np.arange(N_DATES)
    base = np.datetime64("1998-01-01")
    dates = base + doff
    dow = ((doff + 3) % 7)           # 1998-01-01 was a Thursday
    day_names = np.array(["Monday", "Tuesday", "Wednesday", "Thursday",
                          "Friday", "Saturday", "Sunday"])
    years = dates.astype("datetime64[Y]").astype(int) + 1970
    months = dates.astype("datetime64[M]").astype(int) % 12 + 1
    dom = (dates - dates.astype("datetime64[M]")).astype(int) + 1
    date_dim = pa.table({
        "d_date_sk": pa.array(DATE_SK0 + doff, pa.int64()),
        "d_date": pa.array(dates.astype("datetime64[D]"), pa.date32()),
        "d_year": pa.array(years.astype(np.int64)),
        "d_moy": pa.array(months.astype(np.int64)),
        "d_dom": pa.array(dom.astype(np.int64)),
        "d_qoy": pa.array(((months - 1) // 3 + 1).astype(np.int64)),
        "d_day_name": pa.array(day_names[dow]),
        "d_month_seq": pa.array(((years - 1998) * 12 + months - 1)
                                .astype(np.int64)),
        # week boundary on Monday; base offset keeps values dsdgen-like
        "d_week_seq": pa.array(((doff + 3) // 7 + 5270).astype(np.int64)),
    })
    out["date_dim"] = _write(root, "date_dim", date_dim)

    # -- time_dim -----------------------------------------------------------
    tsk = np.arange(86400 // 60)     # one row per minute
    time_dim = pa.table({
        "t_time_sk": pa.array(tsk, pa.int64()),
        "t_hour": pa.array(tsk // 60, pa.int64()),
        "t_minute": pa.array(tsk % 60, pa.int64()),
    })
    out["time_dim"] = _write(root, "time_dim", time_dim)

    # -- item ---------------------------------------------------------------
    cats = np.array(["Books", "Electronics", "Home", "Jewelry", "Men",
                     "Music", "Shoes", "Sports", "Women", "Children"])
    isk = np.arange(1, n_item + 1)
    cat_idx = rng.integers(0, len(cats), n_item)
    class_id = rng.integers(1, 17, n_item)
    brand_id = rng.integers(1, 1000, n_item)
    item = pa.table({
        "i_item_sk": pa.array(isk, pa.int64()),
        "i_item_id": pa.array([f"AAAAAAAA{k:08d}" for k in isk]),
        "i_item_desc": pa.array([f"item desc {k % 977}" for k in isk]),
        "i_brand_id": pa.array(brand_id, pa.int64()),
        "i_brand": pa.array([f"brand#{b}" for b in brand_id]),
        "i_class_id": pa.array(class_id, pa.int64()),
        "i_class": pa.array([f"class{c:02d}" for c in class_id]),
        "i_category_id": pa.array(cat_idx.astype(np.int64) + 1),
        "i_category": pa.array(cats[cat_idx]),
        "i_manufact_id": pa.array(rng.integers(1, 1000, n_item), pa.int64()),
        "i_manufact": pa.array([f"manufact#{m}" for m in
                                rng.integers(1, 100, n_item)]),
        "i_manager_id": pa.array(rng.integers(1, 100, n_item), pa.int64()),
        "i_current_price": _money(rng, n_item, 0.09, 99.0),
        "i_color": pa.array(np.array(
            ["red", "blue", "green", "black", "white", "plum",
             "orchid", "slate"])[rng.integers(0, 8, n_item)]),
        "i_size": pa.array(np.array(
            ["small", "medium", "large", "extra large",
             "economy"])[rng.integers(0, 5, n_item)]),
        "i_units": pa.array(np.array(
            ["Each", "Dozen", "Case", "Pound"])[rng.integers(
                0, 4, n_item)]),
    })
    out["item"] = _write(root, "item", item)

    # -- customer & co ------------------------------------------------------
    csk = np.arange(1, n_cust + 1)
    firsts = np.array(["James", "Mary", "John", "Ana", "Wei", "Omar",
                       "Kai", "Zoe", "Ivan", "Lena"])
    lasts = np.array(["Smith", "Lee", "Garcia", "Khan", "Chen", "Olsen",
                      "Patel", "Okafor", "Ross", "Kim"])
    customer = pa.table({
        "c_customer_sk": pa.array(csk, pa.int64()),
        "c_customer_id": pa.array([f"AAAAAAAA{k:08d}" for k in csk]),
        "c_current_cdemo_sk": _fk_array(rng, n_cust, n_cd, 0.02),
        "c_current_hdemo_sk": _fk_array(rng, n_cust, n_hd, 0.02),
        "c_current_addr_sk": _fk_array(rng, n_cust, n_addr),
        "c_first_name": pa.array(firsts[rng.integers(0, 10, n_cust)]),
        "c_last_name": pa.array(lasts[rng.integers(0, 10, n_cust)]),
        "c_birth_month": pa.array(rng.integers(1, 13, n_cust), pa.int64()),
        "c_birth_year": pa.array(rng.integers(1924, 1993, n_cust),
                                 pa.int64()),
        "c_birth_country": pa.array(np.array(
            ["UNITED STATES", "CANADA", "MEXICO", "BRAZIL", "JAPAN",
             "GERMANY"])[rng.integers(0, 6, n_cust)]),
    })
    out["customer"] = _write(root, "customer", customer, 2)

    states = np.array(["CA", "TX", "NY", "WA", "GA", "OH", "IL", "MI",
                       "TN", "SD", "KY", "FL"])
    cities = np.array(["Fairview", "Midway", "Oak Grove", "Five Points",
                       "Centerville", "Liberty", "Georgetown", "Salem",
                       "Riverside", "Greenfield"])
    counties = np.array(["Ziebach County", "Walker County", "Daviess County",
                         "Barrow County", "Fairfield County",
                         "Luce County", "Richland County", "Bronx County"])
    ask = np.arange(1, n_addr + 1)
    customer_address = pa.table({
        "ca_address_sk": pa.array(ask, pa.int64()),
        "ca_city": pa.array(cities[rng.integers(0, len(cities), n_addr)]),
        "ca_county": pa.array(counties[rng.integers(0, len(counties),
                                                    n_addr)]),
        "ca_state": pa.array(states[rng.integers(0, len(states), n_addr)]),
        "ca_zip": pa.array([f"{z:05d}" for z in
                            rng.integers(10000, 99999, n_addr)]),
        "ca_country": pa.array(["United States"] * n_addr),
        "ca_gmt_offset": pa.array(rng.choice([-5.0, -6.0, -7.0, -8.0],
                                             n_addr), pa.float64()),
    })
    out["customer_address"] = _write(root, "customer_address",
                                     customer_address)

    cd_sk = np.arange(1, n_cd + 1)
    genders = np.array(["M", "F"])
    marital = np.array(["M", "S", "D", "W", "U"])
    edu = np.array(["Primary", "Secondary", "College", "2 yr Degree",
                    "4 yr Degree", "Advanced Degree", "Unknown"])
    customer_demographics = pa.table({
        "cd_demo_sk": pa.array(cd_sk, pa.int64()),
        "cd_gender": pa.array(genders[(cd_sk - 1) % 2]),
        "cd_marital_status": pa.array(marital[(cd_sk - 1) // 2 % 5]),
        "cd_education_status": pa.array(edu[(cd_sk - 1) // 10 % 7]),
        "cd_dep_count": pa.array(((cd_sk - 1) // 70 % 7).astype(np.int64)),
    })
    out["customer_demographics"] = _write(root, "customer_demographics",
                                          customer_demographics)

    hd_sk = np.arange(1, n_hd + 1)
    buy_pot = np.array([">10000", "5001-10000", "1001-5000", "501-1000",
                        "0-500", "Unknown"])
    household_demographics = pa.table({
        "hd_demo_sk": pa.array(hd_sk, pa.int64()),
        "hd_income_band_sk": pa.array(((hd_sk - 1) % 20 + 1)
                                      .astype(np.int64)),
        "hd_buy_potential": pa.array(buy_pot[(hd_sk - 1) % 6]),
        "hd_dep_count": pa.array(((hd_sk - 1) // 6 % 10).astype(np.int64)),
        "hd_vehicle_count": pa.array(((hd_sk - 1) // 60 % 5)
                                     .astype(np.int64) - 1),
    })
    out["household_demographics"] = _write(root, "household_demographics",
                                           household_demographics)

    ssk = np.arange(1, n_store + 1)
    store = pa.table({
        "s_store_sk": pa.array(ssk, pa.int64()),
        "s_store_id": pa.array([f"AAAAAAAA{k:08d}" for k in ssk]),
        "s_store_name": pa.array([f"store_{chr(97 + (k - 1) % 26)}"
                                  for k in ssk]),
        "s_number_employees": pa.array(rng.integers(200, 300, n_store),
                                       pa.int64()),
        "s_city": pa.array(cities[rng.integers(0, len(cities), n_store)]),
        "s_county": pa.array(counties[rng.integers(0, len(counties),
                                                   n_store)]),
        "s_state": pa.array(states[rng.integers(0, len(states), n_store)]),
        "s_zip": pa.array([f"{z:05d}" for z in
                           rng.integers(10000, 99999, n_store)]),
        "s_gmt_offset": pa.array(rng.choice([-5.0, -6.0], n_store),
                                 pa.float64()),
        "s_market_id": pa.array(rng.integers(1, 11, n_store), pa.int64()),
    })
    out["store"] = _write(root, "store", store)

    n_promo = 300
    psk = np.arange(1, n_promo + 1)
    yn = np.array(["Y", "N"])
    promotion = pa.table({
        "p_promo_sk": pa.array(psk, pa.int64()),
        "p_promo_id": pa.array([f"AAAAAAAA{k:08d}" for k in psk]),
        "p_channel_dmail": pa.array(yn[rng.integers(0, 2, n_promo)]),
        "p_channel_email": pa.array(yn[rng.integers(0, 2, n_promo)]),
        "p_channel_tv": pa.array(yn[rng.integers(0, 2, n_promo)]),
    })
    out["promotion"] = _write(root, "promotion", promotion)

    wsk = np.arange(1, n_wh + 1)
    warehouse = pa.table({
        "w_warehouse_sk": pa.array(wsk, pa.int64()),
        "w_warehouse_name": pa.array([f"warehouse {k}" for k in wsk]),
        "w_warehouse_sq_ft": pa.array(rng.integers(50_000, 1_000_000, n_wh),
                                      pa.int64()),
    })
    out["warehouse"] = _write(root, "warehouse", warehouse)

    sm_types = np.array(["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR",
                         "TWO DAY", "LIBRARY"])
    smk = np.arange(1, 21)
    ship_mode = pa.table({
        "sm_ship_mode_sk": pa.array(smk, pa.int64()),
        "sm_type": pa.array(sm_types[(smk - 1) % 6]),
        "sm_code": pa.array([f"code{k % 4}" for k in smk]),
    })
    out["ship_mode"] = _write(root, "ship_mode", ship_mode)

    cck = np.arange(1, n_cc + 1)
    call_center = pa.table({
        "cc_call_center_sk": pa.array(cck, pa.int64()),
        "cc_name": pa.array([f"cc_{k}" for k in cck]),
    })
    out["call_center"] = _write(root, "call_center", call_center)

    webk = np.arange(1, n_web + 1)
    web_site = pa.table({
        "web_site_sk": pa.array(webk, pa.int64()),
        "web_name": pa.array([f"site_{k}" for k in webk]),
    })
    out["web_site"] = _write(root, "web_site", web_site)

    # -- store_sales (the 1M-row fact) --------------------------------------
    # seasonal date skew: Nov/Dec holidays sell more (like dsdgen)
    date_w = 1.0 + 0.8 * np.isin(months, (11, 12))
    date_p = date_w / date_w.sum()
    # a TICKET is one basket: every line of a ticket shares customer,
    # store, household, address, date and time (dsdgen's coherence —
    # without it the per-ticket queries q68/q73/q79 group nothing)
    n_tickets = max(n_ss // 6, 2)
    tk_date = rng.choice(N_DATES, n_tickets, p=date_p).astype(np.int64)
    tk_time = rng.integers(0, 1440, n_tickets)
    tk_cust = rng.integers(1, n_cust + 1, n_tickets)
    tk_cust_null = rng.random(n_tickets) < 0.02
    tk_hd = rng.integers(1, n_hd + 1, n_tickets)
    tk_addr = rng.integers(1, n_addr + 1, n_tickets)
    tk_store = rng.integers(1, n_store + 1, n_tickets)
    tickets = rng.integers(0, n_tickets, n_ss).astype(np.int64)
    sold_date = tk_date[tickets]
    qty = rng.integers(1, 101, n_ss)
    wholesale_c = rng.integers(100, 10_000, n_ss)         # cents
    markup = 1.0 + rng.random(n_ss) * 1.5
    list_c = (wholesale_c * markup).astype(np.int64)
    discount = rng.choice([1.0, 1.0, 1.0, 0.9, 0.8, 0.5], n_ss)
    sales_c = (list_c * discount).astype(np.int64)
    coupon_c = np.where(rng.random(n_ss) < 0.1,
                        (sales_c * 0.2).astype(np.int64), 0)
    ss_cust = pa.array(tk_cust[tickets], pa.int64(),
                       mask=tk_cust_null[tickets])
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(DATE_SK0 + sold_date, pa.int64()),
        "ss_sold_time_sk": pa.array(tk_time[tickets], pa.int64()),
        "ss_item_sk": _fk_array(rng, n_ss, n_item, skew=True),
        "ss_customer_sk": ss_cust,
        "ss_cdemo_sk": _fk_array(rng, n_ss, n_cd, 0.02),
        "ss_hdemo_sk": pa.array(tk_hd[tickets], pa.int64()),
        "ss_addr_sk": pa.array(tk_addr[tickets], pa.int64()),
        "ss_store_sk": pa.array(tk_store[tickets], pa.int64()),
        "ss_promo_sk": _fk_array(rng, n_ss, n_promo, 0.05),
        "ss_ticket_number": pa.array(tickets + 1, pa.int64()),
        "ss_quantity": pa.array(qty.astype(np.int64)),
        "ss_wholesale_cost": _money_from_cents(wholesale_c),
        "ss_list_price": _money_from_cents(list_c),
        "ss_sales_price": _money_from_cents(sales_c),
        "ss_ext_sales_price": _money_from_cents(sales_c * qty),
        "ss_ext_list_price": _money_from_cents(list_c * qty),
        "ss_ext_wholesale_cost": _money_from_cents(wholesale_c * qty),
        "ss_coupon_amt": _money_from_cents(coupon_c),
        "ss_net_paid": _money_from_cents(sales_c * qty - coupon_c),
        "ss_net_profit": _money_from_cents(
            (sales_c - wholesale_c) * qty - coupon_c),
    })
    out["store_sales"] = _write(root, "store_sales", store_sales, FACT_FILES)

    # -- store_returns ------------------------------------------------------
    # returns reference real sales rows so sr⋈ss joins hit
    ret_idx = rng.choice(n_ss, n_sr, replace=False)
    ret_lag = rng.integers(1, 90, n_sr)
    ret_amt = (sales_c[ret_idx] * rng.integers(1, qty[ret_idx] + 1)
               * rng.choice([1.0, 0.5], n_sr)).astype(np.int64)
    sr_cust = pa.array(ss_cust.to_pylist(), pa.int64()).take(
        pa.array(ret_idx, pa.int64()))
    store_returns = pa.table({
        "sr_returned_date_sk": pa.array(
            np.minimum(DATE_SK0 + sold_date[ret_idx] + ret_lag,
                       DATE_SK0 + N_DATES - 1), pa.int64()),
        "sr_item_sk": store_sales.column("ss_item_sk").take(
            pa.array(ret_idx, pa.int64())),
        "sr_customer_sk": sr_cust,
        "sr_ticket_number": pa.array(tickets[ret_idx] + 1, pa.int64()),
        "sr_store_sk": store_sales.column("ss_store_sk").take(
            pa.array(ret_idx, pa.int64())),
        "sr_return_quantity": pa.array(
            rng.integers(1, 50, n_sr).astype(np.int64)),
        "sr_return_amt": _money_from_cents(ret_amt),
        "sr_fee": _money(rng, n_sr, 0.5, 100.0),
        "sr_net_loss": _money(rng, n_sr, 0.5, 300.0),
    })
    out["store_returns"] = _write(root, "store_returns", store_returns, 2)

    # -- catalog_sales ------------------------------------------------------
    # ORDER coherence (like ss tickets): lines of one order share the
    # customer, addresses, call center and date — q16/q94-class queries
    # group and EXISTS-probe on order_number
    n_orders = max(n_cs // 4, 2)
    ord_date = rng.choice(N_DATES, n_orders, p=date_p).astype(np.int64)
    ord_cust = rng.integers(1, n_cust + 1, n_orders)
    ord_cust_null = rng.random(n_orders) < 0.02
    ord_addr = rng.integers(1, n_addr + 1, n_orders)
    ord_ship_addr = rng.integers(1, n_addr + 1, n_orders)
    ord_cc = rng.integers(1, n_cc + 1, n_orders)
    cs_ord = rng.integers(0, n_orders, n_cs).astype(np.int64)
    cs_date = ord_date[cs_ord]
    cs_qty = rng.integers(1, 101, n_cs)
    cs_whole = rng.integers(100, 10_000, n_cs)
    cs_list = rng.integers(100, 30_000, n_cs)
    cs_sales = (cs_list * rng.choice([1.0, 0.9, 0.7], n_cs)).astype(np.int64)
    cs_coupon = np.where(rng.random(n_cs) < 0.08,
                         (cs_sales * 0.15).astype(np.int64), 0)
    cs_disc = np.maximum(cs_list - cs_sales, 0) * cs_qty
    cs_cust = pa.array(ord_cust[cs_ord], pa.int64(),
                       mask=ord_cust_null[cs_ord])
    catalog_sales = pa.table({
        "cs_sold_date_sk": pa.array(DATE_SK0 + cs_date, pa.int64()),
        "cs_ship_date_sk": pa.array(
            DATE_SK0 + cs_date + rng.integers(1, 150, n_cs), pa.int64()),
        "cs_item_sk": _fk_array(rng, n_cs, n_item, skew=True),
        "cs_bill_customer_sk": cs_cust,
        "cs_bill_cdemo_sk": _fk_array(rng, n_cs, n_cd, 0.02),
        "cs_bill_addr_sk": pa.array(ord_addr[cs_ord], pa.int64()),
        "cs_ship_addr_sk": pa.array(ord_ship_addr[cs_ord], pa.int64()),
        "cs_warehouse_sk": _fk_array(rng, n_cs, n_wh, 0.01),
        "cs_ship_mode_sk": _fk_array(rng, n_cs, 20, 0.01),
        "cs_call_center_sk": pa.array(ord_cc[cs_ord], pa.int64()),
        "cs_promo_sk": _fk_array(rng, n_cs, n_promo, 0.05),
        "cs_order_number": pa.array(cs_ord + 1, pa.int64()),
        "cs_quantity": pa.array(cs_qty.astype(np.int64)),
        "cs_wholesale_cost": _money_from_cents(cs_whole),
        "cs_list_price": _money_from_cents(cs_list),
        "cs_sales_price": _money_from_cents(cs_sales),
        "cs_coupon_amt": _money_from_cents(cs_coupon),
        "cs_ext_discount_amt": _money_from_cents(cs_disc),
        "cs_ext_ship_cost": _money(rng, n_cs, 0.5, 200.0),
        "cs_ext_sales_price": _money_from_cents(cs_sales * cs_qty),
        "cs_net_profit": _money_from_cents(
            (cs_sales - cs_whole) * cs_qty - cs_coupon),
    })
    out["catalog_sales"] = _write(root, "catalog_sales", catalog_sales, 4)

    # -- catalog_returns (reference real cs order lines) --------------------
    n_cr = n_cs // 10
    cr_idx = rng.choice(n_cs, n_cr, replace=False)
    cr_lag = rng.integers(1, 90, n_cr)
    cr_amt = (cs_sales[cr_idx]
              * rng.integers(1, cs_qty[cr_idx] + 1)
              * rng.choice([1.0, 0.5], n_cr)).astype(np.int64)
    catalog_returns = pa.table({
        "cr_returned_date_sk": pa.array(
            np.minimum(DATE_SK0 + cs_date[cr_idx] + cr_lag,
                       DATE_SK0 + N_DATES - 1), pa.int64()),
        "cr_item_sk": catalog_sales.column("cs_item_sk").take(
            pa.array(cr_idx, pa.int64())),
        "cr_order_number": pa.array(cs_ord[cr_idx] + 1, pa.int64()),
        "cr_returning_customer_sk": cs_cust.take(
            pa.array(cr_idx, pa.int64())),
        "cr_returning_addr_sk": pa.array(ord_addr[cs_ord[cr_idx]],
                                         pa.int64()),
        "cr_call_center_sk": pa.array(ord_cc[cs_ord[cr_idx]], pa.int64()),
        "cr_return_quantity": pa.array(
            rng.integers(1, 50, n_cr).astype(np.int64)),
        "cr_return_amount": _money_from_cents(cr_amt),
        "cr_net_loss": _money(rng, n_cr, 0.5, 300.0),
    })
    out["catalog_returns"] = _write(root, "catalog_returns",
                                    catalog_returns, 2)

    # -- web_sales ----------------------------------------------------------
    n_worders = max(n_ws // 3, 2)
    wo_date = rng.choice(N_DATES, n_worders, p=date_p).astype(np.int64)
    wo_time = rng.integers(0, 1440, n_worders)
    wo_cust = rng.integers(1, n_cust + 1, n_worders)
    wo_cust_null = rng.random(n_worders) < 0.02
    wo_addr = rng.integers(1, n_addr + 1, n_worders)
    wo_ship_addr = rng.integers(1, n_addr + 1, n_worders)
    n_wp = 60
    ws_ord = rng.integers(0, n_worders, n_ws).astype(np.int64)
    ws_date = wo_date[ws_ord]
    ws_qty = rng.integers(1, 101, n_ws)
    ws_whole = rng.integers(100, 10_000, n_ws)
    ws_sales = rng.integers(100, 30_000, n_ws)
    ws_cust = pa.array(wo_cust[ws_ord], pa.int64(),
                       mask=wo_cust_null[ws_ord])
    web_sales = pa.table({
        "ws_sold_date_sk": pa.array(DATE_SK0 + ws_date, pa.int64()),
        "ws_sold_time_sk": pa.array(wo_time[ws_ord], pa.int64()),
        "ws_ship_date_sk": pa.array(
            DATE_SK0 + ws_date + rng.integers(1, 150, n_ws), pa.int64()),
        "ws_item_sk": _fk_array(rng, n_ws, n_item, skew=True),
        "ws_bill_customer_sk": ws_cust,
        "ws_bill_addr_sk": pa.array(wo_addr[ws_ord], pa.int64()),
        "ws_ship_addr_sk": pa.array(wo_ship_addr[ws_ord], pa.int64()),
        "ws_web_site_sk": _fk_array(rng, n_ws, n_web, 0.01),
        "ws_web_page_sk": _fk_array(rng, n_ws, n_wp, 0.01),
        "ws_ship_hdemo_sk": _fk_array(rng, n_ws, n_hd, 0.01),
        "ws_warehouse_sk": _fk_array(rng, n_ws, n_wh, 0.01),
        "ws_ship_mode_sk": _fk_array(rng, n_ws, 20, 0.01),
        "ws_order_number": pa.array(ws_ord + 1, pa.int64()),
        "ws_quantity": pa.array(ws_qty.astype(np.int64)),
        "ws_sales_price": _money_from_cents(ws_sales),
        "ws_ext_sales_price": _money_from_cents(ws_sales * ws_qty),
        "ws_ext_discount_amt": _money_from_cents(
            np.maximum((ws_sales * 0.3).astype(np.int64)
                       - rng.integers(0, 5000, n_ws), 0) * ws_qty),
        "ws_ext_ship_cost": _money(rng, n_ws, 0.5, 200.0),
        "ws_net_paid": _money_from_cents(ws_sales * ws_qty),
        "ws_net_profit": _money_from_cents((ws_sales - ws_whole) * ws_qty),
    })
    out["web_sales"] = _write(root, "web_sales", web_sales, 2)

    # -- web_returns (reference real ws order lines) ------------------------
    n_wr = n_ws // 10
    wr_idx = rng.choice(n_ws, n_wr, replace=False)
    wr_lag = rng.integers(1, 90, n_wr)
    wr_amt = (ws_sales[wr_idx] * rng.integers(1, ws_qty[wr_idx] + 1)
              * rng.choice([1.0, 0.5], n_wr)).astype(np.int64)
    web_returns = pa.table({
        "wr_returned_date_sk": pa.array(
            np.minimum(DATE_SK0 + ws_date[wr_idx] + wr_lag,
                       DATE_SK0 + N_DATES - 1), pa.int64()),
        "wr_item_sk": web_sales.column("ws_item_sk").take(
            pa.array(wr_idx, pa.int64())),
        "wr_order_number": pa.array(ws_ord[wr_idx] + 1, pa.int64()),
        "wr_returning_customer_sk": ws_cust.take(
            pa.array(wr_idx, pa.int64())),
        "wr_refunded_cdemo_sk": _fk_array(rng, n_wr, n_cd, 0.02),
        "wr_returning_cdemo_sk": _fk_array(rng, n_wr, n_cd, 0.02),
        "wr_refunded_addr_sk": pa.array(wo_addr[ws_ord[wr_idx]],
                                        pa.int64()),
        "wr_reason_sk": _fk_array(rng, n_wr, 35, 0.01),
        "wr_return_quantity": pa.array(
            rng.integers(1, 50, n_wr).astype(np.int64)),
        "wr_return_amt": _money_from_cents(wr_amt),
        "wr_fee": _money(rng, n_wr, 0.5, 100.0),
        "wr_net_loss": _money(rng, n_wr, 0.5, 300.0),
    })
    out["web_returns"] = _write(root, "web_returns", web_returns, 2)

    # -- small dims: web_page / income_band / reason ------------------------
    wpk = np.arange(1, n_wp + 1)
    web_page = pa.table({
        "wp_web_page_sk": pa.array(wpk, pa.int64()),
        "wp_char_count": pa.array(rng.integers(100, 8_000, n_wp),
                                  pa.int64()),
    })
    out["web_page"] = _write(root, "web_page", web_page)

    ibk = np.arange(1, 21)
    income_band = pa.table({
        "ib_income_band_sk": pa.array(ibk, pa.int64()),
        "ib_lower_bound": pa.array((ibk - 1) * 10_000, pa.int64()),
        "ib_upper_bound": pa.array(ibk * 10_000 - 1, pa.int64()),
    })
    out["income_band"] = _write(root, "income_band", income_band)

    rk = np.arange(1, 36)
    reasons = pa.table({
        "r_reason_sk": pa.array(rk, pa.int64()),
        "r_reason_desc": pa.array([f"reason {k}" for k in rk]),
    })
    out["reason"] = _write(root, "reason", reasons)

    # -- inventory ----------------------------------------------------------
    inventory = pa.table({
        "inv_date_sk": pa.array(
            DATE_SK0 + rng.integers(0, N_DATES, n_inv), pa.int64()),
        "inv_item_sk": _fk_array(rng, n_inv, n_item),
        "inv_warehouse_sk": _fk_array(rng, n_inv, n_wh),
        "inv_quantity_on_hand": pa.array(
            rng.integers(0, 1000, n_inv).astype(np.int64)),
    })
    out["inventory"] = _write(root, "inventory", inventory, 2)

    return out


def load_arrow(tables: dict) -> dict:
    """{name: pyarrow Table} for the oracle side."""
    out = {}
    for name, files in tables.items():
        out[name] = pa.concat_tables([pq.read_table(f) for f in files])
    return out
