"""Query-level E2E integration harness.

The analogue of the reference's ``dev/auron-it`` (reference:
dev/auron-it/src/main/scala/org/apache/auron/integration/Main.scala:60-128):
generate a TPC-DS-class dataset, run multi-operator queries through the
full proto → planner → exchange pipeline, and diff results against an
independent pandas/pyarrow computation with double tolerance (reference:
comparison/QueryResultComparator.scala:21-100).
"""

from auron_tpu.it.comparator import QueryResultComparator, ComparisonResult
from auron_tpu.it.queries import QUERIES

__all__ = ["QueryResultComparator", "ComparisonResult", "QUERIES"]
