"""TPC-H dataset generator (SF-scaled, coherent star/snowflake FKs).

BASELINE.md lists TPC-H q5/q9/q18 as join-heavy measurement targets;
this generator produces the eight TPC-H tables with the columns those
queries touch, with dbgen-like value domains (25 nations over 5 regions,
part names carrying color words, decimal(12,2) money, order dates over
1992-1998) at ``scale`` × 60k lineitems. Same design rules as the
TPC-DS generator (it/tpcds.py): numpy-vectorized, parquet on disk,
deterministic seed."""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
COLORS = ["green", "blue", "red", "ivory", "navy", "plum", "khaki",
          "puff", "snow", "rose"]
#: epoch days of 1992-01-01 and exclusive end 1998-08-03
DATE_LO = (np.datetime64("1992-01-01") - np.datetime64("1970-01-01")) \
    .astype(int)
DATE_HI = (np.datetime64("1998-08-03") - np.datetime64("1970-01-01")) \
    .astype(int)


def _money(rng, n, lo_c=100, hi_c=10_000_000):
    import decimal
    cents = rng.integers(lo_c, hi_c, n)
    return pa.array([decimal.Decimal(int(c)).scaleb(-2) for c in cents],
                    pa.decimal128(12, 2))


def _write(root, name, table, n_files=1):
    files = []
    rows = table.num_rows
    per = max(1, (rows + n_files - 1) // n_files)
    for i in range(n_files):
        part = table.slice(i * per, per)
        if part.num_rows == 0 and i > 0:
            break
        path = os.path.join(root, f"{name}_{i}.parquet")
        pq.write_table(part, path)
        files.append(path)
    return files


def generate(root: str, scale: float = 1.0, seed: int = 11) -> dict:
    """Write the eight TPC-H tables at ``scale`` (1.0 = 60k lineitems);
    returns {table: [files]}."""
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    out = {}

    n_nation = len(NATIONS)
    nation = pa.table({
        "n_nationkey": pa.array(np.arange(n_nation, dtype=np.int64)),
        "n_name": pa.array([n for n, _ in NATIONS]),
        "n_regionkey": pa.array(
            np.asarray([r for _, r in NATIONS], np.int64)),
    })
    out["nation"] = _write(root, "nation", nation)

    region = pa.table({
        "r_regionkey": pa.array(np.arange(5, dtype=np.int64)),
        "r_name": pa.array(REGIONS),
    })
    out["region"] = _write(root, "region", region)

    n_supp = max(int(100 * scale), 20)
    supplier = pa.table({
        "s_suppkey": pa.array(np.arange(1, n_supp + 1, dtype=np.int64)),
        "s_name": pa.array([f"Supplier#{i:09d}"
                            for i in range(1, n_supp + 1)]),
        "s_nationkey": pa.array(
            rng.integers(0, n_nation, n_supp).astype(np.int64)),
    })
    out["supplier"] = _write(root, "supplier", supplier)

    n_part = max(int(2000 * scale), 200)
    pcolor = rng.integers(0, len(COLORS), n_part)
    part = pa.table({
        "p_partkey": pa.array(np.arange(1, n_part + 1, dtype=np.int64)),
        "p_name": pa.array([
            f"{COLORS[pcolor[i]]} polished {COLORS[(pcolor[i]+3) % len(COLORS)]} item {i+1}"
            for i in range(n_part)]),
        "p_retailprice": _money(rng, n_part, 90_000, 200_000),
    })
    out["part"] = _write(root, "part", part)

    # partsupp: 2 suppliers per part
    ps_part = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 2)
    ps_supp = rng.integers(1, n_supp + 1, 2 * n_part).astype(np.int64)
    partsupp = pa.table({
        "ps_partkey": pa.array(ps_part),
        "ps_suppkey": pa.array(ps_supp),
        "ps_supplycost": _money(rng, 2 * n_part, 100, 100_000),
    })
    out["partsupp"] = _write(root, "partsupp", partsupp)

    n_cust = max(int(1500 * scale), 150)
    segments = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE",
                         "HOUSEHOLD", "MACHINERY"])
    customer = pa.table({
        "c_custkey": pa.array(np.arange(1, n_cust + 1, dtype=np.int64)),
        "c_mktsegment": pa.array(
            segments[rng.integers(0, 5, n_cust)]),
        "c_name": pa.array([f"Customer#{i:09d}"
                            for i in range(1, n_cust + 1)]),
        "c_nationkey": pa.array(
            rng.integers(0, n_nation, n_cust).astype(np.int64)),
    })
    out["customer"] = _write(root, "customer", customer)

    n_ord = max(int(15_000 * scale), 1500)
    o_date = rng.integers(DATE_LO, DATE_HI, n_ord)
    orders = pa.table({
        "o_orderkey": pa.array(np.arange(1, n_ord + 1, dtype=np.int64)),
        "o_custkey": pa.array(
            rng.integers(1, n_cust + 1, n_ord).astype(np.int64)),
        "o_orderdate": pa.array(o_date.astype("datetime64[D]")),
        "o_shippriority": pa.array(np.zeros(n_ord, np.int64)),
        "o_totalprice": _money(rng, n_ord, 100_000, 40_000_000),
    })
    out["orders"] = _write(root, "orders", orders, 2)

    n_li = max(int(60_000 * scale), 6000)
    l_ord = rng.integers(1, n_ord + 1, n_li).astype(np.int64)
    # supplier must exist in partsupp for the part for q9 realism: pick a
    # random partsupp row per lineitem
    ps_row = rng.integers(0, 2 * n_part, n_li)
    qty = rng.integers(1, 51, n_li)
    price_c = rng.integers(90_000, 200_000, n_li)
    disc_c = rng.integers(0, 11, n_li)            # 0.00..0.10
    import decimal
    lineitem = pa.table({
        "l_orderkey": pa.array(l_ord),
        "l_partkey": pa.array(ps_part[ps_row]),
        "l_suppkey": pa.array(ps_supp[ps_row]),
        "l_quantity": pa.array(qty.astype(np.int64)),
        "l_extendedprice": pa.array(
            [decimal.Decimal(int(c)).scaleb(-2)
             for c in price_c * qty], pa.decimal128(12, 2)),
        "l_discount": pa.array(
            [decimal.Decimal(int(d)).scaleb(-2) for d in disc_c],
            pa.decimal128(12, 2)),
        "l_shipdate": pa.array(
            (o_date[l_ord - 1]
             + rng.integers(1, 122, n_li)).astype("datetime64[D]")),
        "l_tax": pa.array(
            [decimal.Decimal(int(x)).scaleb(-2)
             for x in rng.integers(0, 9, n_li)], pa.decimal128(12, 2)),
        "l_returnflag": pa.array(
            np.array(["A", "N", "R"])[rng.integers(0, 3, n_li)]),
        "l_linestatus": pa.array(
            np.array(["F", "O"])[rng.integers(0, 2, n_li)]),
    })
    out["lineitem"] = _write(root, "lineitem", lineitem, 4)
    return out


def load_arrow(tables: dict) -> dict:
    return {name: pa.concat_tables([pq.read_table(f) for f in files])
            for name, files in tables.items()}
