"""TPC-DS-class integration queries.

Each query runs scan → filter/project → (two-phase, exchanged) agg →
join → sort/limit combinations through the FULL pipeline: DataFrame DSL →
protobuf TaskDefinition → physical planner → operators (incl.
ShuffleExchangeOp stages) — the per-query differential methodology of the
reference's auron-it (reference: dev/auron-it/.../Main.scala:60-128).
The oracle for every query is an independent pandas computation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np
import pyarrow as pa

from auron_tpu.columnar.schema import DataType
from auron_tpu.frontend.dataframe import col, functions as F, lit


@dataclass(frozen=True)
class Query:
    name: str
    description: str
    run: Callable        # (session, tables: {name: [files]}) -> pa.Table
    oracle: Callable     # (pd_tables: {name: DataFrame}) -> pandas.DataFrame

    def expected(self, pd_tables) -> pa.Table:
        import pandas as pd
        df = self.oracle(pd_tables)
        return pa.Table.from_pandas(df.reset_index(drop=True),
                                    preserve_index=False)


def _sales(session, tables, partitions=4):
    return session.read_parquet(tables["store_sales"], partitions=partitions)


def _dim(session, tables, name):
    return session.read_parquet(tables[name])


# --------------------------------------------------------------------------
# q01: scan → filter → two-phase agg → sort  (the flagship q01 shape)
# --------------------------------------------------------------------------

def q01_dataframe(s, t, partitions=4):
    """The q01 DataFrame WITHOUT collecting — shared by the e2e query
    below, the bench's profiled explain-analyze section
    (bench.bench_profile_q01) and the mesh scaling bench
    (bench._mesh_child_main, which sweeps ``partitions`` across device
    counts), so every profiled plan is the differential-tested one."""
    return (_sales(s, t, partitions=partitions)
            .filter(col("ss_quantity") > 5)
            .group_by("ss_store_sk")
            .agg(F.sum(col("ss_sales_price")).alias("total"),
                 F.count(col("ss_net_paid")).alias("paid_cnt"),
                 F.avg(col("ss_net_profit")).alias("avg_profit")))


def _q01_run(s, t):
    return q01_dataframe(s, t).collect()


def _q01_oracle(p):
    ss = p["store_sales"]
    f = ss[ss.ss_quantity > 5]
    g = f.groupby("ss_store_sk").agg(
        total=("ss_sales_price", "sum"),
        paid_cnt=("ss_net_paid", "count"),
        avg_profit=("ss_net_profit", "mean")).reset_index()
    return g


# --------------------------------------------------------------------------
# q02: top-k customers by revenue (agg → exchange → global sort+limit)
# --------------------------------------------------------------------------

def _q02_run(s, t):
    return (_sales(s, t)
            .group_by("ss_customer_sk")
            .agg(F.sum(col("ss_net_paid")).alias("revenue"))
            .sort(col("revenue").desc(), col("ss_customer_sk").asc(),
                  limit=100)
            .collect())


def _q02_oracle(p):
    g = p["store_sales"].groupby("ss_customer_sk").agg(
        revenue=("ss_net_paid", "sum")).reset_index()
    return g.sort_values(["revenue", "ss_customer_sk"],
                         ascending=[False, True]).head(100)


# --------------------------------------------------------------------------
# q03: fact ⋈ dim join (co-partitioned) → agg by category → sort
# --------------------------------------------------------------------------

def _q03_run(s, t):
    item = (_dim(s, t, "item")
            .select(col("i_item_sk").alias("ss_item_sk"),
                    col("i_category"), col("i_current_price"))
            .repartition(4, "ss_item_sk"))
    sales = _sales(s, t).repartition(4, "ss_item_sk")
    return (sales.join(item, on="ss_item_sk")
            .filter(col("i_category").isin("Books", "Music", "Shoes"))
            .group_by("i_category")
            .agg(F.sum(col("ss_sales_price")).alias("total"),
                 F.count_star().alias("n"))
            .collect())


def _q03_oracle(p):
    ss, it = p["store_sales"], p["item"]
    j = ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j[j.i_category.isin(["Books", "Music", "Shoes"])]
    return j.groupby("i_category").agg(
        total=("ss_sales_price", "sum"),
        n=("ss_item_sk", "size")).reset_index()


# --------------------------------------------------------------------------
# q04: join store dim → agg by state
# --------------------------------------------------------------------------

def _q04_run(s, t):
    store = (_dim(s, t, "store")
             .select(col("s_store_sk").alias("ss_store_sk"),
                     col("s_state")))
    return (_sales(s, t).repartition(4, "ss_store_sk")
            .join(store.repartition(4, "ss_store_sk"), on="ss_store_sk")
            .group_by("s_state")
            .agg(F.count_star().alias("n"),
                 F.sum(col("ss_net_profit")).alias("profit"))
            .collect())


def _q04_oracle(p):
    j = p["store_sales"].merge(p["store"], left_on="ss_store_sk",
                               right_on="s_store_sk")
    return j.groupby("s_state").agg(
        n=("ss_store_sk", "size"),
        profit=("ss_net_profit", "sum")).reset_index()


# --------------------------------------------------------------------------
# q05: date-dim filter join → agg by month
# --------------------------------------------------------------------------

def _q05_run(s, t):
    dd = (_dim(s, t, "date_dim")
          .filter(col("d_year") == 2000)
          .select(col("d_date_sk").alias("ss_sold_date_sk"), col("d_moy")))
    return (_sales(s, t).repartition(4, "ss_sold_date_sk")
            .join(dd.repartition(4, "ss_sold_date_sk"),
                  on="ss_sold_date_sk")
            .group_by("d_moy")
            .agg(F.sum(col("ss_sales_price")).alias("total"))
            .collect())


def _q05_oracle(p):
    dd = p["date_dim"]
    dd = dd[dd.d_year == 2000]
    j = p["store_sales"].merge(dd, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    return j.groupby("d_moy").agg(
        total=("ss_sales_price", "sum")).reset_index()


# --------------------------------------------------------------------------
# q06: string min/max aggregates over a join (customer emails by state)
# --------------------------------------------------------------------------

def _q06_run(s, t):
    cust = (_dim(s, t, "customer")
            .select(col("c_customer_sk").alias("ss_customer_sk"),
                    col("c_state"), col("c_email")))
    return (_sales(s, t).repartition(4, "ss_customer_sk")
            .join(cust.repartition(4, "ss_customer_sk"),
                  on="ss_customer_sk")
            .group_by("c_state")
            .agg(F.min(col("c_email")).alias("first_email"),
                 F.max(col("c_email")).alias("last_email"),
                 F.count(col("c_email")).alias("n"))
            .collect())


def _q06_oracle(p):
    import pandas as pd
    j = p["store_sales"].merge(p["customer"], left_on="ss_customer_sk",
                               right_on="c_customer_sk")
    # pandas >= 2 groupby.agg(min/max) raises TypeError on object
    # columns containing None (its cython path compares str against the
    # NaN float). SQL min/max skip nulls, so dropna-then-reduce states
    # the intended oracle semantics AND sidesteps the pandas limitation
    # (all-null groups would yield NaN, matching the engine's NULL).
    g = j.groupby("c_state")["c_email"]
    return pd.DataFrame({
        "first_email": g.apply(lambda s: s.dropna().min()),
        "last_email": g.apply(lambda s: s.dropna().max()),
        "n": g.count(),
    }).reset_index()


# --------------------------------------------------------------------------
# q07: three-table join → composite-key agg → sort+limit
# --------------------------------------------------------------------------

def _q07_run(s, t):
    item = (_dim(s, t, "item")
            .select(col("i_item_sk").alias("ss_item_sk"),
                    col("i_category")))
    store = (_dim(s, t, "store")
             .select(col("s_store_sk").alias("ss_store_sk"),
                     col("s_state")))
    return (_sales(s, t).repartition(4, "ss_item_sk")
            .join(item.repartition(4, "ss_item_sk"), on="ss_item_sk")
            .repartition(4, "ss_store_sk")
            .join(store.repartition(4, "ss_store_sk"), on="ss_store_sk")
            .filter(col("ss_net_profit") > 0)
            .group_by("i_category", "s_state")
            .agg(F.sum(col("ss_net_paid")).alias("paid"))
            .sort(col("paid").desc(), col("i_category").asc(),
                  col("s_state").asc(), limit=50)
            .collect())


def _q07_oracle(p):
    j = (p["store_sales"]
         .merge(p["item"], left_on="ss_item_sk", right_on="i_item_sk")
         .merge(p["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    j = j[j.ss_net_profit > 0]
    g = j.groupby(["i_category", "s_state"]).agg(
        paid=("ss_net_paid", "sum")).reset_index()
    return g.sort_values(["paid", "i_category", "s_state"],
                         ascending=[False, True, True]).head(50)


# --------------------------------------------------------------------------
# q08: semi join — states of customers who bought Electronics
# --------------------------------------------------------------------------

def _q08_run(s, t):
    item = (_dim(s, t, "item")
            .select(col("i_item_sk").alias("ss_item_sk"),
                    col("i_category")))
    buyers = (_sales(s, t)
              .join(item, on="ss_item_sk")
              .filter(col("i_category") == "Electronics")
              .select(col("ss_customer_sk").alias("c_customer_sk")))
    cust = _dim(s, t, "customer")
    return (cust.join(buyers, on="c_customer_sk", how="semi")
            .group_by("c_state")
            .agg(F.count_star().alias("n"))
            .collect())


def _q08_oracle(p):
    j = p["store_sales"].merge(p["item"], left_on="ss_item_sk",
                               right_on="i_item_sk")
    buyers = set(j[j.i_category == "Electronics"].ss_customer_sk)
    c = p["customer"]
    c = c[c.c_customer_sk.isin(buyers)]
    return c.groupby("c_state").agg(
        n=("c_customer_sk", "size")).reset_index()


# --------------------------------------------------------------------------
# q09: anti join — items never sold, counted by category
# --------------------------------------------------------------------------

def _q09_run(s, t):
    # "never discounted": anti-join against sub-$1 sales — rare enough
    # (~0.2% of rows) that the anti side stays populated at every scale
    sold = (_sales(s, t)
            .filter(col("ss_sales_price") < 1.0)
            .select(col("ss_item_sk").alias("i_item_sk")))
    item = _dim(s, t, "item")
    return (item.join(sold, on="i_item_sk", how="anti")
            .group_by("i_category")
            .agg(F.count_star().alias("n"))
            .collect())


def _q09_oracle(p):
    ss = p["store_sales"]
    sold = set(ss[ss.ss_sales_price < 1.0].ss_item_sk)
    it = p["item"]
    unsold = it[~it.i_item_sk.isin(sold)]
    g = unsold.groupby("i_category").agg(
        n=("i_item_sk", "size")).reset_index()
    return g


# --------------------------------------------------------------------------
# q10: agg → filter-on-aggregate (HAVING) → sort
# --------------------------------------------------------------------------

def _q10_run(s, t):
    return (_sales(s, t)
            .group_by("ss_quantity")
            .agg(F.count_star().alias("n"),
                 F.avg(col("ss_sales_price")).alias("avg_price"))
            .filter(col("n") > 100)
            .collect())


def _q10_oracle(p):
    g = p["store_sales"].groupby("ss_quantity").agg(
        n=("ss_quantity", "size"),
        avg_price=("ss_sales_price", "mean")).reset_index()
    return g[g.n > 100]


# --------------------------------------------------------------------------
# q11: union of two filtered branches → agg
# --------------------------------------------------------------------------

def _q11_run(s, t):
    lo = (_sales(s, t)
          .filter(col("ss_sales_price") < 10.0)
          .select(col("ss_store_sk"), col("ss_quantity")))
    hi = (_sales(s, t)
          .filter(col("ss_sales_price") > 250.0)
          .select(col("ss_store_sk"), col("ss_quantity")))
    return (lo.union(hi)
            .group_by("ss_store_sk")
            .agg(F.sum(col("ss_quantity")).alias("qty"),
                 F.count_star().alias("n"))
            .collect())


def _q11_oracle(p):
    ss = p["store_sales"]
    u = ss[(ss.ss_sales_price < 10.0) | (ss.ss_sales_price > 250.0)]
    return u.groupby("ss_store_sk").agg(
        qty=("ss_quantity", "sum"),
        n=("ss_quantity", "size")).reset_index()


# --------------------------------------------------------------------------
# q12: projection arithmetic → filter → global top-k by computed column
# --------------------------------------------------------------------------

def _q12_run(s, t):
    return (_sales(s, t)
            .select(col("ss_item_sk"),
                    (col("ss_sales_price")
                     * col("ss_quantity").cast(DataType.FLOAT64))
                    .alias("revenue"),
                    col("ss_net_profit"))
            .filter(col("ss_net_profit") > 0)
            .sort(col("revenue").desc(), col("ss_item_sk").asc(), limit=20)
            .collect())


def _q12_oracle(p):
    ss = p["store_sales"].copy()
    ss["revenue"] = ss.ss_sales_price * ss.ss_quantity
    f = ss[ss.ss_net_profit > 0][["ss_item_sk", "revenue", "ss_net_profit"]]
    return f.sort_values(["revenue", "ss_item_sk"],
                         ascending=[False, True]).head(20)


# --------------------------------------------------------------------------
# q13: distinct count class — number of distinct buying customers per store
# (two nested aggs through an exchange)
# --------------------------------------------------------------------------

def _q13_run(s, t):
    per_cust = (_sales(s, t)
                .group_by("ss_store_sk", "ss_customer_sk")
                .agg(F.count_star().alias("_n")))
    return (per_cust
            .group_by("ss_store_sk")
            .agg(F.count(col("ss_customer_sk")).alias("buyers"))
            .collect())


def _q13_oracle(p):
    g = p["store_sales"].groupby("ss_store_sk").agg(
        buyers=("ss_customer_sk", "nunique")).reset_index()
    return g


# --------------------------------------------------------------------------
# q14: round-3 scalar-function pipeline — string kernels + fused split
# over a dim table, grouped aggregation on a derived key
# --------------------------------------------------------------------------

def _q14_run(s, t):
    return (_dim(s, t, "item")
            .select(col("i_item_sk"),
                    F.initcap(col("i_category")).alias("cat_title"),
                    F.substring_index(col("i_brand"), lit("#"), lit(1))
                    .alias("brand_name"),
                    F.concat_ws(lit("/"), col("i_category"),
                                col("i_brand")).alias("path"))
            .filter(col("i_item_sk") >= 0)
            .group_by("cat_title")
            .agg(F.count_star().alias("n"),
                 F.min(col("path")).alias("first_path"))
            .sort(col("cat_title").asc())
            .collect())


def _q14_oracle(p):
    it = p["item"].copy()
    it["cat_title"] = it.i_category.str.title()
    it["path"] = it.i_category + "/" + it.i_brand
    g = (it.groupby("cat_title")
           .agg(n=("i_item_sk", "size"), first_path=("path", "min"))
           .reset_index())
    return g.sort_values("cat_title")[["cat_title", "n", "first_path"]]


# --------------------------------------------------------------------------
# q15: wide decimals — cast to decimal(25,2), multiply (promotes past 18
# digits onto the two-limb kernels), sort on the wide result
# --------------------------------------------------------------------------

def _q15_run(s, t):
    return (_sales(s, t)
            .select(col("ss_item_sk"),
                    (col("ss_sales_price").cast(DataType.DECIMAL, 25, 2)
                     * col("ss_quantity").cast(DataType.DECIMAL, 20, 0))
                    .alias("rev_dec"))
            .filter(col("ss_item_sk") < 50)
            .sort(col("rev_dec").desc(), col("ss_item_sk").asc(), limit=25)
            .collect())


def _q15_oracle(p):
    import decimal
    with decimal.localcontext() as ctx:
        ctx.prec = 60
        ss = p["store_sales"]
        f = ss[ss.ss_item_sk < 50].copy()
        q = decimal.Decimal("0.01")
        f["rev_dec"] = [
            (decimal.Decimal(str(round(px, 2))).quantize(q)
             * decimal.Decimal(int(n)))
            for px, n in zip(f.ss_sales_price, f.ss_quantity)]
        out = f.sort_values(["rev_dec", "ss_item_sk"],
                            ascending=[False, True]).head(25)
        return out[["ss_item_sk", "rev_dec"]]


# --------------------------------------------------------------------------
# q16: window functions — rank within store by revenue + running sum
# (exchange on partition keys, the TPC-DS windowed-rank query class)
# --------------------------------------------------------------------------

def _q16_run(s, t):
    per_item = (_sales(s, t)
                .group_by("ss_store_sk", "ss_item_sk")
                .agg(F.sum(col("ss_sales_price")).alias("rev")))
    return (per_item
            .window([F.rank().alias("rnk")],
                    partition_by=[col("ss_store_sk")],
                    order_by=[col("rev").desc()])
            .filter(col("rnk") <= 3)
            .sort(col("ss_store_sk").asc(), col("rnk").asc(),
                  col("ss_item_sk").asc())
            .collect())


def _q16_oracle(p):
    ss = p["store_sales"]
    g = (ss.groupby(["ss_store_sk", "ss_item_sk"])
           .agg(rev=("ss_sales_price", "sum")).reset_index())
    g["rnk"] = g.groupby("ss_store_sk")["rev"] \
        .rank(method="min", ascending=False).astype("int64")
    f = g[g.rnk <= 3]
    return f.sort_values(["ss_store_sk", "rnk", "ss_item_sk"])[
        ["ss_store_sk", "ss_item_sk", "rev", "rnk"]]


QUERIES = [
    Query("q01_filter_agg", "scan→filter→two-phase agg", _q01_run, _q01_oracle),
    Query("q02_topk_revenue", "agg→exchange→global sort+limit", _q02_run, _q02_oracle),
    Query("q03_item_join_agg", "co-partitioned join→agg (IN filter)", _q03_run, _q03_oracle),
    Query("q04_store_join_agg", "join→agg by dim attribute", _q04_run, _q04_oracle),
    Query("q05_date_filter_join", "filtered dim join→agg", _q05_run, _q05_oracle),
    Query("q06_string_minmax", "join→min/max(string) agg", _q06_run, _q06_oracle),
    Query("q07_three_table", "3-table join→composite agg→top-k", _q07_run, _q07_oracle),
    Query("q08_semi_join", "semi join→agg", _q08_run, _q08_oracle),
    Query("q09_anti_join", "anti join→agg", _q09_run, _q09_oracle),
    Query("q10_having", "agg→filter-on-aggregate", _q10_run, _q10_oracle),
    Query("q11_union", "union of branches→agg", _q11_run, _q11_oracle),
    Query("q12_computed_topk", "project arithmetic→top-k", _q12_run, _q12_oracle),
    Query("q14_string_functions", "round-3 string fns→agg", _q14_run, _q14_oracle),
    Query("q15_wide_decimal", "decimal(>18) arith→sort", _q15_run, _q15_oracle),
    Query("q16_window_rank", "window rank→filter→sort", _q16_run, _q16_oracle),
    Query("q13_distinct_buyers", "nested aggs through exchange", _q13_run, _q13_oracle),
]
