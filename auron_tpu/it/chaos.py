"""Chaos harness: seeded fault plans driven over recovery scenarios.

The executable contract of the robustness plane (runtime/faults.py):
for ANY fault plan, a scenario run either produces results bit-identical
to its fault-free baseline (recovery worked) or raises a classified
``AuronError`` (failure surfaced with a verdict) — never silently wrong
rows, never an unclassified crash, and never leaked ``.part``/spill
files after teardown. ``tests/test_zz_chaos_battery.py`` asserts it over
seeds; ``tools/chaos_report.py`` sweeps it and prints the site-by-site
outcome table.

Scenarios are self-contained op pipelines chosen so every injection
site has traffic: ``rss_pipeline`` (RSS write/flush/commit/fetch),
``spill_sort`` (spill write/read through the external-sort path),
``agg_pipeline`` (device compute + program build through a
Session-planned two-phase aggregation). Each ``run()`` constructs a
FRESH operator tree — exchange materialization and spill state are
per-run, exactly like a fresh task attempt.
"""

from __future__ import annotations

import gc
import glob
import os
import sys
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np
import pyarrow as pa

from auron_tpu import config as cfg
from auron_tpu import errors
from auron_tpu.runtime import faults


@dataclass
class ChaosOutcome:
    scenario: str
    fault_plan: str
    seed: int
    #: identical | classified | mismatch | unclassified
    status: str
    error_type: Optional[str] = None
    error: Optional[str] = None
    #: {site: {kind: count}} actually injected during the run
    injected: dict = field(default_factory=dict)
    #: leftover .part / spill files after teardown (must be empty)
    leaks: list = field(default_factory=list)
    #: trace id of the run's span timeline (obs/trace; 0 = none)
    trace_id: int = 0
    #: site → {injected, fault_spans, recovery: {span name: count}} —
    #: the fault-injection events linked to the recovery spans they
    #: triggered (tools/chaos_report prints the aggregate table)
    correlation: dict = field(default_factory=dict)
    #: post-mortem bundle directories THIS run created (auron.bundle.*
    #: armed); the bundle audit's findings land in ``leaks`` so a
    #: missing/extra/fault-less bundle fails the run like a leaked file
    bundles: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.status in ("identical", "classified") and not self.leaks


#: span names that ARE recovery actions on the timeline: task-level
#: retries, corrupt-map recomputes, watchdog CPU fallbacks, stall
#: verdicts and pressure-ladder sheds (the lifecycle plane's recovery
#: actions)
RECOVERY_SPAN_NAMES = ("task.retry", "shuffle.corruption_recompute",
                       "watchdog.fallback", "watchdog.stall",
                       "memmgr.shed", "sched.reject",
                       "exchange.demote", "mesh.quarantine")


#: which injection KINDS can cause each recovery span — the corrupt
#: kind has a DEFERRED effect (injected at write, detected at fetch),
#: so a corruption recompute must skip over interleaved io_error/hang
#: injections when walking back for its cause
_RECOVERY_CAUSE_KINDS = {
    "shuffle.corruption_recompute": ("corrupt",),
    # any injected backend.init kind (hang, io_error, fatal) can force
    # the CPU fallback, so the watchdog entry lists them all
    "watchdog.fallback": ("hang", "io_error", "fatal"),
    # only a hang goes silent long enough for the stall monitor
    "watchdog.stall": ("hang",),
    # the pressure ladder sheds on injected denies
    "memmgr.shed": ("deny",),
    # admission control sheds at the door on injected denies
    "sched.reject": ("deny",),
    # the mesh fault domain demotes on device loss (io_error/fatal at
    # mesh.all_to_all) and — under demote_on_straggler — on an injected
    # hang's straggling round
    "exchange.demote": ("io_error", "fatal", "hang"),
    "mesh.quarantine": ("io_error", "fatal"),
}


def correlate_spans(spans) -> dict:
    """Link fault-injection events to the recovery spans they triggered:
    each recovery span is attributed to the NEAREST PRECEDING injection
    of a kind that can cause it (the causality proxy — the run is
    single-pipeline, so the recovery that follows an injection was
    triggered by it). Nearest-preceding, not first-injection-onward: a
    multi-site plan must not double-count one task.retry under every
    armed site; kind-aware, because a corrupt fault injected at WRITE
    time recovers only at fetch time, past unrelated injections."""
    inj = sorted((s for s in spans
                  if s.cat == "fault" and s.name == "fault.injected"),
                 key=lambda s: (s.ts_ns, s.span_id))
    rec = [s for s in spans if s.name in RECOVERY_SPAN_NAMES]
    out: dict = {}
    for s in inj:
        site = s.attrs.get("site")
        entry = out.setdefault(site, {"injected": 0, "fault_spans": [],
                                      "recovery": {}})
        entry["injected"] += 1
        if len(entry["fault_spans"]) < 16:
            entry["fault_spans"].append(s.span_id)
    for r in rec:
        kinds = _RECOVERY_CAUSE_KINDS.get(r.name)
        prev = None
        for s in inj:
            if s.ts_ns > r.ts_ns:
                break
            if kinds is None or s.attrs.get("kind") in kinds:
                prev = s
        if prev is None:
            continue
        counts = out[prev.attrs.get("site")]["recovery"]
        counts[r.name] = counts.get(r.name, 0) + 1
    return out


class Scenario:
    """One recovery scenario: a fresh-run factory + leak audit paths."""

    def __init__(self, name: str, run: Callable[[], pa.Table],
                 leak_globs: list[str]):
        self.name = name
        self._run = run
        self.leak_globs = leak_globs
        self._baseline: Optional[pa.Table] = None

    def run(self) -> pa.Table:
        return self._run()

    def baseline(self) -> pa.Table:
        """Fault-free reference output (computed once, faults disarmed)."""
        if self._baseline is None:
            conf = cfg.get_config()
            conf.unset(cfg.FAULTS_PLAN)
            faults.reset()
            self._baseline = self.run()
        return self._baseline

    def leaks(self) -> list[str]:
        gc.collect()   # drop spill refs held by collected generators
        found = []
        for pattern in self.leak_globs:
            found.extend(glob.glob(pattern, recursive=True))
        extra = getattr(self, "extra_audit", None)
        if extra is not None:
            # scenario-specific resource ledger (registered memmgr
            # consumers, tracked spill files) — the zero-leaked-
            # consumers half of the lifecycle contract
            found.extend(extra())
        return found


def _rows(n: int, seed: int = 11) -> pa.RecordBatch:
    rng = np.random.default_rng(seed)
    return pa.record_batch({
        "k": pa.array(rng.integers(0, 64, n), pa.int64()),
        "v": pa.array(rng.normal(size=n)),
        "c": pa.array(rng.integers(0, 1000, n), pa.int32()),
    })


def _canonical(table: pa.Table) -> pa.Table:
    """Row-order-canonical view for cross-run equality (shuffle reads
    are deterministic per run, but canonicalizing keeps the contract
    about VALUES, which is what integrity protects)."""
    return table.sort_by([(c, "ascending") for c in table.column_names])


def rss_pipeline(workdir: str) -> Scenario:
    """Scan → hash-partitioned RSS shuffle → collect: traffic on every
    rss.* site, map recompute on fetch corruption."""
    from auron_tpu.columnar.arrow_bridge import schema_from_arrow
    from auron_tpu.exprs import ir
    from auron_tpu.io.parquet import MemoryScanOp
    from auron_tpu.parallel.exchange import RssShuffleExchangeOp
    from auron_tpu.parallel.partitioning import HashPartitioning
    from auron_tpu.parallel.shuffle_service import FileShuffleService
    from auron_tpu.runtime.executor import collect

    rb = _rows(4096)
    rss_root = os.path.join(workdir, "rss")
    counter = [0]

    def run() -> pa.Table:
        counter[0] += 1
        root = os.path.join(rss_root, f"run_{counter[0]}")
        per = rb.num_rows // 2
        parts = [[rb.slice(i * per, per).slice(o, 512)
                  for o in range(0, per, 512)] for i in range(2)]
        scan = MemoryScanOp(parts, schema_from_arrow(rb.schema),
                            capacity=512)
        op = RssShuffleExchangeOp(
            scan, HashPartitioning([ir.ColumnRef(0)], 4),
            FileShuffleService(root), shuffle_id=1, input_partitions=2)
        return _canonical(collect(op, num_partitions=4))

    return Scenario("rss_pipeline", run,
                    [os.path.join(rss_root, "**", "*.part")])


def spill_sort(workdir: str) -> Scenario:
    """External sort with a 1-byte device budget and a 1-byte host spill
    budget: every run spills every batch to DISK frames — traffic on
    spill.write/spill.read, task-level recompute on spill corruption."""
    from auron_tpu.columnar.arrow_bridge import schema_from_arrow
    from auron_tpu.exprs import ir
    from auron_tpu.io.parquet import MemoryScanOp
    from auron_tpu.memmgr.manager import MemManager
    from auron_tpu.memmgr.spill import SpillManager
    from auron_tpu.ops.sort import SortOp
    from auron_tpu.runtime.executor import collect

    rb = _rows(3000, seed=5)
    spill_dir = os.path.join(workdir, "spill")

    def run() -> pa.Table:
        rbs = [rb.slice(o, 500) for o in range(0, rb.num_rows, 500)]
        scan = MemoryScanOp([rbs], schema_from_arrow(rb.schema),
                            capacity=512)
        orders = [ir.SortOrder(ir.ColumnRef(0), ascending=True),
                  ir.SortOrder(ir.ColumnRef(2), ascending=False)]
        mm = MemManager(total_bytes=1, min_trigger=0,
                        spill_manager=SpillManager(
                            host_budget_bytes=1,
                            spill_dir=spill_dir))
        return collect(SortOp(scan, orders), num_partitions=1,
                       mem_manager=mm)

    return Scenario("spill_sort", run,
                    [os.path.join(spill_dir, "auron-spill-*")])


def agg_pipeline(workdir: str) -> Scenario:
    """Session-planned two-phase aggregation (the q01 shape): traffic on
    device.compute and program.build through the full planner path."""
    from auron_tpu.frontend.dataframe import col, functions as F
    from auron_tpu.frontend.session import Session

    table = pa.Table.from_batches([_rows(4096, seed=23)])

    def run() -> pa.Table:
        s = Session()
        df = (s.from_arrow(table)
              .filter(col("c") > 50)
              .group_by("k")
              .agg(F.sum(col("v")).alias("sv"),
                   F.count(col("c")).alias("n")))
        return _canonical(s.execute(df))

    return Scenario("agg_pipeline", run, [])


def mesh_pipeline(workdir: str) -> Scenario:
    """SPMD chaos scenario: the agg_pipeline shape (Session-planned
    two-phase aggregation) with ``auron.mesh.enabled`` on, so the hash
    exchange rides the on-device all-to-all stage program — the
    ``device.compute`` site fires both per output batch in the drive
    loop AND per all-to-all round inside the sharded-stage
    materialization, and the mesh fault domain's own sites get traffic
    too: ``mesh.all_to_all`` (per round — io_error/fatal simulate a
    device loss the DEMOTION path must recover bit-identically, hang a
    straggling chip) and ``mesh.gang`` (a cancel racing the gang door
    must dequeue without starting a round). A fault mid-exchange must
    classify cleanly (the gang releases, the mesh buffer unregisters,
    the exchange demotes or the task surfaces the verdict); RSS stays
    untouched as the durable fallback tier, which is exactly what this
    scenario proves out."""
    from auron_tpu.frontend.dataframe import col, functions as F
    from auron_tpu.frontend.session import Session
    from auron_tpu.parallel import mesh as mesh_mod

    table = pa.Table.from_batches([_rows(1024, seed=41 + i)
                                   for i in range(4)])

    def run() -> pa.Table:
        conf = cfg.get_config()
        _missing = object()
        saved = conf._overrides.get(cfg.MESH_ENABLED, _missing)
        conf.set(cfg.MESH_ENABLED, True)
        try:
            # <2 devices is still a valid run — the exchange routes
            # device_buffer and records why; the battery contract
            # (identical-or-classified) holds on either route
            _ = mesh_mod.current_plane()
            s = Session()
            df = (s.from_arrow(table)
                  .repartition(4, "k")
                  .filter(col("c") > 50)
                  .group_by("k")
                  .agg(F.sum(col("v")).alias("sv"),
                       F.count(col("c")).alias("n")))
            return _canonical(s.execute(df))
        finally:
            if saved is _missing:
                conf.unset(cfg.MESH_ENABLED)
            else:
                conf.set(cfg.MESH_ENABLED, saved)

    return Scenario("mesh_pipeline", run, [])


def lifecycle_pipeline(workdir: str) -> Scenario:
    """Chaos 2.0 lifecycle scenario: a Session-planned sort+agg under a
    tiny memory budget so spills/memmgr traffic is guaranteed, run with
    a short stall watchdog and the 'shed' pressure policy. Gives the
    lifecycle sites deterministic traffic: ``cancel.race`` fires the
    query's CancelToken mid-drive (→ QueryCancelled), ``task.hang``
    goes silent past the stall timeout (→ TaskStalled, retried once),
    ``memmgr.deny`` forces the degradation ladder to the shed rung
    (→ MemoryExhausted). Every outcome must be identical-or-classified
    with a clean resource ledger (no spill files, no registered
    consumers) — audited per run via ``extra_audit``."""
    from auron_tpu.frontend.dataframe import col, functions as F
    from auron_tpu.frontend.session import Session
    from auron_tpu.memmgr.manager import MemManager
    from auron_tpu.memmgr.spill import SpillManager

    spill_dir = os.path.join(workdir, "spill")
    os.makedirs(spill_dir, exist_ok=True)
    # several record batches: every one is a checkpoint event in the
    # sort/drive loops, so the seeded cancel.race/task.hang Bernoulli
    # sequences see real traffic
    table = pa.Table.from_batches([_rows(512, seed=31 + i)
                                   for i in range(8)])
    last: dict = {}

    # stall timeout sized ABOVE this mesh's worst single-program compile
    # (the monitor credits completed compiles, but one compile longer
    # than the timeout would still flag); hang_s above the timeout so an
    # injected hang reliably trips the stall verdict
    _KNOBS = {cfg.WATCHDOG_STALL_TIMEOUT_S: 1.5,
              cfg.FAULTS_HANG_S: 4.0,
              cfg.MEMMGR_PRESSURE_POLICY: "shed"}

    def run() -> pa.Table:
        conf = cfg.get_config()
        _missing = object()
        saved = {k: conf._overrides.get(k, _missing) for k in _KNOBS}
        for k, v in _KNOBS.items():
            conf.set(k, v)
        mm = MemManager(
            total_bytes=1 << 22, min_trigger=0,
            spill_manager=SpillManager(host_budget_bytes=1,
                                       spill_dir=spill_dir))
        last["mm"] = mm
        s = Session(mem_manager=mm)
        try:
            df = (s.from_arrow(table)
                  .sort("k")
                  .group_by("k")
                  .agg(F.sum(col("v")).alias("sv"),
                       F.count(col("c")).alias("n")))
            return _canonical(s.execute(df))
        finally:
            s.close()
            for k, prev in saved.items():
                if prev is _missing:
                    conf.unset(k)
                else:
                    conf.set(k, prev)

    sc = Scenario("lifecycle_pipeline", run,
                  [os.path.join(spill_dir, "auron-spill-*")])

    def extra_audit() -> list[str]:
        mm = last.get("mm")
        if mm is None:
            return []
        gc.collect()
        found = [f"memmgr-consumer:{name}"
                 for name in mm.status()["consumers"]]
        live = mm.spill_manager.live_disk_files() \
            if mm.spill_manager is not None else 0
        if live:
            found.append(f"tracked-spill-files:{live}")
        return found

    sc.extra_audit = extra_audit
    return sc


def overload(workdir: str) -> Scenario:
    """Concurrency chaos: THREE identical Session-planned aggregations
    race through ONE Session whose scheduler is clamped tight
    (max_concurrent=1, queue_depth=1) over a small memory budget under
    the 'shed' pressure policy — the 2x-overload posture. Gives the
    admission/arbitration sites deterministic traffic: ``sched.admit``
    denies shed queries at the door (→ AdmissionRejected, transient),
    ``memmgr.deny`` forces the pressure ladder mid-flight (→
    MemoryExhausted). The contract: every per-query outcome is a table
    bit-identical to the fault-free result OR a classified AuronError —
    never an unclassified crash, never divergent successful results,
    never a leaked consumer/spill file. One query runs on the CALLING
    thread so its admission/shed spans land inside the chaos trace and
    correlate."""
    import threading

    from auron_tpu.frontend.dataframe import col, functions as F
    from auron_tpu.frontend.session import Session
    from auron_tpu.memmgr.manager import MemManager
    from auron_tpu.memmgr.spill import SpillManager

    spill_dir = os.path.join(workdir, "spill")
    os.makedirs(spill_dir, exist_ok=True)
    table = pa.Table.from_batches([_rows(768, seed=47 + i)
                                   for i in range(4)])
    last: dict = {}

    _KNOBS = {cfg.SCHED_MAX_CONCURRENT: 1,
              cfg.SCHED_QUEUE_DEPTH: 1,
              cfg.MEMMGR_PRESSURE_POLICY: "shed"}

    def run() -> pa.Table:
        conf = cfg.get_config()
        _missing = object()
        saved = {k: conf._overrides.get(k, _missing) for k in _KNOBS}
        for k, v in _KNOBS.items():
            conf.set(k, v)
        mm = MemManager(
            total_bytes=1 << 22, min_trigger=0,
            spill_manager=SpillManager(host_budget_bytes=1,
                                       spill_dir=spill_dir))
        last["mm"] = mm
        s = Session(mem_manager=mm)

        def query() -> pa.Table:
            df = (s.from_arrow(table)
                  .sort("k")
                  .group_by("k")
                  .agg(F.sum(col("v")).alias("sv"),
                       F.count(col("c")).alias("n")))
            return _canonical(s.execute(df))

        outcomes: list = [None, None, None]

        def worker(i: int) -> None:
            try:
                outcomes[i] = ("ok", query())
            except BaseException as e:   # noqa: BLE001 — audited below
                outcomes[i] = ("err", e)

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in (1, 2)]
        try:
            for t in threads:
                t.start()
            # slot 0 runs on the CALLING thread: its scheduler/memmgr
            # events join the chaos trace scope for correlation
            worker(0)
            for t in threads:
                t.join(timeout=60)
                if t.is_alive():
                    raise RuntimeError("overload worker wedged")
        finally:
            s.close()
            for k, prev in saved.items():
                if prev is _missing:
                    conf.unset(k)
                else:
                    conf.set(k, prev)

        tables = [o[1] for o in outcomes if o and o[0] == "ok"]
        failures = [o[1] for o in outcomes if o and o[0] == "err"]
        for e in failures:
            if not isinstance(e, errors.AuronError):
                raise e     # unclassified: the contract's failure bucket
        for t in tables[1:]:
            if not t.equals(tables[0]):
                raise AssertionError(
                    "concurrent overload queries diverged: identical "
                    "queries produced different tables")
        if not tables:
            raise failures[0]   # everything shed: classified, auditable
        return tables[0]

    sc = Scenario("overload", run,
                  [os.path.join(spill_dir, "auron-spill-*")])

    def extra_audit() -> list[str]:
        mm = last.get("mm")
        if mm is None:
            return []
        gc.collect()
        found = [f"memmgr-consumer:{name}"
                 for name in mm.status()["consumers"]]
        live = mm.spill_manager.live_disk_files() \
            if mm.spill_manager is not None else 0
        if live:
            found.append(f"tracked-spill-files:{live}")
        return found

    sc.extra_audit = extra_audit
    return sc


def journal_pipeline(workdir: str) -> Scenario:
    """Crash-safe-journal chaos scenario: the agg_pipeline shape run
    with ``auron.journal.dir`` armed, so the ``journal.write`` /
    ``journal.commit`` sites see real traffic on the append/fsync path.
    The journal's contract under faults is DEGRADE, NEVER FAIL: an
    injected io_error/fatal on either site disables journaling for that
    query (a ``journal.disable`` event on the timeline) and the query
    itself completes bit-identical — resumability is lost, rows are
    not. The leak audit covers the journal dir: whatever the fault did,
    a completed query leaves no ``*.journal`` file behind."""
    from auron_tpu.frontend.dataframe import col, functions as F
    from auron_tpu.frontend.session import Session

    jdir = os.path.join(workdir, "journal")
    table = pa.Table.from_batches([_rows(2048, seed=61 + i)
                                   for i in range(2)])

    def run() -> pa.Table:
        conf = cfg.get_config()
        _missing = object()
        saved = conf._overrides.get(cfg.JOURNAL_DIR, _missing)
        conf.set(cfg.JOURNAL_DIR, jdir)
        s = None
        try:
            s = Session()
            df = (s.from_arrow(table)
                  .repartition(2, "k")
                  .filter(col("c") > 50)
                  .group_by("k")
                  .agg(F.sum(col("v")).alias("sv"),
                       F.count(col("c")).alias("n")))
            return _canonical(s.execute(df))
        finally:
            # close on EVERY path: a classified failure suspends its
            # journal, and in-process a journal never outlives its
            # Session (cross-process survival is the crash case — this
            # scenario's audit treats a leftover as a leak)
            if s is not None:
                s.close()
            if saved is _missing:
                conf.unset(cfg.JOURNAL_DIR)
            else:
                conf.set(cfg.JOURNAL_DIR, saved)

    return Scenario("journal_pipeline", run,
                    [os.path.join(jdir, "*.journal"),
                     os.path.join(jdir, "**", "*.part")])


def fleet_failover(workdir: str) -> Scenario:
    """Serving-fleet chaos scenario (ISSUE 19): TWO real AuronServer
    subprocesses behind an in-process ``FleetRouter``, one SIGKILLed
    mid-query on EVERY run — the router must fail the in-flight query
    over to the survivor (journal RESUME when committed shuffle state
    exists, guarded re-execution otherwise) and hand the client a table
    bit-identical to the fault-free answer. The seeded plans put faults
    on the router's OWN sites: ``fleet.route`` (the admission/routing
    step) and ``fleet.forward`` (the replica leg of a forwarded query),
    which must surface as spill-over retries, a failover, or a
    classified verdict — never an unclassified crash, never wrong rows.
    ``extra_audit`` force-sweeps every run's shared journal dir after
    teardown: no journal / ``.part`` / ``.claim`` / RSS artifact may
    survive a completed run (a resumed query deletes its journal; a
    torn dead-owner journal is reclaimed by the sweep)."""
    import pyarrow.parquet as pq

    journal_root = os.path.join(workdir, "journal")
    data_path = os.path.join(workdir, "fleet.parquet")
    counter = [0]
    task_box: dict = {}

    def _task() -> bytes:
        if "task" not in task_box:
            from auron_tpu.ir import pb
            rng = np.random.default_rng(19)
            n = 600_000   # ~0.7s of drive time: wide kill window
            os.makedirs(workdir, exist_ok=True)
            pq.write_table(pa.table({
                "k": pa.array(rng.integers(0, 64, n), pa.int64()),
                "v": pa.array(rng.normal(size=n), pa.float64())}),
                data_path)
            col = lambda i: pb.ExprNode(column=pb.ColumnRefE(index=i))
            plan = pb.PlanNode(agg=pb.AggNode(
                child=pb.PlanNode(parquet_scan=pb.ParquetScanNode(
                    files=[data_path])),
                mode="complete", group_exprs=[col(0)],
                aggs=[pb.AggFunctionP(fn="sum", arg=col(1)),
                      pb.AggFunctionP(fn="count", arg=col(1))]))
            task_box["task"] = pb.TaskDefinition(
                plan=plan, task_id=1).SerializeToString()
        return task_box["task"]

    def run() -> pa.Table:
        import threading

        from auron_tpu.fleet.replica import FleetHarness

        task = _task()
        counter[0] += 1
        jdir = os.path.join(journal_root, f"run_{counter[0]}")
        os.makedirs(jdir, exist_ok=True)
        with FleetHarness(2, journal_dir=jdir) as h:
            # warm pass: pays the one-off compile so the measured kill
            # below lands mid-DATA, not mid-compile (an injected
            # fleet.* fault here already classifies the run — fine)
            warm, _ = h.client(timeout_s=120).execute(task)
            box: dict = {}

            def drive() -> None:
                try:
                    tbl, _ = h.client(timeout_s=120).execute(task)
                    box["table"] = tbl
                except BaseException as e:   # noqa: BLE001 — audited below
                    box["err"] = e

            t = threading.Thread(target=drive, daemon=True)
            t.start()
            # SIGKILL whichever replica picks the query up, mid-flight
            victim = None
            deadline = _time.monotonic() + 10.0
            while victim is None and t.is_alive() \
                    and _time.monotonic() < deadline:
                h.router._poll_once()
                for i in range(len(h.replicas)):
                    snap = h.router._replicas[i].snapshot
                    if snap is not None and snap.occupancy > 0:
                        victim = i
                        break
                if victim is None:
                    _time.sleep(0.05)
            if victim is not None and h.replicas[victim].alive():
                h.kill_replica(victim)
            t.join(timeout=120)
            if t.is_alive():
                raise RuntimeError("fleet_failover run wedged: the "
                                   "killed query never completed or "
                                   "classified")
            if "err" in box:
                raise box["err"]
            out = box["table"]
            if not out.equals(warm):
                raise AssertionError(
                    "fleet failover diverged: the failed-over query's "
                    "table differs from the same fleet's warm pass")
        return _canonical(out)

    sc = Scenario("fleet_failover", run, [])

    def extra_audit() -> list[str]:
        from auron_tpu.runtime import journal as jrn
        found: list[str] = []
        for d in sorted(glob.glob(os.path.join(journal_root, "run_*"))):
            try:
                jrn.sweep_orphans(d, force=True)
            except OSError:
                pass   # audit still reports the raw globs below
            found += glob.glob(os.path.join(d, "*.journal"))
            found += glob.glob(os.path.join(d, "*.claim"))
            found += glob.glob(os.path.join(d, "**", "*.part"),
                               recursive=True)
            found += [p for p in glob.glob(os.path.join(d, "rss", "*"))
                      if os.path.isdir(p)]
        return found

    sc.extra_audit = extra_audit
    return sc


SCENARIOS: dict[str, Callable[[str], Scenario]] = {
    "rss_pipeline": rss_pipeline,
    "spill_sort": spill_sort,
    "agg_pipeline": agg_pipeline,
    "mesh_pipeline": mesh_pipeline,
    "lifecycle_pipeline": lifecycle_pipeline,
    "overload": overload,
    "journal_pipeline": journal_pipeline,
    "fleet_failover": fleet_failover,
}


def run_chaos(scenario: Scenario, fault_plan: str, seed: int,
              with_trace: bool = True) -> ChaosOutcome:
    """One chaos run: arm the plan at ``seed``, execute a fresh pipeline,
    classify the outcome against the fault-free baseline, audit leaks.
    The global fault config is restored (and the plane reset) whatever
    happens.

    ``with_trace`` (default) records the run under its own trace id
    (obs/trace) and attaches the site→recovery-span correlation, so a
    chaos report links every injected fault to the recovery it
    triggered."""
    from auron_tpu.obs import bundle as _bundle
    from auron_tpu.obs import trace
    baseline = scenario.baseline()
    conf = cfg.get_config()
    # post-mortem correlation (auron.bundle.enabled armed by the
    # caller): snapshot the bundle inventory so this run's new bundles
    # — and ONLY this run's — are audited against its injections
    bundle_root = (_bundle.bundle_dir(conf)
                   if conf.get(cfg.BUNDLE_ENABLED) else None)
    bundles_before = (set(_bundle.list_bundles(bundle_root))
                      if bundle_root else set())
    conf.set(cfg.FAULTS_PLAN, fault_plan)
    conf.set(cfg.FAULTS_SEED, seed)
    _missing = object()
    saved_trace = {}
    if with_trace:
        # save-and-restore, not unset: a caller's own session override
        # (debugging with tracing armed) must survive the chaos run
        for key in (cfg.TRACE_ENABLED, cfg.TRACE_DIR, cfg.TRACE_EVENTS):
            saved_trace[key] = conf._overrides.get(key, _missing)
        conf.set(cfg.TRACE_ENABLED, True)
        # keep spans in memory and every category recording: an ambient
        # auron.trace.dir (CI env var) would make the query scope
        # export-and-DROP the trace before correlate_spans below ever
        # sees it, and an ambient auron.trace.events allowlist would
        # filter out the fault/recovery events the correlation reads
        conf.set(cfg.TRACE_DIR, "")
        conf.set(cfg.TRACE_EVENTS, "")
    faults.reset()
    injected: dict = {}
    trace_id = 0
    correlation: dict = {}
    try:
        scope = trace.query_scope(label=f"chaos:{scenario.name}") \
            if with_trace else None
        try:
            if scope is not None:
                scope.__enter__()
                trace_id = scope.trace_id
            out = scenario.run()
        finally:
            if scope is not None:
                # real exc_info, not Nones: the root span's error
                # attribute is what makes a failed chaos trace
                # self-explaining in trace_report
                scope.__exit__(*sys.exc_info())
            injected = faults.snapshot()
        status = "identical" if out.equals(baseline) else "mismatch"
        err_t = err = None
        bundle_tag = None
    except errors.AuronError as e:
        status, err_t, err = "classified", type(e).__name__, str(e)
        bundle_tag = _bundle.classify(e)
    except Exception as e:   # noqa: BLE001 — the contract's failure bucket
        status, err_t, err = "unclassified", type(e).__name__, str(e)
        bundle_tag = None
    finally:
        if with_trace:
            correlation = correlate_spans(
                trace.tracer().spans(trace_id or None))
            for key, prev in saved_trace.items():
                if prev is _missing:
                    conf.unset(key)
                else:
                    conf.set(key, prev)
            # drop only THIS run's spans: a caller's own in-progress
            # trace (the debugging scenario the save/restore above
            # protects) must survive — a global reset would wipe it
            if trace_id:
                trace.tracer().drop(trace_id)
        conf.unset(cfg.FAULTS_PLAN)
        conf.unset(cfg.FAULTS_SEED)
        faults.reset()
        # a device quarantined by THIS run's injected loss must not
        # silently reroute the next run's exchanges (each chaos run is
        # a fresh pipeline by contract; the quarantine ledger still
        # counted it for the report)
        from auron_tpu.parallel import mesh as _mesh
        _mesh.clear_quarantine()
    new_bundles = ([p for p in _bundle.list_bundles(bundle_root)
                    if p not in bundles_before] if bundle_root else [])
    bundle_leaks = (_audit_bundles(bundle_root, new_bundles, bundle_tag,
                                   err_t, injected, seed, conf)
                    if bundle_root else [])
    return ChaosOutcome(scenario.name, fault_plan, seed, status,
                        error_type=err_t, error=err, injected=injected,
                        leaks=scenario.leaks() + bundle_leaks,
                        trace_id=trace_id,
                        correlation=correlation, bundles=new_bundles)


def _audit_bundles(root: str, new_bundles: list, bundle_tag,
                   err_t, injected: dict, seed: int, conf) -> list[str]:
    """Bundle half of the chaos leak audit (ISSUE 14): a run whose
    terminal error is bundle-eligible must have produced EXACTLY ONE
    bundle for it, that bundle's flight dump must contain the injected
    fault's ``fault.injected`` event (site + seed match — the
    post-mortem provably shows the cause), and the retention cap
    (auron.bundle.max_bundles, oldest-first) must hold so bundles can
    never become the leak they exist to explain. Findings are leak
    strings — they fail the run through ``ChaosOutcome.ok``."""
    from auron_tpu.obs import bundle as _bundle
    from auron_tpu.obs import flight_recorder as _flight
    probs: list[str] = []
    if bundle_tag is not None:
        matching = []
        for p in new_bundles:
            try:
                mf = _bundle.read_manifest(p)
            except Exception as e:   # noqa: BLE001 — audit verdict
                probs.append(f"bundle-unreadable:{p}:{e}")
                continue
            if mf.get("error_type") == err_t:
                matching.append(p)
        if len(matching) != 1:
            probs.append(
                f"bundle-count:{len(matching)} for {err_t} "
                f"(expected exactly 1; new={new_bundles})")
        for p in matching:
            if not injected:
                continue   # classified by knobs, not by an injection
            try:
                events = _flight.read_jsonl(
                    os.path.join(p, "flight.jsonl"))
            except Exception as e:   # noqa: BLE001 — audit verdict
                probs.append(f"bundle-flight-unreadable:{p}:{e}")
                continue
            hit = any(
                ev.get("name") == "fault.injected"
                and ev.get("attrs", {}).get("site") in injected
                and ev.get("attrs", {}).get("seed") == seed
                for ev in events)
            if not hit:
                probs.append(
                    f"bundle-flight-missing-fault:{p} "
                    f"(sites={sorted(injected)}, seed={seed})")
    keep = int(conf.get(cfg.BUNDLE_MAX_BUNDLES))
    total = len(_bundle.list_bundles(root))
    if keep > 0 and total > keep:
        probs.append(f"bundle-retention:{total} bundles > "
                     f"max_bundles={keep}")
    return probs


# ---------------------------------------------------------------------------
# crash scenario: subprocess SIGKILL at every journal stage boundary
# ---------------------------------------------------------------------------
#
# The one failure mode no in-process chaos run can exercise: the Python
# process DIES (SIGKILL — no unwind, no finally, no atexit). A child
# process runs a two-exchange query with the crash-safe journal armed
# (runtime/journal.py) and kills itself at the k-th journal event (map
# commit record / shuffle commit record — the stage boundaries); the
# parent then resumes from the journal and audits the full contract:
#
#   - resumed result BIT-IDENTICAL to a fresh run (group order included)
#   - the child's uncommitted ``.part`` files, orphaned spill files and
#     journal artifacts are reclaimed by the startup sweeps
#   - nothing unclassified anywhere
#
# ``run_crash_sweep`` sweeps EVERY kill point (1..events+1 — the +1 run
# outlives all boundaries and completes in the child, proving the
# no-kill control path); ``tests/test_zz_crash_battery.py`` asserts a
# fast subset tier-1 and the full sweep under ``slow``.

CRASH_SCALE = 0.25          # ~30k fact rows: multi-batch, fast children


@dataclass
class CrashOutcome:
    """One (kill point → resume) cycle's audited outcome."""
    kill_point: int
    #: child exit: -9 = SIGKILLed at the boundary, 0 = ran past every
    #: boundary and completed (the control run)
    child_rc: int
    #: identical | classified | completed | mismatch | unclassified
    status: str
    error_type: Optional[str] = None
    error: Optional[str] = None
    maps_skipped: int = 0
    maps_recomputed: int = 0
    bytes_reused: int = 0
    resume_wall_s: float = 0.0
    #: leftover .part / spill / journal artifacts after the sweeps +
    #: resume (must be empty)
    leaks: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return (self.status in ("identical", "classified", "completed")
                and not self.leaks)


def crash_query(session, files: list):
    """The sweep's TWO-EXCHANGE TPC-DS-shaped query (hash repartition →
    two-phase agg), shared verbatim by the crashing child and the
    parent's fresh baseline so the bit-identity comparison is about the
    JOURNAL, not the plan."""
    from auron_tpu.frontend.dataframe import col, functions as F
    return (session.read_parquet(files, partitions=3)
            .repartition(3, "ss_store_sk")
            .filter(col("ss_quantity") > 5)
            .group_by("ss_store_sk")
            .agg(F.sum(col("ss_sales_price")).alias("total"),
                 F.count(col("ss_net_paid")).alias("paid_cnt")))


def _crash_workdir_init(workdir: str) -> list:
    """Generate the sweep's dataset once under ``workdir`` and persist
    the file manifest the child re-reads. Returns the fact files."""
    import json as _json
    from auron_tpu.it.tpcds_data import generate as gen_data
    manifest = os.path.join(workdir, "manifest.json")
    if not os.path.exists(manifest):
        tables = gen_data(os.path.join(workdir, "data"),
                          scale=CRASH_SCALE)
        with open(manifest, "w") as f:
            _json.dump({"store_sales": tables["store_sales"]}, f)
    import json as _json2
    with open(manifest) as f:
        return _json2.load(f)["store_sales"]


def _crash_child_main(workdir: str, kill_at: int) -> int:
    """Child half of the crash harness: run ``crash_query`` with the
    journal armed and SIGKILL OURSELVES the moment the ``kill_at``-th
    journal boundary event (map record / shuffle commit) returns — no
    unwind, no cleanup, exactly an OOM-kill. ``kill_at <= 0`` disables
    the kill (the event-count probe / completion control): the child
    then writes its result table to ``result.arrow`` and prints one
    JSON line ``{"completed": true, "events": N}``."""
    import json as _json
    import signal

    from auron_tpu import config as _cfg
    from auron_tpu.frontend.session import Session
    from auron_tpu.memmgr import spill as spill_mod
    from auron_tpu.runtime import journal as jrn

    conf = _cfg.get_config()
    conf.set(_cfg.JOURNAL_DIR, os.path.join(workdir, "journal"))
    # a real crashed engine leaves spill files too: drop one carrying
    # THIS process's pid.epoch owner token so the parent can prove the
    # spill startup sweep reclaims a dead writer's artifact
    spill_dir = os.path.join(workdir, "spill")
    os.makedirs(spill_dir, exist_ok=True)
    if kill_at > 0:
        with open(os.path.join(
                spill_dir,
                f"auron-spill-{spill_mod._owner_token()}-0-crash.atb"),
                "wb") as f:
            f.write(b"orphan")

    counter = [0]
    orig_map = jrn.QueryJournal.record_map
    orig_commit = jrn.QueryJournal.record_shuffle_commit

    def _boundary() -> None:
        counter[0] += 1
        if counter[0] == kill_at:
            os.kill(os.getpid(), signal.SIGKILL)

    def record_map(self, *a, **kw):
        orig_map(self, *a, **kw)
        _boundary()

    def record_shuffle_commit(self, *a, **kw):
        orig_commit(self, *a, **kw)
        _boundary()

    jrn.QueryJournal.record_map = record_map
    jrn.QueryJournal.record_shuffle_commit = record_shuffle_commit

    files = _crash_workdir_init(workdir)
    s = Session()
    table = s.execute(crash_query(s, files))
    s.close()
    import pyarrow.feather as feather
    feather.write_feather(table, os.path.join(workdir, "result.arrow"),
                          compression="uncompressed")
    print(_json.dumps({"completed": True, "events": counter[0],
                       "rows": table.num_rows}))
    return 0


def _spawn_crash_child(workdir: str, kill_at: int,
                       timeout_s: float = 240.0):
    """Run one crash child; returns (rc, stdout)."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # children share a persistent XLA cache so only the first pays the
    # compile bill — the sweep measures crash recovery, not tracing
    env["AURON_CONF_XLA_CACHE_DIR"] = os.path.join(workdir, "xla_cache")
    proc = subprocess.run(
        [sys.executable, "-m", "auron_tpu.it.chaos", "--crash-child",
         workdir, str(kill_at)],
        capture_output=True, text=True, timeout=timeout_s, cwd=repo,
        env=env)
    return proc.returncode, proc.stdout


def crash_probe(workdir: str) -> int:
    """Count the query's journal boundary events (one no-kill child
    run): the sweep's kill points are 1..events."""
    import json as _json
    _crash_workdir_init(workdir)
    rc, out = _spawn_crash_child(workdir, 0)
    if rc != 0:
        raise RuntimeError(f"crash probe child failed rc={rc}: "
                           f"{out[-500:]}")
    return int(_json.loads(out.strip().splitlines()[-1])["events"])


def crash_baseline(workdir: str) -> pa.Table:
    """The parent's fresh, journal-free reference result."""
    from auron_tpu.frontend.session import Session
    files = _crash_workdir_init(workdir)
    s = Session()
    try:
        return s.execute(crash_query(s, files))
    finally:
        s.close()


def run_crash_point(workdir: str, kill_point: int,
                    baseline: Optional[pa.Table] = None) -> CrashOutcome:
    """One full crash cycle: fresh journal/spill dirs for this kill
    point, child SIGKILLed at the boundary, parent startup sweeps
    asserted (spill + RSS tiers), ``Session.resume`` of the journaled
    query, bit-identity vs the fresh baseline, orphan audit."""
    import shutil
    import time

    from auron_tpu.frontend.session import Session
    from auron_tpu.memmgr.spill import SpillManager
    from auron_tpu.runtime import journal as jrn

    if baseline is None:
        baseline = crash_baseline(workdir)
    point_dir = os.path.join(workdir, f"k{kill_point}")
    # each kill point gets fresh journal/spill dirs under the shared
    # data/workdir (the once-per-process sweep memos key on the dir)
    shutil.rmtree(point_dir, ignore_errors=True)
    os.makedirs(point_dir, exist_ok=True)
    for sub in ("journal", "spill"):
        os.makedirs(os.path.join(point_dir, sub), exist_ok=True)
    # the child resolves journal/spill under ITS workdir: symlink the
    # shared data/manifest/xla_cache into the per-point dir
    for shared in ("data", "manifest.json", "xla_cache"):
        src = os.path.join(workdir, shared)
        if os.path.exists(src):
            os.symlink(src, os.path.join(point_dir, shared))

    rc, out = _spawn_crash_child(point_dir, kill_point)
    jdir = os.path.join(point_dir, "journal")
    spill_dir = os.path.join(point_dir, "spill")

    # -- startup sweeps (the satellite assertions) ------------------------
    # spill tier: constructing a SpillManager over the dead child's dir
    # IS the startup sweep; the child's crash marker (its own pid.epoch
    # in the filename, its process now provably dead) must be gone
    SpillManager(host_budget_bytes=1, spill_dir=spill_dir)
    leftover_spill = [p for p in glob.glob(
        os.path.join(spill_dir, "auron-spill-*"))]
    if leftover_spill:
        return CrashOutcome(
            kill_point, rc, "unclassified",
            error_type="SpillSweepFailed",
            error=f"spill startup sweep left {leftover_spill}",
            leaks=leftover_spill)

    if rc == 0:
        # the kill point lies past the last boundary: the child ran to
        # completion — its journal must be gone and its result must
        # match the baseline (read back from result.arrow)
        import pyarrow.feather as feather
        table = feather.read_table(
            os.path.join(point_dir, "result.arrow"))
        status = ("completed" if table.equals(baseline) else "mismatch")
        return CrashOutcome(kill_point, rc, status,
                            leaks=_crash_leaks(jdir, spill_dir))

    outcome = CrashOutcome(kill_point, rc, "unclassified")

    # -- resume -----------------------------------------------------------
    stems = [os.path.splitext(os.path.basename(p))[0]
             for p in glob.glob(os.path.join(jdir, "*.journal"))]
    if len(stems) != 1:
        outcome.error_type = "JournalInventory"
        outcome.error = (f"expected exactly one journal after the "
                         f"crash, found {stems}")
        return outcome
    conf = cfg.get_config()
    _missing = object()
    saved = conf._overrides.get(cfg.JOURNAL_DIR, _missing)
    conf.set(cfg.JOURNAL_DIR, jdir)
    try:
        s = Session()
        t0 = time.perf_counter()
        try:
            table = s.resume(stems[0])
            outcome.resume_wall_s = time.perf_counter() - t0
            stats = jrn.last_stats()
            outcome.maps_skipped = stats.get("maps_skipped", 0)
            outcome.maps_recomputed = stats.get("maps_recomputed", 0)
            outcome.bytes_reused = stats.get("bytes_reused", 0)
            outcome.status = ("identical" if table.equals(baseline)
                              else "mismatch")
        except errors.AuronError as e:
            outcome.status = "classified"
            outcome.error_type = type(e).__name__
            outcome.error = str(e)
        except Exception as e:   # noqa: BLE001 — the failure bucket
            outcome.error_type = type(e).__name__
            outcome.error = str(e)
        finally:
            s.close()
    finally:
        if saved is _missing:
            conf.unset(cfg.JOURNAL_DIR)
        else:
            conf.set(cfg.JOURNAL_DIR, saved)
    outcome.leaks = _crash_leaks(jdir, spill_dir)
    return outcome


def _crash_leaks(jdir: str, spill_dir: str) -> list:
    """Orphan audit after one crash cycle: no ``.part`` anywhere under
    the journal root, no journal files, no RSS run dirs, no spill
    files. ``report_*.json`` is a deliberate artifact (the
    tools/journal_report.py input), not a leak."""
    gc.collect()
    found = glob.glob(os.path.join(jdir, "**", "*.part"), recursive=True)
    found += glob.glob(os.path.join(jdir, "*.journal"))
    found += glob.glob(os.path.join(jdir, "*.claim"))
    found += [d for d in glob.glob(os.path.join(jdir, "rss", "*"))
              if os.path.isdir(d)]
    found += glob.glob(os.path.join(spill_dir, "auron-spill-*"))
    return found


def run_crash_sweep(workdir: Optional[str] = None,
                    kill_points: Optional[list] = None) -> list:
    """Sweep every journal boundary of the two-exchange crash query:
    kill points 1..events (each child dies AT that boundary) plus
    events+1 (the child outlives every boundary and completes). Returns
    the list of ``CrashOutcome``; the contract is ``all(o.ok)``."""
    import tempfile

    own = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="auron_crash_")
    try:
        events = crash_probe(workdir)
        baseline = crash_baseline(workdir)
        points = kill_points or list(range(1, events + 2))
        return [run_crash_point(workdir, k, baseline) for k in points]
    finally:
        if own:
            import shutil
            shutil.rmtree(workdir, ignore_errors=True)


def _main(argv) -> int:
    if len(argv) >= 3 and argv[0] == "--crash-child":
        return _crash_child_main(argv[1], int(argv[2]))
    raise SystemExit(
        "usage: python -m auron_tpu.it.chaos --crash-child "
        "<workdir> <kill_at>")


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))
