"""Result differ.

Mirror of the reference's QueryResultComparator (reference:
dev/auron-it/src/main/scala/org/apache/auron/integration/comparison/
QueryResultComparator.scala:21-100): row counts must match exactly;
both sides are canonically sorted (engine output order is unspecified);
doubles compare with relative tolerance, everything else exactly.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


@dataclass
class ComparisonResult:
    name: str
    ok: bool
    rows: int
    mismatches: list = field(default_factory=list)
    error: str = ""
    elapsed_s: float = 0.0
    #: XLA programs built / seconds spent compiling while the query ran
    #: (utils/compile_stats.py; ~0 on a warm in-process rerun)
    compiles: int = 0
    compile_s: float = 0.0

    def report(self) -> str:
        if self.ok:
            return f"[PASS] {self.name}: {self.rows} rows"
        if self.error:
            return f"[FAIL] {self.name}: {self.error}"
        head = "; ".join(str(m) for m in self.mismatches[:5])
        return (f"[FAIL] {self.name}: {len(self.mismatches)} mismatching "
                f"cells of {self.rows} rows — {head}")


class QueryResultComparator:
    def __init__(self, double_rel_tol: float = 1e-9,
                 double_abs_tol: float = 1e-9):
        self.rel = double_rel_tol
        self.abs = double_abs_tol

    @staticmethod
    def _cell_key(v):
        """Type-ranked, NUMERIC sort key: None < numbers (by value, NaN
        last) < strings < other. Floats must sort by value, not repr —
        lexicographic float keys ('100.0' < '99.9') could align the two
        sides differently and misreport tolerance-level differences as
        row mismatches."""
        if v is None:
            return (0, 0, "")
        if isinstance(v, bool):
            return (1, 2, float(v))
        if isinstance(v, (int, float)):
            f = float(v)
            if math.isnan(f):
                return (1, 2, math.inf)
            return (1, 2, f)
        if isinstance(v, str):
            return (1, 3, v)
        return (1, 4, str(v))

    @classmethod
    def _canon_rows(cls, table) -> list[tuple]:
        """Rows as sortable tuples (engine output order is unspecified)."""
        rows = [tuple(r[c] for c in table.column_names)
                for r in table.to_pylist()]
        return sorted(rows, key=lambda row: tuple(cls._cell_key(v)
                                                  for v in row))

    def _cell_equal(self, a, b) -> bool:
        if a is None or b is None:
            return a is None and b is None
        if isinstance(a, float) or isinstance(b, float):
            if isinstance(a, float) and isinstance(b, float):
                if math.isnan(a) and math.isnan(b):
                    return True
            return math.isclose(float(a), float(b),
                                rel_tol=self.rel, abs_tol=self.abs)
        return a == b

    def compare(self, name: str, got, expected) -> ComparisonResult:
        """got / expected: pyarrow Tables with identical column names."""
        if set(got.column_names) != set(expected.column_names):
            return ComparisonResult(
                name, False, got.num_rows,
                error=f"column sets differ: {got.column_names} vs "
                      f"{expected.column_names}")
        expected = expected.select(got.column_names)
        if got.num_rows != expected.num_rows:
            return ComparisonResult(
                name, False, got.num_rows,
                error=f"row counts differ: {got.num_rows} vs "
                      f"{expected.num_rows}")
        g = self._canon_rows(got)
        e = self._canon_rows(expected)
        mismatches = []
        for i, (gr, er) in enumerate(zip(g, e)):
            for j, (gv, ev) in enumerate(zip(gr, er)):
                if not self._cell_equal(gv, ev):
                    mismatches.append(
                        (i, got.column_names[j], gv, ev))
                    if len(mismatches) > 20:
                        return ComparisonResult(name, False, got.num_rows,
                                                mismatches=mismatches)
        return ComparisonResult(name, not mismatches, got.num_rows,
                                mismatches=mismatches)
