"""Kafka-style streaming scan operator.

The reference's native Kafka consumer (reference: datafusion-ext-plans/src/
flink/kafka_scan_exec.rs) polls rdkafka and deserializes rows into Arrow.
Here the scan polls a broker by bootstrap name — in this build always the
in-process MockBroker (the reference ships kafka_mock_scan_exec for exactly
this role) — decodes message windows into RecordBatches, and yields
DeviceBatches. ``max_batches`` bounds the stream (0/None = drain to the
current end offset), which is how the bounded test/dryrun mode works.

Each execute() partition consumes the matching broker partition, so the
streaming source shards over tasks the way Kafka partitions shard over
consumers in a group.
"""

from __future__ import annotations

from typing import Iterator, Optional

from auron_tpu.columnar.arrow_bridge import to_device
from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.columnar.schema import Schema
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output
from auron_tpu.streaming.broker import MockBroker
from auron_tpu.streaming.rows import DECODERS
from auron_tpu.utils.shapes import DEFAULT_BATCH_CAPACITY


class KafkaScanOp(PhysicalOp):
    name = "kafka_scan"

    def __init__(self, topic: str, bootstrap: str, schema: Schema,
                 fmt: str = "json", max_batches: Optional[int] = None,
                 batch_rows: int = DEFAULT_BATCH_CAPACITY,
                 group_id: Optional[str] = None):
        if fmt not in DECODERS:
            raise ValueError(f"unknown kafka row format {fmt!r} "
                             f"(known: {sorted(DECODERS)})")
        self.topic = topic
        self.bootstrap = bootstrap
        self._schema = schema
        self.fmt = fmt
        self.max_batches = max_batches
        self.batch_rows = batch_rows
        #: non-None: resume from the group's committed offset and commit
        #: after each consumed poll window (at-least-once on restart —
        #: Kafka consumer-group semantics)
        self.group_id = group_id

    @property
    def children(self):
        return []

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        decoder = DECODERS[self.fmt]
        broker = MockBroker.get(self.bootstrap)

        def stream():
            offset = broker.committed(self.group_id, self.topic, partition) \
                if self.group_id else 0
            emitted = 0
            # bounded mode: drain to the end offset captured at start (a
            # snapshot read); max_batches additionally caps emitted batches
            end = broker.end_offset(self.topic, partition)
            while offset < end:
                if self.max_batches and emitted >= self.max_batches:
                    return
                msgs = broker.poll(self.topic, partition, offset,
                                   self.batch_rows)
                if not msgs:
                    break
                offset += len(msgs)
                rb = decoder(msgs, self._schema)
                if rb.num_rows:
                    for off in range(0, rb.num_rows, self.batch_rows):
                        yield to_device(
                            rb.slice(off,
                                     min(self.batch_rows,
                                         rb.num_rows - off)),
                            capacity=self.batch_rows)[0]
                        emitted += 1
                        if self.max_batches and emitted >= self.max_batches:
                            # window partially delivered: do NOT commit it
                            return
                # commit AFTER the poll window has been delivered
                # downstream (the generator resumed past every yield):
                # a crash before this point replays the window on restart
                # — at-least-once, the Kafka consumer-group contract
                if self.group_id:
                    broker.commit(self.group_id, self.topic, partition,
                                  offset)

        return count_output(stream(), metrics, timed=True)

    def __repr__(self):
        return f"KafkaScanOp[{self.topic}@{self.bootstrap}]"
